"""``python -m peasoup_tpu.serve`` — the survey scheduler CLI.

Verbs::

    submit        <files...> [--priority N] [--tenant NAME]
                  [--set key=value ...]
    worker        [--drain] [--max-jobs N] [--poll S] ...
    fleet-worker  [--host-id I --host-count N] [--label L]
                  [--lease-ttl S] [--heartbeat S] + worker options
    supervise     [--interval S] [--ticks N] [--max-workers N]
                  [--dry-run] [--worker-arg FLAG ...]
    admission     [--show] [--max-pending N]
                  [--tenant NAME --rate R --burst B --weight W]
    status        [--jobs] [--fleet] [--watch [--interval S]]
    health        [--json PATH] [--stale-after N] [--window S]
                  [--slo KEY=VALUE ...]
    why           <candidate-id> [--lineage PATH] [--json PATH]
    query         <freq> [--freq-tol F] [--max-harm N] [--json PATH]
    coincidence   [--freq-tol F] [--min-sources N] [--json PATH]
    timeline      <job_id> [--json PATH] [--trace_json PATH]
    requeue       <job_ids...> | --running | --failed | --expired

All verbs take ``--spool DIR`` (default ``./jobs``): the durable spool
directory described in serve/queue.py.  ``submit`` enqueues
observations; ``worker`` claims and runs them (``--drain`` exits when
the queue empties, otherwise it polls forever); ``fleet-worker`` is
the per-host member of a multi-host fleet (serve/fleet.py: leased
claims, idle-time lease reaping, per-host store shard; membership is
auto-detected from jax.distributed, or injected with
``--host-id/--host-count`` for tests and smoke runs); ``status``
prints the queue + store state (``--fleet`` aggregates every host's
snapshot into one table and writes ``fleet_report.json``);
``coincidence`` runs the survey-level coincidencer over the merged
store shards; ``why`` reconstructs a candidate's full selection
decision chain — decode, absorptions with margins, score flags,
fold/limit cuts, store ingest — from its store record and the spool's
lineage ledger (obs/lineage.py, ISSUE 19); ``query`` finds store
records harmonically related to a frequency, each carrying its
candidate id and provenance block; ``requeue`` recovers jobs from a
crashed worker
(``--running``, or ``--expired`` for lease-based recovery that only
touches jobs whose host stopped heartbeating) or retries quarantined
ones (``--failed``).

``timeline`` renders a job's cross-process lifecycle waterfall from
its ``work/<id>/timeline.jsonl`` marks (obs/timeline.py: every spool
transition + every worker phase, stitched clock-skew-tolerantly across
hosts); ``--json`` writes the waterfall document, ``--trace_json``
exports a Chrome/Perfetto trace that merges the worker's device spans
for jobs that ran locally.

Health plane (serve/health.py over obs/telemetry.py shards):
``health`` evaluates every registered rule plus the SLO summary
against the fleet's live telemetry time-series and exits nonzero on a
crit finding — CI/cron-able; ``status --watch`` re-renders the fleet
table and the current findings every ``--interval`` seconds (a
terminal dashboard; ``--iterations`` bounds it for tests and one-shot
scripts).

Self-healing plane (serve/supervisor.py): ``supervise`` runs the
control loop that ACTS on the findings — reaping dead hosts' leases,
spawning/retiring real fleet-worker subprocesses against the backlog
trend, retuning ``--batch`` on bucket-mix drift — with per-action
cooldowns and a global actions-per-window cap (``--dry-run`` prints
the plan without executing).  ``admission`` shows or edits the
spool's shared admission policy (``admission.json``): the backlog
knee plus per-tenant token-bucket rates and fair-share weights that
``submit --tenant`` is subject to.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

from ..utils.atomicio import atomic_write_json


def _parse_override(text: str) -> tuple[str, object]:
    """``key=value`` with the value coerced like the main CLI would:
    int, then float, then bool literals, else string."""
    if "=" not in text:
        from ..errors import ConfigError

        raise ConfigError(f"--set expects key=value, got {text!r}")
    key, raw = text.split("=", 1)
    for cast in (int, float):
        try:
            return key, cast(raw)
        except ValueError:
            pass
    if raw.lower() in ("true", "false"):
        return key, raw.lower() == "true"
    return key, raw


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="peasoup-tpu-serve",
        description="Peasoup-TPU - survey scheduler (job spool + "
                    "workers + candidate store)",
    )
    p.add_argument("--spool", default="./jobs",
                   help="spool directory (pending/running/done/failed)")
    sub = p.add_subparsers(dest="verb", required=True)

    ps = sub.add_parser("submit", help="enqueue observations")
    ps.add_argument("inputs", nargs="+", help="filterbank files")
    ps.add_argument("--priority", type=int, default=0,
                    help="higher claims first (FIFO within a band)")
    ps.add_argument("--set", dest="overrides", action="append",
                    default=[], metavar="KEY=VALUE",
                    help="SearchConfig override (repeatable), e.g. "
                         "--set dm_end=120 --set npdmp=8")
    ps.add_argument("--tenant", default=None,
                    help="tenant identity for admission control and "
                         "fair-share claims (default tenant when "
                         "omitted)")
    ps.add_argument("--canary", default=None, metavar="MANIFEST.json",
                    help="submit as a known-answer canary: the "
                         "injection manifest (obs/injection.py) rides "
                         "the job, the worker matches the result "
                         "against it, and the store tags its "
                         "candidates out of science queries")

    pw = sub.add_parser("worker", help="claim and run jobs")
    _add_worker_args(pw)

    pf = sub.add_parser(
        "fleet-worker",
        help="run this host's member of a multi-host fleet")
    _add_worker_args(pf)
    pf.add_argument("--host-id", type=int, default=None,
                    help="simulated host index (with --host-count); "
                         "default: detect from jax.distributed")
    pf.add_argument("--host-count", type=int, default=None,
                    help="simulated fleet size (with --host-id)")
    pf.add_argument("--label", default=None,
                    help="host label for worker id, store shard and "
                         "status file (default: host-<id>)")
    pf.add_argument("--lease-ttl", type=float, default=None,
                    help="seconds without a heartbeat before another "
                         "host may reap this host's running jobs")
    pf.add_argument("--heartbeat", type=float, default=0.0,
                    help="lease refresh interval (0 = ttl/3)")

    pv = sub.add_parser(
        "supervise",
        help="self-healing control loop: act on health findings "
             "(reap leases, spawn/retire workers, retune batch)")
    pv.add_argument("--interval", type=float, default=10.0,
                    help="seconds between health evaluations")
    pv.add_argument("--ticks", type=int, default=0,
                    help="stop after N ticks (0 = run until signal)")
    pv.add_argument("--max-workers", type=int, default=2,
                    help="ceiling for supervisor-spawned fleet-worker "
                         "subprocesses")
    pv.add_argument("--batch", type=int, default=1,
                    help="initial --batch for spawned workers "
                         "(retune_batch may change it)")
    pv.add_argument("--single_device", action="store_true",
                    help="spawned workers use the host-loop driver")
    pv.add_argument("--dry-run", action="store_true",
                    help="plan and print actions without executing")
    pv.add_argument("--lease-ttl", type=float, default=None,
                    help="TTL the reap_expired action enforces")
    pv.add_argument("--actions-window", type=float, default=120.0,
                    help="global cap window in seconds")
    pv.add_argument("--max-actions", type=int, default=6,
                    help="max executed actions per window (flapping "
                         "rules slow healing, never thrash)")
    pv.add_argument("--cooldown", dest="cooldowns", action="append",
                    default=[], metavar="ACTION=SECONDS",
                    help="override one action's cooldown "
                         "(repeatable), e.g. --cooldown scale_up=3")
    pv.add_argument("--stale-after", type=float, default=None,
                    help="health: missed intervals before a host is "
                         "stale")
    pv.add_argument("--window", type=float, default=None,
                    help="health evaluation window in seconds")
    pv.add_argument("--history", default=None,
                    help="ledger path for kind:\"supervise\" records "
                         "(default: repo benchmarks/history.jsonl)")
    pv.add_argument("--ledger", default=None,
                    help="bench history ledger for health baselines")
    pv.add_argument("--telemetry-interval", type=float, default=None,
                    help="supervisor's own queue-depth sampling "
                         "cadence (default: min(--interval, 5); "
                         "0 disables)")
    pv.add_argument("--worker-arg", dest="worker_args",
                    action="append", default=[], metavar="FLAG",
                    help="extra argument passed verbatim to every "
                         "spawned fleet-worker (repeatable), e.g. "
                         "--worker-arg=--max-attempts "
                         "--worker-arg=2")

    pa = sub.add_parser(
        "admission",
        help="show or edit the spool's shared admission policy "
             "(admission.json: backlog knee + per-tenant limits)")
    pa.add_argument("--show", action="store_true",
                    help="print the policy and per-tenant queue "
                         "counts")
    pa.add_argument("--max-pending", type=int, default=None,
                    help="set the backlog knee (0 = unlimited)")
    pa.add_argument("--tenant", default=None,
                    help="tenant whose limits --rate/--burst/--weight "
                         "set")
    pa.add_argument("--rate", type=float, default=None,
                    help="tenant token-bucket refill rate, submits/s "
                         "(0 = unlimited)")
    pa.add_argument("--burst", type=float, default=None,
                    help="tenant token-bucket capacity")
    pa.add_argument("--weight", type=float, default=None,
                    help="tenant fair-share weight within a priority "
                         "tier")

    pt = sub.add_parser("status", help="queue + store summary")
    pt.add_argument("--jobs", action="store_true",
                    help="list individual jobs per state")
    pt.add_argument("--fleet", action="store_true",
                    help="aggregate per-host fleet snapshots into one "
                         "table and write fleet_report.json")
    pt.add_argument("--lease-ttl", type=float, default=None,
                    help="TTL used to flag stale leases in the fleet "
                         "report")
    pt.add_argument("--watch", action="store_true",
                    help="live dashboard: re-render the fleet table + "
                         "health findings every --interval seconds")
    pt.add_argument("--interval", type=float, default=2.0,
                    help="--watch refresh interval in seconds")
    pt.add_argument("--iterations", type=int, default=0,
                    help="stop --watch after N refreshes (0 = forever)")

    ph = sub.add_parser(
        "health",
        help="evaluate fleet health rules + SLOs over the live "
             "telemetry time-series (exit 1 on a crit finding)")
    ph.add_argument("--json", dest="json_path", default=None,
                    help="also write the full health report to this "
                         "JSON file")
    ph.add_argument("--ledger", default=None,
                    help="bench history ledger for the throughput "
                         "baseline (default: repo "
                         "benchmarks/history.jsonl)")
    ph.add_argument("--stale-after", type=float, default=None,
                    help="a host is stale after this many missed "
                         "sampling intervals (default 5)")
    ph.add_argument("--window", type=float, default=None,
                    help="evaluation window in seconds (default 300)")
    ph.add_argument("--slo", dest="slo", action="append", default=[],
                    metavar="KEY=SECONDS",
                    help="override an SLO target (repeatable), e.g. "
                         "--slo queue_wait_p95_s=120")

    py = sub.add_parser(
        "why",
        help="reconstruct one candidate's full selection decision "
             "chain (store record -> lineage ledger, ISSUE 19)")
    py.add_argument("candidate_id",
                    help="candidate id (or unique prefix) from a store "
                         "record, overview.xml <candidate_id>, or a "
                         "query/coincidence listing")
    py.add_argument("--lineage", dest="lineage_path", default=None,
                    help="lineage ledger to read (default: "
                         "<spool>/lineage.jsonl)")
    py.add_argument("--json", dest="json_path", default=None,
                    help="also write the chain document to this JSON "
                         "file")

    pq = sub.add_parser(
        "query",
        help="store records harmonically related to a frequency "
             "across the survey")
    pq.add_argument("freq", type=float, help="frequency in Hz")
    pq.add_argument("--freq-tol", type=float, default=1e-4,
                    help="fractional frequency-match tolerance")
    pq.add_argument("--max-harm", type=int, default=1,
                    help="match up to this harmonic ratio (1 = plain "
                         "frequency match)")
    pq.add_argument("--json", dest="json_path", default=None,
                    help="also write the matching records (with their "
                         "candidate ids + provenance blocks) to this "
                         "JSON file")

    pc = sub.add_parser(
        "coincidence",
        help="survey-level coincidence over the merged store shards")
    pc.add_argument("--freq-tol", type=float, default=1e-4,
                    help="fractional frequency-match tolerance")
    pc.add_argument("--min-sources", type=int, default=2,
                    help="distinct observations required per group")
    pc.add_argument("--json", dest="json_path", default=None,
                    help="also write the groups to this JSON file")

    pl = sub.add_parser(
        "timeline",
        help="render one job's cross-process lifecycle waterfall "
             "from its timeline marks")
    pl.add_argument("job_id", help="job id (any spool state)")
    pl.add_argument("--json", dest="json_path", default=None,
                    help="also write the waterfall document (marks + "
                         "segments + phase totals) to this JSON file")
    pl.add_argument("--trace_json", dest="trace_path", default=None,
                    help="also export a Chrome/Perfetto trace merging "
                         "the lifecycle with the worker's device "
                         "spans")
    pl.add_argument("--width", type=int, default=40,
                    help="waterfall bar width in characters")

    pk = sub.add_parser(
        "compact",
        help="fold unsealed store shard tails into sealed, indexed "
             "segments (ISSUE 20)")
    pk.add_argument("--min-bytes", type=int, default=None,
                    help="size threshold: fold tails at/above this "
                         "many bytes (default: the compactor's "
                         "1 MiB)")
    pk.add_argument("--min-age", type=float, default=None,
                    help="age threshold: also fold tails whose shard "
                         "has been quiet this many seconds")
    pk.add_argument("--force", action="store_true",
                    help="fold every non-empty tail regardless of "
                         "thresholds")
    pk.add_argument("--status", action="store_true",
                    help="print the segment manifest summary and "
                         "exit without compacting")
    pk.add_argument("--history", action="store_true",
                    help="append a kind:'store' ledger record with "
                         "compaction_s to the bench history")
    pk.add_argument("--fault-stage", default=None,
                    help="chaos drills only: die (os._exit) at this "
                         "compaction stage (scan, segment_partial, "
                         "segment_done, index_done, pre_manifest)")

    pv2 = sub.add_parser(
        "query-service",
        help="long-lived science-query loop over the store "
             "(query/coincidence/why reads via the file inbox, "
             "per-request latency ledger records)")
    pv2.add_argument("--poll", type=float, default=0.5,
                     help="inbox poll interval in seconds")
    pv2.add_argument("--max-requests", type=int, default=0,
                     help="exit after answering this many requests "
                          "(0 = serve forever)")
    pv2.add_argument("--once", action="store_true",
                     help="drain the inbox once and exit (drills, "
                          "tests)")
    pv2.add_argument("--ledger", dest="ledger_path", default=None,
                     help="bench-history ledger to append "
                          "kind:'query' records to (default: the "
                          "repo ledger)")

    pr = sub.add_parser("requeue", help="move jobs back to pending")
    pr.add_argument("job_ids", nargs="*", help="specific job ids")
    pr.add_argument("--running", action="store_true",
                    help="requeue every running job (crashed worker "
                         "recovery)")
    pr.add_argument("--failed", action="store_true",
                    help="requeue every failed job (operator retry)")
    pr.add_argument("--expired", action="store_true",
                    help="reap only lease-expired running jobs (dead "
                         "fleet host recovery; safe while other "
                         "hosts keep working)")
    pr.add_argument("--lease-ttl", type=float, default=None,
                    help="lease TTL for --expired (seconds)")
    return p


def _add_worker_args(pw) -> None:
    """Options shared by ``worker`` and ``fleet-worker``."""
    pw.add_argument("--drain", action="store_true",
                    help="exit when the queue is empty (default: "
                         "poll forever)")
    pw.add_argument("--max-jobs", type=int, default=None,
                    help="stop after claiming this many jobs")
    pw.add_argument("--poll", type=float, default=5.0,
                    help="idle poll interval in seconds (no --drain)")
    pw.add_argument("--timeout", type=float, default=0.0,
                    help="per-job wall-clock budget in seconds "
                         "(0 = unlimited)")
    pw.add_argument("--max-attempts", type=int, default=3,
                    help="bounded retries before a job is failed")
    pw.add_argument("--backoff-base", type=float, default=1.0,
                    help="first-retry backoff in seconds (doubles "
                         "per attempt, capped at 60)")
    pw.add_argument("--backoff-jitter", type=float, default=0.25,
                    help="decorrelation jitter fraction on retry "
                         "delays so N workers don't thundering-herd "
                         "the spool (0 = exact exponential)")
    pw.add_argument("--single_device", action="store_true",
                    help="host-loop driver instead of the mesh")
    pw.add_argument("-t", "--num_threads", type=int, default=14,
                    dest="max_num_threads",
                    help="device cap for the mesh driver")
    pw.add_argument("--no-prefetch", action="store_true",
                    help="disable next-observation read overlap")
    pw.add_argument("--batch", type=int, default=1,
                    help="stack up to B same-geometry pending jobs "
                         "into ONE batched device dispatch (bucket "
                         "fill: mates jump queue order; --timeout "
                         "then bounds the whole dispatch). 1 = "
                         "per-job dispatch")
    pw.add_argument("--history", default=None,
                    help="throughput ledger path (default: the repo "
                         "benchmarks/history.jsonl)")
    pw.add_argument("--telemetry-interval", type=float, default=5.0,
                    help="live telemetry sampling cadence in seconds "
                         "(per-host fleet/ts-<host>.jsonl shard; "
                         "0 disables the sampler)")
    pw.add_argument("--profile-every", type=int, default=0,
                    help="capture a sampled jax.profiler device trace "
                         "for every Nth job (artifacts under "
                         "<spool>/profiles/, registered in the compile "
                         "ledger; tolerant no-op where the profiler "
                         "is unavailable; 0 disables)")
    pw.add_argument("--no-lineage", action="store_true",
                    help="disable the candidate-provenance ledger "
                         "(<spool>/lineage.jsonl; the `why` verb's "
                         "data source — candidate output is "
                         "bit-identical either way)")


def cmd_submit(spool, args) -> int:
    overrides = dict(_parse_override(o) for o in args.overrides)
    canary = None
    if getattr(args, "canary", None):
        from ..obs.injection import load_manifest

        canary = load_manifest(args.canary)
        # the worker's search also runs the per-stage SNR budget probe
        # against the same manifest (search/pipeline.py)
        overrides.setdefault("injection_manifest",
                             os.path.abspath(args.canary))
    from .queue import DEFAULT_TENANT

    tenant = args.tenant or DEFAULT_TENANT
    for path in args.inputs:
        rec = spool.submit(path, overrides, priority=args.priority,
                           canary=canary, tenant=tenant)
        tag = "  canary" if canary else ""
        ten = f"  tenant={rec.tenant}" if args.tenant else ""
        print(f"submitted {rec.job_id}  priority={rec.priority}  "
              f"{rec.input}{tag}{ten}")
    return 0


def cmd_worker(spool, args) -> int:
    from ..obs.events import configure_event_log
    from ..utils import enable_compile_cache
    from .retry import BackoffPolicy
    from .worker import SurveyWorker

    enable_compile_cache()
    configure_event_log(os.path.join(spool.root, "worker-events.jsonl"))
    worker = SurveyWorker(
        spool,
        backoff=BackoffPolicy(max_attempts=args.max_attempts,
                              base_s=args.backoff_base,
                              jitter=args.backoff_jitter),
        timeout_s=args.timeout,
        single_device=args.single_device,
        max_devices=args.max_num_threads,
        prefetch=not args.no_prefetch,
        history_path=args.history,
        batch=args.batch,
        telemetry_interval_s=args.telemetry_interval,
        profile_every=args.profile_every,
        lineage=not args.no_lineage,
    )
    summary = worker.drain(max_jobs=args.max_jobs,
                           wait=not args.drain, poll_s=args.poll)
    print(f"worker {worker.worker_id}: {summary['succeeded']}/"
          f"{summary['claimed']} jobs ok in {summary['elapsed_s']}s "
          f"({summary['jobs_per_hour']} jobs/h, "
          f"{summary['geometry_buckets']} geometry bucket(s))")
    return 0 if summary["failed"] == 0 else 1


def cmd_fleet_worker(spool, args) -> int:
    from ..obs.events import configure_event_log
    from ..utils import enable_compile_cache
    from .fleet import FleetMembership, FleetWorker
    from .queue import DEFAULT_LEASE_TTL_S
    from .retry import BackoffPolicy

    if (args.host_id is None) != (args.host_count is None):
        from ..errors import ConfigError

        raise ConfigError(
            "--host-id and --host-count must be given together")
    if args.host_id is not None:
        membership = FleetMembership.fake(
            args.host_id, args.host_count, args.label)
    else:
        membership = FleetMembership.detect(label=args.label)
    enable_compile_cache()
    configure_event_log(os.path.join(
        spool.root, f"worker-events-{membership.label}.jsonl"))
    worker = FleetWorker(
        spool,
        membership,
        lease_ttl_s=(args.lease_ttl if args.lease_ttl is not None
                     else DEFAULT_LEASE_TTL_S),
        heartbeat_s=args.heartbeat or None,
        backoff=BackoffPolicy(max_attempts=args.max_attempts,
                              base_s=args.backoff_base,
                              jitter=args.backoff_jitter),
        timeout_s=args.timeout,
        single_device=args.single_device,
        max_devices=args.max_num_threads,
        prefetch=not args.no_prefetch,
        history_path=args.history,
        batch=args.batch,
        telemetry_interval_s=args.telemetry_interval,
        profile_every=args.profile_every,
        lineage=not args.no_lineage,
    )
    summary = worker.drain(max_jobs=args.max_jobs,
                           wait=not args.drain, poll_s=args.poll)
    print(f"fleet host {membership.label} "
          f"({membership.host_id + 1}/{membership.host_count}) "
          f"worker {worker.worker_id}: {summary['succeeded']}/"
          f"{summary['claimed']} jobs ok in {summary['elapsed_s']}s "
          f"({summary['jobs_per_hour']} jobs/h)")
    return 0 if summary["failed"] == 0 else 1


def cmd_supervise(spool, args) -> int:
    import signal

    from ..obs.events import configure_event_log
    from .queue import DEFAULT_LEASE_TTL_S
    from .supervisor import Supervisor, WorkerPool

    configure_event_log(os.path.join(spool.root,
                                     "supervisor-events.jsonl"))
    worker_args = list(args.worker_args)
    if args.single_device:
        worker_args.append("--single_device")
    if args.history:
        worker_args += ["--history", args.history]
    pool = WorkerPool(spool.root, max_workers=args.max_workers,
                      batch=args.batch, worker_args=worker_args)
    kw = {}
    if args.window is not None:
        kw["window_s"] = args.window
    if args.stale_after is not None:
        kw["stale_after"] = args.stale_after
    telemetry = (args.telemetry_interval
                 if args.telemetry_interval is not None
                 else min(args.interval, 5.0))
    cooldowns = {}
    for item in args.cooldowns:
        key, val = _parse_override(item)
        cooldowns[key] = float(val)
    sup = Supervisor(
        spool, pool=pool, interval_s=args.interval,
        lease_ttl_s=(args.lease_ttl if args.lease_ttl is not None
                     else DEFAULT_LEASE_TTL_S),
        dry_run=args.dry_run,
        actions_window_s=args.actions_window,
        max_actions_per_window=args.max_actions,
        cooldowns=cooldowns,
        history_path=args.history, ledger_path=args.ledger,
        telemetry_interval_s=telemetry, **kw)

    def _graceful(signum, frame):
        sup.stop()

    signal.signal(signal.SIGTERM, _graceful)
    signal.signal(signal.SIGINT, _graceful)
    print(f"supervisor: spool {spool.root}  interval "
          f"{args.interval:g}s  max-workers {args.max_workers}"
          f"{'  DRY-RUN' if args.dry_run else ''}")
    try:
        ticks = sup.run(ticks=args.ticks)
    finally:
        pool.stop_all()
    executed = len(sup.actions_taken)
    print(f"supervisor: {ticks} tick(s), {executed} action(s) "
          f"executed")
    return 0


def cmd_admission(spool, args) -> int:
    from dataclasses import replace

    from ..errors import ConfigError
    from .queue import AdmissionPolicy, TenantPolicy

    pol = AdmissionPolicy.load(spool.root)
    changed = False
    if args.max_pending is not None:
        pol.max_pending = int(args.max_pending)
        changed = True
    tenant_knobs = [k for k in ("rate", "burst", "weight")
                    if getattr(args, k) is not None]
    if tenant_knobs and not args.tenant:
        raise ConfigError(
            "--rate/--burst/--weight need --tenant NAME")
    if args.tenant and tenant_knobs:
        cur = pol.tenants.get(args.tenant, TenantPolicy())
        updates = {}
        if args.rate is not None:
            updates["rate_per_s"] = float(args.rate)
        if args.burst is not None:
            updates["burst"] = float(args.burst)
        if args.weight is not None:
            updates["weight"] = float(args.weight)
        pol.tenants[args.tenant] = replace(cur, **updates)
        changed = True
    if changed:
        print(f"wrote {pol.save(spool.root)}")
    knee = pol.max_pending or "unlimited"
    print(f"max_pending: {knee}")
    counts = spool.tenant_counts() if (args.show or not changed) \
        else {}
    names = sorted(set(pol.tenants) | set(counts))
    for name in names:
        tp = pol.for_tenant(name)
        rate = f"{tp.rate_per_s:g}/s burst {tp.burst:g}" \
            if tp.rate_per_s else "unlimited"
        line = (f"tenant {name}: rate {rate}, "
                f"weight {tp.weight:g}")
        if name in counts:
            line += "  [" + "  ".join(
                f"{s}={n}" for s, n in counts[name].items()
                if n) + "]"
        print(line)
    return 0


def _print_fleet_table(report: dict, rollup: dict | None = None
                       ) -> None:
    """The per-host table.  ``rollup`` (ISSUE 16, from
    ``obs.warehouse.host_rollup``) adds live telemetry columns: duty
    cycle, HBM utilization and a jobs/hr sparkline straight off the
    ``ts-<host>.jsonl`` shards."""
    cols = ("host", "claimed", "ok", "fail", "jobs/h", "reaped",
            "shard")
    if rollup is not None:
        cols += ("duty", "util", "jobs/h trend")

    def telemetry_cols(label: str) -> tuple:
        if rollup is None:
            return ()
        ent = rollup.get(label)
        if not ent:
            return ("-", "-", "")
        from ..obs.warehouse import sparkline

        util = (f"{ent['util'] * 100:.0f}%"
                if ent.get("util") is not None else "-")
        return (f"{ent['duty'] * 100:.0f}%", util,
                sparkline(ent.get("jobs_per_hour", [])))

    rows = []
    for label, doc in sorted(report["hosts"].items()):
        s = doc.get("summary", {})
        sched = doc.get("scheduler", {})
        rows.append((label, s.get("claimed", 0), s.get("succeeded", 0),
                     s.get("failed", 0), s.get("jobs_per_hour", 0.0),
                     sched.get("lease_reaped", 0),
                     doc.get("shard", "")) + telemetry_cols(label))
    t = report["totals"]
    rows.append(("TOTAL", t["claimed"], t["succeeded"], t["failed"],
                 t["jobs_per_hour"], t["lease_reaped"], "")
                + (("", "", "") if rollup is not None else ()))
    widths = [max(len(str(c)), *(len(str(r[i])) for r in rows))
              for i, c in enumerate(cols)]
    fmt = "  ".join(f"{{:<{w}}}" for w in widths)
    print(fmt.format(*cols))
    for row in rows:
        print(fmt.format(*(str(v) for v in row)))


def _print_health_lines(health: dict) -> None:
    """Non-ok findings + severity out of an embedded health section
    (the ``--watch`` footer; the ``health`` verb prints the full
    report)."""
    print(f"health: {health['severity'].upper()}")
    for f in health.get("findings", []):
        if f["severity"] == "ok":
            continue
        subject = f" {f['host']}" if f.get("host") else ""
        print(f"  [{f['severity'].upper()}] {f['rule']}{subject}: "
              f"{f['message']}")


def _watch_status(spool, args, sleeper=None, clock=None) -> int:
    """``status --watch``: re-render the fleet table + health findings
    every ``--interval`` seconds.  ``sleeper``/``clock`` are
    injectable so tests run N iterations without wall-clock waits."""
    from ..obs.warehouse import host_rollup
    from .fleet import fleet_report
    from .health import default_ts_dir
    from .queue import DEFAULT_LEASE_TTL_S
    from .retry import pause

    clock = clock or time.time
    ttl = (args.lease_ttl if args.lease_ttl is not None
           else DEFAULT_LEASE_TTL_S)
    ts_dir = default_ts_dir(spool)
    done = 0
    try:
        while True:
            if sys.stdout.isatty():
                print("\x1b[2J\x1b[H", end="")
            now = clock()
            report = fleet_report(spool, ttl)
            rollup = host_rollup(ts_dir, now=now)
            stamp = time.strftime("%H:%M:%S", time.localtime(now))
            print(f"{stamp}  spool {spool.root}  "
                  f"(refresh {args.interval:g}s, ctrl-c to stop)")
            _print_fleet_table(report, rollup=rollup)
            print("queue: " + "  ".join(
                f"{k}={v}" for k, v in report["queue"].items()))
            health = report.get("health")
            if health is not None:
                _print_health_lines(health)
            done += 1
            if args.iterations and done >= args.iterations:
                return 0
            pause(args.interval, sleeper)
    except KeyboardInterrupt:
        return 0


def cmd_status(spool, args, sleeper=None, clock=None) -> int:
    from .store import CandidateStore

    if getattr(args, "watch", False):
        return _watch_status(spool, args, sleeper=sleeper, clock=clock)
    if args.fleet:
        from .fleet import fleet_report, write_fleet_report
        from .queue import DEFAULT_LEASE_TTL_S

        report = fleet_report(
            spool, args.lease_ttl if args.lease_ttl is not None
            else DEFAULT_LEASE_TTL_S)
        _print_fleet_table(report)
        q = report["queue"]
        print("queue: " + "  ".join(f"{k}={v}"
                                    for k, v in q.items()))
        st = report["store"]
        print(f"store: {st['candidates']} candidates from "
              f"{st['sources']} observation(s) across "
              f"{len(st['shards'])} shard(s)")
        lz = report["leases"]
        if lz["stale"]:
            print(f"leases: {lz['stale']}/{lz['running']} running "
                  f"job(s) past the {lz['ttl_s']:.0f}s TTL -- run "
                  f"'requeue --expired' or start a fleet worker")
        path = write_fleet_report(spool, report)
        print(f"wrote {path}")
        return 0
    counts = spool.counts()
    print("state     jobs")
    for state, n in counts.items():
        print(f"{state:<9}{n:>5}")
    store = CandidateStore(
        os.path.join(spool.root, "candidates.jsonl"))
    print(f"store     {store.count():>5} candidates from "
          f"{len(store.sources())} observation(s)")
    pending = spool.pending_jobs()
    if pending:
        oldest = time.time() - pending[-1].submitted_utc
        print(f"oldest pending: {oldest:.0f}s")
    if args.jobs:
        for state in counts:
            for rec in spool.jobs(state):
                extra = ""
                if rec.failures:
                    last = rec.failures[-1]
                    extra = (f"  [{last.get('classification')}] "
                             f"{last.get('error', '')[:60]}")
                print(f"{state:<9}{rec.job_id}  prio={rec.priority} "
                      f"attempts={rec.attempts}  {rec.input}{extra}")
    return 0


def cmd_health(spool, args) -> int:
    from ..errors import ConfigError
    from .health import (
        build_context,
        evaluate,
        format_findings,
        write_health_report,
    )

    slo = {}
    for item in args.slo:
        key, val = _parse_override(item)
        if isinstance(val, bool) or not isinstance(val, (int, float)):
            raise ConfigError(
                f"--slo {item!r}: target must be a number of seconds")
        slo[key] = float(val)
    kw = {}
    if args.window is not None:
        kw["window_s"] = args.window
    if args.stale_after is not None:
        kw["stale_after"] = args.stale_after
    ctx = build_context(spool, ledger_path=args.ledger, slo=slo, **kw)
    report = evaluate(ctx)
    print(format_findings(report))
    if args.json_path:
        print(f"wrote {write_health_report(report, args.json_path)}")
    # crit is the CI-able signal; warn still exits 0 (worth a look,
    # but the fleet is making progress)
    return 1 if report["severity"] == "crit" else 0


def _render_why_mark(m: dict) -> str:
    """One lineage mark as a human-readable line (a declared reader of
    the ``lineage`` stream — obs/streams.py — so lint rule PSL013
    proves the keys touched here are ones the writer emits)."""
    bits = []
    if m.get("stage"):
        bits.append(f"stage={m['stage']}")
    if m.get("rule"):
        bits.append(f"rule={m['rule']}")
    if m.get("absorber"):
        bits.append(f"absorber={m['absorber']}")
    if m.get("margin") is not None:
        bits.append(f"margin={float(m['margin']):.3g}")
    if m.get("rank") is not None:
        bits.append(f"rank={m['rank']}")
    if m.get("snr") is not None:
        bits.append(f"snr={float(m['snr']):.2f}")
    if m.get("freq") is not None:
        bits.append(f"freq={float(m['freq']):.6f}")
    if m.get("dm_idx") is not None:
        bits.append(f"dm_idx={m['dm_idx']}")
    if m.get("flags"):
        flags = m["flags"]
        bits.append("flags[" + " ".join(
            (k if v is True else f"{k}={v}")
            for k, v in sorted(flags.items())) + "]")
    kind = str(m.get("kind", "?"))
    return f"{kind:<10}" + ("  " + "  ".join(bits) if bits else "")


def _print_why_chain(chain: dict, indent: int = 0) -> None:
    """Render one candidate's decision chain, recursing into the
    candidates it absorbed."""
    pad = "  " * indent
    head = "absorbed " if indent else ""
    print(f"{pad}{head}candidate {chain['id']}"
          + (f"  (run {chain['run']})" if chain.get("run") else ""))
    if chain.get("decoded"):
        print(f"{pad}  decoded")
    for m in chain.get("annotations", []):
        print(f"{pad}  {_render_why_mark(m)}")
    if chain.get("terminal") is not None:
        print(f"{pad}  {_render_why_mark(chain['terminal'])}")
    elif chain.get("decoded"):
        print(f"{pad}  (no terminal state recorded -- conservation "
              f"violation, or the run is still in flight)")
    for child in chain.get("children", []):
        _print_why_chain(child, indent + 1)


def cmd_why(spool, args) -> int:
    """``why <candidate-id>``: store record -> lineage ledger -> the
    full decision chain (absorbed children, margins, score flags, the
    fold/limit verdicts, and the injection SNR budget when the run
    was a known-answer canary)."""
    import json

    from ..obs import lineage
    from .store import ShardedCandidateStore

    cid = args.candidate_id
    store = ShardedCandidateStore(spool.root)
    # sidecar-index lookup (ISSUE 20): on a compacted store the
    # record join is a cand_id -> segment+offset map hit plus a tail
    # stream — never a shard scan
    matches = [rec for rec, _origin in store.lookup(cid)]
    ids = sorted({r["cand_id"] for r in matches})
    if len(ids) > 1:
        print(f"candidate id prefix {cid!r} is ambiguous: "
              f"{', '.join(ids[:8])}", file=sys.stderr)
        return 1
    rec = matches[-1] if matches else None
    if rec is not None:
        cid = rec["cand_id"]
        run = (rec.get("prov") or {}).get("run") or rec.get("job_id")
    else:
        run = None
    path = (args.lineage_path
            or os.path.join(spool.root, "lineage.jsonl"))
    marks = lineage.read_lineage(path, run=run)
    chain = lineage.why_chain(marks, cid)
    if rec is None and not chain["decoded"] \
            and chain["terminal"] is None:
        print(f"candidate {cid!r}: no store record and no lineage "
              f"marks (looked in {path})", file=sys.stderr)
        return 1
    if rec is not None:
        prov = rec.get("prov") or {}
        print(f"candidate {cid}  job {rec.get('job_id')}  "
              f"source {rec.get('source')}")
        print(f"  freq={rec.get('freq'):.6f} Hz  dm={rec.get('dm')}  "
              f"acc={rec.get('acc')}  snr={rec.get('snr')}"
              + ("  [canary]" if rec.get("canary") else ""))
        if prov:
            print("  provenance: " + "  ".join(
                f"{k}={prov[k]}" for k in
                ("run", "git_sha", "geometry", "lattice", "host")
                if prov.get(k)))
    _print_why_chain(chain)
    # stage-SNR budget (obs/injection.py, ISSUE 14): present when the
    # producing job ran with an injection manifest
    injection = None
    job_id = rec.get("job_id") if rec else run
    if job_id:
        rep_path = os.path.join(spool.work_dir(str(job_id)), "out",
                                "run_report.json")
        try:
            with open(rep_path, encoding="utf-8") as f:
                injection = json.load(f).get("injection")
        except (OSError, ValueError):
            injection = None
    if injection:
        snr = injection.get("snr", {})
        loss = injection.get("loss", {})
        print("  injection budget: " + "  ".join(
            f"{k}={snr[k]}" for k in
            ("whiten", "fourier_bin", "interbin", "harmonic_best",
             "peak") if k in snr))
        if loss:
            print("  injection loss:   " + "  ".join(
                f"{k}={v}" for k, v in sorted(loss.items())))
    if args.json_path:
        atomic_write_json(
            args.json_path,
            {"v": 1, "candidate_id": cid, "record": rec,
             "chain": chain, "injection": injection},
            sort_keys=True)
        print(f"wrote {args.json_path}")
    return 0


def cmd_query(spool, args) -> int:
    from .store import ShardedCandidateStore

    store = ShardedCandidateStore(spool.root)
    recs = store.query(args.freq, freq_tol=args.freq_tol,
                       max_harm=args.max_harm)
    for r in recs:
        prov = r.get("prov") or {}
        sha = f"  git={prov['git_sha']}" if prov.get("git_sha") else ""
        print(f"{r.get('cand_id', '-'):<16}  f={r['freq']:.6f} Hz  "
              f"snr={r.get('snr', 0.0):.2f}  "
              f"{os.path.basename(r.get('source', ''))}{sha}")
    print(f"{len(recs)} record(s) matching {args.freq:g} Hz "
          f"(tol {args.freq_tol:g}, max_harm {args.max_harm})")
    if args.json_path:
        atomic_write_json(
            args.json_path,
            {"v": 1, "freq": args.freq, "freq_tol": args.freq_tol,
             "max_harm": args.max_harm, "records": recs},
            sort_keys=True)
        print(f"wrote {args.json_path}")
    return 0


def cmd_coincidence(spool, args) -> int:
    from .store import ShardedCandidateStore

    store = ShardedCandidateStore(spool.root)
    groups = store.coincident_groups(
        freq_tol=args.freq_tol, min_sources=args.min_sources)
    for i, group in enumerate(groups):
        best = group[0]
        srcs = sorted({os.path.basename(r.get("source", ""))
                       for r in group})
        cid = best.get("cand_id")
        print(f"group {i}: f={best['freq']:.6f} Hz  "
              f"snr={best.get('snr', 0.0):.2f}  "
              + (f"id={cid}  " if cid else "")
              + f"{len(group)} detection(s) in {len(srcs)} "
              f"observation(s): {', '.join(srcs)}")
    print(f"{len(groups)} coincident group(s) across "
          f"{len(store.shard_files())} shard(s)")
    if args.json_path:
        import json

        atomic_write_json(args.json_path,
                          {"v": 1, "freq_tol": args.freq_tol,
                           "min_sources": args.min_sources,
                           "groups": groups}, sort_keys=True)
        print(f"wrote {args.json_path}")
    return 0


def cmd_compact(spool, args) -> int:
    from .compaction import (CompactionLocked, CompactionPolicy,
                             Compactor, shard_tail_sizes)
    from .segments import load_manifest

    if args.status:
        man = load_manifest(spool.root)
        segs = man.get("segments", [])
        total = sum(int(s.get("records", 0)) for s in segs)
        print(f"{len(segs)} sealed segment(s), {total} record(s)")
        for s in segs:
            print(f"  {s['name']}: {s['records']} rec  "
                  f"{s['bytes']} B  "
                  f"f=[{s['freq_min']:.6f}, {s['freq_max']:.6f}] Hz")
        for base, tail in sorted(shard_tail_sizes(spool.root).items()):
            print(f"  tail {base}: {tail} unsealed byte(s)")
        return 0

    kw = {}
    if args.min_bytes is not None:
        kw["min_bytes"] = args.min_bytes
    policy = CompactionPolicy(min_age_s=args.min_age, **kw)
    fault = None
    if args.fault_stage:
        # chaos drills: die with the disk in exactly the state a
        # SIGKILLed compactor would leave (no unwind, no cleanup)
        stage = args.fault_stage

        def fault(s, _stage=stage):
            if s == _stage:
                os._exit(137)

    comp = Compactor(spool.root, policy,
                     **({"fault": fault} if fault else {}))
    try:
        report = comp.compact_once(force=args.force)
    except CompactionLocked as exc:
        print(f"compaction locked: {exc}", file=sys.stderr)
        return 1
    if report.get("compacted"):
        print(f"sealed {report['segment']}: {report['records']} "
              f"record(s) from {len(report['shards'])} shard(s) in "
              f"{report['duration_s']:.3f}s "
              f"({report['duplicates_dropped']} duplicate(s) "
              f"dropped, {report['supersedes']} superseded)")
    else:
        print(f"nothing to compact ({report.get('reason', '?')})")
    if args.history and report.get("compacted"):
        from ..obs.history import append_history, make_history_record
        append_history(make_history_record(
            "store",
            {"compaction_s": report["duration_s"],
             "compacted_records": report["records"]},
            config={"spool": spool.root,
                    "segment": report["segment"]},
            extra={"utc": round(time.time(), 3)}))
    return 0


def cmd_query_service(spool, args) -> int:
    from .query_service import QueryService

    svc = QueryService(spool.root, ledger_path=args.ledger_path)
    if args.once:
        served = svc.poll_once()
    else:
        served = svc.run(poll_s=args.poll,
                         max_requests=args.max_requests)
    print(f"query-service answered {served} request(s)")
    return 0


def cmd_timeline(spool, args) -> int:
    import json

    from ..obs import timeline

    work = os.path.join(spool.root, "work", args.job_id)
    marks = timeline.read_timeline(work)
    if not marks:
        print(f"no timeline marks for job {args.job_id!r} "
              f"(looked in {timeline.timeline_path(work)})",
              file=sys.stderr)
        return 1
    doc = timeline.waterfall(marks, job_id=args.job_id)
    state = spool.get(args.job_id)
    if state is not None:
        doc["state"] = state[0]
    print(timeline.render_waterfall(doc, width=args.width))
    if args.json_path:
        atomic_write_json(args.json_path, doc, sort_keys=True)
        print(f"wrote {args.json_path}")
    if args.trace_path:
        print(f"wrote {timeline.write_trace_json(args.trace_path, doc)}")
    return 0


def cmd_requeue(spool, args) -> int:
    if args.expired:
        from .queue import DEFAULT_LEASE_TTL_S

        ttl = (args.lease_ttl if args.lease_ttl is not None
               else DEFAULT_LEASE_TTL_S)
        reaped = spool.reap_expired(ttl)
        for rec in reaped:
            print(f"reaped {rec.job_id}  attempts={rec.attempts}  "
                  f"{rec.input}")
        # zero expired leases is a healthy fleet, not an error
        print(f"{len(reaped)} lease-expired job(s) back to pending")
        if args.job_ids or args.running or args.failed:
            print("(--expired given; other selectors ignored)",
                  file=sys.stderr)
        return 0
    ids = list(args.job_ids)
    if args.running:
        ids += [r.job_id for r in spool.jobs("running")]
    if args.failed:
        ids += [r.job_id for r in spool.jobs("failed")]
    if not ids:
        print("nothing to requeue (give job ids, --running, --failed "
              "or --expired)", file=sys.stderr)
        return 1
    for job_id in ids:
        rec = spool.requeue(job_id)
        print(f"requeued {rec.job_id}  attempts={rec.attempts}  "
              f"{rec.input}")
    return 0


def main(argv=None) -> int:
    args = build_parser().parse_args(
        sys.argv[1:] if argv is None else argv)
    from .queue import JobSpool

    spool = JobSpool(args.spool)
    return {
        "submit": cmd_submit,
        "worker": cmd_worker,
        "fleet-worker": cmd_fleet_worker,
        "supervise": cmd_supervise,
        "admission": cmd_admission,
        "status": cmd_status,
        "health": cmd_health,
        "why": cmd_why,
        "query": cmd_query,
        "coincidence": cmd_coincidence,
        "compact": cmd_compact,
        "query-service": cmd_query_service,
        "timeline": cmd_timeline,
        "requeue": cmd_requeue,
    }[args.verb](spool, args)


if __name__ == "__main__":
    raise SystemExit(main())
