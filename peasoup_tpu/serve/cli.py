"""``python -m peasoup_tpu.serve`` — the survey scheduler CLI.

Verbs::

    submit  <files...> [--priority N] [--set key=value ...]
    worker  [--drain] [--max-jobs N] [--poll S] [--single_device] ...
    status  [--jobs]
    requeue <job_ids...> | --running | --failed

All verbs take ``--spool DIR`` (default ``./jobs``): the durable spool
directory described in serve/queue.py.  ``submit`` enqueues
observations; ``worker`` claims and runs them (``--drain`` exits when
the queue empties, otherwise it polls forever); ``status`` prints the
queue + store state; ``requeue`` recovers jobs from a crashed worker
(``running/``) or retries quarantined ones (``failed/``).
"""

from __future__ import annotations

import argparse
import os
import sys
import time


def _parse_override(text: str) -> tuple[str, object]:
    """``key=value`` with the value coerced like the main CLI would:
    int, then float, then bool literals, else string."""
    if "=" not in text:
        from ..errors import ConfigError

        raise ConfigError(f"--set expects key=value, got {text!r}")
    key, raw = text.split("=", 1)
    for cast in (int, float):
        try:
            return key, cast(raw)
        except ValueError:
            pass
    if raw.lower() in ("true", "false"):
        return key, raw.lower() == "true"
    return key, raw


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="peasoup-tpu-serve",
        description="Peasoup-TPU - survey scheduler (job spool + "
                    "workers + candidate store)",
    )
    p.add_argument("--spool", default="./jobs",
                   help="spool directory (pending/running/done/failed)")
    sub = p.add_subparsers(dest="verb", required=True)

    ps = sub.add_parser("submit", help="enqueue observations")
    ps.add_argument("inputs", nargs="+", help="filterbank files")
    ps.add_argument("--priority", type=int, default=0,
                    help="higher claims first (FIFO within a band)")
    ps.add_argument("--set", dest="overrides", action="append",
                    default=[], metavar="KEY=VALUE",
                    help="SearchConfig override (repeatable), e.g. "
                         "--set dm_end=120 --set npdmp=8")

    pw = sub.add_parser("worker", help="claim and run jobs")
    pw.add_argument("--drain", action="store_true",
                    help="exit when the queue is empty (default: "
                         "poll forever)")
    pw.add_argument("--max-jobs", type=int, default=None,
                    help="stop after claiming this many jobs")
    pw.add_argument("--poll", type=float, default=5.0,
                    help="idle poll interval in seconds (no --drain)")
    pw.add_argument("--timeout", type=float, default=0.0,
                    help="per-job wall-clock budget in seconds "
                         "(0 = unlimited)")
    pw.add_argument("--max-attempts", type=int, default=3,
                    help="bounded retries before a job is failed")
    pw.add_argument("--backoff-base", type=float, default=1.0,
                    help="first-retry backoff in seconds (doubles "
                         "per attempt, capped at 60)")
    pw.add_argument("--single_device", action="store_true",
                    help="host-loop driver instead of the mesh")
    pw.add_argument("-t", "--num_threads", type=int, default=14,
                    dest="max_num_threads",
                    help="device cap for the mesh driver")
    pw.add_argument("--no-prefetch", action="store_true",
                    help="disable next-observation read overlap")
    pw.add_argument("--history", default=None,
                    help="throughput ledger path (default: the repo "
                         "benchmarks/history.jsonl)")

    pt = sub.add_parser("status", help="queue + store summary")
    pt.add_argument("--jobs", action="store_true",
                    help="list individual jobs per state")

    pr = sub.add_parser("requeue", help="move jobs back to pending")
    pr.add_argument("job_ids", nargs="*", help="specific job ids")
    pr.add_argument("--running", action="store_true",
                    help="requeue every running job (crashed worker "
                         "recovery)")
    pr.add_argument("--failed", action="store_true",
                    help="requeue every failed job (operator retry)")
    return p


def cmd_submit(spool, args) -> int:
    overrides = dict(_parse_override(o) for o in args.overrides)
    for path in args.inputs:
        rec = spool.submit(path, overrides, priority=args.priority)
        print(f"submitted {rec.job_id}  priority={rec.priority}  "
              f"{rec.input}")
    return 0


def cmd_worker(spool, args) -> int:
    from ..obs.events import configure_event_log
    from ..utils import enable_compile_cache
    from .retry import BackoffPolicy
    from .worker import SurveyWorker

    enable_compile_cache()
    configure_event_log(os.path.join(spool.root, "worker-events.jsonl"))
    worker = SurveyWorker(
        spool,
        backoff=BackoffPolicy(max_attempts=args.max_attempts,
                              base_s=args.backoff_base),
        timeout_s=args.timeout,
        single_device=args.single_device,
        max_devices=args.max_num_threads,
        prefetch=not args.no_prefetch,
        history_path=args.history,
    )
    summary = worker.drain(max_jobs=args.max_jobs,
                           wait=not args.drain, poll_s=args.poll)
    print(f"worker {worker.worker_id}: {summary['succeeded']}/"
          f"{summary['claimed']} jobs ok in {summary['elapsed_s']}s "
          f"({summary['jobs_per_hour']} jobs/h, "
          f"{summary['geometry_buckets']} geometry bucket(s))")
    return 0 if summary["failed"] == 0 else 1


def cmd_status(spool, args) -> int:
    from .store import CandidateStore

    counts = spool.counts()
    print("state     jobs")
    for state, n in counts.items():
        print(f"{state:<9}{n:>5}")
    store = CandidateStore(
        os.path.join(spool.root, "candidates.jsonl"))
    print(f"store     {store.count():>5} candidates from "
          f"{len(store.sources())} observation(s)")
    pending = spool.pending_jobs()
    if pending:
        oldest = time.time() - pending[-1].submitted_utc
        print(f"oldest pending: {oldest:.0f}s")
    if args.jobs:
        for state in counts:
            for rec in spool.jobs(state):
                extra = ""
                if rec.failures:
                    last = rec.failures[-1]
                    extra = (f"  [{last.get('classification')}] "
                             f"{last.get('error', '')[:60]}")
                print(f"{state:<9}{rec.job_id}  prio={rec.priority} "
                      f"attempts={rec.attempts}  {rec.input}{extra}")
    return 0


def cmd_requeue(spool, args) -> int:
    ids = list(args.job_ids)
    if args.running:
        ids += [r.job_id for r in spool.jobs("running")]
    if args.failed:
        ids += [r.job_id for r in spool.jobs("failed")]
    if not ids:
        print("nothing to requeue (give job ids, --running or "
              "--failed)", file=sys.stderr)
        return 1
    for job_id in ids:
        rec = spool.requeue(job_id)
        print(f"requeued {rec.job_id}  attempts={rec.attempts}  "
              f"{rec.input}")
    return 0


def main(argv=None) -> int:
    args = build_parser().parse_args(
        sys.argv[1:] if argv is None else argv)
    from .queue import JobSpool

    spool = JobSpool(args.spool)
    return {
        "submit": cmd_submit,
        "worker": cmd_worker,
        "status": cmd_status,
        "requeue": cmd_requeue,
    }[args.verb](spool, args)


if __name__ == "__main__":
    raise SystemExit(main())
