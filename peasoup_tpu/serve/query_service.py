"""Always-on survey query service (ISSUE 20).

Science queries over the candidate store are a first-class workload
with their own SLOs, not an ad-hoc log replay: this module is the
long-lived loop behind the ``peasoup-serve query-service`` verb.  It
serves three read ops over the log-structured store
(serve/store.py + serve/segments.py):

``query``        harmonically related records
                 (``freq``, ``freq_tol``, ``max_harm``)
``coincidence``  cross-observation groups
                 (``freq_tol``, ``min_sources``)
``why``          record → lineage join by ``cand_id`` prefix — the
                 sidecar-index lookup the ``why`` verb uses
                 (``cand_id``, optional ``run_dir``)

Transport is the spool's own medium — files, not sockets: a client
drops ``queries/q-<id>.json`` (atomic rename, like every spool
artifact) and collects ``queries/q-<id>.result.json``; the service
polls the inbox on a ``threading.Event`` wait (PSL008-clean, same
idiom as the supervisor loop).  In-process callers skip the files and
call :meth:`QueryService.serve_request`.

Every request appends one ``kind:"query"`` record to the bench
history ledger (obs/history.py) with its latency and result size —
the stream the ``query_latency`` SLO rule (serve/health.py) and the
perf gate's ``store_query_p50_ms`` metric read."""

from __future__ import annotations

import json
import os
import threading
import time
import uuid

from ..obs.history import append_history, make_history_record
from ..obs.metrics import REGISTRY as METRICS
from ..utils.atomicio import atomic_write_json
from .store import ShardedCandidateStore

QUERIES_DIRNAME = "queries"

REQUEST_PREFIX = "q-"

#: ops the service accepts; anything else is answered with an error
#: result (never a crash — a malformed request must not kill the loop)
OPS = ("query", "coincidence", "why")


def queries_dir(root: str) -> str:
    return os.path.join(os.path.abspath(root), QUERIES_DIRNAME)


def submit_request(root: str, req: dict) -> str:
    """Client side: drop one request into the inbox (atomic rename so
    the service never reads a torn request).  Returns the request id;
    the result will land at :func:`result_path`."""
    d = queries_dir(root)
    os.makedirs(d, exist_ok=True)
    rid = str(req.get("id") or uuid.uuid4().hex[:12])
    req = dict(req, id=rid)
    atomic_write_json(os.path.join(d, f"{REQUEST_PREFIX}{rid}.json"),
                      req, sort_keys=True, trailing_newline=True)
    return rid


def result_path(root: str, rid: str) -> str:
    return os.path.join(queries_dir(root),
                        f"{REQUEST_PREFIX}{rid}.result.json")


class QueryService:
    """One store's query loop.  Injectable clock and stop event (the
    supervisor pattern) keep it deterministic under test."""

    def __init__(self, root: str, *, ledger_path: str | None = None,
                 clock=time.perf_counter, utc=time.time,
                 stop_event: threading.Event | None = None):
        self.root = os.path.abspath(root)
        self.ledger_path = ledger_path
        self.clock = clock
        self.utc = utc
        self._stop = stop_event or threading.Event()
        self.served = 0

    def stop(self) -> None:
        self._stop.set()

    # -- op handlers -------------------------------------------------------

    def _store(self) -> ShardedCandidateStore:
        return ShardedCandidateStore(self.root)

    def _op_query(self, store, req: dict) -> dict:
        hits = store.query(float(req["freq"]),
                           float(req.get("freq_tol", 1e-4)),
                           int(req.get("max_harm", 1)))
        return {"records": hits}

    def _op_coincidence(self, store, req: dict) -> dict:
        groups = store.coincident_groups(
            float(req.get("freq_tol", 1e-4)),
            int(req.get("min_sources", 2)))
        return {"groups": groups}

    def _op_why(self, store, req: dict) -> dict:
        """The ``why`` verb's record join: sidecar-index lookup of the
        newest record per matching cand id, plus each record's origin
        (segment name or live shard basename)."""
        prefix = str(req.get("cand_id", ""))
        if not prefix:
            raise ValueError("why needs a cand_id prefix")
        hits = store.lookup(prefix)
        return {
            "records": [
                dict(rec, _origin=origin) for rec, origin in hits
            ],
        }

    # -- request plumbing --------------------------------------------------

    def serve_request(self, req: dict) -> dict:
        """Answer one request dict; always returns a result dict
        (``ok`` False + ``error`` on a bad request) and always appends
        the ``kind:"query"`` latency ledger record."""
        t0 = float(self.clock())
        op = str(req.get("op", ""))
        try:
            store = self._store()
            if op == "query":
                body = self._op_query(store, req)
            elif op == "coincidence":
                body = self._op_coincidence(store, req)
            elif op == "why":
                body = self._op_why(store, req)
            else:
                raise ValueError(f"unknown op {op!r} (expected one of "
                                 f"{', '.join(OPS)})")
            result = {"ok": True, "op": op, **body}
            nrec = len(body.get("records", body.get("groups", ())))
        except (KeyError, TypeError, ValueError) as exc:
            result = {"ok": False, "op": op, "error": str(exc)}
            nrec = 0
        latency_ms = (float(self.clock()) - t0) * 1000.0
        result["latency_ms"] = round(latency_ms, 3)
        if "id" in req:
            result["id"] = req["id"]
        self.served += 1
        METRICS.inc("store.query_requests")
        self._ledger(op, latency_ms, nrec, result["ok"])
        return result

    def _ledger(self, op: str, latency_ms: float, nrec: int,
                ok: bool) -> None:
        rec = make_history_record(
            "query",
            {"query_latency_ms": round(latency_ms, 3),
             "result_records": int(nrec)},
            config={"spool": self.root, "op": op, "ok": bool(ok)},
            extra={"utc": round(float(self.utc()), 3)},
        )
        append_history(rec, self.ledger_path)

    # -- the inbox loop ----------------------------------------------------

    def poll_once(self) -> int:
        """Serve every pending inbox request; returns how many."""
        d = queries_dir(self.root)
        try:
            names = sorted(os.listdir(d))
        except OSError:
            return 0
        served = 0
        for name in names:
            if not name.startswith(REQUEST_PREFIX):
                continue
            if not name.endswith(".json") or \
                    name.endswith(".result.json"):
                continue
            path = os.path.join(d, name)
            try:
                with open(path, encoding="utf-8") as f:
                    req = json.load(f)
            except (OSError, ValueError):
                continue  # mid-rename or garbage: next poll
            if not isinstance(req, dict):
                req = {"op": "invalid"}
            rid = str(req.get("id")
                      or name[len(REQUEST_PREFIX):-len(".json")])
            req.setdefault("id", rid)
            result = self.serve_request(req)
            atomic_write_json(result_path(self.root, rid), result,
                              sort_keys=True, trailing_newline=True,
                              default=str)
            try:
                os.unlink(path)
            except OSError:
                pass
            served += 1
        return served

    def run(self, *, poll_s: float = 0.5,
            max_requests: int = 0) -> int:
        """The service loop: drain the inbox, wait, repeat — until
        :meth:`stop` (or ``max_requests`` answered, for drills and
        tests).  Returns requests served this run."""
        served = 0
        while not self._stop.is_set():
            served += self.poll_once()
            if max_requests and served >= max_requests:
                break
            if self._stop.wait(float(poll_s)):
                break
        return served


def wait_result(root: str, rid: str, *, timeout_s: float = 30.0,
                poll_s: float = 0.05,
                stop_event: threading.Event | None = None) -> dict | None:
    """Client side: block until the service answers ``rid`` (or the
    timeout passes); waits on an Event, never a bare sleep."""
    ev = stop_event or threading.Event()
    path = result_path(root, rid)
    deadline = time.monotonic() + float(timeout_s)
    while time.monotonic() < deadline:
        try:
            with open(path, encoding="utf-8") as f:
                return json.load(f)
        except (OSError, ValueError):
            pass
        if ev.wait(float(poll_s)):
            return None
    return None
