"""Long-running survey worker: claim -> search -> ingest, repeated.

The driver that turns the single-shot pipeline into a service.  Per
job it

1. claims the best pending job (priority, then FIFO) from the spool;
2. reads the observation — from the prefetch slot when the previous
   iteration already fetched it (see below) — and builds the existing
   :class:`~peasoup_tpu.search.pipeline.PulsarSearch` /
   :class:`~peasoup_tpu.parallel.mesh.MeshPulsarSearch` on it;
3. kicks a background read+unpack of the NEXT pending observation, so
   host I/O overlaps the current job's device search — the
   ``utils/hostfetch``-style double buffering of the chunked driver,
   lifted to observation granularity;
4. runs the search under a ``Job-<id>`` root span, writes the usual
   per-run artefacts (overview.xml, run_report.json) into the job's
   work directory, and ingests the distilled candidates into the
   cross-run store;
5. on failure, classifies (serve/retry.py): quarantine straight to
   ``failed/``, transient back to ``pending/`` after backoff, with
   the captured run report + traceback on the job record either way.

Program reuse across jobs: jitted programs are keyed by array shapes,
so the worker buckets each observation's geometry to the plan shapes
— observations whose sample counts share a power-of-two FFT size are
LOSSLESSLY trimmed to ``size + max_delay + 1`` samples (the search
reads nothing beyond that: trials use the first ``size`` columns and
the fold's power-of-two length is preserved by the ``+ 1``), so every
job in the bucket replays the already-compiled programs instead of
paying a per-observation XLA compile.

Per-job checkpointing: each job gets a checkpoint file in its work
directory, so a worker killed mid-job resumes that job's completed DM
rows on the next claim instead of recomputing from scratch.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
import traceback
from dataclasses import replace

from ..errors import ConfigError
from ..obs import timeline
from ..obs.events import warn_event
from ..obs.metrics import REGISTRY as METRICS
from ..obs.trace import device_seconds, span, span_cursor
from .queue import JobRecord, JobSpool
from .retry import (
    QUARANTINE,
    BackoffPolicy,
    abandoned_count,
    classify_failure,
    pause,
    run_with_timeout,
)
from .store import CandidateStore

#: per-job ``events.jsonl`` byte budget (ISSUE 16): a retry-looping
#: or event-heavy job rotates its log to ``events.jsonl.1`` instead
#: of growing without bound — the fleet's disk footprint stays
#: proportional to job count, not event volume
EVENT_LOG_MAX_BYTES = 512 * 1024


class ObservationPrefetcher:
    """Multi-slot background filterbank reader (double buffering at
    observation granularity; ``slots`` of them for batched dispatch).

    ``start(path)`` spawns a daemon thread reading + unpacking the
    file while the caller's search occupies the devices; ``take(path)``
    joins and hands the :class:`Filterbank` over — or returns None on
    a slot miss (a different job won the claim) or a read error (the
    claimer's own synchronous read then raises the real, classifiable
    exception in job context).  With ``slots > 1`` the batched worker
    fills the NEXT batch's observations while the current batch is on
    device; when full, the oldest slot is evicted (its read result is
    simply dropped — prefetch is only ever a hint).

    ``device_stage`` (ISSUE 11) extends the prefetch one stage toward
    the device: after a successful read the same thread calls
    ``device_stage(fil, job)`` — the worker's pack + ``device_put`` of
    the raw bytes — so the h2d upload ALSO overlaps the previous job's
    search.  The staged value of the most recent successful ``take``
    is parked on ``self.last_staged`` (None on misses or when staging
    failed; staging failures never fail the prefetch — the read result
    alone is still a hit).
    """

    def __init__(self, slots: int = 1, device_stage=None):
        self.slots = max(1, int(slots))
        self.device_stage = device_stage
        self.last_staged = None
        # path -> {"thread", "result", "error", "staged", "job"};
        # insertion-ordered so eviction drops the oldest prefetch first
        self._inflight: dict[str, dict] = {}

    def start(self, path: str, job=None) -> None:
        if path in self._inflight:
            return  # already in flight (or landed) for this path
        while len(self._inflight) >= self.slots:
            oldest = next(iter(self._inflight))
            slot = self._inflight.pop(oldest)
            if slot["thread"].is_alive():
                slot["thread"].join()  # reads are short next to a search
        slot = {"thread": None, "result": None, "error": None,
                "staged": None, "job": job}

        def _read():
            from ..io.sigproc import read_filterbank

            try:
                slot["result"] = read_filterbank(path)
            except BaseException as exc:
                slot["error"] = exc
                return
            if self.device_stage is not None and slot["job"] is not None:
                try:
                    slot["staged"] = self.device_stage(
                        slot["result"], slot["job"])
                except BaseException:
                    pass  # a hint, never a failure: upload on claim

        slot["thread"] = threading.Thread(
            target=_read, daemon=True, name="serve-prefetch")
        self._inflight[path] = slot
        slot["thread"].start()

    def take(self, path: str):
        self.last_staged = None
        slot = self._inflight.pop(path, None)
        if slot is None:
            # plain slot miss (a different job won the claim): routine
            # at the drain tail, so a counter is enough
            METRICS.inc("scheduler.prefetch_misses")
            return None
        slot["thread"].join()
        if slot["error"] is not None or slot["result"] is None:
            # classified miss (ISSUE 11 satellite): the claimer's
            # synchronous re-read will raise the real exception in job
            # context, but the EVENT log should already say what the
            # background read hit and how retry.py would class it
            err = slot["error"]
            kind = classify_failure(err) if err is not None else "unknown"
            METRICS.inc("scheduler.prefetch_misses")
            METRICS.inc(f"scheduler.prefetch_miss.{kind}")
            warn_event(
                "prefetch_miss",
                f"background prefetch of {path} failed "
                f"({type(err).__name__ if err is not None else 'no result'}"
                f"; classified {kind}); falling back to a synchronous "
                f"read",
                path=path, classification=kind,
                error=(f"{type(err).__name__}: {err}"
                       if err is not None else ""),
            )
            return None
        METRICS.inc("scheduler.prefetch_hits")
        self.last_staged = slot.get("staged")
        return slot["result"]


class SurveyWorker:
    """Claims and runs spool jobs until the queue drains (or a job
    budget is reached).

    ``run_job_fn`` is injectable for tests: it replaces the real
    search (:meth:`_run_job`) but keeps the whole claim / classify /
    retry / quarantine machinery live.  ``sleeper`` routes backoff
    waits (serve/retry.py) to a fake in tests.
    """

    def __init__(self, spool: JobSpool, store: CandidateStore | None = None,
                 *, base_config=None, backoff: BackoffPolicy | None = None,
                 timeout_s: float = 0.0, single_device: bool = False,
                 max_devices: int | None = None, worker_id: str = "",
                 prefetch: bool = True, run_job_fn=None,
                 history_path: str | None = None, sleeper=None,
                 batch: int = 1, telemetry_interval_s: float = 5.0,
                 profile_every: int = 0, profile_dir: str | None = None,
                 lineage: bool = True):
        self.spool = spool
        self.store = store if store is not None else CandidateStore(
            os.path.join(spool.root, "candidates.jsonl"))
        self.base_config = base_config
        self.backoff = backoff if backoff is not None else BackoffPolicy()
        self.timeout_s = float(timeout_s)
        self.single_device = single_device
        self.max_devices = max_devices
        self.worker_id = worker_id or f"worker-{os.getpid()}"
        #: fleet host label stamped on claims ("" single-host; set by
        #: serve/fleet.py FleetWorker)
        self.host_label = ""
        self.prefetch = prefetch
        self.run_job_fn = run_job_fn
        self.history_path = history_path
        self.sleeper = sleeper
        #: batched dispatch (ISSUE 9): stack up to ``batch``
        #: same-geometry pending jobs into ONE fused device program per
        #: round trip; 1 = historical per-job dispatch
        self.batch = max(1, int(batch))
        #: live telemetry cadence (obs/telemetry.py); 0 disables the
        #: sampler.  The shard lands in the spool's ``fleet/`` dir so
        #: ``health`` / ``status --watch`` see single-host workers too
        self.telemetry_interval_s = float(telemetry_interval_s)
        #: sampled device profiling (ISSUE 18): capture a jax.profiler
        #: trace for every Nth job (0 disables).  Tolerant no-op where
        #: the profiler is unavailable; each capture lands under
        #: ``profile_dir`` and is registered in the compile ledger
        #: (kind ``profile``) so the warehouse knows the artifact path
        self.profile_every = max(0, int(profile_every))
        self.profile_dir = profile_dir or os.path.join(
            spool.root, "profiles")
        #: candidate provenance (ISSUE 19): record every selection
        #: decision into the spool's ``lineage.jsonl``; False is the
        #: ``--no-lineage`` escape hatch (candidate output is
        #: bit-identical either way)
        self.lineage = bool(lineage)
        self._jobs_started = 0
        #: observation-granularity pipeline depth (ISSUE 11): how many
        #: jobs ahead the prefetcher reads (and device-stages).  Jobs
        #: are still CLAIMED one at a time — lookahead uses peeks, so a
        #: crashed worker never holds leases on unstarted jobs
        self.pipeline_depth = max(1, int(getattr(
            base_config, "pipeline_depth", 2) or 1))
        self._prefetcher = ObservationPrefetcher(
            slots=max(self.batch, self.pipeline_depth),
            device_stage=(None if single_device
                          else self._stage_observation),
        )
        #: geometry bucket -> jobs served (program-reuse accounting)
        self.geometries: dict[tuple, int] = {}
        #: per-drain latency samples for the serve ledger record:
        #: submit->done sojourns (timeline-derived) and submit->claim
        #: waits of every job this worker finished
        self._sojourns: list[float] = []
        self._queue_waits: list[float] = []
        #: run ids (job ids) finished this drain — the funnel scope of
        #: the drain summary's lineage block
        self._drained_runs: list[str] = []

    # -- config / geometry -------------------------------------------------

    def _job_config(self, job: JobRecord):
        """Base config + the job's overrides + per-job spool paths."""
        from ..search.plan import SearchConfig

        cfg = (replace(self.base_config) if self.base_config is not None
               else SearchConfig())
        for key, val in (job.overrides or {}).items():
            if not hasattr(cfg, key):
                raise ConfigError(
                    f"job {job.job_id}: unknown SearchConfig override "
                    f"{key!r}")
            setattr(cfg, key, val)
        cfg.infilename = job.input
        work = self.spool.work_dir(job.job_id)
        cfg.outdir = os.path.join(work, "out")
        # lineage run id (ISSUE 19): every decision mark this job's
        # search emits is attributed to the job, so per-job funnels
        # and the `why` verb scope to one observation exactly
        cfg.lineage_run = job.job_id
        if not cfg.checkpoint_file:
            # crash-resume: a re-claimed job resumes its completed DM
            # rows instead of recomputing (search/checkpoint.py keys
            # on header content, so the spool can even be relocated)
            cfg.checkpoint_file = os.path.join(work, "search.ckpt")
        return cfg

    def _build_search(self, fil, cfg):
        """Construct the search, bucketing geometry for program reuse
        (lossless trim — see module docstring)."""
        if self.single_device:
            from ..search.pipeline import PulsarSearch

            make = lambda f: PulsarSearch(f, cfg)
        else:
            from ..parallel.mesh import MeshPulsarSearch

            make = lambda f: MeshPulsarSearch(
                f, cfg, max_devices=self.max_devices)
        search = make(fil)
        keep = search.size + search.max_delay + 1
        if fil.nsamps > keep:
            from ..io.sigproc import Filterbank

            cfg.size = search.size  # pin: the trim must not shrink it
            hdr = replace(fil.header, nsamples=keep)
            fil = Filterbank(header=hdr, data=fil.data[:keep])
            search = make(fil)
            METRICS.inc("scheduler.geometry_trimmed")
        gkey = (fil.nchans, fil.header.nbits, search.size,
                int(search.out_nsamps), len(search.dm_list))
        if gkey in self.geometries:
            METRICS.inc("scheduler.plan_reuse")
        self.geometries[gkey] = self.geometries.get(gkey, 0) + 1
        # compile attribution (ISSUE 18): every backend compile fired
        # while this search runs is ledgered against the reuse-bucket
        # geometry — a cold bucket shows its compiles, a warm one shows
        # recompiles (the compile_storm health rule watches the latter)
        from ..obs.compilation import set_compile_context

        set_compile_context(
            program="serve.search",
            geometry={"nchans": gkey[0], "nbits": gkey[1],
                      "size": gkey[2], "out_nsamps": gkey[3],
                      "n_dm": gkey[4]})
        return fil, search

    def _stage_observation(self, fil, job: JobRecord):
        """Prefetch device stage (ISSUE 11): pack + upload the raw
        filterbank bytes from the prefetch thread, so the h2d transfer
        overlaps the PREVIOUS job's device time instead of sitting on
        the claim's critical path.  Runs the same lossless trim as
        ``_build_search`` so the staged vector matches the geometry
        the search will ask for (``_staged_raw_device`` re-validates
        shape/dtype before trusting it).  Single-process only: the
        multi-host ``put_global`` assembly is not thread-safe against
        a concurrently dispatching main thread."""
        import jax

        if self.single_device or jax.process_count() != 1:
            return None
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..io.sigproc import Filterbank
        from ..parallel.mesh import MeshPulsarSearch
        from ..utils.hostfetch import put_global

        cfg = self._job_config(job)
        search = MeshPulsarSearch(fil, cfg, max_devices=self.max_devices)
        keep = search.size + search.max_delay + 1
        if fil.nsamps > keep:
            hdr = replace(fil.header, nsamples=keep)
            fil = Filterbank(header=hdr, data=fil.data[:keep])
        raw = search._pack_raw(fil)
        staged = put_global(raw, NamedSharding(search.mesh, P()))
        METRICS.inc("scheduler.staged_raw_uploads")
        return staged

    # -- batched dispatch (ISSUE 9) ----------------------------------------

    def _batch_key(self, job: JobRecord):
        """Geometry fingerprint computable from the HEADER alone.

        Two jobs may share one batched dispatch iff they resolve to
        the identical plan: same overrides and same (nchans, nbits,
        tsamp, fch1, foff) — which fix the delay table and accel grid
        — plus the same fft ``size`` and the same effective (post
        lossless-trim) sample count.  Deliberately STRICTER than
        ``_build_search``'s reuse bucket, which omits the frequency
        axis.  Only the SIGPROC header is read (cheap), never the
        data.  None = don't batch this job (unreadable header, odd
        config); it then runs through the normal solo path.
        """
        try:
            cfg = self._job_config(job)
            from ..io.sigproc import read_sigproc_header

            with open(job.input, "rb") as f:
                hdr = read_sigproc_header(f)
            from ..ops import delay_table, generate_dm_list, max_delay
            from ..search.plan import prev_power_of_two

            if cfg.dm_list is not None:
                import numpy as np

                dm_list = np.asarray(cfg.dm_list, dtype=np.float32)
            elif cfg.dm_file:
                from ..search.pipeline import load_dm_file

                dm_list = load_dm_file(cfg.dm_file)
            else:
                dm_list = generate_dm_list(
                    cfg.dm_start, cfg.dm_end, hdr.tsamp,
                    cfg.dm_pulse_width, hdr.fch1, hdr.foff, hdr.nchans,
                    cfg.dm_tol,
                )
            md = max_delay(dm_list, delay_table(
                hdr.nchans, hdr.tsamp, hdr.fch1, hdr.foff))
            size = cfg.size or prev_power_of_two(hdr.nsamples)
            eff = min(int(hdr.nsamples), int(size) + int(md) + 1)
            ovr = tuple(sorted(
                (k, repr(v)) for k, v in (job.overrides or {}).items()))
            return (ovr, int(hdr.nchans), int(hdr.nbits),
                    float(hdr.tsamp), float(hdr.fch1), float(hdr.foff),
                    int(size), eff,
                    # jerk axis + trial lattice change the padded grid
                    # and the traced program — never batch across them
                    float(cfg.jerk_start), float(cfg.jerk_end),
                    float(cfg.jerk_step), str(cfg.trial_lattice))
        except Exception:
            return None

    def _claim_batch_mates(self, leader: JobRecord,
                           room: int) -> list[JobRecord]:
        """Claim up to ``room`` pending jobs sharing the leader's
        batch key (bucket-fill: mates jump the priority queue — a full
        batch beats strict queue order because the marginal cost of a
        same-bucket beam is near zero)."""
        key = self._batch_key(leader)
        if key is None:
            return []
        mates: list[JobRecord] = []
        for rec in self.spool.pending_jobs():
            if len(mates) >= room:
                break
            if self._batch_key(rec) != key:
                continue
            got = self.spool.claim_job(
                rec.job_id, self.worker_id, host=self.host_label)
            if got is not None:  # lost races just shrink the batch
                self._mark_job(got, "batch-claim",
                               leader=leader.job_id)
                mates.append(got)
        return mates

    # -- lifecycle timeline (obs/timeline.py) ------------------------------

    def _mark_job(self, job: JobRecord, phase: str, **attrs) -> None:
        """One worker-side mark in the job's lifecycle timeline."""
        timeline.mark(self.spool.work_dir(job.job_id), phase,
                      host=self.host_label, attempt=job.attempts,
                      **attrs)

    def _recorder(self, jobs) -> timeline.TimelineRecorder:
        """Span-close listener mapping this worker's pipeline spans
        (read/dedisperse/dispatch/fetch/.../store-ingest, plus
        interpolated compile marks) into the given jobs' timelines;
        batched dispatch passes every batch-mate so the shared device
        phases land in each beam's waterfall."""
        recs = jobs if isinstance(jobs, list) else [jobs]
        return timeline.TimelineRecorder(
            [self.spool.work_dir(j.job_id) for j in recs],
            host=self.host_label,
            attempt=max((j.attempts for j in recs), default=0),
        )

    def _note_done(self, job: JobRecord) -> None:
        """Latency accounting for a finished job: the submit->done
        sojourn from its timeline marks (clock-step-proof), falling
        back to wall stamps for pre-timeline records, into the
        ``scheduler.sojourn`` timer + this drain's percentile pools."""
        soj = timeline.sojourn_for(self.spool.work_dir(job.job_id))
        if soj is None:
            soj = max(0.0, job.finished_utc - job.submitted_utc)
        METRICS.observe("scheduler.sojourn", soj)
        self._sojourns.append(float(soj))
        self._queue_waits.append(float(job.queue_wait_s or 0.0))
        self._drained_runs.append(str(job.job_id))

    def _mark_store(self, job: JobRecord, result) -> None:
        """Lineage annotations for the store ingest (ISSUE 19):
        science candidates are ``stored``; a canary job's candidates
        are ``quarantined`` — tagged out of every science read — so
        the funnel shows known-answer probes leaving the population."""
        from ..obs import lineage

        if not lineage.enabled() or not result.candidates:
            return
        run = str(job.job_id)
        lineage.mark(
            "quarantined" if job.canary else "stored", run=run,
            ids=[lineage.candidate_uid(run, c)
                 for c in result.candidates],
            n=len(result.candidates))

    def _run_batch_jobs(self, jobs: list[JobRecord]) -> int:
        """Run claimed same-bucket jobs through ONE batched dispatch;
        returns the success count.  Failures stay per-job: a beam that
        fails to read, search or ingest goes through the usual
        classify/retry/quarantine path without touching its
        batch-mates (their checkpoints are per-job files)."""
        from ..cli import write_search_output
        from ..io.sigproc import read_filterbank
        from ..obs.events import configure_event_log

        # phase A: per-job config + observation read; a beam failing
        # HERE (e.g. truncated file -> typed InputFileError) peels off
        # through _handle_failure before the dispatch
        ready: list[tuple] = []
        for job in jobs:
            try:
                cfg = self._job_config(job)
                configure_event_log(
                    os.path.join(self.spool.work_dir(job.job_id),
                                 "events.jsonl"),
                    max_log_bytes=EVENT_LOG_MAX_BYTES)
                fil = (self._prefetcher.take(job.input)
                       if self.prefetch else None)
                if fil is not None:
                    self._mark_job(job, "prefetch-hit")
                else:
                    with self._recorder(job), \
                            span("Observation-Read", metric="obs_read",
                                 input=job.input):
                        fil = read_filterbank(job.input)
                ready.append((job, cfg, fil))
            except Exception as exc:
                self._handle_failure(job, exc)
        # phase B: build per-job searches (lossless trim + geometry
        # accounting per job); the first survivor's search leads
        js, cfgs, fils, searches = [], [], [], []
        for job, cfg, fil in ready:
            try:
                fil2, search = self._build_search(fil, cfg)
            except Exception as exc:
                self._handle_failure(job, exc)
                continue
            js.append(job)
            cfgs.append(cfg)
            fils.append(fil2)
            searches.append(search)
        if not js:
            return 0
        leader = searches[0]
        ok = 0
        if len(js) > 1:
            # defensive: the batch key should guarantee this; anything
            # incompatible is peeled back out to the solo path
            want = leader._batch_fields(fils[0])
            solo = [i for i in range(1, len(js))
                    if leader._batch_fields(fils[i]) != want]
            for i in reversed(solo):
                job_i = js.pop(i)
                cfgs.pop(i)
                fils.pop(i)
                searches.pop(i)
                if self.run_one(job_i):
                    ok += 1
        if len(js) == 1:
            return ok + (1 if self.run_one(js[0]) else 0)
        # overlap the NEXT wave's reads with this batch's device time
        if self.prefetch:
            for rec in self.spool.pending_jobs()[: self.batch]:
                self._prefetcher.start(rec.input)
        B = len(js)
        try:
            # the shared device phases (dedisperse/dispatch/fetch/...)
            # land in EVERY batch-mate's timeline
            with self._recorder(js):
                results = run_with_timeout(
                    lambda: leader.run_batch(fils, cfgs),
                    self.timeout_s,
                    label=f"batch {js[0].job_id}+{B - 1}")
        except Exception as exc:
            # whole-dispatch failure (timeout, compile error): every
            # beam classifies/retries individually
            for job in js:
                self._handle_failure(job, exc)
            return ok
        if getattr(leader, "last_dispatch_batched", False):
            METRICS.inc("scheduler.batched_dispatches")
            METRICS.inc("scheduler.batch_fill", B)
        for job, cfg, result in zip(js, cfgs, results):
            with self._recorder(job), \
                    span(f"Job-{job.job_id}", metric="job",
                         job_id=job.job_id, input=job.input,
                         attempt=job.attempts, priority=job.priority,
                         batch=B):
                if isinstance(result, BaseException):
                    self._handle_failure(job, result)
                    continue
                try:
                    write_search_output(result, cfg.outdir)
                    with span("Store-Ingest", metric="store_ingest",
                              job_id=job.job_id):
                        ingested = self.store.ingest(
                            job.job_id, job.input, result.candidates,
                            canary=bool(job.canary),
                            provenance=result.provenance)
                        self._mark_store(job, result)
                    best = max((float(c.snr)
                                for c in result.candidates), default=0.0)
                    summary = {
                        "candidates": len(result.candidates),
                        "ingested": ingested,
                        "best_snr": round(best, 4),
                        "outdir": cfg.outdir,
                        "batch": B,
                        "timers": {k: round(float(v), 3)
                                   for k, v in result.timers.items()},
                    }
                    if job.canary:
                        summary["canary"] = self._check_canary(job,
                                                               result)
                except Exception as exc:
                    self._handle_failure(job, exc)
                    continue
            self.spool.mark_done(job, summary)
            self._note_done(job)
            METRICS.inc("scheduler.succeeded")
            ok += 1
        return ok

    # -- one job -----------------------------------------------------------

    def _run_job(self, job: JobRecord) -> dict:
        from ..cli import write_search_output
        from ..io.sigproc import read_filterbank
        from ..obs.events import configure_event_log

        cfg = self._job_config(job)
        configure_event_log(
            os.path.join(self.spool.work_dir(job.job_id),
                         "events.jsonl"),
            max_log_bytes=EVENT_LOG_MAX_BYTES)
        fil = self._prefetcher.take(job.input) if self.prefetch else None
        staged = self._prefetcher.last_staged if self.prefetch else None
        if fil is None:
            with span("Observation-Read", metric="obs_read",
                      input=job.input):
                fil = read_filterbank(job.input)
        else:
            self._mark_job(job, "prefetch-hit")
        if staged is not None:
            self._mark_job(job, "stage")
        fil, search = self._build_search(fil, cfg)
        if staged is not None:
            # prefetch-thread upload (ISSUE 11): _device_inputs /
            # dedisperse_sharded consume it if the geometry matches
            search._staged_raw = staged
        # overlap the next pipeline_depth-1 observations' read+unpack
        # (and their pack+upload, via the prefetcher's device stage)
        # with this search; depth=1 is the unpipelined A/B reference
        if self.prefetch:
            for rec in self.spool.pending_jobs()[
                    : self.pipeline_depth - 1]:
                self._prefetcher.start(rec.input, job=rec)
        result = search.run()
        write_search_output(result, cfg.outdir)
        with span("Store-Ingest", metric="store_ingest",
                  job_id=job.job_id):
            ingested = self.store.ingest(
                job.job_id, job.input, result.candidates,
                canary=bool(job.canary),
                provenance=result.provenance)
            self._mark_store(job, result)
        best = max((float(c.snr) for c in result.candidates),
                   default=0.0)
        summary = {
            "candidates": len(result.candidates),
            "ingested": ingested,
            "best_snr": round(best, 4),
            "outdir": cfg.outdir,
            "timers": {k: round(float(v), 3)
                       for k, v in result.timers.items()},
        }
        if job.canary:
            summary["canary"] = self._check_canary(job, result)
        return summary

    def _check_canary(self, job: JobRecord, result) -> dict:
        """Match a completed canary job against its injection manifest
        (obs/injection.py, ISSUE 14).

        The serving stack's known-answer probe: counters + a
        ``canary_missed`` event feed the telemetry stream and the
        ``canary_recovery`` health rule, and the verdict rides the job
        summary into the ``done/`` record and the serve ledger.
        Matching failures count as misses — a canary that cannot be
        checked is a canary that did not come back.
        """
        from ..obs.injection import match_candidates

        man = job.canary
        try:
            verdict = match_candidates(man, result.candidates)
            out = {
                "recovered": bool(verdict["recovered"]),
                "best_snr": round(float(verdict["best_snr"]), 4),
                "n_matches": int(verdict["n_matches"]),
                "freq": man.get("freq"),
                "target_snr": man.get("target_snr"),
            }
        except Exception as exc:
            out = {"recovered": False, "best_snr": 0.0, "n_matches": 0,
                   "error": str(exc)}
        if out["recovered"]:
            METRICS.inc("canary.recovered")
        else:
            METRICS.inc("canary.missed")
            warn_event(
                "canary_missed",
                f"canary job {job.job_id} did not recover its "
                f"injected pulsar (freq {man.get('freq')}, target SNR "
                f"{man.get('target_snr')})",
                job_id=job.job_id, freq=man.get("freq"),
                target_snr=man.get("target_snr"),
            )
        return out

    def _capture_failure_report(self, job: JobRecord) -> str:
        """Snapshot the run's telemetry (stage timers, counters,
        events up to the crash) next to the job; best effort."""
        path = os.path.join(
            self.spool.work_dir(job.job_id),
            f"run_report.attempt{job.attempts}.json")
        try:
            from ..obs.report import write_run_report

            write_run_report(path)
        except Exception:
            return ""
        return path

    def _handle_failure(self, job: JobRecord, exc: BaseException) -> None:
        kind = classify_failure(exc)
        job.failures.append({
            "utc": round(time.time(), 3),
            "t_mono": round(time.perf_counter(), 6),
            "attempt": job.attempts,
            "classification": kind,
            "error": f"{type(exc).__name__}: {exc}",
            "traceback": traceback.format_exc(),
            "run_report": self._capture_failure_report(job),
        })
        if kind == QUARANTINE:
            warn_event(
                "job_quarantined",
                f"job {job.job_id} quarantined (attempt "
                f"{job.attempts}): {exc}",
                job_id=job.job_id, input=job.input,
                error=str(exc),
            )
            METRICS.inc("scheduler.quarantined")
            self.spool.mark_failed(job)
        elif self.backoff.exhausted(job.attempts):
            warn_event(
                "job_retries_exhausted",
                f"job {job.job_id} failed {job.attempts} attempts; "
                f"giving up: {exc}",
                job_id=job.job_id, input=job.input,
                attempts=job.attempts, error=str(exc),
            )
            METRICS.inc("scheduler.exhausted")
            self.spool.mark_failed(job)
        else:
            delay = self.backoff.delay_for(job.attempts)
            warn_event(
                "job_retry",
                f"job {job.job_id} attempt {job.attempts} failed "
                f"({type(exc).__name__}); re-queueing with "
                f"{delay:.1f}s backoff",
                job_id=job.job_id, attempt=job.attempts,
                delay_s=delay, error=str(exc),
            )
            METRICS.inc("scheduler.retried")
            self.spool.release(job)
            pause(delay, self.sleeper)

    def _maybe_profile(self, job: JobRecord):
        """Sampled device profiling (ISSUE 18): a ``jax.profiler``
        trace context for every ``profile_every``-th job started, a
        no-op context otherwise.  Start/stop failures (no profiler in
        this jax build, no TensorFlow trace backend, double-start) are
        swallowed — profiling must never fail a job — and a successful
        capture is registered in the compile ledger (kind ``profile``)
        + the ``profile.captures`` counter so the warehouse ingests
        the artifact path."""
        self._jobs_started += 1
        if (self.profile_every <= 0
                or self._jobs_started % self.profile_every != 0):
            return contextlib.nullcontext()
        return self._profile_capture(job)

    @contextlib.contextmanager
    def _profile_capture(self, job: JobRecord):
        path = os.path.join(self.profile_dir, f"job-{job.job_id}")
        try:
            import jax

            os.makedirs(path, exist_ok=True)
            jax.profiler.start_trace(path)
        except Exception:
            yield  # tolerant no-op where the profiler is unavailable
            return
        try:
            yield
        finally:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
            try:
                from ..obs.compilation import record_profile

                record_profile(path)
            except Exception:
                pass

    def run_one(self, job: JobRecord) -> bool:
        """Run one claimed job through the retry machinery; True on
        success."""
        runner = self.run_job_fn or self._run_job
        resumes0 = int(METRICS.snapshot().get("counters", {}).get(
            "checkpoint.resumes", 0))
        with self._recorder(job), self._maybe_profile(job), \
                span(f"Job-{job.job_id}", metric="job",
                     job_id=job.job_id, input=job.input,
                     attempt=job.attempts, priority=job.priority,
                     batch=1):
            try:
                summary = run_with_timeout(
                    lambda: runner(job), self.timeout_s,
                    label=f"job {job.job_id}")
            except Exception as exc:
                self._handle_failure(job, exc)
                return False
            resumed = int(METRICS.snapshot().get("counters", {}).get(
                "checkpoint.resumes", 0)) - resumes0
            if resumed > 0:
                self._mark_job(job, "checkpoint-resume",
                               resumes=resumed)
        self.spool.mark_done(job, summary if isinstance(summary, dict)
                             else {})
        self._note_done(job)
        METRICS.inc("scheduler.succeeded")
        return True

    # -- the drain loop ----------------------------------------------------

    def drain(self, max_jobs: int | None = None, wait: bool = False,
              poll_s: float = 5.0) -> dict:
        """Claim and run jobs until the queue is empty (or ``wait``
        to poll for more), appending one throughput record to the
        bench history ledger (obs/history.py, kind ``serve``)."""
        from ..obs.compilation import (
            configure_compile_ledger,
            install_compile_ledger,
        )
        from ..obs.metrics import install_compile_hook

        install_compile_hook()
        # geometry-keyed compile ledger (ISSUE 18): one spool-level
        # compiles.jsonl attributing every backend compile this drain
        # pays to the search geometry that triggered it
        configure_compile_ledger(
            os.path.join(self.spool.root, "compiles.jsonl"))
        install_compile_ledger()
        from ..obs import lineage

        # candidate provenance ledger (ISSUE 19): one spool-level
        # lineage.jsonl recording every selection decision of every
        # job this drain runs; empty path = the --no-lineage hatch
        lineage_path = os.path.join(self.spool.root, "lineage.jsonl")
        lineage.configure_lineage(lineage_path if self.lineage else "")
        lov0 = lineage.overhead()  # lineage mark-cost origin
        sampler = self._start_telemetry()
        ov0 = timeline.overhead()  # mark-cost ledger origin
        t0 = time.time()
        timers0 = {
            name: float(rec.get("host_s", 0.0))
            for name, rec in
            METRICS.snapshot().get("timers", {}).items()
        }  # cold-start phase-decomposition origin
        span_c0 = span_cursor()  # drain-level duty-cycle ledger origin
        claimed = succeeded = 0
        coldstart: dict | None = None
        try:
            while max_jobs is None or claimed < max_jobs:
                job = self.spool.claim(self.worker_id,
                                       host=self.host_label)
                if job is None:
                    if not wait:
                        break
                    self._idle_poll()
                    pause(poll_s, self.sleeper)
                    continue
                mates: list = []
                if self.batch > 1 and self.run_job_fn is None:
                    room = self.batch - 1
                    if max_jobs is not None:
                        room = min(room, max_jobs - claimed - 1)
                    if room > 0:
                        mates = self._claim_batch_mates(job, room)
                claimed += 1 + len(mates)
                if mates:
                    succeeded += self._run_batch_jobs([job] + mates)
                elif self.run_one(job):
                    succeeded += 1
                if coldstart is None and succeeded > 0:
                    coldstart = self._coldstart(t0, timers0, span_c0)
            elapsed = time.time() - t0
            jobs_per_hour = (succeeded / (elapsed / 3600.0)
                             if elapsed > 0 else 0.0)
            METRICS.gauge("scheduler.jobs_per_hour", jobs_per_hour)
            # drain-level device_duty_cycle (ISSUE 11): device/link
            # seconds across EVERY job's spans over drain wall-clock —
            # 1.0 means the devices never idled between jobs.
            # Overwrites the per-run figure _finalise left, so the
            # serve ledger and the final telemetry sample carry the
            # drain-level number (the health rule reads this gauge)
            duty = (device_seconds(span_c0) / elapsed
                    if elapsed > 0 else 0.0)
            METRICS.gauge("device_duty_cycle", round(duty, 4))
        finally:
            # stop AFTER the jobs_per_hour gauge so the final sample
            # carries the drain's headline figure
            if sampler is not None:
                sampler.stop()
        summary = {
            "claimed": claimed,
            "succeeded": succeeded,
            "failed": claimed - succeeded,
            "elapsed_s": round(elapsed, 3),
            "jobs_per_hour": round(jobs_per_hour, 3),
            "geometry_buckets": len(self.geometries),
            "batch": self.batch,
            # timed-out attempt threads still alive in this process
            # (run_with_timeout abandons them; serve/retry.py)
            "timeout_abandoned": abandoned_count(),
        }
        if coldstart is not None:
            summary["coldstart"] = coldstart
        if sampler is not None:
            summary["telemetry"] = {
                "samples": sampler.samples_written,
                "overhead_s": round(sampler.overhead_s, 6),
                "shard": sampler.path,
            }
        ov1 = timeline.overhead()
        summary["timeline"] = {
            "marks": ov1["marks"] - ov0["marks"],
            "overhead_s": round(ov1["seconds"] - ov0["seconds"], 6),
            "errors": ov1["errors"] - ov0["errors"],
        }
        lov1 = lineage.overhead()
        lg = {
            "marks": lov1["marks"] - lov0["marks"],
            "overhead_s": round(lov1["seconds"] - lov0["seconds"], 6),
            "errors": lov1["errors"] - lov0["errors"],
        }
        if self.lineage and self._drained_runs:
            # the drain's selection funnel, scoped to the jobs THIS
            # worker finished (fleet mates write their own records)
            fn = lineage.funnel(lineage.read_lineage(lineage_path),
                                runs=self._drained_runs)
            lg.update({
                "decoded": fn["decoded"],
                "absorbed": fn["absorbed"],
                "cut": fn["cut"],
                "emitted": fn["emitted"],
                "pass_frac": round(fn["pass_frac"], 6),
                "absorbed_frac": round(fn["absorbed_frac"], 6),
            })
        summary["lineage"] = lg
        self._append_throughput(summary)
        return summary

    def _coldstart(self, t0: float, timers0: dict,
                   span_c0: int) -> dict:
        """Cold-start decomposition (ISSUE 18): wall time from drain
        start to the FIRST finished job, split into where it went —
        observation ``read`` (obs_read host seconds), XLA ``compile``
        (jit_compile host seconds), device ``execute`` (span-attributed
        device seconds) and ``trace`` (the remainder: jax tracing +
        host dispatch + claim bookkeeping).  The headline total lands
        in the ``coldstart.cold_to_first_candidate_s`` gauge (so it
        rides the telemetry stream) and in the drain summary; bench
        ``--coldstart`` ledgers it for the perf gate."""
        snap = METRICS.snapshot()
        timers = snap.get("timers", {})

        def delta(name: str) -> float:
            now = float(timers.get(name, {}).get("host_s", 0.0))
            return max(0.0, now - float(timers0.get(name, 0.0)))

        total = max(0.0, time.time() - t0)
        read_s = delta("obs_read")
        compile_s = delta("jit_compile")
        execute_s = max(0.0, device_seconds(span_c0))
        trace_s = max(0.0, total - read_s - compile_s - execute_s)
        METRICS.gauge("coldstart.cold_to_first_candidate_s",
                      round(total, 6))
        return {
            "cold_to_first_candidate_s": round(total, 6),
            "read_s": round(read_s, 6),
            "trace_s": round(trace_s, 6),
            "compile_s": round(compile_s, 6),
            "execute_s": round(execute_s, 6),
        }

    def _start_telemetry(self):
        """Spin up the per-host telemetry sampler for this drain (None
        when disabled).  The worker owns the obs->serve seam: it hands
        the sampler a shard path and a queue-depth callable, so
        obs/telemetry.py never imports serve/."""
        if self.telemetry_interval_s <= 0:
            return None
        from ..obs.telemetry import TelemetrySampler, shard_path

        label = self.host_label or self.worker_id
        sampler = TelemetrySampler(
            shard_path(os.path.join(self.spool.root, "fleet"), label),
            label,
            self.telemetry_interval_s,
            extras=lambda: {"queue": self.spool.counts()},
        )
        return sampler.start()

    def _idle_poll(self) -> None:
        """Hook run on every empty poll of a waiting drain (before
        the pause).  The fleet worker reaps expired leases here —
        idle hosts are the ones with time to adopt a dead host's
        jobs."""

    def _append_throughput(self, summary: dict) -> None:
        """One ledger record per drain (the survey-level counterpart
        of bench.py's per-run records; jobs_per_hour is the headline
        metric the README schema table documents)."""
        if summary["claimed"] == 0:
            return  # an empty poll is not a throughput sample
        from ..obs.history import (
            append_history,
            make_history_record,
            stage_device_seconds,
        )
        from .health import percentile

        snap = METRICS.snapshot()
        counters = snap.get("counters", {})
        tl = summary.get("timeline", {})
        lg = summary.get("lineage", {})
        rec = make_history_record(
            "serve",
            {
                "jobs_claimed": summary["claimed"],
                "jobs_succeeded": summary["succeeded"],
                "jobs_failed": summary["failed"],
                "elapsed_s": summary["elapsed_s"],
                "jobs_per_hour": summary["jobs_per_hour"],
                # batched dispatch (ISSUE 9): configured stack depth
                # plus how well the dispatches actually filled — the
                # perf gate watches the jobs_per_hour multiplier
                # between batch=1 and batch=B records
                "batch": self.batch,
                "batched_dispatches": int(
                    counters.get("scheduler.batched_dispatches", 0)),
                "batch_fill": int(
                    counters.get("scheduler.batch_fill", 0)),
                # pipelined dispatch (ISSUE 11): the drain's device
                # seconds per wall second; perf_report's serve table
                # shows it next to jobs_per_hour
                "device_duty_cycle": float(
                    snap.get("gauges", {}).get("device_duty_cycle",
                                               0.0)),
                # load observatory (ISSUE 12): end-to-end latency of
                # the jobs this drain finished (sojourn = submit->done
                # from timeline marks) and the cost of writing the
                # timeline itself — perf_report's serve table shows
                # the p95s next to jobs_per_hour
                "sojourn_p50": round(
                    percentile(self._sojourns, 0.50), 6),
                "sojourn_p95": round(
                    percentile(self._sojourns, 0.95), 6),
                "queue_wait_p50": round(
                    percentile(self._queue_waits, 0.50), 6),
                "queue_wait_p95": round(
                    percentile(self._queue_waits, 0.95), 6),
                "timeline_marks": int(tl.get("marks", 0)),
                "timeline_overhead_s": float(
                    tl.get("overhead_s", 0.0)),
                # sensitivity observatory (ISSUE 14): known-answer
                # canary jobs this drain checked; the canary_recovery
                # health rule goes crit on a missed one
                "canary_recovered": int(
                    counters.get("canary.recovered", 0)),
                "canary_missed": int(
                    counters.get("canary.missed", 0)),
                # candidate provenance (ISSUE 19): the drain's exact
                # selection funnel + the ledger's self-accounted cost;
                # baselines band the fracs and the distill_collapse
                # health rule fires on departures
                "lineage_marks": int(lg.get("marks", 0)),
                "lineage_overhead_s": float(lg.get("overhead_s", 0.0)),
                "lineage_decoded": int(lg.get("decoded", 0)),
                "lineage_emitted": int(lg.get("emitted", 0)),
                "lineage_pass_frac": float(lg.get("pass_frac", 0.0)),
                "lineage_absorbed_frac": float(
                    lg.get("absorbed_frac", 0.0)),
            },
            stage_device_s=stage_device_seconds(snap),
            config={
                "spool": self.spool.root,
                "worker": self.worker_id,
                "single_device": self.single_device,
                "geometry_buckets": summary["geometry_buckets"],
                # fleet mode: which host this throughput sample is
                # from (obs/history.py documents the serve schema)
                **({"host": self.host_label}
                   if self.host_label else {}),
            },
        )
        append_history(rec, self.history_path)
