"""Long-running survey worker: claim -> search -> ingest, repeated.

The driver that turns the single-shot pipeline into a service.  Per
job it

1. claims the best pending job (priority, then FIFO) from the spool;
2. reads the observation — from the prefetch slot when the previous
   iteration already fetched it (see below) — and builds the existing
   :class:`~peasoup_tpu.search.pipeline.PulsarSearch` /
   :class:`~peasoup_tpu.parallel.mesh.MeshPulsarSearch` on it;
3. kicks a background read+unpack of the NEXT pending observation, so
   host I/O overlaps the current job's device search — the
   ``utils/hostfetch``-style double buffering of the chunked driver,
   lifted to observation granularity;
4. runs the search under a ``Job-<id>`` root span, writes the usual
   per-run artefacts (overview.xml, run_report.json) into the job's
   work directory, and ingests the distilled candidates into the
   cross-run store;
5. on failure, classifies (serve/retry.py): quarantine straight to
   ``failed/``, transient back to ``pending/`` after backoff, with
   the captured run report + traceback on the job record either way.

Program reuse across jobs: jitted programs are keyed by array shapes,
so the worker buckets each observation's geometry to the plan shapes
— observations whose sample counts share a power-of-two FFT size are
LOSSLESSLY trimmed to ``size + max_delay + 1`` samples (the search
reads nothing beyond that: trials use the first ``size`` columns and
the fold's power-of-two length is preserved by the ``+ 1``), so every
job in the bucket replays the already-compiled programs instead of
paying a per-observation XLA compile.

Per-job checkpointing: each job gets a checkpoint file in its work
directory, so a worker killed mid-job resumes that job's completed DM
rows on the next claim instead of recomputing from scratch.
"""

from __future__ import annotations

import os
import threading
import time
import traceback
from dataclasses import replace

from ..errors import ConfigError
from ..obs.events import warn_event
from ..obs.metrics import REGISTRY as METRICS
from ..obs.trace import span
from .queue import JobRecord, JobSpool
from .retry import (
    QUARANTINE,
    BackoffPolicy,
    classify_failure,
    pause,
    run_with_timeout,
)
from .store import CandidateStore


class ObservationPrefetcher:
    """Single-slot background filterbank reader (double buffering at
    observation granularity).

    ``start(path)`` spawns a daemon thread reading + unpacking the
    file while the caller's search occupies the devices; ``take(path)``
    joins and hands the :class:`Filterbank` over — or returns None on
    a slot miss (a different job won the claim) or a read error (the
    claimer's own synchronous read then raises the real, classifiable
    exception in job context).
    """

    def __init__(self):
        self._thread: threading.Thread | None = None
        self._path: str | None = None
        self._result = None
        self._error: BaseException | None = None

    def start(self, path: str) -> None:
        if self._path == path:
            return  # already in flight (or landed) for this path
        if self._thread is not None and self._thread.is_alive():
            self._thread.join()  # reads are short next to a search
        self._path = path
        self._result = None
        self._error = None

        def _read():
            from ..io.sigproc import read_filterbank

            try:
                self._result = read_filterbank(path)
            except BaseException as exc:
                self._error = exc

        self._thread = threading.Thread(
            target=_read, daemon=True, name="serve-prefetch")
        self._thread.start()

    def take(self, path: str):
        if self._path != path:
            METRICS.inc("scheduler.prefetch_misses")
            return None
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        result, error = self._result, self._error
        self._path = self._result = self._error = None
        if error is not None or result is None:
            METRICS.inc("scheduler.prefetch_misses")
            return None
        METRICS.inc("scheduler.prefetch_hits")
        return result


class SurveyWorker:
    """Claims and runs spool jobs until the queue drains (or a job
    budget is reached).

    ``run_job_fn`` is injectable for tests: it replaces the real
    search (:meth:`_run_job`) but keeps the whole claim / classify /
    retry / quarantine machinery live.  ``sleeper`` routes backoff
    waits (serve/retry.py) to a fake in tests.
    """

    def __init__(self, spool: JobSpool, store: CandidateStore | None = None,
                 *, base_config=None, backoff: BackoffPolicy | None = None,
                 timeout_s: float = 0.0, single_device: bool = False,
                 max_devices: int | None = None, worker_id: str = "",
                 prefetch: bool = True, run_job_fn=None,
                 history_path: str | None = None, sleeper=None):
        self.spool = spool
        self.store = store if store is not None else CandidateStore(
            os.path.join(spool.root, "candidates.jsonl"))
        self.base_config = base_config
        self.backoff = backoff if backoff is not None else BackoffPolicy()
        self.timeout_s = float(timeout_s)
        self.single_device = single_device
        self.max_devices = max_devices
        self.worker_id = worker_id or f"worker-{os.getpid()}"
        #: fleet host label stamped on claims ("" single-host; set by
        #: serve/fleet.py FleetWorker)
        self.host_label = ""
        self.prefetch = prefetch
        self.run_job_fn = run_job_fn
        self.history_path = history_path
        self.sleeper = sleeper
        self._prefetcher = ObservationPrefetcher()
        #: geometry bucket -> jobs served (program-reuse accounting)
        self.geometries: dict[tuple, int] = {}

    # -- config / geometry -------------------------------------------------

    def _job_config(self, job: JobRecord):
        """Base config + the job's overrides + per-job spool paths."""
        from ..search.plan import SearchConfig

        cfg = (replace(self.base_config) if self.base_config is not None
               else SearchConfig())
        for key, val in (job.overrides or {}).items():
            if not hasattr(cfg, key):
                raise ConfigError(
                    f"job {job.job_id}: unknown SearchConfig override "
                    f"{key!r}")
            setattr(cfg, key, val)
        cfg.infilename = job.input
        work = self.spool.work_dir(job.job_id)
        cfg.outdir = os.path.join(work, "out")
        if not cfg.checkpoint_file:
            # crash-resume: a re-claimed job resumes its completed DM
            # rows instead of recomputing (search/checkpoint.py keys
            # on header content, so the spool can even be relocated)
            cfg.checkpoint_file = os.path.join(work, "search.ckpt")
        return cfg

    def _build_search(self, fil, cfg):
        """Construct the search, bucketing geometry for program reuse
        (lossless trim — see module docstring)."""
        if self.single_device:
            from ..search.pipeline import PulsarSearch

            make = lambda f: PulsarSearch(f, cfg)
        else:
            from ..parallel.mesh import MeshPulsarSearch

            make = lambda f: MeshPulsarSearch(
                f, cfg, max_devices=self.max_devices)
        search = make(fil)
        keep = search.size + search.max_delay + 1
        if fil.nsamps > keep:
            from ..io.sigproc import Filterbank

            cfg.size = search.size  # pin: the trim must not shrink it
            hdr = replace(fil.header, nsamples=keep)
            fil = Filterbank(header=hdr, data=fil.data[:keep])
            search = make(fil)
            METRICS.inc("scheduler.geometry_trimmed")
        gkey = (fil.nchans, fil.header.nbits, search.size,
                int(search.out_nsamps), len(search.dm_list))
        if gkey in self.geometries:
            METRICS.inc("scheduler.plan_reuse")
        self.geometries[gkey] = self.geometries.get(gkey, 0) + 1
        return fil, search

    # -- one job -----------------------------------------------------------

    def _run_job(self, job: JobRecord) -> dict:
        from ..cli import write_search_output
        from ..io.sigproc import read_filterbank
        from ..obs.events import configure_event_log

        cfg = self._job_config(job)
        configure_event_log(
            os.path.join(self.spool.work_dir(job.job_id),
                         "events.jsonl"))
        fil = self._prefetcher.take(job.input) if self.prefetch else None
        if fil is None:
            with span("Observation-Read", metric="obs_read",
                      input=job.input):
                fil = read_filterbank(job.input)
        fil, search = self._build_search(fil, cfg)
        # overlap the NEXT observation's read+unpack with this search
        if self.prefetch:
            nxt = self.spool.peek()
            if nxt is not None:
                self._prefetcher.start(nxt.input)
        result = search.run()
        write_search_output(result, cfg.outdir)
        ingested = self.store.ingest(
            job.job_id, job.input, result.candidates)
        best = max((float(c.snr) for c in result.candidates),
                   default=0.0)
        return {
            "candidates": len(result.candidates),
            "ingested": ingested,
            "best_snr": round(best, 4),
            "outdir": cfg.outdir,
            "timers": {k: round(float(v), 3)
                       for k, v in result.timers.items()},
        }

    def _capture_failure_report(self, job: JobRecord) -> str:
        """Snapshot the run's telemetry (stage timers, counters,
        events up to the crash) next to the job; best effort."""
        path = os.path.join(
            self.spool.work_dir(job.job_id),
            f"run_report.attempt{job.attempts}.json")
        try:
            from ..obs.report import write_run_report

            write_run_report(path)
        except Exception:
            return ""
        return path

    def _handle_failure(self, job: JobRecord, exc: BaseException) -> None:
        kind = classify_failure(exc)
        job.failures.append({
            "utc": round(time.time(), 3),
            "attempt": job.attempts,
            "classification": kind,
            "error": f"{type(exc).__name__}: {exc}",
            "traceback": traceback.format_exc(),
            "run_report": self._capture_failure_report(job),
        })
        if kind == QUARANTINE:
            warn_event(
                "job_quarantined",
                f"job {job.job_id} quarantined (attempt "
                f"{job.attempts}): {exc}",
                job_id=job.job_id, input=job.input,
                error=str(exc),
            )
            METRICS.inc("scheduler.quarantined")
            self.spool.mark_failed(job)
        elif self.backoff.exhausted(job.attempts):
            warn_event(
                "job_retries_exhausted",
                f"job {job.job_id} failed {job.attempts} attempts; "
                f"giving up: {exc}",
                job_id=job.job_id, input=job.input,
                attempts=job.attempts, error=str(exc),
            )
            METRICS.inc("scheduler.exhausted")
            self.spool.mark_failed(job)
        else:
            delay = self.backoff.delay_for(job.attempts)
            warn_event(
                "job_retry",
                f"job {job.job_id} attempt {job.attempts} failed "
                f"({type(exc).__name__}); re-queueing with "
                f"{delay:.1f}s backoff",
                job_id=job.job_id, attempt=job.attempts,
                delay_s=delay, error=str(exc),
            )
            METRICS.inc("scheduler.retried")
            self.spool.release(job)
            pause(delay, self.sleeper)

    def run_one(self, job: JobRecord) -> bool:
        """Run one claimed job through the retry machinery; True on
        success."""
        runner = self.run_job_fn or self._run_job
        with span(f"Job-{job.job_id}", metric="job",
                  job_id=job.job_id, input=job.input,
                  attempt=job.attempts, priority=job.priority):
            try:
                summary = run_with_timeout(
                    lambda: runner(job), self.timeout_s,
                    label=f"job {job.job_id}")
            except Exception as exc:
                self._handle_failure(job, exc)
                return False
        self.spool.mark_done(job, summary if isinstance(summary, dict)
                             else {})
        METRICS.inc("scheduler.succeeded")
        return True

    # -- the drain loop ----------------------------------------------------

    def drain(self, max_jobs: int | None = None, wait: bool = False,
              poll_s: float = 5.0) -> dict:
        """Claim and run jobs until the queue is empty (or ``wait``
        to poll for more), appending one throughput record to the
        bench history ledger (obs/history.py, kind ``serve``)."""
        from ..obs.metrics import install_compile_hook

        install_compile_hook()
        t0 = time.time()
        claimed = succeeded = 0
        while max_jobs is None or claimed < max_jobs:
            job = self.spool.claim(self.worker_id, host=self.host_label)
            if job is None:
                if not wait:
                    break
                self._idle_poll()
                pause(poll_s, self.sleeper)
                continue
            claimed += 1
            if self.run_one(job):
                succeeded += 1
        elapsed = time.time() - t0
        jobs_per_hour = (succeeded / (elapsed / 3600.0)
                         if elapsed > 0 else 0.0)
        METRICS.gauge("scheduler.jobs_per_hour", jobs_per_hour)
        summary = {
            "claimed": claimed,
            "succeeded": succeeded,
            "failed": claimed - succeeded,
            "elapsed_s": round(elapsed, 3),
            "jobs_per_hour": round(jobs_per_hour, 3),
            "geometry_buckets": len(self.geometries),
        }
        self._append_throughput(summary)
        return summary

    def _idle_poll(self) -> None:
        """Hook run on every empty poll of a waiting drain (before
        the pause).  The fleet worker reaps expired leases here —
        idle hosts are the ones with time to adopt a dead host's
        jobs."""

    def _append_throughput(self, summary: dict) -> None:
        """One ledger record per drain (the survey-level counterpart
        of bench.py's per-run records; jobs_per_hour is the headline
        metric the README schema table documents)."""
        if summary["claimed"] == 0:
            return  # an empty poll is not a throughput sample
        from ..obs.history import (
            append_history,
            make_history_record,
            stage_device_seconds,
        )

        snap = METRICS.snapshot()
        rec = make_history_record(
            "serve",
            {
                "jobs_claimed": summary["claimed"],
                "jobs_succeeded": summary["succeeded"],
                "jobs_failed": summary["failed"],
                "elapsed_s": summary["elapsed_s"],
                "jobs_per_hour": summary["jobs_per_hour"],
            },
            stage_device_s=stage_device_seconds(snap),
            config={
                "spool": self.spool.root,
                "worker": self.worker_id,
                "single_device": self.single_device,
                "geometry_buckets": summary["geometry_buckets"],
                # fleet mode: which host this throughput sample is
                # from (obs/history.py documents the serve schema)
                **({"host": self.host_label}
                   if self.host_label else {}),
            },
        )
        append_history(rec, self.history_path)
