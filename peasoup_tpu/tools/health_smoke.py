"""Health-plane smoke test (``make health-smoke``).

Exercises the live telemetry + health pipeline end-to-end with REAL
worker processes — ``python -m peasoup_tpu.serve fleet-worker``
subprocesses on fake membership — the way the fleet smoke drives the
control plane:

Phase 1 — healthy fleet: two hosts drain two good synthetic
observations with fast telemetry (``--telemetry-interval 0.2``).
Assert every host left a ``fleet/ts-<host>.jsonl`` shard behind, the
merged reader sees schema-v1 samples carrying queue depths and the
final ``jobs_per_hour`` gauge, the ``health`` verb exits 0 on the
drained fleet, and the sampler's measured overhead stays under 1% of
each host's drain wall-clock (read back from the per-host status
snapshots — the plane measures its own cost).

Phase 2 — dead host: submit another observation, SIGKILL the claiming
worker mid-job, wait out the staleness threshold, and assert
``health`` now exits NONZERO with a crit ``stale_host`` finding naming
the dead host (it still holds the lease).  ``requeue --expired``
recovers the job, a second host re-drains it, and ``health`` returns
to exit 0 — the silent host departed cleanly, which is not an alert.

Exit status 0 only if every assertion holds — CI-gateable like
``fleet-smoke``.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import subprocess
import sys
import time

from .fleet_smoke import FAST, _check, _write_synthetic

#: fast sampling so the smoke's staleness threshold is ~1s, not ~25s
TELEMETRY_INTERVAL = "0.2"


def _worker_cmd(spool_dir: str, host_id: int, history: str,
                extra: list[str] | None = None) -> list[str]:
    return [
        sys.executable, "-m", "peasoup_tpu.serve",
        "--spool", spool_dir, "fleet-worker",
        "--host-id", str(host_id), "--host-count", "2",
        "--drain", "--single_device", "--max-attempts", "2",
        "--backoff-base", "0", "--history", history,
        "--lease-ttl", "60", "--heartbeat", "0.5",
        "--telemetry-interval", TELEMETRY_INTERVAL,
    ] + (extra or [])


def _health(spool_dir: str, history: str, env: dict,
            json_path: str | None = None) -> tuple[int, str]:
    cmd = [sys.executable, "-m", "peasoup_tpu.serve", "--spool",
           spool_dir, "health", "--ledger", history]
    if json_path:
        cmd += ["--json", json_path]
    r = subprocess.run(cmd, env=env, capture_output=True, text=True,
                       timeout=120)
    return r.returncode, r.stdout


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="peasoup-tpu-health-smoke",
        description="Peasoup-TPU - telemetry/health-plane smoke test",
    )
    p.add_argument("--dir", default="/tmp/peasoup-health-smoke",
                   help="scratch directory (wiped)")
    args = p.parse_args(argv)

    shutil.rmtree(args.dir, ignore_errors=True)
    os.makedirs(args.dir)
    spool_dir = os.path.join(args.dir, "jobs")
    history = os.path.join(args.dir, "history.jsonl")

    from peasoup_tpu.obs.telemetry import read_samples, shard_hosts
    from peasoup_tpu.serve import JobSpool
    from peasoup_tpu.serve.fleet import load_host_statuses
    from peasoup_tpu.serve.retry import pause

    spool = JobSpool(spool_dir)
    for i in range(2):
        spool.submit(_write_synthetic(
            os.path.join(args.dir, f"obs{i}.fil"), seed=i), FAST)

    failures: list[str] = []
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    ts_dir = os.path.join(spool.root, "fleet")

    # ---- phase 1: healthy two-host drain with live telemetry ---------
    # --max-jobs 1 guarantees BOTH hosts work (and leave a shard)
    procs = [
        subprocess.Popen(_worker_cmd(spool_dir, h, history,
                                     ["--max-jobs", "1"]),
                         env=env, stdout=subprocess.PIPE,
                         stderr=subprocess.STDOUT, text=True)
        for h in (0, 1)
    ]
    outs = [proc.communicate(timeout=600)[0] for proc in procs]
    for h, out in enumerate(outs):
        print(f"---- fleet-worker host-{h} ----")
        print(out.strip())

    _check(spool.counts()["done"] == 2, "2 jobs in done/", failures)
    _check(shard_hosts(ts_dir) == ["host-0", "host-1"],
           "both hosts wrote ts- telemetry shards", failures)
    samples = read_samples(ts_dir)
    _check(len(samples) >= 4 and all(s.get("v") == 1 for s in samples),
           f"merged reader sees schema-v1 samples ({len(samples)})",
           failures)
    _check(all(isinstance(s.get("queue"), dict) for s in samples),
           "every sample carries queue depths (extras seam)", failures)
    finals = {}
    for s in samples:
        finals[s["host"]] = s
    _check(all(f["gauges"].get("scheduler.jobs_per_hour", 0) > 0
               for f in finals.values()),
           "final samples carry the jobs_per_hour gauge", failures)

    # sampler overhead: measured by the sampler itself, surfaced in
    # the drain summary, persisted in the host status snapshot
    for label, doc in sorted(load_host_statuses(spool).items()):
        summ = doc.get("summary", {})
        telem = summ.get("telemetry", {})
        elapsed = float(summ.get("elapsed_s", 0.0))
        overhead = float(telem.get("overhead_s", -1.0))
        frac = overhead / elapsed if elapsed > 0 else 1.0
        _check(0.0 <= overhead and frac < 0.01,
               f"{label} sampler overhead {overhead:.4f}s is <1% of "
               f"{elapsed:.2f}s drain ({100 * frac:.3f}%)", failures)

    rc, out = _health(spool_dir, history, env)
    print(out.strip())
    _check(rc == 0 and "fleet severity: ok" in out,
           "health exits 0 on the drained fleet", failures)

    # ---- phase 2: SIGKILL one host -> crit -> recover -> ok ----------
    kill_rec = spool.submit(_write_synthetic(
        os.path.join(args.dir, "obs_kill.fil"), seed=3), FAST)
    proc = subprocess.Popen(
        _worker_cmd(spool_dir, 0, history), env=env,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    deadline = time.time() + 120.0
    while spool.counts()["running"] == 0 and time.time() < deadline:
        pause(0.05)
    claimed_mid_job = spool.counts()["running"] == 1
    proc.send_signal(signal.SIGKILL)
    proc.wait(timeout=60)
    _check(claimed_mid_job, "worker SIGKILLed mid-job", failures)

    # wait out the staleness threshold (stale_after x interval, ~1s)
    pause(3.0)
    report_path = os.path.join(args.dir, "health_crit.json")
    rc, out = _health(spool_dir, history, env, json_path=report_path)
    print(out.strip())
    doc = json.load(open(report_path))
    crit_stale = [f for f in doc["findings"]
                  if f["rule"] == "stale_host"
                  and f["severity"] == "crit"]
    _check(rc != 0, "health exits NONZERO on the dead host", failures)
    _check(len(crit_stale) == 1 and crit_stale[0]["host"] == "host-0",
           "crit stale_host finding names the killed host", failures)
    _check("requeue --expired" in crit_stale[0]["message"],
           "finding tells the operator the recovery verb", failures)

    rq = subprocess.run(
        [sys.executable, "-m", "peasoup_tpu.serve", "--spool",
         spool_dir, "requeue", "--expired", "--lease-ttl", "0"],
        env=env, capture_output=True, text=True, timeout=120)
    print(rq.stdout.strip())
    _check(rq.returncode == 0 and kill_rec.job_id in rq.stdout,
           "requeue --expired reaped the dead host's job", failures)

    redrain = subprocess.run(
        _worker_cmd(spool_dir, 1, history), env=env,
        capture_output=True, text=True, timeout=600)
    print(redrain.stdout.strip())
    state, _rec = spool.get(kill_rec.job_id)
    _check(redrain.returncode == 0 and state == "done",
           "host-1 re-drained the recovered job", failures)

    rc, out = _health(spool_dir, history, env)
    print(out.strip())
    _check(rc == 0 and "fleet severity: ok" in out,
           "health back to exit 0 after recovery (silent host "
           "departed cleanly)", failures)

    if failures:
        print(f"\nhealth-smoke: {len(failures)} check(s) FAILED",
              file=sys.stderr)
        return 1
    print("\nhealth-smoke: all checks passed")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
