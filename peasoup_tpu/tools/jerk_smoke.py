"""Jerk-search smoke test (``make jerk-smoke``).

CPU end-to-end proof that the jerk axis (ISSUE 13) buys real
sensitivity and that the quantised trial lattice engages only through
the parity gate:

Phase 1 — zero-jerk parity: one synthetic constant-period observation
searched twice — the accel-only default config vs the same config
spelled through the new machinery (explicit zero jerk grid, forced
``trial_lattice="f32"``).  The candidate fingerprints must be
BIT-IDENTICAL: a jerk axis nobody asked for must cost nothing and
change nothing.

Phase 2 — jerked-pulse recovery: a pulse train synthesised with the
resampler's own cubic index ramp run backwards (a constant-period
signal smeared by a known jerk), searched with the accel-only grid and
with a {-j, 0, +j} jerk grid.  The accel-only search must MISS the
pulse (its quadratic trials cannot de-smear a cubic drift); the jerk
search must recover it at the injected period with the injected jerk
trial attached.  This is the 10-100x grid paying for itself.

Phase 3 — lattice sidecar: the jerk search re-run under each forced
lattice dtype; per-dtype device seconds and parity verdicts vs the f32
reference (max SNR delta, candidates moved) are recorded through
``search/tuning.py:update_lattice``, and ``resolve_trial_lattice`` is
asserted to return the recorded pick for ``auto`` — and to refuse any
dtype whose verdict failed.  A ``kind="jerk_smoke"`` ledger record is
appended and read back.

Exit status 0 only if every assertion holds — CI-gateable like
``serve-smoke``.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import time

#: synthetic observation geometry (small enough for a CPU smoke, long
#: enough that the injected jerk smears the pulse by tens of samples).
#: SIZE is the search's fft length — the cubic ramp is pinned to it so
#: the matched (0, jerk) trial de-smears exactly; PAD keeps
#: size + max_shift + 1 samples available after the lossless trim
SIZE = 8192
PAD = 320
NSAMPS = SIZE + PAD
NCHANS = 16
TSAMP = 0.000256
F0 = 50.0          # injected topocentric spin frequency, Hz
PULSE_AMP = 30     # on-pulse amplitude over the noise floor
DUTY = 0.06
MIN_SNR = 7.0


def _write_synthetic(path: str, jerk: float = 0.0,
                     seed: int = 0) -> str:
    """An 8-bit filterbank carrying a DM-0 pulse train smeared by
    ``jerk``: observed sample m holds the rest-frame signal at
    ``m - shift(m)`` where shift is resample2's cubic index ramp
    ``m*jf*(m-n)*(m+n)`` — so the search's matching (0, jerk) trial
    de-smears it exactly, and no quadratic accel trial can.  Thin
    wrapper over the injection synthesizer (byte-identical to the
    historical private recipe — ``size=SIZE`` pins the cubic ramp to
    the search's fft length)."""
    from peasoup_tpu.obs.injection import synthesize

    synthesize(path, freq=F0, jerk=jerk, duty=DUTY, amp=PULSE_AMP,
               noise_max=24, nsamps=NSAMPS, nchans=NCHANS, tsamp=TSAMP,
               seed=seed, size=SIZE)
    return path


def _check(ok: bool, what: str, failures: list[str]) -> None:
    print(("PASS " if ok else "FAIL ") + what)
    if not ok:
        failures.append(what)


def _run_search(path: str, **overrides):
    """One MeshPulsarSearch over ``path``; returns (result, search,
    elapsed_s of the run)."""
    from peasoup_tpu.io import read_filterbank
    from peasoup_tpu.parallel.mesh import MeshPulsarSearch
    from peasoup_tpu.search.plan import SearchConfig

    cfg = SearchConfig(**dict(
        dict(dm_start=0.0, dm_end=10.0, acc_start=-5.0, acc_end=5.0,
             min_snr=MIN_SNR, npdmp=0, limit=64, size=SIZE),
        **overrides))
    search = MeshPulsarSearch(read_filterbank(path), cfg)
    t0 = time.time()
    result = search.run()
    return result, search, time.time() - t0


def _fingerprint(result) -> list[tuple]:
    return sorted(
        (round(float(c.freq), 9), round(float(c.dm), 3),
         round(float(c.acc), 3), round(float(c.snr), 4))
        for c in result.candidates)


def _find_pulse(result, tol: float = 2e-3):
    """The strongest candidate within ``tol`` fractional frequency of
    the injected F0 (or a harmonic fold of it), or None."""
    best = None
    for c in result.candidates:
        for h in (1.0, 0.5, 2.0):
            if abs(c.freq * h - F0) / F0 < tol:
                if best is None or c.snr > best.snr:
                    best = c
    return best


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="peasoup-tpu-jerk-smoke",
        description="Peasoup-TPU - jerk-search + trial-lattice smoke",
    )
    p.add_argument("--dir", default="/tmp/peasoup-jerk-smoke",
                   help="scratch directory (wiped)")
    p.add_argument("--jerk", type=float, default=6.0e6,
                   help="injected jerk magnitude, m/s^3 (scaled for "
                        "the smoke's short synthetic observation)")
    args = p.parse_args(argv)

    shutil.rmtree(args.dir, ignore_errors=True)
    os.makedirs(args.dir)
    failures: list[str] = []
    jerk = float(args.jerk)
    jgrid = dict(jerk_start=-jerk, jerk_end=jerk, jerk_step=jerk)

    # ---- phase 1: zero-jerk parity -----------------------------------
    clean = _write_synthetic(os.path.join(args.dir, "clean.fil"))
    res_default, _, _ = _run_search(clean)
    res_zero, search_zero, _ = _run_search(
        clean, jerk_start=0.0, jerk_end=0.0, jerk_step=0.0,
        trial_lattice="f32")
    _check(_fingerprint(res_default) == _fingerprint(res_zero),
           "zero-jerk run bit-identical to the accel-only default",
           failures)
    _check(search_zero.jerk_plan.njerk == 1
           and search_zero.lattice == "f32",
           "zero jerk grid collapses to one trial, lattice f32",
           failures)
    _check(_find_pulse(res_default) is not None,
           "clean pulse found by the accel-only search", failures)

    # ---- phase 2: jerked-pulse recovery ------------------------------
    jerked = _write_synthetic(os.path.join(args.dir, "jerked.fil"),
                              jerk=jerk)
    res_acc, _, _ = _run_search(jerked)
    res_jerk, search_jerk, t_f32 = _run_search(jerked, **jgrid)
    missed = _find_pulse(res_acc)
    found = _find_pulse(res_jerk)
    _check(missed is None,
           "accel-only grid misses the jerk-smeared pulse", failures)
    _check(found is not None,
           "jerk grid recovers the smeared pulse", failures)
    if found is not None:
        _check(abs(abs(float(found.jerk)) - jerk) / jerk < 1e-6,
               f"recovered candidate carries the injected jerk trial "
               f"(got {float(found.jerk):g})", failures)
    _check(search_jerk.jerk_plan.njerk == 3,
           "jerk plan is the 3-trial {-j, 0, +j} grid", failures)

    # ---- phase 3: lattice sidecar + ledger ---------------------------
    sidecar = os.path.join(args.dir, "tune.json")
    ref_fp = {f: s for f, _, _, s in _fingerprint(res_jerk)}
    costs, parity = {"f32": t_f32}, {}
    for dtype in ("u8", "bf16"):
        res_q, _, t_q = _run_search(jerked, trial_lattice=dtype,
                                    **jgrid)
        costs[dtype] = t_q
        q_fp = {f: s for f, _, _, s in _fingerprint(res_q)}
        moved = len(set(ref_fp) ^ set(q_fp))
        deltas = [abs(q_fp[f] - ref_fp[f])
                  for f in set(ref_fp) & set(q_fp)]
        q_pulse = _find_pulse(res_q)
        parity[dtype] = {
            "ok": q_pulse is not None and moved == 0,
            "max_snr_delta": max(deltas, default=0.0),
            "candidates_moved": moved,
        }
        _check(q_pulse is not None,
               f"forced {dtype} lattice still recovers the pulse",
               failures)

    from peasoup_tpu.search.tuning import (
        _device_kind_default, resolve_trial_lattice, update_lattice,
    )

    device_kind = _device_kind_default()
    nsamps = int(search_jerk.size)
    ok_dtypes = [d for d in costs
                 if d == "f32" or parity.get(d, {}).get("ok")]
    picked = min(ok_dtypes, key=costs.get)
    update_lattice(sidecar, device_kind, "dedisperse", nsamps,
                   costs=costs, picked=picked, parity=parity)
    _check(os.path.exists(sidecar)
           and "lattice" in json.load(open(sidecar)),
           "lattice sidecar section written", failures)
    resolved = resolve_trial_lattice(
        "auto", device_kind=device_kind, sidecar=sidecar,
        stage="dedisperse", nsamps=nsamps)
    _check(resolved == picked,
           f"auto resolution returns the recorded pick ({picked})",
           failures)
    # poison one verdict: a failed parity entry must force f32 back
    bad = {d: dict(parity.get(d, {}), ok=False, candidates_moved=1)
           for d in ("u8", "bf16")}
    poisoned = os.path.join(args.dir, "tune_bad.json")
    update_lattice(poisoned, device_kind, "dedisperse", nsamps,
                   costs=costs, picked="u8", parity=bad)
    _check(resolve_trial_lattice(
        "auto", device_kind=device_kind, sidecar=poisoned,
        stage="dedisperse", nsamps=nsamps) == "f32",
           "failed parity verdict refuses the quantised pick",
           failures)

    from peasoup_tpu.obs.history import (
        append_history, load_history, make_history_record,
    )

    history = os.path.join(args.dir, "history.jsonl")
    append_history(make_history_record(
        "jerk_smoke",
        metrics={"njerk": 3,
                 "f32_elapsed_s": round(costs["f32"], 4),
                 **{f"{d}_elapsed_s": round(costs[d], 4)
                    for d in ("u8", "bf16")}},
        parity=f"picked={picked}",
        extra={"trial_lattice": picked},
    ), path=history)
    back = load_history(history, kinds=("jerk_smoke",))
    _check(len(back) == 1
           and back[0].get("trial_lattice") == picked,
           "jerk_smoke ledger record emitted and read back", failures)

    print()
    if failures:
        print(f"jerk-smoke: {len(failures)} FAILURE(S)")
        for f in failures:
            print("  - " + f)
        return 1
    print("jerk-smoke: all checks passed")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
