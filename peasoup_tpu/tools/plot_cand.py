"""Candidate diagnostic plot (`tools/peasoup_tools.py:167-383`).

One page per candidate: folded profile, sub-integration waterfall and
per-subint statistics, a parameter table, DM/S-N and acceleration/S-N
scatter of the candidate's associated hits, a DM-acceleration map, and
a period-DM overview of all hits.  Matplotlib is imported lazily so the
search pipeline has no hard plotting dependency.
"""

from __future__ import annotations

import numpy as np

from .postprocess import JoinedCandidate, PeasoupOutput, radec_to_str

_HARM_COLORS = ["darkblue", "lightblue", "green", "orange", "darkred"]


class CandidatePlotter:
    def __init__(self, output: PeasoupOutput):
        import matplotlib

        matplotlib.use("Agg", force=False)
        import matplotlib.pyplot as plt

        self._plt = plt
        self.output = output
        self.fig = plt.figure(figsize=[14, 12])
        grid = [5, 9]
        self.prof_ax = plt.subplot2grid(grid, [0, 1], colspan=2)
        self.fold_ax = plt.subplot2grid(grid, [1, 1], colspan=2, rowspan=2,
                                        sharex=self.prof_ax)
        self.subs_ax = plt.subplot2grid(grid, [1, 0], rowspan=2,
                                        sharey=self.fold_ax)
        self.table_ax = plt.subplot2grid(grid, [0, 3], colspan=3, rowspan=3,
                                         frameon=False)
        self.dm_ax = plt.subplot2grid(grid, [0, 6], colspan=2)
        self.acc_ax = plt.subplot2grid(grid, [1, 8], rowspan=2)
        self.dm_acc_ax = plt.subplot2grid(grid, [1, 6], colspan=2, rowspan=2,
                                          sharex=self.dm_ax,
                                          sharey=self.acc_ax)
        self.all_ax = plt.subplot2grid([6, 9], [4, 0], colspan=9, rowspan=3)

    # -- panels ------------------------------------------------------------

    def _plot_profile(self, ax, fold):
        ax.plot(fold.sum(axis=0))
        ax.set_ylabel("Flux")
        ax.set_title("Profile")
        ax.tick_params(labelbottom=False, labelleft=False)

    def _plot_subints(self, ax, fold):
        ax.imshow(fold, aspect="auto", interpolation="nearest")
        ax.set_xlim(-0.5, fold.shape[1] - 0.5)
        ax.set_xlabel("Phase bin")
        ax.tick_params(labelleft=False)

    def _plot_subint_stats(self, ax, fold):
        y = np.arange(fold.shape[0])
        mean = fold.mean(axis=1)
        std = fold.std(axis=1)
        ax.fill_betweenx(y, mean - 3 * std, mean + 3 * std, alpha=0.5,
                         color="lightblue", label="+-3 sigma")
        ax.plot(mean, y, lw=2, alpha=0.8, color="lightblue", label="mean")
        ax.plot(fold.min(axis=1), y, lw=2, c="darkblue", label="min")
        ax.plot(fold.max(axis=1), y, lw=2, c="darkred", label="max")
        ax.legend(loc="lower left", bbox_to_anchor=(-0.2, 1.0),
                  prop={"size": 10})
        m1, m2 = ax.get_xlim()
        ax.set_xlim(m2, m1)
        ax.set_ylim(-0.5, fold.shape[0] - 0.5)
        ax.tick_params(labelbottom=False)
        ax.set_ylabel("Subintegration")

    def _fill_table(self, ax, cand: JoinedCandidate):
        ax.xaxis.set_visible(False)
        ax.yaxis.set_visible(False)
        hdr = self.output.overview.section("header_parameters")
        s = cand.stats
        rows = [
            ("R.A.", radec_to_str(float(hdr.get("src_raj", 0.0)))),
            ("Decl.", radec_to_str(float(hdr.get("src_dej", 0.0)))),
            ("P0", "%.9f" % s["period"]),
            ("Opt P0", "%.9f" % s["opt_period"]),
            ("DM", "%.2f" % s["dm"]),
            ("Acc", "%.2f" % s["acc"]),
            ("Jerk", "%.2f" % s["jerk"]),
            ("Harmonic", "%d" % s["nh"]),
            ("Spec S/N", "%.1f" % s["snr"]),
            ("Fold S/N", "%.1f" % s["folded_snr"]),
            ("Adjacent?", str(bool(s["is_adjacent"]))),
            ("Physical?", str(bool(s["is_physical"]))),
            ("DDM ratio 1", "%.3f" % s["ddm_count_ratio"]),
            ("DDM ratio 2", "%.3f" % s["ddm_snr_ratio"]),
            ("Nassoc", "%d" % s["nassoc"]),
        ]
        tab = ax.table(cellText=rows, cellLoc="left", colLoc="left",
                       loc="center")
        tab.scale(1.0, 2.0)

    def _by_harmonic(self, ax, hits, xfield, yfield):
        for ii, harm in enumerate(np.unique(hits["nh"])):
            sub = hits[hits["nh"] == harm]
            ax.scatter(sub[xfield], sub[yfield], edgecolor="none",
                       facecolor=_HARM_COLORS[int(ii) % len(_HARM_COLORS)],
                       label="Harm. %d" % harm)

    def _plot_dm_scatter(self, ax, hits):
        self._by_harmonic(ax, hits, "dm", "snr")
        ax.yaxis.tick_right()
        ax.yaxis.set_label_position("right")
        ax.set_ylabel("S/N", rotation=-90)
        ax.tick_params(labelbottom=False)

    def _plot_acc_scatter(self, ax, hits):
        self._by_harmonic(ax, hits, "snr", "acc")
        ax.yaxis.tick_right()
        ax.yaxis.set_label_position("right")
        ax.set_ylabel("Acceleration (m/s/s)", rotation=-90)
        ax.set_xlabel("S/N")
        ax.legend(loc="lower left", bbox_to_anchor=(0.2, 1.0),
                  prop={"size": 10})

    def _plot_acc_dm_map(self, ax, hits):
        snrs = hits["snr"].astype(float).copy()
        ptp = snrs.max() - snrs.min()
        sizes = 5 + 250 * (snrs - snrs.min()) / (ptp if ptp else 1.0)
        for ii, harm in enumerate(np.unique(hits["nh"])):
            m = hits["nh"] == harm
            ax.scatter(hits["dm"][m], hits["acc"][m],
                       facecolor=_HARM_COLORS[int(ii) % len(_HARM_COLORS)],
                       edgecolor="none", s=sizes[m])
        ax.tick_params(labelleft=False)
        ax.set_xlabel("DM (pc cm^-3)")

    def _plot_all_hits(self, ax, hits, period, dm):
        ax.set_xscale("log")
        ax.scatter(1.0 / hits["freq"], hits["dm"], s=hits["snr"])
        ax.axvline(period, color="grey", lw=0.5)
        ax.axhline(dm, color="grey", lw=0.5)
        ax.set_xlabel("Period (s)")
        ax.set_ylabel("DM (pc cm^-3)")

    # -- page --------------------------------------------------------------

    def plot_cand(self, idx: int, filename: str | None = None):
        cand = self.output.get_candidate(idx)
        hits = np.sort(cand.hits, order="snr")[::-1]
        fold = cand.fold
        if fold is not None:
            fold = fold - fold.min()
            peak = fold.max()
            if peak:
                fold = fold / peak
            self._plot_profile(self.prof_ax, fold)
            self._plot_subints(self.fold_ax, fold)
            self._plot_subint_stats(self.subs_ax, fold)
        self._fill_table(self.table_ax, cand)
        if len(hits):
            self._plot_dm_scatter(self.dm_ax, hits)
            self._plot_acc_scatter(self.acc_ax, hits)
            self._plot_acc_dm_map(self.dm_acc_ax, hits)
            self._plot_all_hits(
                self.all_ax, hits, cand.stats["period"], cand.stats["dm"]
            )
        if filename is not None:
            self.fig.savefig(filename)
        return self.fig


def plot_cand_main(argv=None) -> int:
    import sys

    args = argv if argv is not None else sys.argv[1:]
    if len(args) < 2:
        print("usage: peasoup-tpu-plot-cand <overview.xml> <cand_id> [out.png]")
        return 1
    out = PeasoupOutput(args[0])
    plotter = CandidatePlotter(out)
    filename = args[2] if len(args) > 2 else f"Cand{int(args[1]):04d}.png"
    plotter.plot_cand(int(args[1]), filename)
    print(f"Wrote {filename}")
    return 0


if __name__ == "__main__":
    raise SystemExit(plot_cand_main())
