"""Perf trends + regression gate over the bench history ledger.

Reads ``benchmarks/history.jsonl`` (``obs/history.py`` — appended by
``bench.py`` and the ``benchmarks/`` harnesses) plus the legacy
committed ``BENCH_r0*.json`` artifacts, prints per-metric trend tables
with sparklines, and implements a baseline-aware regression gate
(ISSUE 16, statistics from ``obs/baseline.py``):

    head  = median of the newest ``--head`` records' gate metric
    base  = median of the ``--window`` records immediately before them
    band  = max(z · 1.4826 · MAD(window), (threshold-1) · base)
    FAIL when head > base + band          (lower-is-better metrics)

Medians on both sides reject single-capture jitter (the remote-TPU
tunnel adds 50-100 ms of per-fetch noise and occasional multi-second
stalls).  A noisy history widens its own acceptance band via the
robust z-score; a quiet history (MAD ≈ 0) falls back to the absolute
floor — the old 1.4x fixed ratio — so noise-level wobble never trips
while a genuine 3x slowdown always does, deterministically given the
checked-in ledger.

Usage::

    python -m peasoup_tpu.tools.perf_report              # trends
    python -m peasoup_tpu.tools.perf_report --gate       # CI gate
    python bench.py --gate                               # bench + gate
    make perf-gate

Exit status: 0 clean (or not enough history to judge), 1 regression,
2 usage/IO errors.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

from ..obs.history import default_ledger_path, load_history, repo_root

#: the gate's default headline metric (bench.py's best-of-N end-to-end
#: wall-clock, seconds, lower is better)
GATE_METRIC = "e2e_s"

#: device-time columns the gate ALSO checks (ISSUE 6): wall-clock can
#: hide a device-side regression behind host/tunnel jitter, so the
#: peak-extraction share and the pooled search-stage device seconds
#: (bench.py's ``peaks_device_s`` / ``search_device_s`` metrics) are
#: gated too, as is the jerk bench's per-trial cost
#: (``jerk_s_per_ktrial``, from ``kind:"jerk"`` records — ISSUE 13),
#: the sensitivity sweep's ``recovery_fraction`` (from
#: ``kind:"sensitivity"`` records — ISSUE 14; higher is better, see
#: below), and the chaos harness's ``chaos_recovery_s`` (from
#: ``kind:"chaos"`` records — ISSUE 15; fault injection to health
#: exit-0, lower is better), and the cold-start observatory's
#: ``cold_to_first_candidate_s`` (from ``kind:"coldstart"`` records —
#: ISSUE 18; worker start to first finished job, lower is better).  A
#: metric with fewer than 2 records passes vacuously — ledgers
#: predating a metric stay green.
STAGE_GATE_METRICS = ("peaks_device_s", "search_device_s",
                      "jerk_s_per_ktrial", "recovery_fraction",
                      "chaos_recovery_s", "cold_to_first_candidate_s",
                      "store_query_p50_ms", "compaction_s")

#: metrics where UP is good (ISSUE 11's device_duty_cycle ledger:
#: device seconds per wall second — a drop means the dispatch pipeline
#: stopped hiding host work).  The gate inverts its ratio for these;
#: they are not gated by default (CPU smoke figures are noise) but
#: ``--stage-metrics device_duty_cycle`` gates them correctly.
HIGHER_IS_BETTER_METRICS = ("device_duty_cycle", "vs_baseline",
                            "jobs_per_hour", "knee_throughput_per_s",
                            "recovery_fraction", "store_query_speedup")

SPARK_BLOCKS = "▁▂▃▄▅▆▇█"


def sparkline(values: list[float], width: int = 24) -> str:
    """Unicode block sparkline of ``values`` (newest right), resampled
    to at most ``width`` columns."""
    vals = [float(v) for v in values if v is not None]
    if not vals:
        return ""
    if len(vals) > width:
        # keep the newest `width` points — trends care about the tail
        vals = vals[-width:]
    lo, hi = min(vals), max(vals)
    if hi <= lo:
        return SPARK_BLOCKS[0] * len(vals)
    out = []
    for v in vals:
        idx = int((v - lo) / (hi - lo) * (len(SPARK_BLOCKS) - 1))
        out.append(SPARK_BLOCKS[idx])
    return "".join(out)


def _median(vals: list[float]) -> float:
    s = sorted(vals)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


# --------------------------------------------------------------------------
# record loading (ledger + legacy BENCH_r0*.json)
# --------------------------------------------------------------------------

def load_legacy_bench(pattern: str | None = None) -> list[dict]:
    """The committed ``BENCH_r0*.json`` artifacts as pseudo-ledger
    records (kind ``bench``, ``legacy: true``), ordered by filename —
    they predate the ledger and seed its history."""
    pattern = pattern or os.path.join(repo_root(), "BENCH_r0*.json")
    out: list[dict] = []
    for path in sorted(glob.glob(pattern)):
        try:
            with open(path, encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        parsed = doc.get("parsed")
        if not isinstance(parsed, dict) or parsed.get("value") is None:
            continue
        metrics = {GATE_METRIC: float(parsed["value"])}
        for key in ("median_s", "vs_baseline"):
            if isinstance(parsed.get(key), (int, float)):
                metrics[key] = float(parsed[key])
        rec = {
            "v": 0, "kind": "bench", "legacy": True,
            "source": os.path.basename(path),
            "metrics": metrics,
        }
        timers = parsed.get("timers")
        if isinstance(timers, dict):
            rec["timers"] = {
                k: v for k, v in timers.items()
                if isinstance(v, (int, float))
            }
        out.append(rec)
    return out


def collect_records(ledger: str | None, legacy_glob: str | None,
                    kind: str = "bench") -> list[dict]:
    """Legacy artifacts first (oldest), then ledger records in append
    order — the gate's notion of time."""
    records = load_legacy_bench(legacy_glob) if kind == "bench" else []
    records += load_history(ledger or default_ledger_path(),
                            kinds=(kind,))
    return records


def metric_series(records: list[dict]) -> dict[str, list[float]]:
    """{metric: ordered values} over every numeric ``metrics`` entry."""
    series: dict[str, list[float]] = {}
    for rec in records:
        for name, val in rec.get("metrics", {}).items():
            if isinstance(val, (int, float)):
                series.setdefault(name, []).append(float(val))
    return series


# --------------------------------------------------------------------------
# output
# --------------------------------------------------------------------------

def trend_table(records: list[dict]) -> str:
    series = metric_series(records)
    if not series:
        return "no records"
    width = max(len("metric"), *(len(n) for n in series)) + 2
    lines = [f"{'metric':<{width}}{'n':>4} {'min':>10} {'median':>10} "
             f"{'last':>10}  trend"]
    for name in sorted(series):
        vals = series[name]
        lines.append(
            f"{name:<{width}}{len(vals):>4} {min(vals):>10.4g} "
            f"{_median(vals):>10.4g} {vals[-1]:>10.4g}  "
            f"{sparkline(vals)}"
        )
    return "\n".join(lines)


def serve_table(ledger: str | None = None, limit: int = 12) -> str:
    """Serve-throughput history out of the ``kind:"serve"`` ledger
    records every worker drain appends: ``jobs_per_hour`` next to the
    batched-dispatch engagement figures (``batch``, dispatches, mean
    fill), the drain's ``device_duty_cycle`` (ISSUE 11 — device
    seconds per wall second; low duty with work queued means the
    pipeline is starving the devices), the end-to-end latency tail
    (``sojourn_p95``/``queue_wait_p95``, from the per-job lifecycle
    timelines — obs/timeline.py) and the fleet host, so "did batching
    engage" and "which host is slow" are answerable from the default
    report view."""
    records = load_history(ledger or default_ledger_path(),
                           kinds=("serve",))
    if not records:
        return ""
    jph = [float(r["metrics"]["jobs_per_hour"]) for r in records
           if isinstance(r.get("metrics", {}).get("jobs_per_hour"),
                         (int, float))]

    def _sec(m, key):
        v = m.get(key)
        return f"{float(v):>7.3g}" if isinstance(v, (int, float)) \
            else f"{'-':>7}"

    lines = [f"serve throughput ({len(records)} drain record(s); "
             f"newest last):",
             f"  {'ts':<20}{'host':<12}{'ok/claimed':>11}"
             f"{'jobs/h':>10}{'batch':>6}{'disp':>6}{'fill':>6}"
             f"{'duty':>6}{'soj95':>7}{'qw95':>7}"]
    for rec in records[-limit:]:
        m = rec.get("metrics", {})
        cfg = rec.get("config", {})
        disp = int(m.get("batched_dispatches", 0))
        fill = (f"{int(m.get('batch_fill', 0)) / disp:.2f}"
                if disp else "-")
        ok_claimed = (f"{int(m.get('jobs_succeeded', 0))}/"
                      f"{int(m.get('jobs_claimed', 0))}")
        duty = m.get("device_duty_cycle")
        lines.append(
            f"  {str(rec.get('ts', ''))[:19]:<20}"
            f"{str(cfg.get('host') or '-')[:11]:<12}"
            f"{ok_claimed:>11}"
            f"{float(m.get('jobs_per_hour', 0.0)):>10.4g}"
            f"{int(m.get('batch', 1)):>6}{disp:>6}{fill:>6}"
            + (f"{float(duty):>6.2f}"
               if isinstance(duty, (int, float)) else f"{'-':>6}")
            + _sec(m, "sojourn_p95") + _sec(m, "queue_wait_p95"))
    if jph:
        lines.append(f"  jobs/h trend: {sparkline(jph)}  "
                     f"(median {_median(jph):.4g}, last {jph[-1]:.4g})")
    return "\n".join(lines)


def loadgen_table(ledger: str | None = None) -> str:
    """The newest saturation sweep (``kind:"loadgen"`` ledger record,
    ``tools/loadgen.py``) as a rate x percentile table: offered vs
    achieved throughput with the phase-decomposed sojourn tail per
    rate point, the detected knee, and the knee-throughput trend
    across sweeps."""
    records = load_history(ledger or default_ledger_path(),
                           kinds=("loadgen",))
    if not records:
        return ""
    rec = records[-1]
    m = rec.get("metrics", {})
    lines = [f"loadgen saturation ({len(records)} sweep(s); newest "
             f"from {str(rec.get('ts', ''))[:19]}):",
             f"  {'rate/s':>8}{'ach/s':>8}{'p50_s':>9}{'p95_s':>9}"
             f"{'p99_s':>9}{'duty':>6}{'quar':>6}"]
    for row in rec.get("rates", []):
        if not isinstance(row, dict):
            continue
        lines.append(
            f"  {float(row.get('rate', 0.0)):>8.4g}"
            f"{float(row.get('achieved', 0.0)):>8.4g}"
            f"{float(row.get('p50_s', 0.0)):>9.4g}"
            f"{float(row.get('p95_s', 0.0)):>9.4g}"
            f"{float(row.get('p99_s', 0.0)):>9.4g}"
            f"{float(row.get('duty', 0.0)):>6.2f}"
            f"{int(row.get('quarantined', 0)):>6}")
    knee_r = m.get("knee_rate_per_s")
    knee_t = m.get("knee_throughput_per_s")
    if isinstance(knee_t, (int, float)):
        lines.append(f"  knee: {float(knee_r or 0.0):.4g}/s offered "
                     f"-> {float(knee_t):.4g}/s achieved")
    knees = [float(r["metrics"]["knee_throughput_per_s"])
             for r in records
             if isinstance(r.get("metrics", {}).get(
                 "knee_throughput_per_s"), (int, float))]
    if len(knees) > 1:
        lines.append(f"  knee trend: {sparkline(knees)}  "
                     f"(median {_median(knees):.4g}, "
                     f"last {knees[-1]:.4g})")
    return "\n".join(lines)


def jerk_table(ledger: str | None = None, limit: int = 12) -> str:
    """Jerk-bench history (``kind:"jerk"`` ledger records — ISSUE 13):
    per-trial cost next to the jerk-grid size and the resolved trial
    LATTICE column, so "did the tuner's u8/bf16 pick actually engage"
    and "what does a jerk trial cost" are trendable from the default
    report view."""
    records = load_history(ledger or default_ledger_path(),
                           kinds=("jerk",))
    if not records:
        return ""
    lines = [f"jerk bench ({len(records)} record(s); newest last):",
             f"  {'ts':<20}{'njerk':>6}{'mult':>7}{'lattice':>9}"
             f"{'s/ktrial':>10}{'wall_x':>8}"]
    for rec in records[-limit:]:
        m = rec.get("metrics", {})
        lat = str(rec.get("trial_lattice") or "-")
        lines.append(
            f"  {str(rec.get('ts', ''))[:19]:<20}"
            f"{int(m.get('njerk', 0)):>6}"
            f"{float(m.get('jerk_trial_multiplier', 0.0)):>7.3g}"
            f"{lat:>9}"
            f"{float(m.get('jerk_s_per_ktrial', 0.0)):>10.4g}"
            f"{float(m.get('jerk_wallclock_ratio', 0.0)):>8.3g}")
    vals = [float(r["metrics"]["jerk_s_per_ktrial"]) for r in records
            if isinstance(r.get("metrics", {}).get("jerk_s_per_ktrial"),
                          (int, float))]
    if vals:
        lines.append(f"  s/ktrial trend: {sparkline(vals)}  "
                     f"(median {_median(vals):.4g}, last "
                     f"{vals[-1]:.4g})")
    return "\n".join(lines)


def sensitivity_table(ledger: str | None = None,
                      limit: int = 12) -> str:
    """Sensitivity-sweep history (``kind:"sensitivity"`` ledger
    records — ISSUE 14): recovery fraction and min detectable SNR per
    sweep, with the newest sweep's injected->recovered transfer curve,
    so "is the pipeline still finding the pulsars we plant" is
    trendable from the default report view."""
    records = load_history(ledger or default_ledger_path(),
                           kinds=("sensitivity",))
    if not records:
        return ""
    lines = [f"sensitivity sweeps ({len(records)} record(s); "
             f"newest last):",
             f"  {'ts':<20}{'cells':>6}{'recov':>6}{'fraction':>9}"
             f"{'min_snr':>8}{'sweep_s':>8}"]
    for rec in records[-limit:]:
        m = rec.get("metrics", {})
        min_snr = m.get("min_detectable_snr")
        lines.append(
            f"  {str(rec.get('ts', ''))[:19]:<20}"
            f"{int(m.get('cells', 0)):>6}"
            f"{int(m.get('recovered', 0)):>6}"
            f"{float(m.get('recovery_fraction', 0.0)):>9.3g}"
            + (f"{float(min_snr):>8.3g}" if min_snr is not None
               else f"{'-':>8}")
            + f"{float(m.get('sweep_elapsed_s', 0.0)):>8.3g}")
    transfer = records[-1].get("transfer") or []
    for row in transfer:
        lines.append(
            f"    snr_in {float(row.get('snr_in', 0.0)):>6.3g}  -> "
            f"recovered {int(row.get('recovered', 0))}/"
            f"{int(row.get('cells', 0))}"
            f"  snr_out_mean {float(row.get('snr_out_mean', 0.0)):.4g}")
    vals = [float(r["metrics"]["recovery_fraction"]) for r in records
            if isinstance(r.get("metrics", {}).get("recovery_fraction"),
                          (int, float))]
    if vals:
        lines.append(f"  recovery trend: {sparkline(vals)}  "
                     f"(median {_median(vals):.4g}, last "
                     f"{vals[-1]:.4g})")
    return "\n".join(lines)


def chaos_table(ledger: str | None = None, limit: int = 12) -> str:
    """Chaos-recovery history (``kind:"chaos"`` ledger records —
    ISSUE 15): how fast the supervisor brought ``health`` back to
    exit 0 after the seeded fault plan, next to the run's job and
    admission accounting, so "is the fleet still self-healing, and is
    it getting slower at it" is trendable from the default report
    view."""
    records = load_history(ledger or default_ledger_path(),
                           kinds=("chaos",))
    if not records:
        return ""
    lines = [f"chaos recovery ({len(records)} record(s); newest "
             f"last):",
             f"  {'ts':<20}{'faults':>7}{'jobs':>6}{'done':>6}"
             f"{'failed':>7}{'rejected':>9}{'recov_s':>9}"]
    for rec in records[-limit:]:
        m = rec.get("metrics", {})
        lines.append(
            f"  {str(rec.get('ts', ''))[:19]:<20}"
            f"{int(m.get('faults_injected', 0)):>7}"
            f"{int(m.get('jobs_total', 0)):>6}"
            f"{int(m.get('jobs_done', 0)):>6}"
            f"{int(m.get('jobs_failed', 0)):>7}"
            f"{int(m.get('admission_rejected', 0)):>9}"
            f"{float(m.get('chaos_recovery_s', 0.0)):>9.3g}")
    vals = [float(r["metrics"]["chaos_recovery_s"]) for r in records
            if isinstance(r.get("metrics", {}).get("chaos_recovery_s"),
                          (int, float))]
    if vals:
        lines.append(f"  recovery trend: {sparkline(vals)}  "
                     f"(median {_median(vals):.4g} s, last "
                     f"{vals[-1]:.4g} s)")
    return "\n".join(lines)


def coldstart_table(ledger: str | None = None, limit: int = 12) -> str:
    """Cold-start history (``kind:"coldstart"`` ledger records —
    ISSUE 18): wall time from worker start to the first finished job,
    decomposed into read / trace / compile / execute phases, next to
    the warm-drain figure and the compile count the cold drain paid,
    so "did dispatch get slower to first science, and which phase ate
    it" is trendable from the default report view."""
    records = load_history(ledger or default_ledger_path(),
                           kinds=("coldstart",))
    if not records:
        return ""
    lines = [f"cold start ({len(records)} record(s); newest last):",
             f"  {'ts':<20}{'cold_s':>8}{'read':>7}{'trace':>7}"
             f"{'compile':>8}{'exec':>7}{'warm_s':>8}{'compiles':>9}"]
    for rec in records[-limit:]:
        m = rec.get("metrics", {})
        lines.append(
            f"  {str(rec.get('ts', ''))[:19]:<20}"
            f"{float(m.get('cold_to_first_candidate_s', 0.0)):>8.3g}"
            f"{float(m.get('coldstart_read_s', 0.0)):>7.2g}"
            f"{float(m.get('coldstart_trace_s', 0.0)):>7.2g}"
            f"{float(m.get('coldstart_compile_s', 0.0)):>8.2g}"
            f"{float(m.get('coldstart_execute_s', 0.0)):>7.2g}"
            f"{float(m.get('warm_to_first_candidate_s', 0.0)):>8.3g}"
            f"{int(m.get('coldstart_compiles', 0)):>9}")
    vals = [float(r["metrics"]["cold_to_first_candidate_s"])
            for r in records
            if isinstance(r.get("metrics", {}).get(
                "cold_to_first_candidate_s"), (int, float))]
    if vals:
        lines.append(f"  cold-start trend: {sparkline(vals)}  "
                     f"(median {_median(vals):.4g} s, last "
                     f"{vals[-1]:.4g} s)")
    return "\n".join(lines)


def stage_table(records: list[dict]) -> str:
    """Trailing per-stage device-time and utilization figures (from the
    newest record that carries them)."""
    for rec in reversed(records):
        stages = rec.get("stage_device_s")
        if stages:
            util = rec.get("utilization", {})
            lines = ["latest per-stage figures:"]
            for name in sorted(stages, key=lambda k: -stages[k]):
                u = util.get(name)
                ustr = f"{100 * u:6.1f}%" if u is not None else "      -"
                lines.append(
                    f"  {name:<24}{stages[name]:>10.4f} s  util {ustr}")
            return "\n".join(lines)
    return ""


# --------------------------------------------------------------------------
# regression gate
# --------------------------------------------------------------------------

def regression_gate(records: list[dict], metric: str = GATE_METRIC,
                    head: int = 1, window: int = 8,
                    threshold: float = 1.4,
                    z: float = 4.0) -> tuple[int, str]:
    """(exit_code, message).  0 = clean or not enough history; 1 =
    regression.  Baseline-aware (ISSUE 16): the head median is judged
    against the trailing window's statistical band

        median ± max(z · 1.4826 · MAD, (threshold-1) · median)

    so a *noisy* history widens its own acceptance band (a 4-sigma
    robust z-score must be exceeded) while a *quiet* history keeps
    the old fixed-ratio floor exactly (MAD ≈ 0 collapses the band to
    ``threshold × median``).  Deterministic given the ledger — same
    history in, same verdict out.  Metrics in
    ``HIGHER_IS_BETTER_METRICS`` flip the band, so a duty-cycle
    COLLAPSE trips the same gate a wall-clock blow-up does."""
    from ..obs.baseline import baseline_band

    vals = metric_series(records).get(metric, [])
    if len(vals) < 2:
        return 0, (f"gate: only {len(vals)} `{metric}` record(s) — "
                   f"not enough history to judge (pass)")
    head = max(1, int(head))
    window = max(1, int(window))
    head_vals = vals[-head:]
    base_vals = vals[-(head + window):-head]
    if not base_vals:
        base_vals = vals[:-head]
    head_med = _median(head_vals)
    floor_frac = max(float(threshold) - 1.0, 0.0)
    base_med, band = baseline_band(base_vals, z=z,
                                   floor_frac=floor_frac)
    if base_med <= 0:
        return 0, f"gate: non-positive baseline for `{metric}` (pass)"
    higher_better = metric in HIGHER_IS_BETTER_METRICS
    if higher_better and head_med <= 0:
        return 1, (f"REGRESSION gate: {metric} collapsed to "
                   f"{head_med:.4g} (higher is better)")
    limit = base_med - band if higher_better else base_med + band
    tripped = (head_med < limit) if higher_better \
        else (head_med > limit)
    desc = (f"gate: {metric} head median {head_med:.4g} "
            f"(n={len(head_vals)}) vs baseline {base_med:.4g} "
            f"± {band:.4g} (n={len(base_vals)}, z={z:g}, "
            f"floor {threshold:.2f}x"
            + (", inverted: higher is better)"
               if higher_better else ")"))
    if tripped:
        return 1, "REGRESSION " + desc
    return 0, "OK " + desc


# --------------------------------------------------------------------------
# CLI
# --------------------------------------------------------------------------

def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m peasoup_tpu.tools.perf_report",
        description="perf trends + regression gate over the bench "
                    "history ledger (benchmarks/history.jsonl) and the "
                    "legacy BENCH_r0*.json artifacts",
    )
    p.add_argument("--ledger", default=None,
                   help=f"history ledger path (default: "
                        f"{default_ledger_path()})")
    p.add_argument("--legacy-glob", default=None,
                   help="glob for the committed BENCH artifacts "
                        "(default: <repo>/BENCH_r0*.json; pass an "
                        "empty string to skip them)")
    p.add_argument("--kind", default="bench",
                   help="ledger record kind to report on "
                        "(default: bench)")
    p.add_argument("--metric", default=GATE_METRIC,
                   help=f"gate metric, lower is better "
                        f"(default: {GATE_METRIC})")
    p.add_argument("--stage-metrics",
                   default=",".join(STAGE_GATE_METRICS),
                   help="comma-separated per-stage device-time metrics "
                        "the gate additionally checks (default: "
                        f"{','.join(STAGE_GATE_METRICS)}; pass an "
                        "empty string to gate wall-clock only)")
    p.add_argument("--head", type=int, default=1,
                   help="newest records whose median is gated "
                        "(default: 1)")
    p.add_argument("--window", type=int, default=8,
                   help="trailing records forming the baseline median "
                        "(default: 8)")
    p.add_argument("--threshold", type=float, default=1.4,
                   help="fail when head/base exceeds this ratio "
                        "(default: 1.4)")
    p.add_argument("--gate", action="store_true",
                   help="run the regression gate (nonzero exit on "
                        "regression)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit one JSON object instead of text")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    legacy = args.legacy_glob
    if legacy == "":
        legacy = os.path.join("/nonexistent", "none")  # skip legacy
    try:
        records = collect_records(args.ledger, legacy, kind=args.kind)
    except OSError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2

    gate_code, gate_msg = 0, None
    if args.gate:
        metrics = [args.metric] + [
            m.strip() for m in (args.stage_metrics or "").split(",")
            if m.strip() and m.strip() != args.metric
        ]
        # the jerk bench's metrics live in kind="jerk" records and the
        # sensitivity sweep's in kind="sensitivity"; widen the gate's
        # view so jerk_s_per_ktrial / recovery_fraction are judged
        # against their own history (metric_series keys never collide
        # across kinds — absent metrics still pass vacuously)
        gate_records = records
        if args.kind == "bench":
            try:
                gate_records = records + load_history(
                    args.ledger or default_ledger_path(),
                    kinds=("jerk", "sensitivity", "chaos",
                           "coldstart"))
            except OSError:
                pass
        codes, msgs = [], []
        for m in metrics:
            code, msg = regression_gate(
                gate_records, metric=m, head=args.head,
                window=args.window, threshold=args.threshold)
            codes.append(code)
            msgs.append(msg)
        gate_code, gate_msg = max(codes), "\n".join(msgs)

    if args.as_json:
        doc = {
            "records": len(records),
            "metrics": {
                name: {"n": len(vals), "min": min(vals),
                       "median": _median(vals), "last": vals[-1]}
                for name, vals in metric_series(records).items()
            },
        }
        if args.gate:
            doc["gate"] = {"ok": gate_code == 0, "message": gate_msg}
        print(json.dumps(doc, indent=1, sort_keys=True))
        return gate_code

    n_legacy = sum(1 for r in records if r.get("legacy"))
    print(f"{len(records)} `{args.kind}` record(s) "
          f"({n_legacy} legacy BENCH artifact(s) + "
          f"{len(records) - n_legacy} ledger)")
    print()
    print(trend_table(records))
    st = stage_table(records)
    if st:
        print()
        print(st)
    if args.kind == "bench":
        sv = serve_table(args.ledger)
        if sv:
            print()
            print(sv)
        lg = loadgen_table(args.ledger)
        if lg:
            print()
            print(lg)
        jt = jerk_table(args.ledger)
        if jt:
            print()
            print(jt)
        sn = sensitivity_table(args.ledger)
        if sn:
            print()
            print(sn)
        ct = chaos_table(args.ledger)
        if ct:
            print()
            print(ct)
        cs = coldstart_table(args.ledger)
        if cs:
            print()
            print(cs)
    if gate_msg:
        print()
        print(gate_msg)
    return gate_code


if __name__ == "__main__":
    raise SystemExit(main())
