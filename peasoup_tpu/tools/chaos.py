"""Chaos harness (``make chaos-smoke``): prove the fleet self-heals.

The fleet/health/loadgen smokes prove the *sensing* plane; this
driver proves the *acting* plane (serve/supervisor.py) by injecting
real faults into a real-subprocess fleet under live two-rate loadgen
traffic and asserting the system returns to ``health`` exit 0 on its
own, with zero jobs lost or double-run.

Seeded fault plan (``--seed``), per ISSUE 15's smoke recipe:

* **worker SIGKILL mid-job** — the claimed job's lease goes stale;
  the supervisor must detect (``stale_host`` crit), reap
  (``reap_expired`` action) and respawn capacity (``scale_up``), and
  the job must finish on its second attempt — exactly one
  ``lease_expired`` failure entry, never a double-run;
* **one poison input** — a filterbank truncated mid-data must be
  quarantined (typed, attempt 1) without poisoning the drain;
* **one over-quota tenant** — a flooding tenant is deferred with a
  typed :class:`~peasoup_tpu.errors.AdmissionError` by its token
  bucket while the fair-share tenant's jobs all complete within the
  recovery budget.

Phase B (control) re-runs the SIGKILL fault with NO supervisor and
asserts ``health`` stays at exit 1 — proving the loop, not the
absence of faults, is what heals.

The module also exposes the raw fault primitives (SIGSTOP/SIGCONT
freeze, spool-record corruption, lease clock-skew, input truncation)
for targeted tests; the smoke exercises the ISSUE recipe only —
a corrupted *pending* record, for instance, deliberately never
drains, so it cannot sit in a health-gated drain loop.

``--smoke`` appends one ``kind:"chaos"`` ledger record whose headline
``chaos_recovery_s`` (fault injection -> health exit 0) is what
``bench.py --chaos`` prints and ``tools/perf_report.py`` trends.

Flight-recorder tie-in (ISSUE 16): the smoke additionally asserts
that ``obs.baseline.fleet_presence_anomalies`` *detects* the SIGKILL
purely from the telemetry shards — typed ``kind:"anomaly"`` records
in the fault window, clean bins again once capacity respawns — and
appends those records to the ledger, where ``serve health``'s
``anomaly`` rule reads them.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import shutil
import signal
import subprocess
import sys
import time
import warnings

from .fleet_smoke import FAST, _check, _write_synthetic

#: default wall-clock budget for the supervised phase (submit ->
#: fault -> full recovery)
DEFAULT_BUDGET_S = 360.0

#: worker flags the supervisor passes to every spawned fleet-worker
WORKER_ARGS = [
    "--max-attempts", "2", "--backoff-base", "0",
    "--lease-ttl", "5", "--heartbeat", "0.5",
    "--telemetry-interval", "0.25", "--poll", "0.3",
]


# -- fault primitives ------------------------------------------------------

def sigkill(pid: int) -> None:
    """Hard-kill a worker mid-job (no cleanup, lease goes stale)."""
    os.kill(int(pid), signal.SIGKILL)


def freeze(pid: int) -> None:
    """SIGSTOP a worker: telemetry and heartbeats freeze but the
    process survives — indistinguishable from a wedged host until
    thawed."""
    os.kill(int(pid), signal.SIGSTOP)


def thaw(pid: int) -> None:
    os.kill(int(pid), signal.SIGCONT)


def truncate_input(path: str, keep_bytes: int) -> str:
    """Chop an input file short of what its header declares (poison:
    typed quarantine at the worker)."""
    with open(path, "rb+") as f:
        f.truncate(max(0, int(keep_bytes)))
    return path


def corrupt_record(spool, state: str, job_id: str) -> str:
    """Overwrite a job record with garbage (readers must warn
    ``job_record_corrupt`` and skip, never crash)."""
    path = os.path.join(spool.root, state, f"{job_id}.json")
    with open(path, "w") as f:
        f.write("{torn json" + os.urandom(4).hex())
    return path


def clock_skew_lease(spool, job_id: str, skew_s: float) -> None:
    """Rewrite a lease heartbeat as if the writer's clock were off by
    ``skew_s`` seconds (negative = heartbeat from the past, ages the
    lease toward reaping)."""
    lease = spool.lease_info(job_id) or {"v": 1, "job_id": job_id}
    lease["utc"] = round(float(lease.get("utc", time.time()))
                         + float(skew_s), 3)
    path = spool._lease_path(job_id)
    tmp = path + f".tmp{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(lease, f)
    os.replace(tmp, path)


def compactor_kill(store_root: str, stage: str,
                   timeout_s: float = 120.0) -> int:
    """Run one store compaction in a subprocess and hard-kill it at
    ``stage`` (ISSUE 20 crash drill).  The subprocess uses the
    ``compact`` verb's ``--fault-stage`` hook, which dies via
    ``os._exit`` — no unwind, no cleanup — so the on-disk state is
    exactly what a SIGKILLed compactor leaves: a ``.tmp*`` orphan at
    worst, the live JSONL shards untouched, the manifest never
    half-written.  Stages: ``scan``, ``segment_partial``,
    ``segment_done``, ``index_done``, ``pre_manifest``.  Returns the
    subprocess exit code (137 when the fault fired)."""
    proc = subprocess.run(
        _serve(store_root, "compact", "--force",
               "--fault-stage", str(stage)),
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True, timeout=float(timeout_s))
    return proc.returncode


def make_plan(seed: int) -> list[dict]:
    """The smoke's seeded fault plan.  The fault *set* is fixed (the
    ISSUE recipe); the seed varies the arrival schedule and which
    science job is poisoned, so repeated CI runs walk different
    interleavings while any single run reproduces from its seed."""
    rng = random.Random(int(seed))
    return [
        {"fault": "sigkill_worker", "when": "first claim"},
        {"fault": "poison_input",
         "science_slot": rng.randrange(5)},
        {"fault": "overquota_tenant", "tenant": "flood",
         "submits": 8},
    ]


# -- process helpers -------------------------------------------------------

def _serve(spool_dir: str, *verb_args: str) -> list[str]:
    return [sys.executable, "-m", "peasoup_tpu.serve",
            "--spool", spool_dir] + list(verb_args)


def _health_cmd(spool_dir: str, history: str) -> list[str]:
    return _serve(spool_dir, "health", "--stale-after", "6",
                  "--window", "45", "--ledger", history)


def _health_exit(spool_dir: str, history: str, env: dict) -> int:
    proc = subprocess.run(_health_cmd(spool_dir, history), env=env,
                          capture_output=True, text=True, timeout=120)
    return proc.returncode


def _read_status(spool_dir: str) -> dict:
    try:
        with open(os.path.join(spool_dir, "supervisor.json")) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def _stop_proc(proc, timeout_s: float = 20.0) -> None:
    if proc is None or proc.poll() is not None:
        return
    proc.terminate()
    try:
        proc.wait(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait(timeout=10.0)


# -- the smoke -------------------------------------------------------------

def run_smoke(workdir: str, *, budget_s: float = DEFAULT_BUDGET_S,
              seed: int = 0, history: str | None = None,
              control: bool = True) -> tuple[int, dict]:
    """Run the seeded chaos plan; returns (exit_code, report)."""
    from peasoup_tpu.errors import AdmissionError
    from peasoup_tpu.obs.baseline import (
        fleet_presence_anomalies,
        write_anomalies,
    )
    from peasoup_tpu.obs.history import (
        append_history,
        load_history,
        make_history_record,
    )
    from peasoup_tpu.serve import (
        LEASE_EXPIRED,
        AdmissionPolicy,
        JobSpool,
        TenantPolicy,
    )
    from peasoup_tpu.serve.retry import pause

    shutil.rmtree(workdir, ignore_errors=True)
    os.makedirs(workdir)
    spool_dir = os.path.join(workdir, "jobs")
    history = history or os.path.join(workdir, "history.jsonl")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    failures: list[str] = []
    plan = make_plan(seed)
    print("chaos plan (seed %d):" % seed)
    for fault in plan:
        print("  " + json.dumps(fault, sort_keys=True))

    # admission policy BEFORE the spool loads it: science is the
    # fair-share tenant (weight 2, unlimited rate), flood is capped at
    # a 3-submit burst refilling slowly
    os.makedirs(spool_dir, exist_ok=True)
    AdmissionPolicy(max_pending=64, tenants={
        "science": TenantPolicy(weight=2.0),
        "flood": TenantPolicy(rate_per_s=0.2, burst=3.0, weight=1.0),
    }).save(spool_dir)
    spool = JobSpool(spool_dir)

    # ---- phase A: supervised fleet under the fault plan --------------
    sup_proc = subprocess.Popen(
        _serve(spool_dir, "supervise", "--interval", "1",
               "--ticks", "0", "--max-workers", "2",
               "--single_device", "--lease-ttl", "5",
               "--stale-after", "6", "--window", "45",
               "--actions-window", "60", "--max-actions", "10",
               "--cooldown", "scale_up=3",
               "--cooldown", "reap_expired=4",
               "--telemetry-interval", "0.3",
               "--history", history, "--ledger", history,
               *[f"--worker-arg={a}" for a in WORKER_ARGS]),
        env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)

    t0 = time.time()
    deadline = t0 + float(budget_s)
    report: dict = {"v": 1, "seed": int(seed), "plan": plan}
    killed_pid = None
    killed_job = None
    t_fault = None
    recovery_s = None
    try:
        # two-rate drive: a slow science trickle, then a fast wave the
        # flood tenant piggybacks on (its burst is 3; the rest must be
        # refused with a typed AdmissionError)
        poison_slot = plan[1]["science_slot"]
        science_jobs = []
        rng = random.Random(seed + 1)
        for i in range(5):
            path = _write_synthetic(
                os.path.join(workdir, f"sci{i}.fil"), seed=i)
            if i == poison_slot:
                truncate_input(path, os.path.getsize(path) - 1024)
            science_jobs.append(
                spool.submit(path, FAST, tenant="science"))
            pause(0.4 + 0.2 * rng.random() if i < 2 else 0.05)
        flood_jobs, rejected = [], 0
        for i in range(int(plan[2]["submits"])):
            path = _write_synthetic(
                os.path.join(workdir, f"flood{i}.fil"), seed=10 + i)
            try:
                flood_jobs.append(
                    spool.submit(path, FAST, tenant="flood"))
            except AdmissionError as exc:
                rejected += 1
                assert exc.tenant == "flood"
        _check(rejected == 5 and len(flood_jobs) == 3,
               f"over-quota tenant deferred with AdmissionError "
               f"(3 admitted, {rejected} rejected)", failures)
        all_jobs = science_jobs + flood_jobs

        # wait for the supervisor to scale up and a worker to claim
        workers: list = []
        while time.time() < deadline:
            status = _read_status(spool_dir)
            workers = status.get("workers", [])
            if workers and spool.counts()["running"] >= 1:
                break
            pause(0.2)
        running = spool.jobs("running")
        _check(bool(running) and bool(workers),
               "supervisor spawned a worker that claimed a job",
               failures)

        # FAULT: SIGKILL the worker that owns a running job's lease
        by_label = {w["label"]: w["pid"] for w in workers}
        for rec in running:
            if rec.host in by_label:
                killed_job, killed_pid = rec, by_label[rec.host]
                break
        if killed_job is None and running:
            killed_job = running[0]
            killed_pid = workers[0]["pid"]
        _check(killed_pid is not None,
               "found a worker pid holding a running-job lease",
               failures)
        if killed_pid is not None:
            sigkill(killed_pid)
        t_fault = time.time()
        print(f"chaos: SIGKILL worker pid {killed_pid} holding job "
              f"{killed_job.job_id if killed_job else '?'} "
              f"at t+{t_fault - t0:.1f}s")

        # recovery: all jobs terminal AND health exit 0, inside budget
        t_terminal = None
        while time.time() < deadline:
            counts = spool.counts()
            terminal = counts["done"] + counts["failed"]
            if terminal >= len(all_jobs) \
                    and counts["running"] == counts["pending"] == 0:
                if t_terminal is None:
                    t_terminal = time.time()
                if _health_exit(spool_dir, history, env) == 0:
                    recovery_s = time.time() - t_fault
                    break
            pause(0.5)
        _check(recovery_s is not None,
               f"health back to exit 0 within the "
               f"{budget_s:.0f}s budget", failures)
        if recovery_s is not None:
            print(f"chaos: recovered in {recovery_s:.1f}s after the "
                  f"fault")

        # zero lost, zero double-run: every job exactly once terminal,
        # attempts prove single execution (a double-run REQUIRES a
        # second claim, which increments attempts)
        done = {r.job_id: r for r in spool.jobs("done")}
        failed = {r.job_id: r for r in spool.jobs("failed")}
        ids = [r.job_id for r in all_jobs]
        _check(all((j in done) != (j in failed) for j in ids)
               and len(done) + len(failed) == len(ids),
               "zero lost jobs (every submit exactly once terminal)",
               failures)
        poison_id = science_jobs[poison_slot].job_id
        _check(poison_id in failed
               and failed[poison_id].failures[0]["classification"]
               == "quarantine"
               and failed[poison_id].attempts == 1,
               "poison input quarantined (typed, attempt 1)",
               failures)
        kid = killed_job.job_id if killed_job else None
        krec = done.get(kid)
        _check(krec is not None and krec.attempts == 2
               and [f["classification"] for f in krec.failures]
               == [LEASE_EXPIRED],
               "killed job reaped + finished on attempt 2 (exactly "
               "one lease_expired entry)", failures)
        clean = [r for j, r in done.items()
                 if j != kid]
        _check(all(r.attempts == 1 for r in clean),
               "zero double-runs (all other done jobs: attempt 1)",
               failures)
        sci_done = [j.job_id for j in science_jobs
                    if j.job_id in done or j.job_id in failed]
        _check(len(sci_done) == len(science_jobs),
               "fair-share tenant completed its whole quota despite "
               "the flood", failures)

        # the supervisor's paper trail: typed events + ledger records
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            sup_recs = load_history(history, kinds=["supervise"])
        actions = [r.get("action", {}).get("name") for r in sup_recs]
        _check(actions.count("scale_up") >= 2,
               f"scale_up respawned capacity after the kill "
               f"(ledger: {actions})", failures)
        _check("reap_expired" in actions,
               "reap_expired action recorded in the ledger", failures)
        _check(all(r.get("action", {}).get("finding_before")
                   for r in sup_recs),
               "every supervise record carries before/after finding "
               "state", failures)
        events_path = os.path.join(spool_dir,
                                   "supervisor-events.jsonl")
        kinds = []
        if os.path.exists(events_path):
            with open(events_path) as f:
                kinds = [json.loads(line).get("kind")
                         for line in f if line.strip()]
        _check(kinds.count("supervise_action") == len(sup_recs),
               "one typed supervise_action event per ledger record",
               failures)

        # the flight recorder must SEE the fault (ISSUE 16): the
        # killed worker's telemetry shard goes silent, so the
        # distinct-hosts-sampling-per-second count drops below its
        # own leave-one-out baseline during the kill window; once
        # scale_up respawns capacity the bins are clean again.  The
        # scan ends at t_terminal (drain complete) — past that the
        # supervisor may legitimately retire idle workers, which is
        # drawdown, not a fault.
        anoms: list[dict] = []
        during: list[dict] = []
        tail: list[dict] = []
        if t_fault is not None and t_terminal is not None:
            anoms = fleet_presence_anomalies(
                os.path.join(spool_dir, "fleet"),
                t_start=max(t0, t_fault - 10.0), t_end=t_terminal)
            during = [a for a in anoms
                      if t_fault - 1.0 <= a["ts"] <= t_fault + 20.0]
            tail = [a for a in anoms
                    if a["ts"] > t_terminal - 3.0]
        _check(bool(during),
               f"presence anomaly emitted during the fault window "
               f"({len(during)}/{len(anoms)} anomalies in window)",
               failures)
        _check(t_terminal is not None and not tail,
               "presence anomalies cleared after recovery (last 3s "
               "of bins clean)", failures)
        if anoms:
            write_anomalies(anoms, history)
        report["presence_anomalies"] = len(anoms)
    finally:
        _stop_proc(sup_proc)
        out = sup_proc.stdout.read() if sup_proc.stdout else ""
        print("---- supervisor ----")
        print("\n".join(out.strip().splitlines()[-12:]))

    counts = spool.counts()
    report.update(
        recovery_s=(round(recovery_s, 3)
                    if recovery_s is not None else None),
        jobs_total=len(all_jobs),
        jobs_done=counts["done"],
        jobs_failed=counts["failed"],
        admission_rejected=rejected,
        supervise_actions=actions,
    )

    # ---- phase B: same fault, NO supervisor -> health stays 1 --------
    if control:
        control_dir = os.path.join(workdir, "jobs-control")
        cspool = JobSpool(control_dir)
        cfil = _write_synthetic(os.path.join(workdir, "ctl.fil"),
                                seed=99)
        crec = cspool.submit(cfil, FAST)
        wproc = subprocess.Popen(
            _serve(control_dir, "fleet-worker", "--host-id", "0",
                   "--host-count", "1", "--label", "ctl-0",
                   "--single_device", *WORKER_ARGS),
            env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL)
        cdeadline = time.time() + 120.0
        while cspool.counts()["running"] == 0 \
                and time.time() < cdeadline:
            pause(0.1)
        _check(cspool.counts()["running"] == 1,
               "control: worker claimed mid-job", failures)
        sigkill(wproc.pid)
        wproc.wait(timeout=30)
        pause(6.0)  # past the 5s lease TTL and staleness threshold
        rc1 = _health_exit(control_dir, history, env)
        pause(3.0)
        rc2 = _health_exit(control_dir, history, env)
        _check(rc1 == 1 and rc2 == 1,
               "control: without a supervisor the same fault leaves "
               "health at exit 1", failures)
        _check(cspool.counts()["running"] == 1
               and cspool.get(crec.job_id)[0] == "running",
               "control: the job stays stuck in running/ (nothing "
               "heals it)", failures)
        report["control_health_exits"] = [rc1, rc2]

    # ---- ledger record + report --------------------------------------
    if recovery_s is not None:
        rec = make_history_record(
            "chaos",
            {"chaos_recovery_s": round(recovery_s, 3),
             "faults_injected": len(plan),
             "jobs_total": report["jobs_total"],
             "jobs_done": report["jobs_done"],
             "jobs_failed": report["jobs_failed"],
             "admission_rejected": rejected,
             "presence_anomalies": report.get(
                 "presence_anomalies", 0)},
            config={"seed": int(seed), "budget_s": float(budget_s),
                    "plan": plan})
        append_history(rec, history)
    report_path = os.path.join(workdir, "chaos_report.json")
    with open(report_path, "w") as f:
        json.dump(report, f, sort_keys=True, indent=1)
    print(f"wrote {report_path}")

    if failures:
        print(f"\nchaos-smoke: {len(failures)} check(s) FAILED",
              file=sys.stderr)
        return 1, report
    print("\nchaos-smoke: all checks passed")
    return 0, report


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="peasoup-tpu-chaos",
        description="Peasoup-TPU - chaos harness: fault injection "
                    "against the self-healing fleet")
    p.add_argument("--smoke", action="store_true",
                   help="run the seeded smoke plan (the make target)")
    p.add_argument("--dir", default="/tmp/peasoup-chaos-smoke",
                   help="scratch directory (wiped)")
    p.add_argument("--budget", type=float, default=DEFAULT_BUDGET_S,
                   help="recovery budget in seconds")
    p.add_argument("--seed", type=int, default=0,
                   help="fault-plan seed")
    p.add_argument("--history", default=None,
                   help="ledger path for the kind:\"chaos\" record "
                        "(default: <dir>/history.jsonl, hermetic)")
    p.add_argument("--no-control", action="store_true",
                   help="skip the no-supervisor control phase")
    args = p.parse_args(argv)
    rc, _ = run_smoke(args.dir, budget_s=args.budget, seed=args.seed,
                      history=args.history,
                      control=not args.no_control)
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
