"""Post-processing tools (python3 equivalents of the reference's
``tools/`` directory: peasoup_tools.py, peasoup_as_text.py,
peasoup_plot_cand.py)."""

from .postprocess import (
    JoinedCandidate,
    PeasoupOutput,
    as_text,
    as_text_main,
    radec_to_str,
)

__all__ = [
    "JoinedCandidate",
    "PeasoupOutput",
    "as_text",
    "as_text_main",
    "radec_to_str",
    "CandidatePlotter",
    "plot_cand_main",
]


def __getattr__(name):
    # lazy: plotting pulls in matplotlib
    if name in ("CandidatePlotter", "plot_cand_main"):
        from . import plot_cand

        return getattr(plot_cand, name)
    raise AttributeError(name)
