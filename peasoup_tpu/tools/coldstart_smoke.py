"""Cold-start observatory smoke test (``make coldstart-smoke``).

ISSUE 18's end-to-end check of the cold-start instrumentation, in one
process so the second drain is genuinely WARM (the jit program caches
of the first drain are still live):

Phase 1 — cold drain: spool two synthetic same-geometry observations
into a fresh spool and ``drain()`` a worker.  Assert the drain summary
carries the ``coldstart`` decomposition, that its read / trace /
compile / execute phases sum to the ``cold_to_first_candidate_s``
total (the decomposition is a partition, not a sampling), that the
``coldstart.cold_to_first_candidate_s`` gauge was recorded, and that
the spool-level compile ledger (``compiles.jsonl``) attributes every
backend compile to a named program AND a geometry fingerprint — an
anonymous compile is exactly the blind spot the ledger exists to
close.

Phase 2 — warm drain: the same observations through a SECOND spool +
worker in the same process.  The geometry is identical, so every
device program must replay from the in-process jit cache: the warm
spool's compile ledger must hold ZERO compile records.  A warm worker
that recompiles has broken program reuse (the regression the
``compile_storm`` health rule pages on).

Exit status 0 only if every assertion holds — CI-gateable like
``serve-smoke``.
"""

from __future__ import annotations

import argparse
import os
import shutil
import sys


def _check(ok: bool, what: str, failures: list[str]) -> None:
    print(("PASS " if ok else "FAIL ") + what)
    if not ok:
        failures.append(what)


def _drain(spool_dir: str, obs: list[str], overrides: dict,
           history: str | None) -> dict:
    from peasoup_tpu.serve import JobSpool, SurveyWorker

    spool = JobSpool(spool_dir)
    for path in obs:
        spool.submit(path, overrides)
    worker = SurveyWorker(spool, single_device=True,
                          history_path=history,
                          sleeper=lambda s: None)
    return worker.drain()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="peasoup-tpu-coldstart-smoke",
        description="Peasoup-TPU - cold-start observatory smoke test",
    )
    p.add_argument("--dir", default="/tmp/peasoup-coldstart-smoke",
                   help="scratch directory (wiped)")
    p.add_argument("--history", default=None,
                   help="history ledger to append serve records to "
                        "(default: the repo benchmarks/history.jsonl)")
    args = p.parse_args(argv)

    shutil.rmtree(args.dir, ignore_errors=True)
    os.makedirs(args.dir)

    from peasoup_tpu.obs.compilation import (
        read_compiles, reset_seen_geometries, summarize_compiles,
    )
    from peasoup_tpu.obs.metrics import REGISTRY
    from peasoup_tpu.tools.batch_smoke import _write_synthetic

    REGISTRY.reset()
    reset_seen_geometries()
    obs = [
        _write_synthetic(os.path.join(args.dir, f"obs{i}.fil"), seed=i)
        for i in range(2)
    ]
    overrides = {"dm_end": 20.0, "min_snr": 6.0, "npdmp": 0,
                 "limit": 10}
    failures: list[str] = []

    # ---- phase 1: cold drain -----------------------------------------
    cold_spool = os.path.join(args.dir, "jobs_cold")
    summary = _drain(cold_spool, obs, overrides, args.history)
    _check(summary["succeeded"] == 2, "cold drain finished 2/2 jobs",
           failures)

    cold = summary.get("coldstart") or {}
    total = float(cold.get("cold_to_first_candidate_s", 0.0))
    _check(total > 0.0,
           f"cold_to_first_candidate_s measured ({total:.3f} s)",
           failures)
    phases = (float(cold.get("read_s", 0.0))
              + float(cold.get("trace_s", 0.0))
              + float(cold.get("compile_s", 0.0))
              + float(cold.get("execute_s", 0.0)))
    _check(abs(phases - total) < 0.01,
           f"read/trace/compile/execute partition the total "
           f"({phases:.3f} vs {total:.3f} s)", failures)
    gauge = REGISTRY.snapshot()["gauges"].get(
        "coldstart.cold_to_first_candidate_s")
    _check(gauge is not None and float(gauge) == total,
           "coldstart.cold_to_first_candidate_s gauge recorded",
           failures)

    cold_recs = read_compiles(
        os.path.join(cold_spool, "compiles.jsonl"), kinds=("compile",))
    _check(len(cold_recs) > 0,
           f"cold drain ledgered {len(cold_recs)} compile(s)",
           failures)
    anon = [r for r in cold_recs
            if not r.get("program") or not r.get("geometry")]
    _check(not anon,
           "every ledgered compile names its program and geometry "
           f"({len(anon)} anonymous)", failures)
    for row in summarize_compiles(cold_recs)[:5]:
        print(f"  compile: {row['program']} @{row['geometry']} "
              f"x{row['compiles']} ({row['total_s']:.3f} s)")

    # ---- phase 2: warm drain (same process, same geometry) -----------
    REGISTRY.reset()
    warm_spool = os.path.join(args.dir, "jobs_warm")
    summary2 = _drain(warm_spool, obs, overrides, args.history)
    _check(summary2["succeeded"] == 2, "warm drain finished 2/2 jobs",
           failures)
    warm = summary2.get("coldstart") or {}
    _check(float(warm.get("cold_to_first_candidate_s", 0.0)) > 0.0,
           "warm drain decomposed its first-candidate time too",
           failures)
    warm_recs = read_compiles(
        os.path.join(warm_spool, "compiles.jsonl"), kinds=("compile",))
    _check(len(warm_recs) == 0,
           f"warm drain ledgered zero new compiles "
           f"({len(warm_recs)} found)", failures)

    if failures:
        print(f"\ncoldstart-smoke: {len(failures)} check(s) FAILED",
              file=sys.stderr)
        return 1
    print("\ncoldstart-smoke: all checks passed")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
