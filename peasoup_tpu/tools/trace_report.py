"""Summarise a span trace without Perfetto.

Reads the Chrome trace-event JSON written by ``--trace_json`` /
``bench.py --trace`` (`obs/trace.py`), rebuilds the span forest from
the ``B``/``E`` phase pairs, and prints

* a top-N **self-time** table (total minus direct children — the
  "where did the run actually go" ordering), and
* the **critical path**: starting from the longest root span, descend
  into the longest child at every level.

``--require NAME...`` exits nonzero unless every named span is
present — the ``make trace-smoke`` gate.

``--compare A.json B.json`` prints the per-stage **self-time delta**
table between two trace files (reusing the same forest rebuilder), so
a before/after perf investigation is one command instead of manual
Perfetto diffing.

Usage::

    python -m peasoup_tpu.tools.trace_report outdir/trace.json
    python -m peasoup_tpu.tools.trace_report trace.json --top 20
    python -m peasoup_tpu.tools.trace_report trace.json \
        --require Dedisperse DM-Loop Accel-Search Distill Folding
    python -m peasoup_tpu.tools.trace_report --compare before.json after.json
"""

from __future__ import annotations

import argparse
import json
import sys


def load_events(path: str) -> list[dict]:
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict):
        return doc.get("traceEvents", [])
    return doc  # bare event array is also a valid Chrome trace


def rebuild_spans(events: list[dict]) -> list[dict]:
    """Span forest from B/E pairs, per (pid, tid) stack.

    Returns the flat list of spans ``{name, pid, tid, ts, dur_ms,
    self_ms, device_ms, args, children}`` (roots have ``parent`` None).
    Raises ValueError on unbalanced phases — a trace that cannot be
    trusted should fail loudly, not summarise garbage.
    """
    per: dict[tuple, list[dict]] = {}
    for e in events:
        if e.get("ph") in ("B", "E"):
            per.setdefault((e.get("pid", 0), e.get("tid", 0)),
                           []).append(e)
    spans: list[dict] = []
    for (pid, tid), evs in per.items():
        evs.sort(key=lambda e: e["ts"])  # stable: file order on ties
        stack: list[dict] = []
        for e in evs:
            if e["ph"] == "B":
                s = {
                    "name": e.get("name", "?"), "pid": pid, "tid": tid,
                    "ts": e["ts"], "args": e.get("args", {}),
                    "children": [],
                    "parent": stack[-1] if stack else None,
                }
                if stack:
                    stack[-1]["children"].append(s)
                stack.append(s)
                spans.append(s)
            else:
                if not stack:
                    raise ValueError(
                        f"unbalanced trace: E without B at ts={e['ts']} "
                        f"(pid={pid}, tid={tid})")
                s = stack.pop()
                s["dur_ms"] = (e["ts"] - s["ts"]) / 1e3
        if stack:
            raise ValueError(
                f"unbalanced trace: {len(stack)} unclosed span(s) on "
                f"pid={pid}, tid={tid} (first: {stack[0]['name']})")
    for s in spans:
        s["self_ms"] = max(
            s["dur_ms"] - sum(c["dur_ms"] for c in s["children"]), 0.0)
        s["device_ms"] = float(s["args"].get("device_ms", 0.0))
    return spans


def aggregate_by_name(spans: list[dict]) -> dict[str, dict]:
    """Per-name ``{count, total_ms, self_ms, device_ms}`` totals —
    shared by the self-time table and ``--compare``."""
    agg: dict[str, dict] = {}
    for s in spans:
        rec = agg.setdefault(s["name"], {
            "count": 0, "total_ms": 0.0, "self_ms": 0.0,
            "device_ms": 0.0})
        rec["count"] += 1
        rec["total_ms"] += s["dur_ms"]
        rec["self_ms"] += s["self_ms"]
        rec["device_ms"] += s["device_ms"]
    return agg


def self_time_table(spans: list[dict], top: int = 15) -> str:
    agg = aggregate_by_name(spans)
    rows = sorted(agg.items(), key=lambda kv: -kv[1]["self_ms"])[:top]
    width = max([len("span")] + [len(name) for name, _ in rows]) + 2
    lines = [f"{'span':<{width}}{'n':>5} {'total_ms':>10} "
             f"{'self_ms':>10} {'device_ms':>10}"]
    for name, rec in rows:
        lines.append(
            f"{name:<{width}}{rec['count']:>5} {rec['total_ms']:>10.2f} "
            f"{rec['self_ms']:>10.2f} {rec['device_ms']:>10.2f}")
    if len(agg) > top:
        lines.append(f"... ({len(agg) - top} more span name(s))")
    return "\n".join(lines)


def compare_table(spans_a: list[dict], spans_b: list[dict],
                  label_a: str = "A", label_b: str = "B",
                  top: int = 0) -> str:
    """Per-stage self-time delta between two traces, largest absolute
    delta first.  B - A, so positive delta = B is slower there."""
    agg_a = aggregate_by_name(spans_a)
    agg_b = aggregate_by_name(spans_b)
    names = sorted(set(agg_a) | set(agg_b))
    zero = {"count": 0, "self_ms": 0.0, "device_ms": 0.0,
            "total_ms": 0.0}
    rows = []
    for name in names:
        a = agg_a.get(name, zero)
        b = agg_b.get(name, zero)
        delta = b["self_ms"] - a["self_ms"]
        ratio = (b["self_ms"] / a["self_ms"]
                 if a["self_ms"] > 0 else None)
        rows.append((name, a, b, delta, ratio))
    rows.sort(key=lambda r: -abs(r[3]))
    if top:
        rows = rows[:top]
    width = max([len("span")] + [len(r[0]) for r in rows]) + 2
    lines = [
        f"self-time delta ({label_b} - {label_a}; positive = "
        f"{label_b} slower):",
        f"{'span':<{width}}{'n_A':>5} {'n_B':>5} {'self_A_ms':>11} "
        f"{'self_B_ms':>11} {'delta_ms':>10} {'ratio':>7}",
    ]
    for name, a, b, delta, ratio in rows:
        lines.append(
            f"{name:<{width}}{a['count']:>5} {b['count']:>5} "
            f"{a['self_ms']:>11.2f} {b['self_ms']:>11.2f} "
            f"{delta:>+10.2f} "
            + (f"{ratio:>6.2f}x" if ratio is not None else f"{'new':>7}"))
    tot_a = sum(r[1]["self_ms"] for r in rows)
    tot_b = sum(r[2]["self_ms"] for r in rows)
    lines.append(
        f"{'TOTAL':<{width}}{'':>5} {'':>5} {tot_a:>11.2f} "
        f"{tot_b:>11.2f} {tot_b - tot_a:>+10.2f} "
        + (f"{tot_b / tot_a:>6.2f}x" if tot_a > 0 else f"{'-':>7}"))
    return "\n".join(lines)


def critical_path(spans: list[dict]) -> list[dict]:
    roots = [s for s in spans if s["parent"] is None]
    if not roots:
        return []
    path = []
    node = max(roots, key=lambda s: s["dur_ms"])
    while node is not None:
        path.append(node)
        node = (max(node["children"], key=lambda s: s["dur_ms"])
                if node["children"] else None)
    return path


def format_critical_path(path: list[dict]) -> str:
    lines = ["critical path (longest child at each level):"]
    for depth, s in enumerate(path):
        lines.append(
            f"{'  ' * (depth + 1)}{s['name']}  "
            f"{s['dur_ms']:.2f} ms (self {s['self_ms']:.2f} ms, "
            f"device {s['device_ms']:.2f} ms)")
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m peasoup_tpu.tools.trace_report",
        description="top-N self-time table + critical path of a "
                    "peasoup-tpu span trace (Chrome trace-event JSON)",
    )
    p.add_argument("trace", nargs="?", default=None,
                   help="trace JSON (--trace_json output)")
    p.add_argument("--top", type=int, default=15,
                   help="rows in the self-time table (default 15)")
    p.add_argument("--require", nargs="+", default=None, metavar="NAME",
                   help="exit 1 unless every named span is present "
                        "(smoke-test gate)")
    p.add_argument("--compare", nargs=2, default=None,
                   metavar=("A.json", "B.json"),
                   help="print the per-stage self-time delta table "
                        "between two traces instead of summarising one")
    args = p.parse_args(argv)

    if args.compare:
        path_a, path_b = args.compare
        try:
            spans_a = rebuild_spans(load_events(path_a))
            spans_b = rebuild_spans(load_events(path_b))
        except (OSError, ValueError, KeyError) as exc:
            print(f"error: {exc}", file=sys.stderr)
            return 2
        print(compare_table(
            spans_a, spans_b,
            label_a=path_a, label_b=path_b, top=args.top))
        return 0
    if args.trace is None:
        p.error("a trace file (or --compare A.json B.json) is required")

    try:
        events = load_events(args.trace)
        spans = rebuild_spans(events)
    except (OSError, ValueError, KeyError) as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 2
    if not spans:
        print("empty trace: no B/E span events", file=sys.stderr)
        return 2
    pids = sorted({s["pid"] for s in spans})
    print(f"{len(spans)} spans over {len(pids)} process(es) "
          f"{pids}")
    print()
    print(self_time_table(spans, args.top))
    print()
    print(format_critical_path(critical_path(spans)))
    if args.require:
        names = {s["name"] for s in spans}
        missing = [n for n in args.require if n not in names]
        if missing:
            print(f"\nMISSING required span(s): {', '.join(missing)}",
                  file=sys.stderr)
            return 1
        print(f"\nall {len(args.require)} required spans present")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
