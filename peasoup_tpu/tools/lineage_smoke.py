"""Candidate-provenance smoke test (``make lineage-smoke``).

Phase 1 — conservation: drain one synthetic pulsar observation with
the lineage ledger on and prove the selection funnel conserves
EXACTLY — every decoded candidate id reaches exactly one terminal
state (``decoded == absorbed + cut + emitted``, the
:func:`peasoup_tpu.obs.lineage.check_conservation` proof), the drain
summary exports the same funnel, and the writer's self-measured
overhead stays below 1% of the drain wall-clock.

Phase 2 — the ``why`` verb: starting from ONLY the strongest store
record (the golden injected-pulse candidate), ``why <candidate-id>``
must reconstruct the full decision chain — decoded, annotations,
``emitted`` terminal, the ``stored`` mark — and report the absorbed
children with their rules and margins.

Phase 3 — bit-identical output: draining the same observation with
``--no-lineage`` must produce candidates whose physics fields match
the lineage-on drain byte for byte (provenance is observation, never
behaviour), and must leave no ``lineage.jsonl`` behind.

Phase 4 — distill collapse: three baseline drains build funnel-rate
history in a scratch serve ledger; a fourth drain with a
deliberately widened harmonic/frequency tolerance (``freq_tol``)
must shift the funnel enough that the ``distill_collapse`` health
rule leaves ``ok`` and :func:`peasoup_tpu.obs.baseline.funnel_anomalies`
emits a typed anomaly record.

Exit status 0 only if every assertion holds — CI-gateable like the
other smokes.
"""

from __future__ import annotations

import argparse
import io
import json
import os
import shutil
import sys
import time
import warnings
from contextlib import redirect_stdout


def _check(ok: bool, what: str, failures: list[str]) -> None:
    print(("PASS " if ok else "FAIL ") + what)
    if not ok:
        failures.append(what)


def _drain(spool_dir: str, fil: str, overrides: dict, history: str,
           lineage: bool = True) -> tuple:
    """Submit ``fil`` into a spool and drain it with one worker;
    returns (spool, drain summary, wall seconds)."""
    from peasoup_tpu.obs.metrics import REGISTRY
    from peasoup_tpu.serve import BackoffPolicy, JobSpool, SurveyWorker

    REGISTRY.reset()
    spool = JobSpool(spool_dir)
    spool.submit(fil, overrides)
    worker = SurveyWorker(
        spool, single_device=True,
        backoff=BackoffPolicy(max_attempts=2, base_s=0.0),
        history_path=history, sleeper=lambda s: None,
        lineage=lineage,
    )
    t0 = time.perf_counter()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        summary = worker.drain()
    return spool, summary, time.perf_counter() - t0


def _physics(rec: dict) -> tuple:
    """A store record's candidate physics — everything that must be
    invariant under the lineage flag (ids/provenance excluded: they
    embed the per-drain job id by design)."""
    return (rec["dm"], rec["acc"], rec["jerk"], rec["freq"],
            rec["snr"], rec["folded_snr"], rec["nh"])


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="peasoup-tpu-lineage-smoke",
        description="Peasoup-TPU - candidate provenance smoke test",
    )
    p.add_argument("--dir", default="/tmp/peasoup-lineage-smoke",
                   help="scratch directory (wiped)")
    args = p.parse_args(argv)

    shutil.rmtree(args.dir, ignore_errors=True)
    os.makedirs(args.dir)
    # scratch ledger: the widened-tolerance drain writes distorted
    # funnel records that must never pollute the repo baseline
    history = os.path.join(args.dir, "history.jsonl")

    from peasoup_tpu.obs import lineage
    from peasoup_tpu.obs.injection import smoke_observation
    from peasoup_tpu.serve.store import CandidateStore

    fil = os.path.join(args.dir, "obs.fil")
    smoke_observation(fil, nsamps=4096, nchans=16, seed=0)
    overrides = {"dm_end": 20.0, "min_snr": 6.0, "npdmp": 2,
                 "limit": 10}

    failures: list[str] = []

    # ---- phase 1: exact conservation on a real drain -----------------
    spool_dir = os.path.join(args.dir, "jobs")
    spool, summary, wall = _drain(spool_dir, fil, overrides, history)
    done = spool.jobs("done")
    _check(len(done) == 1, "drain finished the job", failures)
    runs = [j.job_id for j in done]

    ledger_path = os.path.join(spool_dir, "lineage.jsonl")
    marks = lineage.read_lineage(ledger_path)
    _check(os.path.exists(ledger_path) and len(marks) > 0,
           f"lineage ledger written ({len(marks)} marks)", failures)

    problems = lineage.check_conservation(marks, runs=runs)
    _check(problems == [],
           "conservation: every decoded id reaches exactly one "
           "terminal state" + (f" ({problems[:3]})" if problems else ""),
           failures)
    fn = lineage.funnel(marks, runs=runs)
    _check(fn["decoded"] > 0 and fn["decoded"]
           == fn["absorbed"] + fn["cut"] + fn["emitted"],
           f"funnel conserves exactly: {fn['decoded']} decoded == "
           f"{fn['absorbed']} absorbed + {fn['cut']} cut + "
           f"{fn['emitted']} emitted", failures)

    lg = summary.get("lineage", {})
    _check(lg.get("decoded") == fn["decoded"]
           and lg.get("emitted") == fn["emitted"],
           "drain summary exports the same funnel", failures)
    overhead_s = float(lg.get("overhead_s", float("inf")))
    _check(overhead_s < 0.01 * wall,
           f"lineage overhead {overhead_s:.4f}s < 1% of "
           f"{wall:.2f}s drain", failures)

    # ---- phase 2: `why` reconstructs the chain from the store --------
    store = CandidateStore(os.path.join(spool_dir, "candidates.jsonl"))
    recs = store.records()
    _check(bool(recs) and all(r.get("cand_id") for r in recs),
           f"store records carry candidate ids ({len(recs)})",
           failures)
    _check(bool(recs) and all(
        (r.get("prov") or {}).get("run") for r in recs),
        "store records carry a provenance block", failures)

    why_ok = chain = None
    if recs:
        golden = max(recs, key=lambda r: r.get("snr", 0.0))
        from peasoup_tpu.serve import cli as serve_cli

        why_json = os.path.join(args.dir, "why.json")
        buf = io.StringIO()
        with redirect_stdout(buf):
            rc = serve_cli.main(["--spool", spool_dir, "why",
                                 golden["cand_id"], "--json", why_json])
        chain = json.load(open(why_json))["chain"] if rc == 0 else None
        why_ok = (rc == 0 and chain is not None and chain["decoded"]
                  and (chain["terminal"] or {}).get("kind") == "emitted"
                  and any(m.get("kind") == "stored"
                          for m in chain["annotations"]))
        print(buf.getvalue(), end="")
    _check(bool(why_ok),
           "`why` reconstructs decoded -> emitted -> stored from the "
           "store record alone", failures)
    if chain is not None and chain["children"]:
        kid = chain["children"][0]
        _check((kid["terminal"] or {}).get("kind") == "absorbed"
               and (kid["terminal"] or {}).get("rule") is not None,
               f"absorbed child carries its rule "
               f"({(kid['terminal'] or {}).get('rule')})", failures)

    # ---- phase 3: bit-identical candidates with lineage off ----------
    spool_off_dir = os.path.join(args.dir, "jobs-off")
    _, summary_off, _ = _drain(spool_off_dir, fil, overrides, history,
                               lineage=False)
    off_recs = CandidateStore(
        os.path.join(spool_off_dir, "candidates.jsonl")).records()
    same = (sorted(map(_physics, recs))
            == sorted(map(_physics, off_recs)))
    _check(same and len(off_recs) == len(recs),
           f"--no-lineage candidates bit-identical "
           f"({len(off_recs)} == {len(recs)})", failures)
    _check(not os.path.exists(
        os.path.join(spool_off_dir, "lineage.jsonl")),
        "--no-lineage leaves no ledger behind", failures)
    _check("lineage" in summary and "decoded" not in
           summary_off.get("lineage", {"decoded": None}),
           "drain summaries reflect the lineage flag", failures)

    # ---- phase 4: widened tolerance trips distill_collapse -----------
    # a noise-only observation (no injected train) keeps the BASELINE
    # absorption moderate — the pulse train's harmonic comb would sit
    # near-fully absorbed already, leaving no headroom for the widened
    # tolerance to depart from
    noise_fil = os.path.join(args.dir, "noise.fil")
    from peasoup_tpu.obs.injection import synthesize

    synthesize(noise_fil, period=16.0 * 0.000256, duty=0.05, amp=0.0,
               noise_max=32, nsamps=4096, nchans=16, tsamp=0.000256,
               seed=7)
    noise_ov = {"dm_end": 5.0, "min_snr": 3.5, "npdmp": 0,
                "limit": 10}
    # scratch ledger for this phase only: phases 1/3 appended records
    # with a different observation's funnel shape
    collapse_history = os.path.join(args.dir, "history-collapse.jsonl")
    for i in range(3):  # three identical baseline drains
        _drain(os.path.join(args.dir, f"jobs-base{i}"), noise_fil,
               noise_ov, collapse_history)
    wide = dict(noise_ov)
    wide["freq_tol"] = 0.5  # absurd harmonic/frequency tolerance:
    # every candidate within a factor-~2 frequency band matches, so
    # the distillers absorb nearly the whole decoded population
    _drain(os.path.join(args.dir, "jobs-wide"), noise_fil, wide,
           collapse_history)

    from peasoup_tpu.obs.baseline import funnel_anomalies
    from peasoup_tpu.obs.history import load_history
    from peasoup_tpu.serve.health import (
        HealthContext,
        rule_distill_collapse,
    )

    serve_recs = load_history(collapse_history, kinds=("serve",))
    _check(len(serve_recs) == 4 and all(
        r.get("metrics", {}).get("lineage_decoded", 0) > 0
        for r in serve_recs),
        f"{len(serve_recs)} serve records carry funnel metrics",
        failures)
    ctx = HealthContext(now=time.time(), samples=[], recent=[],
                        latest={}, queue={}, running=[],
                        ledger=serve_recs)
    findings = rule_distill_collapse(ctx)
    verdict = findings[0].severity if findings else "?"
    base_abs = serve_recs[0]["metrics"].get("lineage_absorbed_frac")
    head_abs = serve_recs[-1]["metrics"].get("lineage_absorbed_frac")
    _check(verdict in ("warn", "crit"),
           f"distill_collapse trips on the widened tolerance "
           f"(severity={verdict}, absorbed {base_abs} -> {head_abs})",
           failures)
    anoms = funnel_anomalies(serve_recs)
    _check(bool(anoms),
           f"funnel baseline emits {len(anoms)} anomaly record(s) "
           f"({[a['metric'] for a in anoms]})", failures)

    if failures:
        print(f"\nlineage-smoke: {len(failures)} check(s) FAILED",
              file=sys.stderr)
        return 1
    print("\nlineage-smoke: all checks passed")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
