"""Open-loop fleet load generator + saturation sweep
(``make loadgen-smoke``).

The missing half of the load observatory (ISSUE 12, ROADMAP item
4(a)): the fleet has leases, batching, telemetry, health rules and a
duty-cycle ledger, but nothing ever *measured* it under sustained
traffic.  This tool submits synthetic filterbank streams at
configurable **offered** arrival rates — open-loop, i.e. the submit
schedule never waits for completions, exactly the regime where queues
actually blow up (Dean & Barroso, "The Tail at Scale", CACM 2013) —
against real ``fleet-worker`` subprocesses sharing one spool, and
reports, per rate point:

* achieved throughput vs offered rate (their ratio detects the
  saturation knee — the highest offered rate the fleet still served
  at >= :data:`KNEE_EFFICIENCY` efficiency);
* p50/p95/p99 end-to-end sojourn (submit -> done, from each job's
  lifecycle timeline — obs/timeline.py), decomposed by timeline phase
  (per-phase mean/p95/share of sojourn);
* quarantined (poison) jobs reported SEPARATELY so a bad input's
  fast-fail can never flatter the latency percentiles;
* the queue-depth trajectory and device duty cycle from the workers'
  telemetry shards;
* the cost of the timeline plane itself (submitter-side
  ``obs/timeline.overhead()`` + the workers' ``timeline_mark`` timer
  deltas), which ``--smoke`` gates under 1% of drain wall-clock — the
  telemetry-sampler precedent.

Results land in three places sharing one schema: a
``saturation_report.json`` (the full per-point documents), one
``kind:"loadgen"`` record in the bench history ledger
(obs/history.py — the ``loadgen_saturation`` health rule reads the
knee from there), and ``tools/perf_report.py``'s rate x percentile
table.

Job mixes are seeded and deterministic (same ``--seed`` -> identical
arrival schedule, geometry buckets, priorities and poison picks), so
a sweep is reproducible and diffable across PRs.  ``--inprocess``
swaps the real search for a constant-service-time stub worker in this
process — seconds instead of minutes, same queueing physics — which
is what the saturation tests use.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import threading
import time

import numpy as np

from .fleet_smoke import FAST, _check, _write_synthetic

#: a rate point still "keeps up" while achieved/offered >= this;
#: the knee is the last point that does
KNEE_EFFICIENCY = 0.85

#: telemetry cadence for loadgen workers (fast enough for queue-depth
#: trajectories over bursts lasting seconds)
TELEMETRY_INTERVAL_S = 0.2

REPORT_BASENAME = "saturation_report.json"


# --------------------------------------------------------------------------
# deterministic mix + schedule
# --------------------------------------------------------------------------

def arrival_offsets(rate_per_s: float, n: int, rng) -> list[float]:
    """Open-loop Poisson arrivals: cumulative offsets (seconds from
    burst start) of ``n`` submissions at ``rate_per_s`` — seeded
    exponential inter-arrival gaps, so the schedule is deterministic
    per rng."""
    if rate_per_s <= 0:
        return [0.0] * n
    gaps = rng.exponential(1.0 / float(rate_per_s), size=n)
    return [round(float(t), 6) for t in np.cumsum(gaps)]


def job_mix(n: int, rng, *, buckets=(4096,), priorities=(0,),
            poison_fraction: float = 0.0,
            canary_fraction: float = 0.0) -> list[dict]:
    """``n`` deterministic job specs: geometry bucket (sample count),
    priority tier, per-job data seed, which jobs are poisoned
    (truncated mid-data -> typed quarantine at the worker), and which
    are canaries (known-answer injections, ISSUE 14 — disjoint from
    the poison set: a truncated canary could never be recovered)."""
    n = int(n)
    n_poison = min(n, int(round(float(poison_fraction) * n)))
    poison = (set(rng.choice(n, size=n_poison, replace=False).tolist())
              if n_poison else set())
    clean = np.array([i for i in range(n) if i not in poison])
    n_canary = min(len(clean), int(round(float(canary_fraction) * n)))
    canary = (set(rng.choice(clean, size=n_canary,
                             replace=False).tolist())
              if n_canary else set())
    return [{
        "i": i,
        "nsamps": int(buckets[int(rng.integers(0, len(buckets)))]),
        "priority": int(priorities[int(rng.integers(0,
                                                    len(priorities)))]),
        "poison": i in poison,
        "canary": i in canary,
        "seed": int(rng.integers(0, 2**31 - 1)),
    } for i in range(n)]


def write_observations(specs: list[dict], obs_dir: str) -> list[dict]:
    """Materialise each spec as a real filterbank (poisoned specs are
    truncated 1 KiB short of their header's promise); sets
    ``spec["path"]``."""
    from ..obs.injection import save_manifest, smoke_observation

    os.makedirs(obs_dir, exist_ok=True)
    for spec in specs:
        path = os.path.join(obs_dir, f"obs-{spec['i']:04d}.fil")
        if spec.get("canary"):
            # canary inputs ARE injections: keep the manifest so the
            # worker can match candidates against the known answer
            manifest = smoke_observation(
                path, nsamps=spec["nsamps"],
                seed=spec["seed"] % (2**16))
            spec["canary_manifest"] = manifest
            spec["manifest_path"] = save_manifest(
                manifest, path + ".manifest.json")
            spec["path"] = path
        else:
            spec["path"] = _write_synthetic(
                path, nsamps=spec["nsamps"],
                seed=spec["seed"] % (2**16),
                truncate_bytes=1024 if spec["poison"] else 0)
    return specs


def submit_burst(spool, specs: list[dict], offsets: list[float],
                 overrides: dict | None = None, *, sleeper=None,
                 clock=time.monotonic) -> list:
    """Submit every spec on its open-loop schedule (sleeping out each
    gap; a slow submitter shrinks gaps rather than re-planning — the
    offered rate is a CEILING the report compares against what was
    actually achieved)."""
    from ..serve.retry import pause

    t0 = clock()
    recs = []
    for spec, off in zip(specs, offsets):
        delay = t0 + off - clock()
        if delay > 0:
            pause(delay, sleeper)
        ov = dict(overrides or {})
        if spec.get("canary_manifest"):
            if spec.get("manifest_path"):
                ov["injection_manifest"] = spec["manifest_path"]
        recs.append(spool.submit(spec["path"], ov,
                                 priority=spec["priority"],
                                 canary=spec.get("canary_manifest")))
    return recs


# --------------------------------------------------------------------------
# per-rate-point measurement
# --------------------------------------------------------------------------

def _point_stats(spool, *, offered_rate: float, n_jobs: int,
                 elapsed_s: float, arrival_span_s: float = 0.0,
                 timed_out: bool = False) -> dict:
    """One rate point's report row: throughput, phase-decomposed
    sojourn percentiles (done jobs ONLY), quarantine reported
    separately, queue trajectory + duty cycle + timeline cost from
    the workers' telemetry shards."""
    from ..obs import timeline
    from ..obs.telemetry import read_samples
    from ..serve.health import percentile

    def _latency(recs):
        sojourns, phase_lists = [], {}
        for rec in recs:
            wd = os.path.join(spool.root, "work", rec.job_id)
            doc = timeline.waterfall(timeline.read_timeline(wd),
                                     job_id=rec.job_id)
            soj = doc["sojourn_s"]
            if soj <= 0:
                soj = max(0.0, rec.finished_utc - rec.submitted_utc)
            sojourns.append(soj)
            for ph, s in doc["phase_s"].items():
                phase_lists.setdefault(ph, []).append(s)
        return sojourns, phase_lists

    done = spool.jobs("done")
    failed = spool.jobs("failed")
    sojourns, phase_lists = _latency(done)
    q_sojourns, _ = _latency(failed)
    total_soj = sum(sojourns)
    phases = {}
    for ph, vals in sorted(phase_lists.items()):
        tot = sum(vals)
        phases[ph] = {
            "mean_s": round(tot / len(vals), 6),
            "p95_s": round(percentile(vals, 0.95), 6),
            "share": round(tot / total_soj, 4) if total_soj > 0
            else 0.0,
        }
    samples = read_samples(os.path.join(spool.root, "fleet"))
    queue_depth = [
        {"ts": round(float(s.get("ts", 0.0)), 3),
         "host": s.get("host", ""),
         "pending": int(s["queue"].get("pending", 0)),
         "running": int(s["queue"].get("running", 0))}
        for s in samples if isinstance(s.get("queue"), dict)
    ]
    device_s = mark_s = 0.0
    marks = 0
    for s in samples:
        for name, delta in s.get("timers", {}).items():
            if not isinstance(delta, dict):
                continue
            if name == "timeline_mark":
                mark_s += float(delta.get("host_s", 0.0))
                marks += int(delta.get("count", 0))
            elif name != "job":  # job would double-count its stages
                device_s += float(delta.get("device_s", 0.0))
    canary_rec = sum(int(s.get("counters", {}).get(
        "canary.recovered", 0)) for s in samples)
    canary_mis = sum(int(s.get("counters", {}).get(
        "canary.missed", 0)) for s in samples)
    achieved = len(done) / elapsed_s if elapsed_s > 0 else 0.0
    # the schedule's EMPIRICAL rate: with small n the sampled
    # exponential gaps can realize a window far from nominal, so knee
    # detection compares achieved throughput against what was actually
    # offered, not what was asked for
    realized = (n_jobs / arrival_span_s if arrival_span_s > 0
                else float(offered_rate))
    return {
        "offered_rate_per_s": round(float(offered_rate), 6),
        "realized_rate_per_s": round(realized, 6),
        "jobs": int(n_jobs),
        "done": len(done),
        "failed": len(failed),
        "elapsed_s": round(elapsed_s, 3),
        "timed_out": bool(timed_out),
        "achieved_per_s": round(achieved, 6),
        "sojourn": {
            "p50_s": round(percentile(sojourns, 0.50), 6),
            "p95_s": round(percentile(sojourns, 0.95), 6),
            "p99_s": round(percentile(sojourns, 0.99), 6),
            "mean_s": round(total_soj / len(sojourns), 6)
            if sojourns else 0.0,
            "n": len(sojourns),
        },
        "phases": phases,
        # poison/quarantined jobs: their (fast) failure latency must
        # never flatter the done-job percentiles above
        "quarantined": {
            "count": len(failed),
            "sojourn_p50_s": round(percentile(q_sojourns, 0.50), 6),
            "sojourn_p95_s": round(percentile(q_sojourns, 0.95), 6),
        },
        "canary": {"recovered": canary_rec, "missed": canary_mis},
        "queue_depth": queue_depth,
        "device_duty_cycle": round(device_s / elapsed_s, 6)
        if elapsed_s > 0 else 0.0,
        "timeline": {"worker_marks": marks,
                     "worker_overhead_s": round(mark_s, 6)},
    }


def _worker_cmd(spool_dir: str, host_id: int, host_count: int,
                history: str) -> list[str]:
    """A POLLING fleet worker (no ``--drain``): it claims whatever
    arrives until the sweep terminates it — the service side of the
    open loop."""
    return [
        sys.executable, "-m", "peasoup_tpu.serve",
        "--spool", spool_dir, "fleet-worker",
        "--host-id", str(host_id), "--host-count", str(host_count),
        "--single_device", "--max-attempts", "2",
        "--backoff-base", "0", "--history", history,
        "--lease-ttl", "60", "--heartbeat", "0.5",
        "--poll", "0.1",
        "--telemetry-interval", str(TELEMETRY_INTERVAL_S),
    ]


def run_rate_point(point_dir: str, rate: float, specs: list[dict], *,
                   workers: int = 2, overrides: dict | None = None,
                   history: str, seed: int = 0,
                   timeout_s: float = 900.0) -> dict:
    """One offered-rate point against REAL fleet-worker subprocesses:
    fresh spool, ``workers`` polling hosts, the burst submitted on its
    open-loop schedule, then wait for the queue to drain (bounded by
    ``timeout_s`` — a saturated point that can't drain still reports,
    flagged ``timed_out``)."""
    from ..serve.queue import JobSpool
    from ..serve.retry import pause

    os.makedirs(point_dir, exist_ok=True)
    spool = JobSpool(os.path.join(point_dir, "jobs"))
    rng = np.random.default_rng(seed)
    offsets = arrival_offsets(rate, len(specs), rng)
    env = dict(os.environ, JAX_PLATFORMS=os.environ.get(
        "JAX_PLATFORMS", "cpu"))
    logs, procs = [], []
    for h in range(workers):
        log = open(os.path.join(point_dir, f"worker-{h}.log"), "w")
        logs.append(log)
        procs.append(subprocess.Popen(
            _worker_cmd(spool.root, h, workers, history), env=env,
            stdout=log, stderr=subprocess.STDOUT, text=True))
    n = len(specs)
    t0 = time.monotonic()
    timed_out = False
    try:
        submit_burst(spool, specs, offsets, dict(FAST,
                                                 **(overrides or {})))
        deadline = time.monotonic() + float(timeout_s)
        while True:
            c = spool.counts()
            if (c["pending"] == 0 and c["running"] == 0
                    and c["done"] + c["failed"] >= n):
                break
            if time.monotonic() > deadline:
                timed_out = True
                break
            pause(0.1)
    finally:
        elapsed = time.monotonic() - t0
        for proc in procs:
            proc.terminate()
        for proc in procs:
            try:
                proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=15)
        for log in logs:
            log.close()
    return _point_stats(spool, offered_rate=rate, n_jobs=n,
                        elapsed_s=elapsed,
                        arrival_span_s=offsets[-1] if offsets else 0.0,
                        timed_out=timed_out)


def run_rate_point_inprocess(point_dir: str, rate: float, n: int, *,
                             service_s: float = 0.03, seed: int = 0,
                             timeout_s: float = 120.0) -> dict:
    """One rate point with a constant-service-time stub worker in THIS
    process — the real spool/claim/timeline machinery with the search
    swapped out, so saturation tests run in seconds and the knee is
    analytically checkable (capacity = 1/service_s)."""
    from ..serve.queue import JobSpool
    from ..serve.retry import pause
    from ..serve.worker import SurveyWorker

    os.makedirs(point_dir, exist_ok=True)
    spool = JobSpool(os.path.join(point_dir, "jobs"))
    rng = np.random.default_rng(seed)
    specs = job_mix(n, rng)
    for spec in specs:
        spec["path"] = os.path.join(point_dir, f"obs-{spec['i']}.fil")
    offsets = arrival_offsets(rate, n, rng)

    def _serve(job):
        pause(service_s)
        return {"candidates": 0}

    worker = SurveyWorker(
        spool, prefetch=False, run_job_fn=_serve,
        history_path=os.path.join(point_dir, "serve-history.jsonl"),
        telemetry_interval_s=TELEMETRY_INTERVAL_S)
    t0 = time.monotonic()
    thread = threading.Thread(
        target=lambda: worker.drain(max_jobs=n, wait=True,
                                    poll_s=0.02),
        daemon=True, name="loadgen-worker")
    thread.start()
    try:
        submit_burst(spool, specs, offsets)
        thread.join(timeout=float(timeout_s))
    finally:
        elapsed = time.monotonic() - t0
    return _point_stats(spool, offered_rate=rate, n_jobs=n,
                        elapsed_s=elapsed,
                        arrival_span_s=offsets[-1] if offsets else 0.0,
                        timed_out=thread.is_alive())


# --------------------------------------------------------------------------
# sweep + knee + report
# --------------------------------------------------------------------------

def detect_knee(points: list[dict],
                efficiency: float = KNEE_EFFICIENCY) -> dict:
    """The saturation knee over a sweep: the LAST offered rate (in
    rate order) the fleet still served at >= ``efficiency`` of what
    was offered (the REALIZED schedule rate — small bursts can sample
    a window far from nominal); beyond it the queue grows without
    bound.  If even the first point is saturated, the knee is that
    point's ACHIEVED throughput — the best available capacity
    estimate."""
    pts = sorted(points, key=lambda p: p["offered_rate_per_s"])
    keeping_up = [p for p in pts
                  if p["achieved_per_s"]
                  >= efficiency * p.get("realized_rate_per_s",
                                        p["offered_rate_per_s"])
                  and not p.get("timed_out")]
    knee_pt = keeping_up[-1] if keeping_up else pts[0]
    return {
        "rate_per_s": knee_pt["offered_rate_per_s"],
        "throughput_per_s": knee_pt["achieved_per_s"],
        "saturated": len(keeping_up) < len(pts),
        "efficiency_threshold": float(efficiency),
    }


def write_report(path: str, doc: dict) -> str:
    tmp = path + f".tmp{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f, sort_keys=True, indent=1)
    os.replace(tmp, path)
    return path


def append_loadgen_record(doc: dict, history: str | None) -> dict:
    """One ``kind:"loadgen"`` ledger record per sweep: the knee is the
    headline (the ``loadgen_saturation`` health rule compares live
    arrival rates against it), plus slim per-rate rows for
    perf_report's rate x percentile table."""
    from ..obs.history import append_history, make_history_record

    points = doc["points"]
    knee = doc["knee"]
    rec = make_history_record(
        "loadgen",
        {
            "rates_swept": len(points),
            "jobs_total": sum(p["jobs"] for p in points),
            "jobs_done": sum(p["done"] for p in points),
            "jobs_failed": sum(p["failed"] for p in points),
            "knee_rate_per_s": knee["rate_per_s"],
            "knee_throughput_per_s": knee["throughput_per_s"],
            "max_achieved_per_s": max(
                (p["achieved_per_s"] for p in points), default=0.0),
            "timeline_overhead_frac": doc["timeline"]["overhead_frac"],
        },
        config=doc["config"],
        extra={"rates": [{
            "rate": p["offered_rate_per_s"],
            "achieved": p["achieved_per_s"],
            "p50_s": p["sojourn"]["p50_s"],
            "p95_s": p["sojourn"]["p95_s"],
            "p99_s": p["sojourn"]["p99_s"],
            "duty": p["device_duty_cycle"],
            "quarantined": p["quarantined"]["count"],
        } for p in points]},
    )
    append_history(rec, history)
    return rec


def sweep(dirpath: str, rates: list[float], jobs: int, *,
          workers: int = 2, seed: int = 0,
          poison_fractions=None, canary_fraction: float = 0.0,
          buckets=(4096,), priorities=(0,),
          overrides: dict | None = None, history: str | None = None,
          timeout_s: float = 900.0, inprocess: bool = False,
          service_s: float = 0.03, verbose: bool = True) -> dict:
    """Run every rate point (fresh spool each), detect the knee, write
    ``saturation_report.json`` + the ledger record; returns the full
    report document."""
    from ..obs import timeline

    os.makedirs(dirpath, exist_ok=True)
    if poison_fractions is None:
        poison_fractions = [0.0] * len(rates)
    elif not isinstance(poison_fractions, (list, tuple)):
        poison_fractions = [float(poison_fractions)] * len(rates)
    say = print if verbose else (lambda *a, **kw: None)
    ov0 = timeline.overhead()
    points = []
    for i, rate in enumerate(rates):
        point_dir = os.path.join(dirpath, f"rate-{i}")
        say(f"loadgen: rate point {i} -- {rate:g} jobs/s x {jobs} "
            f"job(s)" + (" [inprocess]" if inprocess else
                         f" against {workers} worker(s)"))
        if inprocess:
            point = run_rate_point_inprocess(
                point_dir, rate, jobs, service_s=service_s,
                seed=seed + i, timeout_s=timeout_s)
        else:
            rng = np.random.default_rng(seed + i)
            specs = write_observations(
                job_mix(jobs, rng, buckets=buckets,
                        priorities=priorities,
                        poison_fraction=poison_fractions[i],
                        canary_fraction=canary_fraction),
                os.path.join(point_dir, "obs"))
            point = run_rate_point(
                point_dir, rate, specs, workers=workers,
                overrides=overrides, history=history or os.path.join(
                    dirpath, "serve-history.jsonl"),
                seed=seed + i, timeout_s=timeout_s)
        say(f"loadgen: rate {rate:g}/s -> achieved "
            f"{point['achieved_per_s']:g}/s, sojourn p50/p95/p99 = "
            f"{point['sojourn']['p50_s']:g}/"
            f"{point['sojourn']['p95_s']:g}/"
            f"{point['sojourn']['p99_s']:g}s "
            f"({point['done']} done, {point['failed']} failed)")
        points.append(point)
    ov1 = timeline.overhead()
    wall = sum(p["elapsed_s"] for p in points)
    overhead_s = (ov1["seconds"] - ov0["seconds"]) + sum(
        p["timeline"]["worker_overhead_s"] for p in points)
    doc = {
        "v": 1,
        "seed": int(seed),
        "points": points,
        "knee": detect_knee(points),
        "timeline": {
            "submitter_marks": ov1["marks"] - ov0["marks"],
            "overhead_s": round(overhead_s, 6),
            "overhead_frac": round(overhead_s / wall, 6)
            if wall > 0 else 0.0,
        },
        "config": {
            "jobs_per_rate": int(jobs),
            "workers": int(workers),
            "inprocess": bool(inprocess),
            "buckets": list(buckets),
            "priorities": list(priorities),
            "poison_fractions": [float(f) for f in poison_fractions],
            "canary_fraction": float(canary_fraction),
            **({"service_s": service_s} if inprocess else {}),
        },
    }
    doc["report_path"] = write_report(
        os.path.join(dirpath, REPORT_BASENAME), doc)
    doc["ledger_record"] = append_loadgen_record(doc, history)
    return doc


# --------------------------------------------------------------------------
# smoke (make loadgen-smoke)
# --------------------------------------------------------------------------

def run_smoke(dirpath: str) -> int:
    """Two-worker, two-rate saturation smoke with one poison job —
    the ISSUE 12 acceptance gate.  Real ``fleet-worker`` subprocesses,
    real searches, real timelines; every assertion prints PASS/FAIL
    and the exit status is 0 only if all hold."""
    shutil.rmtree(dirpath, ignore_errors=True)
    os.makedirs(dirpath)
    history = os.path.join(dirpath, "history.jsonl")
    jobs = 15
    failures: list[str] = []

    doc = sweep(dirpath, rates=[1.0, 8.0], jobs=jobs, workers=2,
                seed=7, poison_fractions=[1.0 / jobs, 0.0],
                history=history, timeout_s=900.0)
    points = doc["points"]

    _check(os.path.exists(doc["report_path"]) and len(points) >= 2,
           "saturation_report.json with >= 2 rate points", failures)
    _check(all(not p["timed_out"] for p in points),
           "both rate points drained inside the budget", failures)
    _check(all(p["sojourn"]["n"] > 0
               and p["sojourn"]["p50_s"] <= p["sojourn"]["p95_s"]
               <= p["sojourn"]["p99_s"] for p in points),
           "phase-decomposed p50<=p95<=p99 sojourn at every point",
           failures)
    _check(all(p["phases"] for p in points),
           "every point decomposes sojourn by timeline phase",
           failures)
    _check(points[0]["quarantined"]["count"] == 1
           and points[1]["quarantined"]["count"] == 0
           and points[0]["done"] == jobs - 1
           and points[0]["sojourn"]["n"] == jobs - 1,
           "1 poison job quarantined and excluded from the "
           "percentile pool (reported separately)", failures)
    knee = doc["knee"]
    _check(knee["throughput_per_s"] > 0,
           f"saturation knee detected ({knee['rate_per_s']:g}/s "
           f"offered -> {knee['throughput_per_s']:g}/s achieved)",
           failures)

    from peasoup_tpu.obs.history import load_history

    lrecs = load_history(history, kinds=["loadgen"])
    _check(len(lrecs) == 1 and lrecs[0]["metrics"][
        "knee_throughput_per_s"] == knee["throughput_per_s"],
        "kind:\"loadgen\" ledger record carries the knee", failures)

    # -- the timeline verb: waterfall whose phase sum == sojourn -------
    from peasoup_tpu.serve.queue import JobSpool

    spool = JobSpool(os.path.join(dirpath, "rate-0", "jobs"))
    done = spool.jobs("done")
    job_id = done[0].job_id if done else ""
    wf_json = os.path.join(dirpath, "waterfall.json")
    trace_json = os.path.join(dirpath, "trace.json")
    tl = subprocess.run(
        [sys.executable, "-m", "peasoup_tpu.serve", "--spool",
         spool.root, "timeline", job_id, "--json", wf_json,
         "--trace_json", trace_json],
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True, text=True, timeout=120)
    print(tl.stdout.strip())
    _check(tl.returncode == 0 and "sojourn" in tl.stdout
           and "phase totals:" in tl.stdout,
           "timeline verb renders the waterfall", failures)
    wf = json.load(open(wf_json)) if os.path.exists(wf_json) else {}
    phase_sum = sum(wf.get("phase_s", {}).values())
    sojourn = wf.get("sojourn_s", 0.0)
    _check(sojourn > 0
           and abs(phase_sum - sojourn) <= 0.01 * sojourn + 1e-6,
           f"waterfall phase sum ({phase_sum:.4f}s) ~= sojourn "
           f"({sojourn:.4f}s)", failures)
    _check(any(m.get("phase") in ("dispatch", "fold", "store-ingest")
               for m in wf.get("marks", [])),
           "worker span phases present in the merged timeline",
           failures)
    trace = (json.load(open(trace_json))
             if os.path.exists(trace_json) else {})
    _check(any(e.get("ph") == "X" and e.get("tid") == 1
               for e in trace.get("traceEvents", [])),
           "chrome export merges the worker's device spans", failures)

    # -- the plane's own cost: <1% of drain wall-clock -----------------
    frac = doc["timeline"]["overhead_frac"]
    _check(0.0 <= frac < 0.01,
           f"timeline overhead {100 * frac:.3f}% of drain wall-clock "
           f"(< 1%)", failures)

    if failures:
        print(f"\nloadgen-smoke: {len(failures)} check(s) FAILED",
              file=sys.stderr)
        return 1
    print("\nloadgen-smoke: all checks passed")
    return 0


# --------------------------------------------------------------------------
# read-heavy science-query mix (ISSUE 20)
# --------------------------------------------------------------------------

#: the science-query op mix: surveys re-read far more than they
#: ingest, and most reads are targeted frequency joins
QUERY_MIX = (("query", 0.70), ("coincidence", 0.20), ("why", 0.10))


def query_mix(n: int, rng, *, freqs: list[float],
              cand_ids: list[str]) -> list[dict]:
    """Seeded read-heavy request mix over a live store: ~70%
    harmonic ``query``, ~20% ``coincidence``, ~10% ``why`` joins —
    same seed, identical request stream.  ``freqs``/``cand_ids`` are
    sampled from the store so every request can actually hit."""
    reqs: list[dict] = []
    for _ in range(max(0, int(n))):
        r = rng.random()
        if r < QUERY_MIX[0][1] or not cand_ids:
            f = rng.choice(freqs) if freqs else 10.0
            reqs.append({"op": "query",
                         "freq": f * (1.0 + rng.uniform(-5e-5, 5e-5)),
                         "freq_tol": 1e-4,
                         "max_harm": rng.choice((1, 2, 4))})
        elif r < QUERY_MIX[0][1] + QUERY_MIX[1][1]:
            reqs.append({"op": "coincidence", "freq_tol": 1e-4,
                         "min_sources": 2})
        else:
            reqs.append({"op": "why",
                         "cand_id": rng.choice(cand_ids)[:12]})
    return reqs


def run_query_mix(store_root: str, n: int, *, seed: int = 0,
                  history: str | None = None) -> dict:
    """Drive ``n`` seeded science-query requests through the query
    service in-process and report per-op latency percentiles.  Every
    request also appends its own ``kind:"query"`` ledger record (the
    ``query_latency`` SLO rule's input); the summary here is the
    sweep-level view."""
    import random

    from ..serve.health import percentile
    from ..serve.query_service import QueryService
    from ..serve.store import ShardedCandidateStore

    rng = random.Random(int(seed))
    store = ShardedCandidateStore(store_root)
    freqs: list[float] = []
    cand_ids: list[str] = []
    for rec in store.iter_records():
        freqs.append(float(rec["freq"]))
        if rec.get("cand_id"):
            cand_ids.append(str(rec["cand_id"]))
        if len(freqs) >= 512:
            break
    svc = QueryService(store_root, ledger_path=history)
    lat_by_op: dict[str, list[float]] = {}
    failures = 0
    t0 = time.perf_counter()
    for req in query_mix(n, rng, freqs=freqs, cand_ids=cand_ids):
        res = svc.serve_request(req)
        lat_by_op.setdefault(req["op"], []).append(
            float(res["latency_ms"]))
        if not res.get("ok"):
            failures += 1
    wall_s = time.perf_counter() - t0
    all_lat = sorted(x for v in lat_by_op.values() for x in v)
    doc = {
        "v": 1,
        "store": os.path.abspath(store_root),
        "requests": int(n),
        "failures": failures,
        "wall_s": round(wall_s, 3),
        "query_p50_ms": round(percentile(all_lat, 0.50), 3),
        "query_p95_ms": round(percentile(all_lat, 0.95), 3),
        "per_op": {
            op: {"n": len(v),
                 "p50_ms": round(percentile(v, 0.50), 3),
                 "p95_ms": round(percentile(v, 0.95), 3)}
            for op, v in sorted(lat_by_op.items())
        },
    }
    return doc


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="peasoup-tpu-loadgen",
        description="Peasoup-TPU - open-loop fleet load generator / "
                    "saturation sweep",
    )
    p.add_argument("--dir", default="/tmp/peasoup-loadgen",
                   help="scratch directory (one subdir per rate "
                        "point; --smoke wipes it)")
    p.add_argument("--rates", default="0.5,1,2,4",
                   help="comma-separated offered rates (jobs/s)")
    p.add_argument("--jobs", type=int, default=20,
                   help="jobs per rate point")
    p.add_argument("--workers", type=int, default=2,
                   help="fleet-worker subprocesses per point")
    p.add_argument("--seed", type=int, default=0,
                   help="mix + schedule seed (same seed -> identical "
                        "sweep)")
    p.add_argument("--poison-fraction", type=float, default=0.0,
                   help="fraction of each point's jobs truncated "
                        "mid-data (quarantine path)")
    p.add_argument("--canary-fraction", type=float, default=0.0,
                   help="fraction of each point's jobs carrying a "
                        "known-answer injection manifest (canary "
                        "recovery under load, ISSUE 14)")
    p.add_argument("--buckets", default="4096",
                   help="comma-separated geometry buckets (nsamps)")
    p.add_argument("--priorities", default="0",
                   help="comma-separated priority tiers")
    p.add_argument("--history", default=None,
                   help="bench history ledger for the kind:\"loadgen\" "
                        "record (default: repo "
                        "benchmarks/history.jsonl)")
    p.add_argument("--timeout", type=float, default=900.0,
                   help="per-point drain budget in seconds")
    p.add_argument("--inprocess", action="store_true",
                   help="stub constant-service worker in this process "
                        "(seconds, not minutes; queueing physics "
                        "only)")
    p.add_argument("--service-s", type=float, default=0.03,
                   help="--inprocess stub service time per job")
    p.add_argument("--smoke", action="store_true",
                   help="run the loadgen-smoke acceptance gate "
                        "instead of a custom sweep")
    args = p.parse_args(argv)

    if args.smoke:
        return run_smoke(args.dir)
    rates = [float(r) for r in args.rates.split(",") if r.strip()]
    doc = sweep(
        args.dir, rates, args.jobs, workers=args.workers,
        seed=args.seed, poison_fractions=args.poison_fraction,
        canary_fraction=args.canary_fraction,
        buckets=tuple(int(b) for b in args.buckets.split(",")),
        priorities=tuple(int(x) for x in args.priorities.split(",")),
        history=args.history, timeout_s=args.timeout,
        inprocess=args.inprocess, service_s=args.service_s)
    knee = doc["knee"]
    print(f"knee: {knee['rate_per_s']:g}/s offered -> "
          f"{knee['throughput_per_s']:g}/s achieved"
          + (" (fleet saturates beyond this)" if knee["saturated"]
             else " (never saturated in this sweep)"))
    print(f"wrote {doc['report_path']}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
