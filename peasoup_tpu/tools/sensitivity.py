"""Sensitivity sweep + canary smoke (``make sensitivity-smoke``).

The judging half of the sensitivity observatory (ISSUE 14).
``obs/injection.py`` supplies ground truth — synthetic pulsars with
serialisable manifests and a recovery matcher — and this tool turns it
into numbers an operator can gate on:

* :func:`run_sweep` — a grid of injected SNR x period x accel, each
  cell a real :class:`MeshPulsarSearch` over a fresh injection with the
  per-stage SNR budget probe attached (``injection_manifest`` on the
  search config), reduced to a **recovery fraction**, SNR-in vs
  SNR-out **transfer curves**, and the **minimum detectable SNR** (the
  lowest injected SNR still recovered in at least half its cells).
  Results land in ``sensitivity_report.json`` and ONE
  ``kind:"sensitivity"`` record in the bench history ledger — the
  baseline the ``canary_recovery`` health rule and
  ``tools/perf_report.py`` read.

* :func:`run_lattice_sweep` — the same sweep repeated under each
  forced trial-lattice dtype; each dtype's ``recovery_delta`` (its
  recovery fraction minus f32's) rides the parity verdict into the
  tuner sidecar via ``search/tuning.py:update_lattice``, so ``auto``
  lattice resolution is informed by *sensitivity*, not just speed.

* ``--smoke`` — the CI gate: three injections at descending SNR (the
  faintest deliberately sub-threshold) must come back as two
  recoveries + one reported miss with the per-stage budget table
  rendered; then a real ``worker --drain`` subprocess recovers a
  canary job (``submit --canary`` -> ``health`` ok), a deliberately
  sub-threshold canary drives ``canary_recovery`` to crit (``health``
  exits nonzero), and a clean re-drain returns the fleet to ok.
"""

from __future__ import annotations

import argparse
import itertools
import json
import os
import shutil
import subprocess
import sys
import time

from .fleet_smoke import FAST, _check

REPORT_BASENAME = "sensitivity_report.json"

#: default sweep grid: bright / marginal / sub-threshold injected SNR
#: at the smoke recipe's on-grid period (16 samples -> an exact FFT
#: bin at any power-of-two size)
DEFAULT_SNRS = (40.0, 12.0, 1.5)
DEFAULT_TSAMP = 0.000256
DEFAULT_PERIODS = (16.0 * DEFAULT_TSAMP,)
DEFAULT_ACCELS = (0.0,)

#: a grid row "detects" at an injected SNR when at least this fraction
#: of its period x accel cells recovered
DETECT_FRACTION = 0.5


# --------------------------------------------------------------------------
# one grid cell
# --------------------------------------------------------------------------

def run_cell(path: str, *, snr: float, period: float, accel: float,
             dm: float = 0.0, jerk: float = 0.0, duty: float = 0.05,
             noise_max: int = 32, nsamps: int = 4096, nchans: int = 16,
             tsamp: float = DEFAULT_TSAMP, size: int = 2048,
             seed: int = 0, overrides: dict | None = None) -> dict:
    """Inject one synthetic pulsar, search it, match it back.

    The manifest path rides the search config as
    ``injection_manifest``, so the cell's result carries the per-stage
    SNR budget the drivers' probe attributes (whiten -> Fourier bin ->
    interbin -> harmonic levels -> extracted peak).
    """
    from ..io import read_filterbank
    from ..obs.injection import (
        match_candidates, save_manifest, synthesize,
    )
    from ..parallel.mesh import MeshPulsarSearch
    from ..search.plan import SearchConfig

    manifest = synthesize(
        path, period=period, dm=dm, accel=accel, jerk=jerk, duty=duty,
        snr=snr, noise_max=noise_max, nsamps=nsamps, nchans=nchans,
        tsamp=tsamp, seed=seed, size=size)
    man_path = save_manifest(manifest, path + ".manifest.json")
    acc_span = max(5.0, abs(accel) + 5.0)
    cfg = SearchConfig(**dict(
        dict(dm_start=0.0, dm_end=max(20.0, dm + 5.0),
             acc_start=-acc_span, acc_end=acc_span,
             min_snr=6.0, npdmp=0, limit=16, size=size),
        **(overrides or {}), injection_manifest=man_path))
    search = MeshPulsarSearch(read_filterbank(path), cfg)
    t0 = time.time()
    result = search.run()
    elapsed = time.time() - t0
    match = match_candidates(manifest, result.candidates)
    probe = getattr(result, "injection", None) or {}
    return {
        "snr_in": float(snr),
        "period": float(period),
        "freq": float(manifest["freq"]),
        "dm": float(dm),
        "accel": float(accel),
        "jerk": float(jerk),
        "recovered": bool(match["recovered"]),
        "snr_out": round(float(match["best_snr"]), 4),
        "n_matches": int(match["n_matches"]),
        "budget": probe.get("snr", {}),
        "loss": probe.get("loss", {}),
        "elapsed_s": round(elapsed, 3),
        "manifest_path": man_path,
        "size": int(search.size),
    }


# --------------------------------------------------------------------------
# sweep + report + ledger
# --------------------------------------------------------------------------

def run_sweep(dirpath: str, *, snrs=DEFAULT_SNRS,
              periods=DEFAULT_PERIODS, accels=DEFAULT_ACCELS,
              dm: float = 0.0, jerk: float = 0.0,
              nsamps: int = 4096, size: int = 2048, seed: int = 0,
              overrides: dict | None = None,
              lattice: str | None = None,
              history: str | None = None,
              ledger: bool = True, verbose: bool = True) -> dict:
    """Run the full grid, reduce it, write the report + ledger record.

    ``lattice`` forces ``trial_lattice`` for every cell (the per-dtype
    recovery_delta mode); ``ledger=False`` skips the history record
    (the lattice sweep's per-dtype passes are diagnostics, not
    baselines).  Returns the report document.
    """
    os.makedirs(dirpath, exist_ok=True)
    say = print if verbose else (lambda *a, **kw: None)
    ov = dict(overrides or {})
    if lattice:
        ov["trial_lattice"] = lattice
    cells = []
    for i, (snr, period, accel) in enumerate(
            itertools.product(snrs, periods, accels)):
        cell = run_cell(
            os.path.join(dirpath, f"cell-{i:03d}.fil"),
            snr=snr, period=period, accel=accel, dm=dm, jerk=jerk,
            nsamps=nsamps, size=size, seed=seed + i, overrides=ov)
        say(f"sensitivity: cell {i} snr_in={snr:g} "
            f"period={period:g}s accel={accel:g} -> "
            f"{'recovered' if cell['recovered'] else 'MISSED'} "
            f"(snr_out={cell['snr_out']:g})")
        cells.append(cell)
    n_rec = sum(c["recovered"] for c in cells)
    fraction = n_rec / len(cells) if cells else 0.0

    # SNR-in -> SNR-out transfer: one row per injected SNR, averaged
    # over its period x accel cells (recovered cells only for the
    # output side — a miss has no meaningful SNR-out)
    transfer = []
    for snr in sorted(set(float(s) for s in snrs)):
        row_cells = [c for c in cells if c["snr_in"] == snr]
        rec = [c for c in row_cells if c["recovered"]]
        transfer.append({
            "snr_in": snr,
            "cells": len(row_cells),
            "recovered": len(rec),
            "fraction": round(len(rec) / len(row_cells), 4)
            if row_cells else 0.0,
            "snr_out_mean": round(
                sum(c["snr_out"] for c in rec) / len(rec), 4)
            if rec else 0.0,
        })
    detectable = [t["snr_in"] for t in transfer
                  if t["fraction"] >= DETECT_FRACTION]
    min_detectable = min(detectable) if detectable else None

    doc = {
        "v": 1,
        "seed": int(seed),
        "grid": {"snrs": [float(s) for s in snrs],
                 "periods": [float(p) for p in periods],
                 "accels": [float(a) for a in accels],
                 "dm": float(dm), "jerk": float(jerk)},
        "config": {"nsamps": int(nsamps), "size": int(size),
                   "lattice": lattice or "auto-default",
                   "overrides": {k: v for k, v in ov.items()}},
        "cells": cells,
        "transfer": transfer,
        "recovery_fraction": round(fraction, 4),
        "min_detectable_snr": min_detectable,
        "elapsed_s": round(sum(c["elapsed_s"] for c in cells), 3),
    }
    report_path = os.path.join(dirpath, REPORT_BASENAME)
    tmp = report_path + f".tmp{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f, sort_keys=True, indent=1)
    os.replace(tmp, report_path)
    doc["report_path"] = report_path
    if ledger:
        doc["ledger_record"] = append_sensitivity_record(doc, history)
    return doc


def append_sensitivity_record(doc: dict, history: str | None) -> dict:
    """One ``kind:"sensitivity"`` ledger record per sweep: recovery
    fraction + detection floor are the headline (the
    ``canary_recovery`` health rule and perf_report's table read
    them), the transfer rows ride along slim."""
    from ..obs.history import append_history, make_history_record

    metrics = {
        "cells": len(doc["cells"]),
        "recovered": sum(c["recovered"] for c in doc["cells"]),
        "recovery_fraction": doc["recovery_fraction"],
        "sweep_elapsed_s": doc["elapsed_s"],
    }
    if doc["min_detectable_snr"] is not None:
        metrics["min_detectable_snr"] = float(doc["min_detectable_snr"])
    rec = make_history_record(
        "sensitivity", metrics,
        config=doc["config"],
        extra={"transfer": doc["transfer"]},
    )
    append_history(rec, history)
    return rec


def run_lattice_sweep(dirpath: str, *, lattices=("u8", "bf16"),
                      sidecar: str | None = None,
                      stage: str = "dedisperse",
                      history: str | None = None, **sweep_kw) -> dict:
    """The sweep per trial-lattice dtype: f32 is the reference (and
    the pass that writes the ledger baseline); each quantised dtype's
    ``recovery_delta`` — its recovery fraction minus f32's — is
    recorded on the tuner sidecar's parity verdict, so ``auto``
    resolution can never pick a lattice that silently loses pulsars
    (``update_lattice`` refuses dtypes whose verdict failed)."""
    from ..search.tuning import _device_kind_default, update_lattice

    ref = run_sweep(os.path.join(dirpath, "f32"), lattice="f32",
                    history=history, **sweep_kw)
    costs = {"f32": ref["elapsed_s"]}
    parity = {}
    docs = {"f32": ref}
    for dtype in lattices:
        doc = run_sweep(os.path.join(dirpath, dtype), lattice=dtype,
                        ledger=False, **sweep_kw)
        docs[dtype] = doc
        costs[dtype] = doc["elapsed_s"]
        delta = doc["recovery_fraction"] - ref["recovery_fraction"]
        moved = sum(
            a["recovered"] != b["recovered"]
            for a, b in zip(ref["cells"], doc["cells"]))
        snr_deltas = [abs(a["snr_out"] - b["snr_out"])
                      for a, b in zip(ref["cells"], doc["cells"])
                      if a["recovered"] and b["recovered"]]
        parity[dtype] = {
            "ok": delta >= 0.0 and moved == 0,
            "max_snr_delta": round(max(snr_deltas, default=0.0), 4),
            "candidates_moved": moved,
            "recovery_delta": round(delta, 4),
        }
    size = int(sweep_kw.get("size", 2048))
    ok_dtypes = [d for d in costs
                 if d == "f32" or parity.get(d, {}).get("ok")]
    picked = min(ok_dtypes, key=costs.get)
    if sidecar:
        update_lattice(sidecar, _device_kind_default(), stage, size,
                       costs=costs, picked=picked, parity=parity)
    return {"reference": ref, "parity": parity, "costs": costs,
            "picked": picked, "docs": docs}


def format_budget_table(cells: list[dict]) -> str:
    """The per-stage SNR budget as one row per cell (the smoke's
    human-readable artifact): where each injection's SNR went."""
    lines = [f"{'snr_in':>8} {'whiten':>8} {'fourier':>8} "
             f"{'interbin':>8} {'harm':>8} {'peak':>8}  recovered"]
    for c in cells:
        b = c.get("budget", {})

        def col(key):
            val = b.get(key)
            return f"{val:8.2f}" if isinstance(val, (int, float)) \
                else f"{'-':>8}"

        lines.append(
            f"{c['snr_in']:8.2f} {col('whiten')} {col('fourier_bin')} "
            f"{col('interbin')} {col('harmonic_best')} {col('peak')}"
            f"  {'yes' if c['recovered'] else 'NO'}")
    return "\n".join(lines)


# --------------------------------------------------------------------------
# smoke (make sensitivity-smoke)
# --------------------------------------------------------------------------

def _serve(spool_dir: str, *verb_args, env=None) -> \
        subprocess.CompletedProcess:
    return subprocess.run(
        [sys.executable, "-m", "peasoup_tpu.serve", "--spool",
         spool_dir, *verb_args],
        env=env or dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True, text=True, timeout=900)


def run_smoke(dirpath: str, history: str | None = None) -> int:
    """The ISSUE 14 acceptance gate, two phases.

    Phase 1 — sweep: three injections at descending SNR through
    :func:`run_sweep`; at least the two bright ones recover, the
    sub-threshold one is reported missed, the budget table renders,
    and exactly one ``kind:"sensitivity"`` ledger record appears.

    Phase 2 — canaries under a REAL worker: ``submit --canary`` +
    ``worker --drain`` subprocesses; a good canary leaves ``health``
    at ok (exit 0), a deliberately sub-threshold canary drives
    ``canary_recovery`` to crit (``health`` exits nonzero), and a
    clean re-drain returns the fleet to ok.
    """
    from peasoup_tpu.obs.injection import (
        save_manifest, smoke_observation, synthesize,
    )

    shutil.rmtree(dirpath, ignore_errors=True)
    os.makedirs(dirpath)
    history = history or os.path.join(dirpath, "history.jsonl")
    failures: list[str] = []

    # ---- phase 1: sweep + budget table -------------------------------
    doc = run_sweep(os.path.join(dirpath, "sweep"), seed=5,
                    overrides=dict(FAST), history=history)
    print()
    print(format_budget_table(doc["cells"]))
    print()
    cells = doc["cells"]
    by_snr = {c["snr_in"]: c for c in cells}
    bright = [c for c in cells if c["snr_in"] >= 10.0]
    faint = by_snr[min(by_snr)]
    _check(os.path.exists(doc["report_path"]),
           "sensitivity_report.json written", failures)
    _check(sum(c["recovered"] for c in cells) >= 2
           and all(c["recovered"] for c in bright),
           "bright + marginal injections recovered (>= 2 of 3)",
           failures)
    _check(not faint["recovered"],
           f"sub-threshold injection (snr_in={faint['snr_in']:g}) "
           f"reported missed", failures)
    _check(all(isinstance(c["budget"].get("whiten"), (int, float))
               and isinstance(c["budget"].get("interbin"), (int, float))
               and isinstance(c["budget"].get("peak"), (int, float))
               for c in cells),
           "per-stage SNR budget attached to every cell", failures)
    _check(doc["min_detectable_snr"] is not None
           and doc["min_detectable_snr"] <= 12.0,
           f"detection floor measured "
           f"(min_detectable_snr={doc['min_detectable_snr']})",
           failures)

    from peasoup_tpu.obs.history import load_history

    recs = load_history(history, kinds=("sensitivity",))
    _check(len(recs) == 1
           and recs[0]["metrics"]["recovery_fraction"]
           == doc["recovery_fraction"]
           and "min_detectable_snr" in recs[0]["metrics"],
           "one kind:\"sensitivity\" ledger record with "
           "recovery_fraction + min_detectable_snr", failures)

    # ---- phase 2: canaries through a real worker ---------------------
    spool_dir = os.path.join(dirpath, "jobs")
    fast_flags = [x for k, v in FAST.items()
                  for x in ("--set", f"{k}={v}")]
    worker_args = ["worker", "--drain", "--single_device",
                   "--history", history, "--telemetry-interval", "0.2",
                   "--backoff-base", "0", "--max-attempts", "2"]

    good_fil = os.path.join(dirpath, "canary-good.fil")
    good_man = save_manifest(smoke_observation(good_fil, seed=11),
                             good_fil + ".manifest.json")
    sub = _serve(spool_dir, "submit", "--canary", good_man,
                 good_fil, *fast_flags)
    _check(sub.returncode == 0 and "canary" in sub.stdout,
           "submit --canary enqueues a tagged job", failures)
    drain = _serve(spool_dir, *worker_args)
    _check(drain.returncode == 0,
           "worker --drain completes the canary job", failures)
    health = _serve(spool_dir, "health", "--ledger", history)
    print(health.stdout.strip())
    _check(health.returncode == 0
           and "canary_recovery" in health.stdout,
           "recovered canary: health reports ok (exit 0)", failures)

    # a canary whose injection is too faint to find: the search runs
    # clean, the matcher finds nothing, the fleet must go crit
    bad_fil = os.path.join(dirpath, "canary-faint.fil")
    bad_man = save_manifest(
        synthesize(bad_fil, period=16.0 * DEFAULT_TSAMP, duty=0.05,
                   snr=1.0, seed=13),
        bad_fil + ".manifest.json")
    _serve(spool_dir, "submit", "--canary", bad_man, bad_fil,
           *fast_flags)
    _serve(spool_dir, *worker_args)
    health_bad = _serve(spool_dir, "health", "--ledger", history)
    print(health_bad.stdout.strip())
    _check(health_bad.returncode != 0
           and "canary_recovery" in health_bad.stdout
           and "CRIT" in health_bad.stdout,
           "missed canary drives canary_recovery to crit "
           "(health exits nonzero)", failures)

    # clean re-drain: a newer recovered-only canary sample returns the
    # fleet to ok without purging history
    good2_fil = os.path.join(dirpath, "canary-good2.fil")
    good2_man = save_manifest(smoke_observation(good2_fil, seed=17),
                              good2_fil + ".manifest.json")
    _serve(spool_dir, "submit", "--canary", good2_man, good2_fil,
           *fast_flags)
    _serve(spool_dir, *worker_args)
    health_again = _serve(spool_dir, "health", "--ledger", history)
    print(health_again.stdout.strip())
    _check(health_again.returncode == 0,
           "clean re-drain returns health to ok", failures)

    # canary isolation: the store's science reads must not see the
    # canary records the three drains ingested
    from peasoup_tpu.serve.store import CandidateStore

    store = CandidateStore(os.path.join(spool_dir, "candidates.jsonl"))
    _check(store.count() == 0
           and len(store.records(include_canary=True)) > 0,
           "canary records excluded from science reads "
           "(include_canary=True still sees them)", failures)

    print()
    if failures:
        print(f"sensitivity-smoke: {len(failures)} check(s) FAILED",
              file=sys.stderr)
        return 1
    print("sensitivity-smoke: all checks passed")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="peasoup-tpu-sensitivity",
        description="Peasoup-TPU - synthetic-pulsar sensitivity sweep "
                    "/ canary smoke",
    )
    p.add_argument("--dir", default="/tmp/peasoup-sensitivity",
                   help="scratch directory (--smoke wipes it)")
    p.add_argument("--snrs", default=None,
                   help="comma-separated injected SNRs "
                        f"(default {','.join(str(s) for s in DEFAULT_SNRS)})")
    p.add_argument("--periods", default=None,
                   help="comma-separated injected periods, seconds")
    p.add_argument("--accels", default=None,
                   help="comma-separated injected accels, m/s^2")
    p.add_argument("--dm", type=float, default=0.0,
                   help="injected dispersion measure")
    p.add_argument("--nsamps", type=int, default=4096,
                   help="samples per injected observation")
    p.add_argument("--size", type=int, default=2048,
                   help="search FFT length the smear ramp is pinned to")
    p.add_argument("--seed", type=int, default=0,
                   help="noise seed (same seed -> identical sweep)")
    p.add_argument("--history", default=None,
                   help="bench history ledger for the "
                        "kind:\"sensitivity\" record (default: repo "
                        "benchmarks/history.jsonl)")
    p.add_argument("--lattices", default=None,
                   help="comma-separated trial-lattice dtypes to sweep "
                        "per-dtype (records recovery_delta on the "
                        "tuner sidecar)")
    p.add_argument("--sidecar", default=None,
                   help="tuner sidecar path for --lattices verdicts")
    p.add_argument("--smoke", action="store_true",
                   help="run the sensitivity-smoke acceptance gate")
    args = p.parse_args(argv)

    if args.smoke:
        return run_smoke(args.dir, history=args.history)

    def _floats(text, default):
        if text is None:
            return default
        return tuple(float(x) for x in text.split(",") if x.strip())

    kw = dict(
        snrs=_floats(args.snrs, DEFAULT_SNRS),
        periods=_floats(args.periods, DEFAULT_PERIODS),
        accels=_floats(args.accels, DEFAULT_ACCELS),
        dm=args.dm, nsamps=args.nsamps, size=args.size,
        seed=args.seed,
    )
    os.makedirs(args.dir, exist_ok=True)
    if args.lattices:
        out = run_lattice_sweep(
            args.dir,
            lattices=tuple(d for d in args.lattices.split(",")
                           if d.strip()),
            sidecar=args.sidecar, history=args.history, **kw)
        doc = out["reference"]
        for dtype, verdict in out["parity"].items():
            print(f"{dtype}: recovery_delta="
                  f"{verdict['recovery_delta']:+g} "
                  f"({'ok' if verdict['ok'] else 'FAILED'})")
        print(f"picked: {out['picked']}")
    else:
        doc = run_sweep(args.dir, history=args.history, **kw)
    print()
    print(format_budget_table(doc["cells"]))
    print(f"\nrecovery_fraction: {doc['recovery_fraction']:g}  "
          f"min_detectable_snr: {doc['min_detectable_snr']}")
    print(f"wrote {doc['report_path']}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
