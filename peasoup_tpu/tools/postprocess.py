"""Joined XML+binary candidate access and predictor text.

Modern (python3, stdlib+numpy) equivalents of the reference's
post-processing helpers `tools/peasoup_tools.py:14-43,153-164`:
``PeasoupOutput`` joins a candidate's ``overview.xml`` record with its
fold/hits block in ``candidates.peasoup`` via the XML ``byte_offset``.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

import numpy as np

from ..output.binary import CandidateFileParser
from ..output.parsers import OverviewFile


def radec_to_str(val: float) -> str:
    """SIGPROC packed ddmmss.s / hhmmss.s float -> 'dd:mm:ss.ssss'
    (`peasoup_tools.py:14-24`)."""
    sign = -1 if val < 0 else 1
    fractional, integral = np.modf(abs(val))
    xx = (integral - (integral % 10000)) / 10000
    yy = ((integral - (integral % 100)) / 100) - xx * 100
    zz = integral - 100 * yy - 10000 * xx + fractional
    return "%02d:%02d:%07.4f" % (sign * xx, yy, zz)


@dataclass
class JoinedCandidate:
    """One candidate with its XML stats, fold array, and hit list."""

    stats: dict
    fold: np.ndarray | None
    hits: np.ndarray = field(default_factory=lambda: np.zeros(0))

    def __getattr__(self, name):
        try:
            return self.stats[name]
        except KeyError:
            raise AttributeError(name) from None


class PeasoupOutput:
    """Join overview.xml and candidates.peasoup
    (`peasoup_tools.py:35-43`)."""

    def __init__(self, overview_file: str, candidate_file: str | None = None):
        if candidate_file is None:
            candidate_file = os.path.join(
                os.path.dirname(overview_file), "candidates.peasoup"
            )
        self.overview = OverviewFile(overview_file)
        self._cand_file = candidate_file

    @property
    def ncands(self) -> int:
        return self.overview.ncands

    def get_candidate(self, idx: int) -> JoinedCandidate:
        stats = self.overview.get_candidate(idx)
        with CandidateFileParser(self._cand_file) as parser:
            fold, hits = parser.cand_from_offset(int(stats["byte_offset"]))
        return JoinedCandidate(stats=stats, fold=fold, hits=hits)

    def make_predictor(self, idx: int) -> str:
        """TEMPO-style predictor text (`peasoup_tools.py:153-164`)."""
        stats = self.overview.get_candidate(idx)
        hdr = self.overview.section("header_parameters")
        return "\n".join((
            "SOURCE: %s" % hdr.get("source_name", "unknown"),
            "PERIOD: %.15f" % stats["period"],
            "DM: %.3f" % stats["dm"],
            "ACC: %.3f" % stats["acc"],
            "RA: %s" % radec_to_str(float(hdr.get("src_raj", 0.0))),
            "DEC: %s" % radec_to_str(float(hdr.get("src_dej", 0.0))),
        ))


def as_text(overview_file: str, sort_by: str = "period") -> str:
    """Plain-text candidate table (`tools/peasoup_as_text.py`)."""
    ar = OverviewFile(overview_file).as_array()
    lines = ["    ".join(ar.dtype.names)]
    order = np.argsort(ar[sort_by])
    for row in ar[order]:
        lines.append("    ".join(str(v) for v in row))
    return "\n".join(lines)


def as_text_main(argv=None) -> int:
    import sys

    args = argv if argv is not None else sys.argv[1:]
    if not args:
        print("usage: peasoup-tpu-as-text <overview.xml> [sort_field]")
        return 1
    sort_by = args[1] if len(args) > 1 else "period"
    print(as_text(args[0], sort_by))
    return 0
