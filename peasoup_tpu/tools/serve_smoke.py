"""Survey-scheduler smoke test (``make serve-smoke``).

Phase 1 — drain: spool three synthetic observations (one deliberately
truncated mid-data), run ``worker --drain``, and assert the terminal
state the scheduler promises: two jobs in ``done/`` with their
distilled candidates in the cross-run store, ONE quarantined job in
``failed/`` carrying the :class:`InputFileError` byte counts, the
scheduler counters consistent, and a ``serve`` throughput record
(jobs/hour) appended to the bench history ledger.

Phase 2 — crash-resume: submit a fourth observation, fail its first
attempt mid-search after several checkpointed DM trials (a controlled
stand-in for a killed worker), and assert the retry attempt RESUMES
from the per-job checkpoint (``checkpoint.rows_resumed`` > 0) instead
of recomputing, finishing the job in ``done/``.

Exit status 0 only if every assertion holds — CI-gateable like
``trace-smoke``.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import warnings


def _write_synthetic(path: str, nsamps: int = 4096, nchans: int = 16,
                     seed: int = 0, truncate_bytes: int = 0) -> str:
    """A small 8-bit filterbank with a pulse train; ``truncate_bytes``
    chops the data section short of what the header (written WITH
    nsamples, so the promise is explicit) declares.  Thin wrapper over
    the injection synthesizer's shared smoke recipe (byte-identical to
    the historical private helper), so smoke inputs and injections are
    one code path."""
    from peasoup_tpu.obs.injection import smoke_observation

    smoke_observation(path, nsamps=nsamps, nchans=nchans, seed=seed,
                      truncate_bytes=truncate_bytes)
    return path


def _check(ok: bool, what: str, failures: list[str]) -> None:
    print(("PASS " if ok else "FAIL ") + what)
    if not ok:
        failures.append(what)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="peasoup-tpu-serve-smoke",
        description="Peasoup-TPU - survey scheduler smoke test",
    )
    p.add_argument("--dir", default="/tmp/peasoup-serve-smoke",
                   help="scratch directory (wiped)")
    p.add_argument("--history", default=None,
                   help="history ledger to append to (default: the "
                        "repo benchmarks/history.jsonl)")
    args = p.parse_args(argv)

    shutil.rmtree(args.dir, ignore_errors=True)
    os.makedirs(args.dir)
    spool_dir = os.path.join(args.dir, "jobs")

    from peasoup_tpu.obs.metrics import REGISTRY
    from peasoup_tpu.serve import (
        BackoffPolicy, CandidateStore, JobSpool, SurveyWorker,
    )

    REGISTRY.reset()
    spool = JobSpool(spool_dir)
    fils = [
        _write_synthetic(os.path.join(args.dir, f"obs{i}.fil"), seed=i)
        for i in range(2)
    ]
    truncated = _write_synthetic(
        os.path.join(args.dir, "obs_truncated.fil"), seed=2,
        truncate_bytes=1024)
    overrides = {"dm_end": 20.0, "min_snr": 6.0, "npdmp": 0,
                 "limit": 10}
    for path in fils + [truncated]:
        spool.submit(path, overrides)

    failures: list[str] = []
    worker = SurveyWorker(
        spool, single_device=True,
        backoff=BackoffPolicy(max_attempts=2, base_s=0.0),
        history_path=args.history, sleeper=lambda s: None,
    )
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")  # quarantine warns by design
        summary = worker.drain()

    counts = spool.counts()
    _check(counts["done"] == 2, "2 jobs in done/", failures)
    _check(counts["failed"] == 1, "1 job in failed/", failures)
    _check(counts["pending"] == counts["running"] == 0,
           "queue fully drained", failures)

    bad = spool.jobs("failed")
    quarantined = bool(bad) and all(
        f["classification"] == "quarantine"
        and "truncated filterbank" in f["error"]
        and "bytes" in f["error"]
        for f in bad[0].failures
    )
    _check(quarantined,
           "truncated observation quarantined with byte counts",
           failures)
    _check(bool(bad) and bad[0].attempts == 1,
           "quarantine is immediate (no retries burned)", failures)

    store = CandidateStore(os.path.join(spool_dir, "candidates.jsonl"))
    n_store = store.count()
    _check(n_store > 0 and len(store.sources()) == 2,
           f"store holds {n_store} candidates from 2 observations",
           failures)

    counters = REGISTRY.snapshot()["counters"]
    _check(counters.get("scheduler.claimed") == 3
           and counters.get("scheduler.succeeded") == 2
           and counters.get("scheduler.quarantined") == 1,
           "scheduler counters: claimed=3 succeeded=2 quarantined=1",
           failures)
    _check(summary["jobs_per_hour"] > 0, "jobs/hour computed", failures)

    from peasoup_tpu.obs.history import load_history

    serve_recs = load_history(args.history, kinds=["serve"])
    ok_rec = bool(serve_recs) and \
        serve_recs[-1]["metrics"].get("jobs_per_hour", 0) > 0 and \
        serve_recs[-1]["metrics"].get("jobs_succeeded") == 2
    _check(ok_rec, "throughput record in benchmarks/history.jsonl",
           failures)

    # ---- phase 2: crash mid-job, requeue, resume via checkpoint ------
    from peasoup_tpu.search.pipeline import PulsarSearch

    REGISTRY.reset()
    crash_fil = _write_synthetic(
        os.path.join(args.dir, "obs_crash.fil"), seed=3)
    spool.submit(crash_fil, {**overrides, "checkpoint_interval": 1})

    orig = PulsarSearch.search_dm_trial
    state = {"calls": 0, "resumed_calls": 0, "crashed": False}

    def _crashing(self, trials, idx):
        if not state["crashed"]:
            state["calls"] += 1
            if state["calls"] > 5:
                state["crashed"] = True
                raise RuntimeError("injected mid-job crash")
        else:
            state["resumed_calls"] += 1
        return orig(self, trials, idx)

    PulsarSearch.search_dm_trial = _crashing
    try:
        worker2 = SurveyWorker(
            spool, single_device=True,
            backoff=BackoffPolicy(max_attempts=2, base_s=0.0),
            history_path=args.history, sleeper=lambda s: None,
        )
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            worker2.drain()
    finally:
        PulsarSearch.search_dm_trial = orig

    counters = REGISTRY.snapshot()["counters"]
    _check(spool.counts()["done"] == 3,
           "crashed job retried to done/", failures)
    _check(counters.get("scheduler.retried", 0) == 1,
           "first attempt classified transient and re-queued",
           failures)
    resumed = counters.get("checkpoint.rows_resumed", 0)
    _check(resumed >= 5,
           f"retry resumed {resumed} checkpointed DM rows instead of "
           f"recomputing", failures)

    status = spool.get(spool.jobs("done")[-1].job_id)
    report_ok = False
    if status is not None:
        outdir = status[1].summary.get("outdir", "")
        report = os.path.join(outdir, "run_report.json")
        if os.path.exists(report):
            report_ok = json.load(open(report)).get(
                "candidates", {}).get("count", 0) >= 0
    _check(report_ok, "per-job run_report.json written", failures)

    if failures:
        print(f"\nserve-smoke: {len(failures)} check(s) FAILED",
              file=sys.stderr)
        return 1
    print("\nserve-smoke: all checks passed")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
