"""Fleet smoke test (``make fleet-smoke``).

Exercises the fleet control plane (serve/fleet.py) with REAL worker
processes — ``python -m peasoup_tpu.serve fleet-worker`` subprocesses
on fake membership — against one shared spool, the way a multi-host
slice shares a filesystem:

Phase 1 — two-host drain: spool two good synthetic observations plus
one truncated mid-data, start fleet workers for host 0 and host 1
concurrently, and assert the fleet's promises: 2 done + 1 quarantined
with ZERO double-claims (every terminal record shows exactly one
attempt), candidates landing in per-host ``store-<host>.jsonl``
shards, no leases left behind, and both hosts' status snapshots
present.

Phase 2 — dead-host recovery: submit another observation, SIGKILL the
claiming worker mid-job, and assert ``requeue --expired`` returns the
job to ``pending/`` with a ``lease_expired`` failure entry and the
attempt history intact; a second host's re-drain then finishes it.

Phase 3 — fleet queries: the merged-shard ``coincident_groups`` must
equal a single store holding the concatenated shards and find the
cross-observation pulse train; ``status --fleet`` must render every
host and write ``fleet_report.json``.

Exit status 0 only if every assertion holds — CI-gateable like
``serve-smoke``.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import subprocess
import sys
import time
import warnings

#: fast-search overrides shared by every smoke job
FAST = {"dm_end": 20.0, "min_snr": 6.0, "npdmp": 0, "limit": 10}


def _write_synthetic(path: str, nsamps: int = 4096, nchans: int = 16,
                     seed: int = 0, truncate_bytes: int = 0) -> str:
    """A small 8-bit filterbank with a pulse train (the SAME period in
    every observation, so the survey coincidencer has a cross-source
    signal to find); ``truncate_bytes`` chops the data section short
    of what the header declares.  Thin wrapper over the injection
    synthesizer's shared smoke recipe (byte-identical to the
    historical private helper)."""
    from peasoup_tpu.obs.injection import smoke_observation

    smoke_observation(path, nsamps=nsamps, nchans=nchans, seed=seed,
                      truncate_bytes=truncate_bytes)
    return path


def _check(ok: bool, what: str, failures: list[str]) -> None:
    print(("PASS " if ok else "FAIL ") + what)
    if not ok:
        failures.append(what)


def _fleet_worker_cmd(spool_dir: str, host_id: int, history: str,
                      extra: list[str] | None = None) -> list[str]:
    return [
        sys.executable, "-m", "peasoup_tpu.serve",
        "--spool", spool_dir, "fleet-worker",
        "--host-id", str(host_id), "--host-count", "2",
        "--drain", "--single_device", "--max-attempts", "2",
        "--backoff-base", "0", "--history", history,
        "--lease-ttl", "60", "--heartbeat", "0.5",
    ] + (extra or [])


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="peasoup-tpu-fleet-smoke",
        description="Peasoup-TPU - fleet control-plane smoke test",
    )
    p.add_argument("--dir", default="/tmp/peasoup-fleet-smoke",
                   help="scratch directory (wiped)")
    args = p.parse_args(argv)

    shutil.rmtree(args.dir, ignore_errors=True)
    os.makedirs(args.dir)
    spool_dir = os.path.join(args.dir, "jobs")
    history = os.path.join(args.dir, "history.jsonl")

    from peasoup_tpu.serve import (
        LEASE_EXPIRED, CandidateStore, JobSpool, ShardedCandidateStore,
    )
    from peasoup_tpu.serve.retry import pause

    spool = JobSpool(spool_dir)
    good = [
        _write_synthetic(os.path.join(args.dir, f"obs{i}.fil"),
                         seed=i)
        for i in range(2)
    ]
    truncated = _write_synthetic(
        os.path.join(args.dir, "obs_truncated.fil"), seed=2,
        truncate_bytes=1024)
    for path in good + [truncated]:
        spool.submit(path, FAST)

    failures: list[str] = []
    env = dict(os.environ, JAX_PLATFORMS="cpu")

    # ---- phase 1: two hosts drain one spool concurrently -------------
    # --max-jobs 2 caps either host at 2 of the 3 jobs, so BOTH hosts
    # are guaranteed work (and a per-host throughput ledger record)
    procs = [
        subprocess.Popen(_fleet_worker_cmd(spool_dir, h, history,
                                           ["--max-jobs", "2"]),
                         env=env, stdout=subprocess.PIPE,
                         stderr=subprocess.STDOUT, text=True)
        for h in (0, 1)
    ]
    outs = [proc.communicate(timeout=600)[0] for proc in procs]
    for h, out in enumerate(outs):
        print(f"---- fleet-worker host-{h} ----")
        print(out.strip())

    counts = spool.counts()
    _check(counts["done"] == 2, "2 jobs in done/", failures)
    _check(counts["failed"] == 1, "1 job in failed/ (quarantine)",
           failures)
    _check(counts["pending"] == counts["running"] == 0,
           "queue fully drained", failures)
    terminal = spool.jobs("done") + spool.jobs("failed")
    _check(all(rec.attempts == 1 for rec in terminal),
           "zero double-claims (every terminal job: exactly 1 attempt)",
           failures)
    _check(not os.listdir(os.path.join(spool.root, "leases")),
           "no leases left behind", failures)
    bad = spool.jobs("failed")
    _check(bool(bad) and bad[0].input == truncated
           and bad[0].failures[0]["classification"] == "quarantine",
           "truncated observation quarantined", failures)

    from peasoup_tpu.serve.fleet import load_host_statuses

    statuses = load_host_statuses(spool)
    _check(set(statuses) == {"host-0", "host-1"},
           "both hosts wrote status snapshots", failures)
    claimed_total = sum(s["summary"]["claimed"]
                       for s in statuses.values())
    _check(claimed_total == 3,
           f"per-host claims sum to 3 (got {claimed_total})", failures)

    merged = ShardedCandidateStore(spool_dir)
    shard_counts = merged.shard_counts()
    _check(merged.count() > 0 and all(
        name.startswith("store-host-") for name in shard_counts),
        f"candidates in per-host shards {shard_counts}", failures)
    _check(set(merged.sources()) == set(good),
           "merged store sees both observations", failures)

    # ---- phase 2: SIGKILL mid-job, lease-expiry recovery -------------
    kill_fil = _write_synthetic(os.path.join(args.dir, "obs_kill.fil"),
                                seed=3)
    kill_rec = spool.submit(kill_fil, FAST)
    proc = subprocess.Popen(
        _fleet_worker_cmd(spool_dir, 0, history), env=env,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    deadline = time.time() + 120.0
    while spool.counts()["running"] == 0 and time.time() < deadline:
        pause(0.05)
    claimed_mid_job = spool.counts()["running"] == 1
    proc.send_signal(signal.SIGKILL)
    proc.wait(timeout=60)
    _check(claimed_mid_job and spool.counts()["running"] == 1,
           "worker SIGKILLed mid-job (job stuck in running/)",
           failures)

    rq = subprocess.run(
        [sys.executable, "-m", "peasoup_tpu.serve", "--spool",
         spool_dir, "requeue", "--expired", "--lease-ttl", "0"],
        env=env, capture_output=True, text=True, timeout=120)
    print(rq.stdout.strip())
    _check(rq.returncode == 0 and kill_rec.job_id in rq.stdout,
           "requeue --expired reaped the killed worker's job",
           failures)
    _, back = spool.get(kill_rec.job_id)
    _check(spool.counts()["pending"] == 1 and back.attempts == 1
           and back.failures[-1]["classification"] == LEASE_EXPIRED,
           "reaped job pending with attempt history + lease_expired "
           "entry", failures)

    redrain = subprocess.run(
        _fleet_worker_cmd(spool_dir, 1, history), env=env,
        capture_output=True, text=True, timeout=600)
    print(redrain.stdout.strip())
    _check(redrain.returncode == 0, "host-1 re-drain exit 0", failures)
    state, done_rec = spool.get(kill_rec.job_id)
    _check(state == "done" and done_rec.attempts == 2,
           "killed job recovered to done/ on the second attempt",
           failures)

    # ---- phase 3: merged coincidence + status --fleet ----------------
    merged = ShardedCandidateStore(spool_dir)
    single_path = os.path.join(args.dir, "all_candidates.jsonl")
    with open(single_path, "w") as out:
        for shard in merged.shard_files():
            with open(shard) as f:
                out.write(f.read())
    single = CandidateStore(single_path)
    strip = lambda recs: sorted(
        (r["source"], r["freq"], r["snr"]) for r in recs)
    g_m = merged.coincident_groups(freq_tol=1e-3, min_sources=2)
    g_s = single.coincident_groups(freq_tol=1e-3, min_sources=2)
    _check([strip(g) for g in g_m] == [strip(g) for g in g_s],
           "merged-shard coincident_groups == single-store groups",
           failures)
    cross = [g for g in g_m
             if len({r["source"] for r in g}) >= 2]
    _check(bool(cross),
           f"cross-observation pulse train found "
           f"({len(g_m)} group(s))", failures)

    st = subprocess.run(
        [sys.executable, "-m", "peasoup_tpu.serve", "--spool",
         spool_dir, "status", "--fleet"],
        env=env, capture_output=True, text=True, timeout=120)
    print(st.stdout.strip())
    _check(st.returncode == 0 and "host-0" in st.stdout
           and "host-1" in st.stdout and "TOTAL" in st.stdout,
           "status --fleet renders every host + totals", failures)
    report_path = os.path.join(spool_dir, "fleet_report.json")
    report = (json.load(open(report_path))
              if os.path.exists(report_path) else {})
    _check(report.get("totals", {}).get("hosts") == 2
           and report.get("queue", {}).get("done") == 3
           and report.get("queue", {}).get("failed") == 1
           and len(report.get("store", {}).get("shards", {})) >= 1,
           "fleet_report.json aggregates hosts, queue and shards",
           failures)

    from peasoup_tpu.obs.history import load_history

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        serve_recs = load_history(history, kinds=["serve"])
    hosts_in_ledger = {r.get("config", {}).get("host")
                       for r in serve_recs}
    _check({"host-0", "host-1"} <= hosts_in_ledger,
           "per-host throughput records in the history ledger",
           failures)

    if failures:
        print(f"\nfleet-smoke: {len(failures)} check(s) FAILED",
              file=sys.stderr)
        return 1
    print("\nfleet-smoke: all checks passed")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
