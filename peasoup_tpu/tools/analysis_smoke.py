"""Static-analysis smoke test (``make analysis-smoke``).

Proves the concurrency & contracts prover (ISSUE 17) actually *fires*:
writes a deliberately broken fixture tree — an unguarded shared
attribute, an AB/BA lock-order cycle, a raw truncating ``open`` under
``serve/``, and a stream writer smuggling an undeclared key next to a
drifted schema-version constant — then runs each of PSL010–PSL013 over
it via ``python -m peasoup_tpu.analysis --rules PSL0xx`` and asserts a
NONZERO exit naming the rule.  A detector that cannot detect is worse
than none: the repo-clean gate in tests/test_concurrency_lint.py only
means the tree is quiet, this smoke means the alarm still works.

Also exercises the ``--rules`` subsetting path both ways: a combined
``--rules PSL010,PSL011`` run must flag both fixtures, and the same
four rules over the *real* tree must exit 0 (every real finding was
fixed or pragma'd, not baselined).

Exit status 0 only if every assertion holds — CI-gateable like the
other ``*-smoke`` targets.
"""

from __future__ import annotations

import argparse
import os
import shutil
import subprocess
import sys
import textwrap

#: fixture relpath -> (source, rule expected to fire)
FIXTURES: dict[str, tuple[str, str]] = {
    "peasoup_tpu/serve/unguarded.py": ("""
        import threading

        class Worker:
            def __init__(self):
                self.count = 0
                self._t = threading.Thread(target=self._run,
                                           daemon=True)

            def _run(self):
                while True:
                    self.count += 1

            def snapshot(self):
                return self.count
    """, "PSL010"),
    "peasoup_tpu/serve/deadlock.py": ("""
        import threading

        LOCK_A = threading.Lock()
        LOCK_B = threading.Lock()

        def forward():
            with LOCK_A:
                with LOCK_B:
                    pass

        def backward():
            with LOCK_B:
                with LOCK_A:
                    pass
    """, "PSL011"),
    "peasoup_tpu/serve/rawwrite.py": ("""
        import json

        def save_status(path, doc):
            with open(path, "w") as f:
                json.dump(doc, f)
    """, "PSL012"),
    # impersonates a declared PSL013 writer site: drifted version
    # constant + an undeclared record key
    "peasoup_tpu/obs/events.py": ("""
        SCHEMA_VERSION = 99

        class EventLog:
            def emit(self, kind, message):
                rec = {"v": SCHEMA_VERSION, "ts": 0.0,
                       "kind": kind, "message": message,
                       "smuggled": True}
                return rec
    """, "PSL013"),
}


def _check(ok: bool, msg: str) -> None:
    if not ok:
        print(f"analysis-smoke FAIL: {msg}", file=sys.stderr)
        raise SystemExit(1)


def _run_lint(rules: str, root: str, paths: list[str]) -> tuple[int, str]:
    proc = subprocess.run(
        [sys.executable, "-m", "peasoup_tpu.analysis",
         "--rules", rules, "--no-jaxpr", "--root", root] + paths,
        capture_output=True, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    return proc.returncode, proc.stdout + proc.stderr


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--dir", default="/tmp/peasoup-analysis-smoke",
                    help="fixture tree scratch directory")
    args = ap.parse_args(argv)

    shutil.rmtree(args.dir, ignore_errors=True)
    for rel, (code, _rule) in FIXTURES.items():
        path = os.path.join(args.dir, rel)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            f.write(textwrap.dedent(code))

    # each broken fixture must trip exactly its rule
    for rel, (_code, rule) in FIXTURES.items():
        path = os.path.join(args.dir, rel)
        rc, out = _run_lint(rule, args.dir, [path])
        _check(rc == 1, f"{rule} did not fire on {rel} "
                        f"(exit {rc}):\n{out}")
        _check(rule in out, f"{rule} verdict does not name the rule:"
                            f"\n{out}")
        print(f"analysis-smoke: {rule} fired on {rel}")

    # --rules subsetting: a combined run flags both concurrency
    # fixtures, and only those rules ran (no PSL012 noise from the
    # rawwrite fixture sitting in the same tree)
    rc, out = _run_lint("PSL010,PSL011", args.dir,
                        [os.path.join(args.dir, "peasoup_tpu")])
    _check(rc == 1, f"combined --rules run should fail (exit {rc})")
    _check("PSL010" in out and "PSL011" in out,
           f"combined run missing a rule:\n{out}")
    _check("PSL012" not in out,
           f"--rules subset leaked an unrequested rule:\n{out}")
    print("analysis-smoke: --rules PSL010,PSL011 subsetting works")

    # the real tree is clean under the same four rules
    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    proc = subprocess.run(
        [sys.executable, "-m", "peasoup_tpu.analysis",
         "--rules", "PSL010,PSL011,PSL012,PSL013", "--no-jaxpr"],
        capture_output=True, text=True, cwd=repo,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    _check(proc.returncode == 0,
           f"real tree not clean under PSL010-013:\n"
           f"{proc.stdout}{proc.stderr}")
    print("analysis-smoke: real tree clean under PSL010-013")
    print("analysis-smoke OK")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
