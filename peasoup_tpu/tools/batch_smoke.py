"""Batched-dispatch smoke test (``make batch-smoke``).

Phase 1 — sequential reference: spool four same-geometry synthetic
observations and drain them with a ``batch=1`` worker, recording the
per-source store records and the number of fused device dispatches
(``runs.mesh_fused``).

Phase 2 — batched drain: re-spool the SAME four observations plus one
odd-geometry observation (different ``nchans``, so it cannot share a
compiled program) and drain with ``batch=4``.  Assert the terminal
state ISSUE 9 promises: ONE batched dispatch carrying all four
same-bucket beams (``scheduler.batched_dispatches == 1``,
``scheduler.batch_fill == 4``) plus one singleton dispatch for the odd
observation, all five jobs in ``done/``, fewer fused dispatches than
the sequential drain (the point of batching), per-source store records
BIT-IDENTICAL to the sequential reference (the per-beam parity
guarantee — batching must not change any candidate), and a ``serve``
ledger record carrying the new ``batch`` / ``batched_dispatches`` /
``batch_fill`` metrics with ``batch_fill >= 2``.

On CPU the win is asserted as a dispatch-count reduction rather than
wall-clock (single-core XLA gains little from stacking); on TPU the
same two drains show the round-trip amortisation directly.

Exit status 0 only if every assertion holds — CI-gateable like
``serve-smoke``.
"""

from __future__ import annotations

import argparse
import os
import shutil
import sys


def _write_synthetic(path: str, nsamps: int = 4096, nchans: int = 16,
                     seed: int = 0) -> str:
    """A small 8-bit filterbank with a pulse train (same recipe as
    serve_smoke so the two smokes exercise identical observations).
    Thin wrapper over the injection synthesizer's shared smoke recipe
    (byte-identical to the historical private helper)."""
    from peasoup_tpu.obs.injection import smoke_observation

    smoke_observation(path, nsamps=nsamps, nchans=nchans, seed=seed)
    return path


def _check(ok: bool, what: str, failures: list[str]) -> None:
    print(("PASS " if ok else "FAIL ") + what)
    if not ok:
        failures.append(what)


def _store_fingerprint(store, sources) -> dict:
    """Per-source candidate tuples, order-normalised — the bit-identity
    comparison key (store records round floats identically on both
    paths, so exact equality is the right predicate)."""
    out = {}
    for src in sources:
        out[os.path.basename(src)] = sorted(
            (r["dm"], r["acc"], r["freq"], r["snr"], r["folded_snr"],
             r["nh"])
            for r in store.records(source=src)
        )
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="peasoup-tpu-batch-smoke",
        description="Peasoup-TPU - batched-dispatch smoke test",
    )
    p.add_argument("--dir", default="/tmp/peasoup-batch-smoke",
                   help="scratch directory (wiped)")
    p.add_argument("--batch", type=int, default=4,
                   help="batch width for the batched drain")
    args = p.parse_args(argv)

    shutil.rmtree(args.dir, ignore_errors=True)
    os.makedirs(args.dir)
    history = os.path.join(args.dir, "history.jsonl")

    from peasoup_tpu.obs.metrics import REGISTRY
    from peasoup_tpu.serve import CandidateStore, JobSpool, SurveyWorker

    B = max(2, args.batch)
    overrides = {"dm_end": 20.0, "min_snr": 6.0, "npdmp": 0,
                 "limit": 10}
    same = [
        _write_synthetic(os.path.join(args.dir, f"obs{i}.fil"), seed=i)
        for i in range(B)
    ]
    odd = _write_synthetic(os.path.join(args.dir, "obs_odd.fil"),
                           nchans=32, seed=7)
    failures: list[str] = []

    # ---- phase 1: sequential reference (batch=1) ---------------------
    REGISTRY.reset()
    seq_dir = os.path.join(args.dir, "jobs_seq")
    seq_spool = JobSpool(seq_dir)
    for path in same:
        seq_spool.submit(path, overrides)
    SurveyWorker(seq_spool, history_path=history,
                 sleeper=lambda s: None).drain()
    seq_counters = REGISTRY.snapshot()["counters"]
    seq_dispatches = seq_counters.get("runs.mesh_fused", 0)
    _check(seq_spool.counts()["done"] == B,
           f"sequential reference: {B} jobs in done/", failures)
    seq_store = CandidateStore(os.path.join(seq_dir, "candidates.jsonl"))
    seq_fp = _store_fingerprint(seq_store, same)
    _check(all(seq_fp.values()),
           "sequential reference found candidates in every beam",
           failures)

    # ---- phase 2: batched drain (batch=B, plus one odd bucket) -------
    REGISTRY.reset()
    bat_dir = os.path.join(args.dir, "jobs_batch")
    bat_spool = JobSpool(bat_dir)
    for path in same + [odd]:
        bat_spool.submit(path, overrides)
    worker = SurveyWorker(bat_spool, batch=B, history_path=history,
                          sleeper=lambda s: None)
    summary = worker.drain()

    counts = bat_spool.counts()
    _check(counts["done"] == B + 1,
           f"batched drain: {B + 1} jobs in done/", failures)
    _check(counts["pending"] == counts["running"] == counts["failed"]
           == 0, "batched drain: queue fully drained, no failures",
           failures)

    counters = REGISTRY.snapshot()["counters"]
    n_batched = counters.get("scheduler.batched_dispatches", 0)
    fill = counters.get("scheduler.batch_fill", 0)
    _check(n_batched == 1,
           f"exactly one batched dispatch (got {n_batched})", failures)
    _check(fill == B,
           f"batched dispatch carried all {B} same-bucket beams "
           f"(batch_fill={fill})", failures)
    bat_dispatches = counters.get("runs.mesh_fused", 0)
    _check(bat_dispatches == 2,
           f"odd-geometry observation ran as a singleton "
           f"(fused dispatches={bat_dispatches}: 1 batched + 1 odd)",
           failures)
    _check(bat_dispatches < seq_dispatches,
           f"dispatch count reduced: {bat_dispatches} batched vs "
           f"{seq_dispatches} sequential", failures)
    _check(counters.get("scheduler.succeeded") == B + 1,
           f"scheduler counters: succeeded={B + 1}", failures)

    bat_store = CandidateStore(os.path.join(bat_dir, "candidates.jsonl"))
    bat_fp = _store_fingerprint(bat_store, same)
    _check(bat_fp == seq_fp,
           "per-beam candidates BIT-IDENTICAL to sequential reference",
           failures)
    _check(len(bat_store.sources()) == B + 1,
           f"store holds candidates from all {B + 1} observations",
           failures)
    _check(summary["jobs_per_hour"] > 0, "jobs/hour computed", failures)

    from peasoup_tpu.obs.history import load_history

    serve_recs = load_history(history, kinds=["serve"])
    m = serve_recs[-1]["metrics"] if serve_recs else {}
    _check(m.get("batch") == B and m.get("batched_dispatches") == 1
           and m.get("batch_fill", 0) >= 2,
           "ledger record carries batch metrics "
           f"(batch={m.get('batch')} fill={m.get('batch_fill')})",
           failures)

    if failures:
        print(f"\nbatch-smoke: {len(failures)} check(s) FAILED",
              file=sys.stderr)
        return 1
    print("\nbatch-smoke: all checks passed")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
