"""CLI runner: dump an overview.xml as a text table
(`tools/peasoup_as_text.py`)."""

from .postprocess import as_text_main

if __name__ == "__main__":
    raise SystemExit(as_text_main())
