"""Dispatch-pipeline smoke test (``make pipeline-smoke``).

Phase 1 — serial reference: spool four same-geometry synthetic
observations with overrides that force the CHUNKED driver, drain them
at ``pipeline_depth=1`` (the pre-ISSUE-11 serial
dispatch→fetch→decode loop) and record the per-source store records
plus the run's ``device_duty_cycle`` ledger gauge.

Phase 2 — pipelined drain: re-spool the SAME observations and drain
at depth 2 (the default).  Assert the terminal state ISSUE 11
promises: every job lands in ``done/``, the ``chunk.pipeline_depth``
gauge records the requested depth, the ``device_duty_cycle`` gauge is
measured and sane on BOTH drains, the ``serve`` ledger record carries
it, and the per-source store records are BIT-IDENTICAL between the
two depths (the pipeline is pure scheduling — it must not change a
single candidate).

On CPU the duty-cycle numbers themselves prove only the ledger
plumbing (single-core XLA leaves little to overlap); on TPU the same
two drains show the depth-2 duty gain directly.

Exit status 0 only if every assertion holds — CI-gateable like
``batch-smoke``.
"""

from __future__ import annotations

import argparse
import os
import shutil
import sys


def _write_synthetic(path: str, nsamps: int = 4096, nchans: int = 16,
                     seed: int = 0) -> str:
    """A small 8-bit filterbank with a pulse train (same recipe as
    batch_smoke so the smokes exercise identical observations).  Thin
    wrapper over the injection synthesizer's shared smoke recipe
    (byte-identical to the historical private helper)."""
    from peasoup_tpu.obs.injection import smoke_observation

    smoke_observation(path, nsamps=nsamps, nchans=nchans, seed=seed)
    return path


def _check(ok: bool, what: str, failures: list[str]) -> None:
    print(("PASS " if ok else "FAIL ") + what)
    if not ok:
        failures.append(what)


def _store_fingerprint(store, sources) -> dict:
    """Per-source candidate tuples, order-normalised — the bit-identity
    comparison key across pipeline depths."""
    out = {}
    for src in sources:
        out[os.path.basename(src)] = sorted(
            (r["dm"], r["acc"], r["freq"], r["snr"], r["folded_snr"],
             r["nh"])
            for r in store.records(source=src)
        )
    return out


def _drain(jobs_dir, history, sources, overrides, failures, label):
    """Spool ``sources`` with ``overrides``, drain, and return
    (fingerprint, gauges, counters)."""
    from peasoup_tpu.obs.metrics import REGISTRY
    from peasoup_tpu.serve import CandidateStore, JobSpool, SurveyWorker

    REGISTRY.reset()
    spool = JobSpool(jobs_dir)
    for path in sources:
        spool.submit(path, overrides)
    SurveyWorker(spool, history_path=history,
                 sleeper=lambda s: None).drain()
    _check(spool.counts()["done"] == len(sources),
           f"{label}: {len(sources)} jobs in done/", failures)
    snap = REGISTRY.snapshot()
    store = CandidateStore(os.path.join(jobs_dir, "candidates.jsonl"))
    fp = _store_fingerprint(store, sources)
    _check(all(fp.values()),
           f"{label}: candidates found in every observation", failures)
    return fp, snap["gauges"], snap["counters"]


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="peasoup-tpu-pipeline-smoke",
        description="Peasoup-TPU - dispatch-pipeline smoke test",
    )
    p.add_argument("--dir", default="/tmp/peasoup-pipeline-smoke",
                   help="scratch directory (wiped)")
    p.add_argument("--jobs", type=int, default=4,
                   help="number of same-geometry observations")
    p.add_argument("--depth", type=int, default=2,
                   help="pipeline depth for the pipelined drain")
    args = p.parse_args(argv)

    shutil.rmtree(args.dir, ignore_errors=True)
    os.makedirs(args.dir)
    history = os.path.join(args.dir, "history.jsonl")

    B = max(2, args.jobs)
    depth = max(2, args.depth)
    # dm_chunk forces the chunked driver (the pipeline's home turf);
    # small values give several chunks per observation even at this
    # synthetic scale
    base = {"dm_end": 20.0, "min_snr": 6.0, "npdmp": 0, "limit": 10,
            "dm_chunk": 4, "accel_block": 1}
    sources = [
        _write_synthetic(os.path.join(args.dir, f"obs{i}.fil"), seed=i)
        for i in range(B)
    ]
    failures: list[str] = []

    # ---- phase 1: serial reference (pipeline_depth=1) ----------------
    fp1, g1, _ = _drain(
        os.path.join(args.dir, "jobs_d1"), history, sources,
        dict(base, pipeline_depth=1), failures, "depth-1 reference")
    _check(g1.get("chunk.pipeline_depth") == 1,
           "depth-1 drain recorded chunk.pipeline_depth=1", failures)
    _check(0.0 <= g1.get("device_duty_cycle", -1.0) <= 1.5,
           f"depth-1 device_duty_cycle measured "
           f"({g1.get('device_duty_cycle')})", failures)

    # ---- phase 2: pipelined drain (pipeline_depth=depth) -------------
    fp2, g2, _ = _drain(
        os.path.join(args.dir, "jobs_d2"), history, sources,
        dict(base, pipeline_depth=depth), failures,
        f"depth-{depth} drain")
    _check(g2.get("chunk.pipeline_depth") == depth,
           f"pipelined drain recorded chunk.pipeline_depth={depth}",
           failures)
    _check(0.0 <= g2.get("device_duty_cycle", -1.0) <= 1.5,
           f"depth-{depth} device_duty_cycle measured "
           f"({g2.get('device_duty_cycle')})", failures)

    _check(fp1 == fp2,
           "per-source candidates BIT-IDENTICAL across pipeline depths",
           failures)

    from peasoup_tpu.obs.history import load_history

    serve_recs = load_history(history, kinds=["serve"])
    m = serve_recs[-1]["metrics"] if serve_recs else {}
    _check("device_duty_cycle" in m,
           f"serve ledger record carries device_duty_cycle "
           f"({m.get('device_duty_cycle')})", failures)

    if failures:
        print(f"\npipeline-smoke: {len(failures)} check(s) FAILED",
              file=sys.stderr)
        return 1
    print("\npipeline-smoke: all checks passed")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
