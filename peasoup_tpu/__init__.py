"""peasoup_tpu — a TPU-native pulsar acceleration-search framework.

A from-scratch re-design of the capabilities of the CUDA ``peasoup``
pipeline (reference: xiaobotianxie/peasoup) for TPU hardware using
JAX/XLA.  The search chain — incoherent dedispersion over a DM-trial
grid, red-noise whitening, time-domain acceleration resampling,
interbinned power spectra, harmonic summing, peak finding, candidate
distillation/scoring and phase folding with PDMP-style optimisation —
runs as jitted XLA programs with the DM x acceleration trial grid
mapped onto batch axes and (multi-chip) a ``jax.sharding.Mesh``.

Layout:
    io/        SIGPROC filterbank/time-series readers and writers
    ops/       numerical kernels (jnp/XLA; exact reference numerics)
    search/    the search pipeline, plans, distillers, scorer, folder
    parallel/  device-mesh sharding of the trial grid
    output/    overview.xml + candidates.peasoup writers/readers
    native/    C++ helpers (bit unpacking) with NumPy fallbacks
    obs/       run telemetry: metrics registry, JSONL event log,
               machine-readable run_report.json
    analysis/  peasoup-lint: AST rule engine + jaxpr invariant checker
               (``python -m peasoup_tpu.analysis``)
    serve/     survey scheduler: durable job spool, retrying workers
               with observation prefetch, cross-run candidate store
               (``python -m peasoup_tpu.serve``)
    errors     typed exception hierarchy (the reference's ErrorChecker)
"""

import jax as _jax

# The acceleration-resampling index ramp (ops/resample.py) needs true
# float64: i*(i-n) reaches ~2^45 for 2^23-point series and a 1-sample
# index error moves power between Fourier bins. Everything else is kept
# explicitly float32/bfloat16.
_jax.config.update("jax_enable_x64", True)

# Persistent compilation cache: the fused/chunked search programs take
# minutes of XLA/Mosaic compile at production shapes; caching makes
# every rerun (and the escalation rebuilds) pay it once per shape.
import os as _os

_cache_dir = _os.environ.get(
    "PEASOUP_TPU_COMPILE_CACHE",
    _os.path.join(_os.path.expanduser("~"), ".cache", "peasoup_tpu_xla"),
)
if _cache_dir and _cache_dir != "0":
    try:
        _jax.config.update("jax_compilation_cache_dir", _cache_dir)
        _jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)
    except Exception:  # older jax without the knobs: harmless
        pass

__version__ = "0.1.0"
