"""peasoup_tpu — a TPU-native pulsar acceleration-search framework.

A from-scratch re-design of the capabilities of the CUDA ``peasoup``
pipeline (reference: xiaobotianxie/peasoup) for TPU hardware using
JAX/XLA.  The search chain — incoherent dedispersion over a DM-trial
grid, red-noise whitening, time-domain acceleration resampling,
interbinned power spectra, harmonic summing, peak finding, candidate
distillation/scoring and phase folding with PDMP-style optimisation —
runs as jitted XLA programs with the DM x acceleration trial grid
mapped onto batch axes and (multi-chip) a ``jax.sharding.Mesh``.

Layout:
    io/        SIGPROC filterbank/time-series readers and writers
    ops/       numerical kernels (jnp/XLA; exact reference numerics)
    search/    the search pipeline, plans, distillers, scorer, folder
    parallel/  device-mesh sharding of the trial grid
    output/    overview.xml + candidates.peasoup writers/readers
    native/    C++ helpers (bit unpacking) with NumPy fallbacks
"""

__version__ = "0.1.0"
