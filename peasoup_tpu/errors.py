"""Typed exception layer (the reference's ErrorChecker, pythonised).

The reference centralises failure detection in a static checker class
(`/root/reference/include/utils/exceptions.hpp:13-153`) that turns
dedisp/CUDA/cuFFT status codes and bad file streams into
`std::runtime_error`s with context.  On TPU there are no status codes
to poll — XLA raises on its own — so the equivalent surface is a small
hierarchy of typed exceptions raised at the framework's guard sites,
so callers can catch a *class* of failure (bad config vs bad input
file vs HBM budget vs numeric-domain limit) instead of string-matching
``ValueError``s.

Every class also subclasses the builtin its guard historically raised
(``ValueError`` / ``OSError``), so existing ``except ValueError``
callers and tests keep working.
"""


class PeasoupError(Exception):
    """Base class for all peasoup_tpu errors."""


class ConfigError(PeasoupError, ValueError):
    """Invalid or inconsistent :class:`SearchConfig` / CLI options
    (empty DM list, bad subband mode, negative acc_step, ...)."""


class InputFileError(PeasoupError, OSError, ValueError):
    """Malformed or unreadable input file (SIGPROC header, zap/kill
    lists, candidate binaries) — the reference's check_file_error.
    Subclasses both ``OSError`` (its natural category) and
    ``ValueError`` (what the sigproc guards historically raised)."""


class HBMBudgetError(PeasoupError, ValueError):
    """The requested search cannot fit the configured
    ``hbm_budget_gb`` even after chunking (reference analogue: cudaMalloc
    failure surfaced by check_cuda_error)."""


class DomainError(PeasoupError, ValueError):
    """Numerically out-of-domain request: the algorithm's validity
    conditions do not hold for these parameters (e.g. the staircase
    resampler's ``4*max_shift < n`` bound, f32-exact packing limits)."""


class CheckpointError(PeasoupError, ValueError):
    """Corrupt or torn checkpoint/resume state."""


class AdmissionError(PeasoupError, RuntimeError):
    """The spool refused a submit under admission control
    (serve/queue.py): either the pending backlog is past the configured
    knee, or the tenant's token-bucket rate limit is exhausted.  The
    job was NOT enqueued; ``retry_after_s`` hints when a resubmit can
    succeed (0.0 = unknown, re-check the backlog)."""

    def __init__(self, message: str, *, tenant: str = "",
                 reason: str = "", retry_after_s: float = 0.0):
        super().__init__(message)
        self.tenant = str(tenant)
        self.reason = str(reason)
        self.retry_after_s = float(retry_after_s)
