"""overview.xml writer.

Format-compatible with the reference's minimal XML tree writer
(`include/utils/xml_util.hpp:13-91` + the section layout of
`include/utils/output_stats.hpp:17-218`): 15-significant-digit values,
single-quoted attributes, two-space indentation, ISO-8859-1 prologue —
so the reference's own ``tools/peasoup_tools.py`` can parse our output
unchanged.
"""

from __future__ import annotations

import getpass
import time

import numpy as np


def _fmt(value) -> str:
    if isinstance(value, bool):
        return str(int(value))
    if isinstance(value, (float, np.floating)):
        return f"{float(value):.15g}"
    return str(value)


class XMLElement:
    def __init__(self, name: str, value=None):
        self.name = name
        self.attributes: dict[str, str] = {}
        self.children: list[XMLElement] = []
        self.text = "" if value is None else _fmt(value)

    def append(self, child: "XMLElement") -> "XMLElement":
        self.children.append(child)
        return child

    def add_attribute(self, key: str, value) -> None:
        self.attributes[key] = f"'{_fmt(value)}'"

    def set_text(self, value) -> None:
        self.text = _fmt(value)

    def to_string(self, header: bool = False, level: int = 0) -> str:
        parts = []
        if header:
            parts.append("<?xml version='1.0' encoding='ISO-8859-1'?>\n")
        indent = "  " * level
        attrs = "".join(f" {k}={v}" for k, v in self.attributes.items())
        parts.append(f"{indent}<{self.name}{attrs}>")
        if not self.children:
            parts.append(self.text)
        else:
            parts.append("\n")
            for child in self.children:
                parts.append(child.to_string(False, level + 1))
            parts.append(indent)
        parts.append(f"</{self.name}>\n")
        return "".join(parts)


class OutputFileWriter:
    """Build the overview.xml report (`output_stats.hpp:17-218`)."""

    def __init__(self):
        self.root = XMLElement("peasoup_search")

    def to_string(self) -> str:
        return self.root.to_string(header=True)

    def to_file(self, filename: str) -> None:
        with open(filename, "w", encoding="latin-1") as f:
            f.write(self.to_string())

    def add_misc_info(self) -> None:
        info = self.root.append(XMLElement("misc_info"))
        try:
            user = getpass.getuser()
        except Exception:
            user = "unknown"
        info.append(XMLElement("username", user))
        t = time.time()
        info.append(
            XMLElement("local_datetime",
                       time.strftime("%Y-%m-%d-%H:%M", time.localtime(t)))
        )
        info.append(
            XMLElement("utc_datetime",
                       time.strftime("%Y-%m-%d-%H:%M", time.gmtime(t)))
        )

    def add_header(self, hdr) -> None:
        el = self.root.append(XMLElement("header_parameters"))
        el.append(XMLElement("source_name", hdr.source_name))
        el.append(XMLElement("rawdatafile", hdr.rawdatafile))
        for key in ("az_start", "za_start", "src_raj", "src_dej", "tstart",
                    "tsamp", "period", "fch1", "foff", "nchans",
                    "telescope_id", "machine_id", "data_type", "ibeam",
                    "nbeams", "nbits", "barycentric", "pulsarcentric",
                    "nbins", "nsamples", "nifs", "npuls", "refdm"):
            el.append(XMLElement(key, getattr(hdr, key)))
        el.append(XMLElement("signed", int(hdr.signed_data)))

    def add_search_parameters(self, cfg) -> None:
        el = self.root.append(XMLElement("search_parameters"))
        el.append(XMLElement("infilename", cfg.infilename))
        el.append(XMLElement("outdir", cfg.outdir))
        el.append(XMLElement("killfilename", cfg.killfilename))
        el.append(XMLElement("zapfilename", cfg.zapfilename))
        if getattr(cfg, "dm_file", ""):
            el.append(XMLElement("dm_file", cfg.dm_file))
        el.append(XMLElement("max_num_threads", cfg.max_num_threads))
        el.append(XMLElement("size", cfg.size))
        for key in ("dm_start", "dm_end", "dm_tol", "dm_pulse_width",
                    "acc_start", "acc_end", "acc_tol", "acc_pulse_width",
                    "boundary_5_freq", "boundary_25_freq", "nharmonics",
                    "npdmp", "min_snr", "min_freq", "max_freq", "max_harm",
                    "freq_tol", "verbose", "progress_bar"):
            el.append(XMLElement(key, getattr(cfg, key)))

    def add_dm_list(self, dms) -> None:
        el = self.root.append(XMLElement("dedispersion_trials"))
        el.add_attribute("count", len(dms))
        for ii, dm in enumerate(dms):
            trial = el.append(XMLElement("trial", float(dm)))
            trial.add_attribute("id", ii)

    def add_acc_list(self, accs, dm=0) -> None:
        el = self.root.append(XMLElement("acceleration_trials"))
        el.add_attribute("count", len(accs))
        el.add_attribute("DM", dm)
        for ii, acc in enumerate(accs):
            trial = el.append(XMLElement("trial", float(acc)))
            trial.add_attribute("id", ii)

    def add_device_info(self, devices=None) -> None:
        """TPU stand-in for the reference's cuda_device_parameters."""
        import jax

        el = self.root.append(XMLElement("device_parameters"))
        el.append(XMLElement("backend", jax.default_backend()))
        el.append(XMLElement("jax_version", jax.__version__))
        devices = devices if devices is not None else jax.devices()
        for ii, dev in enumerate(devices):
            d = el.append(XMLElement("device"))
            d.add_attribute("id", ii)
            d.append(XMLElement("name", str(dev.device_kind)))
            d.append(XMLElement("platform", str(dev.platform)))

    def add_provenance(self, prov: dict) -> None:
        """``<provenance>`` block (obs/lineage.py, ISSUE 19): the
        producing run's identity — run id, git sha, geometry
        fingerprint, trial lattice (requested and actual), host — so
        any candidate in this file can be traced back through the
        lineage ledger with the ``why`` verb."""
        if not prov:
            return
        el = self.root.append(XMLElement("provenance"))
        for key in ("run", "git_sha", "geometry", "lattice",
                    "lattice_requested", "host"):
            if prov.get(key) is not None:
                el.append(XMLElement(key, prov[key]))

    def add_candidates(self, candidates, byte_mapping,
                       cand_ids=None) -> None:
        el = self.root.append(XMLElement("candidates"))
        for ii, c in enumerate(candidates):
            cand = el.append(XMLElement("candidate"))
            cand.add_attribute("id", ii)
            if cand_ids is not None:
                # lineage join key (ISSUE 19): the content-derived id
                # the `why` verb resolves, distinct from the ordinal
                cand.append(XMLElement("candidate_id", cand_ids[ii]))
            cand.append(XMLElement("period", 1.0 / c.freq))
            cand.append(XMLElement("opt_period", c.opt_period))
            cand.append(XMLElement("dm", c.dm))
            cand.append(XMLElement("acc", c.acc))
            cand.append(XMLElement("jerk", getattr(c, "jerk", 0.0)))
            cand.append(XMLElement("nh", c.nh))
            cand.append(XMLElement("snr", c.snr))
            cand.append(XMLElement("folded_snr", c.folded_snr))
            cand.append(XMLElement("is_adjacent", c.is_adjacent))
            cand.append(XMLElement("is_physical", c.is_physical))
            cand.append(XMLElement("ddm_count_ratio", c.ddm_count_ratio))
            cand.append(XMLElement("ddm_snr_ratio", c.ddm_snr_ratio))
            cand.append(XMLElement("nassoc", c.count_assoc()))
            cand.append(XMLElement("byte_offset", byte_mapping.get(ii, 0)))

    def add_timing_info(self, timers: dict) -> None:
        el = self.root.append(XMLElement("execution_times"))
        for key in sorted(timers):
            el.append(XMLElement(key, float(timers[key])))

    def add_telemetry(self, report: dict) -> None:
        """``<telemetry>`` section mirroring ``run_report.json``
        (obs/report.py) for the legacy XML toolchain: stage timers
        with the host/device split, counters, gauges and the event
        summary.  Names travel as ``name=''`` attributes — registry
        keys are dotted (``events.foo``), which XML tag names reject.
        """
        el = self.root.append(XMLElement("telemetry"))
        stages = el.append(XMLElement("stage_timers"))
        for name in sorted(report.get("stage_timers", {})):
            rec = report["stage_timers"][name]
            st = stages.append(XMLElement("stage"))
            st.add_attribute("name", name)
            st.add_attribute("count", rec["count"])
            st.append(XMLElement("host_s", float(rec["host_s"])))
            st.append(XMLElement("device_s", float(rec["device_s"])))
        counters = el.append(XMLElement("counters"))
        for name in sorted(report.get("counters", {})):
            c = counters.append(
                XMLElement("counter", int(report["counters"][name])))
            c.add_attribute("name", name)
        gauges = el.append(XMLElement("gauges"))
        for name in sorted(report.get("gauges", {})):
            g = gauges.append(
                XMLElement("gauge", float(report["gauges"][name])))
            g.add_attribute("name", name)
        events = el.append(XMLElement("events"))
        for kind in sorted(report.get("events", {})):
            ev = events.append(
                XMLElement("event", int(report["events"][kind])))
            ev.add_attribute("kind", kind)
        jit = report.get("jit", {})
        jel = el.append(XMLElement("jit"))
        jel.append(XMLElement("backend_compiles",
                              int(jit.get("backend_compiles", 0))))
        jel.append(XMLElement("compile_s",
                              float(jit.get("compile_s", 0.0))))
