from .xml_writer import XMLElement, OutputFileWriter
from .binary import write_candidate_binary, CandidateFileParser
from .parsers import OverviewFile
