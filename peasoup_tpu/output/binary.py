"""candidates.peasoup binary writer/reader.

Byte-compatible with `include/utils/output_stats.hpp:237-270`: per
candidate, an optional ``FOLD`` magic + int32 nbins + int32 nints +
float32 fold[nbins*nints], then int32 ndets followed by ndets packed
CandidatePOD records (float32 dm, int32 dm_idx, float32 acc, int32 nh,
float32 snr, float32 freq) — the candidate itself first, then its
flattened assoc tree in pre-order.

The jerk axis (ISSUE 13/14) extends the layout with an optional
``JRK0`` section between the fold block and the POD block: magic +
int32 ndets + ndets float32 jerks, one per POD record in the same
pre-order.  It is written ONLY when some detection carries a nonzero
jerk, so accel-only searches keep emitting reference-byte-compatible
files; the reader tolerates its absence (legacy files parse
unchanged, jerk column zero) and every hit row it returns carries a
``jerk`` field.
"""

from __future__ import annotations

import struct

import numpy as np

POD_DTYPE = np.dtype(
    [
        ("dm", "<f4"),
        ("dm_idx", "<i4"),
        ("acc", "<f4"),
        ("nh", "<i4"),
        ("snr", "<f4"),
        ("freq", "<f4"),
    ]
)

#: what the reader hands back: the reference POD plus the jerk column
#: (zero when the file predates the JRK0 section)
HIT_DTYPE = np.dtype(POD_DTYPE.descr + [("jerk", "<f4")])


def write_candidate_binary(candidates, filename: str) -> dict[int, int]:
    """Write candidates; returns {candidate_index: byte_offset}."""
    byte_mapping: dict[int, int] = {}
    with open(filename, "wb") as f:
        for ii, cand in enumerate(candidates):
            byte_mapping[ii] = f.tell()
            if cand.fold is not None and np.size(cand.fold) > 0:
                f.write(b"FOLD")
                f.write(struct.pack("<ii", cand.nbins, cand.nints))
                f.write(
                    np.ascontiguousarray(cand.fold, dtype=np.float32).tobytes()
                )
            dets = cand.collect()
            jerks = np.array(
                [float(getattr(d, "jerk", 0.0)) for d in dets],
                dtype=np.float32)
            if np.any(jerks):
                f.write(b"JRK0")
                f.write(struct.pack("<i", len(dets)))
                f.write(jerks.tobytes())
            f.write(struct.pack("<i", len(dets)))
            pods = np.empty(len(dets), dtype=POD_DTYPE)
            for jj, d in enumerate(dets):
                pods[jj] = (d.dm, d.dm_idx, d.acc, d.nh, d.snr, d.freq)
            f.write(pods.tobytes())
    return byte_mapping


class CandidateFileParser:
    """Reader mirroring ``tools/peasoup_tools.py:46-80``."""

    def __init__(self, filename: str):
        self._f = open(filename, "rb")

    def close(self) -> None:
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def cand_from_offset(self, offset: int):
        self._f.seek(offset)
        magic = self._f.read(4)
        fold = None
        if magic == b"FOLD":
            nbins, nints = struct.unpack("<ii", self._f.read(8))
            fold = np.frombuffer(
                self._f.read(4 * nbins * nints), dtype=np.float32
            ).reshape(nints, nbins)
        else:
            self._f.seek(offset)
        # second peek: the optional jerk section (absent in legacy
        # files — the first int32 there is ndets, never b"JRK0")
        pos = self._f.tell()
        jerks = None
        if self._f.read(4) == b"JRK0":
            (njerk,) = struct.unpack("<i", self._f.read(4))
            jerks = np.frombuffer(self._f.read(4 * njerk),
                                  dtype=np.float32)
        else:
            self._f.seek(pos)
        (count,) = struct.unpack("<i", self._f.read(4))
        pods = np.frombuffer(
            self._f.read(POD_DTYPE.itemsize * count), dtype=POD_DTYPE
        )
        hits = np.zeros(count, dtype=HIT_DTYPE)
        for name in POD_DTYPE.names:
            hits[name] = pods[name]
        if jerks is not None and len(jerks) == count:
            hits["jerk"] = jerks
        return fold, hits
