"""candidates.peasoup binary writer/reader.

Byte-compatible with `include/utils/output_stats.hpp:237-270`: per
candidate, an optional ``FOLD`` magic + int32 nbins + int32 nints +
float32 fold[nbins*nints], then int32 ndets followed by ndets packed
CandidatePOD records (float32 dm, int32 dm_idx, float32 acc, int32 nh,
float32 snr, float32 freq) — the candidate itself first, then its
flattened assoc tree in pre-order.
"""

from __future__ import annotations

import struct

import numpy as np

POD_DTYPE = np.dtype(
    [
        ("dm", "<f4"),
        ("dm_idx", "<i4"),
        ("acc", "<f4"),
        ("nh", "<i4"),
        ("snr", "<f4"),
        ("freq", "<f4"),
    ]
)


def write_candidate_binary(candidates, filename: str) -> dict[int, int]:
    """Write candidates; returns {candidate_index: byte_offset}."""
    byte_mapping: dict[int, int] = {}
    with open(filename, "wb") as f:
        for ii, cand in enumerate(candidates):
            byte_mapping[ii] = f.tell()
            if cand.fold is not None and np.size(cand.fold) > 0:
                f.write(b"FOLD")
                f.write(struct.pack("<ii", cand.nbins, cand.nints))
                f.write(
                    np.ascontiguousarray(cand.fold, dtype=np.float32).tobytes()
                )
            dets = cand.collect()
            f.write(struct.pack("<i", len(dets)))
            pods = np.empty(len(dets), dtype=POD_DTYPE)
            for jj, d in enumerate(dets):
                pods[jj] = (d.dm, d.dm_idx, d.acc, d.nh, d.snr, d.freq)
            f.write(pods.tobytes())
    return byte_mapping


class CandidateFileParser:
    """Reader mirroring ``tools/peasoup_tools.py:46-80``."""

    def __init__(self, filename: str):
        self._f = open(filename, "rb")

    def close(self) -> None:
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def cand_from_offset(self, offset: int):
        self._f.seek(offset)
        magic = self._f.read(4)
        fold = None
        if magic == b"FOLD":
            nbins, nints = struct.unpack("<ii", self._f.read(8))
            fold = np.frombuffer(
                self._f.read(4 * nbins * nints), dtype=np.float32
            ).reshape(nints, nbins)
        else:
            self._f.seek(offset)
        (count,) = struct.unpack("<i", self._f.read(4))
        hits = np.frombuffer(
            self._f.read(POD_DTYPE.itemsize * count), dtype=POD_DTYPE
        )
        return fold, hits
