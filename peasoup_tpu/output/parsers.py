"""overview.xml parser (modern replacement for
``tools/peasoup_tools.py:83-164``, stdlib-only)."""

from __future__ import annotations

import xml.etree.ElementTree as ET

import numpy as np

CAND_DTYPE = np.dtype(
    [
        ("cand_num", "<i4"),
        ("period", "<f4"),
        ("opt_period", "<f4"),
        ("dm", "<f4"),
        ("acc", "<f4"),
        ("jerk", "<f4"),
        ("nh", "<f4"),
        ("snr", "<f4"),
        ("folded_snr", "<f4"),
        ("is_adjacent", "u1"),
        ("is_physical", "u1"),
        ("ddm_count_ratio", "<f4"),
        ("ddm_snr_ratio", "<f4"),
        ("nassoc", "<i4"),
        ("byte_offset", "<i4"),
    ]
)


class OverviewFile:
    def __init__(self, filename: str):
        self._tree = ET.parse(filename)
        self._root = self._tree.getroot()
        self._candidates = self._root.find("candidates").findall("candidate")

    @property
    def ncands(self) -> int:
        return len(self._candidates)

    def section(self, name: str) -> dict:
        el = self._root.find(name)
        return {child.tag: child.text for child in el} if el is not None else {}

    def dm_list(self) -> np.ndarray:
        el = self._root.find("dedispersion_trials")
        return np.array([float(t.text) for t in el.findall("trial")])

    def acc_list(self) -> np.ndarray:
        el = self._root.find("acceleration_trials")
        return np.array([float(t.text) for t in el.findall("trial")])

    def as_array(self) -> np.ndarray:
        out = np.recarray(self.ncands, dtype=CAND_DTYPE)
        for rec, cand in zip(out, self._candidates):
            rec["cand_num"] = int(cand.attrib["id"])
            for tag, _ in CAND_DTYPE.descr:
                if tag != "cand_num":
                    # pre-jerk files have no <jerk> element: absent
                    # tags read as 0 so legacy output parses unchanged
                    el = cand.find(tag)
                    rec[tag] = float(el.text) if el is not None else 0.0
        return out

    def get_candidate(self, idx: int) -> dict:
        cand = self._candidates[idx]
        out = {"cand_num": int(cand.attrib["id"])}
        for tag, typename in CAND_DTYPE.descr:
            if tag != "cand_num":
                el = cand.find(tag)
                text = el.text if el is not None else "0"
                out[tag] = np.array([text]).astype(typename)[0]
        return out
