"""Raw profiler trace annotations — the NVTX-range equivalent.

The reference compiles NVTX push/pop ranges around "Dedisperse",
"DM-Loop", "Acceleration-Loop" and "Harmonic summing"
(`include/utils/nvtx.hpp:8-24`, `src/pipeline_multi.cu:144,207,318`).
On TPU the analogue is ``jax.profiler``: ``trace_range`` annotates a
host-side region so it shows up in TensorBoard/Perfetto traces captured
with ``start_trace``/``stop_trace`` (or the CLI's ``--profile_dir``).
Annotations are no-ops unless a trace is being captured.

NOTE: pipeline code must NOT call ``trace_range`` directly any more —
``peasoup_tpu.obs.trace.span`` is the one stage-timing API (it still
forwards the name to ``jax.profiler.TraceAnnotation``, and adds the
always-on span record, registry stage timer, HBM watermark and
Chrome-trace export).  Lint rule PSL006 enforces this outside
``obs/``; ``trace_range`` stays for external users and the profiler
start/stop helpers below.
"""

from __future__ import annotations

from contextlib import contextmanager


@contextmanager
def trace_range(name: str):
    """Named profiler range (PUSH_NVTX_RANGE/POP_NVTX_RANGE analogue)."""
    import jax.profiler

    with jax.profiler.TraceAnnotation(name):
        yield


def start_trace(log_dir: str) -> None:
    import jax.profiler

    jax.profiler.start_trace(log_dir)


def stop_trace() -> None:
    import jax.profiler

    jax.profiler.stop_trace()
