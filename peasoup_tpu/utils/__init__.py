"""Cross-cutting utilities: progress reporting, profiler tracing.

Equivalents of the reference's `include/utils/` aux layer
(`progress_bar.hpp`, `nvtx.hpp`, `stopwatch.hpp` — the timing map
itself lives in each driver's ``timers`` dict)."""

from .progress import ProgressBar
from .tracing import trace_range, start_trace, stop_trace
from .hostfetch import fetch_to_host
from .compilecache import enable_compile_cache

__all__ = [
    "ProgressBar", "trace_range", "start_trace", "stop_trace",
    "fetch_to_host", "enable_compile_cache",
]
