"""Sanctioned atomic-write helpers (ISSUE 17, PSL012).

Every durable artifact the serve and obs planes publish — spool
records, leases, admission state, status sidecars, run reports,
warehouse indexes, trace exports — must land with rename atomicity: a
killed writer leaves either the old file or the new one on disk,
never a torn half-write (OBSERVABILITY.md "Shared design rules").
Before this module each call site hand-rolled the same four lines
(tmp name, write, optional fsync, ``os.replace``), and lint rule
PSL012 could only pattern-match the idiom, not enforce it.  Now the
idiom lives here, **outside** ``serve/`` and ``obs/``, and PSL012
simply forbids any truncating ``open(path, "w")`` in those packages:
the only sanctioned spelling is a call into this module — the same
single-sanctioned-site scheme PSL008 uses for ``time.sleep``.

``fsync`` is opt-in per call because durability and latency trade off
per stream: the spool's job records fsync when ``PEASOUP_SPOOL_FSYNC``
says so, while high-frequency lease heartbeats deliberately never do
(rename atomicity alone is their contract; see serve/queue.py).
"""

from __future__ import annotations

import contextlib
import json
import os


def _replace_via_tmp(path: str, payload: str, *, fsync: bool,
                     encoding: str) -> None:
    path = str(path)
    tmp = path + f".tmp{os.getpid()}"
    try:
        with open(tmp, "w", encoding=encoding) as f:
            f.write(payload)
            if fsync:
                f.flush()
                os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def atomic_write_text(path: str, text: str, *, fsync: bool = False,
                      encoding: str = "utf-8") -> None:
    """Write ``text`` to ``path`` with rename atomicity.

    The payload lands in ``path + ".tmp<pid>"`` first (pid-suffixed so
    concurrent writers from different processes never clobber each
    other's tmp) and is renamed over ``path`` in one step.  With
    ``fsync=True`` the tmp file is flushed to stable storage before
    the rename — required where the artifact must survive power loss,
    skipped where rename atomicity alone is the contract.  The tmp
    file is best-effort removed on failure.
    """
    _replace_via_tmp(path, text, fsync=fsync, encoding=encoding)


def atomic_write_json(path: str, obj, *, fsync: bool = False,
                      indent: int | None = None, sort_keys: bool = False,
                      trailing_newline: bool = False,
                      default=None) -> None:
    """:func:`atomic_write_text` for a JSON document."""
    payload = json.dumps(obj, indent=indent, sort_keys=sort_keys,
                         default=default)
    if trailing_newline:
        payload += "\n"
    _replace_via_tmp(path, payload, fsync=fsync, encoding="utf-8")


@contextlib.contextmanager
def atomic_writer(path: str, *, fsync: bool = False,
                  encoding: str = "utf-8"):
    """Streaming :func:`atomic_write_text`: yields a writable text
    file object positioned on ``path + ".tmp<pid>"``; the tmp file is
    renamed over ``path`` only when the ``with`` body exits cleanly,
    and best-effort removed when it raises.  For artifacts too large
    to assemble in memory (sealed store segments, ISSUE 20) where the
    same killed-writer contract must hold: readers see the old file or
    the complete new one, never a prefix.
    """
    path = str(path)
    tmp = path + f".tmp{os.getpid()}"
    try:
        with open(tmp, "w", encoding=encoding) as f:
            yield f
            if fsync:
                f.flush()
                os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def fsync_dir(path: str) -> None:
    """Best-effort fsync of the directory holding ``path`` so the
    rename itself is durable, not just the file contents.  No-op on
    platforms/filesystems that refuse directory fds."""
    d = os.path.dirname(os.path.abspath(path))
    try:
        fd = os.open(d, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)
