"""Device->host fetch that works on multi-host (global) arrays."""

from __future__ import annotations

import numpy as np


def fetch_to_host(arr) -> np.ndarray:
    """Fetch a jax array to host memory, multi-host safe.

    A plain ``np.asarray`` raises on arrays spanning non-addressable
    devices; in that case every process all-gathers the global value
    over ICI/DCN first (`jax.experimental.multihost_utils`).  This is
    the TPU-native replacement for the reference's pthread-join +
    append merge (`src/pipeline_multi.cu:356-359`)."""
    if isinstance(arr, np.ndarray):
        return arr
    import jax

    if all(
        d.process_index == jax.process_index()
        for d in arr.sharding.device_set
    ):
        return np.asarray(arr)
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(arr, tiled=True))


def start_fetch(arr):
    """Begin the device->host copy of ``arr`` without blocking (the
    async half of the start-fetch/finish-fetch pair, ISSUE 11): the
    link transfer then overlaps whatever the host does next, and the
    eventual :func:`finish_fetch` finds the bytes already landed.

    Host arrays are already home; multi-host arrays (non-addressable
    shards) cannot start early — their allgather happens inside
    :func:`finish_fetch` — so both degrade to a no-op.  Returns
    ``arr`` for call-through use."""
    if isinstance(arr, np.ndarray):
        return arr
    import jax

    try:
        if all(
            d.process_index == jax.process_index()
            for d in arr.sharding.device_set
        ):
            arr.copy_to_host_async()
    except Exception:
        pass  # best-effort: finish_fetch blocks either way
    return arr


def finish_fetch(arr) -> np.ndarray:
    """Complete a fetch begun by :func:`start_fetch` (same semantics
    as :func:`fetch_to_host`; when the async copy already landed the
    conversion is near-free)."""
    return fetch_to_host(arr)


def put_global(arr, sharding):
    """``device_put`` that works for global shardings in multi-process
    runs.

    Multi-process ``jax.device_put`` verifies the value is identical on
    every process with an array equality check that trips on NaN
    padding (NaN != NaN) — and the accel grid is NaN-padded by design.
    ``make_array_from_callback`` assembles the same global array from
    per-shard slices without the check; all callers pass
    process-identical host values."""
    import jax

    if jax.process_count() == 1:
        return jax.device_put(arr, sharding)
    import numpy as np_

    host = np_.asarray(arr)
    return jax.make_array_from_callback(
        host.shape, sharding, lambda idx: host[idx]
    )
