"""Device->host fetch that works on multi-host (global) arrays."""

from __future__ import annotations

import numpy as np


def fetch_to_host(arr) -> np.ndarray:
    """Fetch a jax array to host memory, multi-host safe.

    A plain ``np.asarray`` raises on arrays spanning non-addressable
    devices; in that case every process all-gathers the global value
    over ICI/DCN first (`jax.experimental.multihost_utils`).  This is
    the TPU-native replacement for the reference's pthread-join +
    append merge (`src/pipeline_multi.cu:356-359`)."""
    if isinstance(arr, np.ndarray):
        return arr
    import jax

    if all(
        d.process_index == jax.process_index()
        for d in arr.sharding.device_set
    ):
        return np.asarray(arr)
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(arr, tiled=True))
