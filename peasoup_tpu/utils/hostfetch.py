"""Device->host fetch that works on multi-host (global) arrays."""

from __future__ import annotations

import numpy as np


def fetch_to_host(arr) -> np.ndarray:
    """Fetch a jax array to host memory, multi-host safe.

    A plain ``np.asarray`` raises on arrays spanning non-addressable
    devices; in that case every process all-gathers the global value
    over ICI/DCN first (`jax.experimental.multihost_utils`).  This is
    the TPU-native replacement for the reference's pthread-join +
    append merge (`src/pipeline_multi.cu:356-359`)."""
    if isinstance(arr, np.ndarray):
        return arr
    import jax

    if all(
        d.process_index == jax.process_index()
        for d in arr.sharding.device_set
    ):
        return np.asarray(arr)
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(arr, tiled=True))


def put_global(arr, sharding):
    """``device_put`` that works for global shardings in multi-process
    runs.

    Multi-process ``jax.device_put`` verifies the value is identical on
    every process with an array equality check that trips on NaN
    padding (NaN != NaN) — and the accel grid is NaN-padded by design.
    ``make_array_from_callback`` assembles the same global array from
    per-shard slices without the check; all callers pass
    process-identical host values."""
    import jax

    if jax.process_count() == 1:
        return jax.device_put(arr, sharding)
    import numpy as np_

    host = np_.asarray(arr)
    return jax.make_array_from_callback(
        host.shape, sharding, lambda idx: host[idx]
    )
