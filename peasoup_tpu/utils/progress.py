"""Terminal progress reporting with ETA and throughput.

Equivalent of the reference's pthread progress bar
(`include/utils/progress_bar.hpp:7-73`), which prints percent complete
and an ETA extrapolated from elapsed wall-clock.  Here progress is
driven by explicit ``update(done)`` calls from the search loop instead
of a polling thread, and the line carries done/total counts plus the
observed trials/s; ``finish()`` leaves a one-line run summary.
"""

from __future__ import annotations

import sys
import time


class ProgressBar:
    def __init__(self, total: int, label: str = "", stream=None,
                 width: int = 40, enabled: bool = True):
        self.total = max(int(total), 1)
        self.label = label
        self.stream = stream if stream is not None else sys.stderr
        self.width = width
        self.enabled = enabled
        self._start = None
        self._last_len = 0
        self._done = 0

    def start(self) -> None:
        self._start = time.time()
        self.update(0)

    def update(self, done: int) -> None:
        if not self.enabled:
            return
        if self._start is None:
            self._start = time.time()
        self._done = int(done)
        frac = min(done / self.total, 1.0)
        elapsed = time.time() - self._start
        eta = elapsed * (1.0 - frac) / frac if frac > 0 else float("inf")
        rate = done / elapsed if elapsed > 0 and done > 0 else 0.0
        nfill = int(frac * self.width)
        bar = "#" * nfill + "-" * (self.width - nfill)
        eta_s = f"{eta:6.1f}s" if eta != float("inf") else "   ?  "
        line = (f"\r{self.label}[{bar}] {done}/{self.total} "
                f"{100 * frac:5.1f}%  {rate:6.1f}/s  ETA {eta_s}")
        self.stream.write(line + " " * max(0, self._last_len - len(line)))
        self._last_len = len(line)
        self.stream.flush()

    def finish(self) -> None:
        if not self.enabled:
            return
        self.update(self.total)
        elapsed = time.time() - self._start if self._start else 0.0
        rate = self.total / elapsed if elapsed > 0 else 0.0
        self.stream.write(
            f"\n{self.label}{self.total} trials in {elapsed:.1f} s, "
            f"{rate:.1f} trials/s\n"
        )
        self.stream.flush()
