"""Opt-in persistent XLA compilation cache.

Remote-attached TPU compiles are expensive (the production chunk
program costs ~44 s, an escalated re-search shape ~1-2 min), and the
reference pays nothing comparable (nvcc compiles ahead of time).  JAX's
persistent compilation cache serialises compiled executables to disk
keyed by HLO hash, so every program shape is compiled at most once
*ever* per machine — across processes and runs.

Enabled by the CLI and the benchmarks (not on import: library users
may manage their own cache policy).  Harmless if the backend cannot
serialise executables — jax falls back to compiling as usual.
"""

from __future__ import annotations

import os


def enable_compile_cache(cache_dir: str | None = None) -> str | None:
    """Point jax at a persistent on-disk compilation cache.

    ``cache_dir`` defaults to ``$PEASOUP_XLA_CACHE`` or
    ``~/.cache/peasoup_tpu/xla``.  Returns the directory used, or None
    if the cache could not be enabled.  Either way the decision is
    recorded as a ``kind:"cache"`` compile-ledger record (plus the
    ``compile_cache.enabled`` counter when it engaged) so cache
    engagement is a queryable fact, not an invisible return value.
    """
    if cache_dir is None:
        cache_dir = os.environ.get("PEASOUP_XLA_CACHE") or os.path.join(
            os.path.expanduser("~"), ".cache", "peasoup_tpu", "xla"
        )
    try:
        import jax

        if jax.default_backend() == "cpu":
            # CPU AOT cache entries are machine-feature-pinned (XLA
            # warns about SIGILL on mismatch) and CPU compiles are
            # fast anyway — only accelerator executables are worth
            # persisting
            _record_cache(False, cache_dir)
            return None
        os.makedirs(cache_dir, exist_ok=True)

        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # cache everything that took a measurable compile: the default
        # 1 GB / 1 s floors would skip the many small-but-remote
        # programs whose round-trip latency is the actual cost
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        _record_cache(True, cache_dir)
        return cache_dir
    except Exception as exc:  # unwritable dir, unknown config, ...
        from ..obs.events import warn_event

        _record_cache(False, cache_dir)
        warn_event(
            "compile_cache_disabled",
            f"persistent compile cache disabled: {exc}",
            cache_dir=cache_dir,
        )
        return None


def _record_cache(enabled: bool, cache_dir: str) -> None:
    """Ledger whether the cache engaged (and where) — engagement was
    previously an invisible return value (ISSUE 18)."""
    try:
        from ..obs.compilation import record_cache_event

        record_cache_event(enabled, cache_dir)
    except Exception:
        pass
