"""Device-mesh parallelism for the trial grid.

TPU-native replacement of the reference's multi-GPU strategy: where
`src/pipeline_multi.cu:33-81` runs a mutex-guarded DM-trial work queue
over pthread workers (one per GPU) and merges candidate vectors after
join, here the DM axis is a named mesh axis:

* dedispersion is one jitted program whose delay table and output
  carry a ``NamedSharding`` over ``("dm",)`` — XLA partitions the
  channel sweep so each device produces only its DM rows (the input
  filterbank block is replicated, as dedisp's multi-GPU plan does);
* the search is a ``shard_map`` program: each device scans its local
  block of DM trials (whiten -> accel-batch search) and emits
  fixed-capacity peak buffers, which are device-local outputs of the
  same sharding — a single device->host gather replaces the pthread
  join + append of the reference;
* the dynamic DM dispenser becomes a static balanced assignment: DM
  trials cost the same per trial, and ragged accel lists are padded to
  a rectangle with a validity mask (SURVEY.md section 7).

On multi-host systems the same program runs under
``jax.distributed.initialize`` with a global mesh: the per-shard peak
buffers are all-gathered over ICI/DCN by the final host transfer, and
candidate distillation remains a (cheap) host-side pass.
"""

from __future__ import annotations

from functools import partial

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.dedisperse import dedisperse
from ..search.pipeline import (
    PulsarSearch,
    SearchResult,
    search_one_accel,
    whiten_core,
)
from ..search.plan import SearchConfig
from ..data.candidates import Candidate, CandidateCollection
from ..io.unpack import pack_bits
from ..ops.peaks import segmented_unique_peaks


from ..utils.hostfetch import fetch_to_host  # re-exported; also used below


def make_mesh(max_devices: int | None = None, axis: str = "dm") -> Mesh:
    devs = jax.devices()
    if max_devices:
        devs = devs[: max_devices]
    return Mesh(np.array(devs), (axis,))


def _search_dm_row(tim, accs_row, birdies, widths, *, bin_width, tsamp,
                   nharms, bounds, capacity, min_snr, b5, b25, use_zap,
                   max_shift=None):
    """Whiten one DM trial and search its (NaN-padded) accel batch.

    Shared body of both sharded programs: returns (idxs, snrs, counts)
    with padded accel slots fully masked out.
    """
    tim_w, mean, std = whiten_core(
        tim, birdies, widths, bin_width, b5, b25, use_zap
    )
    search = lambda a: search_one_accel(
        tim_w, jnp.nan_to_num(a), mean, std, tsamp, nharms, bounds,
        capacity, min_snr, max_shift,
    )
    idxs, snrs, counts = jax.vmap(search)(accs_row)
    valid = ~jnp.isnan(accs_row)
    idxs = jnp.where(valid[:, None, None], idxs, -1)
    snrs = jnp.where(valid[:, None, None], snrs, 0.0)
    counts = jnp.where(valid[:, None], counts, 0)
    return idxs, snrs, counts


def sharded_search_program(
    mesh: Mesh,
    size: int,
    bin_width: float,
    tsamp: float,
    nharms: int,
    bounds: tuple,
    capacity: int,
    min_snr: float,
    b5: float,
    b25: float,
    use_zap: bool,
):
    """Build the jitted shard_map search over the ``dm`` mesh axis.

    Returns a callable (trials, accs, birdies, widths) -> (idxs, snrs,
    counts) where trials is (ndm_padded, size) sharded over dm, accs is
    (ndm_padded, naccel_max) with NaN padding, and outputs have leading
    dim ndm_padded (sharded over dm).
    """

    def per_dm(carry, inp):
        tim, accs = inp
        birdies, widths = carry
        outs = _search_dm_row(
            tim, accs, birdies, widths, bin_width=bin_width, tsamp=tsamp,
            nharms=nharms, bounds=bounds, capacity=capacity,
            min_snr=min_snr, b5=b5, b25=b25, use_zap=use_zap,
        )
        return carry, outs

    def shard_fn(trials, accs, birdies, widths):
        # trials: (ndm_local, size); accs: (ndm_local, naccel_max)
        _, outs = lax.scan(per_dm, (birdies, widths), (trials, accs))
        return outs

    mapped = jax.shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(P("dm", None), P("dm", None), P(None), P(None)),
        out_specs=(P("dm", None, None), P("dm", None, None), P("dm", None)),
    )
    return jax.jit(mapped)


from functools import lru_cache


@lru_cache(maxsize=32)
def build_fused_search(
    mesh: Mesh,
    *,
    nbits: int,
    nchans: int,
    nsamps: int,
    out_nsamps: int,
    size: int,
    bin_width: float,
    tsamp: float,
    nharms: int,
    bounds: tuple,
    capacity: int,
    min_snr: float,
    b5: float,
    b25: float,
    use_zap: bool,
    use_killmask: bool,
    compact_k: int,
    max_shift: int | None = None,
):
    """One jitted program for the ENTIRE device side of the search.

    packed filterbank bytes (replicated) -> device bit-unpack ->
    dedisperse (DM rows sharded over the mesh) -> per-DM whiten ->
    batched accel trials -> harmonic sums -> thresholded peaks ->
    global compaction of all (dm, accel, level) peak buffers into one
    small tagged buffer per shard.

    This exists because device->host transfers and program dispatches
    dominate wall-clock on a remote-attached TPU: the reference pays
    neither (its host loop talks to a local PCIe GPU per DM trial,
    `src/pipeline_multi.cu:145-244`), so the TPU-native design moves the
    whole search into one dispatch and ships home ONE packed f32 buffer
    per shard (ints bitcast), laid out as:

    * ``[0:compact_k]``  spectrum bin indices (int32 bitcast)
    * ``[compact_k:2k]`` SNR values (f32)
    * ``[2k:2k+nspec]``  per-spectrum above-threshold counts
      (ndm_local*naccel*nlevels int32 bitcast; overflow check + the
      key to reconstructing each entry's (dm, accel, level) tag)
    * ``[-1]``           true total valid count (int32 bitcast)

    plus ``trials`` (ndm_local, out_nsamps) f32 — full-width, staying
    device-resident for the folding phase; never copied to host.

    Returns a jitted callable
    ``fn(raw, delays, killmask, accs, birdies, widths)``.
    """
    from ..ops.unpack import unpack_bits_device

    nlevels = nharms + 1

    def shard_fn(raw, delays, killmask, accs, birdies, widths):
        vals = unpack_bits_device(raw, nbits)[: nsamps * nchans]
        data = vals.reshape(nsamps, nchans).T.astype(jnp.float32)
        if use_killmask:
            data = data * killmask[:, None]
        # full-width trials are returned for the folding phase (which
        # must see prev_power_of_two(out_nsamps) real samples exactly
        # like the single-device path, `folder.hpp:352-406`); the
        # search itself runs on the fft-size-truncated/padded view
        trials = dedisperse(data, delays, out_nsamps)
        if out_nsamps >= size:
            trials_sz = trials[:, :size]
        else:
            pad_mean = jnp.mean(trials, axis=1, keepdims=True)
            pad = jnp.broadcast_to(
                pad_mean, (trials.shape[0], size - out_nsamps)
            )
            trials_sz = jnp.concatenate([trials, pad], axis=1)

        def per_dm(tim, accs_row):
            return _search_dm_row(
                tim, accs_row, birdies, widths, bin_width=bin_width,
                tsamp=tsamp, nharms=nharms, bounds=bounds,
                capacity=capacity, min_snr=min_snr, b5=b5, b25=b25,
                use_zap=use_zap, max_shift=max_shift,
            )

        # vmap (not scan): all local DM trials are one batch of FFTs /
        # gathers / top_ks, keeping the VPU/MXU fed instead of running
        # 59 small sequential program iterations
        idxs, snrs, counts = jax.vmap(per_dm)(trials_sz, accs)

        flat_bin = idxs.reshape(-1)
        flat_snr = snrs.reshape(-1)
        n = flat_bin.shape[0]
        pos = jnp.arange(n, dtype=jnp.int32)
        valid = flat_bin >= 0
        sentinel = jnp.int32(-n - 1)
        score = jnp.where(valid, -pos, sentinel)
        top, _ = lax.top_k(score, compact_k)  # first compact_k valid slots
        got = top != sentinel
        sel = jnp.where(got, -top, 0)
        # the host reconstructs each entry's (dm, accel, level, slot) tag
        # from ``counts`` alone: valid slots appear in flat spectrum
        # order, so only bins+snrs are shipped
        sel_bin = jnp.where(got, flat_bin[sel], -1)
        sel_snr = jnp.where(got, flat_snr[sel], 0.0).astype(jnp.float32)
        nvalid = jnp.sum(valid, dtype=jnp.int32)[None]
        # pack everything into ONE f32 buffer (ints bitcast) so the
        # host pays a single device->host round trip
        packed = jnp.concatenate([
            lax.bitcast_convert_type(sel_bin, jnp.float32),
            sel_snr,
            lax.bitcast_convert_type(counts.reshape(-1), jnp.float32),
            lax.bitcast_convert_type(nvalid, jnp.float32),
        ])
        return packed, trials

    mapped = jax.shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(
            P(), P("dm", None), P(), P("dm", None), P(), P(),
        ),
        out_specs=(P("dm"), P("dm", None)),
    )
    return jax.jit(mapped)


class MeshPulsarSearch(PulsarSearch):
    """Multi-device search: DM trials sharded over a 1-D device mesh."""

    def __init__(self, fil, config: SearchConfig, max_devices=None,
                 mesh: Mesh | None = None):
        super().__init__(fil, config)
        self.mesh = mesh if mesh is not None else make_mesh(max_devices)
        self.ndev = self.mesh.devices.size

    def _padded_trial_count(self) -> int:
        ndm = len(self.dm_list)
        return int(np.ceil(ndm / self.ndev)) * self.ndev

    def dedisperse_sharded(self) -> jax.Array:
        """Dedisperse with the DM axis sharded across the mesh."""
        ndm = len(self.dm_list)
        ndm_p = self._padded_trial_count()
        delays = np.zeros((ndm_p, self.fil.nchans), np.int32)
        delays[:ndm] = self.delays
        data = jnp.asarray(self.fil.data.T, dtype=jnp.float32)
        km = (
            jnp.asarray(self.killmask)
            if self.killmask is not None
            else None
        )
        rep = NamedSharding(self.mesh, P())
        shard = NamedSharding(self.mesh, P("dm", None))
        data = jax.device_put(data, rep)
        delays_d = jax.device_put(jnp.asarray(delays), shard)
        fn = jax.jit(
            partial(dedisperse, out_nsamps=self.out_nsamps),
            out_shardings=shard,
        )
        if km is not None:
            return fn(data, delays_d, killmask=jax.device_put(km, rep))
        return fn(data, delays_d)

    def _device_inputs(self, acc_lists, ndm_p: int, namax: int):
        """Build (once) and cache the device-resident static inputs.

        The filterbank bytes, delay table, killmask and accel grid are
        constant for a given search object, so they live in HBM across
        ``run()`` calls — re-uploading them per run costs more than the
        entire device search on a remote-attached TPU.
        """
        if getattr(self, "_dev_inputs", None) is not None:
            return self._dev_inputs
        ndm = len(self.dm_list)
        accs = np.full((ndm_p, namax), np.nan, np.float32)
        for i, a in enumerate(acc_lists):
            accs[i, : len(a)] = a
        delays = np.zeros((ndm_p, self.fil.nchans), np.int32)
        delays[:ndm] = self.delays
        killmask = (
            self.killmask
            if self.killmask is not None
            else np.ones(self.fil.nchans, np.float32)
        )
        nbits = self.fil.header.nbits
        if nbits == 32:  # float data: nothing to pack
            raw = np.ascontiguousarray(self.fil.data, np.float32).ravel()
        else:
            raw = pack_bits(self.fil.data.ravel(), nbits)
        rep = NamedSharding(self.mesh, P())
        shard = NamedSharding(self.mesh, P("dm", None))
        self._dev_inputs = (
            jax.device_put(jnp.asarray(raw), rep),
            jax.device_put(jnp.asarray(delays), shard),
            jax.device_put(jnp.asarray(killmask, dtype=jnp.float32), rep),
            jax.device_put(jnp.asarray(accs), shard),
            jax.device_put(jnp.asarray(self.birdies), rep),
            jax.device_put(jnp.asarray(self.bwidths), rep),
        )
        return self._dev_inputs

    def run(self) -> SearchResult:
        import time
        import warnings

        cfg = self.config
        timers: dict[str, float] = {}
        t_total = time.time()

        ndm = len(self.dm_list)

        # checkpoint resume: the mesh search is a single dispatch, so a
        # complete checkpoint skips the device program entirely (trials
        # are re-dedispersed only if folding needs them)
        ckpt, ckpt_done = self._make_checkpoint()
        if ckpt and len(ckpt_done) == ndm:
            timers["dedispersion"] = 0.0
            timers["searching"] = 0.0
            dm_cands = CandidateCollection()
            for ii in range(ndm):
                dm_cands.append(ckpt_done[ii])
            trials = (
                self.dedisperse_sharded() if cfg.npdmp > 0 else None
            )
            result = self._finalise(dm_cands, trials, timers, t_total)
            ckpt.remove()
            return result
        ndm_p = self._padded_trial_count()
        ndev = self.ndev
        ndm_local = ndm_p // ndev
        acc_lists = [
            self.acc_plan.generate_accel_list(dm) for dm in self.dm_list
        ]
        namax = max(len(a) for a in acc_lists)
        nlevels = cfg.nharmonics + 1
        cap = cfg.peak_capacity
        # clamp to the shard's total slot count (small configs)
        compact_k = min(
            cfg.compact_capacity, ndm_local * namax * nlevels * cap
        )

        program = build_fused_search(
            self.mesh,
            nbits=self.fil.header.nbits,
            nchans=self.fil.nchans,
            nsamps=self.fil.nsamps,
            out_nsamps=self.out_nsamps,
            size=self.size,
            bin_width=self.bin_width,
            tsamp=float(self.fil.tsamp),
            nharms=cfg.nharmonics,
            bounds=self.bounds,
            capacity=cap,
            min_snr=cfg.min_snr,
            b5=cfg.boundary_5_freq,
            b25=cfg.boundary_25_freq,
            use_zap=bool(len(self.birdies)),
            use_killmask=self.killmask is not None,
            compact_k=compact_k,
            max_shift=self.max_shift,
        )

        from ..utils import trace_range

        t0 = time.time()
        with trace_range("Fused-Search"):
            inputs = self._device_inputs(acc_lists, ndm_p, namax)
            packed, trials = program(*inputs)
            # ONE gather over ICI/DCN -> host; ``trials`` stays on device
            packed = fetch_to_host(packed)
        nspec_local = ndm_local * namax * nlevels
        blk_len = 2 * compact_k + nspec_local + 1
        sel_bin = np.empty(ndev * compact_k, np.int32)
        sel_snr = np.empty(ndev * compact_k, np.float32)
        counts = np.empty((ndm_p, namax, nlevels), np.int32)
        nvalid = np.empty(ndev, np.int32)
        for sidx in range(ndev):
            blk = packed[sidx * blk_len : (sidx + 1) * blk_len]
            sel_bin[sidx * compact_k : (sidx + 1) * compact_k] = (
                blk[:compact_k].view(np.int32)
            )
            sel_snr[sidx * compact_k : (sidx + 1) * compact_k] = (
                blk[compact_k : 2 * compact_k]
            )
            counts[sidx * ndm_local : (sidx + 1) * ndm_local] = (
                blk[2 * compact_k : 2 * compact_k + nspec_local]
                .view(np.int32)
                .reshape(ndm_local, namax, nlevels)
            )
            nvalid[sidx] = blk[-1:].view(np.int32)[0]
        timers["dedispersion"] = 0.0  # fused into the search program
        # sub-span of "searching" (which covers device + host decode)
        timers["searching_device"] = time.time() - t0

        if counts.max(initial=0) > cap:
            warnings.warn(
                f"peak buffer overflow: max count {counts.max()} > "
                f"capacity {cap}; raise peak_capacity"
            )

        # reconstruct each entry's (dm_local, accel, level) tag from
        # counts (the device compaction keeps valid slots in flat
        # spectrum order), then run the unique-peak merge over ALL
        # spectra in one native segmented call per shard
        factors = np.array([b[2] for b in self.bounds])
        per_dm_groups: dict[int, list] = {}
        for s in range(ndev):
            if nvalid[s] > compact_k:
                warnings.warn(
                    f"compacted peak buffer overflow on shard {s}: "
                    f"{nvalid[s]} > {compact_k}; raise compact_capacity"
                )
            k = np.minimum(
                counts[s * ndm_local : (s + 1) * ndm_local], cap
            ).reshape(-1)
            seg_bounds = np.minimum(
                np.concatenate([[0], np.cumsum(k)]), compact_k
            )
            total = int(seg_bounds[-1])
            blk = slice(s * compact_k, s * compact_k + total)
            merged_bin, merged_snr, seg_counts = segmented_unique_peaks(
                sel_bin[blk], sel_snr[blk], seg_bounds
            )
            spec = np.repeat(
                np.arange(nspec_local, dtype=np.int64), seg_counts
            )
            lvl = spec % nlevels
            acc_i = (spec // nlevels) % namax
            dml = spec // (nlevels * namax)
            freqs = merged_bin * factors[lvl]
            for d in np.unique(dml):
                m = dml == d
                per_dm_groups[int(s * ndm_local + d)] = (
                    freqs[m], merged_snr[m], acc_i[m], lvl[m]
                )

        dm_cands = CandidateCollection()
        ckpt_done = {}
        for ii in range(ndm):
            if ii not in per_dm_groups:
                ckpt_done[ii] = []
                continue
            efreq, esnr, eacc, elvl = per_dm_groups[ii]
            dm = float(self.dm_list[ii])
            groups = []
            for j in range(len(acc_lists[ii])):
                m = eacc == j
                acc = float(acc_lists[ii][j])
                groups.append([
                    Candidate(dm=dm, dm_idx=ii, acc=acc, nh=int(nh),
                              snr=float(sn), freq=float(fq))
                    for fq, sn, nh in zip(efreq[m], esnr[m], elvl[m])
                ])
            cands_ii = self._distill_accel_groups(groups)
            ckpt_done[ii] = cands_ii
            dm_cands.append(cands_ii)
        if ckpt:
            ckpt.save(ckpt_done)
        timers["searching"] = time.time() - t0
        result = self._finalise(dm_cands, trials, timers, t_total)
        if ckpt:
            ckpt.remove()
        return result
