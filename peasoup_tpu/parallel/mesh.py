"""Device-mesh parallelism for the trial grid.

TPU-native replacement of the reference's multi-GPU strategy: where
`src/pipeline_multi.cu:33-81` runs a mutex-guarded DM-trial work queue
over pthread workers (one per GPU) and merges candidate vectors after
join, here the DM axis is a named mesh axis:

* dedispersion is one jitted program whose delay table and output
  carry a ``NamedSharding`` over ``("dm",)`` — XLA partitions the
  channel sweep so each device produces only its DM rows (the input
  filterbank block is replicated, as dedisp's multi-GPU plan does);
* the search is a ``shard_map`` program: each device scans its local
  block of DM trials (whiten -> accel-batch search) and emits
  fixed-capacity peak buffers, which are device-local outputs of the
  same sharding — a single device->host gather replaces the pthread
  join + append of the reference;
* the dynamic DM dispenser becomes a static balanced assignment: DM
  trials cost the same per trial, and ragged accel lists are padded to
  a rectangle with a validity mask (SURVEY.md section 7).

On multi-host systems the same program runs under
``jax.distributed.initialize`` with a global mesh: the per-shard peak
buffers are all-gathered over ICI/DCN by the final host transfer, and
candidate distillation remains a (cheap) host-side pass.
"""

from __future__ import annotations

import os
from functools import partial

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..errors import ConfigError, HBMBudgetError
from ..obs import lineage
from ..obs.events import warn_event
from ..obs.metrics import REGISTRY as METRICS
from ..obs.trace import span, span_cursor
from ..ops.dedisperse import (
    dedisperse,
    dedisperse_flat,
    quantise_trials_bf16,
    quantise_trials_u8,
    split_flat_channels,
)
from .dispatch import DispatchPipeline
from ..search.pipeline import (
    FoldInputCache,
    PulsarSearch,
    SearchResult,
    fold_epilogue_core,
    search_one_accel,
    search_one_accel_legacy,
    whiten_core,
)
from ..search.plan import SearchConfig
from ..data.candidates import CandidateCollection
from ..io.unpack import pack_bits
from ..ops.peaks import segmented_unique_peaks


from ..utils.hostfetch import (  # re-exported; also used below
    fetch_to_host,
    finish_fetch,
    put_global,
    start_fetch,
)


def _shard_map(fn, *, mesh, in_specs, out_specs, check_vma: bool):
    """``jax.shard_map`` across jax versions: the top-level binding (and
    its ``check_vma`` kwarg) only exist from 0.5/0.7; earlier releases
    ship ``jax.experimental.shard_map`` with the equivalent
    ``check_rep`` flag."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check_vma)


def make_mesh(max_devices: int | None = None, axis: str = "dm") -> Mesh:
    devs = jax.devices()
    if max_devices:
        devs = devs[: max_devices]
    return Mesh(np.array(devs), (axis,))


def _onehot_select_rows(values, row_ids, n_rows: int,
                        select_dtype=jnp.bfloat16):
    """Row gather as a one-hot matmul: ``values[row_ids]`` computed on
    the MXU (a ``jnp.take`` row gather measured 28 ms on v5e for the
    kernel2 stage-2 selection this implements; the matmul is ~1 ms).

    Exact by construction when the contraction keeps the f32 operand
    at full precision: every one-hot entry is 0.0 or 1.0 (exact in
    ``select_dtype``), so each output element is one f32 value times
    1.0 plus zeros — ``assert_onehot_selection_exact`` proves the
    bit-identity ON DEVICE before any driver trusts this path (ADVICE
    round 5: the claim was only ever tested against a host float32
    einsum)."""
    onehot = (
        row_ids[:, None] == jnp.arange(n_rows, dtype=jnp.int32)[None, :]
    ).astype(select_dtype)
    return jnp.einsum(
        "rp,pl->rl", onehot, values,
        precision=(lax.Precision.DEFAULT, lax.Precision.HIGHEST),
        preferred_element_type=jnp.float32,
    )


_onehot_exact_checked: dict[tuple, bool] = {}


def assert_onehot_selection_exact(select_dtype=jnp.bfloat16,
                                  value_dtype=jnp.float32,
                                  n_rows: int = 96,
                                  row_len: int = 512) -> None:
    """On-device proof that :func:`_onehot_select_rows` is bit-exact.

    Runs the REAL einsum (same dtypes/precision as the kernel2 row
    selection) on this process's default device over full-mantissa
    random values — including exact-integer and subnormal-adjacent
    magnitudes — and compares bitwise against ``jnp.take``.  Raises
    ``DomainError`` on any mismatch: a backend where
    ``Precision.HIGHEST`` is not an exact limb decomposition of the
    f32 operand would otherwise silently break stage-2 bit-parity
    with the direct sweep.  Cached per (backend, dtypes) — the check
    costs one tiny dispatch, once per process.

    ``value_dtype`` exists for the negative test: casting the VALUES
    through an inexact dtype (e.g. bfloat16) truncates mantissas and
    must trip the assert (tests/test_parallel.py).
    """
    from ..errors import DomainError

    try:
        backend = jax.devices()[0].platform
    except Exception:
        backend = "unknown"
    key = (backend, jnp.dtype(select_dtype).name,
           jnp.dtype(value_dtype).name, n_rows, row_len)
    if _onehot_exact_checked.get(key):
        return
    rng = np.random.default_rng(1234)
    # full f32 mantissas across magnitudes the dedispersed partials
    # span; bf16-truncation of any of these changes the bits
    vals32 = np.concatenate([
        rng.normal(size=(n_rows - 2, row_len)).astype(np.float32)
        * np.logspace(-6, 6, n_rows - 2, dtype=np.float32)[:, None],
        np.full((1, row_len), np.float32(1.0 + 2.0 ** -23)),
        rng.integers(0, 2 ** 23, (1, row_len)).astype(np.float32),
    ])
    row_ids = rng.integers(0, n_rows, size=2 * n_rows).astype(np.int32)
    vals_d = jnp.asarray(vals32).astype(value_dtype)
    sel = jax.jit(
        partial(_onehot_select_rows, n_rows=n_rows,
                select_dtype=select_dtype)
    )(vals_d, jnp.asarray(row_ids))
    want = np.asarray(vals32)[row_ids]
    got = np.asarray(sel)
    if got.dtype != want.dtype or not np.array_equal(
            got.view(np.uint32), want.view(np.uint32)):
        bad = int((got != want).sum())
        raise DomainError(
            f"one-hot row selection is NOT bit-exact on backend "
            f"{backend!r} (select_dtype={jnp.dtype(select_dtype).name}, "
            f"value_dtype={jnp.dtype(value_dtype).name}): {bad} of "
            f"{got.size} elements differ from the jnp.take gather — "
            f"the sub-band kernel2 path would silently break bit-"
            f"parity; use dedisp_method='xla' for stage 2 or report "
            f"the backend"
        )
    _onehot_exact_checked[key] = True


from functools import lru_cache


def _compact_peaks(idxs, snrs, counts, compact_k, method: str = "xla"):
    """Shared device-side tail of both fused programs: compact all
    (dm, accel, level) peak buffers of a shard into one packed f32
    buffer (layout documented in :func:`build_fused_search`).

    Ships BOTH the true above-threshold ``counts`` (escalation sizing)
    and the per-spectrum DELIVERED slot counts (= how many valid
    entries each spectrum actually contributed to the stream).  The
    host segments the stream by ``delivered``, so a device-side
    extraction anomaly (a backend bug under-filling a top-k buffer)
    can never desynchronise the (dm, accel, level) attribution of
    later spectra — it surfaces as ``delivered < min(count, cap)`` on
    the affected spectrum, which the drivers re-search like any
    clipped row.

    ``method``: ``"xla"`` (cumsum+scatter) or ``"pallas"`` (the
    ops/peaks_pallas.py threshold-compaction kernel applied to slot
    validity — bit-identical output, O(n) streaming instead of a
    whole-buffer cumsum+scatter pair).  The drivers pick via
    :meth:`MeshPulsarSearch.compact_method_for`.
    """
    ns = counts.reshape(-1).shape[0]
    delivered = jnp.sum(
        (idxs >= 0).reshape(ns, -1), axis=1, dtype=jnp.int32)
    flat_bin = idxs.reshape(-1)
    flat_snr = snrs.reshape(-1)
    n = flat_bin.shape[0]
    if n > 2**31 - 2:
        raise ConfigError(
            f"peak-buffer slot count {n} overflows int32 slot indices; "
            f"reduce peak_capacity, accel count per dispatch "
            f"(accel_block) or DM rows per shard"
        )
    if method == "pallas":
        from ..ops.peaks_pallas import (
            compact_valid_slots_pallas,
            pallas_peaks_interpret,
        )

        sel_bin, sel_snr, nv = compact_valid_slots_pallas(
            flat_bin, flat_snr, compact_k,
            interpret=pallas_peaks_interpret(),
        )
        nvalid = nv.reshape(-1)[:1].astype(jnp.int32)
    else:
        valid = flat_bin >= 0
        # stream compaction via cumsum + scatter.  (A top_k(score,
        # compact_k) formulation is algebraically equivalent but
        # k ~ 10^5 top_k MISCOMPILES on v5e: shape-dependent garbage
        # output or a TPU worker crash.  The scatter runs once per
        # dispatch.)
        pos = jnp.cumsum(valid.astype(jnp.int32)) - 1
        dest = jnp.where(valid, pos, compact_k)  # OOB -> dropped
        # the host reconstructs each entry's (dm, accel, level, slot)
        # tag from ``counts`` alone: valid slots appear in flat
        # spectrum order, so only bins+snrs are shipped
        sel_bin = (
            jnp.full((compact_k,), -1, flat_bin.dtype)
            .at[dest].set(flat_bin, mode="drop")
        )
        sel_snr = (
            jnp.zeros((compact_k,), jnp.float32)
            .at[dest].set(flat_snr.astype(jnp.float32), mode="drop")
        )
        nvalid = jnp.sum(valid, dtype=jnp.int32)[None]
    counts_f = counts.reshape(-1)
    # pack everything into ONE f32 buffer so the host pays a single
    # device->host round trip.  Every int travels as TWO 16-bit halves
    # in plain f32 (exactly representable), so bin indices, counts and
    # nvalid are exact at ANY spectrum length that fits int32 — the
    # reference has no size ceiling either (`src/pipeline_multi.cu:
    # 326-331`).  Floor-div semantics keep the -1 invalid sentinel
    # exact: -1 -> (hi -1, lo 65535) -> -65536 + 65535 = -1.
    # (bitcast_convert_type int32->f32 MISCOMPILES inside this program
    # on v5e: shape-dependent zeroed outputs — hence halves, not bits.)
    return jnp.concatenate([
        (sel_bin // 65536).astype(jnp.float32),
        (sel_bin % 65536).astype(jnp.float32),
        sel_snr,
        (counts_f // 65536).astype(jnp.float32),
        (counts_f % 65536).astype(jnp.float32),
        (delivered // 65536).astype(jnp.float32),
        (delivered % 65536).astype(jnp.float32),
        (nvalid // 65536).astype(jnp.float32),
        (nvalid % 65536).astype(jnp.float32),
    ])


@lru_cache(maxsize=32)
def build_fused_search(
    mesh: Mesh,
    *,
    nbits: int,
    nchans: int,
    nsamps: int,
    out_nsamps: int,
    size: int,
    bin_width: float,
    tsamp: float,
    nharms: int,
    bounds: tuple,
    capacity: int,
    min_snr: float,
    b5: float,
    b25: float,
    use_zap: bool,
    use_killmask: bool,
    compact_k: int,
    max_shift: int | None = None,
    block: int | None = None,
    dedisp_pallas: tuple | None = None,
    lattice: str = "f32",
    use_jerks: bool = False,
    peaks_methods: tuple | None = None,
    compact_method: str = "xla",
    batch: int = 1,
):
    """One jitted program for the ENTIRE device side of the search.

    packed filterbank bytes (replicated) -> device bit-unpack ->
    dedisperse (DM rows sharded over the mesh) -> per-DM whiten ->
    batched accel trials -> harmonic sums -> thresholded peaks ->
    global compaction of all (dm, accel, level) peak buffers into one
    small tagged buffer per shard.

    This exists because device->host transfers and program dispatches
    dominate wall-clock on a remote-attached TPU: the reference pays
    neither (its host loop talks to a local PCIe GPU per DM trial,
    `src/pipeline_multi.cu:145-244`), so the TPU-native design moves the
    whole search into one dispatch and ships home ONE packed f32 buffer
    per shard, laid out as (k = compact_k, ns = ndm_local*naccel*
    nlevels; every int travels as two 16-bit halves in plain f32, so
    transport is exact at any int32 spectrum length):

    * ``[0:k]`` / ``[k:2k]``      bin index hi / lo halves
    * ``[2k:3k]``                 SNR values (f32)
    * ``[3k:3k+ns]`` / ``+2ns``   per-spectrum above-threshold count
      hi / lo halves (overflow check / escalation sizing)
    * ``+2ns:+4ns``               per-spectrum DELIVERED slot count
      hi / lo halves — the key to reconstructing each entry's
      (dm, accel, level) tag; derived from the same buffers the
      compaction scatters, so host segmentation can never desync
    * ``[-2]`` / ``[-1]``         true total valid count hi / lo

    plus ``trials`` (ndm_local, out_nsamps) f32 — full-width, staying
    device-resident for the folding phase; never copied to host.

    Returns a jitted callable
    ``fn(raw, delays, killmask, accs, uidx, d0_u, pos_u, step_u,
    birdies, widths)``.  The table args are always required; when
    ``block`` is None (legacy on-device resampler path) they are
    unused dummies (see ``MeshPulsarSearch._resample_tables``).

    ``dedisp_pallas``: optional static (dm_tile, time_tile, slack,
    pad_to, max_delay) from ``_plan_fused_pallas_dedisp`` — replaces
    the XLA channel-scan sweep with the flat Pallas kernel on the
    uint8 data (measured 2.1 ms vs 46 ms at tutorial scale on v5e;
    the vmapped dynamic_slice lowers to a batched gather).  Requires
    per-shard DM rows divisible by dm_tile and nbits <= 8.

    ``lattice``: the RESOLVED trial dtype (``PulsarSearch.lattice``,
    see search/tuning.py): ``"u8"`` applies the dedisp out_nbits=8
    staircase, ``"bf16"`` the half-bandwidth round-trip cast, ``"f32"``
    nothing.

    ``use_jerks``: jerk-axis search on the LEGACY (``block=None``)
    resampler — an extra trailing ``jerks`` input (same (ndm, namax)
    shape/sharding as ``accs``, the combined trial axis's per-slot
    jerk) is vmapped into :func:`search_one_accel_legacy`.  The table
    path never needs it: unique (accel, jerk) pair tables bake the
    cubic term host-side (``resample2_unique_tables``), so the program
    body is byte-identical with or without a jerk axis there.

    ``batch``: leading observation axis B (ISSUE 9).  ``batch == 1``
    is byte-for-byte the historical single-observation program.  For
    ``batch > 1`` the ``raw`` input becomes ``(B, rawlen)`` packed
    bytes (replicated) and the per-observation body is UNROLLED B
    times — deliberately not vmapped: the Pallas dedisperse /
    compaction kernels take no batch dim, and unrolling keeps each
    beam's HLO identical to the B=1 program so per-beam results stay
    bit-identical to sequential runs (the batched-parity gate).
    Outputs become ``packed (B, ndev*blk_len)`` — row ``b`` is
    exactly the B=1 packed global buffer — and ``trials
    (B, ndm, out_nsamps)``.  Everything else (delay tables, accel
    grid, masks) is shared: callers must only batch observations from
    the same geometry bucket.
    """
    from ..ops.unpack import unpack_bits_device

    nlevels = nharms + 1
    use_tables = block is not None
    take_jerks = use_jerks and not use_tables

    def one_obs(raw, delays, killmask, accs, uidx, d0_u, pos_u, step_u,
                birdies, widths, jerks=None):
        vals = unpack_bits_device(raw, nbits)[: nsamps * nchans]
        # full-width trials are returned for the folding phase (which
        # must see prev_power_of_two(out_nsamps) real samples exactly
        # like the single-device path, `folder.hpp:352-406`); the
        # search itself runs on the fft-size-truncated/padded view
        if dedisp_pallas is not None:
            from ..ops.dedisperse_pallas import dedisperse_pallas_flat

            dd_tile, dd_T, dd_slack, dd_pad, dd_maxdelay = dedisp_pallas
            # true uint8 (unpack yields int32): the kernel's flat
            # buffer needs the u8 1024-element tiling
            data8 = vals.astype(jnp.uint8).reshape(nsamps, nchans).T
            if use_killmask:
                data8 = jnp.where(
                    killmask[:, None] > 0, data8,
                    jnp.zeros((), data8.dtype))
            flat = jnp.pad(
                data8, ((0, 0), (0, dd_pad - nsamps))).reshape(-1)
            trials = dedisperse_pallas_flat(
                [flat], delays, dd_pad, out_nsamps,
                window_slack=dd_slack, dm_tile=dd_tile,
                time_tile=dd_T, chan_group=16, max_delay=dd_maxdelay,
            )
        else:
            data = vals.reshape(nsamps, nchans).T.astype(jnp.float32)
            if use_killmask:
                data = data * killmask[:, None]
            trials = dedisperse(data, delays, out_nsamps)
        if lattice == "u8":  # dedisp's out_nbits=8 staircase
            trials = quantise_trials_u8(trials, nbits, nchans)
        elif lattice == "bf16":
            trials = quantise_trials_bf16(trials)
        if out_nsamps >= size:
            trials_sz = trials[:, :size]
        else:
            pad_mean = jnp.mean(trials, axis=1, keepdims=True)
            pad = jnp.broadcast_to(
                pad_mean, (trials.shape[0], size - out_nsamps)
            )
            trials_sz = jnp.concatenate([trials, pad], axis=1)

        # whiten once per DM row, then FLATTEN (dm, accel) into one wide
        # batch: a single-level vmap keeps every FFT/top_k one big
        # batched op (the nested dm-over-accel vmap measured ~25 ms
        # slower at 59x3 trials on v5e)
        tw, mean, std = jax.vmap(
            lambda t: whiten_core(t, birdies, widths, bin_width, b5, b25,
                                  use_zap)
        )(trials_sz)
        namax = accs.shape[1]
        tw_f = jnp.repeat(tw, namax, axis=0)
        mean_f = jnp.repeat(mean, namax)
        std_f = jnp.repeat(std, namax)
        accs_f = accs.reshape(-1)
        if use_tables:
            search = lambda t, m, s, ui: search_one_accel(
                t, (d0_u[ui], pos_u[ui], step_u[ui]), m, s, tsamp,
                nharms, bounds, capacity, min_snr, max_shift, block,
                peaks_methods,
            )
            idxs, snrs, counts = jax.vmap(search)(
                tw_f, mean_f, std_f, uidx.reshape(-1))
        elif take_jerks:
            search = lambda t, m, s, a, j: search_one_accel_legacy(
                t, jnp.nan_to_num(a), m, s, tsamp, nharms, bounds,
                capacity, min_snr, max_shift, peaks_methods,
                jnp.nan_to_num(j),
            )
            idxs, snrs, counts = jax.vmap(search)(
                tw_f, mean_f, std_f, accs_f, jerks.reshape(-1))
        else:
            search = lambda t, m, s, a: search_one_accel_legacy(
                t, jnp.nan_to_num(a), m, s, tsamp, nharms, bounds,
                capacity, min_snr, max_shift, peaks_methods,
            )
            idxs, snrs, counts = jax.vmap(search)(
                tw_f, mean_f, std_f, accs_f)
        valid = ~jnp.isnan(accs_f)
        idxs = jnp.where(valid[:, None, None], idxs, -1)
        snrs = jnp.where(valid[:, None, None], snrs, 0.0)
        counts = jnp.where(valid[:, None], counts, 0)
        # flat batch is (dm-major, accel) row order — exactly the
        # (dm, accel, level, slot) layout _compact_peaks flattens to
        packed = _compact_peaks(idxs, snrs, counts, compact_k,
                                compact_method)
        return packed, trials

    if batch == 1:
        shard_fn = one_obs
        out_specs = (P("dm"), P("dm", None))
    else:
        def shard_fn(raw, delays, killmask, accs, uidx, d0_u, pos_u,
                     step_u, birdies, widths, jerks=None):
            outs = [one_obs(raw[b], delays, killmask, accs, uidx, d0_u,
                            pos_u, step_u, birdies, widths, jerks)
                    for b in range(batch)]
            packed = jnp.stack([o[0] for o in outs])
            trials = jnp.stack([o[1] for o in outs])
            return packed, trials

        # packed: shards concatenate along the buffer axis so row b of
        # the global (B, ndev*blk_len) result IS the B=1 packed layout
        # _decode_packed already understands; trials keep dm sharded
        out_specs = (P(None, "dm"), P(None, "dm", None))

    mapped = _shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(
            P(), P("dm", None), P(), P("dm", None), P("dm", None),
            P(), P(), P(), P(), P(),
        ) + ((P("dm", None),) if take_jerks else ()),
        out_specs=out_specs,
        # pallas_call out_shapes carry no varying-mesh-axes annotation
        # (same waiver as build_chunked_search)
        check_vma=False,
    )
    return jax.jit(mapped)


@lru_cache(maxsize=16)
def build_chunked_search(
    mesh: Mesh,
    *,
    nchans: int,
    out_nsamps: int,
    size: int,
    ndm_local: int,
    dm_chunk: int,
    namax: int,
    accel_block: int,
    bin_width: float,
    tsamp: float,
    nharms: int,
    bounds: tuple,
    capacity: int,
    min_snr: float,
    b5: float,
    b25: float,
    use_zap: bool,
    compact_k: int,
    max_shift: int | None,
    dedisp_method: str,
    window_slack: int = 0,
    dm_tile: int = 32,
    time_tile: int = 15360,
    chan_group: int = 16,
    max_delay_samples: int = 0,
    block: int | None = None,
    n_parts: int = 1,
    subband: tuple | None = None,
    quantise_nbits: int = 0,
    lattice: str = "f32",
    use_jerks: bool = False,
    peaks_methods: tuple | None = None,
    compact_method: str = "xla",
):
    """Bounded-HBM variant of :func:`build_fused_search`.

    The full-materialisation program holds ``(ndm_local, out_nsamps)``
    trials plus ``ndm_local*namax`` batched search intermediates — at
    SURVEY-scale inputs (2^23 samples x 10^3 DM trials) that is
    terabytes. This program is the same single dispatch, but streams
    the work in the shape the reference streams it
    (`src/pipeline_multi.cu:145-157` processes one trial at a time):

    * an outer ``lax.scan`` over DM chunks of ``dm_chunk`` trials:
      dedisperse (Pallas kernel or XLA scan) -> per-row whiten;
    * an inner ``lax.scan`` over accel blocks of ``accel_block``
      trials, so at most ``dm_chunk * accel_block`` spectra worth of
      FFT/harmonic intermediates are ever live;
    * only the fixed-size peak buffers survive each step (stacked by
      the scans), and the usual global compaction ships ONE packed
      buffer per shard home.

    ``trials`` are NOT returned: at this scale they cannot stay
    HBM-resident, so folding re-dedisperses just the candidate DM rows
    (see ``MeshPulsarSearch._fold_trials_provider``).

    ``data`` is channel-major and stays uint8 in HBM for 8-bit inputs
    (f32 at 4096 chans x 2^23 samples would be 34 GB); the caller
    pre-applies the killmask and pre-pads the tail so the Pallas
    kernel's window padding is a no-op on the hot path.

    Returns a jitted ``fn(data, delays, accs, uidx, d0_u, pos_u,
    step_u, birdies, widths) -> packed`` with delays/accs/uidx sharded
    over ``dm`` and ``ndm_local = n_chunks * dm_chunk`` rows per shard.
    The table args are always required; with ``block=None`` they are
    unused dummies (see ``MeshPulsarSearch._resample_tables``).

    ``lattice`` selects the resolved trial dtype exactly like
    :func:`build_fused_search` (``quantise_nbits`` is the INPUT nbits
    the u8 staircase scales by, only read when ``lattice="u8"``), and
    ``use_jerks`` + ``block=None`` adds a per-slot ``jerks`` input
    between ``accs`` and ``uidx`` for the legacy resampler — the table
    path bakes jerk into the unique (accel, jerk) pair tables instead.

    ``subband``: optional static 9-tuple (bounds, L1, n_anchor_p,
    slack, csub, t_sub, k_sub, dm_tile, kernel2) —
    two-stage sub-band dedispersion (``_plan_subband_chunks``): three
    extra leading inputs follow the data parts, all dm-sharded.  With
    ``kernel2`` None they are anchor_delays (n_anchor_p, nchans),
    assign (dm_chunk,), shifts (dm_chunk, nsub) and the per-chunk
    direct sweep is replaced by ``dedisperse_subband_flat`` (anchor
    sweeps + shifted-window XLA assembly).  With ``kernel2`` = (R2,
    slack2, shift_max, chan_group2, dm_tile2, T2) — the Pallas path —
    they are anchor_delays, delays2 (R2, nsub), unpad (dm_chunk,),
    and stage 2 runs as ONE direct-kernel launch over the flat f32
    partials followed by an exact one-hot row selection (see
    ``subband_trials``).  Requires the driver's one-chunk-per-dispatch
    shape.
    """
    from ..ops.dedisperse_pallas import (
        dedisperse_pallas_flat,
        dedisperse_pallas_flat_subband,
    )
    from ..ops.dedisperse import dedisperse_subband_flat

    nlevels = nharms + 1
    n_chunks = ndm_local // dm_chunk
    n_ablocks = namax // accel_block
    assert ndm_local == n_chunks * dm_chunk
    assert namax == n_ablocks * accel_block
    assert subband is None or n_chunks == 1, \
        "sub-band mode needs one chunk per dispatch (the driver's shape)"
    use_tables = block is not None
    take_jerks = use_jerks and not use_tables

    def shard_fn(*args):
        # data arrives AND STAYS flat, split into int32-indexable
        # whole-channel parts — any 2-D view (even a reshape) costs a
        # full-size relayout copy under shard_map, 8 GB at production
        # scale (see ops.dedisperse.dedisperse_flat)
        parts = list(args[:n_parts])
        if subband is not None:
            # in kernel2 mode the last two are (delays2, unpad) — see
            # subband_trials; names kept for the shared unpack
            (anchor_delays, sb_assign, sb_shifts) = args[n_parts:n_parts + 3]
            rest = args[n_parts + 3:]
        else:
            rest = args[n_parts:]
        if take_jerks:
            (delays, accs, jerks, uidx, d0_u, pos_u, step_u, birdies,
             widths) = rest
        else:
            jerks = None
            (delays, accs, uidx, d0_u, pos_u, step_u, birdies,
             widths) = rest
        nsamps_dev = sum(p.shape[0] for p in parts) // nchans

        if subband is not None:
            (sb_bounds, sb_L1, sb_nanch, sb_slack, sb_csub,
             sb_T, sb_K, sb_dm_tile, sb_kernel2) = subband
            if dedisp_method == "pallas":
                # one-launch stage 1 (grid over sub-bands, K-tile
                # windows — see _dedisperse_flat_sb_kernel)
                def stage1(ad):
                    return dedisperse_pallas_flat_subband(
                        parts, ad, nsamps_dev, sb_L1, csub=sb_csub,
                        window_slack=sb_slack, dm_tile=sb_dm_tile,
                        time_tile=sb_T, k_tiles=sb_K,
                        chan_group=chan_group,
                        max_delay=max_delay_samples,
                    )
            else:
                def stage1(cr, ad):
                    return dedisperse_flat(parts, ad, nsamps_dev, sb_L1,
                                           chan_range=cr)

        def subband_trials():
            if dedisp_method == "pallas" and sb_kernel2 is not None:
                # stage 2 as ONE direct-kernel launch over the flat
                # f32 partials (synthetic nsub-channel filterbank,
                # per-row delays = anchor stride + shift); the padded
                # rows are then selected back to chunk order with an
                # exact one-hot matmul — a jnp.take row gather
                # measured 28 ms for the same selection on v5e
                (k2_R2, k2_slack, k2_maxd, k2_G, k2_tile, k2_T) = \
                    sb_kernel2
                partials = stage1(anchor_delays)
                out2 = dedisperse_pallas_flat(
                    [partials.reshape(-1)], sb_assign, sb_L1,
                    out_nsamps, window_slack=k2_slack,
                    max_delay=k2_maxd, dm_tile=k2_tile,
                    time_tile=k2_T, chan_group=k2_G,
                    data_tail_ok=True,
                )
                return _onehot_select_rows(out2, sb_shifts, k2_R2)
            return dedisperse_subband_flat(
                anchor_delays, sb_assign, sb_shifts, out_nsamps,
                bounds=sb_bounds, L1=sb_L1, stage1=stage1,
            )

        def chunk_body(_, ci):
            z = jnp.int32(0)  # literal 0 is weak-i64 under x64
            delays_c = lax.dynamic_slice(
                delays, (ci * dm_chunk, z), (dm_chunk, nchans)
            )
            accs_c = lax.dynamic_slice(
                accs, (ci * dm_chunk, z), (dm_chunk, namax)
            )
            uidx_c = lax.dynamic_slice(
                uidx, (ci * dm_chunk, z), (dm_chunk, namax)
            )
            if take_jerks:
                jerks_c = lax.dynamic_slice(
                    jerks, (ci * dm_chunk, z), (dm_chunk, namax)
                )
            if subband is not None:
                trials = subband_trials()
            elif dedisp_method == "pallas":
                trials = dedisperse_pallas_flat(
                    parts, delays_c, nsamps_dev, out_nsamps,
                    window_slack=window_slack, dm_tile=dm_tile,
                    time_tile=time_tile, chan_group=chan_group,
                    max_delay=max_delay_samples,
                )
            else:
                trials = dedisperse_flat(
                    parts, delays_c, nsamps_dev, out_nsamps)
            if lattice == "u8":  # dedisp's out_nbits=8 staircase
                trials = quantise_trials_u8(
                    trials, quantise_nbits, nchans)
            elif lattice == "bf16":
                trials = quantise_trials_bf16(trials)
            if out_nsamps >= size:
                trials_sz = trials[:, :size]
            else:
                pad_mean = jnp.mean(trials, axis=1, keepdims=True)
                pad = jnp.broadcast_to(
                    pad_mean, (dm_chunk, size - out_nsamps)
                )
                trials_sz = jnp.concatenate([trials, pad], axis=1)

            # scan over DM ROWS with a WIDE accel vmap per step: a
            # wide trial batch keeps the chip fed (measured 18.6
            # ms/trial at 2^23 for a 21-wide vmap vs ~72 ms/trial for
            # the inverted nesting of an 8-row vmap stepping accels
            # one at a time); accel_block bounds the live spectra per
            # step for the HBM budget
            def row_body(_, row_in):
                tim, arow, urow = row_in[:3]
                jrow = row_in[3] if take_jerks else None
                tw, m, s = whiten_core(
                    tim, birdies, widths, bin_width, b5, b25, use_zap
                )

                def ab_body(__, ai):
                    a_blk = lax.dynamic_slice(
                        arow, (ai * accel_block,), (accel_block,))
                    u_blk = lax.dynamic_slice(
                        urow, (ai * accel_block,), (accel_block,))
                    if use_tables:
                        search = lambda ui: search_one_accel(
                            tw, (d0_u[ui], pos_u[ui], step_u[ui]), m, s,
                            tsamp, nharms, bounds, capacity, min_snr,
                            max_shift, block, peaks_methods,
                        )
                        i2, s2, c2 = jax.vmap(search)(u_blk)
                    elif take_jerks:
                        j_blk = lax.dynamic_slice(
                            jrow, (ai * accel_block,), (accel_block,))
                        search = lambda a, j: search_one_accel_legacy(
                            tw, jnp.nan_to_num(a), m, s, tsamp, nharms,
                            bounds, capacity, min_snr, max_shift,
                            peaks_methods, jnp.nan_to_num(j),
                        )
                        i2, s2, c2 = jax.vmap(search)(a_blk, j_blk)
                    else:
                        search = lambda a: search_one_accel_legacy(
                            tw, jnp.nan_to_num(a), m, s, tsamp, nharms,
                            bounds, capacity, min_snr, max_shift,
                            peaks_methods,
                        )
                        i2, s2, c2 = jax.vmap(search)(a_blk)
                    valid = ~jnp.isnan(a_blk)
                    i2 = jnp.where(valid[:, None, None], i2, -1)
                    s2 = jnp.where(valid[:, None, None], s2, 0.0)
                    c2 = jnp.where(valid[:, None], c2, 0)
                    return 0, (i2, s2, c2)

                _, (bi, bs, bc) = lax.scan(
                    ab_body, 0, jnp.arange(n_ablocks, dtype=jnp.int32)
                )
                return 0, (
                    bi.reshape(namax, nlevels, capacity),
                    bs.reshape(namax, nlevels, capacity),
                    bc.reshape(namax, nlevels),
                )

            _, (bi, bs, bc) = lax.scan(
                row_body, 0,
                (trials_sz, accs_c, uidx_c)
                + ((jerks_c,) if take_jerks else ()),
            )
            return 0, (bi, bs, bc)

        _, (idxs, snrs, counts) = lax.scan(
            chunk_body, 0, jnp.arange(n_chunks, dtype=jnp.int32)
        )
        idxs = idxs.reshape(ndm_local, namax, nlevels, capacity)
        snrs = snrs.reshape(ndm_local, namax, nlevels, capacity)
        counts = counts.reshape(ndm_local, namax, nlevels)
        return _compact_peaks(idxs, snrs, counts, compact_k,
                              compact_method)

    if subband is None:
        sb_specs = ()
    elif dedisp_method == "pallas" and subband[8] is not None:
        # kernel2 transport: delays2 (R2, nsub) + unpad (dm_chunk,)
        sb_specs = (P("dm", None), P("dm", None), P("dm"))
    else:
        sb_specs = (P("dm", None), P("dm"), P("dm", None))
    n_rowspecs = 4 if take_jerks else 3
    mapped = _shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(P(),) * n_parts + sb_specs
        + (P("dm", None),) * n_rowspecs + (P(), P(), P(), P(), P()),
        out_specs=P("dm"),
        # pallas_call out_shapes carry no varying-mesh-axes annotation;
        # every output here is trivially dm-varying, so skip the check
        check_vma=False,
    )
    # the per-chunk uploads (sub-band tables + delays/accs/[jerks/]
    # uidx) are consumed by exactly one dispatch each — donate their
    # buffers so depth>=2 pipelining doesn't hold two chunks' worth of
    # input HBM.  The resident operands (data parts, resample tables,
    # birdies) are reused by every chunk and must NOT be donated.  CPU
    # jax can't donate (every dispatch would warn) so the hint is
    # dropped there.
    donate = ()
    if jax.default_backend() != "cpu":
        donate = tuple(
            range(n_parts, n_parts + len(sb_specs) + n_rowspecs))
    return jax.jit(mapped, donate_argnums=donate)


class MeshPulsarSearch(PulsarSearch):
    """Multi-device search: DM trials sharded over a 1-D device mesh."""

    def __init__(self, fil, config: SearchConfig, max_devices=None,
                 mesh: Mesh | None = None):
        super().__init__(fil, config)
        self.mesh = mesh if mesh is not None else make_mesh(max_devices)
        self.ndev = self.mesh.devices.size

    def _padded_trial_count(self) -> int:
        ndm = len(self.dm_list)
        return int(np.ceil(ndm / self.ndev)) * self.ndev

    def _trial_lists(self, acc_lists):
        """Combined (accel, jerk) per-DM trial lists (ISSUE 13).

        Returns ``(trial_accs, trial_jerks)`` where each DM row's lists
        flatten the accel x jerk product with accel varying fastest
        (``search/plan.py:combine_trials``).  Jerk-free plans return
        the accel lists UNCHANGED with ``trial_jerks=None``, so every
        downstream grid, table and compiled program is bit-identical
        to the accel-only search."""
        if self.jerk_plan.max_abs == 0.0:
            return acc_lists, None
        from ..search.plan import combine_trials

        jl = self.jerk_plan.jerk_list()
        pairs = [combine_trials(a, jl) for a in acc_lists]
        return [p[0] for p in pairs], [p[1] for p in pairs]

    def _legacy_jerks(self) -> bool:
        """True when the legacy (table-free) resampler must receive an
        explicit per-slot jerks input — the table path bakes jerk into
        the unique (accel, jerk) pair tables instead."""
        return (self.jerk_plan.max_abs > 0.0
                and self.resample_block is None)

    def compact_method_for(self, compact_k: int) -> str:
        """Lowering of the whole-buffer stream compaction
        (:func:`_compact_peaks`): the ops/peaks_pallas.py threshold-
        compaction kernel replaces the cumsum+scatter when the
        compiled kernel is available and the compacted buffer is small
        enough that the kernel's one-hot scatter tiles stay in VMEM.
        ``COMPACT_PALLAS_MAX_K`` admits exactly the tuned common case
        (the drivers round ``ck_hw`` up in 8192 quanta with an 8192
        floor); bigger untuned buffers keep the XLA lowering.  Forced
        ``peaks_method="sort"/"two_stage"`` pins XLA — the compaction
        is peak-path machinery, so the A/B forcing flag governs it
        too; forced ``"pallas"`` off-TPU stays XLA here (an interpret-
        mode compaction inside the fused program would serialise the
        whole dispatch ~100x; per-level extraction keeps its own
        forced-pallas fallback story).
        """
        from ..ops.peaks_pallas import COMPACT_PALLAS_MAX_K
        from ..search.pipeline import _pallas_mode

        if (int(compact_k) <= COMPACT_PALLAS_MAX_K
                and self.config.peaks_method in ("auto", "pallas")
                and _pallas_mode() == "compiled"):
            METRICS.inc("peaks.compact_pallas")
            return "pallas"
        return "xla"

    def _plan_fused_pallas_dedisp(self) -> dict | None:
        """Flat Pallas-kernel dedispersion for the FUSED path.

        The XLA channel-scan sweep (``ops.dedisperse.dedisperse``)
        lowers its vmapped dynamic_slice to a batched gather: 46 ms at
        tutorial scale (59 rows x 64 chans x 2^17) on v5e where the
        flat kernel runs 2.1 ms, bit-exact.  Only for <=8-bit inputs
        (the kernel's in-program flat buffer needs the uint8 1024-
        element tiling; an f32 reshape gets a mismatched layout) and
        TPU.  Returns {ndm_p, params} or None; ndm_p is widened so
        every shard's rows divide dm_tile.  Cached on the search
        object (the slack scan is O(ndm_p x nchans) host work and the
        inputs are fixed per search).
        """
        if "_dd_pallas_plan" in self.__dict__:
            return self._dd_pallas_plan
        self._dd_pallas_plan = self._plan_fused_pallas_dedisp_uncached()
        return self._dd_pallas_plan

    def _plan_fused_pallas_dedisp_uncached(self) -> dict | None:
        if self.mesh.devices.flat[0].platform != "tpu":
            return None
        if self.fil.header.nbits > 8 or self.fil.nchans % 32:
            return None
        if self.killmask is not None and not np.isin(
                self.killmask, (0.0, 1.0)).all():
            # the uint8 branch gates channels with where(mask > 0);
            # only a strict 0/1 mask matches the f32 multiply semantics
            return None
        T = 15360
        if self.out_nsamps < T:
            return None
        dm_tile, G = 8, 16
        from ..ops.dedisperse_pallas import (
            dedisperse_flat_pad_to,
            dedisperse_window_slack,
        )

        ndm = len(self.dm_list)
        step = self.ndev * dm_tile
        ndm_p = -(-ndm // step) * step
        # edge-pad (matches _device_inputs): zero-delay pad rows next
        # to max-delay rows would explode the slack bound
        delays_p = np.empty((ndm_p, self.fil.nchans), np.int32)
        delays_p[:ndm] = self.delays
        delays_p[ndm:] = self.delays[-1]
        slack = int(dedisperse_window_slack(delays_p, dm_tile, G))
        pad_to = dedisperse_flat_pad_to(
            self.out_nsamps, self.max_delay, slack, T, uint8=True)
        return dict(
            ndm_p=ndm_p,
            params=(dm_tile, T, slack, pad_to, self.max_delay),
        )

    def _tune_scoped_key(self, driver: str) -> str:
        """Tune-sidecar key including mesh geometry: the recorded
        high-waters are per-SHARD quantities (and fused/chunked count
        them differently), so a record from another device count or
        driver must not alias this one."""
        return f"{driver}:ndev={self.ndev}:" + self._tune_key()

    def _expected_raw_len(self) -> int:
        """Length of the packed raw-bytes vector ``_pack_raw`` builds
        (f32 count at nbits=32, else ``pack_bits``'s ceil-divided byte
        count) — the shape a prefetch-staged upload must match."""
        n = self.fil.nsamps * self.fil.nchans
        nbits = self.fil.header.nbits
        if nbits == 32:
            return n
        spb = 8 // nbits
        return (n + spb - 1) // spb

    def _staged_raw_device(self, rep):
        """Consume a prefetch-thread device staging (ISSUE 11): the
        survey worker's ``ObservationPrefetcher`` packs + device_puts
        the raw filterbank bytes while the PREVIOUS job computes
        (``SurveyWorker._stage_observation``), parking the result on
        ``self._staged_raw``.  Returns the replicated device array, or
        None when nothing usable was staged (wrong geometry after a
        header surprise, multi-process runs — where the staging thread
        can't build the global array safely — or no worker at all)."""
        staged = getattr(self, "_staged_raw", None)
        if staged is None or jax.process_count() != 1:
            return None
        dtype = np.float32 if self.fil.header.nbits == 32 else np.uint8
        if (getattr(staged, "shape", None) != (self._expected_raw_len(),)
                or staged.dtype != dtype):
            return None
        METRICS.inc("scheduler.staged_raw_hits")
        # no-op when the staging thread already committed this sharding
        return jax.device_put(staged, rep)

    def dedisperse_sharded(self) -> jax.Array:
        """Dedisperse with the DM axis sharded across the mesh.

        Consumes the PACKED filterbank bytes and unpacks on device —
        exactly like the fused search program — so the only permanent
        HBM residents are the (1x) packed bytes, shared with
        ``_device_inputs`` when that cache exists.  (A previous version
        permanently cached a replicated f32 host transpose: 4x the u8
        footprint, invisible to ``_plan_chunking``'s budget, and the
        reason near-boundary fused searches could RESOURCE_EXHAUST
        once stage measurement warmed it.)
        """
        cached = getattr(self, "_dedisp_sharded_state", None)
        if cached is None:
            from ..ops.unpack import unpack_bits_device

            rep = NamedSharding(self.mesh, P())
            shard = NamedSharding(self.mesh, P("dm", None))
            if getattr(self, "_dev_inputs", None) is not None:
                # the fused program's resident inputs already hold the
                # packed bytes, padded delay table and killmask
                raw_d, delays_d, km_d = self._dev_inputs[:3]
            else:
                ndm = len(self.dm_list)
                ndm_p = self._padded_trial_count()
                delays = np.zeros((ndm_p, self.fil.nchans), np.int32)
                delays[:ndm] = self.delays
                nbits = self.fil.header.nbits
                raw_d = self._staged_raw_device(rep)
                if raw_d is None:
                    if nbits == 32:
                        raw = np.ascontiguousarray(
                            self.fil.data, np.float32).ravel()
                    else:
                        raw = pack_bits(self.fil.data.ravel(), nbits)
                    raw_d = put_global(raw, rep)
                km = (
                    np.asarray(self.killmask, dtype=np.float32)
                    if self.killmask is not None
                    else np.ones(self.fil.nchans, np.float32)
                )
                delays_d = put_global(delays, shard)
                km_d = put_global(km, rep)
            nbits = self.fil.header.nbits
            nchans, nsamps = self.fil.nchans, self.fil.nsamps
            use_km = self.killmask is not None

            def dedisp_from_raw(raw, delays, km):
                # the f32 channel-major view is a transient inside this
                # program (the fused search program materialises the
                # same transient, so this fits whenever it does)
                vals = unpack_bits_device(raw, nbits)[: nsamps * nchans]
                data = vals.reshape(nsamps, nchans).T.astype(jnp.float32)
                if use_km:
                    data = data * km[:, None]
                return dedisperse(data, delays, self.out_nsamps)

            fn = jax.jit(dedisp_from_raw, out_shardings=shard)
            cached = (fn, raw_d, delays_d, km_d)
            self._dedisp_sharded_state = cached
        fn, raw_d, delays_d, km_d = cached
        return fn(raw_d, delays_d, km_d)

    def _device_inputs(self, acc_lists, ndm_p: int, namax: int,
                       jerk_lists=None):
        """Build (once) and cache the device-resident static inputs.

        The filterbank bytes, delay table, killmask and accel grid are
        constant for a given search object, so they live in HBM across
        ``run()`` calls — re-uploading them per run costs more than the
        entire device search on a remote-attached TPU.

        ``acc_lists``/``jerk_lists`` are the COMBINED trial lists
        (``_trial_lists``): jerk is folded into the unique-pair
        resample tables, and a trailing jerks grid joins the residents
        only on the legacy table-free path (``_legacy_jerks``).
        """
        if getattr(self, "_dev_inputs", None) is not None:
            return self._dev_inputs
        ndm = len(self.dm_list)
        accs = np.full((ndm_p, namax), np.nan, np.float32)
        jerks = (np.full((ndm_p, namax), np.nan, np.float32)
                 if jerk_lists is not None else None)
        for i, a in enumerate(acc_lists):
            accs[i, : len(a)] = a
            if jerks is not None:
                jerks[i, : len(a)] = jerk_lists[i]
        # edge-pad the DM rows (their accel slots are NaN, so they
        # emit nothing): zero-delay pad rows would sit next to
        # max-delay rows in the Pallas kernel's last dm_tile block and
        # explode its window-slack bound
        delays = np.empty((ndm_p, self.fil.nchans), np.int32)
        delays[:ndm] = self.delays
        delays[ndm:] = self.delays[-1] if ndm else 0
        killmask = (
            self.killmask
            if self.killmask is not None
            else np.ones(self.fil.nchans, np.float32)
        )
        nbits = self.fil.header.nbits
        rep = NamedSharding(self.mesh, P())
        shard = NamedSharding(self.mesh, P("dm", None))
        raw_d = self._staged_raw_device(rep)
        if raw_d is None:
            if nbits == 32:  # float data: nothing to pack
                raw = np.ascontiguousarray(
                    self.fil.data, np.float32).ravel()
            else:
                raw = pack_bits(self.fil.data.ravel(), nbits)
            raw_d = put_global(raw, rep)
        uidx, d0_u, pos_u, step_u = self._resample_tables(accs, jerks)
        self._dev_inputs = (
            raw_d,
            put_global(delays, shard),
            put_global(np.asarray(killmask, dtype=np.float32), rep),
            put_global(accs, shard),
            put_global(uidx, shard),
            put_global(d0_u, rep),
            put_global(pos_u, rep),
            put_global(step_u, rep),
            put_global(self.birdies, rep),
            put_global(self.bwidths, rep),
        ) + ((put_global(jerks, shard),)
             if jerks is not None and self._legacy_jerks() else ())
        return self._dev_inputs

    def _resample_tables(self, accs: np.ndarray, jerks=None):
        """Host-exact unique-accel resample tables for a NaN-padded
        accel grid (dummies when the legacy path is active).  A jerks
        grid (same shape) switches the dedup to unique (accel, jerk)
        PAIRS with the jerk term baked into each table row."""
        if self.resample_block is None:
            return (
                np.zeros(accs.shape, np.int32),
                np.zeros((1, 1), np.int32),
                np.zeros((1, 1, 1), np.int32),
                np.zeros((1, 1, 1), np.int32),
            )
        from ..ops.resample import resample2_unique_tables

        d0_u, pos_u, step_u, uidx = resample2_unique_tables(
            accs, float(self.fil.tsamp), self.size, self.max_shift,
            block=self.resample_block,
            jerks_grid=jerks,
            width=(self.table_width if jerks is not None else None),
        )
        return uidx, d0_u, pos_u, step_u

    # -- bounded-HBM chunked path (production scale) --------------------

    # per-element planner coefficient, validated against
    # compiled-program memory_analysis at 2^23 x 1024 chans on v5e
    # (temp = ~0.42 GB per live accel spectrum at accel_block 8->12):
    # ~12 full-length f32 buffers per live spectrum (resample windows,
    # fft, interbin, harmonic-sum einsum windows).  Since ISSUE 18 this
    # hand-measured figure is only the FALLBACK: on TPU _plan_chunking
    # asks obs/memprof.probed_bytes_per("spectrum") for the live
    # compiler's measured slope first.
    _SPECTRUM_BYTES = 48

    def _plan_chunking(self, namax: int) -> dict | None:
        """Decide full-materialisation vs chunked execution and pick
        chunk sizes within ``config.hbm_budget_gb``.

        Returns None for the (small-input) full path, else a plan dict.
        """
        cfg = self.config
        budget = int(cfg.hbm_budget_gb * 1e9)
        # measured planner coefficients (ISSUE 18): on TPU the
        # obs/memprof compiled-program probes supply the B/element
        # slopes this planner previously hardcoded; the literals below
        # remain the documented fallbacks (the probe returns None off
        # TPU and on any probe failure, so CPU plans are unchanged)
        from ..obs.memprof import probed_bytes_per

        spectrum_bytes = int(probed_bytes_per("spectrum")
                             or self._SPECTRUM_BYTES)
        row_bytes = int(probed_bytes_per("row") or 8)
        ndm = len(self.dm_list)
        ndm_local = int(np.ceil(ndm / self.ndev))
        dd = self._plan_fused_pallas_dedisp()
        if dd is not None:
            # the fused path widens the per-shard rows to a dm_tile
            # multiple (Pallas dedispersion); budget the rows it will
            # actually run, not the narrower pre-widening count
            ndm_local = dd["ndm_p"] // self.ndev
        est_full = (
            spectrum_bytes * ndm_local * namax * self.size
            + row_bytes * ndm_local * self.out_nsamps
            + self._data_bytes()
            # the fused program's device unpack materialises a full f32
            # channel-major transient alongside the packed input
            + 4 * self.fil.nchans * self.fil.nsamps
        )
        METRICS.gauge("hbm.est_full_bytes", est_full)
        METRICS.gauge("hbm.budget_bytes", budget)
        if est_full <= budget and not cfg.dm_chunk and not cfg.accel_block:
            return None

        avail = budget - self._data_bytes()
        if avail <= 0:
            raise HBMBudgetError(
                f"filterbank alone ({self._data_bytes()/1e9:.1f} GB) "
                f"exceeds hbm_budget_gb={cfg.hbm_budget_gb}"
            )
        # the row scan keeps ONE whiten + accel_block spectra live;
        # dm_chunk rows only cost their dedispersed trials.  A quarter
        # of the budget goes to trials, the rest to the accel batch
        # (wider batches keep the chip fed: 21-wide measured 18.6
        # ms/trial vs 72 ms/trial for 8-row x 1-accel nesting)
        if cfg.dm_chunk:
            dm_chunk = cfg.dm_chunk
        else:
            # marginal HBM cost per DM row, validated against the
            # compiler's memory_analysis at 2^23 x 1024 chans: 68 MB/row
            # = two f32 trial-length buffers (the whiten workspace is
            # per-spectrum, not per-row — one row is whitened at a time
            # inside the scan).  Larger chunks matter: dedispersion
            # re-reads the whole filterbank once per chunk
            per_row = row_bytes * self.out_nsamps
            dm_chunk = int(max(1, min(32, (avail // 4) // per_row)))
        if cfg.accel_block:
            accel_block = cfg.accel_block
        else:
            live = (avail * 3 // 4) // (spectrum_bytes * self.size)
            accel_block = int(max(1, min(namax, live)))
        ndm_local_p = int(np.ceil(ndm_local / dm_chunk)) * dm_chunk
        namax_p = int(np.ceil(namax / accel_block)) * accel_block

        # dedispersion method: the FLAT-input tiled Pallas kernel
        # (ops/dedisperse_pallas.py:_dedisperse_flat_kernel) needs a
        # TPU, a 2*chan_group-divisible channel count (pairwise static
        # double buffering) and a full time tile.  The XLA scan
        # fallback's unaligned u8 slices run at ~3% of the HBM
        # roofline — 11.2 s vs the kernel's ~0.7 s per 9-row chunk at
        # 2^23 x 1024 chans on v5e.
        chan_group = 16
        time_tile = next(
            (t for t in (31744, 15360, 7168, 3072, 1024)
             if t <= self.out_nsamps), 0,
        )
        # VMEM out-block is (dm_tile, 8, TQ) f32 — cap the tile at 32
        # rows (~2 MB at TQ=1920) so a large user-set dm_chunk cannot
        # blow VMEM; the largest divisor <= 32 always tiles dm_chunk
        # evenly, so no dm_chunk value forces the slow scan fallback
        dm_tile = next(t for t in range(min(32, dm_chunk), 0, -1)
                       if dm_chunk % t == 0)
        on_tpu = jax.devices()[0].platform == "tpu"
        use_pallas = (
            on_tpu
            and time_tile >= 7168  # kernel needs 8*TQ with TQ >= 896
            and self.out_nsamps >= time_tile
            and self.fil.nchans % (2 * chan_group) == 0
        )
        plan = dict(
            dm_chunk=dm_chunk, accel_block=accel_block,
            ndm_local_p=ndm_local_p, namax_p=namax_p,
            dedisp_method="pallas" if use_pallas else "scan",
            dm_tile=dm_tile, time_tile=time_tile, chan_group=chan_group,
            window_slack=0, pad_to=self.fil.nsamps,
        )
        if use_pallas:
            from ..ops.dedisperse_pallas import (
                dedisperse_flat_pad_to,
                dedisperse_window_slack,
            )

            ndm_pp = ndm_local_p * self.ndev
            # edge-pad (like the kernel wrapper): zero-padding would put
            # max-delay rows next to zero rows in the last DM tile and
            # explode the slack bound to ~max_delay
            delays_p = np.empty((ndm_pp, self.fil.nchans), np.int32)
            delays_p[:ndm] = self.delays
            delays_p[ndm:] = self.delays[-1]
            slack = dedisperse_window_slack(delays_p, dm_tile, chan_group)
            plan["window_slack"] = slack
            plan["pad_to"] = dedisperse_flat_pad_to(
                self.out_nsamps, self.max_delay, slack, time_tile,
                uint8=self.fil.header.nbits <= 8,
            )
        return plan

    def _plan_subband_chunks(self, plan) -> dict | None:
        """Sub-band (two-stage) dedispersion plan for the chunked
        driver, honouring ``config.subband_dedisp`` (never/auto/always).

        Anchors are chosen per (chunk, shard) cell so partial sums
        never cross a dispatch; "auto" engages only when the total
        adds compress at least 2x.  The fold/re-search paths keep the
        EXACT direct sweep for their few rows regardless (their trials
        come from ``_dedisperse_rows_device``), so folded SNRs are
        never affected by the bounded stage-2 smearing."""
        cfg = self.config
        mode = cfg.subband_dedisp
        if mode == "never":
            return None
        if mode not in ("auto", "always"):
            raise ConfigError(
                f"subband_dedisp={mode!r}: use auto, always or never")
        from ..ops.dedisperse import subband_chunk_plan
        from ..ops.dedisperse_pallas import (
            dedisperse_flat_pad_to,
            dedisperse_window_slack,
        )

        ndm = len(self.dm_list)
        ndev = self.ndev
        ndm_local_p = plan["ndm_local_p"]
        dm_chunk = plan["dm_chunk"]
        ndm_pp = ndm_local_p * ndev
        nchans = self.fil.nchans
        dm_pad = np.concatenate([
            np.asarray(self.dm_list, np.float64),
            np.repeat(float(self.dm_list[-1]), ndm_pp - ndm),
        ])
        delays_p = np.empty((ndm_pp, nchans), np.int32)
        delays_p[:ndm] = self.delays
        delays_p[ndm:] = self.delays[-1]
        n_chunks = ndm_local_p // dm_chunk
        cells = [
            np.arange(d * ndm_local_p + ci * dm_chunk,
                      d * ndm_local_p + ci * dm_chunk + dm_chunk)
            for ci in range(n_chunks)
            for d in range(ndev)
        ]
        use_pallas = plan["dedisp_method"] == "pallas"
        chan_align = 2 * plan["chan_group"] if use_pallas else 1
        sbp = subband_chunk_plan(
            dm_pad, delays_p, self.delay_tab, cells,
            chan_align=chan_align, eps=cfg.subband_eps,
        )

        def infeasible(reason):
            # an explicitly requested mode must not silently degrade to
            # the direct sweep; auto simply declines
            if mode == "always":
                raise ConfigError(
                    f"subband_dedisp=always, but the two-stage plan is "
                    f"infeasible for this search: {reason}")
            if cfg.verbose:
                print(f"sub-band dedispersion declined: {reason}")
            return None

        if sbp is None:
            return infeasible(
                "no valid anchor decomposition (nchans not aligned, "
                "non-ascending DM list, or negative stage-2 shifts)")
        if mode == "auto" and sbp["cost_ratio"] > 0.5:
            return None
        L1 = self.out_nsamps + sbp["shift_max"]
        n_anchor_p = sbp["n_anchor_p"]
        csub = sbp["bounds"][0][1] - sbp["bounds"][0][0]
        t_sub = k_sub = dm_tile_sub = None
        if use_pallas:
            # stage-1 kernel geometry (dedisperse_pallas_flat_subband).
            # Its VMEM footprint has three parts: the double-buffered
            # (D, 1, K, 8, TQ) f32 out blocks (2*D*K*T*4 bytes — the
            # dominant term once anchors pile up), the 2*chan_group
            # window buffers of W1 ~ K*T samples each, and the
            # (chan_group, 8, WQ) f32 accumulator.  Search (D, K)
            # largest-first under a 14 MB budget so a large anchor
            # count can never hit a Mosaic VMEM compile error (the
            # direct kernel caps dm_tile at 32 for the same reason).
            G = plan["chan_group"]
            t_sub = plan["time_tile"]
            if L1 < t_sub:
                return infeasible(
                    f"output too short for the stage-1 kernel window "
                    f"({L1} < time_tile={t_sub})")
            itemsize = 1 if self.fil.header.nbits <= 8 else 4
            align = 1024  # flat-kernel DMA alignment, any dtype
            # each device runs the kernel on ITS cell's n_anchor_p rows
            # (blocked from row 0 at stride D), so the slack bound must
            # be the max over per-cell tables — blocking one big
            # concatenated table would misalign when D does not divide
            # n_anchor_p and underestimate the window
            cell_tables = [
                delays_p[pad_rows] for pad_rows, _a, _s in sbp["per_cell"]
            ]
            # dm tiles the kernel can keep SMEM-blocked: the whole
            # anchor block (ntiles == 1) or sublane multiples of 8
            for D in [n_anchor_p] + [
                    d for d in (32, 24, 16, 8) if d < n_anchor_p]:
                slack_d = max(
                    int(dedisperse_window_slack(t, D, G))
                    for t in cell_tables
                )
                WL = -(-(t_sub + slack_d + align) // align) * align
                acc_b = G * 8 * (t_sub // 8 + slack_d + align) * 4
                for K in (4, 3, 2, 1):
                    W1 = -(-((K - 1) * t_sub + WL) // align) * align
                    vmem = (2 * D * K * t_sub * 4
                            + 2 * G * W1 * itemsize + acc_b)
                    if vmem <= (14 << 20):
                        dm_tile_sub, k_sub, slack = D, K, slack_d
                        break
                if k_sub is not None:
                    break
            if k_sub is None:
                return infeasible(
                    f"stage-1 kernel cannot fit VMEM even at "
                    f"dm_tile=8, k_tiles=1 (chan_group={G}, "
                    f"time_tile={t_sub}, slack={slack_d})")
            # stage 2 AS a dedispersion: the flat (n_anchor_p, nsub,
            # L1) f32 partials are a synthetic nsub-channel filterbank
            # and each fine row's assembly is one direct-kernel row
            # with delays ``assign*nsub*L1 + shift`` — one launch
            # replaces ndm*nsub XLA dynamic slices (~0.19 s/chunk on
            # v5e, more than the stage-1 sweep itself).  Rows are
            # padded per anchor (subband_stage2_layout) so no tile
            # straddles two anchors and the slack stays at the shift
            # spread, not the anchor stride.
            kernel2 = None
            nsub = sbp["nsub"]
            T2 = t_sub
            # the stage-2 kernel needs nsub % (2*chan_group) == 0
            G2 = next((g for g in (16, 8, 4, 2, 1)
                       if nsub % (2 * g) == 0), None)
            if G2 is not None and self.out_nsamps >= T2:
                from ..ops.dedisperse import subband_stage2_layout

                dm_tile2 = 8
                _, cells2p = subband_stage2_layout(
                    sbp["per_cell"], 0, dm_tile2)
                slack2 = max(
                    int(dedisperse_window_slack(c[0], dm_tile2, G2))
                    for c in cells2p)
                need2 = dedisperse_flat_pad_to(
                    self.out_nsamps, sbp["shift_max"], slack2, T2)
                L1k = -(-max(L1, need2) // align) * align
                if (n_anchor_p * nsub * L1k < 2**31
                        and (n_anchor_p - 1) * nsub * L1k
                        + sbp["shift_max"] < 2**31):
                    # int32 flat offsets hold: engage the kernel path.
                    # The path's final row selection is a bf16 one-hot
                    # einsum — prove ON THIS DEVICE, once per process,
                    # that it is bit-identical to a plain row gather
                    # before trusting it with stage-2 output
                    assert_onehot_selection_exact()
                    L1 = L1k
                    R2, cells2 = subband_stage2_layout(
                        sbp["per_cell"], L1, dm_tile2)
                    kernel2 = (R2, int(slack2), int(sbp["shift_max"]),
                               G2, dm_tile2, T2)
            # slack + align: the sb kernel's per-kk aligned slices
            # round its window one alignment unit past the K*T formula
            pad_sub = dedisperse_flat_pad_to(
                L1, self.max_delay, slack + align, k_sub * t_sub,
            )
            # every flat part must hold whole sub-bands
            plan["part_align"] = max(2 * G, csub)
        else:
            kernel2 = None
            slack = 0
            pad_sub = self.out_nsamps + self.max_delay + sbp["shift_max"]
        plan["pad_to"] = max(plan["pad_to"], pad_sub)
        # per-ci transport arrays (cells are ci-major, shard-minor)
        per_ci = []
        for ci in range(n_chunks):
            cell = sbp["per_cell"][ci * ndev : (ci + 1) * ndev]
            if kernel2 is not None:
                c2 = cells2[ci * ndev : (ci + 1) * ndev]
                per_ci.append((
                    np.concatenate([c[0] for c in cell]),      # anchor rows
                    np.concatenate([d for d, _u in c2]),       # delays2
                    np.concatenate([u for _d, u in c2]),       # unpad
                ))
            else:
                per_ci.append((
                    np.concatenate([c[0] for c in cell]),      # anchor rows
                    np.concatenate([c[1] for c in cell]),      # assign
                    np.concatenate([c[2] for c in cell], axis=0),  # shifts
                ))
        if self.config.verbose:
            print(
                f"sub-band dedispersion: nsub={sbp['nsub']} "
                f"anchors<={n_anchor_p}/cell cost_ratio="
                f"{sbp['cost_ratio']:.2f} max_err={sbp['max_err']} "
                f"samples"
            )
        return dict(
            bounds=sbp["bounds"], L1=L1, n_anchor_p=n_anchor_p,
            slack=int(slack), per_ci=per_ci, max_err=sbp["max_err"],
            cost_ratio=sbp["cost_ratio"], nsub=sbp["nsub"],
            csub=csub, t_sub=t_sub, k_sub=k_sub,
            dm_tile_sub=dm_tile_sub, kernel2=kernel2,
        )

    def _device_inputs_chunked(self, plan, acc_lists, jerk_lists=None):
        """Upload-once device state for the per-chunk dispatches.

        Big replicated arrays (flat data, unique resample tables,
        zap lists) live in HBM across all dispatches in
        ``self._dev_chunk_static``; the per-row arrays (delays, accel
        grid, per-slot jerks, table indices) stay HOST-side in
        ``self._host_chunk_arrays`` — each dispatch uploads only its
        chunk's (tiny) row slices.  ``acc_lists``/``jerk_lists`` are
        the COMBINED trial lists (``_trial_lists``)."""
        if getattr(self, "_dev_chunk_static", None) is not None:
            return
        ndm = len(self.dm_list)
        ndm_pp = plan["ndm_local_p"] * self.ndev
        namax_p = plan["namax_p"]
        accs = np.full((ndm_pp, namax_p), np.nan, np.float32)
        jerks = np.full((ndm_pp, namax_p), np.nan, np.float32)
        for i, a in enumerate(acc_lists):
            accs[i, : len(a)] = a
            if jerk_lists is not None:
                jerks[i, : len(a)] = jerk_lists[i]
        # edge-pad to match the planner's slack bound (padded rows emit
        # nothing: their accel slots are all NaN)
        delays = np.empty((ndm_pp, self.fil.nchans), np.int32)
        delays[:ndm] = self.delays
        delays[ndm:] = self.delays[-1]
        nbits = self.fil.header.nbits
        nchans, nsamps = self.fil.nchans, self.fil.nsamps
        # single allocation: transpose-copy + killmask + tail pad in
        # place (three sequential full copies would transiently need
        # ~3x the multi-GB input on the host).  The transpose itself is
        # threaded over channel blocks: a byte-granular (nsamps, nchans)
        # -> (nchans, nsamps) strided assignment is the single largest
        # host cost of the production prep (numpy releases the GIL in
        # the strided copy, so threads scale)
        data = np.zeros(
            (nchans, max(plan["pad_to"], nsamps)),
            np.uint8 if nbits <= 8 else np.float32,
        )
        src = self.fil.data
        km = self.killmask

        def _tblock(c0):
            c1 = min(c0 + 64, nchans)
            data[c0:c1, :nsamps] = src[:, c0:c1].T
            if km is not None:
                data[c0:c1, :nsamps] *= km[c0:c1, None].astype(data.dtype)

        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(min(16, os.cpu_count() or 8)) as ex:
            list(ex.map(_tblock, range(0, nchans, 64)))
        rep = NamedSharding(self.mesh, P())
        uidx, d0_u, pos_u, step_u = self._resample_tables(
            accs, jerks if jerk_lists is not None else None)
        self._host_chunk_arrays = (delays, accs, jerks, uidx)
        parts = tuple(
            put_global(p, rep)
            for p in split_flat_channels(
                data,
                # part_align: sub-band stage 1 needs every part to
                # hold whole sub-bands (set by _plan_subband_chunks)
                align=plan.get(
                    "part_align",
                    2 * plan["chan_group"]
                    if plan["dedisp_method"] == "pallas" else 1),
            )
        )
        self._dev_chunk_static = (
            parts,
            put_global(d0_u, rep),
            put_global(pos_u, rep),
            put_global(step_u, rep),
            put_global(self.birdies, rep),
            put_global(self.bwidths, rep),
        )

    def _dedisperse_rows_device(self, delays_rows, dm_tile=1):
        """One dedispersion-only dispatch over the resident flat parts
        for the given delay rows (fold re-dedispersion and stage
        measurement).

        ``dm_tile=1`` is always slack-valid — a (1, chan_group)
        block's delay spread is <= the plan's (dm_tile, chan_group)
        bound — and is required when the rows are scattered DMs; the
        stage measurement passes the plan's tile to reflect the real
        chunk configuration."""
        plan = self._chunk_plan
        data_parts = self._dev_chunk_static[0]  # flat parts (see
        nchans = self.fil.nchans                # _device_inputs_chunked)
        nsamps_dev = sum(p.shape[0] for p in data_parts) // nchans
        # one jit object per dm_tile, cached on the search object: a
        # fresh jax.jit per call would recompile every invocation (the
        # jit cache lives on the callable)
        cache = self.__dict__.setdefault("_dedisp_rows_jit", {})
        fn = cache.get(dm_tile)
        if fn is None:
            if plan["dedisp_method"] == "pallas":
                from ..ops.dedisperse_pallas import dedisperse_pallas_flat

                fn = jax.jit(
                    lambda d, *fs: dedisperse_pallas_flat(
                        list(fs), d, nsamps_dev, self.out_nsamps,
                        window_slack=plan["window_slack"],
                        dm_tile=dm_tile, time_tile=plan["time_tile"],
                        chan_group=plan["chan_group"],
                        max_delay=self.max_delay,
                    )
                )
            else:
                fn = jax.jit(
                    lambda d, *fs: dedisperse_flat(
                        list(fs), d, nsamps_dev, self.out_nsamps)
                )
            cache[dm_tile] = fn
        with span("Dedisperse", metric="dedispersion",
                  n_rows=int(len(delays_rows)),
                  dm_tile=int(dm_tile),
                  gflops=round(self._dedisp_rows_gflops(
                      len(delays_rows)), 3)) as sp:
            return self._maybe_quantise(
                sp.block(fn(jnp.asarray(delays_rows), *data_parts)))

    def _dedisp_rows_gflops(self, n_rows: int) -> float:
        """Modelled Gflops of an ``n_rows``-row dedispersion dispatch
        (obs/costmodel.py — the span attribute trace viewers read)."""
        from ..obs.costmodel import dedisperse_cost

        return dedisperse_cost(
            int(n_rows), self.fil.nchans, self.out_nsamps,
            1 if self.fil.header.nbits <= 8 else 4,
        ).flops / 1e9

    def measure_dedispersion_stage(self) -> float:
        """One warm + one timed dedispersion-only dispatch; returns the
        steady-state stage seconds (also recorded as a ``Dedisperse``
        span / ``dedispersion`` stage timer).

        The mesh programs fuse dedispersion into the search dispatch,
        so there is no in-run stage boundary to clock — this dedicated
        dispatch is how ``--measure_stages`` (and bench.py) put a real
        number in ``timers["dedispersion"]`` instead of the 0.0 the
        fused path otherwise reports.
        """
        import time

        warm = self.dedisperse_sharded()
        np.asarray(warm[:1, :1])  # compile + execute untimed
        t0 = time.time()
        with span("Dedisperse", metric="dedispersion",
                  n_dm_trials=len(self.dm_list), measured=True,
                  gflops=round(self._dedisp_rows_gflops(
                      len(self.dm_list)), 3)) as sp:
            trials = self.dedisperse_sharded()
            sp.block(trials)
        return time.time() - t0

    def _fold_trials_provider(self, dm_idxs):
        """Re-dedisperse just the candidate DM rows for folding (the
        chunked program cannot keep (ndm, out_nsamps) trials resident;
        the reference holds them host-side, `pipeline_multi.cu:258`)."""
        uniq = sorted(set(int(i) for i in dm_idxs))
        row_map = {dm: r for r, dm in enumerate(uniq)}
        trials = self._dedisperse_rows_device(self.delays[uniq])
        return trials, row_map

    def _fused_fold_provider(self, dm_idxs):
        """On-device fold fusion (ISSUE 11): (dm_idxs) -> (fold_program,
        row_map) for ``_finalise``'s ``fold_fuser`` seam.

        The returned program composes candidate-row dedispersion with
        :func:`fold_epilogue_core` in ONE dispatch: unpack the resident
        packed filterbank bytes, dedisperse just the ``len(uniq)``
        candidate DM rows, (optionally) quantise exactly as
        ``_maybe_quantise`` would, then whiten/resample/fold/optimise.
        The trial lattice never leaves the device — the only
        device->host traffic of the whole folding phase is the packed
        optimum-per-candidate buffer, so candidates cross the link
        once per job.  Numerically identical to the host-resident
        path: the per-row dedisperse -> (quantise) -> epilogue chain
        is the same jnp graph, just composed into one program."""
        from ..ops.unpack import unpack_bits_device

        uniq = sorted(set(int(i) for i in dm_idxs))
        row_map = {dm: r for r, dm in enumerate(uniq)}
        rep = NamedSharding(self.mesh, P())
        nbits = self.fil.header.nbits
        if getattr(self, "_dev_inputs", None) is not None:
            # the fused search program's residents already hold the
            # packed bytes and killmask — zero re-upload
            raw_d, _dl, km_d = self._dev_inputs[:3]
        elif getattr(self, "_dedisp_sharded_state", None) is not None:
            _fn, raw_d, _dl, km_d = self._dedisp_sharded_state
        else:
            if nbits == 32:  # float data: nothing to pack
                raw = np.ascontiguousarray(
                    self.fil.data, np.float32).ravel()
            else:
                raw = pack_bits(self.fil.data.ravel(), nbits)
            km = (
                np.asarray(self.killmask, dtype=np.float32)
                if self.killmask is not None
                else np.ones(self.fil.nchans, np.float32)
            )
            raw_d = put_global(raw, rep)
            km_d = put_global(km, rep)
        delays_d = put_global(self.delays[uniq].astype(np.int32), rep)
        nchans, nsamps_in = self.fil.nchans, self.fil.nsamps
        out_nsamps = self.out_nsamps
        use_km = self.killmask is not None
        lattice = self.lattice

        @partial(jax.jit, static_argnames=(
            "bin_width", "fold_nsamps", "tsamp", "nbins", "nints",
            "max_shift", "block", "nu", "nb", "w"))
        def fused(raw, km, delays, packed_in, periods, *, bin_width,
                  fold_nsamps, tsamp, nbins, nints, max_shift, block,
                  nu, nb, w):
            # same transient channel-major view as dedisperse_sharded
            vals = unpack_bits_device(raw, nbits)[: nsamps_in * nchans]
            data = vals.reshape(nsamps_in, nchans).T.astype(jnp.float32)
            if use_km:
                data = data * km[:, None]
            trials = dedisperse(data, delays, out_nsamps)
            if lattice == "u8":
                trials = quantise_trials_u8(trials, nbits, nchans)
            elif lattice == "bf16":
                trials = quantise_trials_bf16(trials)
            return fold_epilogue_core(
                trials, packed_in, periods, bin_width, fold_nsamps,
                tsamp, nbins, nints, max_shift, block, nu, nb, w)

        def fold_program(packed_d, periods_d, bin_width, fold_nsamps,
                         tsamp, nbins, nints, max_shift, block, nu,
                         nb, w):
            METRICS.inc("runs.fused_fold_dispatches")
            return fused(
                raw_d, km_d, delays_d, packed_d, periods_d,
                bin_width=bin_width, fold_nsamps=fold_nsamps,
                tsamp=tsamp, nbins=nbins, nints=nints,
                max_shift=max_shift, block=block, nu=nu, nb=nb, w=w)

        return fold_program, row_map

    def _run_chunked(self, plan, acc_lists, namax, timers, t_total, ckpt,
                     ckpt_done, jerk_lists=None):
        """Bounded-HBM production driver: ONE dispatch per DM chunk.

        A single whole-search dispatch at production scale (500 DM x
        21 accel x 2^23 samples) runs for minutes inside one XLA
        program — long enough to hit backend execution limits (the v5e
        worker died mid-run with a kernel-fault report), with no
        progress visibility and an all-or-nothing failure mode.  Each
        chunk of ``dm_chunk`` rows per device is instead its own
        dispatch (~10 s of device time): the per-chunk program is
        compiled once (and persistent-cached), results stream home,
        the checkpoint advances as chunks land, and buffer escalation
        re-runs one chunk instead of the whole search.  The reference
        streams trials the same way (`src/pipeline_multi.cu:145-157`).
        """
        import time

        cfg = self.config
        METRICS.inc("runs.mesh_chunked")
        if cfg.dump_dir:
            warn_event(
                "path_fallback",
                "--dump_dir is ignored on the bounded-HBM chunked path "
                "(trials are never all resident); re-run with "
                "--single_device or a smaller input to dump whitening "
                "stages",
                what="dump_dir", path="chunked",
            )
        ndm = len(self.dm_list)
        ndm_local_p = plan["ndm_local_p"]
        dm_chunk = plan["dm_chunk"]
        namax_p = plan["namax_p"]
        nlevels = cfg.nharmonics + 1
        # persistent buffer tuning: a prior run of the SAME search
        # recorded its true high-water counts, so this run can size the
        # per-spectrum capacity for the bulk of rows (pathological
        # ones stay on the re-search path by design) and
        # the compacted transfer buffer to the observed total (+margin)
        # instead of the worst case.  Results are identical either way;
        # see search/tuning.py.
        from ..search.tuning import load_tuning, round_up, save_tuning

        tune = (load_tuning(cfg.tune_file, self._tune_scoped_key("chunked"))
                if cfg.tune_file else None)
        if tune is not None:
            from ..search.tuning import (
                calibration_constants,
                pick_row_capacity,
            )

            # bound the capacity so the stacked per-chunk peak buffers
            # (dm_chunk x namax x nlevels x cap, idx+snr) stay <= 1 GB
            cap_ceil = max(64, (1 << 30) // (dm_chunk * namax_p
                                             * nlevels * 8))
            if tune.get("row_hw"):
                # per-row counts known: cover the BULK of rows and
                # leave pathological ones to the cheap re-search (a
                # 13k-count pulsar row must not make every spectrum's
                # top_k 13x bigger — measured +330 s at full scale);
                # cost constants are this device's measured calibration
                # when the sidecar has one, v5e defaults otherwise
                n_tr = sum(len(a) for a in acc_lists)
                cal = calibration_constants(cfg.tune_file)
                cap = round_up(
                    pick_row_capacity(
                        tune["row_hw"], n_tr,
                        slot_s=cal["slot_s"],
                        research_s=cal["research_s"],
                        compile_s=cal["compile_s"]),
                    64, 64, cap_ceil)
            else:
                cap = round_up(tune["cap_hw"] + 32, 64, 64, cap_ceil)
        else:
            cap = cfg.peak_capacity
        # per-SHARD slot count: compact_k and nvalid are per-shard
        chunk_slots = dm_chunk * namax_p * nlevels * cap
        if tune is not None:
            # margin absorbs same-data jitter; a genuinely different
            # input mismatches the tune key and never reaches here
            compact_k = round_up(int(tune["ck_hw"] * 1.2) + 1024, 8192,
                                 8192, chunk_slots)
        else:
            compact_k = chunk_slots
        # observability: the benchmark's transfer model reads these
        self._chunk_buffer_shapes = (cap, compact_k)
        self._chunk_plan = plan
        self.record_peaks_selection(cap)
        METRICS.gauge("chunk.dm_chunk", dm_chunk)
        METRICS.gauge("chunk.accel_block", plan["accel_block"])
        METRICS.gauge("chunk.peak_capacity", cap)
        METRICS.gauge("chunk.compact_k", compact_k)

        t0 = time.time()
        # sub-band (two-stage) dedispersion plan — must precede the
        # data upload: stage-1 windows may need extra tail padding
        # (plan["pad_to"] is updated in place)
        sb = self._plan_subband_chunks(plan)
        self._device_inputs_chunked(plan, acc_lists, jerk_lists)
        data_parts, d0_u, pos_u, step_u, birdies_d, widths_d = (
            self._dev_chunk_static
        )
        delays_h, accs_h, jerks_h, uidx_h = self._host_chunk_arrays
        use_jerks = self._legacy_jerks()
        rep = NamedSharding(self.mesh, P())
        shard = NamedSharding(self.mesh, P("dm", None))
        shard1 = NamedSharding(self.mesh, P("dm"))

        def build(cap_, ck_):
            return build_chunked_search(
                self.mesh,
                nchans=self.fil.nchans,
                out_nsamps=self.out_nsamps,
                size=self.size,
                ndm_local=dm_chunk,
                dm_chunk=dm_chunk,
                namax=namax_p,
                accel_block=plan["accel_block"],
                bin_width=self.bin_width,
                tsamp=float(self.fil.tsamp),
                nharms=cfg.nharmonics,
                bounds=self.bounds,
                capacity=cap_,
                min_snr=cfg.min_snr,
                b5=cfg.boundary_5_freq,
                b25=cfg.boundary_25_freq,
                use_zap=bool(len(self.birdies)),
                compact_k=ck_,
                max_shift=self.max_shift,
                dedisp_method=plan["dedisp_method"],
                window_slack=plan["window_slack"],
                dm_tile=plan["dm_tile"],
                time_tile=plan["time_tile"],
                chan_group=plan["chan_group"],
                max_delay_samples=self.max_delay,
                block=self.resample_block,
                n_parts=len(data_parts),
                subband=(
                    (sb["bounds"], sb["L1"], sb["n_anchor_p"],
                     sb["slack"], sb["csub"], sb["t_sub"],
                     sb["k_sub"], sb["dm_tile_sub"], sb["kernel2"])
                    if sb is not None else None
                ),
                quantise_nbits=(
                    self.fil.header.nbits
                    if self.lattice == "u8" else 0
                ),
                lattice=self.lattice,
                use_jerks=use_jerks,
                peaks_methods=self.peaks_methods_for(cap_),
                compact_method=self.compact_method_for(ck_),
            )

        n_chunks = ndm_local_p // dm_chunk
        dm_cands = CandidateCollection()
        all_clipped: dict[int, int] = {}  # global row -> max count
        # per-phase breakdown across all chunks (VERDICT r2 item 2:
        # the wall/device-model gap must be attributable).  "prep" is
        # the host-side setup before the first dispatch — sub-band
        # planning, the threaded transpose into flat parts, resample
        # tables, upload initiation — previously unattributed (~200 s
        # of searching_device at production scale, VERDICT r3)
        phases = {"prep": 0.0, "upload": 0.0, "compile": 0.0,
                  "dispatch": 0.0, "fetch": 0.0, "decode": 0.0,
                  "distill": 0.0, "checkpoint": 0.0}
        self._chunk_phases = phases

        tc = time.time()
        phases["prep"] = tc - t0
        # untuned, the compacted buffer is the FULL slot count (~7 MB
        # at dm_chunk=8 x 21 accels x 5 levels x 1024): truncation is
        # impossible, so no escalation/recompile path exists here
        # (per-spectrum capacity overflow is handled by the row
        # re-runs below).  Tuned, compact_k < slots and a truncated
        # row (possible only if the data changed under the tune key)
        # joins the clipped set for the same re-run path.
        program = build(cap, compact_k)
        todo = []
        n_live = 0  # chunks holding any real (non-padding) DM row
        for ci in range(n_chunks):
            # per-device row block ci: rows d*ndm_local_p + [c0, c0+dm_chunk)
            c0 = ci * dm_chunk
            rows = np.concatenate([
                np.arange(d * ndm_local_p + c0,
                          d * ndm_local_p + c0 + dm_chunk)
                for d in range(self.ndev)
            ])
            n_live += any(int(r) < ndm for r in rows)
            if all(int(r) in ckpt_done or int(r) >= ndm for r in rows):
                continue  # checkpoint resume: chunk already searched
            todo.append((ci, rows))

        def dispatch(ci, rows):
            sb_args = ()
            if sb is not None:
                anchor_rows, a2, a3 = sb["per_ci"][ci]
                if (plan["dedisp_method"] == "pallas"
                        and sb["kernel2"] is not None):
                    # (delays2 (ndev*R2, nsub), unpad (ndev*dm_chunk,))
                    sb_args = (
                        put_global(delays_h[anchor_rows], shard),
                        put_global(a2, shard),
                        put_global(a3, shard1),
                    )
                else:
                    sb_args = (
                        put_global(delays_h[anchor_rows], shard),
                        put_global(a2, shard1),
                        put_global(a3, shard),
                    )
            # per-chunk attribution: which DM rows this dispatch covers
            # and how many real (non-padding) trials it searches.  NB
            # the span closes at dispatch RETURN (execution is async by
            # design — double-buffering); the wait shows up in the
            # fetch span of the same chunk.
            live = [int(r) for r in rows if int(r) < ndm]
            n_trials_chunk = sum(len(acc_lists[r]) for r in live)
            # modelled per-chunk work: each live trial's search cost
            # plus each live row's dedisp + whiten (obs/costmodel.py)
            gflops = (getattr(self, "_per_trial_gflops", 0.0)
                      * n_trials_chunk
                      + getattr(self, "_per_dmrow_gflops", 0.0)
                      * len(live))
            with span(f"Chunked-Search-{ci}", chunk=int(ci),
                      n_dm_rows=len(live),
                      dm_lo=(float(self.dm_list[min(live)])
                             if live else None),
                      dm_hi=(float(self.dm_list[max(live)])
                             if live else None),
                      n_trials=n_trials_chunk,
                      gflops=round(gflops, 3)):
                return program(
                    *data_parts,
                    *sb_args,
                    put_global(delays_h[rows], shard),
                    put_global(accs_h[rows], shard),
                    *((put_global(jerks_h[rows], shard),)
                      if use_jerks else ()),
                    put_global(uidx_h[rows], shard),
                    d0_u, pos_u, step_u, birdies_d, widths_d,
                )

        hw_count = 0  # observed high-waters for the tune sidecar
        hw_valid = 0
        row_hw = np.zeros(ndm, np.int64)  # per-DM-row max counts
        first_dispatch = True

        def dispatch_item(item):
            # the first dispatch triggers the (possibly minutes-long
            # remote) XLA compile; charge it separately from steady
            # -state dispatch latency.  The multi-GB filterbank h2d
            # transfer (async since _device_inputs_chunked) overlaps
            # the compile; the residual wait is charged to "upload" so
            # the first chunk's fetch time stays comparable to the rest
            nonlocal first_dispatch, tc
            if first_dispatch:
                first_dispatch = False
                out = dispatch(*item)
                phases["compile"] = time.time() - tc
                tc = time.time()
                # a computed scalar over every part proves the h2d
                # upload landed (device_put'ed arrays keep a host copy,
                # so np.asarray of them returns instantly).  The probe
                # queues behind chunk 1's execution, so "upload" here =
                # residual transfer after compile + one chunk's device
                # time; the multi-GB transfer dominates at production
                # scale
                np.asarray(jax.jit(
                    lambda *ps: sum(p[-1].astype(jnp.float32)
                                    for p in ps)
                )(*data_parts))
                phases["upload"] = time.time() - tc
                return out
            tp = time.time()
            out = dispatch(*item)
            phases["dispatch"] += time.time() - tp
            return out

        def retire_item(token, item):
            nonlocal hw_count, hw_valid
            ci, rows = item
            tp = time.time()
            with span("Chunk-Fetch", chunk=int(ci)) as sp_f:
                tf = time.time()
                packed = finish_fetch(token)
                # the fetch wait IS device (+link) time: the dispatch
                # span closed at async return, so the wait lands here
                sp_f.add_device_time(time.time() - tf)
            phases["fetch"] += time.time() - tp
            tp = time.time()
            with span("Peak-Decode", metric="peak_decode",
                      chunk=int(ci)):
                (groups_l, mx_count, mx_valid, counts_l,
                 clipped_l, _truncated_l) = self._decode_packed(
                    packed, dm_chunk, namax_p, nlevels, cap, compact_k
                )
            hw_count = max(hw_count, mx_count)
            # per-shard TRUE totals (uncapped counts), not nvalid: when
            # this run clipped, nvalid under-measures what an unclipped
            # re-run will ship
            hw_valid = max(hw_valid, int(
                counts_l.reshape(self.ndev, -1).sum(axis=1).max()
            ))
            row_max_l = counts_l.max(axis=(1, 2))
            for key in range(len(rows)):
                ii = int(rows[key])
                if ii < ndm:
                    row_hw[ii] = max(row_hw[ii], int(row_max_l[key]))
            phases["decode"] += time.time() - tp
            for key in clipped_l:
                ii = int(rows[key])
                if ii < ndm:
                    all_clipped[ii] = int(counts_l[key].max())
                    if lineage.enabled():
                        # the clipped row's partial decode is discarded
                        # here; the post-loop escalated re-search emits
                        # fresh ``decoded`` marks for the row
                        grp = groups_l.get(key)
                        if grp is not None and len(grp[0]):
                            lineage.mark(
                                "superseded", run=self._lineage_run(),
                                n=int(len(grp[0])),
                                stage="clip_rerun", dm_idx=ii)
            # (overlapping the escalated re-search compiles with the
            # remaining chunks via a background warm thread was tried
            # and REVERTED: the warm executable's arena co-resides with
            # the chunk program's ~3.5 GB arena and the filterbank, and
            # an allocation failure would abort the MAIN dispatches —
            # the exact co-residency the post-loop clear exists to
            # avoid — for a benefit within run-to-run compile-cache
            # noise)
            # one segmented native call distills every non-clipped row
            # of the chunk (rows with no peaks get an empty group)
            tp = time.time()
            with span("Distill", metric="distillation", chunk=int(ci)):
                batch = self._distill_rows_batch(
                    (int(rows[key]), groups_l.get(key),
                     acc_lists[int(rows[key])],
                     None if jerk_lists is None
                     else jerk_lists[int(rows[key])])
                    for key in range(len(rows))
                    if int(rows[key]) < ndm and key not in clipped_l
                )
            n_new = 0
            for ii, cands_ii in batch.items():
                ckpt_done[ii] = cands_ii
                n_new += 1
            phases["distill"] += time.time() - tp
            tp = time.time()
            if ckpt:
                # cfg.checkpoint_interval counts DM rows (host-loop
                # cadence); tick once per completed row
                for _ in range(n_new):
                    ckpt.maybe_save(ckpt_done)
            phases["checkpoint"] += time.time() - tp
            if cfg.verbose:
                print(f"chunk {ci + 1}/{n_chunks} done "
                      f"({time.time() - t0:.0f}s; "
                      + " ".join(f"{p}={v:.1f}" for p, v in
                                 phases.items()) + ")", flush=True)

        # generalised double-buffer (ISSUE 11): at depth d the pipeline
        # keeps up to d chunk programs in flight, retiring the oldest
        # (fetch -> decode -> distill -> checkpoint, all host work)
        # only when the window is full — so host post-processing hides
        # behind device execution.  depth=2 reproduces the historical
        # dispatch(k+1)-then-fetch(k) interleave exactly; depth=1 is
        # the unpipelined A/B reference.  start_fetch begins the d2h
        # copy of each chunk's packed buffer the moment its program is
        # enqueued, so the link transfer overlaps the next dispatch.
        depth = max(1, int(getattr(cfg, "pipeline_depth", 2) or 1))
        METRICS.gauge("chunk.pipeline_depth", depth)
        DispatchPipeline(
            dispatch_item, retire_item, depth=depth,
            start_fetch=start_fetch,
        ).run(todo)

        tp = time.time()
        # drop OUR per-chunk executables before the re-search / fold
        # phases: TPU executables reserve their temp arenas at load
        # time, and the chunk program's (accel_block full-length
        # spectra, ~3.5 GB at 2^23) plus the resident filterbank left
        # too little HBM for the later phases (observed
        # RESOURCE_EXHAUSTED at production scale).  Fine-grained —
        # unlike the previous process-wide jax.clear_caches(), every
        # other compiled program (fold, whiten, tutorial-scale paths)
        # survives.  clear_cache() on the jit object itself: the local
        # `program` / `dispatch` closure still hold the callable, so
        # dropping only the lru entry would leave the executable (and
        # its arena) alive.  (Program caches keyed on Mesh are safe
        # across equal meshes: jax interns Mesh instances.)
        import gc

        if todo:  # `program` is only bound when any chunk was searched
            program.clear_cache()
        build_chunked_search.cache_clear()
        gc.collect()
        # cleanup (cache drop + full-heap gc, ~1 s on a big host heap)
        # is charged to its own phase: billing it to "research" made
        # clip-free runs look like they paid a re-search
        phases["cleanup"] = time.time() - tp
        tp = time.time()
        rerun = self._rerun_clipped_rows(
            set(all_clipped), all_clipped, self._fold_trials_provider,
        )
        for ii, cands_ii in rerun.items():
            ckpt_done[ii] = cands_ii
        # (the escalated-capacity re-search executables are freed by
        # _finalise itself before folding, for every driver)
        phases["research"] = time.time() - tp
        phases["n_clipped_rows"] = len(all_clipped)
        if cfg.tune_file and len(todo) == n_live:
            # record high-waters only when EVERY live chunk was
            # observed this run (a checkpoint resume sees a subset and
            # would understate them)
            save_tuning(cfg.tune_file, self._tune_scoped_key("chunked"),
                        hw_count, hw_valid, row_hw=row_hw)
            from ..search.tuning import record_run_calibration

            record_run_calibration(
                cfg.tune_file,
                research_s=(phases["research"] / len(all_clipped)
                            if all_clipped else None))
        # dedispersion is fused into the chunk dispatches; when stage
        # measurement is on, time one real dedisp-only dispatch and
        # scale by the number of chunks executed
        timers["dedispersion"] = 0.0
        if cfg.measure_stages and todo:
            rows0 = todo[0][1]
            # warm (compile) untimed, then time a steady-state dispatch
            warm = self._dedisperse_rows_device(
                delays_h[rows0], dm_tile=plan["dm_tile"])
            np.asarray(warm[:1, :1])
            tp = time.time()
            trials0 = self._dedisperse_rows_device(
                delays_h[rows0], dm_tile=plan["dm_tile"])
            np.asarray(trials0[:1, :1])
            timers["dedispersion"] = (time.time() - tp) * len(todo)
        timers.update({f"chunk_{p}": round(v, 2)
                       for p, v in phases.items()})
        timers["searching_device"] = time.time() - t0
        # mirror the per-phase breakdown into the metrics registry;
        # dispatch/fetch/compile are time spent waiting on the device
        # (or the link to it) — the chunked driver's device share
        for p, v in phases.items():
            if isinstance(v, float):
                METRICS.observe(f"chunk_{p}", v)
        METRICS.observe(
            "chunked_search", timers["searching_device"],
            phases["dispatch"] + phases["fetch"] + phases["compile"],
        )
        for ii in range(ndm):
            dm_cands.append(ckpt_done.get(ii, []))
        if ckpt:
            ckpt.save(ckpt_done)
        timers["searching"] = time.time() - t0
        result = self._finalise(
            dm_cands, None, timers, t_total,
            trials_provider=self._fold_trials_provider,
        )
        if ckpt:
            ckpt.remove()
        return result

    def _decode_packed(self, packed, ndm_local, namax, nlevels, cap,
                       compact_k):
        """Host decode of the per-shard packed peak buffers into
        (per_dm_groups, max_count, max_nvalid).

        ``max_count`` / ``max_nvalid`` are the TRUE high-water marks
        (the device reports true above-threshold counts even when the
        fixed buffers clipped) — the callers re-run with escalated
        buffer sizes when they exceed capacity, so no candidate is
        ever silently dropped (the reference simply sizes its buffer
        at 100000, `peakfinder.hpp:17,61`)."""
        ndev = self.ndev
        nspec_local = ndm_local * namax * nlevels
        # layout: bin_hi | bin_lo | sel_snr | counts_hi | counts_lo |
        # delivered_hi | delivered_lo | nvalid_hi | nvalid_lo — every
        # int travels as two 16-bit halves in plain f32 (exact at any
        # int32 spectrum length), see _compact_peaks
        blk_len = 3 * compact_k + 4 * nspec_local + 2
        sel_bin = np.empty(ndev * compact_k, np.int64)
        sel_snr = np.empty(ndev * compact_k, np.float32)
        counts = np.empty((ndev * ndm_local, namax, nlevels), np.int64)
        delivered = np.empty(ndev * nspec_local, np.int64)
        nvalid = np.empty(ndev, np.int64)
        for sidx in range(ndev):
            blk = packed[sidx * blk_len : (sidx + 1) * blk_len]
            sel_bin[sidx * compact_k : (sidx + 1) * compact_k] = (
                blk[:compact_k].astype(np.int64) * 65536
                + blk[compact_k : 2 * compact_k].astype(np.int64)
            )
            sel_snr[sidx * compact_k : (sidx + 1) * compact_k] = (
                blk[2 * compact_k : 3 * compact_k]
            )
            c0 = 3 * compact_k
            counts[sidx * ndm_local : (sidx + 1) * ndm_local] = (
                blk[c0 : c0 + nspec_local].astype(np.int64) * 65536
                + blk[c0 + nspec_local : c0 + 2 * nspec_local]
                .astype(np.int64)
            ).reshape(ndm_local, namax, nlevels)
            c1 = c0 + 2 * nspec_local
            delivered[sidx * nspec_local : (sidx + 1) * nspec_local] = (
                blk[c1 : c1 + nspec_local].astype(np.int64) * 65536
                + blk[c1 + nspec_local : c1 + 2 * nspec_local]
                .astype(np.int64)
            )
            nvalid[sidx] = int(blk[-2]) * 65536 + int(blk[-1])

        # reconstruct each entry's (dm_local, accel, level) tag from
        # the per-spectrum DELIVERED counts (the device compaction
        # keeps valid slots in flat spectrum order, and delivered is
        # derived from the same buffers the scatter read — so the
        # segmentation can never desynchronise even if a device-side
        # extraction anomaly under-fills a buffer), then run the
        # unique-peak merge over ALL spectra in one native segmented
        # call per shard
        factors = np.array([b[2] for b in self.bounds])
        per_dm_groups: dict[int, tuple] = {}
        clipped_rows: set[int] = set()
        truncated_rows: set[int] = set()
        for s in range(ndev):
            shard_counts = counts[s * ndm_local : (s + 1) * ndm_local]
            expect = np.minimum(shard_counts, cap).reshape(-1)
            k = delivered[s * nspec_local : (s + 1) * nspec_local]
            seg_bounds = np.minimum(
                np.concatenate([[0], np.cumsum(k)]), compact_k
            )
            # rows whose slots ran past the compacted buffer (dropped
            # tail), whose per-spectrum buffers clipped, or whose
            # extraction under-delivered: re-searched by the caller on
            # the small host path.  The causes are tracked separately:
            # only TRUNCATION is fixable by regrowing compact_k (see
            # `_escalated`)
            truncated = np.cumsum(k) > compact_k
            over = (shard_counts > cap).any(axis=(1, 2))
            under = k < expect
            if under.any():
                warn_event(
                    "peak_underdelivery",
                    f"device peak extraction under-delivered on "
                    f"{int(under.sum())} spectra (shard {s}): got "
                    f"{int(k[under].sum())} of "
                    f"{int(expect[under].sum())} expected slots — "
                    f"re-searching the affected DM rows on the host "
                    f"path (this indicates a backend top-k anomaly "
                    f"worth reporting)",
                    n_spectra=int(under.sum()), shard=int(s),
                    got=int(k[under].sum()),
                    expected=int(expect[under].sum()),
                )
            for d in range(ndm_local):
                sl = slice(d * namax * nlevels, (d + 1) * namax * nlevels)
                if truncated[sl].any():
                    truncated_rows.add(s * ndm_local + d)
                if truncated[sl].any() or over[d] or under[sl].any():
                    clipped_rows.add(s * ndm_local + d)
            total = int(seg_bounds[-1])
            blk = slice(s * compact_k, s * compact_k + total)
            # device buffers are SNR-ordered (extract_top_peaks); the
            # merge walk needs ascending bin order within each segment
            seg_id = np.repeat(
                np.arange(len(seg_bounds) - 1), np.diff(seg_bounds)
            )
            order = np.lexsort((sel_bin[blk], seg_id))
            merged_bin, merged_snr, seg_counts = segmented_unique_peaks(
                sel_bin[blk][order], sel_snr[blk][order], seg_bounds
            )
            spec = np.repeat(
                np.arange(nspec_local, dtype=np.int64), seg_counts
            )
            lvl = spec % nlevels
            acc_i = (spec // nlevels) % namax
            dml = spec // (nlevels * namax)
            freqs = merged_bin * factors[lvl]
            for d in np.unique(dml):
                m = dml == d
                per_dm_groups[int(s * ndm_local + d)] = (
                    freqs[m], merged_snr[m], acc_i[m], lvl[m]
                )
        return (per_dm_groups, int(counts.max(initial=0)),
                int(nvalid.max()), counts, clipped_rows, truncated_rows)

    def _rerun_clipped_rows(self, clipped_rows, counts, trials_provider):
        """Re-search DM rows whose peak buffers clipped, on the small
        host-loop path with a capacity sized to their true counts.

        Replaces the old escalate-and-redispatch design: the whole
        fused/chunked program would otherwise be recompiled and
        re-executed for a handful of RFI-loud rows (and large per-trial
        top_k capacities inside the big program crash the v5e
        backend).  Returns {dm_idx: distilled candidates}.
        """
        ndm = len(self.dm_list)
        rows = sorted(ii for ii in clipped_rows if ii < ndm)
        if not rows:
            return {}
        warn_event(
            "capacity_escalation",
            f"peak buffers clipped on {len(rows)} DM trial(s); "
            f"re-searching those rows with escalated capacity",
            n_rows=len(rows), rows=rows[:64],
        )
        # NOTE: a one-dispatch batched re-search (an escalated-capacity
        # chunk program over all clipped rows) was tried and REVERTED:
        # its fresh program shape cost a ~550 s remote compile at
        # production scale, more than the whole per-row loop below
        # (130-240 s, dominated by 1-2 search_accel_chunk compiles
        # shared across rows with equal escalated capacity).
        trials_sel, row_map = trials_provider(rows)

        def row_max_of(ii):
            # ``counts`` maps row -> max above-threshold count (or an
            # array indexable by row on the fused path)
            row_max = counts[ii]
            if not np.isscalar(row_max) and not isinstance(row_max, int):
                row_max = int(np.asarray(row_max).max())
            return int(row_max)

        # ONE shared escalated capacity across every clipped row: each
        # distinct capacity is a fresh search_accel_chunk compile
        # (~15-25 s through the remote compiler) while the extra top_k
        # slots cost milliseconds — per-row capacities measured 170 s
        # for 10 rows at production scale, mostly compiles
        cap2 = 1 << int(np.ceil(np.log2(max(
            max(row_max_of(ii) for ii in rows),
            self.config.peak_capacity) + 1)))
        out = {}
        for ii in rows:
            tim = self._trial_tim(trials_sel, row_map[ii])
            # narrow accel batches: at production scale the replicated
            # filterbank already occupies most of HBM, and escalated
            # capacities widen every per-trial buffer (a 16-wide batch
            # OOM'd on v5e with 8.6 GB of data resident)
            out[ii] = self._search_tim(tim, ii, start_capacity=cap2,
                                       accel_chunk=4)
        return out

    @staticmethod
    def _escalated(cap, compact_k, max_count, max_nvalid, total_slots,
                   n_truncated, ndm):
        """Next (capacity, compact_k) after a compacted-buffer
        overflow, or None.

        Per-spectrum capacity is NEVER escalated here (clipped rows are
        re-searched individually, `_rerun_clipped_rows`); the shared
        compacted buffer is only regrown when so many rows TRUNCATED
        by it (over-capacity rows would stay clipped regardless of
        compact_k) that per-row re-runs would cost more than
        recompiling the dispatch."""
        if (max_nvalid > compact_k and compact_k < total_slots
                and n_truncated > max(4, ndm // 4)):
            new_ck = int(min(
                total_slots, 1 << int(np.ceil(np.log2(max_nvalid)))
            ))
            warn_event(
                "compact_buffer_escalation",
                f"compacted peak buffer truncated {n_truncated} rows "
                f"({max_nvalid}/{compact_k}); re-running with "
                f"compact_capacity={new_ck}",
                n_truncated=int(n_truncated), max_nvalid=int(max_nvalid),
                compact_k=int(compact_k), new_compact_k=new_ck,
            )
            return cap, new_ck
        return None

    def run(self) -> SearchResult:
        import time

        from ..obs.compilation import set_compile_context
        from ..obs.metrics import install_compile_hook

        install_compile_hook()
        # compile attribution (ISSUE 18): ledger every backend compile
        # this run triggers against its search geometry
        set_compile_context(
            program="mesh.search",
            geometry={"nchans": int(self.fil.nchans),
                      "nbits": int(self.fil.header.nbits),
                      "size": int(self.size),
                      "out_nsamps": int(self.out_nsamps),
                      "n_dm": len(self.dm_list)})
        cfg = self.config
        timers: dict[str, float] = {}
        t_total = time.time()
        # duty-cycle ledger origin: _finalise sums device seconds of
        # every span recorded from here on (ISSUE 11)
        self._span_cursor0 = span_cursor()
        METRICS.gauge("hbm.data_bytes", self._data_bytes())
        METRICS.gauge("search.n_dm_trials", len(self.dm_list))
        METRICS.gauge("search.fft_size", self.size)
        METRICS.gauge("search.n_devices", self.ndev)

        ndm = len(self.dm_list)

        # checkpoint resume: the mesh search is a single dispatch, so a
        # complete checkpoint skips the device program entirely (trials
        # are re-dedispersed only if folding needs them)
        ckpt, ckpt_done = self._make_checkpoint()
        if ckpt and len(ckpt_done) == ndm:
            timers["dedispersion"] = 0.0
            timers["searching"] = 0.0
            dm_cands = CandidateCollection()
            for ii in range(ndm):
                dm_cands.append(ckpt_done[ii])
            # a production-scale resume must not fall back to full
            # trial materialisation: honour the bounded-HBM plan
            acc_lists = [
                self.acc_plan.generate_accel_list(dm)
                for dm in self.dm_list
            ]
            acc_lists, jerk_lists = self._trial_lists(acc_lists)
            namax = max(len(a) for a in acc_lists)
            plan = self._plan_chunking(namax) if cfg.npdmp > 0 else None
            if plan is not None:
                self._chunk_plan = plan
                self._device_inputs_chunked(plan, acc_lists, jerk_lists)
                result = self._finalise(
                    dm_cands, None, timers, t_total,
                    trials_provider=self._fold_trials_provider,
                )
            else:
                # fused fold (ISSUE 11): instead of materialising every
                # DM row's trial HBM-resident just to fold a handful of
                # candidates, _finalise hands the candidate DM set to
                # _fused_fold_provider, whose program dedisperses ONLY
                # those rows and folds them in the same dispatch — the
                # (ndm, out_nsamps) trials array never exists and the
                # only device->host traffic is the folded profiles
                result = self._finalise(
                    dm_cands, None, timers, t_total,
                    fold_fuser=self._fused_fold_provider,
                )
            ckpt.remove()
            return result
        ndm_p = self._padded_trial_count()
        ndev = self.ndev
        ndm_local = ndm_p // ndev
        acc_lists = [
            self.acc_plan.generate_accel_list(dm) for dm in self.dm_list
        ]
        # jerk axis (ISSUE 13): from here on acc_lists are the COMBINED
        # (accel, jerk) per-DM trial lists — identical objects when the
        # plan is jerk-free — so the padded grid, HBM budget, cost
        # model and dispatch attribution all scale with the full
        # trial product without further special-casing
        acc_lists, jerk_lists = self._trial_lists(acc_lists)
        namax = max(len(a) for a in acc_lists)
        n_trials_total = sum(len(a) for a in acc_lists)
        from ..obs.costmodel import record_run_costs

        run_costs = record_run_costs(self, acc_lists)["stages"]

        plan = self._plan_chunking(namax)
        if plan is not None:
            if cfg.verbose:
                print(
                    f"chunked search: dm_chunk={plan['dm_chunk']} "
                    f"accel_block={plan['accel_block']} "
                    f"dedisp={plan['dedisp_method']}"
                )
            return self._run_chunked(
                plan, acc_lists, namax, timers, t_total, ckpt,
                ckpt_done, jerk_lists,
            )
        if cfg.subband_dedisp != "never":
            warn_event(
                "path_fallback",
                "subband_dedisp is ignored on the fused (small-input) "
                "mesh path: its one-dispatch program keeps the exact "
                "direct sweep, which is already cheap at this scale; "
                "the chunked production driver and --single_device "
                "honour it",
                what="subband_dedisp", path="fused",
            )
        nlevels = cfg.nharmonics + 1
        # Pallas-kernel dedispersion inside the fused program: needs DM
        # rows divisible by dm_tile per shard, so the row padding
        # widens before the device inputs are built
        dd_pallas = self._plan_fused_pallas_dedisp()
        if dd_pallas is not None:
            ndm_p = dd_pallas["ndm_p"]
            ndm_local = ndm_p // ndev
        # capacity auto-tune: a previous run on this object observed the
        # true per-spectrum high-water count, so later runs shrink the
        # per-spectrum top_k (its cost scales with k on v5e); overflow
        # stays impossible — clipped rows are re-searched with escalated
        # capacity like any other overflow
        from ..search.tuning import load_tuning, round_up, save_tuning

        if cfg.tune_file and getattr(self, "_cap_hint", None) is None:
            # cross-RUN seeding of the same hints (search/tuning.py)
            tune = load_tuning(cfg.tune_file, self._tune_scoped_key("fused"))
            if tune is not None:
                self._cap_hint = round_up(tune["cap_hw"] + 32, 64, 64,
                                          cfg.peak_capacity)
                self._ck_hint = round_up(int(tune["ck_hw"] * 1.1), 8192,
                                         8192, cfg.compact_capacity)
        cap = min(cfg.peak_capacity,
                  getattr(self, "_cap_hint", cfg.peak_capacity))
        # clamp to the shard's total slot count (small configs); a
        # previous run's true valid-peak count also tightens the
        # compacted buffer (the packed fetch rides a ~35 MB/s tunnel,
        # so every shipped megabyte costs ~30 ms)
        compact_k = min(
            cfg.compact_capacity, ndm_local * namax * nlevels * cap,
            getattr(self, "_ck_hint", cfg.compact_capacity),
        )

        t0 = time.time()
        inputs = self._device_inputs(acc_lists, ndm_p, namax, jerk_lists)
        cap0 = cap
        self.record_peaks_selection(cap)

        def make_program(capacity, ck):
            return self._fused_program(capacity, ck, dd_pallas)

        METRICS.inc("runs.mesh_fused")
        while True:
            program = make_program(cap, compact_k)
            # modelled work of everything fused into this one dispatch
            # (dedispersion + whiten + per-trial spectra/harmonics/
            # peaks) so the trace slice reads as achieved Gflop/s
            fused_gflops = sum(
                run_costs[s].flops
                for s in ("dedisperse", "spectrum", "harmonics", "peaks")
            ) / 1e9
            with span("Fused-Search", metric="fused_search",
                      n_dm_trials=ndm, n_trials=int(n_trials_total),
                      dm_lo=float(self.dm_list[0]),
                      dm_hi=float(self.dm_list[-1]),
                      capacity=int(cap), compact_k=int(compact_k),
                      hbm_budget_bytes=float(cfg.hbm_budget_gb * 1e9),
                      gflops=round(fused_gflops, 3),
                      ) as sp:
                packed, trials = program(*inputs)
                # ONE gather over ICI/DCN -> host; ``trials`` stays on
                # device for the folding phase.  The fetch wait is the
                # device (plus link) share of this stage's wall-clock.
                # start_fetch begins the d2h copy the moment XLA
                # finishes the packed buffer, so the link transfer
                # overlaps whatever Python does before the blocking
                # finish (depth=1 A/B keeps the old synchronous fetch)
                if getattr(cfg, "pipeline_depth", 2) > 1:
                    start_fetch(packed)
                tf = time.time()
                packed = finish_fetch(packed)
                sp.add_device_time(time.time() - tf)
            with span("Peak-Decode", metric="peak_decode"):
                (per_dm_groups, mx_count, mx_valid, counts_arr,
                 clipped, truncated) = self._decode_packed(
                    packed, ndm_local, namax, nlevels, cap, compact_k
                )
            nxt = self._escalated(
                cap, compact_k, mx_count, mx_valid,
                ndm_local * namax * nlevels * cap,
                len(truncated), ndm,
            )
            if nxt is None:
                break
            if lineage.enabled():
                # the escalated re-dispatch discards this pass's decode
                # wholesale — its peaks never received candidate ids,
                # so the ledger carries an AGGREGATE count only (the
                # re-run emits fresh ``decoded`` marks)
                n_disc = sum(
                    len(g[0]) for ii, g in per_dm_groups.items()
                    if ii < ndm)
                if n_disc:
                    lineage.mark("superseded", run=self._lineage_run(),
                                 n=n_disc, stage="redispatch")
            cap, compact_k = nxt
        rerun = self._rerun_clipped_rows(
            clipped, counts_arr,
            lambda rows: (trials, {ii: ii for ii in rows}),
        )
        if rerun and lineage.enabled():
            # clipped rows' partial decodes are discarded in favour of
            # the escalated host re-search (which emits its own
            # ``decoded`` marks via process_dm_peaks)
            lrun = self._lineage_run()
            for ii in sorted(rerun):
                grp = per_dm_groups.get(ii)
                if grp is not None and len(grp[0]):
                    lineage.mark("superseded", run=lrun,
                                 n=int(len(grp[0])),
                                 stage="clip_rerun", dm_idx=int(ii))
        if cfg.dump_dir:
            # debug buffer dumps work here because the fused path keeps
            # every dedispersed trial HBM-resident (the chunked driver
            # cannot; it warns instead)
            from ..search.pipeline import dump_whiten_stages

            for ii in range(ndm):
                dump_whiten_stages(
                    cfg.dump_dir, ii, self._trial_tim(trials, ii),
                    jnp.asarray(self.birdies), jnp.asarray(self.bwidths),
                    self.bin_width, cfg.boundary_5_freq,
                    cfg.boundary_25_freq, bool(len(self.birdies)),
                )
        # record the observed high-waters for the NEXT run's buffer
        # sizes (margins — +32 counts, x1.1 valid peaks — keep
        # same-data reruns from ever clipping; different data falls
        # back to the usual re-search/escalation paths)
        # multiple-of-64, not power-of-two: top_k/approx_max_k accept
        # any k and their cost scales with it, so the tightest safe
        # capacity wins (same arithmetic as the tune-file seeding above)
        hint = round_up(mx_count + 32, 64, 64, cfg.peak_capacity)
        ck_hint = round_up(int(mx_valid * 1.1), 8192, 8192,
                           cfg.compact_capacity)
        retune = (hint != getattr(self, "_cap_hint", None)
                  or ck_hint < getattr(self, "_ck_hint", 1 << 62))
        warm_shapes = None
        if retune:
            self._cap_hint = hint
            self._ck_hint = ck_hint
            new_ck = min(ck_hint, ndm_local * namax * nlevels * hint)
            if hint < cap0 or new_ck < compact_k:
                warm_shapes = (hint, new_ck)
        if cfg.tune_file:
            # true per-shard totals (see _run_chunked's hw_valid note)
            save_tuning(
                cfg.tune_file, self._tune_scoped_key("fused"), mx_count,
                int(counts_arr.reshape(self.ndev, -1).sum(axis=1).max()),
            )
            from ..search.tuning import record_run_calibration

            record_run_calibration(cfg.tune_file)
        timers["dedispersion"] = 0.0  # fused into the search program
        if cfg.measure_stages:
            timers["dedispersion"] = self.measure_dedispersion_stage()
        # sub-span of "searching" (which covers device + host decode)
        timers["searching_device"] = time.time() - t0
        dm_cands = CandidateCollection()
        ckpt_done = {}
        with span("Distill", metric="distillation", n_dm_trials=ndm):
            batch = self._distill_rows_batch(
                (ii, per_dm_groups.get(ii), acc_lists[ii],
                 None if jerk_lists is None else jerk_lists[ii])
                for ii in range(ndm) if ii not in rerun
            )
        for ii in range(ndm):
            cands_ii = rerun[ii] if ii in rerun else batch[ii]
            ckpt_done[ii] = cands_ii
            dm_cands.append(cands_ii)
        if ckpt:
            ckpt.save(ckpt_done)
        timers["searching"] = time.time() - t0
        result = self._finalise(dm_cands, trials, timers, t_total)
        if warm_shapes is not None and getattr(self, "prewarm_tuned",
                                               False):
            # pre-compile + warm the tuned program AFTER all timed
            # stages, so a later run on this object pays neither
            # compile nor jit-cache miss.  Opt-in (bench.py's repeated
            # -run pattern): a one-shot CLI run would pay an extra
            # compile and a duplicate search execution for nothing.
            wp, _wt = make_program(*warm_shapes)(*inputs)
            np.asarray(wp[-1:])  # sync: don't queue ahead of next run
        if ckpt:
            ckpt.remove()
        return result

    # -- batched multi-observation dispatch (ISSUE 9) --------------------

    def _fused_program(self, capacity, ck, dd_pallas, batch: int = 1):
        """The fused one-dispatch program for this search's geometry
        (shared by ``run`` and ``run_batch``; lru-cached by shape)."""
        cfg = self.config
        return build_fused_search(
            self.mesh,
            nbits=self.fil.header.nbits,
            nchans=self.fil.nchans,
            nsamps=self.fil.nsamps,
            out_nsamps=self.out_nsamps,
            size=self.size,
            bin_width=self.bin_width,
            tsamp=float(self.fil.tsamp),
            nharms=cfg.nharmonics,
            bounds=self.bounds,
            capacity=capacity,
            min_snr=cfg.min_snr,
            b5=cfg.boundary_5_freq,
            b25=cfg.boundary_25_freq,
            use_zap=bool(len(self.birdies)),
            use_killmask=self.killmask is not None,
            compact_k=ck,
            max_shift=self.max_shift,
            block=self.resample_block,
            dedisp_pallas=(
                dd_pallas["params"] if dd_pallas is not None else None
            ),
            lattice=self.lattice,
            use_jerks=self._legacy_jerks(),
            peaks_methods=self.peaks_methods_for(capacity),
            compact_method=self.compact_method_for(ck),
            batch=batch,
        )

    def _spawn(self, fil, cfg):
        return MeshPulsarSearch(fil, cfg, mesh=self.mesh)

    def _pack_raw(self, fil) -> np.ndarray:
        if fil.header.nbits == 32:  # float data: nothing to pack
            return np.ascontiguousarray(fil.data, np.float32).ravel()
        return pack_bits(fil.data.ravel(), fil.header.nbits)

    def run_batch(self, fils, configs=None) -> list:
        """ONE fused dispatch over B same-bucket observations.

        The per-dispatch fixed costs (compile lookup + two ~0.1 s
        host<->device round trips) dominate fused-search wall-clock, so
        stacking B beams into one ``(B, ...)`` program is a near-linear
        ``jobs_per_hour`` multiplier for survey drains (ROADMAP open
        item 2).  Per-beam semantics are preserved exactly: the batched
        program unrolls the B=1 body per beam (bit-identical HLO),
        decode/rerun/distill/checkpoint/finalise run per beam, and a
        beam whose post-processing fails returns its exception in its
        result slot without touching its batch-mates.  Falls back to
        the sequential base implementation when the bounded-HBM
        chunked plan is active or every beam is a checkpoint resume.
        """
        import time

        from ..obs.metrics import install_compile_hook

        B = len(fils)
        configs = ([self.config] * B if configs is None
                   else list(configs))
        if B == 1:
            return super().run_batch(fils, configs)
        self._assert_batch_compatible(fils)
        install_compile_hook()
        cfg = self.config
        ndm = len(self.dm_list)
        acc_lists = [
            self.acc_plan.generate_accel_list(dm) for dm in self.dm_list
        ]
        # combined (accel, jerk) trial lists, as in run()
        acc_lists, jerk_lists = self._trial_lists(acc_lists)
        namax = max(len(a) for a in acc_lists)
        n_trials_total = sum(len(a) for a in acc_lists)
        plan = self._plan_chunking(namax)
        if plan is not None:
            # production-scale chunked path has no batch axis (its HBM
            # budget is already saturated by ONE observation): run the
            # beams sequentially rather than refuse
            warn_event(
                "batch_fallback",
                "bounded-HBM chunked plan active: batched dispatch "
                "falls back to sequential per-beam runs",
                batch=B, path="chunked",
            )
            return super().run_batch(fils, configs)
        # per-beam checkpoints: complete resumes skip decode/distill
        # for that beam (mirrors run()'s all-done short-circuit)
        ckpts, resumed = [], {}
        for b in range(B):
            ck_b, done_b = self._make_checkpoint(fils[b], configs[b])
            ckpts.append(ck_b)
            if ck_b and len(done_b) == ndm:
                resumed[b] = done_b
        live = [b for b in range(B) if b not in resumed]
        if not live:
            # nothing left to search; sequential resumes also handle
            # the npdmp>0 re-dedisperse correctly
            return super().run_batch(fils, configs)

        timers: dict[str, float] = {}
        t_total = time.time()
        self._span_cursor0 = span_cursor()  # duty-cycle ledger origin
        METRICS.gauge("search.n_dm_trials", ndm)
        METRICS.gauge("search.fft_size", self.size)
        METRICS.gauge("search.n_devices", self.ndev)
        METRICS.gauge("search.batch", B)
        ndm_p = self._padded_trial_count()
        ndev = self.ndev
        nlevels = cfg.nharmonics + 1
        from ..obs.costmodel import record_run_costs

        run_costs = record_run_costs(self, acc_lists, batch=B)["stages"]
        dd_pallas = self._plan_fused_pallas_dedisp()
        if dd_pallas is not None:
            ndm_p = dd_pallas["ndm_p"]
        ndm_local = ndm_p // ndev
        from ..search.tuning import load_tuning, round_up, save_tuning

        # capacity/compaction tuning is per BEAM (every beam compacts
        # its own buffer), so the B=1 hints and sidecar cells apply
        # unchanged — see search/tuning.py "Batch axis" note
        if cfg.tune_file and getattr(self, "_cap_hint", None) is None:
            tune = load_tuning(cfg.tune_file,
                               self._tune_scoped_key("fused"))
            if tune is not None:
                self._cap_hint = round_up(tune["cap_hw"] + 32, 64, 64,
                                          cfg.peak_capacity)
                self._ck_hint = round_up(int(tune["ck_hw"] * 1.1), 8192,
                                         8192, cfg.compact_capacity)
        cap = min(cfg.peak_capacity,
                  getattr(self, "_cap_hint", cfg.peak_capacity))
        compact_k = min(
            cfg.compact_capacity, ndm_local * namax * nlevels * cap,
            getattr(self, "_ck_hint", cfg.compact_capacity),
        )
        t0 = time.time()
        inputs = self._device_inputs(acc_lists, ndm_p, namax, jerk_lists)
        raw_B = np.stack([self._pack_raw(f) for f in fils])
        inputs = (put_global(raw_B, NamedSharding(self.mesh, P())),
                  ) + tuple(inputs[1:])
        self.record_peaks_selection(cap)
        METRICS.inc("runs.mesh_fused")
        METRICS.inc("runs.mesh_fused_batched")
        beam_fail: dict[int, BaseException] = {}
        decoded: dict[int, tuple] = {}
        while True:
            program = self._fused_program(cap, compact_k, dd_pallas,
                                          batch=B)
            fused_gflops = sum(
                run_costs[s].flops
                for s in ("dedisperse", "spectrum", "harmonics", "peaks")
            ) / 1e9
            with span("Fused-Search", metric="fused_search",
                      batch=B, n_dm_trials=ndm,
                      n_trials=int(n_trials_total),
                      dm_lo=float(self.dm_list[0]),
                      dm_hi=float(self.dm_list[-1]),
                      capacity=int(cap), compact_k=int(compact_k),
                      hbm_budget_bytes=float(cfg.hbm_budget_gb * 1e9),
                      gflops=round(fused_gflops, 3),
                      ) as sp:
                packed, trials = program(*inputs)
                if getattr(cfg, "pipeline_depth", 2) > 1:
                    start_fetch(packed)  # d2h overlaps host-side prep
                tf = time.time()
                # (B, ndev*blk_len): row b IS the B=1 packed layout
                packed = finish_fetch(packed)
                sp.add_device_time(time.time() - tf)
            beam_fail, decoded = {}, {}
            with span("Peak-Decode", metric="peak_decode", batch=B):
                for b in live:
                    try:
                        decoded[b] = self._decode_packed(
                            packed[b], ndm_local, namax, nlevels, cap,
                            compact_k,
                        )
                    except Exception as exc:  # beam-fatal, mates live on
                        beam_fail[b] = exc
            if not decoded:
                break
            mx_count = max(d[1] for d in decoded.values())
            mx_valid = max(d[2] for d in decoded.values())
            n_trunc = max(len(d[5]) for d in decoded.values())
            nxt = self._escalated(
                cap, compact_k, mx_count, mx_valid,
                ndm_local * namax * nlevels * cap, n_trunc, ndm,
            )
            if nxt is None:
                break
            if lineage.enabled():
                # escalated re-dispatch discards every live beam's
                # decode; aggregate supersession per beam, attributed
                # to that beam's run id (see run()'s fused-path note)
                for b in decoded:
                    n_disc = sum(
                        len(g[0])
                        for ii, g in decoded[b][0].items() if ii < ndm)
                    if n_disc:
                        lineage.mark(
                            "superseded",
                            run=getattr(configs[b], "lineage_run", ""),
                            n=n_disc, stage="redispatch")
            cap, compact_k = nxt
        # per-beam clipped-row re-searches on that beam's trials
        reruns: dict[int, dict] = {}
        for b in list(decoded):
            try:
                _g, _mc, _mv, counts_b, clipped_b, _t = decoded[b]
                trials_b = trials[b]
                # host-path re-search marks (decoded/absorbed) must
                # carry THIS beam's run id, not the driver config's
                self._lineage_run_override = getattr(
                    configs[b], "lineage_run", "")
                try:
                    reruns[b] = self._rerun_clipped_rows(
                        clipped_b, counts_b,
                        lambda rows, _t=trials_b: (
                            _t, {ii: ii for ii in rows}),
                    )
                finally:
                    self._lineage_run_override = ""
                if reruns[b] and lineage.enabled():
                    lrun_b = getattr(configs[b], "lineage_run", "")
                    for ii in sorted(reruns[b]):
                        grp = decoded[b][0].get(ii)
                        if grp is not None and len(grp[0]):
                            lineage.mark(
                                "superseded", run=lrun_b,
                                n=int(len(grp[0])),
                                stage="clip_rerun", dm_idx=int(ii))
            except Exception as exc:
                beam_fail[b] = exc
                decoded.pop(b)
        if decoded:
            # observed high-waters tighten the NEXT dispatch's buffers;
            # max over beams — a per-beam quantity, so B=1 and batched
            # runs feed the same hints/sidecar cells (B-invariance)
            mx_count = max(d[1] for d in decoded.values())
            mx_valid = max(d[2] for d in decoded.values())
            self._cap_hint = round_up(mx_count + 32, 64, 64,
                                      cfg.peak_capacity)
            ck_hint = round_up(int(mx_valid * 1.1), 8192, 8192,
                               cfg.compact_capacity)
            if ck_hint < getattr(self, "_ck_hint", 1 << 62):
                self._ck_hint = ck_hint
            if cfg.tune_file:
                hw_valid = max(
                    int(d[3].reshape(self.ndev, -1).sum(axis=1).max())
                    for d in decoded.values()
                )
                save_tuning(cfg.tune_file,
                            self._tune_scoped_key("fused"),
                            mx_count, hw_valid)
                from ..search.tuning import record_run_calibration

                record_run_calibration(cfg.tune_file)
        timers["dedispersion"] = 0.0  # fused into the search program
        timers["searching_device"] = time.time() - t0
        # ONE segmented distill across every live beam: (beam, dm) keys
        # keep the segments per-beam, so cross-beam absorption is
        # structurally impossible
        with span("Distill", metric="distillation",
                  n_dm_trials=ndm * max(len(decoded), 1), batch=B):
            distilled = self._distill_rows_batch(
                (((b, ii), decoded[b][0].get(ii), acc_lists[ii],
                  None if jerk_lists is None else jerk_lists[ii])
                 for b in decoded for ii in range(ndm)
                 if ii not in reruns[b]),
                dm_of=lambda k: k[1],
                run_of=lambda k: getattr(
                    configs[k[0]], "lineage_run", ""),
            )
        timers["searching"] = time.time() - t0
        # fan results back out per beam; a beam that fails here keeps
        # its exception in its own slot (checkpoints of batch-mates are
        # untouched — each beam has its own checkpoint file/key)
        results: list = [None] * B
        for b in range(B):
            if b in beam_fail:
                results[b] = beam_fail[b]
                continue
            try:
                dm_cands = CandidateCollection()
                ckpt_done = {}
                if b in resumed:
                    for ii in range(ndm):
                        dm_cands.append(resumed[b][ii])
                else:
                    rerun_b = reruns[b]
                    for ii in range(ndm):
                        cands_ii = (rerun_b[ii] if ii in rerun_b
                                    else distilled[(b, ii)])
                        ckpt_done[ii] = cands_ii
                        dm_cands.append(cands_ii)
                    if ckpts[b]:
                        ckpts[b].save(ckpt_done)
                # folding inputs are per-beam: never share the cache
                self._fold_input_cache = FoldInputCache()
                results[b] = self._finalise(
                    dm_cands, trials[b], dict(timers), t_total,
                    config=configs[b],
                )
                if ckpts[b]:
                    ckpts[b].remove()
            except Exception as exc:  # per-beam failure isolation
                results[b] = exc
        self.last_dispatch_batched = True
        return results
