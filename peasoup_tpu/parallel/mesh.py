"""Device-mesh parallelism for the trial grid.

TPU-native replacement of the reference's multi-GPU strategy: where
`src/pipeline_multi.cu:33-81` runs a mutex-guarded DM-trial work queue
over pthread workers (one per GPU) and merges candidate vectors after
join, here the DM axis is a named mesh axis:

* dedispersion is one jitted program whose delay table and output
  carry a ``NamedSharding`` over ``("dm",)`` — XLA partitions the
  channel sweep so each device produces only its DM rows (the input
  filterbank block is replicated, as dedisp's multi-GPU plan does);
* the search is a ``shard_map`` program: each device scans its local
  block of DM trials (whiten -> accel-batch search) and emits
  fixed-capacity peak buffers, which are device-local outputs of the
  same sharding — a single device->host gather replaces the pthread
  join + append of the reference;
* the dynamic DM dispenser becomes a static balanced assignment: DM
  trials cost the same per trial, and ragged accel lists are padded to
  a rectangle with a validity mask (SURVEY.md section 7).

On multi-host systems the same program runs under
``jax.distributed.initialize`` with a global mesh: the per-shard peak
buffers are all-gathered over ICI/DCN by the final host transfer, and
candidate distillation remains a (cheap) host-side pass.
"""

from __future__ import annotations

from functools import partial

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.dedisperse import dedisperse
from ..search.pipeline import (
    PulsarSearch,
    SearchResult,
    search_one_accel,
    whiten_core,
    fold_candidates,
)
from ..search.distill import DMDistiller, HarmonicDistiller
from ..search.plan import SearchConfig
from ..search.score import CandidateScorer
from ..data.candidates import CandidateCollection


def make_mesh(max_devices: int | None = None, axis: str = "dm") -> Mesh:
    devs = jax.devices()
    if max_devices:
        devs = devs[: max_devices]
    return Mesh(np.array(devs), (axis,))


def sharded_search_program(
    mesh: Mesh,
    size: int,
    bin_width: float,
    tsamp: float,
    nharms: int,
    bounds: tuple,
    capacity: int,
    min_snr: float,
    b5: float,
    b25: float,
    use_zap: bool,
):
    """Build the jitted shard_map search over the ``dm`` mesh axis.

    Returns a callable (trials, accs, birdies, widths) -> (idxs, snrs,
    counts) where trials is (ndm_padded, size) sharded over dm, accs is
    (ndm_padded, naccel_max) with NaN padding, and outputs have leading
    dim ndm_padded (sharded over dm).
    """

    def per_dm(carry, inp):
        tim, accs = inp
        birdies, widths = carry
        tim_w, mean, std = whiten_core(
            tim, birdies, widths, bin_width, b5, b25, use_zap
        )
        search = lambda a: search_one_accel(
            tim_w, jnp.nan_to_num(a), mean, std, tsamp, nharms, bounds,
            capacity, min_snr,
        )
        idxs, snrs, counts = jax.vmap(search)(accs)
        # mask out padded accel slots entirely
        valid = ~jnp.isnan(accs)
        idxs = jnp.where(valid[:, None, None], idxs, -1)
        snrs = jnp.where(valid[:, None, None], snrs, 0.0)
        counts = jnp.where(valid[:, None], counts, 0)
        return carry, (idxs, snrs, counts)

    def shard_fn(trials, accs, birdies, widths):
        # trials: (ndm_local, size); accs: (ndm_local, naccel_max)
        _, outs = lax.scan(per_dm, (birdies, widths), (trials, accs))
        return outs

    mapped = jax.shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(P("dm", None), P("dm", None), P(None), P(None)),
        out_specs=(P("dm", None, None), P("dm", None, None), P("dm", None)),
    )
    return jax.jit(mapped)


class MeshPulsarSearch(PulsarSearch):
    """Multi-device search: DM trials sharded over a 1-D device mesh."""

    def __init__(self, fil, config: SearchConfig, max_devices=None,
                 mesh: Mesh | None = None):
        super().__init__(fil, config)
        self.mesh = mesh if mesh is not None else make_mesh(max_devices)
        self.ndev = self.mesh.devices.size

    def _padded_trial_count(self) -> int:
        ndm = len(self.dm_list)
        return int(np.ceil(ndm / self.ndev)) * self.ndev

    def dedisperse_sharded(self) -> jax.Array:
        """Dedisperse with the DM axis sharded across the mesh."""
        ndm = len(self.dm_list)
        ndm_p = self._padded_trial_count()
        delays = np.zeros((ndm_p, self.fil.nchans), np.int32)
        delays[:ndm] = self.delays
        data = jnp.asarray(self.fil.data.T, dtype=jnp.float32)
        km = (
            jnp.asarray(self.killmask)
            if self.killmask is not None
            else None
        )
        rep = NamedSharding(self.mesh, P())
        shard = NamedSharding(self.mesh, P("dm", None))
        data = jax.device_put(data, rep)
        delays_d = jax.device_put(jnp.asarray(delays), shard)
        fn = jax.jit(
            partial(dedisperse, out_nsamps=self.out_nsamps),
            out_shardings=shard,
        )
        if km is not None:
            return fn(data, delays_d, killmask=jax.device_put(km, rep))
        return fn(data, delays_d)

    def run(self) -> SearchResult:
        import time

        cfg = self.config
        timers: dict[str, float] = {}
        t_total = time.time()
        t0 = time.time()
        trials = self.dedisperse_sharded()
        trials.block_until_ready()
        timers["dedispersion"] = time.time() - t0

        t0 = time.time()
        ndm = len(self.dm_list)
        ndm_p = self._padded_trial_count()
        acc_lists = [
            self.acc_plan.generate_accel_list(dm) for dm in self.dm_list
        ]
        namax = max(len(a) for a in acc_lists)
        accs = np.full((ndm_p, namax), np.nan, np.float32)
        for i, a in enumerate(acc_lists):
            accs[i, : len(a)] = a

        # trim/pad trials to (ndm_p, size)
        if self.out_nsamps >= self.size:
            trials_sz = trials[:, : self.size]
        else:
            pad_means = jnp.mean(trials, axis=1, keepdims=True)
            pad = jnp.broadcast_to(
                pad_means, (trials.shape[0], self.size - self.out_nsamps)
            )
            trials_sz = jnp.concatenate([trials, pad], axis=1)
        if trials_sz.shape[0] < ndm_p:
            trials_sz = jnp.pad(
                trials_sz, ((0, ndm_p - trials_sz.shape[0]), (0, 0))
            )

        shard = NamedSharding(self.mesh, P("dm", None))
        trials_sz = jax.device_put(trials_sz, shard)
        accs_d = jax.device_put(
            jnp.asarray(accs), NamedSharding(self.mesh, P("dm", None))
        )

        program = sharded_search_program(
            self.mesh, self.size, self.bin_width, float(self.fil.tsamp),
            cfg.nharmonics, self.bounds, cfg.peak_capacity, cfg.min_snr,
            cfg.boundary_5_freq, cfg.boundary_25_freq,
            bool(len(self.birdies)),
        )
        idxs, snrs, counts = program(
            trials_sz, accs_d, jnp.asarray(self.birdies),
            jnp.asarray(self.bwidths),
        )
        idxs = np.asarray(idxs)   # gather over ICI -> host
        snrs = np.asarray(snrs)
        counts = np.asarray(counts)

        dm_cands = CandidateCollection()
        for ii in range(ndm):
            dm_cands.append(
                self.process_dm_peaks(
                    float(self.dm_list[ii]), ii, acc_lists[ii],
                    idxs[ii], snrs[ii], counts[ii],
                )
            )
        timers["searching"] = time.time() - t0

        dm_still = DMDistiller(cfg.freq_tol, True)
        harm_still = HarmonicDistiller(cfg.freq_tol, cfg.max_harm, True, False)
        cands = dm_still.distill(dm_cands.cands)
        cands = harm_still.distill(cands)

        hdr = self.fil.header
        scorer = CandidateScorer(
            hdr.tsamp, hdr.cfreq, hdr.foff, abs(hdr.foff) * self.fil.nchans
        )
        scorer.score_all(cands)

        t0 = time.time()
        if cfg.npdmp > 0:
            fold_candidates(
                cands, trials, self.out_nsamps, hdr.tsamp, cfg.npdmp,
                boundary_5_freq=cfg.boundary_5_freq,
                boundary_25_freq=cfg.boundary_25_freq,
            )
        timers["folding"] = time.time() - t0

        cands = cands[: cfg.limit]
        timers["total"] = time.time() - t_total
        return SearchResult(
            candidates=CandidateCollection(cands),
            dm_list=self.dm_list,
            acc_list_dm0=self.acc_plan.generate_accel_list(0.0),
            timers=timers,
            config=cfg,
            header=hdr,
        )
