"""Device-mesh parallelism for the trial grid.

TPU-native replacement of the reference's multi-GPU strategy: where
`src/pipeline_multi.cu:33-81` runs a mutex-guarded DM-trial work queue
over pthread workers (one per GPU) and merges candidate vectors after
join, here the DM axis is a named mesh axis:

* dedispersion is one jitted program whose delay table and output
  carry a ``NamedSharding`` over ``("dm",)`` — XLA partitions the
  channel sweep so each device produces only its DM rows (the input
  filterbank block is replicated, as dedisp's multi-GPU plan does);
* the search is a ``shard_map`` program: each device scans its local
  block of DM trials (whiten -> accel-batch search) and emits
  fixed-capacity peak buffers, which are device-local outputs of the
  same sharding — a single device->host gather replaces the pthread
  join + append of the reference;
* the dynamic DM dispenser becomes a static balanced assignment: DM
  trials cost the same per trial, and ragged accel lists are padded to
  a rectangle with a validity mask (SURVEY.md section 7).

On multi-host systems the same program runs under
``jax.distributed.initialize`` with a global mesh: the per-shard peak
buffers are all-gathered over ICI/DCN by the final host transfer, and
candidate distillation remains a (cheap) host-side pass.
"""

from __future__ import annotations

from functools import partial

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.dedisperse import dedisperse
from ..search.pipeline import (
    PulsarSearch,
    SearchResult,
    search_one_accel,
    whiten_core,
)
from ..search.plan import SearchConfig
from ..data.candidates import Candidate, CandidateCollection
from ..io.unpack import pack_bits
from ..ops.peaks import identify_unique_peaks


def make_mesh(max_devices: int | None = None, axis: str = "dm") -> Mesh:
    devs = jax.devices()
    if max_devices:
        devs = devs[: max_devices]
    return Mesh(np.array(devs), (axis,))


def _search_dm_row(tim, accs_row, birdies, widths, *, bin_width, tsamp,
                   nharms, bounds, capacity, min_snr, b5, b25, use_zap):
    """Whiten one DM trial and search its (NaN-padded) accel batch.

    Shared body of both sharded programs: returns (idxs, snrs, counts)
    with padded accel slots fully masked out.
    """
    tim_w, mean, std = whiten_core(
        tim, birdies, widths, bin_width, b5, b25, use_zap
    )
    search = lambda a: search_one_accel(
        tim_w, jnp.nan_to_num(a), mean, std, tsamp, nharms, bounds,
        capacity, min_snr,
    )
    idxs, snrs, counts = jax.vmap(search)(accs_row)
    valid = ~jnp.isnan(accs_row)
    idxs = jnp.where(valid[:, None, None], idxs, -1)
    snrs = jnp.where(valid[:, None, None], snrs, 0.0)
    counts = jnp.where(valid[:, None], counts, 0)
    return idxs, snrs, counts


def sharded_search_program(
    mesh: Mesh,
    size: int,
    bin_width: float,
    tsamp: float,
    nharms: int,
    bounds: tuple,
    capacity: int,
    min_snr: float,
    b5: float,
    b25: float,
    use_zap: bool,
):
    """Build the jitted shard_map search over the ``dm`` mesh axis.

    Returns a callable (trials, accs, birdies, widths) -> (idxs, snrs,
    counts) where trials is (ndm_padded, size) sharded over dm, accs is
    (ndm_padded, naccel_max) with NaN padding, and outputs have leading
    dim ndm_padded (sharded over dm).
    """

    def per_dm(carry, inp):
        tim, accs = inp
        birdies, widths = carry
        outs = _search_dm_row(
            tim, accs, birdies, widths, bin_width=bin_width, tsamp=tsamp,
            nharms=nharms, bounds=bounds, capacity=capacity,
            min_snr=min_snr, b5=b5, b25=b25, use_zap=use_zap,
        )
        return carry, outs

    def shard_fn(trials, accs, birdies, widths):
        # trials: (ndm_local, size); accs: (ndm_local, naccel_max)
        _, outs = lax.scan(per_dm, (birdies, widths), (trials, accs))
        return outs

    mapped = jax.shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(P("dm", None), P("dm", None), P(None), P(None)),
        out_specs=(P("dm", None, None), P("dm", None, None), P("dm", None)),
    )
    return jax.jit(mapped)


from functools import lru_cache


@lru_cache(maxsize=32)
def build_fused_search(
    mesh: Mesh,
    *,
    nbits: int,
    nchans: int,
    nsamps: int,
    out_nsamps: int,
    size: int,
    bin_width: float,
    tsamp: float,
    nharms: int,
    bounds: tuple,
    capacity: int,
    min_snr: float,
    b5: float,
    b25: float,
    use_zap: bool,
    use_killmask: bool,
    compact_k: int,
):
    """One jitted program for the ENTIRE device side of the search.

    packed filterbank bytes (replicated) -> device bit-unpack ->
    dedisperse (DM rows sharded over the mesh) -> per-DM whiten ->
    batched accel trials -> harmonic sums -> thresholded peaks ->
    global compaction of all (dm, accel, level) peak buffers into one
    small tagged buffer per shard.

    This exists because device->host transfers and program dispatches
    dominate wall-clock on a remote-attached TPU: the reference pays
    neither (its host loop talks to a local PCIe GPU per DM trial,
    `src/pipeline_multi.cu:145-244`), so the TPU-native design moves the
    whole search into one dispatch and ships home only:

    * ``sel_bin``  (compact_k,) int32 — spectrum bin indices
    * ``sel_snr``  (compact_k,) f32   — SNR values
    * ``nvalid``   (1,) int32 — true total peak count (overflow check)
    * ``counts``   (ndm_local, naccel, nlevels) int32 — per-spectrum
      above-threshold counts (per-spectrum overflow check)
    * ``trials``   (ndm_local, out_nsamps) f32 — full-width, stays
      device-resident for the folding phase; never copied to host.

    Returns a jitted callable
    ``fn(raw, delays, killmask, accs, birdies, widths)``.
    """
    from ..ops.unpack import unpack_bits_device

    nlevels = nharms + 1

    def shard_fn(raw, delays, killmask, accs, birdies, widths):
        vals = unpack_bits_device(raw, nbits)[: nsamps * nchans]
        data = vals.reshape(nsamps, nchans).T.astype(jnp.float32)
        if use_killmask:
            data = data * killmask[:, None]
        # full-width trials are returned for the folding phase (which
        # must see prev_power_of_two(out_nsamps) real samples exactly
        # like the single-device path, `folder.hpp:352-406`); the
        # search itself runs on the fft-size-truncated/padded view
        trials = dedisperse(data, delays, out_nsamps)
        if out_nsamps >= size:
            trials_sz = trials[:, :size]
        else:
            pad_mean = jnp.mean(trials, axis=1, keepdims=True)
            pad = jnp.broadcast_to(
                pad_mean, (trials.shape[0], size - out_nsamps)
            )
            trials_sz = jnp.concatenate([trials, pad], axis=1)

        def per_dm(carry, inp):
            tim, accs_row = inp
            outs = _search_dm_row(
                tim, accs_row, birdies, widths, bin_width=bin_width,
                tsamp=tsamp, nharms=nharms, bounds=bounds,
                capacity=capacity, min_snr=min_snr, b5=b5, b25=b25,
                use_zap=use_zap,
            )
            return carry, outs

        _, (idxs, snrs, counts) = lax.scan(per_dm, 0, (trials_sz, accs))

        flat_bin = idxs.reshape(-1)
        flat_snr = snrs.reshape(-1)
        n = flat_bin.shape[0]
        pos = jnp.arange(n, dtype=jnp.int32)
        valid = flat_bin >= 0
        sentinel = jnp.int32(-n - 1)
        score = jnp.where(valid, -pos, sentinel)
        top, _ = lax.top_k(score, compact_k)  # first compact_k valid slots
        got = top != sentinel
        sel = jnp.where(got, -top, 0)
        # the host reconstructs each entry's (dm, accel, level, slot) tag
        # from ``counts`` alone: valid slots appear in flat spectrum
        # order, so only bins+snrs are shipped
        sel_bin = jnp.where(got, flat_bin[sel], -1)
        sel_snr = jnp.where(got, flat_snr[sel], 0.0).astype(jnp.float32)
        nvalid = jnp.sum(valid, dtype=jnp.int32)[None]
        return sel_bin, sel_snr, nvalid, counts, trials

    mapped = jax.shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(
            P(), P("dm", None), P(), P("dm", None), P(), P(),
        ),
        out_specs=(
            P("dm"), P("dm"), P("dm"),
            P("dm", None, None), P("dm", None),
        ),
    )
    return jax.jit(mapped)


class MeshPulsarSearch(PulsarSearch):
    """Multi-device search: DM trials sharded over a 1-D device mesh."""

    def __init__(self, fil, config: SearchConfig, max_devices=None,
                 mesh: Mesh | None = None):
        super().__init__(fil, config)
        self.mesh = mesh if mesh is not None else make_mesh(max_devices)
        self.ndev = self.mesh.devices.size

    def _entries_to_dm_cands(self, dm, dm_idx, acc_list, ebins, esnrs,
                             eacc, elvl):
        """Sparse equivalent of ``PulsarSearch.process_dm_peaks``: turn
        this DM's compacted peak entries into distilled candidates.
        Entry order within each (accel, level) spectrum is ascending bin
        index (compaction preserves slot order), as the unique-peak
        merge requires."""
        groups: list[list[Candidate]] = []
        for j, acc in enumerate(acc_list):
            m_acc = eacc == j
            cands: list[Candidate] = []
            for level, (_start, _stop, factor) in enumerate(self.bounds):
                m = m_acc & (elvl == level)
                if not m.any():
                    continue
                pidx, psnr = identify_unique_peaks(ebins[m], esnrs[m])
                for p, s in zip(pidx, psnr):
                    cands.append(
                        Candidate(dm=dm, dm_idx=dm_idx, acc=float(acc),
                                  nh=level, snr=float(s),
                                  freq=float(p * factor))
                    )
            groups.append(cands)
        return self._distill_accel_groups(groups)

    def _padded_trial_count(self) -> int:
        ndm = len(self.dm_list)
        return int(np.ceil(ndm / self.ndev)) * self.ndev

    def dedisperse_sharded(self) -> jax.Array:
        """Dedisperse with the DM axis sharded across the mesh."""
        ndm = len(self.dm_list)
        ndm_p = self._padded_trial_count()
        delays = np.zeros((ndm_p, self.fil.nchans), np.int32)
        delays[:ndm] = self.delays
        data = jnp.asarray(self.fil.data.T, dtype=jnp.float32)
        km = (
            jnp.asarray(self.killmask)
            if self.killmask is not None
            else None
        )
        rep = NamedSharding(self.mesh, P())
        shard = NamedSharding(self.mesh, P("dm", None))
        data = jax.device_put(data, rep)
        delays_d = jax.device_put(jnp.asarray(delays), shard)
        fn = jax.jit(
            partial(dedisperse, out_nsamps=self.out_nsamps),
            out_shardings=shard,
        )
        if km is not None:
            return fn(data, delays_d, killmask=jax.device_put(km, rep))
        return fn(data, delays_d)

    def run(self) -> SearchResult:
        import time
        import warnings

        cfg = self.config
        timers: dict[str, float] = {}
        t_total = time.time()

        ndm = len(self.dm_list)

        # checkpoint resume: the mesh search is a single dispatch, so a
        # complete checkpoint skips the device program entirely (trials
        # are re-dedispersed only if folding needs them)
        ckpt, ckpt_done = self._make_checkpoint()
        if ckpt and len(ckpt_done) == ndm:
            timers["dedispersion"] = 0.0
            timers["searching"] = 0.0
            dm_cands = CandidateCollection()
            for ii in range(ndm):
                dm_cands.append(ckpt_done[ii])
            trials = (
                self.dedisperse_sharded() if cfg.npdmp > 0 else None
            )
            result = self._finalise(dm_cands, trials, timers, t_total)
            ckpt.remove()
            return result
        ndm_p = self._padded_trial_count()
        ndev = self.ndev
        ndm_local = ndm_p // ndev
        acc_lists = [
            self.acc_plan.generate_accel_list(dm) for dm in self.dm_list
        ]
        namax = max(len(a) for a in acc_lists)
        accs = np.full((ndm_p, namax), np.nan, np.float32)
        for i, a in enumerate(acc_lists):
            accs[i, : len(a)] = a
        delays = np.zeros((ndm_p, self.fil.nchans), np.int32)
        delays[:ndm] = self.delays
        killmask = (
            self.killmask
            if self.killmask is not None
            else np.ones(self.fil.nchans, np.float32)
        )
        nbits = self.fil.header.nbits
        if nbits == 32:  # float data: nothing to pack
            raw = np.ascontiguousarray(self.fil.data, np.float32).ravel()
        else:
            raw = pack_bits(self.fil.data.ravel(), nbits)
        nlevels = cfg.nharmonics + 1
        cap = cfg.peak_capacity
        # clamp to the shard's total slot count (small configs)
        compact_k = min(
            cfg.compact_capacity, ndm_local * namax * nlevels * cap
        )

        program = build_fused_search(
            self.mesh,
            nbits=nbits,
            nchans=self.fil.nchans,
            nsamps=self.fil.nsamps,
            out_nsamps=self.out_nsamps,
            size=self.size,
            bin_width=self.bin_width,
            tsamp=float(self.fil.tsamp),
            nharms=cfg.nharmonics,
            bounds=self.bounds,
            capacity=cap,
            min_snr=cfg.min_snr,
            b5=cfg.boundary_5_freq,
            b25=cfg.boundary_25_freq,
            use_zap=bool(len(self.birdies)),
            use_killmask=self.killmask is not None,
            compact_k=compact_k,
        )

        from ..utils import trace_range

        t0 = time.time()
        with trace_range("Fused-Search"):
            rep = NamedSharding(self.mesh, P())
            shard = NamedSharding(self.mesh, P("dm", None))
            raw_d = jax.device_put(jnp.asarray(raw), rep)
            delays_d = jax.device_put(jnp.asarray(delays), shard)
            km_d = jax.device_put(
                jnp.asarray(killmask, dtype=jnp.float32), rep
            )
            accs_d = jax.device_put(jnp.asarray(accs), shard)
            sel_bin, sel_snr, nvalid, counts, trials = program(
                raw_d, delays_d, km_d, accs_d,
                jnp.asarray(self.birdies), jnp.asarray(self.bwidths),
            )
            # tiny gathers over ICI -> host; ``trials`` stays on device
            sel_bin = np.asarray(sel_bin)
            sel_snr = np.asarray(sel_snr)
            nvalid = np.asarray(nvalid)
            counts = np.asarray(counts)
        timers["dedispersion"] = 0.0  # fused into the search program
        # sub-span of "searching" (which covers device + host decode)
        timers["searching_device"] = time.time() - t0

        if counts.max(initial=0) > cap:
            warnings.warn(
                f"peak buffer overflow: max count {counts.max()} > "
                f"capacity {cap}; raise peak_capacity"
            )

        # reconstruct each entry's (dm_local, accel, level) tag from
        # counts: the device compaction keeps valid slots in flat
        # (dm_local, accel, level, slot) order
        per_dm_entries: dict[int, tuple] = {}
        nspec_local = ndm_local * namax * nlevels
        for s in range(ndev):
            if nvalid[s] > compact_k:
                warnings.warn(
                    f"compacted peak buffer overflow on shard {s}: "
                    f"{nvalid[s]} > {compact_k}; raise compact_capacity"
                )
            k = np.minimum(
                counts[s * ndm_local : (s + 1) * ndm_local], cap
            ).reshape(-1)
            spec = np.repeat(
                np.arange(nspec_local, dtype=np.int64), k
            )[:compact_k]
            nent = spec.shape[0]
            blk = slice(s * compact_k, s * compact_k + nent)
            bins = sel_bin[blk]
            snrs = sel_snr[blk]
            lvl = spec % nlevels
            acc_i = (spec // nlevels) % namax
            dml = spec // (nlevels * namax)
            for d in np.unique(dml):
                m = dml == d
                per_dm_entries[int(s * ndm_local + d)] = (
                    bins[m], snrs[m], acc_i[m], lvl[m]
                )

        dm_cands = CandidateCollection()
        ckpt_done = {}
        for ii in range(ndm):
            if ii not in per_dm_entries:
                ckpt_done[ii] = []
                continue
            ebins, esnrs, eacc, elvl = per_dm_entries[ii]
            cands_ii = self._entries_to_dm_cands(
                float(self.dm_list[ii]), ii, acc_lists[ii],
                ebins, esnrs, eacc, elvl,
            )
            ckpt_done[ii] = cands_ii
            dm_cands.append(cands_ii)
        if ckpt:
            ckpt.save(ckpt_done)
        timers["searching"] = time.time() - t0
        result = self._finalise(dm_cands, trials, timers, t_total)
        if ckpt:
            ckpt.remove()
        return result
