"""Reusable async dispatch pipeline (ISSUE 11).

The chunked driver's double-buffer — dispatch chunk k+1 before chunk
k's results are fetched, so host decode/distill/checkpoint hide behind
device time (`src/pipeline_multi.cu`'s stream overlap, host-side) —
generalised to any dispatch depth and shared between drivers:

* ``dispatch(item) -> token`` enqueues device work and returns without
  blocking (a jax dispatch is async by design);
* ``start_fetch(token)`` (optional) begins the device->host copy of
  the token's results immediately, so the link transfer overlaps the
  next item's compute (``utils/hostfetch.start_fetch``);
* ``retire(token, item) -> result`` completes the fetch and does the
  host-side work (decode, distill, checkpoint).

``depth`` is the number of dispatches in flight before the oldest is
retired: depth=1 is the unpipelined A/B reference (dispatch, retire,
dispatch, ...), depth=2 reproduces the chunked driver's historical
double-buffer exactly (dispatch 0, dispatch 1, retire 0, dispatch 2,
retire 1, ...), higher depths keep more device work queued at the cost
of that many result buffers resident in HBM.

Deliberately jax-free: tokens are opaque, so tests drive it with plain
lists and the serve layer can import it without the mesh stack.
"""

from __future__ import annotations

from collections import deque

from ..errors import ConfigError


class DispatchPipeline:
    """Run ``items`` through dispatch -> [start_fetch] -> retire with
    up to ``depth`` dispatches in flight; results keep item order."""

    def __init__(self, dispatch, retire, *, depth: int = 2,
                 start_fetch=None):
        if depth < 1:
            raise ConfigError(
                f"pipeline depth must be >= 1, got {depth}")
        self.dispatch = dispatch
        self.retire = retire
        self.depth = int(depth)
        self.start_fetch = start_fetch
        #: high-water of concurrently in-flight dispatches (observable
        #: proof the requested depth was actually reached)
        self.max_inflight = 0

    def run(self, items) -> list:
        results: list = []
        inflight: deque = deque()
        for item in items:
            token = self.dispatch(item)
            if self.start_fetch is not None:
                self.start_fetch(token)
            inflight.append((token, item))
            if len(inflight) > self.max_inflight:
                self.max_inflight = len(inflight)
            while len(inflight) >= self.depth:
                results.append(self.retire(*inflight.popleft()))
        while inflight:
            results.append(self.retire(*inflight.popleft()))
        return results
