"""Multi-host execution entry point.

The reference's cross-device story stops at one host: a pthread pool
over local GPUs with a shared-memory merge (`src/pipeline_multi.cu:
33-81,356-359`) and no NCCL/MPI.  The TPU build scales past one host
with the standard JAX SPMD recipe instead:

1. every host calls :func:`initialize` (jax.distributed) at startup;
2. :func:`global_mesh` builds a ``Mesh`` over ALL devices in the slice
   (ICI within a host/pod, DCN across pods — XLA routes collectives);
3. ``MeshPulsarSearch`` runs unchanged on that mesh: the DM axis is
   sharded globally, and the single packed peak buffer per shard is
   gathered to every host by ``fetch_to_host`` (a
   ``multihost_utils.process_allgather`` over ICI/DCN when the array
   spans non-addressable devices);
4. each host runs the identical (deterministic) distillation, so the
   outputs agree without any explicit broadcast.

Single-chip CI cannot exercise real multi-host runs; the mesh semantics
are validated on the virtual multi-device CPU mesh (tests/conftest.py)
and by the driver's ``dryrun_multichip``.
"""

from __future__ import annotations

import numpy as np


def initialize(coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None) -> None:
    """Bring up jax.distributed (no-op if already initialised).

    On TPU pods the three arguments are auto-detected from the
    environment; pass them explicitly elsewhere.
    """
    import os

    import jax

    auto_detectable = any(
        v in os.environ
        for v in ("COORDINATOR_ADDRESS", "JAX_COORDINATOR_ADDRESS",
                  "TPU_WORKER_HOSTNAMES", "MEGASCALE_COORDINATOR_ADDRESS")
    )
    if coordinator_address is None and not auto_detectable:
        # plain single-process run: nothing to initialise
        return
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except (RuntimeError, ValueError):
        # already initialised, or the environment cannot support a
        # coordinator: fall back to single-process execution
        pass


def process_identity() -> tuple[int, int]:
    """This host's ``(process_index, process_count)`` in the slice.

    The fleet control plane (``serve/fleet.py``) derives each host's
    membership — which spool worker identity it runs and which
    candidate-store shard it owns — from exactly this pair, after
    :func:`initialize` has (maybe) brought up jax.distributed.
    Returns ``(0, 1)`` for a plain single-process run, or when jax
    itself is unavailable: the serve layer must keep operating on a
    login/submit node with no accelerator runtime.
    """
    try:
        import jax

        return int(jax.process_index()), int(jax.process_count())
    except Exception:
        return 0, 1


def global_mesh(axis: str = "dm"):
    """1-D mesh over every device of every participating host."""
    import jax
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices()), (axis,))


def gather_host_payloads(payload: bytes) -> list[bytes]:
    """All-gather one opaque bytes payload per process, ordered by
    process index.

    The span tracer uses this to merge per-host traces: every host
    serialises its local spans (``obs.trace.local_trace_payload``),
    the payloads ride a padded uint8 ``process_allgather`` over
    ICI/DCN, and process 0 writes the merged Chrome trace.  A
    single-process run returns ``[payload]`` without touching
    collectives, so the path is free off-pod.
    """
    import jax

    payload = bytes(payload)
    if jax.process_count() == 1:
        return [payload]
    from jax.experimental import multihost_utils

    arr = np.frombuffer(payload, np.uint8)
    # lengths first: payload sizes differ per host (span counts do)
    lens = np.asarray(multihost_utils.process_allgather(
        np.array([arr.size], np.int64))).reshape(-1)
    width = max(int(lens.max()), 1)
    padded = np.zeros(width, np.uint8)
    padded[: arr.size] = arr
    gathered = np.asarray(
        multihost_utils.process_allgather(padded)
    ).reshape(len(lens), width)
    return [bytes(gathered[i, : int(lens[i])]) for i in range(len(lens))]
