from .mesh import MeshPulsarSearch, make_mesh
