from .mesh import MeshPulsarSearch, make_mesh, sharded_search_program
