"""FFT cross-correlation delay finding (experimental tool).

Reference: ``DelayFinder`` (`include/transforms/correlator.hpp:33-92`,
driven only by the stale ``accmap.cpp``): for every antenna baseline
(i, j>i) it forms ifft(conj(fft(x_i)) * fft(x_j)), keeps the first and
last ``max_delay`` lags, and reports the argmax of |c|^2 within that
window ("Distance", an index in [0, 2*max_delay)).

TPU redesign: all antenna FFTs are computed once and every baseline's
correlation/argmax is evaluated in a single vmapped jitted program
(the reference loops baselines serially with one FFT per visit,
`correlator.hpp:63-88`).
"""

from __future__ import annotations

from functools import partial

import numpy as np
import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnames=("max_delay",))
def _baseline_delays(arrays: jnp.ndarray, ii: jnp.ndarray, jj: jnp.ndarray,
                     max_delay: int):
    """arrays: (n, size) complex64; ii/jj: (nbase,) baseline indices."""
    ffts = jnp.fft.fft(arrays, axis=1)

    def one(i, j):
        corr = jnp.fft.ifft(jnp.conj(ffts[i]) * ffts[j])
        window = jnp.concatenate(
            [corr[:max_delay], corr[-max_delay:]]
        )
        power = jnp.abs(window) ** 2
        return jnp.argmax(power), jnp.max(power)

    return jax.vmap(one)(ii, jj)


def distance_to_lag(distance: int, max_delay: int) -> int:
    """Window index -> signed sample lag: the second half of the window
    holds the negative lags (`correlator.hpp:77-78`)."""
    return (
        int(distance)
        if distance < max_delay
        else int(distance) - 2 * max_delay
    )


def find_delays(arrays: np.ndarray, max_delay: int) -> list[dict]:
    """Delay of every baseline of an (nant, size) array stack.

    Returns one record per pair (i, j>i): the reference's window-index
    ``distance`` plus the signed ``lag`` in samples and the peak
    correlation power.
    """
    arrays = jnp.asarray(arrays, jnp.complex64)
    n = arrays.shape[0]
    size = arrays.shape[1]
    if not 0 < max_delay <= size // 2:
        raise ValueError(
            f"max_delay must be in (0, size//2]; got {max_delay} for "
            f"size {size}"
        )
    pairs = [(i, j) for i in range(n) for j in range(i + 1, n)]
    if not pairs:
        return []
    ii = jnp.asarray([p[0] for p in pairs], jnp.int32)
    jj = jnp.asarray([p[1] for p in pairs], jnp.int32)
    distances, powers = _baseline_delays(arrays, ii, jj, int(max_delay))
    distances = np.asarray(distances)
    powers = np.asarray(powers)
    return [
        {
            "i": i, "j": j,
            "distance": int(d),
            "lag": distance_to_lag(int(d), int(max_delay)),
            "power": float(p),
        }
        for (i, j), d, p in zip(pairs, distances, powers)
    ]
