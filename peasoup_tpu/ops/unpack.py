"""Device-side bit unpacking for 1/2/4/8-bit filterbank words.

Host→device transfer of a whole filterbank is bandwidth-bound; shipping
the *packed* bytes and unpacking on device cuts the transfer by 8/nbits.
Bit order matches ``peasoup_tpu.io.unpack`` (little-endian within each
byte), which mirrors what the reference feeds to ``dedisp_execute``
(`include/transforms/dedisperser.hpp:104-112`).
"""

from __future__ import annotations

import jax.numpy as jnp


def unpack_bits_device(raw: jnp.ndarray, nbits: int) -> jnp.ndarray:
    """Unpack a uint8 byte vector to one value per sample (on device).

    Returns an int32 vector of length ``len(raw) * (8 // nbits)``.
    32-bit input is already one float per sample and passes through.
    """
    if nbits == 32:
        return raw
    if nbits == 8:
        return raw.astype(jnp.int32)
    if nbits not in (1, 2, 4):
        raise ValueError(f"unsupported nbits: {nbits}")
    spb = 8 // nbits
    mask = (1 << nbits) - 1
    b = raw.astype(jnp.int32)
    shifts = jnp.arange(spb, dtype=jnp.int32) * nbits
    vals = (b[:, None] >> shifts[None, :]) & mask  # (nbytes, spb)
    return vals.reshape(-1)
