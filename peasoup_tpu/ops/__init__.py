from .dedisperse import (
    generate_dm_list,
    delay_table,
    delays_in_samples,
    max_delay,
    dedisperse,
)
from .spectrum import form_power, form_interpolated
from .rednoise import median_scrunch5, linear_stretch, running_median, deredden
from .zap import zap_birdies, load_zaplist
from .stats import mean_rms_std, normalise, normalise_spectrum, transpose
from .resample import resample, resample2
from .harmonics import harmonic_sums
from .peaks import (
    extract_above_threshold,
    extract_top_peaks,
    identify_unique_peaks,
    spectrum_search_bounds,
)
from .unpack import unpack_bits_device
