from .dedisperse import (
    generate_dm_list,
    delay_table,
    delays_in_samples,
    max_delay,
    dedisperse,
)
