"""Thresholded peak extraction and unique-peak merging.

Reference semantics: `src/kernels.cu:384-416` (Thrust ``copy_if`` of all
bins above threshold, in index order) +
`include/transforms/peakfinder.hpp:27-94` (host merge of peaks closer
than ``min_gap`` bins, then conversion to fundamental frequency).

The dynamic-size ``copy_if`` is re-cast for TPU as a fixed-capacity
top-k compaction: the k smallest above-threshold bin indices (plus the
true above-threshold count) come back in one device->host transfer per
spectrum, keeping the jitted program shape-static and making per-shard
candidate buffers collective-friendly.  The reference's own capacity is
100000 (`peakfinder.hpp:17,61`).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

# two-stage extraction kicks in above this searched-prefix length on
# the default ("auto") path; the row width balances the row-reduction
# pass against the second top_k.
# PERF NOTE (r6 — the sweep the r5 NOTE was blocked on is DONE, see
# benchmarks/peaks_sweep.json + trace_summary_r6.md): below the
# threshold the batched approx_max_k lowers to full SORTS inside
# fused programs — ~64 ms of the r5 tutorial search's ~100 ms device
# time.  The shape-stability sweep (C in {64,128,256} x stop 9k..131k
# x cap 64..2048, each cell subprocess-isolated) confirmed the r5
# crash is specific to C=64 at stop >= 65537 on v5e (Mosaic row count
# >= 1024 with a 64-lane tile) — those cells are recorded unsafe in
# the sweep artifact and the tuner never picks them; every C=128/256
# cell is stable and exact.  Outcome, measured standalone AND
# in-program (per-op traces of both formulations, closing the r5
# attribution gap — in-program sort time is ~1.35x standalone because
# the sorts serialise against the surrounding fused ops): the narrow
# two-stage wins only at cap <= 64 (3.1 vs 9.5 ms at stop=65537 x
# 177, cap=64), loses at the tutorial's tuned cap=320 (13.5 vs
# 9.5 ms) — so it is landed behind the tuner for the cells where it
# measured faster, while the Pallas threshold-compaction kernel
# (ops/peaks_pallas.py, O(survivors) like the reference's Thrust
# copy_if) wins EVERY swept cell on TPU (1.1 ms at stop=65537 x 177,
# cap=320) and is the tuner's default there.  Method selection:
# search/tuning.py:resolve_peaks_methods (measured-cost sidecar per
# device kind / stop bucket / capacity); force one path with
# SearchConfig.peaks_method / --peaks_method for A/B runs.
_TWO_STAGE_MIN_SIZE = 1 << 17
_TWO_STAGE_ROW_WIDTH = 512
# narrow row width for two-stage below 2^17 (the sweep's stable
# all-sizes pick; C=64 is faster still at tiny caps but unsafe at
# stop >= 65537 on v5e — see benchmarks/peaks_sweep.json)
_TWO_STAGE_NARROW_WIDTH = 128

#: selectable extraction lowerings (search/tuning.py picks per
#: (device kind, stop bucket, capacity); "auto" = the legacy
#: size-based heuristic, used when no measured costs apply)
EXTRACTION_METHODS = ("sort", "two_stage", "pallas")

_pallas_fallback_warned = False


def _resolve_method(method: str, stop_idx: int) -> str:
    """Static (trace-time) method resolution.  "auto" keeps the legacy
    heuristic bit-for-bit: two-stage above ``_TWO_STAGE_MIN_SIZE``,
    sort (approx_max_k) below.  Tuned selection happens in the DRIVERS
    (search/tuning.py) and arrives here as a concrete method."""
    if method == "auto":
        return "two_stage" if stop_idx > _TWO_STAGE_MIN_SIZE else "sort"
    if method not in EXTRACTION_METHODS:
        raise ValueError(
            f"peaks method {method!r}: use one of "
            f"{('auto',) + EXTRACTION_METHODS}")
    return method


def _two_stage_width(row_width: int, stop_idx: int) -> int:
    """Row width for the two-stage path: caller-pinned, else the
    legacy 512 above 2^17 and the sweep's narrow 128 below."""
    if row_width:
        return int(row_width)
    return (_TWO_STAGE_ROW_WIDTH if stop_idx > _TWO_STAGE_MIN_SIZE
            else _TWO_STAGE_NARROW_WIDTH)


def _pallas_or_fallback(spectrum, thresh, start_idx, stop_idx, capacity):
    """The pallas-compaction path, falling back to the score-based XLA
    formulation (same ascending-index contract) where the kernel can
    run neither compiled nor in interpret mode — so a forced
    ``peaks_method="pallas"`` config stays runnable (and result-
    equivalent) on any backend."""
    from .peaks_pallas import (
        extract_above_threshold_pallas,
        pallas_peaks_interpret,
        pallas_peaks_supported,
    )

    ok, reason = pallas_peaks_supported()
    if ok:
        return extract_above_threshold_pallas(
            spectrum, thresh, start_idx, stop_idx, capacity,
            interpret=pallas_peaks_interpret(),
        )
    global _pallas_fallback_warned
    if not _pallas_fallback_warned:
        _pallas_fallback_warned = True
        from ..obs.events import warn_event

        warn_event(
            "peaks_pallas_fallback",
            f"pallas peak compaction unavailable ({reason}); using the "
            f"XLA score-based formulation (same contract)",
            reason=reason,
        )
    return _extract_above_threshold_xla(
        spectrum, thresh, start_idx, stop_idx, capacity,
        two_stage=stop_idx > _TWO_STAGE_MIN_SIZE,
        row_width=_TWO_STAGE_ROW_WIDTH,
    )


def _extract_above_threshold_xla(
    spectrum, thresh, start_idx, stop_idx, capacity,
    *, two_stage: bool, row_width: int,
):
    """The XLA score-top_k formulations behind
    :func:`extract_above_threshold` (``two_stage`` selects the
    row-reduction variant; ``row_width`` is its C)."""
    size = spectrum.shape[0]
    spec = spectrum[:stop_idx]
    k_eff = min(capacity, stop_idx)
    sentinel = jnp.int32(-(size + 1))
    if two_stage:
        # two-stage extraction: a single lax.top_k over millions of
        # bins costs ~8 ms on v5e; selecting the top-`capacity` ROWS
        # first (by earliest qualifying index) cuts it to ~0.5 ms.
        # Exact because global index order is (row, col) lex order and
        # every selected row holds >= 1 hit: the first k_eff hits
        # always lie within the first k_eff hit-rows.
        C = row_width
        R = -(-stop_idx // C)
        i = jnp.arange(R * C, dtype=jnp.int32)
        sp = jnp.pad(spec, (0, R * C - stop_idx))
        mask2 = (i >= start_idx) & (i < stop_idx) & (sp > thresh)
        score2 = jnp.where(mask2, -i, sentinel).reshape(R, C)
        _, rows = jax.lax.top_k(jnp.max(score2, axis=1), min(k_eff, R))
        # min(k_eff, R)*C >= k_eff always (k_eff <= stop_idx <= R*C),
        # so the flattened selection can honour k_eff directly
        top, _ = jax.lax.top_k(score2[rows].reshape(-1), k_eff)
        count = jnp.sum(mask2, dtype=jnp.int32)
    else:
        i = jnp.arange(stop_idx, dtype=jnp.int32)
        mask = (i >= start_idx) & (spec > thresh)
        score = jnp.where(mask, -i, sentinel)
        top, _ = jax.lax.top_k(score, k_eff)  # largest = smallest idx
        count = jnp.sum(mask, dtype=jnp.int32)
    valid = top != sentinel
    idxs = jnp.where(valid, -top, -1)
    snrs = jnp.where(valid, spec[jnp.clip(-top, 0, stop_idx - 1)], 0.0)
    if k_eff < capacity:
        idxs = jnp.pad(idxs, (0, capacity - k_eff), constant_values=-1)
        snrs = jnp.pad(snrs, (0, capacity - k_eff))
    return idxs, snrs.astype(jnp.float32), count


def extract_above_threshold(
    spectrum: jnp.ndarray,
    thresh,
    start_idx: int,
    stop_idx: int,
    capacity: int,
    method: str = "auto",
    row_width: int = 0,
):
    """Compact the above-threshold bins of [start_idx, stop_idx).

    Returns (idxs, snrs, count): the ``capacity`` smallest qualifying
    bin indices in ascending order (padded with -1), their values, and
    the true number of qualifying bins (may exceed ``capacity``).

    ``method`` selects the lowering — ``"sort"`` (one score top_k,
    which XLA lowers to a full sort), ``"two_stage"`` (row-reduction
    then a small top_k; ``row_width`` pins its C, 0 = tuned default),
    or ``"pallas"`` (the O(survivors) threshold-compaction kernel,
    ops/peaks_pallas.py).  All three return BIT-IDENTICAL results
    (tests/test_ops.py pins this across the edge shapes); ``"auto"``
    keeps the legacy size heuristic.
    """
    size = spectrum.shape[0]
    # bins >= stop_idx can never qualify: sort only the searched prefix
    # (for low harmonic levels stop_idx << size, cutting the top_k cost)
    stop_idx = min(stop_idx, size)
    start_idx = min(start_idx, stop_idx)
    method = _resolve_method(method, stop_idx)
    if method == "pallas":
        return _pallas_or_fallback(
            spectrum, thresh, start_idx, stop_idx, capacity)
    return _extract_above_threshold_xla(
        spectrum, thresh, start_idx, stop_idx, capacity,
        two_stage=method == "two_stage" and stop_idx > 0,
        row_width=_two_stage_width(row_width, stop_idx),
    )


def extract_top_peaks(
    spectrum: jnp.ndarray,
    thresh,
    start_idx: int,
    stop_idx: int,
    capacity: int,
    method: str = "auto",
    row_width: int = 0,
):
    """Value-ordered thresholded peak extraction (the hot-path variant).

    Returns (idxs, snrs, count): the ``capacity`` LARGEST qualifying
    values with their bin indices — hit slots form a prefix (descending
    SNR), padded with idx=-1/snr=0 — plus the true qualifying count.

    Differences from :func:`extract_above_threshold`, both exploited
    for speed on v5e (top_k over the index scores costs ~0.1 ms per
    spectrum; selecting by VALUE needs no iota/score materialisation
    and no snr gather):

    * slot order is descending SNR, not ascending index — callers sort
      segments host-side before the unique-peak merge (cheap: ~10^5
      entries per dispatch);
    * when ``count > capacity`` the kept subset is the largest-SNR one,
      not the smallest-index one.  Every driver re-searches clipped
      rows with escalated capacity (`_rerun_clipped_rows`,
      `_search_tim`), so the subset choice never reaches results.

    Exactness: small spectra use ``lax.approx_max_k`` with
    ``recall_target=1.0`` (exact per its contract; verified against
    ``lax.top_k`` on clustered/strided adversarial hit patterns).
    Large spectra use a two-stage row-selected top_k: the global top-k
    values always lie within the top-k rows by row-max (if a row were
    excluded, the k selected rows' maxima would all exceed the k-th
    value — a contradiction).  NaNs never qualify (compare is False),
    matching the score-based path.

    ``method``/``row_width``: see :func:`extract_above_threshold`.
    The ``"pallas"`` lowering compacts in INDEX order — hit slots are
    then ascending-index (not descending-SNR) and a clipped row keeps
    the smallest-index subset; both deviations are invisible to the
    drivers (every consumer sorts segments host-side before the peak
    merge, and clipped rows are re-searched — the same argument as the
    bullet list above).
    """
    size = spectrum.shape[0]
    stop_idx = min(stop_idx, size)
    start_idx = min(start_idx, stop_idx)
    method = _resolve_method(method, stop_idx)
    if method == "pallas":
        return _pallas_or_fallback(
            spectrum, thresh, start_idx, stop_idx, capacity)
    k_eff = min(capacity, stop_idx)
    neg = jnp.float32(-jnp.inf)
    spec = spectrum[:stop_idx]
    body = jnp.where(spec[start_idx:] > thresh, spec[start_idx:], neg)
    if start_idx > 0:
        masked = jnp.concatenate(
            [jnp.full((start_idx,), neg, spectrum.dtype), body]
        )
    else:
        masked = body
    count = jnp.sum(masked > thresh, dtype=jnp.int32)
    C = _two_stage_width(row_width, stop_idx)
    R = -(-stop_idx // C)
    if method == "two_stage" and k_eff < R and stop_idx > 0:
        # two-stage by value: top-k_eff rows by row-max provably
        # contain the k_eff largest values (see docstring)
        m2 = jnp.pad(masked, (0, R * C - stop_idx),
                     constant_values=neg).reshape(R, C)
        _, rows = jax.lax.top_k(jnp.max(m2, axis=1), k_eff)
        top, ti_local = jax.lax.top_k(m2[rows].reshape(-1), k_eff)
        ti = rows[ti_local // C] * C + ti_local % C
    elif method == "two_stage" and stop_idx > _TWO_STAGE_MIN_SIZE:
        # k_eff >= R: row selection cannot help; exact single top_k
        top, ti = jax.lax.top_k(masked, k_eff)
    elif method == "two_stage":
        # k_eff >= R below the legacy threshold: the narrow-row
        # selection degenerates — keep the small-spectrum lowering
        top, ti = jax.lax.approx_max_k(masked, k_eff, recall_target=1.0)
    elif stop_idx > _TWO_STAGE_MIN_SIZE:
        # "sort" on a large prefix: one exact top_k (approx_max_k's
        # reduction path is tuned for <= 2^17 operands)
        top, ti = jax.lax.top_k(masked, k_eff)
    else:
        top, ti = jax.lax.approx_max_k(masked, k_eff, recall_target=1.0)
    hit = top > thresh
    idxs = jnp.where(hit, ti.astype(jnp.int32), -1)
    snrs = jnp.where(hit, top, 0.0).astype(jnp.float32)
    if k_eff < capacity:
        idxs = jnp.pad(idxs, (0, capacity - k_eff), constant_values=-1)
        snrs = jnp.pad(snrs, (0, capacity - k_eff))
    return idxs, snrs, count


def segmented_unique_peaks(
    idxs: np.ndarray,
    snrs: np.ndarray,
    seg_bounds: np.ndarray,
    min_gap: int = 30,
):
    """Run the unique-peak merge over every segment of a concatenated
    entry list in one native call (segments = per-spectrum slices).

    Returns (merged_idxs, merged_snrs, per_segment_counts).
    """
    try:
        from ..native import lib as _native
    except Exception:
        _native = None
    if _native is not None:
        return _native.unique_peaks_segmented(idxs, snrs, seg_bounds,
                                              min_gap)
    outs_i, outs_s, counts = [], [], []
    for lo, hi in zip(seg_bounds[:-1], seg_bounds[1:]):
        pi, ps = identify_unique_peaks(idxs[lo:hi], snrs[lo:hi], min_gap)
        outs_i.append(pi)
        outs_s.append(ps)
        counts.append(len(pi))
    return (
        np.concatenate(outs_i) if outs_i else np.zeros(0, np.int64),
        np.concatenate(outs_s) if outs_s else np.zeros(0, np.float32),
        np.array(counts, np.int64),
    )


def identify_unique_peaks(
    idxs: np.ndarray, snrs: np.ndarray, min_gap: int = 30
):
    """Greedy merge of above-threshold bins into unique peaks.

    Exact reproduction of `peakfinder.hpp:27-56`: walking in index
    order, a group keeps absorbing bins while the next bin is within
    ``min_gap`` of the index of the group's current best peak (the
    "last" index only advances when a higher value is found).  The walk
    is sequential, so a native C++ fast path is used when available.
    """
    try:
        from ..native import lib as _native
    except Exception:
        _native = None
    if _native is not None:
        return _native.unique_peaks(idxs, snrs, min_gap)
    peak_idxs: list[int] = []
    peak_snrs: list[float] = []
    count = len(idxs)
    ii = 0
    while ii < count:
        cpeak = snrs[ii]
        cpeakidx = idxs[ii]
        lastidx = idxs[ii]
        ii += 1
        while ii < count and (idxs[ii] - lastidx) < min_gap:
            if snrs[ii] > cpeak:
                cpeak = snrs[ii]
                cpeakidx = idxs[ii]
                lastidx = idxs[ii]
            ii += 1
        peak_idxs.append(int(cpeakidx))
        peak_snrs.append(float(cpeak))
    return np.array(peak_idxs, dtype=np.int64), np.array(peak_snrs, dtype=np.float32)


def spectrum_search_bounds(
    size: int, bin_width: float, nh: int, min_freq: float, max_freq: float
):
    """Search window and frequency factor for a harmonic-summed spectrum.

    Matches `peakfinder.hpp:77-94`: ``nh`` is the harmonic level (0 for
    the fundamental spectrum, k for the 2^k-harmonic sum); returned
    ``freq_factor`` converts a bin index to the fundamental frequency.
    """
    nyquist = bin_width * size
    orig_size = 2.0 * (size - 1.0)
    max_bin = int((max_freq / bin_width) * 2.0 ** nh)
    start_idx = int(orig_size * (min_freq / nyquist) * 2.0 ** nh)
    stop_idx = min(size, max_bin)
    freq_factor = 1.0 / size * nyquist / 2.0 ** nh
    return start_idx, stop_idx, freq_factor
