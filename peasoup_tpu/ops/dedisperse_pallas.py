"""Tiled Pallas dedispersion kernel for real channel counts.

The XLA formulation in :mod:`peasoup_tpu.ops.dedisperse` scans channels
sequentially with the (ndm, out_nsamps) accumulator living in HBM, so
its traffic is ``nchans * ndm * out_nsamps * 8`` bytes — fine for the
64-channel tutorial file, catastrophic at 1024-4096 channels (the scale
``libdedisp`` handles inside `include/transforms/dedisperser.hpp:104-112`).

This kernel keeps a (DM_TILE, TIME_TILE) accumulator in VMEM and
streams the input past it once per DM tile:

* grid = (ndm / DM_TILE, out_nsamps / TIME_TILE);
* per program, channels are processed in groups of CHAN_GROUP; each
  group's samples for the whole DM tile live in one rectangular window
  ``data[g0:g0+G, t0 + min_delay : t0 + min_delay + TIME_TILE + slack]``
  (delays vary smoothly across both channels and neighbouring DM
  trials, so the window height ``slack`` is small), DMA'd HBM->VMEM
  with double buffering;
* the inner loop adds dynamically-shifted window rows into the
  accumulator rows — the only data-dependent addressing left, and it
  is VMEM-resident.

HBM traffic drops to ``(ndm / DM_TILE) * nchans * nsamps`` input reads
plus one output write — DM_TILE x less than the scan — and the kernel
becomes VPU-add bound (the algorithm's inherent ndm*nchans*T adds).

Input may be float32 or uint8 (8-bit filterbanks stay packed in HBM;
the f32 conversion happens on VMEM tiles, reference analogue
`src/kernels.cu:1144-1171` conversion_kernel).
"""

from __future__ import annotations

from functools import partial

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def dedisperse_window_slack(
    delays: np.ndarray, dm_tile: int, chan_group: int
) -> int:
    """Static bound on (max - min) delay within any (dm_tile, chan_group)
    block of the delay table, rounded up to a lane multiple.

    This is the extra window width the kernel DMAs per channel group so
    that every row's shifted slice lands inside VMEM.
    """
    delays = np.asarray(delays)
    ndm, nchans = delays.shape
    slack = 0
    for i0 in range(0, ndm, dm_tile):
        blk = delays[i0 : i0 + dm_tile]
        for g0 in range(0, nchans, chan_group):
            sub = blk[:, g0 : g0 + chan_group]
            slack = max(slack, int(sub.max()) - int(sub.min()))
    return -(-(slack + 1) // 128) * 128  # pad + round up to 128


def _dedisperse_kernel(
    delays_ref, data_ref, out_ref, win_ref, sem_ref,
    *, dm_tile, time_tile, chan_group, slack, nchans, nsamps,
):
    T, G, S = time_tile, chan_group, slack
    W = T + S
    t0 = pl.program_id(1) * T
    ngroups = nchans // G

    # the wrapper pads the input so every window [t0+dmin, t0+dmin+W)
    # is in bounds — no clamping, so per-(d,c) offsets stay exact
    def group_start(g):
        return t0 + jnp.min(delays_ref[:, pl.ds(g * G, G)])

    def group_dma(slot, g):
        return pltpu.make_async_copy(
            data_ref.at[pl.ds(g * G, G), pl.ds(group_start(g), W)],
            win_ref.at[slot],
            sem_ref.at[slot],
        )

    out_ref[:] = jnp.zeros_like(out_ref)
    group_dma(0, 0).start()

    def group_body(g, _):
        slot = g % 2

        @pl.when(g + 1 < ngroups)
        def _():
            group_dma((g + 1) % 2, g + 1).start()

        group_dma(slot, g).wait()
        start = group_start(g)

        def d_body(d, _):
            def c_body(c, acc):
                off = t0 + delays_ref[d, g * G + c] - start
                w = win_ref[slot, c, pl.ds(off, T)]
                if w.dtype == jnp.uint8:
                    w = w.astype(jnp.int32)  # Mosaic has no u8->f32 cast
                return acc + w.astype(jnp.float32)

            row = jax.lax.fori_loop(
                jnp.int32(0), jnp.int32(G), c_body,
                jnp.zeros((T,), jnp.float32),
            )
            out_ref[d, :] += row
            return 0

        jax.lax.fori_loop(jnp.int32(0), jnp.int32(dm_tile), d_body, 0)
        return 0

    # int32 bounds: under jax_enable_x64 python-int bounds make the
    # index i64, which Mosaic's memref slicing rejects
    jax.lax.fori_loop(jnp.int32(0), jnp.int32(ngroups), group_body, 0)


@partial(
    jax.jit,
    static_argnames=(
        "out_nsamps", "window_slack", "dm_tile", "time_tile",
        "chan_group", "interpret",
    ),
)
def dedisperse_pallas(
    data: jax.Array,
    delays: jax.Array,
    out_nsamps: int,
    *,
    window_slack: int,
    dm_tile: int = 32,
    time_tile: int = 8192,
    chan_group: int = 16,
    interpret: bool = False,
) -> jax.Array:
    """Dedisperse with the tiled VMEM-accumulator kernel.

    Args:
        data: (nchans, nsamps) float32 or uint8, channel-major, already
            killmask-multiplied.
        delays: (ndm, nchans) int32 sample delays.
        out_nsamps: output samples per trial (nsamps - max_delay).
        window_slack: static per-(tile, group) delay spread bound from
            :func:`dedisperse_window_slack` (must be computed from the
            same dm_tile/chan_group).
        interpret: run the interpreter (CPU tests).

    Returns:
        (ndm, out_nsamps) float32.
    """
    ndm, nchans = delays.shape
    nsamps = data.shape[1]
    if nchans % chan_group:
        raise ValueError(f"{nchans=} not a multiple of {chan_group=}")
    T, S = time_tile, window_slack
    if out_nsamps < T:
        raise ValueError(
            f"input too short for the kernel window ({out_nsamps=} < "
            f"{T}); use the XLA scan path"
        )
    ndm_p = -(-ndm // dm_tile) * dm_tile
    out_p = -(-out_nsamps // T) * T
    # every (tile, group) window [t0 + dmin, t0 + dmin + T + S) must be
    # in bounds without clamping (clamping would shift valid offsets).
    # max delay is statically nsamps - out_nsamps (the dedisp contract,
    # `dedisperser.hpp:100-101`), so the worst window end is
    # (out_p - T) + max_delay + T + S; pad the tail to reach it.  The
    # chunked driver bakes this padding into its device-resident buffer,
    # so the pad here is a no-op on the hot path.
    need = out_p + (nsamps - out_nsamps) + S
    if nsamps < need:
        data = jnp.pad(data, ((0, 0), (0, need - nsamps)))
        nsamps = need
    if ndm_p != ndm:
        delays = jnp.pad(delays, ((0, ndm_p - ndm), (0, 0)), mode="edge")

    grid = (ndm_p // dm_tile, out_p // T)
    out = pl.pallas_call(
        partial(
            _dedisperse_kernel,
            dm_tile=dm_tile, time_tile=T, chan_group=chan_group,
            slack=S, nchans=nchans, nsamps=nsamps,
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (dm_tile, nchans), lambda i, j: (i, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=pl.BlockSpec(
            (dm_tile, T), lambda i, j: (i, j), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((ndm_p, out_p), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((2, chan_group, T + S), data.dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        interpret=interpret,
    )(delays, data)
    return out[:ndm, :out_nsamps]
