"""Tiled Pallas dedispersion kernel for real channel counts.

The XLA formulation in :mod:`peasoup_tpu.ops.dedisperse` scans channels
sequentially with the (ndm, out_nsamps) accumulator living in HBM, so
its traffic is ``nchans * ndm * out_nsamps * 8`` bytes — fine for the
64-channel tutorial file, catastrophic at 1024-4096 channels (the scale
``libdedisp`` handles inside `include/transforms/dedisperser.hpp:104-112`).

This kernel keeps a (DM_TILE, TIME_TILE) accumulator in VMEM and
streams the input past it once per DM tile:

* grid = (ndm / DM_TILE, out_nsamps / TIME_TILE);
* per program, channels are processed in groups of CHAN_GROUP; each
  group's samples for the whole DM tile live in a VMEM window, DMA'd
  HBM->VMEM with double buffering;
* the inner loop reads a 128-aligned coarse slice of the window and
  applies the 0..127 fine shift with a lane rotate (``pltpu.roll``).

HBM traffic drops to ``(ndm / DM_TILE) * nchans * nsamps`` input reads
plus one output write — DM_TILE x less than the scan — and the kernel
becomes VPU-bound (the algorithm's inherent ndm*nchans*T adds, plus
~2 extra vector ops per add for the coarse-read + rotate).

Sublane-packed time layout
--------------------------

A time series is 1-D, but TPU vector registers are (8 sublanes, 128
lanes): operating on ``(1, T)`` rows uses 1/8 of every vreg. The
kernel therefore splits each DM row's time tile T into 8 sublane
chunks of ``TQ = T/8`` samples, and each channel window into 8
*separately DMA'd* sublane windows whose starts are
``align128(t0 + group_min) + s*TQ``. Because TQ is a multiple of 128,
the residual offset ``off = t0 + delay - align128(t0 + group_min)``
is identical for all 8 sublane rows, so one (8, RW) coarse read + one
lane rotate shifts all 8 chunks at once — full vreg utilisation.

The accumulator and HBM output use the matching packed layout
``(ndm, nj, 8, TQ)``; a host-side reshape to (ndm, nj*T) is exactly
the logical time order.

Input may be float32 or uint8 (8-bit filterbanks stay packed in HBM;
the f32 conversion happens once per VMEM window, reference analogue
`src/kernels.cu:1144-1171` conversion_kernel).

TPU-backend notes (all verified on a real v5e chip):

* the whole pallas_call is traced under ``enable_x64(False)``:
  jax_enable_x64 (which this package switches on for f64 index math
  elsewhere) makes pallas' internal index bookkeeping produce i64
  values that Mosaic either rejects or recurses on;
* ``tpu.dynamic_rotate`` requires a power-of-two lane width and is
  *silently wrong* otherwise (8192/16384 exact; 8320/4224/3840
  corrupt) — hence ``TQ + 128`` must be a power of two;
* vector loads/DMAs need *provably* 128-aligned minor-dim starts:
  every data-dependent offset is decomposed as
  ``(off // 128) * 128 + fine`` with ``pl.multiple_of`` hints;
* scalar reads (the per-(dm, chan) delays) must live in SMEM — from
  VMEM they lower to (1, 1) vector loads with unprovable alignment.
"""

from __future__ import annotations

from functools import partial

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax._src.config import enable_x64


_interpret_probe: tuple[bool, str] | None = None


def pallas_interpret_supported() -> tuple[bool, str]:
    """Capability probe: can this jax/jaxlib run the package's pallas
    kernels in interpret mode (the CPU test path)?

    jax 0.4.37's interpret-mode lowering leaks i64 scalars across the
    internal pjit boundaries of the kernel wrappers when the process
    has ``jax_enable_x64`` on (as this package does) — Mosaic-free
    though it is, the generated MLIR fails verification with
    ``'func.call' op operand type mismatch ... 'tensor<i64>'``.
    Compiled TPU execution is unaffected.  Rather than pin a version
    range, run the real kernel once at a tiny shape and report
    (ok, reason); the result is cached for the process.  Tests gate on
    this via the ``pallas_interpret`` fixture in ``tests/conftest.py``
    so broken builds *skip with the probe's reason* instead of failing
    (or blanket-xfailing on builds where interpret mode works).
    """
    global _interpret_probe
    if _interpret_probe is None:
        try:
            delays = np.zeros((8, 8), np.int32)
            slack = dedisperse_window_slack(delays, 8, 8)
            data = jnp.zeros((8, 1024 + slack + 256), jnp.float32)
            out = dedisperse_pallas(
                data, jnp.asarray(delays), 1024, window_slack=slack,
                dm_tile=8, time_tile=1024, chan_group=8, interpret=True,
            )
            jax.block_until_ready(out)
            _interpret_probe = (True, "")
        except Exception as exc:  # noqa: BLE001 - reported via skip
            _interpret_probe = (
                False, f"{type(exc).__name__}: {str(exc).splitlines()[0]}")
    return _interpret_probe


def dedisperse_window_slack(
    delays: np.ndarray, dm_tile: int, chan_group: int
) -> int:
    """Static bound on (max - min) delay within any (dm_tile, chan_group)
    block of the delay table, rounded up to a lane multiple.

    This is the extra window width the kernel DMAs per channel group so
    that every row's shifted slice lands inside VMEM.
    """
    delays = np.asarray(delays)
    ndm, nchans = delays.shape
    slack = 0
    for i0 in range(0, ndm, dm_tile):
        blk = delays[i0 : i0 + dm_tile]
        for g0 in range(0, nchans, chan_group):
            sub = blk[:, g0 : g0 + chan_group]
            slack = max(slack, int(sub.max()) - int(sub.min()))
    return -(-(slack + 1) // 128) * 128  # pad + round up to 128


def _dedisperse_flat_kernel(
    gmins_ref, delays_ref, *refs, dm_tile, time_tile,
    chan_group, slack, part_chans, nsamps, delays_blocked, align,
    group_range=None,
):
    """Flat-input variant: the filterbank arrives as 1-D u8/f32 part
    refs (whole channels each), so no 2-D entry-parameter layout exists
    for XLA to disagree about (a 2-D u8 operand gets a column-major
    entry layout and relayout-copies the full 8 GB input at production
    scale — the bug that kept the original kernel off the hot path).

    Per (group, channel) ONE contiguous window
    ``[astart, astart + T + S + 128)`` is DMA'd from the channel's flat
    offset; the 8 sublane chunks are repacked in VMEM (the windows
    overlap, so separate sublane DMAs would re-read HBM 8x... they are
    slices of the one window instead).
    """
    G = chan_group
    nparts = len(refs) - 3 - 2 * G
    part_refs = refs[:nparts]
    out_ref = refs[nparts]
    # 2*G separate 1-D (W1,) per-channel window refs — one per
    # (parity, channel).  u8 VMEM planes tile sublanes in blocks the
    # kernel cannot slice per channel, so every DMA destination is a
    # WHOLE 1-D ref; the double-buffer parity is STATIC (groups are
    # processed in pairs) because selecting among separate refs needs a
    # python-level index
    win_refs = refs[nparts + 1 : nparts + 1 + 2 * G]
    winf_ref, sem_ref = refs[nparts + 1 + 2 * G :]
    T, S, A = time_tile, slack, align
    TQ = T // 8          # per-sublane chunk
    RW = TQ + 128        # rotate width (power of two, checked by wrapper)
    WQ = TQ + S + A      # per-sublane window width
    # whole per-channel window (covers all 8 chunks); 1-D HBM memrefs
    # carry an (align,) tiling, so DMA starts AND lengths must be
    # align-multiples (1024 for u8, 256 for f32)
    W1 = -(-(T + S + A) // A) * A
    i_tile = pl.program_id(0)
    t0 = pl.program_id(1) * T

    def group_astart(g):
        start = t0 + gmins_ref[i_tile, g]
        return pl.multiple_of((start // A) * A, A)

    def group_dmas(part_ref, slot, g, g_local):
        astart = group_astart(g)
        return [
            pltpu.make_async_copy(
                part_ref.at[pl.ds(
                    (g_local * G + c) * nsamps + astart, W1)],
                win_refs[slot * G + c],
                sem_ref.at[slot, c],
            )
            for c in range(G)
        ]

    def process_group(slot, g, astart):
        # sublane repack + f32 conversion, once per window (~3% of the
        # inner-loop work): the 8 overlapping sublane chunks are static
        # slices of the one DMA'd window (Mosaic has no u8->f32 cast;
        # go via i32)
        for c in range(G):
            w = win_refs[slot * G + c][:]
            if w.dtype == jnp.uint8:
                w = w.astype(jnp.int32)
            wf = w.astype(jnp.float32)
            for s in range(8):
                winf_ref[c, s, :] = wf[s * TQ : s * TQ + WQ]

        def d_body(d, _):
            dd = d if delays_blocked else i_tile * dm_tile + d

            def chan(c, acc):
                off = t0 + delays_ref[dd, g * G + c] - astart
                coarse = pl.multiple_of((off // 128) * 128, 128)
                fine = off - coarse
                v = winf_ref[c, :, pl.ds(coarse, RW)]  # (8, RW)
                return acc + pltpu.roll(v, -fine, 1)[:, :TQ]

            acc = chan(0, jnp.zeros((8, TQ), jnp.float32))
            for c in range(1, G):
                acc = chan(c, acc)
            out_ref[pl.ds(d, 1), 0] += acc[None]
            return 0

        jax.lax.fori_loop(jnp.int32(0), jnp.int32(dm_tile), d_body, 0)

    out_ref[:] = jnp.zeros_like(out_ref)

    # python loop over parts (a traced channel index cannot select
    # among refs); groups inside a part run PAIRWISE so the
    # double-buffer parity stays static — the wrapper guarantees every
    # part's group count is even.  ``group_range`` (static, global
    # group units) restricts the sweep to a sub-band's groups; the
    # wrapper guarantees its bounds are pair-aligned within every part
    glo, ghi = group_range if group_range is not None else (
        0, sum(part_chans) // G)
    g_base = 0
    for pi, part_ref in enumerate(part_refs):
        ngroups_p = part_chans[pi] // G
        s_lo = max(glo - g_base, 0)
        s_hi = min(ghi - g_base, ngroups_p)
        if s_lo < s_hi:
            npairs = (s_hi - s_lo) // 2

            for cp in group_dmas(part_ref, 0, g_base + s_lo, s_lo):
                cp.start()

            def pair_body(k, _, part_ref=part_ref, g_base=g_base,
                          npairs=npairs, s_lo=s_lo):
                ge, go = s_lo + 2 * k, s_lo + 2 * k + 1  # local group ids
                for cp in group_dmas(part_ref, 1, g_base + go, go):
                    cp.start()
                for cp in group_dmas(part_ref, 0, g_base + ge, ge):
                    cp.wait()
                process_group(0, g_base + ge, group_astart(g_base + ge))

                @pl.when(k + 1 < npairs)
                def _():
                    for cp in group_dmas(part_ref, 0, g_base + go + 1,
                                         go + 1):
                        cp.start()

                for cp in group_dmas(part_ref, 1, g_base + go, go):
                    cp.wait()
                process_group(1, g_base + go, group_astart(g_base + go))
                return 0

            jax.lax.fori_loop(jnp.int32(0), jnp.int32(npairs),
                              pair_body, 0)
        g_base += ngroups_p


def _dedisperse_kernel(
    gmins_ref, delays_ref, data_ref, out_ref, win_ref, winf_ref, sem_ref,
    *, dm_tile, time_tile, chan_group, slack, nchans, delays_blocked,
):
    T, G, S = time_tile, chan_group, slack
    TQ = T // 8        # per-sublane chunk
    RW = TQ + 128      # rotate width (power of two, checked by wrapper)
    WQ = TQ + S + 128  # per-sublane window width
    i_tile = pl.program_id(0)  # hoisted: program_id inside nested
    t0 = pl.program_id(1) * T  # control flow breaks interpret mode
    ngroups = nchans // G

    # the wrapper pads the input so every window stays in bounds — no
    # clamping, so per-(d,c) offsets stay exact.  Group minima come
    # precomputed via SMEM: a vector-min over a dynamic column slice of
    # the delay table is not provably 128-aligned in-kernel.
    def group_astart(g):
        start = t0 + gmins_ref[i_tile, g]
        return pl.multiple_of((start // 128) * 128, 128)

    def group_dmas(slot, g):
        astart = group_astart(g)
        # dst is (s, channel)-ordered: a tiled ref cannot be sliced to
        # a single sublane row, so the s-windows land in the leading
        # dim here and one in-VMEM transpose per group re-packs them
        # into sublanes for the hot loop
        return [
            pltpu.make_async_copy(
                data_ref.at[pl.ds(g * G, G), pl.ds(astart + s * TQ, WQ)],
                win_ref.at[slot, s, :, :],
                sem_ref.at[slot, s],
            )
            for s in range(8)
        ]

    out_ref[:] = jnp.zeros_like(out_ref)
    for cp in group_dmas(0, 0):
        cp.start()

    def group_body(g, _):
        slot = g % 2

        @pl.when(g + 1 < ngroups)
        def _():
            for cp in group_dmas((g + 1) % 2, g + 1):
                cp.start()

        for cp in group_dmas(slot, g):
            cp.wait()
        astart = group_astart(g)

        # one conversion + transpose per window (~3% of the inner-loop
        # work): keeps the hot loop a uniform f32 read+rotate+add for
        # u8 and f32 inputs alike (Mosaic has no u8->f32 cast; go via
        # i32), and moves the 8 sublane windows from the DMA-friendly
        # leading dim into actual sublanes
        w = win_ref[slot]
        if w.dtype == jnp.uint8:
            w = w.astype(jnp.int32)
        winf_ref[:] = jnp.swapaxes(w.astype(jnp.float32), 0, 1)

        # d outer (dynamic fori), c inner (static python unroll): the
        # static c makes the window read's leading index free, and the
        # per-channel contributions accumulate in vector registers so
        # the out_ref read-modify-write happens once per (d, group)
        # instead of once per (d, c)
        def d_body(d, _):
            # unblocked delays (dm_tile not sublane-divisible, e.g. the
            # fold path's scattered-row dm_tile=1) index globally
            dd = d if delays_blocked else i_tile * dm_tile + d

            def chan(c, acc):
                off = t0 + delays_ref[dd, g * G + c] - astart  # [0, S+128)
                coarse = pl.multiple_of((off // 128) * 128, 128)
                fine = off - coarse
                v = winf_ref[c, :, pl.ds(coarse, RW)]  # (8, RW)
                return acc + pltpu.roll(v, -fine, 1)[:, :TQ]

            acc = chan(0, jnp.zeros((8, TQ), jnp.float32))
            for c in range(1, G):
                acc = chan(c, acc)
            out_ref[pl.ds(d, 1), 0] += acc[None]
            return 0

        # int32 bounds: under jax_enable_x64 python-int bounds make the
        # index i64, which Mosaic rejects
        jax.lax.fori_loop(jnp.int32(0), jnp.int32(dm_tile), d_body, 0)
        return 0

    jax.lax.fori_loop(jnp.int32(0), jnp.int32(ngroups), group_body, 0)


def _dedisperse_flat_sb_kernel(
    gmins_ref, delays_ref, *refs, dm_tile, time_tile, k_tiles,
    chan_group, slack, part_chans, nsamps, align, csub, njk,
    delays_blocked,
):
    """Sub-band stage-1 kernel: grid (dm tiles, nsub, time) where each
    step sweeps ONE sub-band's channels over K consecutive time tiles.

    vs computing sub-bands inside the direct kernel (a per-group output
    slot in a (dm, nsub, T) VMEM block): the out block here is
    (dm_tile, 1, K, 8, TQ) — nsub lives in the GRID — so dm_tile and
    the per-DMA window length K*T stay large.  The direct kernel is
    DMA-ISSUE-bound at small windows (one DMA per channel per tile;
    measured flat ~0.2 s/chunk at 1024 chans regardless of row count),
    so cutting the DMA count by K and keeping full tiles is where the
    sub-band speedup actually comes from.

    Window DMAs are double-buffered across the step's channel GROUPS
    (parity = group index, STATIC — a traced slot cannot select among
    python-level window refs): group gg+1 streams in while gg
    computes.  csub >= 2*chan_group guarantees >= 2 groups per step,
    so only the first group's DMA latency is exposed per grid step
    (~15 us of a ~100 us step).
    """
    G = chan_group
    CS = csub
    nparts = len(refs) - 3 - 2 * G
    part_refs = refs[:nparts]
    out_ref = refs[nparts]
    win_refs = refs[nparts + 1 : nparts + 1 + 2 * G]  # (parity, chan)
    winf_ref, sem_ref = refs[nparts + 1 + 2 * G :]
    T, S, A, K = time_tile, slack, align, k_tiles
    TQ = T // 8
    RW = TQ + 128
    WQ = TQ + S + A
    # per-kk slice length must be A-aligned (u8 1-D VMEM tiling), and
    # the window must cover the last kk's rounded slice
    WL = -(-(T + S + A) // A) * A
    W1 = -(-((K - 1) * T + WL) // A) * A
    i_tile = pl.program_id(0)
    s = pl.program_id(1)
    jk = pl.program_id(2)
    gps = CS // G  # channel groups per sub-band (>= 2)

    def astart_of(g):
        start = jk * (K * T) + gmins_ref[i_tile, g]
        return pl.multiple_of((start // A) * A, A)

    def group_dmas(gg, slot):
        """One K*T-long window DMA per channel of sub-band group gg."""
        g_base = 0
        for pi, part_ref in enumerate(part_refs):
            ngroups_p = part_chans[pi] // G
            nsub_p = ngroups_p // gps  # sub-bands in this part
            s_lo = g_base // gps

            @pl.when(jnp.logical_and(s >= s_lo, s < s_lo + nsub_p))
            def _(part_ref=part_ref, s_lo=s_lo):
                gl = (s - s_lo) * gps + gg  # part-local group
                astart = astart_of(s * gps + gg)
                for c in range(G):
                    pltpu.make_async_copy(
                        part_ref.at[pl.ds(
                            (gl * G + c) * nsamps + astart, W1)],
                        win_refs[slot * G + c],
                        sem_ref.at[slot, c],
                    ).start()

            g_base += ngroups_p

    def wait_group(slot):
        for c in range(G):
            pltpu.make_async_copy(
                win_refs[slot * G + c], win_refs[slot * G + c],
                sem_ref.at[slot, c],
            ).wait()

    group_dmas(0, 0)
    out_ref[:] = jnp.zeros_like(out_ref)

    for gg in range(gps):
        slot = gg % 2
        if gg + 1 < gps:
            group_dmas(gg + 1, (gg + 1) % 2)
        wait_group(slot)
        astart = astart_of(s * gps + gg)
        # repack per (kk): winf holds ONE time tile's 8 sublane chunks
        # for the group's G channels (a K-wide winf would not fit
        # VMEM); only the kk-relevant WL-slice is loaded/converted so
        # the u8->f32 conversion volume stays ~1x the window
        for kk in range(K):
            for c in range(G):
                w = win_refs[slot * G + c][pl.ds(kk * T, WL)]
                if w.dtype == jnp.uint8:
                    w = w.astype(jnp.int32)
                wf = w.astype(jnp.float32)
                for s8 in range(8):
                    winf_ref[c, s8, :] = wf[s8 * TQ : s8 * TQ + WQ]

            def d_body(d, _):
                dd = d if delays_blocked else i_tile * dm_tile + d

                def chan(c, acc):
                    off = (jk * (K * T)
                           + delays_ref[dd, (s * gps + gg) * G + c]
                           - astart)
                    coarse = pl.multiple_of((off // 128) * 128, 128)
                    fine = off - coarse
                    v = winf_ref[c, :, pl.ds(coarse, RW)]
                    return acc + pltpu.roll(v, -fine, 1)[:, :TQ]

                acc = chan(0, jnp.zeros((8, TQ), jnp.float32))
                for c in range(1, G):
                    acc = chan(c, acc)
                out_ref[pl.ds(d, 1), 0, kk] += acc[None]
                return 0

            jax.lax.fori_loop(jnp.int32(0), jnp.int32(dm_tile), d_body, 0)


@partial(
    jax.jit,
    static_argnames=(
        "nsamps", "out_nsamps", "window_slack", "dm_tile", "time_tile",
        "k_tiles", "chan_group", "interpret", "max_delay", "csub",
    ),
)
def dedisperse_pallas_flat_subband(
    parts,
    delays: jax.Array,
    nsamps: int,
    out_nsamps: int,
    *,
    csub: int,
    window_slack: int,
    max_delay: int,
    dm_tile: int = 8,
    time_tile: int = 15360,
    k_tiles: int = 4,
    chan_group: int = 16,
    interpret: bool = False,
) -> jax.Array:
    """Stage-1 sub-band partials over flat parts, one kernel launch.

    Returns (ndm, nsub, out_nsamps) f32 where sub-band ``s`` sums
    channels [s*csub, (s+1)*csub).  ``csub`` must be a multiple of
    ``2*chan_group`` and divide every part's channel count.  ``delays``
    is full-width (the ANCHOR rows' delays).  See
    :func:`_dedisperse_flat_sb_kernel` for why this exists.
    """
    with enable_x64(False):
        ndm, nchans = delays.shape
        if not isinstance(parts, (list, tuple)):
            parts = [parts]
        T, S, K = time_tile, window_slack, k_tiles
        TQ = _flat_checks(T, S)
        if csub % (2 * chan_group) or nchans % csub:
            raise ValueError(
                f"csub={csub} must be a multiple of 2*chan_group="
                f"{2 * chan_group} and divide nchans={nchans}"
            )
        nsub = nchans // csub
        dtype = parts[0].dtype
        align = 1024  # see the alignment note in dedisperse_pallas_flat
        if nsamps % align:
            raise ValueError(
                f"flat-part channel stride {nsamps} must be a multiple "
                f"of {align} (pad the tail)"
            )
        part_chans = []
        for p in parts:
            cp, rem = divmod(p.shape[0], nsamps)
            if rem or cp % csub:
                raise ValueError(
                    f"part length {p.shape[0]} must hold whole "
                    f"sub-bands (csub={csub}, stride {nsamps})"
                )
            part_chans.append(cp)
        if sum(part_chans) != nchans:
            raise ValueError("parts do not match delays' channel count")
        if out_nsamps < T:
            raise ValueError(
                f"input too short for the kernel window ({out_nsamps=} "
                f"< {T})")
        delays = delays.astype(jnp.int32)
        ndm_p = -(-ndm // dm_tile) * dm_tile
        TK = K * T
        out_p = -(-out_nsamps // TK) * TK
        njk = out_p // TK
        # mirror the kernel's window size: the last kk's A-aligned
        # per-tile slice rounds the window up past TK + S + A
        WL = -(-(T + S + align) // align) * align
        W1 = -(-((K - 1) * T + WL) // align) * align
        need = out_p - TK + max_delay + W1
        if nsamps < need:
            raise ValueError(
                f"flat parts hold {nsamps} samples per channel but the "
                f"sub-band kernel windows need {need}; pre-pad the data"
            )
        if ndm_p != ndm:
            delays = jnp.pad(delays, ((0, ndm_p - ndm), (0, 0)),
                             mode="edge")
        ntiles, ngroups = ndm_p // dm_tile, nchans // chan_group
        gmins = (
            delays.reshape(ntiles, dm_tile, ngroups, chan_group)
            .min(axis=(1, 3))
            .astype(jnp.int32)
        )
        WQ = TQ + S + align
        delays_blocked = dm_tile % 8 == 0 or ntiles == 1
        delays_spec = (
            pl.BlockSpec(
                (dm_tile, nchans), lambda i, s, j: (i, 0),
                memory_space=pltpu.SMEM,
            )
            if delays_blocked
            else pl.BlockSpec(memory_space=pltpu.SMEM)
        )
        out = pl.pallas_call(
            partial(
                _dedisperse_flat_sb_kernel,
                dm_tile=dm_tile, time_tile=T, k_tiles=K,
                chan_group=chan_group, slack=S,
                part_chans=tuple(part_chans), nsamps=nsamps,
                align=align, csub=csub, njk=njk,
                delays_blocked=delays_blocked,
            ),
            grid=(ntiles, nsub, njk),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.SMEM),  # gmins
                delays_spec,
            ] + [pl.BlockSpec(memory_space=pl.ANY)] * len(parts),
            out_specs=pl.BlockSpec(
                (dm_tile, 1, K, 8, TQ), lambda i, s, j: (i, s, j, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            out_shape=jax.ShapeDtypeStruct(
                (ndm_p, nsub, njk * K, 8, TQ), jnp.float32),
            scratch_shapes=[
                pltpu.VMEM((W1,), dtype)
                for _ in range(2 * chan_group)
            ] + [
                pltpu.VMEM((chan_group, 8, WQ), jnp.float32),
                pltpu.SemaphoreType.DMA((2, chan_group)),
            ],
            interpret=interpret,
        )(gmins, delays, *parts)
        return (out.reshape(ndm_p, nsub, out_p)
                [:ndm, :, :out_nsamps])


def dedisperse_flat_pad_to(out_nsamps: int, max_delay: int,
                           window_slack: int, time_tile: int) -> int:
    """Per-channel stride (samples, incl. padding) the flat kernel
    needs: every window DMA must stay in bounds and tile-aligned.
    The alignment is 1024 for EVERY dtype (f32 flat buffers tile at
    1024 in current Mosaic, same as u8) — the former ``uint8``
    parameter never changed the result and was removed (ADVICE round
    5) so callers cannot come to expect dtype-dependent padding.
    """
    align = 1024
    T, S = time_tile, window_slack
    out_p = -(-out_nsamps // T) * T
    W1 = -(-(T + S + align) // align) * align
    need = out_p - T + max_delay + W1
    return -(-need // align) * align


def _flat_checks(time_tile, window_slack):
    T, S = time_tile, window_slack
    TQ, rem = divmod(T, 8)
    if rem or TQ % 128 or (TQ + 128) & (TQ + 127):
        raise ValueError(
            f"time_tile must be 8*TQ with TQ+128 a power of two (got "
            f"{T}); e.g. 7168, 15360 or 31744"
        )
    if S % 128:
        raise ValueError(
            f"window_slack must be a multiple of 128 (got {S}); use "
            f"dedisperse_window_slack()"
        )
    return TQ


@partial(
    jax.jit,
    static_argnames=(
        "nsamps", "out_nsamps", "window_slack", "dm_tile", "time_tile",
        "chan_group", "interpret", "max_delay", "chan_range",
        "data_tail_ok",
    ),
)
def dedisperse_pallas_flat(
    parts,
    delays: jax.Array,
    nsamps: int,
    out_nsamps: int,
    *,
    window_slack: int,
    max_delay: int,
    dm_tile: int = 32,
    time_tile: int = 15360,
    chan_group: int = 16,
    interpret: bool = False,
    chan_range: tuple[int, int] | None = None,
    data_tail_ok: bool = False,
) -> jax.Array:
    """Dedisperse FLAT channel-major part arrays with the tiled kernel.

    The hot-path entry: ``parts`` is the :func:`split_flat_channels`
    -style list of 1-D u8/f32 arrays (whole channels each, every part's
    channel count a multiple of ``chan_group``), exactly as the chunked
    driver keeps the filterbank in HBM — no 2-D operand exists, so the
    column-major u8 entry-layout relayout that disabled the original
    kernel cannot occur.

    Requirements (all checked): ``nsamps`` (the per-channel stride,
    INCLUDING caller padding) is a multiple of 128 so every channel
    starts lane-aligned; each channel has
    ``ceil(out_nsamps/T)*T + max_delay + slack + 128`` valid samples
    (the caller pre-pads; in-program padding of flat parts would
    relayout-copy them).

    ``chan_range``: optional static (lo, hi) channel bounds — sum only
    those channels.  Both bounds must be multiples of
    ``2 * chan_group`` (pairwise double buffering); ``delays`` stays
    full-width, indexed by global channel.  (Sub-band stage 1 uses the
    dedicated :func:`dedisperse_pallas_flat_subband` kernel instead —
    one launch per sub-band through this entry costs ~0.15 s of fixed
    overhead per chunk.)
    """
    with enable_x64(False):
        ndm, nchans = delays.shape
        if not isinstance(parts, (list, tuple)):
            parts = [parts]
        T, S = time_tile, window_slack
        TQ = _flat_checks(T, S)
        dtype = parts[0].dtype
        # DMA slice starts and lengths must be multiples of the 1-D
        # HBM memref tiling.  u8 memrefs tile at (1024,); f32 USED to
        # tile at (256,) but the current Mosaic assigns (1024,) to
        # in-program f32 flat buffers (observed r5: the sub-band
        # stage-2 partials failed to compile with 256-aligned
        # windows), so 1024 everywhere — a stricter alignment is
        # always safe
        align = 1024
        if nsamps % align:
            raise ValueError(
                f"flat-part channel stride {nsamps} must be a multiple "
                f"of {align} (pad the tail) for tile-aligned window DMAs"
            )
        part_chans = []
        used = 0
        for p in parts:
            cp, rem = divmod(p.shape[0], nsamps)
            if rem:
                raise ValueError(
                    f"part length {p.shape[0]} is not a multiple of the "
                    f"channel stride {nsamps}"
                )
            # data_tail_ok: the part may hold EXTRA trailing strides
            # that only the delay table reaches into (the sub-band
            # stage-2-as-dedispersion call sweeps nsub "channels" of a
            # flat (n_anchor, nsub, L1) partials buffer whose anchor
            # offset rides in the delays); the sweep itself covers
            # exactly nchans channels either way
            take = min(cp, nchans - used) if data_tail_ok else cp
            if take % (2 * chan_group):
                raise ValueError(
                    f"part channel count {take} not a multiple of "
                    f"2*{chan_group=} (pairwise static double "
                    f"buffering); use split_flat_channels(..., "
                    f"align={2 * chan_group})"
                )
            part_chans.append(take)
            used += take
        if used != nchans:
            raise ValueError(
                f"parts hold {used} channels, delays expect {nchans}"
            )
        if out_nsamps < T:
            raise ValueError(
                f"input too short for the kernel window ({out_nsamps=} "
                f"< {T}); use the XLA scan path"
            )
        delays = delays.astype(jnp.int32)
        ndm_p = -(-ndm // dm_tile) * dm_tile
        out_p = -(-out_nsamps // T) * T
        nj = out_p // T
        W1 = -(-(T + S + align) // align) * align
        need = out_p - T + max_delay + W1
        if nsamps < need:
            raise ValueError(
                f"flat parts hold {nsamps} samples per channel but the "
                f"kernel windows need {need}; pre-pad the data "
                f"(use dedisperse_flat_pad_to())"
            )
        if ndm_p != ndm:
            delays = jnp.pad(delays, ((0, ndm_p - ndm), (0, 0)),
                             mode="edge")
        ntiles, ngroups = ndm_p // dm_tile, nchans // chan_group
        group_range = None
        if chan_range is not None:
            c_lo, c_hi = chan_range
            if (c_lo % (2 * chan_group) or c_hi % (2 * chan_group)
                    or not 0 <= c_lo < c_hi <= nchans):
                raise ValueError(
                    f"chan_range {chan_range} must be 2*chan_group"
                    f"(={2 * chan_group})-aligned within [0, {nchans})"
                )
            group_range = (c_lo // chan_group, c_hi // chan_group)
        gmins = (
            delays.reshape(ntiles, dm_tile, ngroups, chan_group)
            .min(axis=(1, 3))
            .astype(jnp.int32)
        )
        WQ = TQ + S + align
        delays_blocked = dm_tile % 8 == 0 or ntiles == 1
        delays_spec = (
            pl.BlockSpec(
                (dm_tile, nchans), lambda i, j: (i, 0),
                memory_space=pltpu.SMEM,
            )
            if delays_blocked
            else pl.BlockSpec(memory_space=pltpu.SMEM)
        )
        out = pl.pallas_call(
            partial(
                _dedisperse_flat_kernel,
                dm_tile=dm_tile, time_tile=T, chan_group=chan_group,
                slack=S, part_chans=tuple(part_chans), nsamps=nsamps,
                delays_blocked=delays_blocked, align=align,
                group_range=group_range,
            ),
            grid=(ntiles, nj),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.SMEM),  # gmins
                delays_spec,
            ] + [pl.BlockSpec(memory_space=pl.ANY)] * len(parts),
            out_specs=pl.BlockSpec(
                (dm_tile, 1, 8, TQ), lambda i, j: (i, j, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            out_shape=jax.ShapeDtypeStruct((ndm_p, nj, 8, TQ),
                                           jnp.float32),
            scratch_shapes=[
                pltpu.VMEM((W1,), dtype)
                for _ in range(2 * chan_group)
            ] + [
                pltpu.VMEM((chan_group, 8, WQ), jnp.float32),
                pltpu.SemaphoreType.DMA((2, chan_group)),
            ],
            interpret=interpret,
        )(gmins, delays, *parts)
        return out.reshape(ndm_p, out_p)[:ndm, :out_nsamps]


@partial(
    jax.jit,
    static_argnames=(
        "out_nsamps", "window_slack", "dm_tile", "time_tile",
        "chan_group", "interpret", "max_delay",
    ),
)
def dedisperse_pallas(
    data: jax.Array,
    delays: jax.Array,
    out_nsamps: int,
    *,
    window_slack: int,
    dm_tile: int = 32,
    time_tile: int = 15360,
    chan_group: int = 16,
    interpret: bool = False,
    max_delay: int | None = None,
) -> jax.Array:
    """Dedisperse with the tiled VMEM-accumulator kernel.

    Args:
        data: (nchans, nsamps) float32 or uint8, channel-major, already
            killmask-multiplied (and possibly tail-padded by the
            caller).
        delays: (ndm, nchans) int32 sample delays.
        out_nsamps: output samples per trial.
        window_slack: static per-(tile, group) delay spread bound from
            :func:`dedisperse_window_slack` (must be computed from the
            same dm_tile/chan_group).
        time_tile: samples per grid step; time_tile/8 + 128 must be a
            power of two (7168, 15360, 31744, ...).
        interpret: run the interpreter (CPU tests).
        max_delay: true maximum delay (the dedisp contract bound,
            `dedisperser.hpp:100-101`).  Pass it whenever ``data`` is
            already tail-padded — inferring it as nsamps - out_nsamps
            from a padded array over-pads AGAIN inside the jitted
            program, i.e. a full HBM copy of the input on every call.

    Returns:
        (ndm, out_nsamps) float32.
    """
    with enable_x64(False):
        return _dedisperse_pallas_impl(
            data, delays, out_nsamps, window_slack, dm_tile, time_tile,
            chan_group, interpret, max_delay,
        )


def _dedisperse_pallas_impl(
    data, delays, out_nsamps, window_slack, dm_tile, time_tile,
    chan_group, interpret, max_delay=None,
):
    ndm, nchans = delays.shape
    nsamps = data.shape[1]
    if nchans % chan_group:
        raise ValueError(f"{nchans=} not a multiple of {chan_group=}")
    T, S = time_tile, window_slack
    TQ, rem = divmod(T, 8)
    # tpu.dynamic_rotate silently produces WRONG results for vector
    # widths that are not a power of two (verified empirically on v5e:
    # 8192/16384 exact, 8320/4224/3840 corrupt) — the kernel's fine
    # shift rolls (8, TQ + 128) chunks, so TQ + 128 must be a power of
    # two (and TQ a lane multiple, for the aligned sublane DMA starts)
    if rem or TQ % 128 or (TQ + 128) & (TQ + 127):
        raise ValueError(
            f"time_tile must be 8*TQ with TQ+128 a power of two (got "
            f"{T}); e.g. 7168, 15360 or 31744"
        )
    # the coarse/fine decomposition bounds coarse by S only when S is a
    # lane multiple; a hand-computed slack like 64 would let the coarse
    # read run past the DMA'd window and sum stale VMEM into the output
    if S % 128:
        raise ValueError(
            f"window_slack must be a multiple of 128 (got {S}); use "
            f"dedisperse_window_slack()"
        )
    if out_nsamps < T:
        raise ValueError(
            f"input too short for the kernel window ({out_nsamps=} < "
            f"{T}); use the XLA scan path"
        )
    delays = delays.astype(jnp.int32)
    ndm_p = -(-ndm // dm_tile) * dm_tile
    out_p = -(-out_nsamps // T) * T
    nj = out_p // T
    # every sublane window [astart + s*TQ, astart + s*TQ + WQ) must be
    # in bounds without clamping (clamping would shift valid offsets).
    # The worst window end is (out_p - T) + max_delay + T + S + 128.
    # The chunked driver bakes this padding into its device-resident
    # buffer (and passes the true max_delay), so the pad here is a
    # no-op on its hot path.
    if max_delay is None:
        max_delay = nsamps - out_nsamps  # the dedisp contract bound
    need = out_p + max_delay + S + 128
    if nsamps < need:
        data = jnp.pad(data, ((0, 0), (0, need - nsamps)))
        nsamps = need
    if ndm_p != ndm:
        delays = jnp.pad(delays, ((0, ndm_p - ndm), (0, 0)), mode="edge")

    ntiles, ngroups = ndm_p // dm_tile, nchans // chan_group
    gmins = (
        delays.reshape(ntiles, dm_tile, ngroups, chan_group)
        .min(axis=(1, 3))
        .astype(jnp.int32)
    )
    WQ = TQ + S + 128
    grid = (ntiles, nj)
    # delays live in SMEM: the kernel only ever reads them as scalars,
    # and scalar reads from VMEM lower to (1,1) vector loads whose
    # dynamic lane index Mosaic cannot prove aligned.  SMEM blocks must
    # still satisfy the (8, 128)-divisible-or-full rule, so small
    # dm_tiles ship the whole table instead (it is tiny in that case).
    delays_blocked = dm_tile % 8 == 0 or ntiles == 1
    delays_spec = (
        pl.BlockSpec(
            (dm_tile, nchans), lambda i, j: (i, 0),
            memory_space=pltpu.SMEM,
        )
        if delays_blocked
        else pl.BlockSpec(memory_space=pltpu.SMEM)
    )
    out = pl.pallas_call(
        partial(
            _dedisperse_kernel,
            dm_tile=dm_tile, time_tile=T, chan_group=chan_group,
            slack=S, nchans=nchans, delays_blocked=delays_blocked,
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # gmins: whole array
            delays_spec,
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(
            (dm_tile, 1, 8, TQ), lambda i, j: (i, j, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((ndm_p, nj, 8, TQ), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((2, 8, chan_group, WQ), data.dtype),
            pltpu.VMEM((chan_group, 8, WQ), jnp.float32),
            pltpu.SemaphoreType.DMA((2, 8)),
        ],
        interpret=interpret,
    )(gmins, delays, data)
    return out.reshape(ndm_p, out_p)[:ndm, :out_nsamps]
