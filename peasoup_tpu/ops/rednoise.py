"""Red-noise running-median estimation and dereddening.

Reference semantics: the Heimdall-style median-scrunch-by-5 cascade and
linear stretch (`src/kernels.cu:875-1011`) spliced at two boundary
frequencies (`include/transforms/dereddener.hpp:40-62`), then complex
division of the Fourier series by the median curve with bins 0-4 zeroed
(`src/kernels.cu:1013-1034`).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp


def median_scrunch5(x: jnp.ndarray) -> jnp.ndarray:
    """Median of each consecutive group of 5; truncates the remainder.

    For inputs shorter than 5 the reference returns a single value
    (median / mean-of-middle pair), `src/kernels.cu:947-981`.
    """
    n = x.shape[0]
    if n >= _LANE_SCRUNCH_MIN:
        return _median_scrunch5_lanes(x)
    if n >= 5:
        groups = x[: (n // 5) * 5].reshape(-1, 5)
        return jnp.sort(groups, axis=1)[:, 2]
    if n == 1:
        return x[:1]
    if n == 2:
        return jnp.mean(x, keepdims=True)
    s = jnp.sort(x)
    if n == 3:
        return s[1:2]
    return jnp.mean(s[1:3], keepdims=True)  # n == 4


# above this input length the lane-aligned path replaces the
# (n//5, 5) reshape+sort: a minor dim of 5 pads 25.6x to the 128-lane
# tile on TPU (~3 GB of HLO temp per 2^23-size whiten when vmapped)
_LANE_SCRUNCH_MIN = 1 << 19


def _median_scrunch5_lanes(x: jnp.ndarray) -> jnp.ndarray:
    """Lane-aligned median-scrunch-by-5.

    Views the output as (R, 128) rows; out[r, l] needs x[640r + 5l + c]
    for c in 0..4 — max offset 5*127 + 4 = 639, so row r's inputs are
    exactly the contiguous 640-wide window starting at 640r: ONE free
    reshape, then five STATIC lane selections, each a one-hot
    (640, 128) matmul (exact under Precision.HIGHEST, as in
    ops/harmonics.py).  The median itself is a 9-exchange sorting
    network of elementwise min/max — identical values to a sort, with
    no lane-hostile (n//5, 5) intermediate.
    """
    n5 = x.shape[0] // 5
    R = -(-n5 // 128)
    pad_len = R * 640
    xp = jnp.pad(x, (0, max(0, pad_len - x.shape[0])))
    W = xp[: R * 640].reshape(R, 640)
    l = np.arange(128)
    cols = []
    for c in range(5):
        M = np.zeros((640, 128), np.float32)
        M[5 * l + c, l] = 1.0
        cols.append(jnp.matmul(
            W, jnp.asarray(M), precision=jax.lax.Precision.HIGHEST))
    v = cols
    # optimal 5-element sorting network; median = 3rd smallest
    def cx(i, j):
        lo = jnp.minimum(v[i], v[j])
        hi = jnp.maximum(v[i], v[j])
        v[i], v[j] = lo, hi

    for i, j in ((0, 1), (3, 4), (2, 4), (2, 3), (0, 3), (0, 2),
                 (1, 4), (1, 3), (1, 2)):
        cx(i, j)
    return v[2].reshape(-1)[:n5]


def linear_stretch(x: jnp.ndarray, out_count: int) -> jnp.ndarray:
    """Linear-interpolation stretch to ``out_count`` points.

    Matches `src/kernels.cu:983-1011`: float32 step arithmetic, and the
    interpolation term is dropped when the fractional part is <= 1e-5.
    """
    # the lanes path's window-start product f32(rb*B) * step is exact
    # only while rb*B < 2^24; beyond that (fft size > 2^25) fall back
    # to the gather path, whose f32 semantics are the reference's own
    if (_LANE_STRETCH_MIN <= out_count < 1 << 24
            and out_count > x.shape[0]):
        return _linear_stretch_lanes(x, out_count)
    in_count = x.shape[0]
    step = jnp.float32(in_count - 1) / jnp.float32(out_count - 1)
    xi = jnp.arange(out_count, dtype=jnp.float32) * step
    j = xi.astype(jnp.int32)
    frac = xi - j.astype(jnp.float32)
    # gather base and next from DIFFERENT operands: gathering x[j] and
    # x[j+1] from the same array lets XLA fuse them into one
    # (out_count, 2) gather whose minor dim pads 64x to the 128-lane
    # tile — 2 GB of HBM temp per 2^23-size whiten on v5e
    x_next = jnp.concatenate([x[1:], x[-1:]])
    nxt = x_next[j]
    base = x[j]
    return jnp.where(frac > 1e-5, base + frac * (nxt - base), base)


# above this output length the windowed-select path replaces the full
# gather (a 4.2M-element gather costs ~120 ms on v5e vs ~6 ms windowed)
_LANE_STRETCH_MIN = 1 << 19


def _linear_stretch_lanes(x: jnp.ndarray, out_count: int,
                          B: int = 640) -> jnp.ndarray:
    """Upsample-stretch without a full-size gather.

    Each block of ``B`` outputs reads a contiguous source window of
    ``ceil(B*step) + 3`` elements (the index map is monotone with
    slope < 1), fetched with one per-block dynamic slice; the
    within-window offset is applied by a select chain.  The index and
    fraction arithmetic is the IDENTICAL f32 expression as the gather
    path, so results are bit-equal; window starts reuse the same
    ``f32(rb*B) * step`` product (exact: rb*B < 2^24).
    """
    in_count = x.shape[0]
    step_py = (in_count - 1) / (out_count - 1)
    Rb = -(-out_count // B)
    Wlen = int(np.ceil(B * step_py)) + 3
    step = jnp.float32(in_count - 1) / jnp.float32(out_count - 1)
    xi = jnp.arange(Rb * B, dtype=jnp.float32) * step
    j = xi.astype(jnp.int32)
    frac = (xi - j.astype(jnp.float32)).reshape(Rb, B)
    s = ((jnp.arange(Rb, dtype=jnp.float32) * np.float32(B)) * step
         ).astype(jnp.int32)
    need = int((Rb * B - 1) * step_py) + Wlen + 3
    xp = jnp.pad(x, (0, max(0, need - in_count)), mode="edge")
    W = jax.vmap(
        lambda st: jax.lax.dynamic_slice(xp, (st,), (Wlen + 1,)))(s)
    o = j.reshape(Rb, B) - s[:, None]
    base = jnp.zeros((Rb, B), x.dtype)
    nxt = jnp.zeros((Rb, B), x.dtype)
    for c in range(Wlen):
        hit = o == c
        base = jnp.where(hit, W[:, c:c + 1], base)
        nxt = jnp.where(hit, W[:, c + 1:c + 2], nxt)
    out = jnp.where(frac > 1e-5, base + frac * (nxt - base), base)
    return out.reshape(-1)[:out_count]


def running_median(
    powers: jnp.ndarray,
    bin_width: float,
    boundary_5_freq: float = 0.05,
    boundary_25_freq: float = 0.5,
) -> jnp.ndarray:
    """Three-level scrunch5 cascade spliced at the boundary frequencies.

    Below ``boundary_5_freq`` the (stretched) scrunch-by-5 median is
    used, below ``boundary_25_freq`` the scrunch-by-25, above it the
    scrunch-by-125 (`dereddener.hpp:40-62`).
    """
    size = powers.shape[0]
    pos5 = int(boundary_5_freq / bin_width)
    pos25 = int(boundary_25_freq / bin_width)
    m5 = median_scrunch5(powers)
    m25 = median_scrunch5(m5)
    m125 = median_scrunch5(m25)
    s5 = linear_stretch(m5, size)
    s25 = linear_stretch(m25, size)
    s125 = linear_stretch(m125, size)
    idx = jnp.arange(size)
    return jnp.where(idx < pos5, s5, jnp.where(idx < pos25, s25, s125))


def deredden(fseries: jnp.ndarray, median: jnp.ndarray) -> jnp.ndarray:
    """Divide the complex series by the real median; zero bins 0-4."""
    out = fseries / median.astype(fseries.real.dtype)
    idx = jnp.arange(fseries.shape[0])
    return jnp.where(idx < 5, jnp.zeros((), dtype=fseries.dtype), out)
