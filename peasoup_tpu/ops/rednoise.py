"""Red-noise running-median estimation and dereddening.

Reference semantics: the Heimdall-style median-scrunch-by-5 cascade and
linear stretch (`src/kernels.cu:875-1011`) spliced at two boundary
frequencies (`include/transforms/dereddener.hpp:40-62`), then complex
division of the Fourier series by the median curve with bins 0-4 zeroed
(`src/kernels.cu:1013-1034`).
"""

from __future__ import annotations

import jax.numpy as jnp


def median_scrunch5(x: jnp.ndarray) -> jnp.ndarray:
    """Median of each consecutive group of 5; truncates the remainder.

    For inputs shorter than 5 the reference returns a single value
    (median / mean-of-middle pair), `src/kernels.cu:947-981`.
    """
    n = x.shape[0]
    if n >= 5:
        groups = x[: (n // 5) * 5].reshape(-1, 5)
        return jnp.sort(groups, axis=1)[:, 2]
    if n == 1:
        return x[:1]
    if n == 2:
        return jnp.mean(x, keepdims=True)
    s = jnp.sort(x)
    if n == 3:
        return s[1:2]
    return jnp.mean(s[1:3], keepdims=True)  # n == 4


def linear_stretch(x: jnp.ndarray, out_count: int) -> jnp.ndarray:
    """Linear-interpolation stretch to ``out_count`` points.

    Matches `src/kernels.cu:983-1011`: float32 step arithmetic, and the
    interpolation term is dropped when the fractional part is <= 1e-5.
    """
    in_count = x.shape[0]
    step = jnp.float32(in_count - 1) / jnp.float32(out_count - 1)
    xi = jnp.arange(out_count, dtype=jnp.float32) * step
    j = xi.astype(jnp.int32)
    frac = xi - j.astype(jnp.float32)
    nxt = x[jnp.minimum(j + 1, in_count - 1)]
    base = x[j]
    return jnp.where(frac > 1e-5, base + frac * (nxt - base), base)


def running_median(
    powers: jnp.ndarray,
    bin_width: float,
    boundary_5_freq: float = 0.05,
    boundary_25_freq: float = 0.5,
) -> jnp.ndarray:
    """Three-level scrunch5 cascade spliced at the boundary frequencies.

    Below ``boundary_5_freq`` the (stretched) scrunch-by-5 median is
    used, below ``boundary_25_freq`` the scrunch-by-25, above it the
    scrunch-by-125 (`dereddener.hpp:40-62`).
    """
    size = powers.shape[0]
    pos5 = int(boundary_5_freq / bin_width)
    pos25 = int(boundary_25_freq / bin_width)
    m5 = median_scrunch5(powers)
    m25 = median_scrunch5(m5)
    m125 = median_scrunch5(m25)
    s5 = linear_stretch(m5, size)
    s25 = linear_stretch(m25, size)
    s125 = linear_stretch(m125, size)
    idx = jnp.arange(size)
    return jnp.where(idx < pos5, s5, jnp.where(idx < pos25, s25, s125))


def deredden(fseries: jnp.ndarray, median: jnp.ndarray) -> jnp.ndarray:
    """Divide the complex series by the real median; zero bins 0-4."""
    out = fseries / median.astype(fseries.real.dtype)
    idx = jnp.arange(fseries.shape[0])
    return jnp.where(idx < 5, jnp.zeros((), dtype=fseries.dtype), out)
