"""Multibeam coincidence masking.

Reference semantics: `src/kernels.cu:1073-1100` (per-bin count of beams
whose value exceeds ``thresh``; mask bin = 1 if count < beam_thresh,
else 0) and `include/transforms/coincidencer.hpp:42-78` (sample-mask
and birdie-list writers).  The per-bin beam loop becomes a batched
reduction over the beam axis.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp


def coincidence_mask(arrays: jnp.ndarray, thresh, beam_thresh) -> jnp.ndarray:
    """0/1 mask over bins: 0 where >= ``beam_thresh`` beams exceed
    ``thresh`` (multibeam RFI), 1 elsewhere.

    Args:
        arrays: (nbeams, size) float32.
    """
    count = jnp.sum(arrays > thresh, axis=0)
    return (count < beam_thresh).astype(jnp.float32)


def birdie_list_from_mask(mask: np.ndarray, bin_width: float) -> np.ndarray:
    """Collapse zero-runs of a spectral mask into (freq, width) birdies.

    Matches `coincidencer.hpp:53-72`: a run of ``count`` zeroed bins
    ending (exclusive) at ``end`` becomes freq = ((end-1) - count/2) *
    bin_width, width = count * bin_width.  (The reference's inner scan
    reads one element past the array when a run touches the end —
    REFERENCE-QUIRK(coincidencer.hpp:64-67) — we stop at the boundary.)

    Returns an (nbirdies, 2) float array.
    """
    mask = np.asarray(mask)
    zero = mask == 0
    if not zero.any():
        return np.zeros((0, 2), np.float64)
    # run-length encode the zero regions
    padded = np.diff(np.concatenate([[0], zero.view(np.int8), [0]]))
    starts = np.nonzero(padded == 1)[0]
    ends = np.nonzero(padded == -1)[0]  # exclusive
    counts = ends - starts
    freqs = ((ends - 1) - counts / 2.0) * bin_width
    widths = counts * bin_width
    return np.stack([freqs, widths], axis=1)


def write_samp_mask(mask: np.ndarray, filename: str) -> None:
    """One 0/1 line per sample, '#0 1' header (`coincidencer.hpp:42-51`)."""
    with open(filename, "w") as f:
        f.write("#0 1\n")
        for v in np.asarray(mask):
            f.write(f"{int(v)}\n")


def write_birdie_list(
    mask: np.ndarray, bin_width: float, filename: str
) -> None:
    """'freq<TAB>width' per birdie (`coincidencer.hpp:73-77`)."""
    birdies = birdie_list_from_mask(mask, bin_width)
    with open(filename, "w") as f:
        for freq, width in birdies:
            f.write(f"{freq:.9f}\t{width:.6f}\n")
