"""Array statistics and normalisation.

Reference semantics: Thrust reductions `src/kernels.cu:420-494` wrapped
by `include/utils/stats.hpp`: mean = sum/n, rms = sqrt(sumsq/n),
std = sqrt(rms^2 - mean^2); normalise maps x -> (x - mean) / sigma.
"""

from __future__ import annotations

import jax.numpy as jnp


def mean_rms_std(x: jnp.ndarray, min_bin: int = 0):
    n = x.shape[0] - min_bin
    xs = x[min_bin:]
    mean = jnp.sum(xs) / n
    rms = jnp.sqrt(jnp.sum(xs * xs) / n)
    std = jnp.sqrt(rms * rms - mean * mean)
    return mean.astype(jnp.float32), rms.astype(jnp.float32), std.astype(jnp.float32)


def normalise(x: jnp.ndarray, mean, sigma) -> jnp.ndarray:
    return ((x - mean) / sigma).astype(jnp.float32)
