"""Array statistics and normalisation.

Reference semantics: Thrust reductions `src/kernels.cu:420-494` wrapped
by `include/utils/stats.hpp`: mean = sum/n, rms = sqrt(sumsq/n),
std = sqrt(rms^2 - mean^2); normalise maps x -> (x - mean) / sigma.
"""

from __future__ import annotations

import jax.numpy as jnp


def mean_rms_std(x: jnp.ndarray, min_bin: int = 0):
    n = x.shape[0] - min_bin
    xs = x[min_bin:]
    mean = jnp.sum(xs) / n
    rms = jnp.sqrt(jnp.sum(xs * xs) / n)
    std = jnp.sqrt(rms * rms - mean * mean)
    return mean.astype(jnp.float32), rms.astype(jnp.float32), std.astype(jnp.float32)


def normalise(x: jnp.ndarray, mean, sigma) -> jnp.ndarray:
    return ((x - mean) / sigma).astype(jnp.float32)


def normalise_spectrum(
    x: jnp.ndarray, sigma: float | None = None, min_bin: int = 0
) -> jnp.ndarray:
    """Legacy divide-by-sigma normalisation
    (`src/kernels.cu:499-522`, unused by the shipped reference binary):
    sigma is computed from the spectrum's own mean/rms above ``min_bin``
    when not supplied, and every bin is divided by it (no mean
    subtraction)."""
    if sigma is None:
        _, _, sigma = mean_rms_std(x, min_bin)
    return (x / sigma).astype(jnp.float32)


def transpose(block: jnp.ndarray) -> jnp.ndarray:
    """2-D transpose (`include/transforms/transpose.hpp:30-263`, the
    tiled Barsdell kernel, unused by the shipped pipelines).  On TPU a
    plain ``jnp.transpose`` lowers to XLA's native layout swap; the
    hand-tiled shared-memory scheme has no equivalent to port."""
    return jnp.transpose(block)
