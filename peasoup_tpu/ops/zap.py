"""Birdie (interference tone) zapping of Fourier series.

Reference semantics: `src/kernels.cu:1036-1069` via
`include/transforms/birdiezapper.hpp:11-73`: for each (freq, width)
pair, bins in [floor((f-w)/bw), ceil((f+w)/bw)) are replaced by 1+0i,
with the low edge clamped to 0 and the high edge to size-1.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp


def load_zaplist(path: str) -> np.ndarray:
    """Parse a "freq_hz width_hz" sidecar file -> (n, 2) float32."""
    rows = []
    with open(path) as f:
        for line in f:
            parts = line.split()
            if parts:
                rows.append((float(parts[0]), float(parts[1])))
    return np.array(rows, dtype=np.float32).reshape(-1, 2)


def zap_birdies(
    fseries: jnp.ndarray,
    birdies: jnp.ndarray,
    widths: jnp.ndarray,
    bin_width: float,
) -> jnp.ndarray:
    """Zap birdie bins to 1+0i.

    Implemented as a scatter of +/-1 interval deltas followed by a
    cumulative sum (interval stabbing) — collective- and fusion-friendly
    on TPU, unlike the per-birdie loop kernel of the reference.
    """
    size = fseries.shape[0]
    bw = jnp.float32(bin_width)
    low = jnp.floor((birdies - widths) / bw).astype(jnp.int32)
    high = jnp.ceil((birdies + widths) / bw).astype(jnp.int32)
    valid = low < size
    low = jnp.clip(low, 0, size)
    high = jnp.minimum(high, size - 1)
    high = jnp.maximum(high, low)  # empty interval when high <= low
    delta = jnp.zeros((size + 1,), dtype=jnp.int32)
    delta = delta.at[jnp.where(valid, low, size)].add(1)
    delta = delta.at[jnp.where(valid, high, size)].add(-1)
    mask = jnp.cumsum(delta[:-1]) > 0
    one = jnp.ones((), dtype=fseries.dtype)
    return jnp.where(mask, one, fseries)
