"""Phase folding and Fourier-domain fold optimisation.

Reference semantics:

* ``fold_time_series`` — `src/kernels.cu:597-651`: nsubints x nbins
  profile; sample j lands in phase bin floor(frac(j*tsamp/period)*nbins)
  of subint j // (nsamps//nsubints); each bin's accumulator is divided
  by (count+1) (the reference initialises its counter to 1).
* ``optimise_fold`` — `include/transforms/folder.hpp:65-335` +
  `src/kernels.cu:655-865`: FFT the subints along phase, apply nshifts
  per-subint linear phase ramps (a period-derivative search), collapse
  subints, multiply by FFT'd boxcar templates of every width / sqrt(w),
  inverse FFT, and take the argmax over (template, shift, bin).  The
  S/N of the optimised profile is computed on-host from on/off-pulse
  statistics (`folder.hpp:140-183`), and the optimised period is
  ``p * (((32 - opt_shift) * p) / (nbins * tobs) + 1)`` — the hardcoded
  32 ( = nbins/2 only when nbins=64) is reproduced as-is and flagged
  here: REFERENCE-QUIRK(folder.hpp:330).

Deviation: jnp's normalised ifft replaces cuFFT's unnormalised inverse;
every consumer (argmax, on/off-pulse S/N) is scale-invariant.  Negative
profile-rotation indices use true modulo where the reference's C ``%``
would read out of bounds (UB) — REFERENCE-QUIRK(folder.hpp:153-155).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import numpy as np
import jax
import jax.numpy as jnp


def phase_bins(nsamps: int, period, tsamp, nbins: int) -> jnp.ndarray:
    """Per-sample phase-bin assignment, matching the reference's
    ``__double2int_rd(modf(jj * (tsamp/period)) * nbins)``
    (`src/kernels.cu:621-627`, f64 with the precomputed tsamp/period)."""
    j = jnp.arange(nsamps, dtype=jnp.float64)  # psl: disable=PSL003 -- reference-exact f64 phase math (__double2int_rd)
    tbp = jnp.asarray(tsamp, jnp.float64) / jnp.asarray(period, jnp.float64)  # psl: disable=PSL003 -- reference-exact f64 phase math
    phase = j * tbp
    frac = phase - jnp.floor(phase)
    return jnp.floor(frac * nbins).astype(jnp.int32)


def fold_time_series_core(
    tim: jnp.ndarray, period, tsamp, nbins: int = 64, nints: int = 16
) -> jnp.ndarray:
    """Fold a time series into an (nints, nbins) sub-integration profile.

    On TPU the scatter-add is reformulated as a one-hot matmul: each
    sub-integration is a CONTIGUOUS block of ``nper`` samples, so the
    (used -> nints*nbins) scatter is block-diagonal and becomes a
    batched (nints, nper) x (nints, nper, nbins) contraction — MXU
    work instead of a serialised scatter (measured on v5e at 2^17
    samples x 10 candidates with per-candidate periods: 0.17 ms vs
    23.3 ms for the vmapped segment_sum, the whole fold stage's
    dominant device cost).  The 0/1 one-hot is exact in one bf16 limb
    (DEFAULT precision); the data operand uses the 3-limb HIGHEST
    decomposition, so each product is exact and only the f32
    accumulation order differs from the sequential scatter (the
    reference's atomicAdd order is arbitrary too, `src/kernels.cu:
    597-651`)."""
    from .harmonics import _on_tpu

    nsamps = tim.shape[0]
    nper = nsamps // nints
    used = nper * nints
    binidx = phase_bins(used, period, tsamp, nbins)
    if _on_tpu():
        return _fold_onehot(tim[:used], binidx, nbins, nints)
    subint = (jnp.arange(used, dtype=jnp.int32) // nper).astype(jnp.int32)
    flat = subint * nbins + binidx
    sums = jax.ops.segment_sum(tim[:used], flat, num_segments=nints * nbins)
    counts = jax.ops.segment_sum(
        jnp.ones((used,), jnp.float32), flat, num_segments=nints * nbins
    )
    prof = sums / (counts + 1.0)  # reference counter starts at 1
    return prof.reshape(nints, nbins).astype(jnp.float32)


def _fold_onehot(tim, binidx, nbins: int, nints: int) -> jnp.ndarray:
    """One-hot matmul fold (the TPU branch of
    :func:`fold_time_series_core`); works on any backend."""
    nper = tim.shape[0] // nints
    bi = binidx.reshape(nints, nper)
    onehot = (
        bi[:, :, None] == jnp.arange(nbins, dtype=jnp.int32)
    ).astype(jnp.bfloat16)
    xm = tim.reshape(nints, nper).astype(jnp.float32)
    sel_prec = (jax.lax.Precision.HIGHEST, jax.lax.Precision.DEFAULT)
    sums = jnp.einsum(
        "ip,ipb->ib", xm, onehot, precision=sel_prec,
        preferred_element_type=jnp.float32,
    )
    counts = jnp.einsum(
        "ip,ipb->ib", jnp.ones_like(xm), onehot, precision=sel_prec,
        preferred_element_type=jnp.float32,
    )
    prof = sums / (counts + 1.0)  # reference counter starts at 1
    return prof.astype(jnp.float32)


fold_time_series = jax.jit(
    fold_time_series_core, static_argnames=("nbins", "nints")
)


def optimise_device(subints: jnp.ndarray):
    """Device part of the fold optimisation, optimum selected on device.

    Returns (argmax_flat, opt_fold (nints, nbins), opt_prof (nbins,)) —
    only the optimal shift's real subints/profile, so a batched caller
    ships home O(nbins*nints) per candidate instead of O(nbins^2*nints).
    """
    nints, nbins = subints.shape
    nshifts = nbins
    argmax, post_shift, profiles = _matched_filter(subints)
    opt_shift = (argmax // nbins) % nshifts
    opt_fold = jnp.real(jnp.fft.ifft(post_shift[opt_shift], axis=1))
    opt_prof = jnp.real(jnp.fft.ifft(profiles[opt_shift]))
    return argmax, opt_fold, opt_prof


def _matched_filter(subints: jnp.ndarray):
    """Shift x template matched filter over the FFT'd subints.

    Returns (argmax_flat, post_shift (s, m, b), profiles (s, b)).
    """
    nints, nbins = subints.shape
    nshifts = nbins
    ntemplates = nbins - 1
    fsub = jnp.fft.fft(subints.astype(jnp.complex64), axis=1)

    shifts = (jnp.arange(nshifts, dtype=jnp.float32) - nshifts // 2)
    m = jnp.arange(nints, dtype=jnp.float32)
    b = jnp.arange(nbins, dtype=jnp.float32)
    ramp = b * (2.0 * np.float32(np.pi)) / nbins
    ramp = jnp.where(b > nbins // 2, ramp - 2.0 * np.float32(np.pi), ramp)
    # shift amount per (s, m): (m/nints) * shifts[s]
    amount = (m[None, :] / nints) * shifts[:, None]  # (s, m)
    phase = -ramp[None, None, :] * amount[:, :, None]  # (s, m, b)
    shiftar = jnp.exp(1j * phase.astype(jnp.float32)).astype(jnp.complex64)

    post_shift = fsub[None, :, :] * shiftar  # (s, m, b)
    profiles = jnp.sum(post_shift, axis=1)  # (s, b)

    w = jnp.arange(ntemplates, dtype=jnp.int32)
    templates = (b[None, :].astype(jnp.int32) <= w[:, None]).astype(jnp.complex64)
    ftemp = jnp.fft.fft(templates, axis=1)  # (w, b)

    norm = jnp.sqrt(w.astype(jnp.float32) + 1.0)
    final = (
        profiles[None, :, :] * ftemp[:, None, :] / norm[:, None, None]
    )  # (w, s, b)
    final = final.at[:, :, 0].set(0.0)
    td = jnp.fft.ifft(final, axis=2)
    absarr = jnp.abs(td)
    argmax = jnp.argmax(absarr.reshape(-1))
    return argmax, post_shift, profiles


@jax.jit
def _optimise_core(subints: jnp.ndarray):
    """All-shifts variant (host selects the optimum); kept for the
    single-candidate ``optimise_fold`` path and its tests."""
    argmax, post_shift, profiles = _matched_filter(subints)
    opt_subints_all = jnp.real(jnp.fft.ifft(post_shift, axis=2))  # (s, m, b)
    opt_profiles_all = jnp.real(jnp.fft.ifft(profiles, axis=1))  # (s, b)
    return argmax, opt_subints_all, opt_profiles_all


def calculate_sn(prof: np.ndarray, bin_: int, width: int, nbins: int):
    """On/off-pulse S/N of a profile (`folder.hpp:140-183`)."""
    edge = int(width * 0.3 + 0.5)
    width_by_2 = int(width / 2.0 + 0.5)
    rprof = np.array([prof[(bin_ - nbins // 2 + ii) % nbins] for ii in range(nbins)])
    bin_ = nbins // 2 - 1
    upper_edge = bin_ + (width_by_2 + edge)
    lower_edge = bin_ - (width_by_2 + edge)
    sel = np.arange(nbins)
    on = rprof[(sel <= upper_edge) & (sel >= lower_edge)]
    off = rprof[(sel > upper_edge) | (sel < lower_edge)]
    on_mean = on.mean()
    off_mean = off.mean()
    off_std = np.sqrt(((off - off_mean) ** 2).mean())
    with np.errstate(divide="ignore", invalid="ignore"):
        sn1 = (on_mean - off_mean) * np.sqrt(width) / off_std
        sn2 = ((rprof - off_mean) / off_std).sum() / np.sqrt(width)
    if not np.isfinite(sn1) or sn1 > 99999:
        sn1 = 0.0
    if not np.isfinite(sn2) or sn2 > 99999:
        sn2 = 0.0
    return float(sn1), float(sn2)


@dataclass
class OptimisedFold:
    opt_sn: float
    opt_period: float
    opt_width: int
    opt_bin: int
    opt_prof: np.ndarray     # (nbins,)
    opt_fold: np.ndarray     # (nints, nbins)


def finalise_fold(
    argmax: int,
    opt_prof: np.ndarray,
    opt_fold: np.ndarray,
    period: float,
    tobs: float,
) -> OptimisedFold:
    """Host tail of the optimisation: S/N + optimised period from the
    device-selected optimum (`folder.hpp:308-332`)."""
    nbins = opt_prof.shape[0]
    nshifts = nbins
    opt_template = argmax // (nbins * nshifts)
    opt_bin = argmax % nbins - opt_template // 2
    opt_shift = (argmax // nbins) % nbins
    sn1, sn2 = calculate_sn(opt_prof, opt_bin, opt_template, nbins)
    # REFERENCE-QUIRK(folder.hpp:330): hardcoded 32 (nbins/2 for nbins=64)
    opt_period = period * ((((32.0 - opt_shift) * period) / (nbins * tobs)) + 1.0)
    return OptimisedFold(
        opt_sn=max(sn1, sn2),
        opt_period=float(opt_period),
        opt_width=opt_template + 1,
        opt_bin=int(opt_bin),
        opt_prof=opt_prof,
        opt_fold=opt_fold,
    )


def optimise_fold(subints: np.ndarray, period: float, tobs: float) -> OptimisedFold:
    """Full fold optimisation for one folded candidate."""
    nints, nbins = subints.shape
    argmax, opt_subints_all, opt_profiles_all = _optimise_core(
        jnp.asarray(subints, jnp.float32)
    )
    argmax = int(argmax)
    opt_shift = (argmax // nbins) % nbins
    opt_prof = np.asarray(opt_profiles_all)[opt_shift]
    opt_fold = np.asarray(opt_subints_all)[opt_shift]
    return finalise_fold(argmax, opt_prof, opt_fold, period, tobs)
