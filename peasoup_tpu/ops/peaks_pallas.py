"""Pallas threshold-compaction peak extraction (the no-sort path).

Reference semantics: `src/kernels.cu:384-416` — the CUDA build's peak
extraction is a Thrust ``copy_if`` of above-threshold bins in index
order, i.e. O(survivors), and never sorts.  The XLA lowerings this
kernel replaces (``lax.approx_max_k`` with ``recall_target=1.0`` and
``lax.top_k`` over index scores) are O(n log n) full sorts inside the
fused search program — ~64 ms of the tutorial search's ~100 ms device
time in the r5 trace (`benchmarks/trace_summary_r5.md`).

Kernel shape (the ISSUE-6 compaction plan):

1. **per-block masked count** — the grid walks the searched prefix in
   lane-aligned blocks; each step counts its qualifying bins
   (``start_idx <= i < stop_idx`` and ``value > thresh``) with one
   vector compare + reduce;
2. **exclusive prefix sum across blocks** — the TPU grid is sequential,
   so a single SMEM scratch scalar carries the running qualifying
   count: each block's scratch value on entry IS its exclusive prefix
   (no separate scan pass, no inter-kernel round trip);
3. **scatter** — only blocks that actually hold survivors (and whose
   prefix is still below ``capacity``) compute within-block ranks (a
   log2(block) shift-and-add inclusive scan — no sort) and materialise
   the qualifying (index, value) pairs into the fixed-capacity output
   via a lane-chunked one-hot select, plus the true-count scalar.

Blocks with no survivors cost one compare+reduce over streamed data —
the kernel is memory-bound O(n) + O(survivor_blocks * capacity)
compute, matching the reference's copy_if complexity class instead of
the sort's O(n log n).

Contract: exactly :func:`peasoup_tpu.ops.peaks.extract_above_threshold`
— the ``capacity`` smallest qualifying bin indices in ascending order,
-1 padding, values paired, and the TRUE qualifying count (which may
exceed ``capacity``; clipped rows are re-searched by every driver).

CPU/testing: compiled Mosaic execution needs a TPU; elsewhere the
kernel runs in interpret mode behind :func:`pallas_peaks_supported`, a
run-the-real-kernel-once capability probe in the same style as
``dedisperse_pallas.pallas_interpret_supported`` (which this kernel
deliberately does NOT reuse: that probe fails on jax 0.4.37 for the
dedispersion kernels' internal pjit/i64 boundary, a construct this
kernel avoids by keeping every scalar strictly int32).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

#: lane-aligned spectrum block per grid step.  8192 f32 lanes = 32 KB
#: per (double-buffered) load — big enough that per-step dispatch
#: overhead amortises (a 2^17-bin level is 16 steps), small enough
#: that the survivor scatter's transient one-hot tiles stay in VMEM.
DEFAULT_BLOCK = 8192

#: lane chunk of the survivor scatter: the one-hot select materialises
#: (capacity_padded, chunk) i32/f32 tiles, <= 2 MB at the sweep's
#: largest capacity (2048).  :func:`_scatter_chunk_for` narrows the
#: chunk for bigger capacities so the transient tiles stay within
#: :data:`_SCATTER_TILE_BYTES` of VMEM.
_SCATTER_CHUNK = 512

#: VMEM ceiling for one transient one-hot scatter tile (i32/f32).
_SCATTER_TILE_BYTES = 4 * 1024 * 1024

#: largest whole-buffer compaction capacity routed to this kernel by
#: the fused drivers (``parallel/mesh._compact_peaks``): at the
#: narrowest scatter chunk (128 lanes) an 8192-slot output keeps the
#: one-hot tile at 4 MB.  Tuned compact_k is rounded up in 8192 quanta
#: with 8192 as the floor, so the gate admits exactly the tuned
#: common case; bigger (untuned) buffers keep the XLA cumsum+scatter.
COMPACT_PALLAS_MAX_K = 8192


def _scatter_chunk_for(cap_p: int) -> int:
    """Widest power-of-two lane chunk (>= 128) whose one-hot tile fits
    :data:`_SCATTER_TILE_BYTES`."""
    chunk = _SCATTER_CHUNK
    while chunk > 128 and cap_p * chunk * 4 > _SCATTER_TILE_BYTES:
        chunk //= 2
    return chunk


def _inclusive_scan_lanes(x: jnp.ndarray, width: int) -> jnp.ndarray:
    """Inclusive prefix sum along the last (lane) axis of a (1, width)
    int32 array via log2(width) shift-and-adds — Mosaic has no native
    cumsum, and a triangular-matmul rank would cost O(width^2) VMEM."""
    shift = 1
    while shift < width:
        shifted = jnp.pad(x, ((0, 0), (shift, 0)))[:, :width]
        x = (x + shifted).astype(jnp.int32)
        shift *= 2
    return x


def _compact_kernel(
    spec_ref, idx_ref, snr_ref, cnt_ref, off_ref,
    *, block, cap_p, capacity, thresh, start_idx, stop_idx,
    scatter_chunk=_SCATTER_CHUNK,
):
    """One grid step = one spectrum block (see module docstring)."""
    bi = pl.program_id(0)

    @pl.when(bi == 0)
    def _init():
        idx_ref[...] = jnp.full_like(idx_ref, jnp.int32(-1))
        snr_ref[...] = jnp.zeros_like(snr_ref)
        cnt_ref[0, 0] = jnp.int32(0)
        off_ref[0] = jnp.int32(0)

    vals = spec_ref[...]  # (1, block) f32
    gidx = (
        jax.lax.broadcasted_iota(jnp.int32, (1, block), 1)
        + (bi * jnp.int32(block))
    ).astype(jnp.int32)
    mask = (
        (gidx >= jnp.int32(start_idx))
        & (gidx < jnp.int32(stop_idx))
        & (vals > jnp.float32(thresh))
    )
    blk_cnt = jnp.sum(mask.astype(jnp.int32)).astype(jnp.int32)
    base = off_ref[0]
    cnt_ref[0, 0] = (cnt_ref[0, 0] + blk_cnt).astype(jnp.int32)
    off_ref[0] = (base + blk_cnt).astype(jnp.int32)

    # survivors only, and only while the output still has open slots:
    # once `base >= capacity` every later qualifying bin is beyond the
    # k smallest — the block contributes nothing but its count
    @pl.when((blk_cnt > 0) & (base < jnp.int32(capacity)))
    def _scatter():
        # destination slot of each qualifying lane = exclusive global
        # prefix: block base + (within-block inclusive rank - 1)
        ranks = _inclusive_scan_lanes(mask.astype(jnp.int32), block)
        dest = jnp.where(
            mask, base + ranks - jnp.int32(1), jnp.int32(-1)
        ).astype(jnp.int32)
        slots = jax.lax.broadcasted_iota(jnp.int32, (cap_p, 1), 0)
        open_slot = slots < jnp.int32(capacity)
        for c0 in range(0, block, scatter_chunk):
            d = dest[:, c0 : c0 + scatter_chunk]  # (1, CHUNK)

            @pl.when(jnp.any(d >= jnp.int32(0)))
            def _chunk(d=d, c0=c0):
                onehot = (d == slots) & open_slot  # (cap_p, CHUNK)
                filled = jnp.any(onehot, axis=1, keepdims=True)
                gi = jnp.sum(
                    jnp.where(onehot, gidx[:, c0 : c0 + scatter_chunk],
                              jnp.int32(0)),
                    axis=1, keepdims=True, dtype=jnp.int32)
                gv = jnp.sum(
                    jnp.where(onehot, vals[:, c0 : c0 + scatter_chunk],
                              jnp.float32(0.0)),
                    axis=1, keepdims=True)
                idx_ref[...] = jnp.where(
                    filled.T, gi.T, idx_ref[...]).astype(jnp.int32)
                snr_ref[...] = jnp.where(filled.T, gv.T, snr_ref[...])


@partial(
    jax.jit,
    static_argnames=(
        "thresh", "start_idx", "stop_idx", "capacity", "block",
        "interpret",
    ),
)
def extract_above_threshold_pallas(
    spectrum: jnp.ndarray,
    thresh,
    start_idx: int,
    stop_idx: int,
    capacity: int,
    *,
    block: int = DEFAULT_BLOCK,
    interpret: bool = False,
):
    """Threshold-compaction peak extraction of ``[start_idx, stop_idx)``.

    Returns (idxs, snrs, count) under the exact
    ``extract_above_threshold`` contract: the ``capacity`` smallest
    qualifying bin indices in ascending order (padded with -1), their
    values, and the true qualifying count (may exceed ``capacity``).

    Safe under ``jax.vmap`` (the hot paths vmap the extraction over
    accel-trial batches): the batch lands as an extra leading grid
    axis, the block axis stays innermost/sequential, and the SMEM
    running-offset scratch resets at block 0 of every spectrum —
    covered by the vmap parity test in ``tests/test_ops.py``.
    """
    size = spectrum.shape[0]
    stop_idx = min(int(stop_idx), size)
    start_idx = min(int(start_idx), stop_idx)
    k_eff = min(int(capacity), stop_idx)
    if stop_idx == 0 or k_eff == 0:
        return (
            jnp.full((capacity,), -1, jnp.int32),
            jnp.zeros((capacity,), jnp.float32),
            jnp.int32(0),
        )
    nblocks = -(-stop_idx // block)
    pad = nblocks * block - stop_idx
    spec = spectrum[:stop_idx].astype(jnp.float32)
    if pad:
        # padding bins fail the gidx < stop_idx mask whatever they hold
        spec = jnp.pad(spec, (0, pad))
    cap_p = -(-k_eff // 128) * 128  # lane-pad the output buffers
    idxs, snrs, cnt = pl.pallas_call(
        partial(
            _compact_kernel,
            block=block, cap_p=cap_p, capacity=k_eff,
            thresh=float(thresh), start_idx=start_idx, stop_idx=stop_idx,
            scatter_chunk=min(_scatter_chunk_for(cap_p), block),
        ),
        grid=(nblocks,),
        in_specs=[
            pl.BlockSpec((1, block), lambda i: (0, i)),
        ],
        out_specs=[
            pl.BlockSpec((1, cap_p), lambda i: (0, 0)),
            pl.BlockSpec((1, cap_p), lambda i: (0, 0)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, cap_p), jnp.int32),
            jax.ShapeDtypeStruct((1, cap_p), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.int32),
        ],
        scratch_shapes=[pltpu.SMEM((1,), jnp.int32)],
        interpret=interpret,
    )(spec.reshape(1, nblocks * block))
    idxs = idxs.reshape(-1)[:k_eff]
    snrs = snrs.reshape(-1)[:k_eff]
    count = cnt.reshape(())
    if k_eff < capacity:
        idxs = jnp.pad(idxs, (0, capacity - k_eff), constant_values=-1)
        snrs = jnp.pad(snrs, (0, capacity - k_eff))
    return idxs, snrs, count


def compact_valid_slots_pallas(flat_idx, flat_val, compact_k: int,
                               *, interpret: bool = False):
    """Whole-buffer stream compaction on the threshold kernel: the
    first ``compact_k`` VALID (``idx >= 0``) slots of a flat peak
    buffer, in slot order — the drop-in device-side replacement for
    ``parallel/mesh._compact_peaks``'s cumsum+scatter lowering.

    Validity IS a threshold test: run the kernel on the slot buffer
    cast to f32 with ``thresh=-0.5`` (any non-negative int32 casts to
    ``>= 0.0``; the -1 sentinel to exactly -1.0, so rounding at large
    indices cannot flip the predicate) and it returns the ``compact_k``
    smallest valid SLOT POSITIONS in ascending order — precisely the
    slots the cumsum+scatter keeps (both retain the first ``compact_k``
    valid entries in flat order; the scatter drops the overflow via
    ``mode="drop"``, the kernel by its capacity gate) — plus the TRUE
    valid count.  The (index, value) payload is then an exact int32/f32
    gather at those positions, so the result is bit-identical to the
    XLA path (tests/test_ops.py asserts this on random buffers).

    Returns ``(sel_idx, sel_val, nvalid)`` shaped ``(compact_k,)``,
    ``(compact_k,)``, scalar — -1/0.0 padding beyond ``nvalid``.
    """
    n = flat_idx.shape[0]
    slots, _, nvalid = extract_above_threshold_pallas(
        flat_idx.astype(jnp.float32), -0.5, 0, n, int(compact_k),
        interpret=interpret,
    )
    ok = slots >= 0
    at = jnp.clip(slots, 0, n - 1)
    sel_idx = jnp.where(ok, flat_idx[at],
                        jnp.asarray(-1, flat_idx.dtype))
    sel_val = jnp.where(ok, flat_val[at].astype(jnp.float32),
                        jnp.float32(0.0))
    return sel_idx, sel_val, nvalid


_peaks_probe: tuple[bool, str] | None = None


def pallas_peaks_supported() -> tuple[bool, str]:
    """Capability probe: can this process run the compaction kernel?

    On TPU the compiled Mosaic path is assumed good (it is exercised by
    the hardware benchmark gate); elsewhere the REAL kernel runs once
    in interpret mode at a tiny shape and the (ok, reason) verdict is
    cached for the process — the same probe design as
    ``dedisperse_pallas.pallas_interpret_supported``, but independent
    of it: that probe's jax-0.4.37 failure is specific to the
    dedispersion wrappers' internal pjit/i64 boundary, which this
    kernel does not have.  Tests gate on the ``peaks_pallas_interpret``
    fixture (``tests/conftest.py``) so broken interpret builds skip
    with the reason instead of failing.
    """
    global _peaks_probe
    if _peaks_probe is None:
        try:
            if jax.devices()[0].platform == "tpu":
                _peaks_probe = (True, "compiled")
                return _peaks_probe
        except Exception:
            pass
        try:
            from jax.core import trace_state_clean
        except ImportError:  # moved in newer jax; default to probing
            def trace_state_clean():
                return True
        if not trace_state_clean():
            # first call arrived from INSIDE another program's trace
            # (the drivers warm the probe eagerly, but a direct
            # method="pallas" extract under a user jit can get here):
            # the probe's concrete fetch cannot run mid-trace, so
            # attempt the kernel inline without caching a verdict
            return (True, "interpret-unprobed")
        try:
            import numpy as np

            spec = np.zeros(512, np.float32)
            spec[[3, 200, 450]] = 5.0
            i, s, c = extract_above_threshold_pallas(
                jnp.asarray(spec), 1.0, 0, 512, 8, block=256,
                interpret=True,
            )
            i, c = np.asarray(i), int(c)
            if c != 3 or list(i[:3]) != [3, 200, 450]:
                raise AssertionError(
                    f"probe mismatch: count={c} idxs={i[:4]}")
            _peaks_probe = (True, "interpret")
        except Exception as exc:  # noqa: BLE001 - reported via skip
            _peaks_probe = (
                False, f"{type(exc).__name__}: {str(exc).splitlines()[0]}")
    return _peaks_probe


def pallas_peaks_interpret() -> bool:
    """True when the kernel must run in interpret mode (non-TPU)."""
    try:
        return jax.devices()[0].platform != "tpu"
    except Exception:
        return True
