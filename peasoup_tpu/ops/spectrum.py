"""Power-spectrum forming (plain and interbinned).

Reference semantics: `src/kernels.cu:215-252` via
`include/transforms/spectrumformer.hpp:6-24`.
"""

from __future__ import annotations

import jax.numpy as jnp


def form_power(fseries: jnp.ndarray) -> jnp.ndarray:
    """Plain amplitude spectrum: sqrt(re^2 + im^2).

    (The reference computes ``z * rsqrtf(z)`` which is sqrt(z) except it
    produces NaN at exact zeros; we produce 0 there.)
    """
    z = jnp.real(fseries) ** 2 + jnp.imag(fseries) ** 2
    return jnp.sqrt(z).astype(jnp.float32)


def form_interpolated(fseries: jnp.ndarray) -> jnp.ndarray:
    """Interbinned spectrum: sqrt(max(|X_k|^2, 0.5*|X_k - X_{k-1}|^2)).

    Recovers scalloping loss for signals between Fourier bins
    (`src/kernels.cu:231-252`); X_{-1} is taken as 0.
    """
    re = jnp.real(fseries).astype(jnp.float32)
    im = jnp.imag(fseries).astype(jnp.float32)
    re_l = jnp.concatenate([jnp.zeros((1,), re.dtype), re[:-1]])
    im_l = jnp.concatenate([jnp.zeros((1,), im.dtype), im[:-1]])
    ampsq = re * re + im * im
    ampsq_diff = 0.5 * ((re - re_l) ** 2 + (im - im_l) ** 2)
    return jnp.sqrt(jnp.maximum(ampsq, ampsq_diff)).astype(jnp.float32)
