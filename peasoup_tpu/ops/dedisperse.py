"""Incoherent dedispersion over a DM-trial grid.

TPU-native replacement for the external ``dedisp`` CUDA library used by
the reference (`include/transforms/dedisperser.hpp:25-112`): the DM-grid
generation formula, per-channel dispersion-delay table and the
channel-sum sweep are re-implemented here, with the sweep expressed as
an XLA program (scan over channels of per-DM dynamic slices) instead of
a CUDA kernel.

Differences from the reference, by design:

* output trials are float32, not the uint8 that ``dedisp_execute`` is
  asked for (`dedisperser.hpp:104-112`) — the TPU path has no reason to
  re-quantise and downstream normalisation is scale-invariant.
  Measured on the tutorial goldens (r5): the f32 trials reproduce the
  reference's folded S/N to <= 0.5% on all ten candidates, so the
  quantisation never was the parity limiter.  An opt-in dedisp-style
  uint8 lattice exists (:func:`quantise_trials_u8`,
  ``SearchConfig.trial_nbits=8``) for sensitivity studies; its floor
  jitter measurably flips which near-tie DM row the distiller keeps —
  the same flips the reference's own lattice baked into its goldens —
  so it is NOT a route to tighter golden parity;
* multi-device parallelism shards the DM axis of the *same* jitted
  program over a ``jax.sharding.Mesh`` (see ``peasoup_tpu.parallel``)
  rather than an internal multi-GPU plan.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

# dedisp uses 4.15e3 MHz^2 pc^-1 cm^3 s for its delay table ("to higher
# precision, 4.148741601e3"); keeping its value preserves the delay
# quantisation and hence trial-level parity.
DM_CONST_S = 4.15e3


def generate_dm_list(
    dm_start: float,
    dm_end: float,
    dt: float,
    ti: float,
    f0: float,
    df: float,
    nchans: int,
    tol: float,
) -> np.ndarray:
    """Generate the tolerance-stepped DM trial grid.

    Same recurrence as ``dedisp_generate_dm_list`` (reached via
    `dedisperser.hpp:54-62`): each step keeps the total smearing
    (intra-channel DM smear, sample time, pulse width ``ti`` in us)
    within ``tol`` of optimal.  Arithmetic in float64 with float32
    storage, mirroring the reference (observable in the golden 59-trial
    list of example_output/overview.xml).
    """
    dt_us = dt * 1e6
    f_ghz = (f0 + ((nchans / 2) - 0.5) * df) * 1e-3
    tol2 = tol * tol
    a = 8.3 * df / (f_ghz ** 3)
    a2 = a * a
    b2 = a2 * float(nchans) ** 2 / 16.0
    c = (dt_us * dt_us + ti * ti) * (tol2 - 1.0)

    dms = [np.float32(dm_start)]
    while dms[-1] < dm_end:
        prev = float(dms[-1])
        prev2 = prev * prev
        k = c + tol2 * a2 * prev2
        dm = (b2 * prev + np.sqrt(-a2 * b2 * prev2 + (b2 + a2) * k)) / (b2 + a2)
        dms.append(np.float32(dm))
    return np.array(dms, dtype=np.float32)


def delay_table(nchans: int, dt: float, f0: float, df: float) -> np.ndarray:
    """Per-channel delay in samples per DM unit (float32, like dedisp)."""
    f = (np.float32(f0) + np.arange(nchans, dtype=np.float32) * np.float32(df))
    a = np.float32(1.0) / f
    b = np.float32(1.0) / np.float32(f0)
    return (np.float32(DM_CONST_S / dt) * (a * a - b * b)).astype(np.float32)


def delays_in_samples(dm_list: np.ndarray, table: np.ndarray) -> np.ndarray:
    """Integer sample delays, round-half-up like dedisp's kernel."""
    frac = np.float32(dm_list)[:, None] * np.float32(table)[None, :]
    return np.floor(frac + 0.5).astype(np.int32)


def max_delay(dm_list: np.ndarray, table: np.ndarray) -> int:
    """``dedisp_get_max_delay``: delay of the last channel at the top DM
    (``max`` rather than ``[-1]`` so user-supplied unsorted DM lists,
    `dedisperser.hpp:34-48`, get a correct bound; identical for the
    generated ascending grid)."""
    return int(np.float32(np.max(dm_list)) * np.float32(table[-1]) + 0.5)


def dedisperse(
    data: jax.Array,
    delays: jax.Array,
    out_nsamps: int,
    killmask: jax.Array | None = None,
) -> jax.Array:
    """Dedisperse a filterbank block over a grid of DM trials.

    Args:
        data: (nchans, nsamps) float32, channel-major (channel 0 = fch1).
        delays: (ndm, nchans) int32 sample delays.
        out_nsamps: output samples per trial (nsamps - max_delay).
        killmask: optional (nchans,) 0/1 float mask
            (`dedisperser.hpp:64-95`).

    Returns:
        (ndm, out_nsamps) float32 dedispersed time series.

    The sweep is a ``lax.scan`` over channels; each step adds a
    dynamically-shifted slice of one channel to every DM's accumulator.
    All shapes are static, so XLA fuses the slice+add chain into a
    bandwidth-bound loop with no host round trips.
    """
    ndm = delays.shape[0]
    if killmask is not None:
        data = data * killmask[:, None].astype(data.dtype)

    def chan_step(acc, inputs):
        col, d = inputs  # col: (nsamps,), d: (ndm,)
        sliced = jax.vmap(
            lambda di: lax.dynamic_slice(col, (di,), (out_nsamps,))
        )(d)
        # u8 input stays packed in HBM (34 GB as f32 at 4k chans x 2^23
        # samples); the cast rides the fused slice+add
        return acc + sliced.astype(jnp.float32), None

    # derive the zero init from ``delays`` so that under shard_map it
    # carries the same varying-manual-axes annotation as the scanned
    # slices (XLA folds the broadcast-of-zeros away)
    init = jnp.zeros((ndm, out_nsamps), dtype=jnp.float32) \
        + delays[:, :1].astype(jnp.float32) * 0.0
    out, _ = lax.scan(chan_step, init, (data, delays.T))
    return out


def quantise_trials_u8(trials: jax.Array, in_nbits: int,
                       nchans: int) -> jax.Array:
    """dedisp's ``out_nbits=8`` output quantisation, opt-in
    (``SearchConfig.trial_nbits=8``).

    `dedisperser.hpp:104-112`'s ``dedisp_execute(..., out_nbits=8)``
    hands every downstream consumer ``DispersionTrials<unsigned
    char>``.  This reconstructs libdedisp's output scaling —
    ``scaled = sum * out_range / (in_range * nchans)`` with
    ``in_range = 2^in_nbits - 1`` and ``out_range = 255``, clipped to
    [0, 255] and C-cast to unsigned char (truncation toward zero) —
    and returns the values as f32 (the search/fold chain is float).

    NOTE (measured, r5): this is a sensitivity-study mode, not a
    parity mode.  The default f32 sums already reproduce the
    reference's folded S/N to <= 0.5% on every tutorial golden; the
    floor jitter of ANY u8 lattice perturbs near-tie DM associations
    in the distiller (ours and the reference's alike), so quantising
    moves output *away* from the published goldens.
    """
    in_range = float((1 << in_nbits) - 1)
    scaled = trials * jnp.float32(255.0 / (in_range * nchans))
    return jnp.floor(jnp.clip(scaled, 0.0, 255.0)).astype(jnp.float32)


def quantise_trials_bf16(trials: jax.Array) -> jax.Array:
    """bf16 trial lattice (ISSUE 13): round-trip the f32 trial sums
    through bfloat16 — 8 significand bits, f32's exponent range — and
    hand them back as f32 for the search/fold chain.

    Halves the lattice's HBM footprint and the dedisperse-write /
    spectrum-read bandwidth with NO dynamic-range surgery (unlike the
    u8 staircase, no dependence on the input's nbits or a channel-sum
    scale), at ~0.4% relative rounding error per sample.  Engaged only
    via ``SearchConfig.trial_lattice`` — an explicit force or a
    parity-validated tuner pick (search/tuning.py)."""
    return trials.astype(jnp.bfloat16).astype(jnp.float32)


# whole-channel pieces of the flat filterbank stay below this many
# elements so every dynamic_slice offset fits int32 (the TPU backend
# rejects 64-bit slice indices outright)
_FLAT_PART_LIMIT = (1 << 31) - 1


def flat_channel_parts(nchans: int, nsamps: int) -> int:
    """Channels per flat part: as many whole channels as fit in int32
    offsets."""
    return max(1, min(nchans, _FLAT_PART_LIMIT // max(nsamps, 1)))


def split_flat_channels(data: np.ndarray, align: int = 1):
    """Split a (nchans, nsamps) array into flat whole-channel parts for
    :func:`dedisperse_flat` (views, no copies).

    ``align`` rounds the channels-per-part down to a multiple (the
    Pallas kernel requires every part to hold whole channel GROUPS)."""
    nchans, nsamps = data.shape
    cpp = flat_channel_parts(nchans, nsamps)
    if align > 1:
        cpp = cpp // align * align
        if cpp == 0:
            # align channels would exceed the int32-offset part limit;
            # exceeding it silently would overflow slice offsets
            raise ValueError(
                f"cannot split {nchans} chans x {nsamps} samps into "
                f"{align}-channel-aligned parts under the int32 offset "
                f"limit; reduce chan_group or the padded sample count"
            )
    return [
        data[p : p + cpp].reshape(-1) for p in range(0, nchans, cpp)
    ]


def dedisperse_flat(
    parts,
    delays: jax.Array,
    nsamps: int,
    out_nsamps: int,
    chan_range: tuple[int, int] | None = None,
) -> jax.Array:
    """`dedisperse` over FLAT channel-major array parts.

    The production path keeps the filterbank 1-D on device: a 2-D u8
    entry parameter is assigned a column-major layout by XLA while
    in-program consumers want row-major tiled, and under shard_map even
    a reshape of the flat array materialises a full-size relayout copy
    (8 GB at 2^23 x 1024 chans).  Slicing each channel straight out of
    a flat array never forms a 2-D view, so no relayout exists.

    ``parts`` is a sequence of flat arrays each holding
    :func:`flat_channel_parts` whole channels: a single flat array
    would need 64-bit slice offsets past 2^31 elements (8.6e9 at
    1024 chans x 2^23 samples), which the TPU backend rejects — and
    int32 arithmetic would wrap, silently dedispersing garbage.
    Killmask handling is the caller's (the chunked driver pre-applies
    it host-side, matching `dedisperser.hpp:64-95`).

    ``chan_range``: optional static (lo, hi) — sum only channels
    [lo, hi) of the parts (sub-band stage-1 partials; ``delays`` stays
    full-width and is indexed by GLOBAL channel).
    """
    if not isinstance(parts, (list, tuple)):
        parts = [parts]
    ndm, nchans = delays.shape
    lo, hi = chan_range if chan_range is not None else (0, nchans)

    # static python loop over DM rows, NOT vmap: a vmap of
    # dynamic_slice lowers to a batched gather with arbitrary start
    # offsets, ~4x slower than ndm real dynamic slices on v5e (11.2 s
    # vs ~2.8 s for 9 rows at 2^23 x 1024 chans).  Only for small row
    # counts — the unrolled body grows the trace by ndm * unroll slice
    # ops, so large-ndm callers keep the single batched gather
    loop_rows = ndm <= 64

    def chan_step(flat_part, c0):
        def body(acc, c_local):
            col = lax.dynamic_slice(
                flat_part, (c_local * nsamps,), (nsamps,))
            d = lax.dynamic_slice(
                delays, (jnp.int32(0), c0 + c_local), (ndm, 1))[:, 0]
            if loop_rows:
                rows = [
                    lax.dynamic_slice(col, (d[i],), (out_nsamps,))
                    .astype(jnp.float32)
                    for i in range(ndm)
                ]
                sliced = jnp.stack(rows)
            else:
                sliced = jax.vmap(
                    lambda di: lax.dynamic_slice(col, (di,),
                                                 (out_nsamps,))
                )(d).astype(jnp.float32)
            return acc + sliced, None

        return body

    acc = jnp.zeros((ndm, out_nsamps), dtype=jnp.float32) \
        + delays[:, :1].astype(jnp.float32) * 0.0
    c_base = 0
    for flat_part in parts:
        nloc = flat_part.shape[0] // nsamps
        # this part's overlap with the requested channel range, in
        # part-local channel indices
        l_lo, l_hi = max(lo - c_base, 0), min(hi - c_base, nloc)
        if l_lo < l_hi:
            # unroll=8: XLA fuses the unrolled bodies' adds, touching
            # the (ndm, out_nsamps) f32 accumulator once per 8 channels
            # instead of every channel (2.4x at 1024 chans x 2^21)
            acc, _ = lax.scan(
                chan_step(flat_part, jnp.int32(c_base)), acc,
                jnp.arange(l_lo, l_hi, dtype=jnp.int32),
                unroll=8 if loop_rows else 1)
        c_base += nloc
    return acc


# --------------------------------------------------------------------------
# two-stage sub-band dedispersion (dedisp's internal algorithm class)
# --------------------------------------------------------------------------

def subband_plan(
    dm_list: np.ndarray,
    delays: np.ndarray,
    table: np.ndarray,
    nsub: int,
    eps: float = 0.5,
) -> dict:
    """Plan a two-stage sub-band dedispersion over a fine DM grid.

    The external ``dedisp`` library the reference links
    (`include/transforms/dedisperser.hpp:104-112`) internally uses a
    sub-band decomposition: channels are grouped into ``nsub``
    sub-bands, each dedispersed over a COARSE set of anchor DMs
    (stage 1), and every fine trial is then assembled from its
    anchor's partial sums with one integer shift per sub-band
    (stage 2).  Cost falls from ``ndm * nchans`` adds to
    ``ncoarse * nchans + ndm * nsub`` — a large win exactly when the
    fine grid is dense relative to the delay resolution (tolerance-
    stepped survey grids; a grid whose step already moves delays by
    many samples gains nothing and the plan says so via ``n_anchors``).

    Anchors are chosen greedily along the (ascending) DM list so that
    the residual intra-sub-band smearing ``(dm - dm_anchor) * spread``
    stays below ``eps`` samples; with delay rounding (+-0.5) the total
    per-channel delay error is bounded by ``eps + 1`` samples, and the
    exact bound for this plan is returned as ``max_err``.  ``eps=0``
    degenerates to anchors == trials (bit-identical to the direct sum
    for integer inputs).

    Returns a dict: ``bounds`` (per-sub-band channel ranges),
    ``anchors`` (fine-trial indices used as stage-1 DMs), ``assign``
    (per-trial anchor slot), ``shifts`` ((ndm, nsub) int32 stage-2
    shifts), ``shift_max``, ``max_err``, ``n_anchors``.
    """
    dm_list = np.asarray(dm_list, np.float64)
    ndm = len(dm_list)
    nchans = len(table)
    nsub = max(1, min(int(nsub), nchans))
    csub = -(-nchans // nsub)
    bounds = [
        (s * csub, min((s + 1) * csub, nchans))
        for s in range(nsub)
        if s * csub < nchans
    ]
    spread = max(float(table[hi - 1] - table[lo]) for lo, hi in bounds)
    ascending = bool(np.all(np.diff(dm_list) >= 0))
    anchors: list[int] = []
    assign = np.empty(ndm, np.int64)
    for i in range(ndm):
        if (not anchors or not ascending
                or (dm_list[i] - dm_list[anchors[-1]]) * spread > eps):
            anchors.append(i)
        assign[i] = len(anchors) - 1
    anchors_a = np.asarray(anchors, np.int64)
    ref = np.asarray([lo for lo, _hi in bounds])
    # stage-2 shift: trial-vs-anchor delay difference at each
    # sub-band's reference (first) channel; >= 0 on ascending grids
    shifts = (delays[:, ref] - delays[anchors_a][assign][:, ref]) \
        .astype(np.int32)
    # exact per-channel effective-delay error of THIS plan
    sub_of_chan = np.repeat(
        np.arange(len(bounds)), [hi - lo for lo, hi in bounds])
    eff = delays[anchors_a][assign] + shifts[:, sub_of_chan]
    err = int(np.abs(eff - delays).max()) if ndm else 0
    return dict(
        bounds=bounds, anchors=anchors_a, assign=assign, shifts=shifts,
        shift_max=int(shifts.max(initial=0)), max_err=err,
        n_anchors=len(anchors),
    )


def dedisperse_subband(
    data: jax.Array,
    delays: jax.Array,
    plan: dict,
    out_nsamps: int,
) -> jax.Array:
    """Two-stage sub-band dedispersion (see :func:`subband_plan`).

    Numerics: each output sample is a sum of the same ``nchans`` input
    samples as the direct sweep, except any channel whose effective
    delay differs (bounded by ``plan['max_err']`` samples — 0 when
    ``eps=0``).  Input is edge-padded by ``shift_max + 1`` samples so
    stage-1 windows never clamp (a clamped ``dynamic_slice`` would
    silently misalign whole rows).
    """
    ndm = delays.shape[0]
    bounds = plan["bounds"]
    anchors = np.asarray(plan["anchors"])
    assign = np.asarray(plan["assign"])
    shifts = np.asarray(plan["shifts"])
    L1 = out_nsamps + int(plan["shift_max"])
    pad_n = int(plan["shift_max"]) + 1
    data = jnp.pad(data, ((0, 0), (0, pad_n)), mode="edge")
    anchor_delays = np.asarray(delays)[anchors]

    # stage 1: per sub-band, dedisperse the anchor rows over its
    # channels only (the usual channel scan, L1-long windows)
    partials = []
    for s, (lo, hi) in enumerate(bounds):
        partials.append(
            dedisperse(data[lo:hi], jnp.asarray(anchor_delays[:, lo:hi]),
                       L1)
        )

    # stage 2: every fine trial sums one shifted window per sub-band
    # from its anchor's partials — n_anchors*nchans + ndm*nsub adds
    # total.  Unrolled slices for small ndm (vmap dynamic_slice lowers
    # to a slow batched gather, see dedisperse_flat), batched above.
    acc = jnp.zeros((ndm, out_nsamps), jnp.float32)
    for s in range(len(bounds)):
        flat = partials[s].reshape(-1)
        offs = assign * L1 + shifts[:, s].astype(np.int64)
        if ndm <= 64:
            rows = [
                lax.dynamic_slice(flat, (int(offs[i]),), (out_nsamps,))
                for i in range(ndm)
            ]
            acc = acc + jnp.stack(rows)
        else:
            acc = acc + jax.vmap(
                lambda o: lax.dynamic_slice(flat, (o,), (out_nsamps,))
            )(jnp.asarray(offs, jnp.int32))
    return acc


def subband_chunk_plan(
    dm_list: np.ndarray,
    delays: np.ndarray,
    table: np.ndarray,
    chunks,
    chan_align: int = 32,
    eps: float = 0.5,
    step_frac: float = 0.25,
) -> dict | None:
    """Per-chunk sub-band plan for the chunked mesh driver.

    The chunked driver dispatches ``dm_chunk`` adjacent fine rows per
    (chunk, shard) cell; anchors are chosen greedily WITHIN each cell
    (sharing never crosses a dispatch, so no partials are recomputed
    or carried between dispatches).  All cells are padded to one
    ``n_anchor_p`` so every dispatch compiles to the same program.

    Args:
        dm_list: (ndm_padded,) fine DM values (padded rows repeat the
            last real value).
        delays: (ndm_padded, nchans) int sample delays.
        chunks: iterable of row-index arrays, one per (chunk, shard)
            cell.
        chan_align: channel alignment of sub-band bounds — csub is
            ``~sqrt(nchans)`` rounded up to a multiple (the Pallas
            kernel's pairwise chan-group DMA needs 2*chan_group-aligned
            ranges).
        eps: stage-2 residual smearing floor in samples (see
            :func:`subband_plan`).  ``eps=0`` selects the exact mode:
            anchors compress only across identical-DM rows.
        step_frac: with ``eps > 0``, the per-row threshold is
            ``max(eps, step_frac * local_dm_step * full_band_spread)``
            — the residual sub-band smearing stays below
            ``step_frac`` of the smearing the DM grid's own step
            already accepts (a trial midway between adjacent grid DMs
            smears by half the step's full-band delay), which is how
            the reference's dedisp budgets its internal sub-band error
            against the grid tolerance.  This makes the
            trials-per-anchor compression roughly uniform
            (~``step_frac * nsub``) across the dense and sparse grid
            regions instead of collapsing to 1 at high DM.

    Returns None when infeasible (non-ascending DM list, or nchans not
    ``chan_align``-aligned); else a dict with static config (bounds,
    L1 shift_max, n_anchor_p, nsub, max_err, cost ratio) and per-cell
    arrays (anchor_rows, assign, shifts).
    """
    dm_list = np.asarray(dm_list, np.float64)
    delays = np.asarray(delays)
    nchans = delays.shape[1]
    if nchans % chan_align or np.any(np.diff(dm_list) < 0):
        return None
    # csub ~ sqrt(nchans), constrained to a chan_align multiple that
    # DIVIDES nchans (the one-launch stage-1 kernel needs uniform
    # sub-bands); chan_align itself always qualifies here
    target = np.sqrt(nchans)
    csub = min(
        (c for c in range(chan_align, nchans + 1, chan_align)
         if nchans % c == 0),
        key=lambda c: abs(c - target),
    )
    bounds = tuple(
        (lo, lo + csub) for lo in range(0, nchans, csub)
    )
    nsub = len(bounds)
    spread = max(float(table[hi - 1] - table[lo]) for lo, hi in bounds)
    spread_full = float(np.max(table) - np.min(table))
    ref = np.asarray([lo for lo, _hi in bounds])
    cells = []
    n_anchor_p = 1
    shift_max = 0
    max_err = 0
    total_anchors = 0
    total_rows = 0
    for rows in chunks:
        rows = np.asarray(rows)
        anchors: list[int] = []
        assign = np.empty(len(rows), np.int64)
        for j, r in enumerate(rows):
            thr = eps
            if eps > 0 and j > 0:
                step = dm_list[r] - dm_list[rows[j - 1]]
                thr = max(eps, step_frac * step * spread_full)
            if (not anchors
                    or (dm_list[r] - dm_list[anchors[-1]]) * spread > thr):
                anchors.append(int(r))
            assign[j] = len(anchors) - 1
        anchors_a = np.asarray(anchors, np.int64)
        shifts = (delays[rows][:, ref]
                  - delays[anchors_a][assign][:, ref]).astype(np.int32)
        if shifts.min(initial=0) < 0:
            return None  # defensive: rounding made a shift negative
        sub_of_chan = np.repeat(
            np.arange(nsub), [hi - lo for lo, hi in bounds])
        eff = delays[anchors_a][assign] + shifts[:, sub_of_chan]
        max_err = max(max_err,
                      int(np.abs(eff - delays[rows]).max(initial=0)))
        shift_max = max(shift_max, int(shifts.max(initial=0)))
        n_anchor_p = max(n_anchor_p, len(anchors))
        total_anchors += len(anchors)
        total_rows += len(rows)
        cells.append((anchors_a, assign.astype(np.int32), shifts))
    # pad every cell's anchor set to n_anchor_p (repeat last: wasted
    # stage-1 rows, never wrong)
    per_cell = []
    for anchors_a, assign, shifts in cells:
        pad = np.pad(anchors_a, (0, n_anchor_p - len(anchors_a)),
                     mode="edge").astype(np.int32)
        per_cell.append((pad, assign, shifts))
    # stage-1 channel sweeps + stage-2 window adds vs the direct sweep
    cost_ratio = (
        (total_anchors * nchans + total_rows * nsub)
        / max(total_rows * nchans, 1)
    )
    return dict(
        bounds=bounds, nsub=nsub, shift_max=shift_max,
        n_anchor_p=n_anchor_p, max_err=max_err, cost_ratio=cost_ratio,
        per_cell=per_cell,
    )


def subband_stage2_layout(per_cell, L1: int, dm_tile2: int = 8):
    """Anchor-aligned padded row layout for the stage-2-as-dedispersion
    trick.

    Stage 2 (each fine row = nsub shifted windows from its anchor's
    partials) IS a dedispersion over a synthetic nsub-channel
    "filterbank" (the flat (n_anchor, nsub, L1) partials) with delays
    ``assign * nsub * L1 + shift`` — so the battle-tested direct
    Pallas kernel runs it in ONE launch instead of ndm*nsub XLA
    dynamic slices (measured ~0.19 s/chunk, the dominant sub-band
    cost).  The kernel's window machinery shares one DMA window per
    (dm_tile, chan_group) block, so rows are PADDED per anchor to
    ``dm_tile2`` multiples: no tile straddles two anchors and the
    static window slack stays at the (small) shift spread instead of
    the (huge) anchor stride.

    Args: ``per_cell`` from :func:`subband_chunk_plan`; ``L1`` the
    (padded) stage-1 row length the synthetic delays stride over.

    Returns (R2, cells2) where cells2[i] = (delays2 (R2, nsub) int32,
    unpad (len(rows),) int32): the synthetic per-row delay table and
    the padded-slot index of each original row.
    """
    lens = []
    for _anchor_rows, assign, _shifts in per_cell:
        n_anchor = int(assign.max()) + 1 if len(assign) else 1
        lens.append(sum(
            -(-int((assign == a).sum()) // dm_tile2) * dm_tile2
            for a in range(n_anchor)
        ))
    R2 = max(lens)
    cells2 = []
    for _anchor_rows, assign, shifts in per_cell:
        nsub = shifts.shape[1]
        n_anchor = int(assign.max()) + 1 if len(assign) else 1
        assign2 = np.zeros(R2, np.int32)
        shifts2 = np.zeros((R2, nsub), np.int32)
        unpad = np.zeros(len(assign), np.int32)
        pos = 0
        for a in range(n_anchor):
            idx = np.flatnonzero(assign == a)
            na = len(idx)
            pad_a = -(-na // dm_tile2) * dm_tile2
            assign2[pos : pos + pad_a] = a
            # padded slots repeat the segment's first row (never wrong)
            src = np.concatenate([idx, np.repeat(idx[:1], pad_a - na)])
            shifts2[pos : pos + pad_a] = shifts[src]
            unpad[idx] = pos + np.arange(na)
            pos += pad_a
        # tail slots: repeat the last anchor (whole tiles, same anchor)
        if pos < R2:
            assign2[pos:] = assign2[pos - 1]
            shifts2[pos:] = shifts2[pos - 1]
        delays2 = (assign2[:, None].astype(np.int64) * (nsub * L1)
                   + shifts2).astype(np.int32)
        cells2.append((delays2, unpad))
    return R2, cells2


def dedisperse_subband_flat(
    anchor_delays: jax.Array,
    assign: jax.Array,
    shifts: jax.Array,
    out_nsamps: int,
    *,
    bounds: tuple,
    L1: int,
    stage1,
) -> jax.Array:
    """Two-stage sub-band dedispersion over FLAT parts (hot path).

    The chunked mesh driver's sub-band mode: stage 1 dedisperses the
    chunk's ``n_anchor_p`` anchor rows per sub-band (``stage1`` is a
    caller-supplied closure ``(chan_range, anchor_delays) -> partials
    (n_anchor_p, L1)`` selecting the Pallas kernel or the XLA scan over
    the resident flat parts), and stage 2 assembles each fine trial
    from one shifted window per sub-band.  Sub-bands are processed
    sequentially so at most ONE partial is live alongside the
    accumulator (peak extra HBM = n_anchor_p * L1 * 4 bytes).

    ``stage1`` is either a one-shot callable ``(anchor_delays) ->
    (n_anchor_p, nsub, L1)`` computing EVERY sub-band's partials in a
    single kernel launch (the Pallas ``subband_slots`` mode — a launch
    per sub-band costs ~0.15 s of fixed overhead per chunk, more than
    the stage-1 sweep itself), or a per-band ``((lo, hi),
    anchor_delays) -> (n_anchor_p, L1)`` callable (the CPU scan
    fallback, where launch overhead is irrelevant); the two are told
    apart by parameter count.

    Args:
        anchor_delays: (n_anchor_p, nchans) int32 (full-width).
        assign: (ndm_c,) int32 — local anchor slot per fine row.
        shifts: (ndm_c, nsub) int32 stage-2 shifts, all in
            [0, L1 - out_nsamps] (host-validated).
        bounds: static per-sub-band (lo, hi) channel ranges.
        L1: static stage-1 length = out_nsamps + shift_max.
    """
    import inspect

    ndm_c = assign.shape[0]
    nsub = len(bounds)
    acc = jnp.zeros((ndm_c, out_nsamps), jnp.float32)
    one_shot = len(inspect.signature(stage1).parameters) == 1

    def add_band(acc, s, flat):
        offs = assign * jnp.int32(L1) + shifts[:, s]
        if ndm_c <= 64:
            rows = [
                lax.dynamic_slice(flat, (offs[i],), (out_nsamps,))
                for i in range(ndm_c)
            ]
            return acc + jnp.stack(rows)
        return acc + jax.vmap(
            lambda o: lax.dynamic_slice(flat, (o,), (out_nsamps,))
        )(offs)

    if one_shot:
        partials = stage1(anchor_delays)  # (n_anchor_p, nsub, L1)
        for s in range(nsub):
            acc = add_band(acc, s, partials[:, s].reshape(-1))
    else:
        for s, (lo, hi) in enumerate(bounds):
            part = stage1((lo, hi), anchor_delays)  # (n_anchor_p, L1)
            acc = add_band(acc, s, part.reshape(-1))
    return acc


def dedisperse_subband_numpy(
    data: np.ndarray,
    delays: np.ndarray,
    plan: dict,
    out_nsamps: int,
) -> np.ndarray:
    """NumPy model of :func:`dedisperse_subband` (for tests)."""
    ndm = delays.shape[0]
    pad_n = int(plan["shift_max"]) + 1
    data = np.pad(data.astype(np.float32), ((0, 0), (0, pad_n)),
                  mode="edge")
    L1 = out_nsamps + int(plan["shift_max"])
    anchors = plan["anchors"]
    out = np.zeros((ndm, out_nsamps), np.float32)
    for s, (lo, hi) in enumerate(plan["bounds"]):
        part = np.zeros((len(anchors), L1), np.float32)
        for c in range(lo, hi):
            for j, a in enumerate(anchors):
                d = delays[a, c]
                part[j] += data[c, d : d + L1]
        for i in range(ndm):
            o = plan["shifts"][i, s]
            out[i] += part[plan["assign"][i], o : o + out_nsamps]
    return out


def dedisperse_numpy(
    data: np.ndarray,
    delays: np.ndarray,
    out_nsamps: int,
    killmask: np.ndarray | None = None,
) -> np.ndarray:
    """NumPy reference implementation (for tests)."""
    ndm, nchans = delays.shape
    out = np.zeros((ndm, out_nsamps), dtype=np.float32)
    for c in range(nchans):
        col = data[c].astype(np.float32)
        if killmask is not None and not killmask[c]:
            continue
        for i in range(ndm):
            d = delays[i, c]
            out[i] += col[d : d + out_nsamps]
    return out
