"""Incremental harmonic summing.

Reference semantics: `src/kernels.cu:33-99`.  Level k (1-based) adds the
spectrum sampled at stretched indices ``(int)(i * m/2^k + 0.5)`` for the
odd numerators m of 2^k, accumulating on the previous level, and stores
``val / sqrt(2^k)``.  Up to 5 levels (2, 4, 8, 16, 32 summed harmonics).

The reference evaluates ``i * m/2^k + 0.5`` in float64; here the index
is computed with exact integer arithmetic — ``(i*m + 2^(k-1)) >> k`` is
identical to ``floor(i * m/2^k + 0.5)`` for all i — avoiding float64 on
TPU entirely.
"""

from __future__ import annotations

import jax.numpy as jnp

_SCALES = [
    0.7071067811865476,  # 1/sqrt(2)
    0.5,
    0.35355339059327373,  # 1/sqrt(8)
    0.25,
    0.17677669529663687,  # 1/sqrt(32)
]


def harmonic_sums(spectrum: jnp.ndarray, nharms: int) -> list[jnp.ndarray]:
    """Return ``nharms`` stretched-and-summed spectra (levels 1..nharms).

    ``spectrum`` is the (normalised, interbinned) power spectrum; output
    level k sums 2^k harmonics and is scaled by 1/sqrt(2^k).
    """
    if not 1 <= nharms <= 5:
        raise ValueError("nharms must be in 1..5")
    size = spectrum.shape[0]
    i = jnp.arange(size, dtype=jnp.int32)
    out = []
    val = spectrum
    for k in range(1, nharms + 1):
        denom_log2 = k
        half = 1 << (k - 1)
        for m in range(1, 1 << k, 2):  # odd numerators: the new harmonics
            idx = (i * m + half) >> denom_log2
            val = val + spectrum[jnp.clip(idx, 0, size - 1)]
        out.append((val * jnp.float32(_SCALES[k - 1])).astype(jnp.float32))
    return out
