"""Incremental harmonic summing.

Reference semantics: `src/kernels.cu:33-99`.  Level k (1-based) adds the
spectrum sampled at stretched indices ``(int)(i * m/2^k + 0.5)`` for the
odd numerators m of 2^k, accumulating on the previous level, and stores
``val / sqrt(2^k)``.  Up to 5 levels (2, 4, 8, 16, 32 summed harmonics).

The reference evaluates ``i * m/2^k + 0.5`` in float64; here the index
is ``(i*m + 2^(k-1)) >> k`` — identical to ``floor(i*m/2^k + 0.5)`` for
all i — avoiding float64 on TPU entirely.

TPU formulation (lane-aligned stretch)
--------------------------------------

A naive ``spectrum[idx]`` per (level, m) is 15 full-size random gathers
(nharms=4): measured 1.13 s for a 10^7-bin spectrum on v5e — it would
dominate the entire search.  Any reformulation with non-128 minor dims
is no better: reshape to (J, m), stride-m 1-D slices, interleaves and
``repeat`` all cost seconds of Mosaic compile and/or tens of ms of
relayout per call.

The lane-aligned decomposition: view in/out as (rows, 128).  For
output element (R, l) — i = R*128 + l — the read index splits exactly:

    (i*m + half) >> k  =  R*S + c_l,   S = 128*m >> k,
                                       c_l = (l*m + half) >> k

because 2^k | 128*m for k <= 7.  The row part R*S decomposes over the
residue rho = R mod 2^k (S has gcd 2^(7-k) with 128, so rho's period
is 2^k): R*S = (t*m + q_rho)*128 + beta_rho for R = t*2^k + rho.  So
each residue class of output rows is

    out[t*2^k + rho, l] = W[t*m + q_rho, beta_rho + c_l]

where W = (rows, 256) pairs of adjacent 128-rows.  That is a stride-m
row slice (no lane relayout) followed by a STATIC lane permutation —
one (2^k, T, 256) x (2^k, 256, 128) einsum against 0/1 selection
matrices.  MXU work instead of gathers; Precision.HIGHEST makes the
selection exact (f32 splits exactly into 3 bf16 limbs; x1.0 summed
with zeros reproduces the f32 value bit-for-bit).  Measured at 10^7
bins on v5e: 0.42 ms for the heaviest single stretch, ~7 s compile,
vs 1130 ms run for the gather path.
"""

from __future__ import annotations

from functools import lru_cache

import numpy as np
import jax
import jax.numpy as jnp

_SCALES = [
    0.7071067811865476,  # 1/sqrt(2)
    0.5,
    0.35355339059327373,  # 1/sqrt(8)
    0.25,
    0.17677669529663687,  # 1/sqrt(32)
]

_L = 128  # TPU lane width


@lru_cache(maxsize=None)
def _stretch_tables(m: int, k: int):
    """Static (row-start, selection-matrix) tables for stretch m/2^k.

    Returns (q: tuple of 2^k row offsets, M: (2^k, 256, 128) f32 0/1).
    """
    P = 1 << k
    half = 1 << (k - 1)
    S = (_L * m) >> k
    l = np.arange(_L)
    c_l = (l * m + half) >> k
    M = np.zeros((P, 2 * _L, _L), np.float32)
    q = []
    for rho in range(P):
        rs = rho * S
        q.append(rs // _L)
        M[rho, (rs % _L) + c_l, l] = 1.0
    return tuple(q), M


# mixed per-operand precision: the spectrum operand needs the full
# 3-limb bf16 decomposition (HIGHEST) for exactness, but the selection
# matrices are 0/1 — exactly representable in ONE bf16 limb (DEFAULT)
# — which halves the MXU passes vs HIGHEST on both operands
_SEL_PRECISION = (jax.lax.Precision.HIGHEST, jax.lax.Precision.DEFAULT)


def _stretch_add(W: jnp.ndarray, nrows: int, m: int, k: int) -> jnp.ndarray:
    """One stretched read of the spectrum, returned as (nrows, 128)."""
    P = 1 << k
    T = nrows // P
    q, M = _stretch_tables(m, k)
    Wb = jnp.stack([W[q[rho]::m][:T] for rho in range(P)], axis=0)
    out = jnp.einsum(
        "ptc,pcl->tpl", Wb, jnp.asarray(M),
        precision=_SEL_PRECISION,
        preferred_element_type=jnp.float32,
    )
    return out.reshape(nrows, _L)


# (A level-fused variant — one concatenated einsum per level — was
# measured SLOWER on v5e: 3.2 ms vs 2.2 ms at 2^22 bins; the big Wb
# concatenation costs more than the extra einsum dispatches save.)


# below this spectrum size the plain gather wins: the lane-aligned
# path's fixed costs (15 stack+einsum stages) exceed the cost of small
# gathers (measured on v5e: gather ~0.1 ms at 2^17 bins vs 1130 ms at
# 10^7; einsum path ~2 ms flat at small sizes)
_GATHER_MAX_SIZE = 1 << 19


def harmonic_sums(spectrum: jnp.ndarray, nharms: int) -> list[jnp.ndarray]:
    """Return ``nharms`` stretched-and-summed spectra (levels 1..nharms).

    ``spectrum`` is the (normalised, interbinned) power spectrum; output
    level k sums 2^k harmonics and is scaled by 1/sqrt(2^k).
    """
    if not 1 <= nharms <= 5:
        raise ValueError("nharms must be in 1..5")
    size = spectrum.shape[0]
    if size <= _GATHER_MAX_SIZE:
        return _harmonic_sums_gather(spectrum, nharms)
    P_max = 1 << nharms
    nrows = -(-size // (_L * P_max)) * P_max
    # row windows reach at most nrows*m/2^k + m + 1 < nrows + P_max + 1
    # rows; edge padding reproduces the reference's index clip
    pad_rows = nrows + P_max + 2
    sp = jnp.pad(spectrum, (0, pad_rows * _L - size), mode="edge")
    X = sp.reshape(pad_rows, _L)
    W = jnp.concatenate([X[:-1], X[1:]], axis=1)  # (rows, 256) pairs
    out = []
    val2d = sp[: nrows * _L].reshape(nrows, _L)
    for k in range(1, nharms + 1):
        for m in range(1, 1 << k, 2):  # odd numerators: the new harmonics
            val2d = val2d + _stretch_add(W, nrows, m, k)
        out.append(
            (val2d.reshape(-1)[:size] * jnp.float32(_SCALES[k - 1]))
            .astype(jnp.float32)
        )
    return out


def _harmonic_sums_gather(spectrum: jnp.ndarray,
                          nharms: int) -> list[jnp.ndarray]:
    """Small-spectrum path: direct stretched gathers."""
    size = spectrum.shape[0]
    i = jnp.arange(size, dtype=jnp.int32)
    out = []
    val = spectrum
    for k in range(1, nharms + 1):
        half = 1 << (k - 1)
        for m in range(1, 1 << k, 2):
            idx = (i * m + half) >> k
            val = val + spectrum[jnp.clip(idx, 0, size - 1)]
        out.append((val * jnp.float32(_SCALES[k - 1])).astype(jnp.float32))
    return out
