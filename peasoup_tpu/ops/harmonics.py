"""Incremental harmonic summing.

Reference semantics: `src/kernels.cu:33-99`.  Level k (1-based) adds the
spectrum sampled at stretched indices ``(int)(i * m/2^k + 0.5)`` for the
odd numerators m of 2^k, accumulating on the previous level, and stores
``val / sqrt(2^k)``.  Up to 5 levels (2, 4, 8, 16, 32 summed harmonics).

The reference evaluates ``i * m/2^k + 0.5`` in float64; here the index
is ``(i*m + 2^(k-1)) >> k`` — identical to ``floor(i*m/2^k + 0.5)`` for
all i — avoiding float64 on TPU entirely.

TPU formulation (lane-aligned stretch)
--------------------------------------

A naive ``spectrum[idx]`` per (level, m) is 15 full-size random gathers
(nharms=4): measured 1.13 s for a 10^7-bin spectrum on v5e — it would
dominate the entire search.  Any reformulation with non-128 minor dims
is no better: reshape to (J, m), stride-m 1-D slices, interleaves and
``repeat`` all cost seconds of Mosaic compile and/or tens of ms of
relayout per call.

The lane-aligned decomposition: view in/out as (rows, 128).  For
output element (R, l) — i = R*128 + l — the read index splits exactly:

    (i*m + half) >> k  =  R*S + c_l,   S = 128*m >> k,
                                       c_l = (l*m + half) >> k

because 2^k | 128*m for k <= 7.  The row part R*S decomposes over the
residue rho = R mod 2^k (S has gcd 2^(7-k) with 128, so rho's period
is 2^k): R*S = (t*m + q_rho)*128 + beta_rho for R = t*2^k + rho.  So
each residue class of output rows is

    out[t*2^k + rho, l] = W[t*m + q_rho, beta_rho + c_l]

where W = (rows, 256) pairs of adjacent 128-rows.  That is a stride-m
row slice (no lane relayout) followed by a STATIC lane permutation —
one (2^k, T, 256) x (2^k, 256, 128) einsum against 0/1 selection
matrices.  MXU work instead of gathers; Precision.HIGHEST makes the
selection exact (f32 splits exactly into 3 bf16 limbs; x1.0 summed
with zeros reproduces the f32 value bit-for-bit).  Measured at 10^7
bins on v5e: 0.42 ms for the heaviest single stretch, ~7 s compile,
vs 1130 ms run for the gather path.
"""

from __future__ import annotations

from functools import lru_cache

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_SCALES = [
    0.7071067811865476,  # 1/sqrt(2)
    0.5,
    0.35355339059327373,  # 1/sqrt(8)
    0.25,
    0.17677669529663687,  # 1/sqrt(32)
]

_L = 128  # TPU lane width


@lru_cache(maxsize=None)
def _stretch_tables(m: int, k: int):
    """Static (row-start, selection-matrix) tables for stretch m/2^k.

    Returns (q: tuple of 2^k row offsets, M: (2^k, 256, 128) f32 0/1).
    """
    P = 1 << k
    half = 1 << (k - 1)
    S = (_L * m) >> k
    l = np.arange(_L)
    c_l = (l * m + half) >> k
    M = np.zeros((P, 2 * _L, _L), np.float32)
    q = []
    for rho in range(P):
        rs = rho * S
        q.append(rs // _L)
        M[rho, (rs % _L) + c_l, l] = 1.0
    return tuple(q), M


# mixed per-operand precision: the spectrum operand needs the full
# 3-limb bf16 decomposition (HIGHEST) for exactness, but the selection
# matrices are 0/1 — exactly representable in ONE bf16 limb (DEFAULT)
# — which halves the MXU passes vs HIGHEST on both operands
_SEL_PRECISION = (jax.lax.Precision.HIGHEST, jax.lax.Precision.DEFAULT)


def _stretch_add(W: jnp.ndarray, nrows: int, m: int, k: int) -> jnp.ndarray:
    """One stretched read of the spectrum, returned as (nrows, 128)."""
    P = 1 << k
    T = nrows // P
    q, M = _stretch_tables(m, k)
    Wb = jnp.stack([W[q[rho]::m][:T] for rho in range(P)], axis=0)
    out = jnp.einsum(
        "ptc,pcl->tpl", Wb, jnp.asarray(M),
        precision=_SEL_PRECISION,
        preferred_element_type=jnp.float32,
    )
    return out.reshape(nrows, _L)


# (A level-fused variant — one concatenated einsum per level — was
# measured SLOWER on v5e: 3.2 ms vs 2.2 ms at 2^22 bins; the big Wb
# concatenation costs more than the extra einsum dispatches save.)


# below this spectrum size the plain gather wins: the lane-aligned
# path's fixed costs (15 stack+einsum stages) exceed the cost of small
# gathers (measured on v5e: gather ~0.1 ms at 2^17 bins vs 1130 ms at
# 10^7; einsum path ~2 ms flat at small sizes)
_GATHER_MAX_SIZE = 1 << 19


def harmonic_sums(spectrum: jnp.ndarray, nharms: int) -> list[jnp.ndarray]:
    """Return ``nharms`` stretched-and-summed spectra (levels 1..nharms).

    ``spectrum`` is the (normalised, interbinned) power spectrum; output
    level k sums 2^k harmonics and is scaled by 1/sqrt(2^k).

    Three size/backend regimes, all bit-exact vs the numpy reference:
    gathers below 2^19 bins, the fused Pallas kernel on TPU (all 5
    levels; see :func:`_hsum_pallas_batched`), the einsum path
    otherwise.
    """
    if not 1 <= nharms <= 5:
        raise ValueError("nharms must be in 1..5")
    size = spectrum.shape[0]
    if size <= _GATHER_MAX_SIZE:
        return _harmonic_sums_gather(spectrum, nharms)
    if _on_tpu():
        return list(_pallas_hsum_fn(nharms)(spectrum))
    return _harmonic_sums_einsum(spectrum, nharms)


@lru_cache(maxsize=1)
def _on_tpu() -> bool:
    return jax.devices()[0].platform == "tpu"


def _harmonic_sums_einsum(spectrum: jnp.ndarray,
                          nharms: int) -> list[jnp.ndarray]:
    """Lane-aligned einsum path (any backend; see module docstring)."""
    size = spectrum.shape[0]
    P_max = 1 << nharms
    nrows = -(-size // (_L * P_max)) * P_max
    # row windows reach at most nrows*m/2^k + m + 1 < nrows + P_max + 1
    # rows; edge padding reproduces the reference's index clip
    pad_rows = nrows + P_max + 2
    sp = jnp.pad(spectrum, (0, pad_rows * _L - size), mode="edge")
    X = sp.reshape(pad_rows, _L)
    W = jnp.concatenate([X[:-1], X[1:]], axis=1)  # (rows, 256) pairs
    out = []
    val2d = sp[: nrows * _L].reshape(nrows, _L)
    for k in range(1, nharms + 1):
        for m in range(1, 1 << k, 2):  # odd numerators: the new harmonics
            val2d = val2d + _stretch_add(W, nrows, m, k)
        out.append(
            (val2d.reshape(-1)[:size] * jnp.float32(_SCALES[k - 1]))
            .astype(jnp.float32)
        )
    return out


def _harmonic_sums_gather(spectrum: jnp.ndarray,
                          nharms: int) -> list[jnp.ndarray]:
    """Small-spectrum path: direct stretched gathers."""
    size = spectrum.shape[0]
    i = jnp.arange(size, dtype=jnp.int32)
    out = []
    val = spectrum
    for k in range(1, nharms + 1):
        half = 1 << (k - 1)
        for m in range(1, 1 << k, 2):
            idx = (i * m + half) >> k
            val = val + spectrum[jnp.clip(idx, 0, size - 1)]
        out.append((val * jnp.float32(_SCALES[k - 1])).astype(jnp.float32))
    return out


# --------------------------------------------------------------------------
# fused Pallas kernel (TPU hot path)
# --------------------------------------------------------------------------
#
# One kernel computes ALL levels: per output row-tile [R0, R0+TR) it
# DMAs each stretch's source window (rows [m*R0/P, m*(R0+TR)/P + m+2),
# ~7.5*TR rows total across the 15 stretches of nharms=4) into VMEM and
# applies the lane-aligned decomposition entirely on-chip:
#
#   out[t*P + rho, l] = W[t*m + q_rho, o_rho + c_l]
#     P = 2^k, S = 128*m/P, q_rho = rho*S // 128, o_rho = rho*S % 128
#
# * the strided row slice W[q::m] becomes a free sublane reshape
#   (TR/P, m, 256) + static middle index;
# * the per-rho lane permutation becomes pltpu.roll by -o_rho + ONE
#   shared (128,128) 0/1 selection matrix per stretch on the MXU
#   (c_l <= 127*m/2^k < 128, so post-roll lanes fit one register row);
# * exact f32 via a manual 3-limb bf16 decomposition: a = hi+mid+lo
#   with every partial sum representable, so the three f32-accumulated
#   selection dots reconstruct the f32 value bit-for-bit (tested).
#
# vs the einsum path this cuts HBM traffic ~4x (no materialised Wb
# stacks) and MXU work 2x (128- not 256-contraction): measured on v5e
# at 10^7 bins (r5 session, benchmarks/micro_results.json): 3.55 ms vs
# 6.44 ms einsum (1.8x) at nharms=4; 5.45 ms vs 13.4 ms (2.45x) at
# nharms=5, bit-exact at every level.  (An earlier 1.62 ms claim here
# did not reproduce on re-measurement and is superseded by the
# committed artifact.)  Two re-formulations measured SLOWER the same
# session: concatenating the 3 bf16 limbs into one (3T,128) dot per
# rho (3.89 ms — the concat relayout beats the saved dot issues) and
# TR=2048 (VMEM overflow, Mosaic compile failure).  The remaining gap
# to the ~0.6 ms HBM roofline is the serialised per-stretch
# wait(window DMA) -> VMEM shift copy -> compute chain; the window
# DMAs themselves are double-buffered.
_TR = 1024  # output rows per grid step (TR=2048 overflows 16M VMEM)


def _hsum_stretch_meta(nharms: int):
    metas = []
    for k in range(1, nharms + 1):
        P = 1 << k
        for m in range(1, 1 << k, 2):
            S = (_L * m) >> k
            q = tuple((rho * S) // _L for rho in range(P))
            o = tuple((rho * S) % _L for rho in range(P))
            metas.append((k, m, P, q, o))
    return metas


@lru_cache(maxsize=None)
def _hsum_sel_matrices(nharms: int) -> np.ndarray:
    """(n_stretch, 128, 128) bf16 selection: M[s][c, l] = (c == c_l)."""
    half_cl = []
    for k in range(1, nharms + 1):
        half = 1 << (k - 1)
        for m in range(1, 1 << k, 2):
            half_cl.append((np.arange(_L) * m + half) >> k)
    M = np.zeros((len(half_cl), _L, _L), np.float32)
    for s, c_l in enumerate(half_cl):
        M[s, c_l, np.arange(_L)] = 1.0
    return M.astype(jnp.bfloat16)


def _limbs3(x: jnp.ndarray):
    """Exact 3-term bf16 decomposition of f32 (hi+mid+lo == x)."""
    hi = x.astype(jnp.bfloat16)
    r1 = x - hi.astype(jnp.float32)
    mid = r1.astype(jnp.bfloat16)
    lo = (r1 - mid.astype(jnp.float32)).astype(jnp.bfloat16)
    return hi, mid, lo


def _make_hsum_kernel(nharms: int, TR: int, n_tiles: int, pad_rows: int):
    metas = _hsum_stretch_meta(nharms)
    wins = [(m * (TR // P) + m + 2, (TR // P) * m)
            for (_k, m, P, _q, _o) in metas]

    def kernel(x_any, m_ref, *rest):
        out_refs, (v_ref, v2_ref, sem, sem2, sem_i) = rest[:-5], rest[-5:]
        # the batch is FLATTENED into the row axis (grid (B*n_tiles,),
        # 2-D blocks): a (B, rows, 128) layout with (1, TR, 128) blocks
        # measured ~1.2 ms slower at 10^7 bins on v5e
        idx = pl.program_id(0)
        b = idx // n_tiles
        i = idx % n_tiles
        base = b * pad_rows
        n_str = len(wins)

        def dmas(si, slot):
            WIN, mult = wins[si]
            start = base + i * mult
            # ONE HBM window of WIN+1 rows; the one-row-shifted copy the
            # lane-pair concat needs is derived VMEM->VMEM (shift_copy):
            # the concat needs both operands at sublane offset 0 (Mosaic
            # rejects concat of an offset-1 view, and a same-buffer roll
            # carries the offset in its layout too), and a second HBM
            # window would double the kernel's HBM read traffic
            return pltpu.make_async_copy(
                x_any.at[pl.ds(start, WIN + 1)],
                v_ref.at[slot, pl.ds(0, WIN + 1)], sem.at[slot])

        def shift_copy(si, slot):
            WIN, _ = wins[si]
            return pltpu.make_async_copy(
                v_ref.at[slot, pl.ds(1, WIN)],
                v2_ref.at[slot, pl.ds(0, WIN)], sem2.at[slot])

        # init tile (the accumulator starts as the spectrum itself) and
        # the first stretch window are in flight together; subsequent
        # stretch windows are double-buffered two slots deep
        dma_i = pltpu.make_async_copy(
            x_any.at[pl.ds(base + i * TR, TR)], v_ref.at[2, pl.ds(0, TR)],
            sem_i)
        dma_i.start()
        dmas(0, 0).start()
        dma_i.wait()
        acc = v_ref[2, pl.ds(0, TR)]
        si = 0
        for k in range(1, nharms + 1):
            P = 1 << k
            T = TR // P
            for m in range(1, 1 << k, 2):
                _, _, _, qs, os_ = metas[si]
                WIN, _ = wins[si]
                slot = si % 2
                if si + 1 < n_str:
                    # next stretch's HBM window overlaps this compute
                    dmas(si + 1, (si + 1) % 2).start()
                dmas(si, slot).wait()
                # the derived shifted copy is the only exposed wait
                # (VMEM->VMEM, ~0.5 MB)
                sc = shift_copy(si, slot)
                sc.start()
                sc.wait()
                Vp = jnp.concatenate(
                    [v_ref[slot, pl.ds(0, WIN)],
                     v2_ref[slot, pl.ds(0, WIN)]], axis=1)
                Vpr = Vp[: m * T].reshape(T, m, 2 * _L)
                Msel = m_ref[si]
                # per-rho small dots, post-dot interleave: measured
                # FASTER (2.6 vs 3.5 ms at 10^7, same session) than one
                # big pre-interleaved (TR,128) dot per limb — the
                # (T,P,128) stack relayout costs more than 3*P extra
                # dot issues save
                adds = []
                for rho in range(P):
                    A = Vpr[:, qs[rho], :]  # (T, 256) f32
                    A = pltpu.roll(A, (2 * _L - os_[rho]) % (2 * _L),
                                   axis=1)[:, :_L]
                    parts = [
                        jax.lax.dot_general(
                            limb, Msel, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
                        for limb in _limbs3(A)
                    ]
                    adds.append(parts[0] + parts[1] + parts[2])
                acc = acc + jnp.stack(adds, axis=1).reshape(TR, _L)
                si += 1
            out_refs[k - 1][:] = acc * jnp.float32(_SCALES[k - 1])

    return kernel


def _hsum_pallas_batched(specs: jnp.ndarray, nharms: int,
                         interpret: bool = False) -> tuple[jnp.ndarray, ...]:
    """(B, size) f32 -> nharms arrays (B, size) f32, bit-exact."""
    from jax._src.config import enable_x64

    # trace under x64=False: the package-global jax_enable_x64 would
    # make the DMA slice indices i64, which tpu.memref_slice rejects
    # (same guard as ops/dedisperse_pallas.py)
    with enable_x64(False):
        return _hsum_pallas_batched_x32(specs, nharms, interpret)


def _hsum_pallas_batched_x32(specs, nharms, interpret):
    B, size = specs.shape
    TR = _TR
    nrows = -(-size // (_L * TR)) * TR
    n_tiles = nrows // TR
    # windows reach at most (15/16)*nrows + m + 3 rows.  ZERO padding:
    # every stretch read for an output bin < size stays < size (the
    # index map (i*m + half) >> k has slope m/2^k < 1), so pad values
    # only feed output rows that are sliced off below — and jnp.pad
    # mode="edge" costs 0.6 ms at 10^7 under jax_enable_x64 (gather
    # lowering) vs 0.014 ms for constant
    pad_rows = nrows + 40
    sp = jnp.pad(specs, ((0, 0), (0, pad_rows * _L - size)))
    X = sp.reshape(B * pad_rows, _L)
    M = jnp.asarray(_hsum_sel_matrices(nharms))
    kernel = _make_hsum_kernel(nharms, TR, n_tiles, pad_rows)
    WIN_MAX = max(max(m * (TR // (1 << k)) + m + 3
                      for k in range(1, nharms + 1)
                      for m in range(1, 1 << k, 2)), TR)
    outs = pl.pallas_call(
        kernel,
        grid=(B * n_tiles,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=tuple(
            pl.BlockSpec((TR, _L), lambda idx: (idx, 0))
            for _ in range(nharms)),
        out_shape=tuple(
            jax.ShapeDtypeStruct((B * nrows, _L), jnp.float32)
            for _ in range(nharms)),
        scratch_shapes=[
            pltpu.VMEM((3, WIN_MAX + 1, _L), jnp.float32),
            pltpu.VMEM((2, WIN_MAX + 1, _L), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA,
        ],
        interpret=interpret,
    )(X, M)
    return tuple(o.reshape(B, -1)[:, :size] for o in outs)


@lru_cache(maxsize=None)
def _pallas_hsum_fn(nharms: int, interpret: bool = False):
    """custom_vmap wrappers: the hot paths vmap ``harmonic_sums`` over
    accel-trial batches; the rules map any vmap nesting depth onto the
    kernel's batch grid axis instead of failing pallas_call's default
    batching (which would shift the kernel's program_id axes)."""
    from jax.custom_batching import custom_vmap

    @custom_vmap
    def f_b(specs):  # (B, size) -> tuple of (B, size)
        return _hsum_pallas_batched(specs, nharms, interpret)

    @f_b.def_vmap
    def _rule_b(axis_size, in_batched, specs):  # noqa: ANN001
        del axis_size, in_batched
        lead = specs.shape[:-1]
        outs = f_b(specs.reshape(-1, specs.shape[-1]))
        return (tuple(o.reshape(*lead, -1) for o in outs),
                tuple(True for _ in outs))

    @custom_vmap
    def f(spec):
        return tuple(o[0] for o in f_b(spec[None]))

    @f.def_vmap
    def _rule(axis_size, in_batched, spec):  # noqa: ANN001
        del axis_size, in_batched
        outs = f_b(spec)
        return outs, tuple(True for _ in outs)

    return f
