"""Time-domain acceleration resampling.

Reference semantics: `src/kernels.cu:308-379`.  Two index maps:

* ``resample`` (kernel I, used for folding): read index
  ``rn(i + af*((i - n/2)^2 - (n/2)^2))`` — symmetric about the midpoint;
* ``resample2`` (kernel II, used by the shipped search binary): read
  index ``rn(i + i*af*(i - n))`` — zero shift at both ends;

with ``af = a * tsamp / (2c)`` and rn = round-half-to-even
(``__double2ull_rn``).  The index ramp must be evaluated in float64:
``i*(i-n)`` reaches ~2^45 for 2^23-point series, far beyond float32's
24-bit mantissa, and a 1-sample index error moves power between Fourier
bins.  float64 is software-emulated on TPU but this is 3 flops/element
against an O(n log n) FFT chain, so it is off the critical path.

The gather itself stays monotone and near-linear, which XLA lowers to a
dynamic-slice-like access pattern rather than a random gather.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..errors import DomainError

SPEED_OF_LIGHT = 299792458.0


def _accel_fact(accel, tsamp) -> jnp.ndarray:
    return (
        jnp.asarray(accel, jnp.float64)  # psl: disable=PSL003 -- index ramp needs true f64 (module docstring)
        * jnp.asarray(tsamp, jnp.float64)  # psl: disable=PSL003 -- index ramp needs true f64
        / (2.0 * SPEED_OF_LIGHT)
    )


def resample(tim: jnp.ndarray, accel, tsamp) -> jnp.ndarray:
    """Kernel-I resampling, symmetric about the midpoint."""
    n = tim.shape[0]
    af = _accel_fact(accel, tsamp)
    i = jnp.arange(n, dtype=jnp.float64)  # psl: disable=PSL003 -- index ramp needs true f64
    half = jnp.float64(n) / 2.0  # psl: disable=PSL003 -- index ramp needs true f64
    idx = jnp.rint(i + af * ((i - half) ** 2 - half * half)).astype(jnp.int32)
    return tim[jnp.clip(idx, 0, n - 1)]


def _jerk_fact(jerk, tsamp) -> jnp.ndarray:
    return (
        jnp.asarray(jerk, jnp.float64)  # psl: disable=PSL003 -- index ramp needs true f64 (module docstring)
        * jnp.asarray(tsamp, jnp.float64)  # psl: disable=PSL003 -- index ramp needs true f64
        * jnp.asarray(tsamp, jnp.float64)  # psl: disable=PSL003 -- index ramp needs true f64
        / (6.0 * SPEED_OF_LIGHT)
    )


#: max_i |i*(i-n)*(i+n)| over [0, n] is 2 n^3 / (3 sqrt(3)), attained
#: at i = n/sqrt(3) (the cubic jerk ramp's peak displacement)
_JERK_PEAK_COEFF = 2.0 / (3.0 * np.sqrt(3.0))


def resample2_max_shift(max_accel, tsamp, n: int, max_jerk=0.0) -> int:
    """Static bound on |read_index - i| for kernel-II resampling:
    |af| * max_i i*(n-i) = |af| * n^2/4 for the quadratic accel term,
    plus |jf| * 2 n^3 / (3 sqrt(3)) for the cubic jerk term (peak of
    |i*(i-n)*(i+n)| at i = n/sqrt(3)), plus one for rounding."""
    af = abs(float(max_accel)) * float(tsamp) / (2.0 * SPEED_OF_LIGHT)
    jf = (abs(float(max_jerk)) * float(tsamp) * float(tsamp)
          / (6.0 * SPEED_OF_LIGHT))
    fn = float(n)
    return int(np.ceil(af * fn * fn / 4.0
                       + jf * _JERK_PEAK_COEFF * fn * fn * fn)) + 1


# above this many shifted copies the select chain loses to the gather
_SELECT_MAX_SHIFT = 64


def _static_zero(val) -> bool:
    """True iff ``val`` is a concrete (non-tracer) exact zero."""
    try:
        return float(val) == 0.0
    except Exception:
        return False


def resample2(tim: jnp.ndarray, accel, tsamp, max_shift: int | None = None,
              jerk=0.0) -> jnp.ndarray:
    """Kernel-II resampling (zero shift at both ends); the search path.

    When ``max_shift`` (a static bound from ``resample2_max_shift``) is
    small, the gather — TPU's weakest access pattern, and the hottest
    op of the fused search — is replaced by a select over 2*max_shift+1
    statically-shifted copies: the read index differs from ``i`` by at
    most a few samples for realistic accelerations, and elementwise
    selects fuse where a 23M-element gather cannot.

    ``jerk`` adds the acceleration-derivative axis as a cubic term of
    the same zero-at-both-ends family: ``i*jf*(i-n)*(i+n)`` with
    ``jf = jerk * tsamp^2 / (6c)`` — zero at i=0 and i=n like the
    quadratic accel term, so the trial's period normalisation is
    unchanged.  A static zero jerk skips the term entirely, keeping
    the accel-only expression bit-identical to the pre-jerk build.
    """
    n = tim.shape[0]
    af = _accel_fact(accel, tsamp)
    i = jnp.arange(n, dtype=jnp.float64)  # psl: disable=PSL003 -- index ramp needs true f64
    # round the SUM like the reference (half-to-even ties depend on the
    # integer part, so rint(i + x) != i + rint(x) exactly at ties)
    ramp = i + i * af * (i - jnp.float64(n))  # psl: disable=PSL003 -- index ramp needs true f64
    if not _static_zero(jerk):
        jf = _jerk_fact(jerk, tsamp)
        ramp = ramp + i * jf * (i - jnp.float64(n)) * (i + jnp.float64(n))  # psl: disable=PSL003 -- index ramp needs true f64
    idx = jnp.rint(ramp)
    if max_shift is None or max_shift > _SELECT_MAX_SHIFT:
        return tim[jnp.clip(idx.astype(jnp.int32), 0, n - 1)]
    d = (idx - i).astype(jnp.int32)
    # edge-replicated padding == the reference's clip of the final index
    padded = jnp.pad(tim, (max_shift, max_shift), mode="edge")
    out = jnp.zeros_like(tim)
    for k in range(-max_shift, max_shift + 1):
        out = jnp.where(d == k, padded[max_shift + k : max_shift + k + n],
                        out)
    return out


def residual_width(max_shift: int, block: int, n: int) -> int:
    """Static per-block residual-table width: the staircase's maximum
    step count inside one block (derivative bound) + 2 for the two
    independent roundings at the block base and the element.  Single
    source of truth for the table builders and the block chooser."""
    return int(np.ceil(4.0 * max_shift * block / n)) + 2


def residual_width_jerk(max_accel, max_jerk, tsamp, block: int,
                        n: int) -> int:
    """Jerk-aware static per-block residual width.

    The accel-only :func:`residual_width` bounds the in-block step
    count via max|d'| = |af|*n = 4*max_shift/n, which UNDERESTIMATES
    once a cubic jerk term joins the ramp (its derivative peaks at
    2*|jf|*n^2, larger than the jerk term's share of max_shift implies)
    — so jerk table builders must use this bound instead:
    max|d'| = |af|*n + 2*|jf|*n^2, times the block length, + 2 for the
    two independent roundings."""
    af = abs(float(max_accel)) * float(tsamp) / (2.0 * SPEED_OF_LIGHT)
    jf = (abs(float(max_jerk)) * float(tsamp) * float(tsamp)
          / (6.0 * SPEED_OF_LIGHT))
    fn = float(n)
    return int(np.ceil((af * fn + 2.0 * jf * fn * fn) * block)) + 2


def _staircase_tables_np(afs: np.ndarray, n: int, max_shift: int,
                         block: int, kernel: int = 2):
    """Host-side (exact IEEE f64) per-block index tables for the
    resampling offset staircases, vectorised over accel trials.

    On real TPU hardware float64 is software-emulated and its
    ``round[NEAREST_EVEN]`` lowering is WRONG for a few percent of
    values (verified on v5e: e.g. rint(42136.49999354) -> 42135), so
    any device-side f64 index math is silently inexact there.  The
    acceleration trial list is always known on the host, so the exact
    staircase is computed here in hardware f64 and shipped as tiny
    int32 tables; the device then does only integer compares/selects.

    ``kernel`` selects the reference formula: 2 = shipped search
    binary's ``rn(i + i*af*(i-n))`` (`src/kernels.cu:335-362`), 1 =
    folding path's ``rn(i + af*((i-n/2)^2 - (n/2)^2))``
    (`src/kernels.cu:364-379`).  Both follow the same parabola, but
    the fp evaluation order differs, so boundaries are bisected on the
    exact per-kernel expression.

    Returns (d0[A, nb], pos[A, nb, m], step[A, nb, m]) numpy int32:
    block-start offsets, and the position/sign of each staircase step
    strictly inside each block (inactive slots: pos = n, step = 0).
    """
    afs = np.atleast_1d(np.asarray(afs, np.float64))
    A = afs.shape[0]
    nb = n // block
    m = residual_width(max_shift, block, n)
    if 4 * max_shift >= n:
        # the bisection below assumes the rounded staircase u(i) is
        # monotone with unit steps on each side of n/2, which holds
        # only while |af|*n < 1 (i.e. 4*max_shift < n); beyond that
        # (extreme accel or tiny n) the tables would be silently wrong
        # without tripping the k1/step-density checks
        raise DomainError(
            f"max_shift={max_shift} too large for n={n} "
            f"(needs 4*max_shift < n): the staircase bisection is only "
            f"valid for |af|*n < 1 — use the on-device resampler or a "
            f"longer series"
        )
    col = afs[:, None]
    if kernel == 2:
        d_of = lambda i: np.rint(i + i * col * (i - np.float64(n))) - i
    else:
        half = np.float64(n) / 2.0
        d_of = lambda i: (
            np.rint(i + col * ((i - half) ** 2 - half * half)) - i)
    sign = np.where(afs >= 0, 1.0, -1.0)[:, None]
    u_of = lambda i: (-sign * d_of(np.asarray(i, np.float64))).astype(
        np.int64)
    vh = n // 2
    k = np.broadcast_to(
        np.arange(1, max_shift + 1, dtype=np.int64), (A, max_shift))

    def bisect(lo0, hi0, pred):
        lo = np.full((A, max_shift), lo0, np.int64)
        hi = np.full((A, max_shift), hi0, np.int64)
        for _ in range(int(np.ceil(np.log2(max(n, 2)))) + 1):
            mid = (lo + hi) // 2
            p = pred(mid)
            lo, hi = np.where(p, lo, mid), np.where(p, mid, hi)
        return hi

    k1 = u_of(np.full((A, 1), vh))
    kend = u_of(np.full((A, 1), n - 1))
    if int(k1.max(initial=0)) > max_shift:
        # enumerating only k = 1..max_shift would silently drop the
        # deeper steps AND under-pad the device slice starts
        raise DomainError(
            f"true peak shift {int(k1.max())} exceeds max_shift="
            f"{max_shift}; pass a bound from resample2_max_shift() for "
            f"the largest |accel| in the batch"
        )
    b = np.where(k <= k1, bisect(0, vh, lambda mid: u_of(mid) >= k), n)
    c = np.where(k <= k1 - kend,
                 bisect(vh, n - 1, lambda mid: u_of(mid) <= k1 - k), n)
    i0 = np.arange(nb, dtype=np.float64) * block
    d0 = d_of(i0).astype(np.int32)
    pos_t = np.full((A, nb, m), n, np.int32)
    step_t = np.zeros((A, nb, m), np.int32)
    s_int = sign.astype(np.int32).ravel()
    for a in range(A):
        bounds = np.concatenate([b[a], c[a]])
        steps = np.concatenate(
            [np.full(max_shift, -s_int[a], np.int32),
             np.full(max_shift, s_int[a], np.int32)])
        active = (bounds < n) & (bounds % block != 0)
        bounds, steps = bounds[active], steps[active]
        order = np.argsort(bounds, kind="stable")
        bounds, steps = bounds[order], steps[order]
        blk = bounds // block
        rank = np.arange(len(bounds)) - np.searchsorted(
            blk, blk, side="left")
        if len(rank) and rank.max() >= m:
            raise AssertionError(
                "staircase step density exceeded static bound")
        pos_t[a, blk, rank] = bounds
        step_t[a, blk, rank] = steps
    return d0, pos_t, step_t


def _staircase_tables_direct_np(afs: np.ndarray, jfs: np.ndarray, n: int,
                                max_shift: int, block: int, m: int):
    """Host-side (exact IEEE f64) per-block index tables by DIRECT
    evaluation of the full kernel-II ramp — the jerk-capable builder.

    The bisection of :func:`_staircase_tables_np` assumes the rounded
    staircase is monotone with unit steps on each side of n/2, which
    the quadratic accel ramp guarantees but the cubic jerk term breaks
    (up to three monotone pieces, and steps can exceed one sample per
    position once |d'| > 1 locally).  This builder instead evaluates
    the exact rounded offset d(i) for every i, one trial at a time
    (bounded host memory), and encodes each non-zero first difference
    as |step| unit entries at its position — the device-side table
    format (:func:`resample2_from_tables`) already supports multiple
    unit steps at one position slot.

    ``m`` is the caller's static residual width (from
    :func:`residual_width_jerk` at the GLOBAL accel/jerk bounds, so
    every chunk's tables share one shape).  Returns the same
    (d0[A, nb], pos[A, nb, m], step[A, nb, m]) int32 layout as the
    bisection builder.
    """
    afs = np.atleast_1d(np.asarray(afs, np.float64))
    jfs = np.atleast_1d(np.asarray(jfs, np.float64))
    A = afs.shape[0]
    nb = n // block
    i = np.arange(n, dtype=np.float64)
    d0 = np.zeros((A, nb), np.int32)
    pos_t = np.full((A, nb, m), n, np.int32)
    step_t = np.zeros((A, nb, m), np.int32)
    for a in range(A):
        ramp = i + i * afs[a] * (i - np.float64(n))
        if jfs[a] != 0.0:
            ramp = ramp + (i * jfs[a] * (i - np.float64(n))
                           * (i + np.float64(n)))
        d = (np.rint(ramp) - i).astype(np.int64)
        peak = int(np.abs(d).max(initial=0))
        if peak > max_shift:
            raise DomainError(
                f"true peak shift {peak} exceeds max_shift={max_shift}; "
                f"pass a bound from resample2_max_shift() for the "
                f"largest |accel|/|jerk| in the batch"
            )
        d0[a] = d[::block].astype(np.int32)
        diff = np.diff(d)
        chg = np.nonzero(diff)[0] + 1     # step takes effect AT i=chg
        active = chg % block != 0         # block-base changes live in d0
        chg, steps = chg[active], diff[chg[active] - 1]
        # expand multi-sample steps into |step| unit entries (the
        # device select counts unit slots)
        reps = np.abs(steps).astype(np.int64)
        bounds = np.repeat(chg, reps)
        units = np.repeat(np.sign(steps).astype(np.int32), reps)
        blk = bounds // block
        rank = np.arange(len(bounds)) - np.searchsorted(
            blk, blk, side="left")
        if len(rank) and rank.max() >= m:
            raise AssertionError(
                "staircase step density exceeded static bound")
        pos_t[a, blk, rank] = bounds
        step_t[a, blk, rank] = units
    return d0, pos_t, step_t


def _afs(accels, tsamp) -> np.ndarray:
    return (np.atleast_1d(np.asarray(accels, np.float64))
            * np.float64(tsamp) / (2.0 * SPEED_OF_LIGHT))


def _jfs(jerks, tsamp) -> np.ndarray:
    return (np.atleast_1d(np.asarray(jerks, np.float64))
            * np.float64(tsamp) * np.float64(tsamp)
            / (6.0 * SPEED_OF_LIGHT))


def resample2_tables(accels, tsamp, n: int, max_shift: int,
                     block: int = 4096, jerks=None, width: int | None = None):
    """Exact host-side kernel-II index tables for a batch of accel
    trials: (d0[A, nb], pos[A, nb, m], step[A, nb, m]), ready to vmap
    :func:`resample2_from_tables` over.

    ``jerks`` (per-trial jerk values, same length as ``accels``)
    switches to the jerk-capable direct builder; ``width`` fixes its
    static residual width (pass :func:`residual_width_jerk` at the
    run's global bounds so chunked callers get shape-stable tables).
    ``jerks=None`` keeps the accel-only bisection builder, bit-exact
    with the pre-jerk build."""
    if jerks is None:
        return _staircase_tables_np(_afs(accels, tsamp), n, max_shift,
                                    block, kernel=2)
    afs = _afs(accels, tsamp)
    jfs = _jfs(jerks, tsamp)
    if width is None:
        amax = float(np.abs(np.atleast_1d(accels)).max(initial=0.0))
        jmax = float(np.abs(np.atleast_1d(jerks)).max(initial=0.0))
        width = residual_width_jerk(amax, jmax, tsamp, block, n)
    return _staircase_tables_direct_np(afs, jfs, n, max_shift, block,
                                       int(width))


def resample1_tables(accels, tsamp, n: int, max_shift: int,
                     block: int = 4096):
    """Exact host-side kernel-I (folding-path) index tables."""
    return _staircase_tables_np(_afs(accels, tsamp), n, max_shift, block,
                                kernel=1)


def resample2_unique_tables(accs_grid, tsamp, n: int, max_shift: int,
                            block: int = 4096, jerks_grid=None,
                            width: int | None = None):
    """Tables for a NaN-padded (ndm, namax) accel grid, deduplicated.

    Accel values repeat heavily across DM trials (0 is in every list,
    grids overlap), so tables are built once per UNIQUE accel and the
    grid maps to rows via ``uidx``.  NaN padding slots map to the 0.0
    row (their outputs are masked anyway).

    ``jerks_grid`` (same shape, the combined trial axis's per-slot
    jerk) switches the dedup to unique (accel, jerk) PAIRS and the
    build to the jerk-capable direct builder — the jerk value is baked
    into each unique table row, so the device program body needs no
    jerk input at all on the table path.

    Returns (d0_u[U, nb], pos_u[U, nb, m], step_u[U, nb, m],
    uidx[ndm, namax] int32).
    """
    grid = np.nan_to_num(np.asarray(accs_grid, np.float64))
    if jerks_grid is None:
        uniq, inv = np.unique(grid, return_inverse=True)
        d0, pos, step = resample2_tables(uniq, tsamp, n, max_shift,
                                         block=block)
        return d0, pos, step, inv.reshape(grid.shape).astype(np.int32)
    jgrid = np.nan_to_num(np.asarray(jerks_grid, np.float64))
    pairs = np.stack([grid.ravel(), jgrid.ravel()], axis=1)
    uniq, inv = np.unique(pairs, axis=0, return_inverse=True)
    d0, pos, step = resample2_tables(
        uniq[:, 0], tsamp, n, max_shift, block=block, jerks=uniq[:, 1],
        width=width)
    return d0, pos, step, inv.reshape(grid.shape).astype(np.int32)


def resample2_from_tables(tim: jnp.ndarray, d0: jnp.ndarray,
                          pos_t: jnp.ndarray, step_t: jnp.ndarray,
                          max_shift: int, block: int = 4096) -> jnp.ndarray:
    """Kernel-II resampling from host-precomputed index tables: pure
    int32 compares + static selects + one contiguous slice per block —
    no device f64, exact on TPU (see `_staircase_tables_np`)."""
    n = tim.shape[0]
    nb, m = pos_t.shape
    pad = max_shift + m
    padded = jnp.pad(tim, (pad, pad), mode="edge")
    starts = (pad - m) + (jnp.arange(nb, dtype=jnp.int32) * block + d0)
    blocks = jax.vmap(
        lambda s: jax.lax.dynamic_slice(padded, (s,), (block + 2 * m,))
    )(starts)
    i_global = (jnp.arange(nb, dtype=jnp.int32)[:, None] * block
                + jnp.arange(block, dtype=jnp.int32)[None, :])
    sel = jnp.full((nb, block), m, jnp.int32)
    for slot in range(m):
        sel = sel + step_t[:, slot:slot + 1] * (
            i_global >= pos_t[:, slot:slot + 1])
    out = jnp.zeros((nb, block), tim.dtype)
    for k in range(2 * m + 1):
        out = jnp.where(
            sel == k, jax.lax.slice_in_dim(blocks, k, k + block, axis=1),
            out)
    return out.reshape(n)


def resample2_blockwise(tim: jnp.ndarray, accel, tsamp, max_shift: int,
                        block: int = 4096) -> jnp.ndarray:
    """Kernel-II resampling via host-exact tables for a CONCRETE accel.

    Convenience wrapper (tests/benchmarks): builds the staircase tables
    on the host — ``accel`` must not be a tracer — and applies
    :func:`resample2_from_tables`.  Production paths build tables for
    whole accel batches up front instead.
    """
    n = tim.shape[0]
    if n % block:
        return resample2(tim, accel, tsamp, max_shift=max_shift)
    d0, pos_t, step_t = resample2_tables(
        [float(accel)], float(tsamp), n, max_shift, block=block)
    return resample2_from_tables(
        tim, jnp.asarray(d0[0]), jnp.asarray(pos_t[0]),
        jnp.asarray(step_t[0]), max_shift, block=block)
