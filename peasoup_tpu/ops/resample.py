"""Time-domain acceleration resampling.

Reference semantics: `src/kernels.cu:308-379`.  Two index maps:

* ``resample`` (kernel I, used for folding): read index
  ``rn(i + af*((i - n/2)^2 - (n/2)^2))`` — symmetric about the midpoint;
* ``resample2`` (kernel II, used by the shipped search binary): read
  index ``rn(i + i*af*(i - n))`` — zero shift at both ends;

with ``af = a * tsamp / (2c)`` and rn = round-half-to-even
(``__double2ull_rn``).  The index ramp must be evaluated in float64:
``i*(i-n)`` reaches ~2^45 for 2^23-point series, far beyond float32's
24-bit mantissa, and a 1-sample index error moves power between Fourier
bins.  float64 is software-emulated on TPU but this is 3 flops/element
against an O(n log n) FFT chain, so it is off the critical path.

The gather itself stays monotone and near-linear, which XLA lowers to a
dynamic-slice-like access pattern rather than a random gather.
"""

from __future__ import annotations

import jax.numpy as jnp

SPEED_OF_LIGHT = 299792458.0


def _accel_fact(accel, tsamp) -> jnp.ndarray:
    return (
        jnp.asarray(accel, jnp.float64)
        * jnp.asarray(tsamp, jnp.float64)
        / (2.0 * SPEED_OF_LIGHT)
    )


def resample(tim: jnp.ndarray, accel, tsamp) -> jnp.ndarray:
    """Kernel-I resampling, symmetric about the midpoint."""
    n = tim.shape[0]
    af = _accel_fact(accel, tsamp)
    i = jnp.arange(n, dtype=jnp.float64)
    half = jnp.float64(n) / 2.0
    idx = jnp.rint(i + af * ((i - half) ** 2 - half * half)).astype(jnp.int32)
    return tim[jnp.clip(idx, 0, n - 1)]


def resample2_max_shift(max_accel, tsamp, n: int) -> int:
    """Static bound on |read_index - i| for kernel-II resampling:
    |af| * max_i i*(n-i) = |af| * n^2/4, plus one for rounding."""
    import numpy as np

    af = abs(float(max_accel)) * float(tsamp) / (2.0 * SPEED_OF_LIGHT)
    return int(np.ceil(af * float(n) * float(n) / 4.0)) + 1


# above this many shifted copies the select chain loses to the gather
_SELECT_MAX_SHIFT = 64


def resample2(tim: jnp.ndarray, accel, tsamp, max_shift: int | None = None
              ) -> jnp.ndarray:
    """Kernel-II resampling (zero shift at both ends); the search path.

    When ``max_shift`` (a static bound from ``resample2_max_shift``) is
    small, the gather — TPU's weakest access pattern, and the hottest
    op of the fused search — is replaced by a select over 2*max_shift+1
    statically-shifted copies: the read index differs from ``i`` by at
    most a few samples for realistic accelerations, and elementwise
    selects fuse where a 23M-element gather cannot.
    """
    n = tim.shape[0]
    af = _accel_fact(accel, tsamp)
    i = jnp.arange(n, dtype=jnp.float64)
    # round the SUM like the reference (half-to-even ties depend on the
    # integer part, so rint(i + x) != i + rint(x) exactly at ties)
    idx = jnp.rint(i + i * af * (i - jnp.float64(n)))
    if max_shift is None or max_shift > _SELECT_MAX_SHIFT:
        return tim[jnp.clip(idx.astype(jnp.int32), 0, n - 1)]
    d = (idx - i).astype(jnp.int32)
    # edge-replicated padding == the reference's clip of the final index
    padded = jnp.pad(tim, (max_shift, max_shift), mode="edge")
    out = jnp.zeros_like(tim)
    for k in range(-max_shift, max_shift + 1):
        out = jnp.where(d == k, padded[max_shift + k : max_shift + k + n],
                        out)
    return out
