// Native unique-peak merge for the candidate extraction hot path.
//
// Exact semantics of the reference's host-side peak grouping
// (include/transforms/peakfinder.hpp:27-56): walking bins in ascending
// index order, a group keeps absorbing bins while the next bin is
// within min_gap of the index of the group's current best peak (the
// "last" index only advances when a higher value is found).  The walk
// is inherently sequential, so it lives in C++ rather than NumPy.

#include <cstddef>
#include <cstdint>

extern "C" {

size_t unique_peaks(const int64_t* idxs, const float* snrs, size_t n,
                    int64_t min_gap, int64_t* out_idx, float* out_snr) {
    size_t nout = 0;
    size_t ii = 0;
    while (ii < n) {
        float cpeak = snrs[ii];
        int64_t cpeakidx = idxs[ii];
        int64_t lastidx = idxs[ii];
        ++ii;
        while (ii < n && (idxs[ii] - lastidx) < min_gap) {
            if (snrs[ii] > cpeak) {
                cpeak = snrs[ii];
                cpeakidx = idxs[ii];
                lastidx = idxs[ii];
            }
            ++ii;
        }
        out_idx[nout] = cpeakidx;
        out_snr[nout] = cpeak;
        ++nout;
    }
    return nout;
}

// Batched variant: merge every segment of a concatenated entry list in
// one call (segments = per-(dm, accel, level) spectra).  seg_bounds has
// nseg+1 entries delimiting [seg_bounds[s], seg_bounds[s+1]).  Outputs
// are written contiguously; out_counts[s] = merged peaks in segment s.
// Returns the total number of merged peaks.

size_t unique_peaks_segmented(const int64_t* idxs, const float* snrs,
                              const int64_t* seg_bounds, size_t nseg,
                              int64_t min_gap, int64_t* out_idx,
                              float* out_snr, int64_t* out_counts) {
    size_t nout = 0;
    for (size_t s = 0; s < nseg; ++s) {
        const size_t lo = static_cast<size_t>(seg_bounds[s]);
        const size_t hi = static_cast<size_t>(seg_bounds[s + 1]);
        const size_t n = hi - lo;
        const size_t before = nout;
        nout += unique_peaks(idxs + lo, snrs + lo, n, min_gap,
                             out_idx + nout, out_snr + nout);
        out_counts[s] = static_cast<int64_t>(nout - before);
    }
    return nout;
}

}  // extern "C"
