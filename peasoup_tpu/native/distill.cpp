// Native candidate distillation: the greedy SNR-sorted dedup of
// include/transforms/distiller.hpp:16-197, with the same IEEE-double
// pair predicates.  Candidates arrive pre-sorted by SNR descending;
// the walk marks absorbed candidates non-unique and records
// (fundamental, absorbed) pairs for the host to append assoc lists.
//
// Predicate types:
//   0 harmonic:      exists j<=max_harm, k<=max_denom[ii] with
//                    1-tol < k*f/(j*f0) < 1+tol      (distiller.hpp:69-103)
//   1 acceleration:  f within [min(f0,fa)-edge, max(f0,fa)+edge], where
//                    fa = f0 + (a0-a)*f0*tobs/c      (distiller.hpp:115-163)
//   2 dm:            1-tol < f/f0 < 1+tol            (distiller.hpp:168-197)

#include <cstddef>
#include <cstdint>

extern "C" {

// Records at most pair_capacity pairs but always returns the TRUE pair
// count, so the caller can retry with an exact-size buffer instead of
// preallocating the O(n^2) worst case.
size_t distill_greedy(int type, const double* freqs, const double* aux,
                      size_t n, double tol, int64_t max_harm,
                      double tobs_over_c, int record_pairs,
                      size_t pair_capacity, uint8_t* unique,
                      int64_t* pair_fundi, int64_t* pair_absorbed) {
    for (size_t i = 0; i < n; ++i) unique[i] = 1;
    size_t npairs = 0;
    const double lower = 1.0 - tol;
    const double upper = 1.0 + tol;
    for (size_t idx = 0; idx < n; ++idx) {
        if (!unique[idx]) continue;
        const double f0 = freqs[idx];
        for (size_t ii = idx + 1; ii < n; ++ii) {
            const double f = freqs[ii];
            bool hit = false;
            if (type == 0) {
                // the reference appends one assoc entry PER matching
                // (j,k) combination (distiller.hpp:91-100) — assoc
                // multiplicity feeds ddm ratios, so no short-circuit
                const int64_t max_denom = static_cast<int64_t>(aux[ii]);
                for (int64_t j = 1; j <= max_harm; ++j) {
                    for (int64_t k = 1; k <= max_denom; ++k) {
                        const double ratio =
                            static_cast<double>(k) * f /
                            (static_cast<double>(j) * f0);
                        if (ratio > lower && ratio < upper) {
                            hit = true;
                            if (record_pairs) {
                                if (npairs < pair_capacity) {
                                    pair_fundi[npairs] =
                                        static_cast<int64_t>(idx);
                                    pair_absorbed[npairs] =
                                        static_cast<int64_t>(ii);
                                }
                                ++npairs;
                            }
                        }
                    }
                    // multiplicity only matters when recording pairs;
                    // otherwise first hit decides and the grid can stop
                    if (hit && !record_pairs) break;
                }
                if (hit) unique[ii] = 0;
                continue;
            } else if (type == 1) {
                const double delta_acc = aux[idx] - aux[ii];
                const double fa = f0 + delta_acc * f0 * tobs_over_c;
                const double edge = f0 * tol;
                if (fa > f0) {
                    hit = (f > f0 - edge) && (f < fa + edge);
                } else {
                    hit = (f > fa - edge) && (f < f0 + edge);
                }
            } else {
                const double ratio = f / f0;
                hit = (ratio > lower) && (ratio < upper);
            }
            if (hit) {
                if (record_pairs) {
                    if (npairs < pair_capacity) {
                        pair_fundi[npairs] = static_cast<int64_t>(idx);
                        pair_absorbed[npairs] = static_cast<int64_t>(ii);
                    }
                    ++npairs;
                }
                unique[ii] = 0;
            }
        }
    }
    return npairs;
}

// Segmented variant: runs the same greedy dedup independently on each
// [seg_bounds[s], seg_bounds[s+1]) slice in ONE call — the per-DM /
// per-accel-trial distillation passes are thousands of small segments,
// and per-call ctypes marshalling dominates their host cost otherwise.
// Pair indices are returned in GLOBAL coordinates.  Like
// distill_greedy, the TRUE total pair count is returned even when it
// exceeds pair_capacity (recorded pairs are truncated).
size_t distill_greedy_segmented(int type, const double* freqs,
                                const double* aux,
                                const int64_t* seg_bounds, size_t nseg,
                                double tol, int64_t max_harm,
                                double tobs_over_c, int record_pairs,
                                size_t pair_capacity, uint8_t* unique,
                                int64_t* pair_fundi,
                                int64_t* pair_absorbed) {
    size_t npairs = 0;
    for (size_t s = 0; s < nseg; ++s) {
        const int64_t lo = seg_bounds[s];
        const int64_t hi = seg_bounds[s + 1];
        const size_t rec0 = npairs < pair_capacity ? npairs : pair_capacity;
        const size_t rem = pair_capacity - rec0;
        const size_t np = distill_greedy(
            type, freqs + lo, aux + lo, static_cast<size_t>(hi - lo), tol,
            max_harm, tobs_over_c, record_pairs, rem, unique + lo,
            pair_fundi + rec0, pair_absorbed + rec0);
        const size_t rec = np < rem ? np : rem;
        for (size_t p = 0; p < rec; ++p) {
            pair_fundi[rec0 + p] += lo;
            pair_absorbed[rec0 + p] += lo;
        }
        npairs += np;
    }
    return npairs;
}

}  // extern "C"
