"""Native (C++) helpers, compiled lazily with g++ and loaded via ctypes.

If compilation fails (no compiler on the host), importing ``lib`` raises
and callers fall back to the NumPy implementations.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO_PATH = os.path.join(_HERE, "_peasoup_native.so")
_SOURCES = [os.path.join(_HERE, "unpack.cpp"), os.path.join(_HERE, "peaks.cpp")]


def _build() -> str:
    newest_src = max(os.path.getmtime(s) for s in _SOURCES)
    if os.path.exists(_SO_PATH) and os.path.getmtime(_SO_PATH) >= newest_src:
        return _SO_PATH
    with tempfile.TemporaryDirectory() as td:
        tmp_so = os.path.join(td, "native.so")
        cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", *_SOURCES, "-o", tmp_so]
        subprocess.run(cmd, check=True, capture_output=True)
        os.replace(tmp_so, _SO_PATH)
    return _SO_PATH


class _NativeLib:
    def __init__(self) -> None:
        self._dll = ctypes.CDLL(_build())
        u8p = ctypes.POINTER(ctypes.c_uint8)
        self._dll.unpack_bits.argtypes = [u8p, ctypes.c_size_t, ctypes.c_int, u8p]
        self._dll.pack_bits.argtypes = [u8p, ctypes.c_size_t, ctypes.c_int, u8p]
        i64p = ctypes.POINTER(ctypes.c_int64)
        f32p = ctypes.POINTER(ctypes.c_float)
        self._dll.unique_peaks.argtypes = [
            i64p, f32p, ctypes.c_size_t, ctypes.c_int64, i64p, f32p,
        ]
        self._dll.unique_peaks.restype = ctypes.c_size_t

    def unpack_bits(self, raw: np.ndarray, nbits: int) -> np.ndarray:
        raw = np.ascontiguousarray(raw, dtype=np.uint8)
        out = np.empty(raw.size * (8 // nbits), dtype=np.uint8)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        self._dll.unpack_bits(
            raw.ctypes.data_as(u8p), raw.size, nbits, out.ctypes.data_as(u8p)
        )
        return out

    def unique_peaks(self, idxs: np.ndarray, snrs: np.ndarray, min_gap: int):
        idxs = np.ascontiguousarray(idxs, dtype=np.int64)
        snrs = np.ascontiguousarray(snrs, dtype=np.float32)
        n = idxs.size
        out_idx = np.empty(n, dtype=np.int64)
        out_snr = np.empty(n, dtype=np.float32)
        i64p = ctypes.POINTER(ctypes.c_int64)
        f32p = ctypes.POINTER(ctypes.c_float)
        nout = self._dll.unique_peaks(
            idxs.ctypes.data_as(i64p), snrs.ctypes.data_as(f32p), n,
            min_gap, out_idx.ctypes.data_as(i64p),
            out_snr.ctypes.data_as(f32p),
        )
        return out_idx[:nout], out_snr[:nout]

    def pack_bits(self, samples: np.ndarray, nbits: int) -> np.ndarray:
        samples = np.ascontiguousarray(samples, dtype=np.uint8)
        spb = 8 // nbits
        out = np.empty((samples.size + spb - 1) // spb, dtype=np.uint8)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        self._dll.pack_bits(
            samples.ctypes.data_as(u8p), samples.size, nbits, out.ctypes.data_as(u8p)
        )
        return out


try:
    lib: _NativeLib | None = _NativeLib()
except Exception:  # pragma: no cover - depends on host toolchain
    lib = None
