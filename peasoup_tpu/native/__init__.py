"""Native (C++) helpers, compiled lazily with g++ and loaded via ctypes.

If compilation fails (no compiler on the host), importing ``lib`` raises
and callers fall back to the NumPy implementations.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO_PATH = os.path.join(_HERE, "_peasoup_native.so")
_SOURCES = [
    os.path.join(_HERE, "unpack.cpp"),
    os.path.join(_HERE, "peaks.cpp"),
    os.path.join(_HERE, "distill.cpp"),
]


def _build() -> str:
    newest_src = max(os.path.getmtime(s) for s in _SOURCES)
    if os.path.exists(_SO_PATH) and os.path.getmtime(_SO_PATH) >= newest_src:
        return _SO_PATH
    with tempfile.TemporaryDirectory() as td:
        tmp_so = os.path.join(td, "native.so")
        cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", *_SOURCES, "-o", tmp_so]
        subprocess.run(cmd, check=True, capture_output=True)
        os.replace(tmp_so, _SO_PATH)
    return _SO_PATH


class _NativeLib:
    def __init__(self) -> None:
        self._dll = ctypes.CDLL(_build())
        u8p = ctypes.POINTER(ctypes.c_uint8)
        self._dll.unpack_bits.argtypes = [u8p, ctypes.c_size_t, ctypes.c_int, u8p]
        self._dll.pack_bits.argtypes = [u8p, ctypes.c_size_t, ctypes.c_int, u8p]
        i64p = ctypes.POINTER(ctypes.c_int64)
        f32p = ctypes.POINTER(ctypes.c_float)
        self._dll.unique_peaks.argtypes = [
            i64p, f32p, ctypes.c_size_t, ctypes.c_int64, i64p, f32p,
        ]
        self._dll.unique_peaks.restype = ctypes.c_size_t
        self._dll.unique_peaks_segmented.argtypes = [
            i64p, f32p, i64p, ctypes.c_size_t, ctypes.c_int64,
            i64p, f32p, i64p,
        ]
        self._dll.unique_peaks_segmented.restype = ctypes.c_size_t
        f64p = ctypes.POINTER(ctypes.c_double)
        u8pp = ctypes.POINTER(ctypes.c_uint8)
        self._dll.distill_greedy.argtypes = [
            ctypes.c_int, f64p, f64p, ctypes.c_size_t, ctypes.c_double,
            ctypes.c_int64, ctypes.c_double, ctypes.c_int,
            ctypes.c_size_t, u8pp, i64p, i64p,
        ]
        self._dll.distill_greedy.restype = ctypes.c_size_t
        self._dll.distill_greedy_segmented.argtypes = [
            ctypes.c_int, f64p, f64p, i64p, ctypes.c_size_t,
            ctypes.c_double, ctypes.c_int64, ctypes.c_double, ctypes.c_int,
            ctypes.c_size_t, u8pp, i64p, i64p,
        ]
        self._dll.distill_greedy_segmented.restype = ctypes.c_size_t

    def unpack_bits(self, raw: np.ndarray, nbits: int) -> np.ndarray:
        raw = np.ascontiguousarray(raw, dtype=np.uint8)
        out = np.empty(raw.size * (8 // nbits), dtype=np.uint8)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        self._dll.unpack_bits(
            raw.ctypes.data_as(u8p), raw.size, nbits, out.ctypes.data_as(u8p)
        )
        return out

    def unique_peaks(self, idxs: np.ndarray, snrs: np.ndarray, min_gap: int):
        idxs = np.ascontiguousarray(idxs, dtype=np.int64)
        snrs = np.ascontiguousarray(snrs, dtype=np.float32)
        n = idxs.size
        out_idx = np.empty(n, dtype=np.int64)
        out_snr = np.empty(n, dtype=np.float32)
        i64p = ctypes.POINTER(ctypes.c_int64)
        f32p = ctypes.POINTER(ctypes.c_float)
        nout = self._dll.unique_peaks(
            idxs.ctypes.data_as(i64p), snrs.ctypes.data_as(f32p), n,
            min_gap, out_idx.ctypes.data_as(i64p),
            out_snr.ctypes.data_as(f32p),
        )
        return out_idx[:nout], out_snr[:nout]

    def distill_greedy(self, type_: int, freqs, aux, tol: float,
                       max_harm: int, tobs_over_c: float,
                       record_pairs: bool):
        freqs = np.ascontiguousarray(freqs, dtype=np.float64)
        aux = np.ascontiguousarray(aux, dtype=np.float64)
        n = freqs.size
        unique = np.empty(n, dtype=np.uint8)
        f64p = ctypes.POINTER(ctypes.c_double)
        i64p = ctypes.POINTER(ctypes.c_int64)
        u8p = ctypes.POINTER(ctypes.c_uint8)

        def run(cap):
            pf = np.empty(max(cap, 1), dtype=np.int64)
            pa = np.empty(max(cap, 1), dtype=np.int64)
            npairs = self._dll.distill_greedy(
                type_, freqs.ctypes.data_as(f64p), aux.ctypes.data_as(f64p),
                n, tol, max_harm, tobs_over_c, int(record_pairs), cap,
                unique.ctypes.data_as(u8p), pf.ctypes.data_as(i64p),
                pa.ctypes.data_as(i64p),
            )
            return npairs, pf, pa

        # generous first guess; the C side keeps counting past capacity,
        # so one exact-size retry covers the (rare) overflow instead of
        # preallocating the O(n^2) worst case
        cap = (16 * n + 1024) if record_pairs else 0
        npairs, pf, pa = run(cap)
        if record_pairs and npairs > cap:
            npairs, pf, pa = run(npairs)
        return unique.astype(bool), pf[:npairs], pa[:npairs]

    def distill_greedy_segmented(self, type_: int, freqs, aux, seg_bounds,
                                 tol: float, max_harm: int,
                                 tobs_over_c: float, record_pairs: bool):
        """Segment-batched distill_greedy; pair indices are global."""
        freqs = np.ascontiguousarray(freqs, dtype=np.float64)
        aux = np.ascontiguousarray(aux, dtype=np.float64)
        seg_bounds = np.ascontiguousarray(seg_bounds, dtype=np.int64)
        n = freqs.size
        nseg = seg_bounds.size - 1
        unique = np.empty(n, dtype=np.uint8)
        f64p = ctypes.POINTER(ctypes.c_double)
        i64p = ctypes.POINTER(ctypes.c_int64)
        u8p = ctypes.POINTER(ctypes.c_uint8)

        def run(cap):
            pf = np.empty(max(cap, 1), dtype=np.int64)
            pa = np.empty(max(cap, 1), dtype=np.int64)
            npairs = self._dll.distill_greedy_segmented(
                type_, freqs.ctypes.data_as(f64p),
                aux.ctypes.data_as(f64p),
                seg_bounds.ctypes.data_as(i64p), nseg, tol, max_harm,
                tobs_over_c, int(record_pairs), cap,
                unique.ctypes.data_as(u8p), pf.ctypes.data_as(i64p),
                pa.ctypes.data_as(i64p),
            )
            return npairs, pf, pa

        cap = (16 * n + 1024) if record_pairs else 0
        npairs, pf, pa = run(cap)
        if record_pairs and npairs > cap:
            npairs, pf, pa = run(npairs)
        return unique.astype(bool), pf[:npairs], pa[:npairs]

    def unique_peaks_segmented(self, idxs, snrs, seg_bounds, min_gap):
        idxs = np.ascontiguousarray(idxs, dtype=np.int64)
        snrs = np.ascontiguousarray(snrs, dtype=np.float32)
        seg_bounds = np.ascontiguousarray(seg_bounds, dtype=np.int64)
        nseg = seg_bounds.size - 1
        n = idxs.size
        out_idx = np.empty(n, dtype=np.int64)
        out_snr = np.empty(n, dtype=np.float32)
        out_counts = np.empty(max(nseg, 1), dtype=np.int64)
        i64p = ctypes.POINTER(ctypes.c_int64)
        f32p = ctypes.POINTER(ctypes.c_float)
        nout = self._dll.unique_peaks_segmented(
            idxs.ctypes.data_as(i64p), snrs.ctypes.data_as(f32p),
            seg_bounds.ctypes.data_as(i64p), nseg, min_gap,
            out_idx.ctypes.data_as(i64p), out_snr.ctypes.data_as(f32p),
            out_counts.ctypes.data_as(i64p),
        )
        return out_idx[:nout], out_snr[:nout], out_counts[:nseg]

    def pack_bits(self, samples: np.ndarray, nbits: int) -> np.ndarray:
        samples = np.ascontiguousarray(samples, dtype=np.uint8)
        spb = 8 // nbits
        out = np.empty((samples.size + spb - 1) // spb, dtype=np.uint8)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        self._dll.pack_bits(
            samples.ctypes.data_as(u8p), samples.size, nbits, out.ctypes.data_as(u8p)
        )
        return out


try:
    lib: _NativeLib | None = _NativeLib()
except Exception:  # pragma: no cover - depends on host toolchain
    lib = None
