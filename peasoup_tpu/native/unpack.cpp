// Native bit-unpack/pack helpers for SIGPROC sub-byte data.
//
// TPU-native counterpart of the byte-level unpacking the reference
// delegates to the dedisp CUDA library (dedisperser.hpp:104-112): here
// the unpack runs on the host CPU as part of the IO layer (the TPU
// compute path receives float32/uint8 arrays).
//
// Samples are packed little-endian within each byte: sample k of a byte
// occupies bits [k*nbits, (k+1)*nbits).

#include <cstddef>
#include <cstdint>

extern "C" {

void unpack_bits(const uint8_t* in, size_t nbytes, int nbits, uint8_t* out) {
    const int spb = 8 / nbits;
    const uint8_t mask = static_cast<uint8_t>((1u << nbits) - 1u);
    for (size_t i = 0; i < nbytes; ++i) {
        const uint8_t b = in[i];
        uint8_t* o = out + i * spb;
        for (int k = 0; k < spb; ++k) {
            o[k] = (b >> (k * nbits)) & mask;
        }
    }
}

void pack_bits(const uint8_t* in, size_t nsamples, int nbits, uint8_t* out) {
    const int spb = 8 / nbits;
    const uint8_t mask = static_cast<uint8_t>((1u << nbits) - 1u);
    const size_t nbytes = (nsamples + spb - 1) / spb;
    for (size_t i = 0; i < nbytes; ++i) {
        uint8_t b = 0;
        for (int k = 0; k < spb; ++k) {
            const size_t s = i * spb + k;
            if (s < nsamples) {
                b |= static_cast<uint8_t>((in[s] & mask) << (k * nbits));
            }
        }
        out[i] = b;
    }
}

}  // extern "C"
