"""peasoup-compatible command-line interface.

Flags and defaults match the reference CLI
(`include/utils/cmdline.hpp:69-209`); the default output directory is
``./YYYY-MM-DD-HH:MM_peasoup/`` (UTC), like ``get_utc_str``.
"""

from __future__ import annotations

import argparse
import os
import sys
import time


def default_outdir() -> str:
    return time.strftime("./%Y-%m-%d-%H:%M_peasoup/", time.gmtime())


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="peasoup-tpu",
        description="Peasoup-TPU - a TPU pulsar search pipeline",
    )
    p.add_argument("-i", "--inputfile", required=True, dest="infilename",
                   help="File to process (.fil)")
    p.add_argument("-o", "--outdir", default=None, help="The output directory")
    p.add_argument("-k", "--killfile", default="", dest="killfilename",
                   help="Channel mask file")
    p.add_argument("-z", "--zapfile", default="", dest="zapfilename",
                   help="Birdie list file")
    p.add_argument("-t", "--num_threads", type=int, default=14,
                   dest="max_num_threads",
                   help="The number of devices to use")
    p.add_argument("--limit", type=int, default=1000,
                   help="upper limit on number of candidates to write out")
    p.add_argument("--fft_size", type=int, default=0, dest="size",
                   help="Transform size to use (defaults to lower power of two)")
    p.add_argument("--dm_start", type=float, default=0.0)
    p.add_argument("--dm_end", type=float, default=100.0)
    p.add_argument("--dm_file", default="", dest="dm_file",
                   help="file with one DM trial per line (overrides "
                        "dm_start/dm_end/dm_tol)")
    p.add_argument("--dm_tol", type=float, default=1.10)
    p.add_argument("--dm_pulse_width", type=float, default=64.0)
    p.add_argument("--acc_start", type=float, default=0.0)
    p.add_argument("--acc_end", type=float, default=0.0)
    p.add_argument("--acc_tol", type=float, default=1.10)
    p.add_argument("--acc_step", type=float, default=0.0,
                   help="Fixed acceleration step (the unshipped serial "
                        "driver's 0.5 m/s/s grid, src/pipeline.cpp:287); "
                        "0 = tolerance-stepped DM-dependent grid")
    p.add_argument("--acc_pulse_width", type=float, default=64.0)
    p.add_argument("--jerk_start", type=float, default=0.0,
                   help="jerk (accel-derivative) grid start, m/s^3; "
                        "start=end=0 (default) disables the jerk axis")
    p.add_argument("--jerk_end", type=float, default=0.0,
                   help="jerk grid end, m/s^3")
    p.add_argument("--jerk_step", type=float, default=0.0,
                   help="fixed jerk step, m/s^3 (required nonzero when "
                        "start != end); the grid always includes 0 "
                        "when the range straddles it")
    p.add_argument("--boundary_5_freq", type=float, default=0.05)
    p.add_argument("--boundary_25_freq", type=float, default=0.5)
    p.add_argument("-n", "--nharmonics", type=int, default=4)
    p.add_argument("--npdmp", type=int, default=0)
    p.add_argument("-m", "--min_snr", type=float, default=9.0)
    p.add_argument("--min_freq", type=float, default=0.1)
    p.add_argument("--max_freq", type=float, default=1100.0)
    p.add_argument("--max_harm_match", type=int, default=16, dest="max_harm")
    p.add_argument("--freq_tol", type=float, default=0.0001)
    p.add_argument("-v", "--verbose", action="store_true")
    p.add_argument("-p", "--progress_bar", action="store_true")
    # TPU-build extras
    p.add_argument("--peak_capacity", type=int, default=1024)
    p.add_argument("--accel_chunk", type=int, default=16)
    p.add_argument("--compact_capacity", type=int, default=131072,
                   help="per-shard compacted peak buffer (fused search)")
    p.add_argument("--checkpoint_file", default="",
                   help="candidate checkpoint for crash-resume")
    p.add_argument("--checkpoint_interval", type=int, default=8,
                   help="DM trials between checkpoint saves (host loop)")
    p.add_argument("--tune_file", default="",
                   help="persistent buffer-tuning sidecar: repeat runs "
                        "of the same search size their peak buffers "
                        "from the recorded high-waters (no clipped-row "
                        "re-search, minimal transfers)")
    p.add_argument("--peaks_method", default="auto", dest="peaks_method",
                   choices=("auto", "sort", "two_stage", "pallas"),
                   help="peak-extraction lowering: auto lets the tuner "
                        "pick per (device kind, stop bucket, capacity) "
                        "from measured costs; force sort (full device "
                        "sorts), two_stage (row-reduced top_k) or "
                        "pallas (O(survivors) threshold-compaction "
                        "kernel) for A/B benchmarking — all three "
                        "produce identical candidates")
    p.add_argument("--subband", default="never", dest="subband_dedisp",
                   choices=("auto", "always", "never"),
                   help="two-stage sub-band dedispersion (dedisp's "
                        "algorithm class; sub-sample smearing like "
                        "dedisp itself): auto = use when the DM grid "
                        "is dense enough to compress >= 2x; default "
                        "never = exact direct sweep")
    p.add_argument("--subband_eps", default=0.5, type=float,
                   help="sub-band stage-2 residual smearing bound in "
                        "samples (0 = bit-identical to the direct "
                        "sweep; larger = more anchor compression)")
    p.add_argument("--pipeline_depth", type=int, default=2,
                   help="async dispatch pipeline depth (chunked "
                        "driver): 2 overlaps the next chunk's dispatch "
                        "and the async result fetch with host decode "
                        "(default), 1 is the unpipelined A/B "
                        "reference; candidates are bit-identical at "
                        "every depth")
    p.add_argument("--trial_nbits", type=int, default=32,
                   choices=(8, 32),
                   help="dedispersed trial sample format: 32 keeps f32 "
                        "sums (default; strictly more information), 8 "
                        "reproduces the reference's uint8 trial "
                        "quantisation (dedisp out_nbits=8) exactly")
    p.add_argument("--trial_lattice", default="auto",
                   choices=("auto", "f32", "u8", "bf16"),
                   help="dedispersed trial storage lattice: auto "
                        "(default) consults the tuner sidecar's "
                        "parity-gated pick for this device/geometry "
                        "and falls back to f32; f32/u8/bf16 force a "
                        "dtype (u8 requires nbits<=8 input)")
    p.add_argument("--measure_stages",
                   action=argparse.BooleanOptionalAction, default=False,
                   help="clock a dedicated dedispersion dispatch so "
                        "overview.xml <execution_times> carries real "
                        "per-stage numbers (the mesh programs fuse "
                        "dedispersion into the search dispatch); off by "
                        "default — it costs one extra dispatch")
    p.add_argument("--no_compile_cache", action="store_true",
                   help="disable the persistent XLA compilation cache "
                        "(default cache dir: $PEASOUP_XLA_CACHE or "
                        "~/.cache/peasoup_tpu/xla)")
    p.add_argument("--no_lineage", action="store_true",
                   help="disable the candidate-provenance ledger "
                        "(<outdir>/lineage.jsonl records every "
                        "selection decision for the `why` verb; "
                        "candidate output is bit-identical either way)")
    p.add_argument("--dump_dir", default="",
                   help="Dump per-DM-trial whitening stages (power "
                        "spectrum, running median, whitened series) as "
                        ".npy for golden-file debugging")
    p.add_argument("--profile_dir", default="",
                   help="capture a jax.profiler trace into this directory")
    p.add_argument("--events_log", default="",
                   help="structured JSONL event log (peak-buffer "
                        "overflows, escalations, checkpoint/tune I/O "
                        "failures, ...); default: <outdir>/events.jsonl")
    p.add_argument("--metrics_json", default="",
                   help="machine-readable end-of-run report (stage "
                        "timers with host/device split, counters, "
                        "event summary, device + HBM figures); "
                        "default: <outdir>/run_report.json")
    p.add_argument("--trace_json", default="",
                   help="Chrome trace-event JSON of the run's span "
                        "tree (per-chunk/per-trial attribution, HBM "
                        "watermarks; open in Perfetto or "
                        "chrome://tracing, or summarise with "
                        "python -m peasoup_tpu.tools.trace_report); "
                        "multihost runs write one merged trace from "
                        "process 0; default: <outdir>/trace.json")
    p.add_argument("--single_device", action="store_true",
                   help="disable mesh sharding even with multiple devices")
    return p


def args_to_config(args):
    from .search.plan import SearchConfig

    cfg = SearchConfig()
    for key in vars(args):
        if hasattr(cfg, key) and getattr(args, key) is not None:
            setattr(cfg, key, getattr(args, key))
    if args.outdir is None:
        cfg.outdir = default_outdir()
    return cfg


def write_search_output(result, outdir: str) -> dict:
    """Write candidates.peasoup + overview.xml + run_report.json for a
    SearchResult; returns the run-report dict (obs/report.py)."""
    from .obs.report import write_run_report
    from .output.binary import write_candidate_binary
    from .output.xml_writer import OutputFileWriter

    os.makedirs(outdir, exist_ok=True)
    cfg = result.config
    report_path = (getattr(cfg, "metrics_json", "") or
                   os.path.join(outdir, "run_report.json"))
    injection = getattr(result, "injection", None)
    report = write_run_report(
        report_path, result,
        extra=({"injection": injection} if injection else None))
    byte_mapping = write_candidate_binary(
        result.candidates, os.path.join(outdir, "candidates.peasoup")
    )
    writer = OutputFileWriter()
    writer.add_misc_info()
    writer.add_header(result.header)
    writer.add_search_parameters(result.config)
    writer.add_dm_list(result.dm_list)
    writer.add_acc_list(result.acc_list_dm0)
    writer.add_device_info()
    prov = getattr(result, "provenance", None)
    if prov:
        writer.add_provenance(prov)
        from .obs.lineage import candidate_uid

        cand_ids = [candidate_uid(prov.get("run", ""), c)
                    for c in result.candidates]
    else:
        cand_ids = None
    writer.add_candidates(result.candidates, byte_mapping,
                          cand_ids=cand_ids)
    writer.add_timing_info(result.timers)
    writer.add_telemetry(report)
    writer.to_file(os.path.join(outdir, "overview.xml"))
    return report


def main(argv=None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    # subcommand dispatch: `peasoup-tpu coincidencer <filterbanks...>`
    if argv and argv[0] == "coincidencer":
        return coincidencer_main(argv[1:])
    if argv and argv[0] == "accmap":
        return accmap_main(argv[1:])
    # `peasoup-tpu obs query|top|tail|diff|baseline|ingest` — the
    # flight-recorder verb family (ISSUE 16)
    if argv and argv[0] == "obs":
        from .obs.cli import main as obs_main

        return obs_main(argv[1:])
    args = build_parser().parse_args(argv)
    cfg = args_to_config(args)
    if not args.no_compile_cache:
        from .utils import enable_compile_cache

        enable_compile_cache()
    # telemetry sinks, live BEFORE the run so events stream as they
    # happen (a crash still leaves the JSONL trail on disk)
    from .obs.events import configure_event_log
    from .obs.metrics import install_compile_hook
    from .obs.trace import get_tracer

    install_compile_hook()
    os.makedirs(cfg.outdir, exist_ok=True)
    configure_event_log(
        cfg.events_log or os.path.join(cfg.outdir, "events.jsonl"))
    # geometry-keyed compile ledger (ISSUE 18): every backend compile
    # this run pays lands in <outdir>/compiles.jsonl attributed to the
    # search geometry (`peasoup-tpu obs compiles` reads it back)
    from .obs.compilation import (
        configure_compile_ledger,
        install_compile_ledger,
    )

    configure_compile_ledger(os.path.join(cfg.outdir, "compiles.jsonl"))
    install_compile_ledger()
    # candidate provenance ledger (ISSUE 19): every selection decision
    # between peak decode and the emitted candidate list, keyed by a
    # run id = the observation basename (`peasoup-tpu obs why` and the
    # serve `why` verb reconstruct decision chains from it)
    from .obs import lineage

    cfg.lineage_run = os.path.basename(cfg.infilename)
    lineage.configure_lineage(
        "" if args.no_lineage
        else os.path.join(cfg.outdir, "lineage.jsonl"))
    # per-run span tree: the trace file must describe THIS run, not
    # every run of a long-lived process
    get_tracer().reset()
    import time as _time

    t_total = _time.time()
    t0 = _time.time()
    from .io import read_filterbank

    fil = read_filterbank(cfg.infilename)
    t_read = _time.time() - t0

    if args.verbose:
        print(f"Read {cfg.infilename}: {fil.nsamps} samples x "
              f"{fil.nchans} chans, {fil.header.nbits}-bit", file=sys.stderr)

    import jax

    from .search.pipeline import PulsarSearch

    # The fused mesh program is the default even on one device: a
    # single dispatch + compact transfer beats the per-DM host loop by
    # an order of magnitude on remote-attached accelerators.
    if args.single_device:
        search = PulsarSearch(fil, cfg)
    else:
        from .parallel.mesh import MeshPulsarSearch

        search = MeshPulsarSearch(
            fil, cfg, max_devices=args.max_num_threads
        )
    if args.profile_dir:
        from .utils import start_trace

        start_trace(args.profile_dir)
    try:
        result = search.run()
    finally:
        if args.profile_dir:
            from .utils import stop_trace

            stop_trace()
    result.timers["reading"] = t_read
    result.timers["total"] = _time.time() - t_total
    report = write_search_output(result, cfg.outdir)
    # span trace LAST (it covers the output-writing tail too); on
    # multihost runs every process gathers, process 0 writes the merge
    from .obs.trace import write_merged_trace

    trace_path = write_merged_trace(
        cfg.trace_json or os.path.join(cfg.outdir, "trace.json"))
    if args.verbose:
        from .obs.report import format_stage_table

        print(format_stage_table(report), file=sys.stderr)
        if trace_path:
            print(f"Wrote span trace to {trace_path} (open in Perfetto "
                  f"or summarise with python -m "
                  f"peasoup_tpu.tools.trace_report)", file=sys.stderr)
        print(f"Wrote {len(result.candidates)} candidates to {cfg.outdir}",
              file=sys.stderr)
    return 0


def accmap_main(argv=None) -> int:
    """Inter-antenna delay finder CLI over ``ops.correlate.find_delays``.

    Equivalent of the reference's ``bin/accmap`` (`src/accmap.cpp`),
    which is broken in-tree (hardcoded DADA path, missing dada.hpp);
    this version reads the same payload layout — per antenna, ``size``
    interleaved complex8 (int8 re, int8 im) samples of one channel —
    from a raw binary file and prints one line per baseline.
    """
    import argparse

    import numpy as np

    p = argparse.ArgumentParser(
        prog="peasoup-tpu-accmap",
        description="Peasoup-TPU - FFT cross-correlation delay finder",
    )
    p.add_argument("datafile", help="raw int8 file: nant x size x 2 "
                                    "(interleaved re/im)")
    p.add_argument("--nant", type=int, default=2)
    p.add_argument("--size", type=int, default=65536,
                   help="samples per antenna (accmap.cpp:13)")
    p.add_argument("--max_delay", type=int, default=2048,
                   help="correlation search window (accmap.cpp:27)")
    args = p.parse_args(argv)

    raw = np.fromfile(args.datafile, dtype=np.int8)
    need = args.nant * args.size * 2
    if raw.size < need:
        print(f"error: {args.datafile} holds {raw.size} bytes; need "
              f"{need} for nant={args.nant} size={args.size}",
              file=sys.stderr)
        return 1
    z = raw[:need].reshape(args.nant, args.size, 2).astype(np.float32)
    arrays = z[..., 0] + 1j * z[..., 1]
    from .ops.correlate import find_delays

    for rec in find_delays(arrays, args.max_delay):
        print(f"baseline {rec['i']}-{rec['j']}: lag {rec['lag']} "
              f"samples  power {rec['power']:.3f}")
    return 0


def coincidencer_main(argv=None) -> int:
    """Multibeam RFI coincidencer CLI (`src/coincidencer.cpp:46-120`)."""
    p = argparse.ArgumentParser(
        prog="peasoup-tpu-coincidencer",
        description="Peasoup-TPU - multibeam RFI coincidencer",
    )
    p.add_argument("filterbanks", nargs="+", help="File names")
    p.add_argument("--o", dest="samp_outfilename", default="rfi.eb_mask",
                   help="Sample mask output filename")
    p.add_argument("--o2", dest="spec_outfilename", default="birdies.txt",
                   help="Birdie list output filename")
    p.add_argument("-l", "--boundary_5_freq", type=float, default=0.05)
    p.add_argument("-a", "--boundary_25_freq", type=float, default=0.5)
    p.add_argument("--thresh", type=float, default=4.0,
                   help="S/N threshold for coincidence matching")
    p.add_argument("--beam_thresh", type=int, default=4,
                   help="number of beams a candidate must appear in")
    p.add_argument("-v", "--verbose", action="store_true")
    args = p.parse_args(argv)

    from .search.coincidence import CoincidencerConfig, run_coincidencer

    cfg = CoincidencerConfig(
        samp_outfilename=args.samp_outfilename,
        spec_outfilename=args.spec_outfilename,
        boundary_5_freq=args.boundary_5_freq,
        boundary_25_freq=args.boundary_25_freq,
        threshold=args.thresh,
        beam_threshold=args.beam_thresh,
        verbose=args.verbose,
    )
    run_coincidencer(args.filterbanks, cfg)
    if args.verbose:
        print(f"Wrote {cfg.samp_outfilename} and {cfg.spec_outfilename}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
