"""Candidate scoring.

Exact semantics of `include/transforms/scorer.hpp:8-87`:

* ``is_physical``: period longer than the per-channel DM smear delay
  (note the reference evaluates ``8300 * foff / cfreq^3`` with the raw,
  typically negative, ``foff`` — reproduced faithfully);
* ``is_adjacent``: an associated detection exists in a neighbouring DM
  trial, or all associations share this DM trial;
* ``ddm_count_ratio`` / ``ddm_snr_ratio``: fraction of associated
  detections (and their SNR) within the expected DM width of the
  candidate.
"""

from __future__ import annotations

from ..data.candidates import Candidate


class CandidateScorer:
    def __init__(self, tsamp: float, cfreq: float, foff: float, bw: float):
        self.tsamp = tsamp
        self.cfreq = cfreq
        self.foff = foff
        ftop = cfreq + bw / 2.0
        fbottom = cfreq - bw / 2.0
        self.tdm_chan_partial = 8300.0 * foff / cfreq ** 3
        self.tdm_band_partial = 4150.0 * (1.0 / fbottom ** 2 - 1.0 / ftop ** 2)

    def _has_physical_period(self, cand: Candidate) -> bool:
        return 1.0 / cand.freq > cand.dm * self.tdm_chan_partial

    def _has_adjacency(self, cand: Candidate) -> bool:
        idx = cand.dm_idx
        adjacent = False
        unique = True
        for a in cand.assoc:
            if a.dm_idx != idx:
                unique = False
            if a.dm_idx in (idx + 1, idx - 1):
                adjacent = True
                break
        return adjacent or unique

    def _delta_dm_ratio(self, cand: Candidate) -> None:
        inside_count = total_count = 1
        inside_snr = total_snr = cand.snr
        ddm = 1.0 / (cand.freq * self.tdm_band_partial)
        for a in cand.assoc:
            total_count += 1
            total_snr += a.snr
            if abs(cand.dm - a.dm) <= ddm:
                inside_count += 1
                inside_snr += a.snr
        cand.ddm_count_ratio = inside_count / total_count
        # C float semantics (`scorer.hpp:62`): 0/0 is a quiet NaN, not
        # a crash — an all-zero-snr family scores nan like the
        # reference would
        cand.ddm_snr_ratio = (
            inside_snr / total_snr if total_snr != 0.0 else float("nan")
        )

    def score(self, cand: Candidate) -> None:
        cand.is_physical = self._has_physical_period(cand)
        cand.is_adjacent = self._has_adjacency(cand)
        self._delta_dm_ratio(cand)

    def score_all(self, cands: list[Candidate], on_score=None) -> None:
        """Score every candidate in place.

        ``on_score(cand, flags)`` — the lineage annotation hook
        (ISSUE 19) — fires after each candidate's verdict with its
        flag dict, so the provenance ledger records why a `why` query
        shows the folds/limit treating it the way they did.  Scoring
        annotates only (`scorer.hpp` never drops candidates): the
        marks are annotations, not terminal states.
        """
        for c in cands:
            self.score(c)
            if on_score is not None:
                on_score(c, {
                    "is_physical": bool(c.is_physical),
                    "is_adjacent": bool(c.is_adjacent),
                    "ddm_count_ratio": round(
                        float(c.ddm_count_ratio), 6),
                    "ddm_snr_ratio": round(
                        float(c.ddm_snr_ratio), 6),
                })
