"""Multibeam coincidencer pipeline (`src/coincidencer.cpp:46-215`).

Dedisperse every beam's filterbank at DM=0 (a plain channel sum, as in
the reference), whiten + normalise each beam's time series and interbinned
spectrum, then coincidence-match across beams: bins hot in at least
``beam_thresh`` beams are multibeam RFI.  Outputs a 0/1 sample mask and
a birdie list consumable by the search's ``--zapfile``.

TPU design: all beams are one (nbeams, size) batch; the per-beam
baselining chain is vmapped inside a single jitted program, and both
coincidence matches are reductions over the beam axis — the reference's
per-beam GPU loop (`coincidencer.cpp:163-180`) collapses into one
dispatch.  Unlike the search, the FFT length is the full ``nsamps``
(not a power of two), as in the reference (`coincidencer.cpp:136`).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import numpy as np
import jax
import jax.numpy as jnp

from ..errors import InputFileError
from ..io.sigproc import read_filterbank
from ..ops import (
    deredden,
    form_interpolated,
    form_power,
    mean_rms_std,
    running_median,
)
from ..ops.coincidence import (
    coincidence_mask,
    write_birdie_list,
    write_samp_mask,
)


@dataclass
class CoincidencerConfig:
    samp_outfilename: str = "rfi.eb_mask"
    spec_outfilename: str = "birdies.txt"
    boundary_5_freq: float = 0.05
    boundary_25_freq: float = 0.5
    threshold: float = 4.0
    beam_threshold: int = 4
    verbose: bool = False


def _baseline_beam(tim, bin_width, b5, b25):
    """Whiten + normalise one beam (`coincidencer.cpp:163-180`):
    rfft -> plain spectrum -> running median -> deredden -> interbin
    spectrum (normalised) -> irfft time series (normalised)."""
    size = tim.shape[0]
    fs = jnp.fft.rfft(tim.astype(jnp.float32)).astype(jnp.complex64)
    pspec = form_power(fs)
    median = running_median(pspec, bin_width, b5, b25)
    fs = deredden(fs, median)
    spec = form_interpolated(fs)
    mean, _, std = mean_rms_std(spec)
    spec = ((spec - mean) / std).astype(jnp.float32)
    tim2 = jnp.fft.irfft(fs, n=size).astype(jnp.float32)
    mean, _, std = mean_rms_std(tim2)
    tim2 = ((tim2 - mean) / std).astype(jnp.float32)
    return tim2, spec


@partial(
    jax.jit,
    static_argnames=("bin_width", "b5", "b25", "thresh", "beam_thresh"),
)
def coincidencer_program(tims, bin_width, b5, b25, thresh, beam_thresh):
    """(nbeams, size) DM=0 time series -> (samp_mask, spec_mask)."""
    tims_n, specs = jax.vmap(
        lambda t: _baseline_beam(t, bin_width, b5, b25)
    )(tims)
    samp_mask = coincidence_mask(tims_n, thresh, beam_thresh)
    spec_mask = coincidence_mask(specs, thresh, beam_thresh)
    return samp_mask, spec_mask


def dedisperse_dm0(fil) -> np.ndarray:
    """DM=0 trial: killmask-free channel sum (zero delays)."""
    return np.asarray(fil.data, np.float32).sum(axis=1)


def run_coincidencer(
    filenames: list[str], cfg: CoincidencerConfig
) -> tuple[np.ndarray, np.ndarray, float]:
    """Full coincidencer; returns (samp_mask, spec_mask, bin_width)."""
    tims = []
    # tsamp comes from the FIRST beam like the reference
    # (`src/coincidencer.cpp` uses filobjs[0]); mismatched beams would
    # silently skew bin_width, so they are an error here
    tsamp = None
    for fn in filenames:
        if cfg.verbose:
            print(f"Reading and dedispersing {fn}")
        fil = read_filterbank(fn)
        tims.append(dedisperse_dm0(fil))
        if tsamp is None:
            tsamp = float(fil.tsamp)
        elif float(fil.tsamp) != tsamp:
            raise InputFileError(
                f"tsamp mismatch across beams: {fn} has {fil.tsamp}, "
                f"first beam has {tsamp}"
            )
    size = len(tims[0])
    for fn, t in zip(filenames, tims):
        if len(t) != size:
            raise InputFileError(
                f"Not all filterbanks the same length: {fn}"
            )
    bin_width = 1.0 / (size * tsamp)
    if cfg.verbose:
        print("Performing cross beam coincidence matching")
    samp_mask, spec_mask = coincidencer_program(
        jnp.asarray(np.stack(tims)), bin_width,
        cfg.boundary_5_freq, cfg.boundary_25_freq,
        cfg.threshold, cfg.beam_threshold,
    )
    samp_mask = np.asarray(samp_mask)
    spec_mask = np.asarray(spec_mask)
    write_samp_mask(samp_mask, cfg.samp_outfilename)
    write_birdie_list(spec_mask, bin_width, cfg.spec_outfilename)
    return samp_mask, spec_mask, bin_width
