"""The end-to-end pulsar search pipeline.

TPU-native re-design of `src/pipeline_multi.cu`: instead of a pthread
worker pool dispensing one DM trial at a time to each GPU
(`pipeline_multi.cu:33-81,100-252`), the whole per-DM whitening chain
and the acceleration-trial loop are jitted XLA programs — the accel
axis is a vmapped batch axis processed in chunks — and the DM axis is a
host loop here (or a sharded mesh axis in ``peasoup_tpu.parallel``).

Per-DM chain (reference walk-through at `pipeline_multi.cu:145-244`):
rfft -> plain power spectrum -> running-median -> deredden -> [zap] ->
interbin spectrum -> stats -> irfft, then per accel trial: resampleII ->
rfft -> interbin -> normalise -> harmonic sums -> thresholded peaks.

Scaling note: cuFFT's unnormalised C2R multiplies the reference's
whitened series by ``size``, which it undoes by normalising spectra
with (mean*size, std*size) (`pipeline_multi.cu:224`).  jnp's irfft is
normalised, so plain (mean, std) give the identical normalised spectra.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from functools import partial

import numpy as np
import jax
import jax.numpy as jnp

from ..data.candidates import Candidate, CandidateCollection
from ..errors import ConfigError
from ..io.sigproc import Filterbank
from ..obs import lineage
from ..obs.events import warn_event
from ..obs.metrics import REGISTRY as METRICS
from ..obs.trace import device_seconds, span, span_cursor
from ..ops import (
    dedisperse,
    delay_table,
    delays_in_samples,
    extract_top_peaks,
    form_interpolated,
    form_power,
    generate_dm_list,
    harmonic_sums,
    identify_unique_peaks,
    max_delay,
    mean_rms_std,
    resample,
    resample2,
    running_median,
    spectrum_search_bounds,
    zap_birdies,
    deredden,
)
from ..ops.fold import (
    finalise_fold,
    fold_time_series_core,
    optimise_device,
)
from .distill import (
    AccelerationDistiller,
    DMDistiller,
    HarmonicDistiller,
    JerkDistiller,
)
from .plan import (
    FOLD_NBINS,
    FOLD_NINTS,
    AccelerationPlan,
    JerkPlan,
    SearchConfig,
    combine_trials,
    prev_power_of_two,
)
from .score import CandidateScorer


# --------------------------------------------------------------------------
# jitted building blocks
# --------------------------------------------------------------------------

def whiten_core(tim, birdies, widths, bin_width, b5, b25, use_zap):
    """Whiten one DM trial; returns (whitened tim, mean, std).

    ``bin_width`` is static: it only depends on the fft size and tsamp,
    and the running-median splice positions derive from it.
    """
    fseries = jnp.fft.rfft(tim.astype(jnp.float32))
    fseries = fseries.astype(jnp.complex64)
    pspec = form_power(fseries)
    median = running_median(pspec, bin_width, b5, b25)
    fseries = deredden(fseries, median)
    if use_zap:
        fseries = zap_birdies(fseries, birdies, widths, bin_width)
    pspec_i = form_interpolated(fseries)
    mean, _, std = mean_rms_std(pspec_i)
    tim_w = jnp.fft.irfft(fseries, n=tim.shape[0]).astype(jnp.float32)
    return tim_w, mean, std


whiten_trial = jax.jit(
    whiten_core, static_argnames=("bin_width", "b5", "b25", "use_zap")
)

#: module-level jit of the channel-scan dedispersion for the EAGER
#: (host-loop) driver.  Calling ``ops.dedisperse.dedisperse`` eagerly
#: recompiles on every call — its ``lax.scan`` body is a fresh closure
#: per call, so jax's tracing cache never hits (the compile ledger of
#: ISSUE 18 surfaced this as one recompile per warm job).  A stable
#: module-level jit keys the cache on THIS function object + shapes,
#: so same-geometry jobs replay the compiled program.  The fused mesh
#: path is unaffected (it traces ``dedisperse`` inside its own jit).
dedisperse_trials = jax.jit(dedisperse, static_argnums=(2,))


def dump_whiten_stages(dump_dir, idx, tim, birdies, widths, bin_width,
                       b5, b25, use_zap) -> None:
    """``--dump_dir`` debug hook (`Utils::dump_device_buffer`,
    `include/utils/utils.hpp:62-72`): re-derive and save the whitening
    chain's intermediates for one DM trial as .npy, enabling the
    reference's golden-file debugging workflow
    (`src/rednoise_test.cpp:84-102`) without ad-hoc scripts."""
    import os

    os.makedirs(dump_dir, exist_ok=True)
    fseries = jnp.fft.rfft(tim.astype(jnp.float32)).astype(jnp.complex64)
    pspec = form_power(fseries)
    median = running_median(pspec, bin_width, b5, b25)
    fseries_d = deredden(fseries, median)
    if use_zap:
        fseries_d = zap_birdies(fseries_d, birdies, widths, bin_width)
    pspec_i = form_interpolated(fseries_d)
    tim_w = jnp.fft.irfft(fseries_d, n=tim.shape[0]).astype(jnp.float32)
    for name, arr in (
        ("tim", tim), ("pspec", pspec), ("median", median),
        ("interp_spec", pspec_i), ("tim_white", tim_w),
    ):
        np.save(os.path.join(dump_dir, f"trial{idx:04d}_{name}.npy"),
                np.asarray(arr))


def _pallas_mode() -> str | None:
    """How the pallas peak-compaction kernel can run on this backend:
    "compiled" on TPU, else None — interpret mode is never auto-picked
    (it is the CPU test vehicle, ~100x the compiled kernel), so the
    probe is not even run on the default path."""
    try:
        return "compiled" if jax.devices()[0].platform == "tpu" else None
    except Exception:
        return None


def resample_block_for(n: int, max_shift: int, width_fn=None) -> int | None:
    """Block size for the table-driven resampler: the largest power of
    two dividing ``n``, capped at 16384 (the measured sweet spot on
    v5e).  None if ``n`` has no useful power-of-two factor, or the
    shift is outside the staircase tables' validity domain
    (4*max_shift >= n) — the legacy on-device path handles both.

    ``width_fn``: optional block -> residual-table width; jerk-axis
    searches pass ``residual_width_jerk`` at their global accel/jerk
    bounds (the accel-only ``residual_width`` underestimates once the
    cubic term contributes drift)."""
    from ..ops.resample import residual_width

    if 4 * max_shift >= n:
        return None  # table bisection invalid (see _staircase_tables_np)
    b = n & -n  # largest power-of-two divisor
    b = min(b, 16384)
    if b < 128:
        return None
    if width_fn is None:
        width_fn = lambda blk: residual_width(max_shift, blk, n)
    # keep the per-block residual table narrow even for huge shifts
    while width_fn(b) > 18 and b > 128:
        b //= 2
    return b


def _spectra_peaks(tim_r, mean, std, nharms, bounds, capacity, min_snr,
                   methods=None):
    fs = jnp.fft.rfft(tim_r).astype(jnp.complex64)
    pspec = form_interpolated(fs)
    pspec = ((pspec - mean) / std).astype(jnp.float32)
    spectra = [pspec] + harmonic_sums(pspec, nharms)
    idxs, snrs, counts = [], [], []
    # value-ordered extraction (slots descend by SNR, not bin index;
    # the pallas compaction lowering instead ascends by index) —
    # every consumer sorts segments host-side before the peak merge.
    # ``methods``: one concrete extraction lowering per harmonic
    # level, resolved by search/tuning.py OUTSIDE the trace; None
    # keeps ops/peaks.py's size heuristic
    if methods is None:
        methods = ("auto",) * len(bounds)
    for spec, (start, stop, _f), meth in zip(spectra, bounds, methods):
        i, s, c = extract_top_peaks(spec, min_snr, start, stop, capacity,
                                    method=meth)
        idxs.append(i)
        snrs.append(s)
        counts.append(c)
    return jnp.stack(idxs), jnp.stack(snrs), jnp.stack(counts)


def search_one_accel(tim_w, rtab, mean, std, tsamp, nharms, bounds, capacity,
                     min_snr, max_shift, block, methods=None):
    from ..ops.resample import resample2_from_tables

    d0, pos_t, step_t = rtab
    tim_r = resample2_from_tables(tim_w, d0, pos_t, step_t, max_shift,
                                  block=block)
    return _spectra_peaks(tim_r, mean, std, nharms, bounds, capacity,
                          min_snr, methods)


@partial(
    jax.jit,
    static_argnames=(
        "tsamp", "nharms", "bounds", "capacity", "min_snr", "max_shift",
        "block", "methods",
    ),
)
def search_accel_chunk(tim_w, rtabs, mean, std, tsamp, nharms, bounds,
                       capacity, min_snr, max_shift, block, methods=None):
    """vmapped acceleration-trial batch: per-accel host-exact resample
    tables (d0[A,nb], pos[A,nb,m], step[A,nb,m]) -> peak buffers."""
    fn = lambda t: search_one_accel(
        tim_w, t, mean, std, tsamp, nharms, bounds, capacity, min_snr,
        max_shift, block, methods,
    )
    return jax.vmap(fn)(rtabs)


def search_one_accel_legacy(tim_w, accel, mean, std, tsamp, nharms, bounds,
                            capacity, min_snr, max_shift=None,
                            methods=None, jerk=0.0):
    """On-device index math fallback for fft sizes with no power-of-two
    factor (no host tables).  NB: on real TPU hardware the emulated-f64
    rint is inexact for a small fraction of indices; the table path is
    exact and preferred."""
    tim_r = resample2(tim_w, accel, tsamp, max_shift, jerk=jerk)
    return _spectra_peaks(tim_r, mean, std, nharms, bounds, capacity,
                          min_snr, methods)


@partial(
    jax.jit,
    static_argnames=(
        "tsamp", "nharms", "bounds", "capacity", "min_snr", "max_shift",
        "methods",
    ),
)
def search_accel_chunk_legacy(tim_w, accels, mean, std, tsamp, nharms,
                              bounds, capacity, min_snr, max_shift=None,
                              methods=None, jerks=None):
    # ``jerks=None`` keeps the accel-only trace (and its compiled
    # program) byte-identical to the pre-jerk build; a jerk-axis search
    # passes the per-trial jerks alongside the accels
    if jerks is None:
        fn = lambda a: search_one_accel_legacy(
            tim_w, a, mean, std, tsamp, nharms, bounds, capacity, min_snr,
            max_shift, methods,
        )
        return jax.vmap(fn)(accels)
    fn = lambda a, j: search_one_accel_legacy(
        tim_w, a, mean, std, tsamp, nharms, bounds, capacity, min_snr,
        max_shift, methods, j,
    )
    return jax.vmap(fn)(accels, jerks)


# --------------------------------------------------------------------------
# host orchestration
# --------------------------------------------------------------------------

@dataclass
class SearchResult:
    candidates: CandidateCollection
    dm_list: np.ndarray
    acc_list_dm0: np.ndarray
    timers: dict = field(default_factory=dict)
    config: SearchConfig | None = None
    header: object | None = None
    # per-stage SNR budget of the injected signal when the config named
    # an injection manifest (obs/injection.py, ISSUE 14); None otherwise
    injection: dict | None = None
    # provenance block (obs/lineage.py, ISSUE 19): run id, git sha,
    # geometry fingerprint, resolved trial lattice, host — stamped
    # into store records and overview.xml so a candidate's origin is
    # reconstructible from either artifact alone
    provenance: dict | None = None


class PulsarSearch:
    """Single-host search driver (multi-device version in parallel/)."""

    def __init__(self, fil: Filterbank, config: SearchConfig):
        self.fil = fil
        self.config = config
        hdr = fil.header
        if config.dm_list is not None:
            # ``dedisp_set_dm_list`` equivalent (`dedisperser.hpp:34-48`)
            self.dm_list = np.asarray(config.dm_list, dtype=np.float32)
        elif config.dm_file:
            self.dm_list = load_dm_file(config.dm_file)
        else:
            self.dm_list = generate_dm_list(
                config.dm_start, config.dm_end, hdr.tsamp,
                config.dm_pulse_width, hdr.fch1, hdr.foff, fil.nchans,
                config.dm_tol,
            )
        if len(self.dm_list) == 0:
            raise ConfigError("empty DM trial list")
        self.delay_tab = delay_table(fil.nchans, hdr.tsamp, hdr.fch1, hdr.foff)
        self.delays = delays_in_samples(self.dm_list, self.delay_tab)
        self.max_delay = max_delay(self.dm_list, self.delay_tab)
        self.out_nsamps = fil.nsamps - self.max_delay
        self.size = config.size or prev_power_of_two(fil.nsamps)
        self.tobs = self.size * hdr.tsamp
        self.bin_width = 1.0 / self.tobs
        if config.trial_nbits not in (8, 32):
            raise ConfigError(
                f"trial_nbits={config.trial_nbits}: use 32 (f32 sums, "
                f"default) or 8 (dedisp's uint8 lattice)")
        if config.trial_nbits == 8 and hdr.nbits > 8:
            raise ConfigError(
                "trial_nbits=8 needs an integer (<=8-bit) input "
                "filterbank: dedisp's scale uses the input dynamic "
                "range (dedisperser.hpp:104-112)")
        if config.acc_step < 0:
            raise ConfigError(
                f"acc_step={config.acc_step} must be positive (the "
                f"serial driver's grid steps upward from acc_start)"
            )
        if config.acc_step > 0:
            from .plan import FixedAccelerationPlan

            self.acc_plan = FixedAccelerationPlan(
                config.acc_start, config.acc_end, config.acc_step,
            )
        else:
            self.acc_plan = AccelerationPlan(
                config.acc_start, config.acc_end, config.acc_tol,
                config.acc_pulse_width, self.size, hdr.tsamp, hdr.cfreq,
                hdr.foff,
            )
        from ..ops.resample import resample2_max_shift

        # jerk axis (ISSUE 13): a fixed-step, DM-independent grid
        # combined with every per-DM accel list (plan.combine_trials);
        # the default (0, 0, 0) plan has exactly one zero-jerk trial
        # and leaves every accel-only code path structurally untouched
        self.jerk_plan = JerkPlan(
            config.jerk_start, config.jerk_end, config.jerk_step)
        max_acc = max(abs(config.acc_start), abs(config.acc_end))
        self.max_shift = resample2_max_shift(
            max_acc, hdr.tsamp, self.size,
            max_jerk=self.jerk_plan.max_abs,
        )
        #: static residual-table width for jerk-axis table builds: ONE
        #: global bound (config-level max |accel| and |jerk|) so every
        #: DM row's tables — and the chunked drivers' scan steps —
        #: share a single shape; None on the accel-only path, whose
        #: bisection builder stays bit-identical to the pre-jerk build
        self.table_width = None
        if self.jerk_plan.max_abs > 0.0:
            from ..ops.resample import residual_width_jerk

            width_fn = lambda blk: residual_width_jerk(
                max_acc, self.jerk_plan.max_abs, hdr.tsamp, blk,
                self.size)
            self.resample_block = resample_block_for(
                self.size, self.max_shift, width_fn=width_fn)
            if self.resample_block is not None:
                self.table_width = width_fn(self.resample_block)
        else:
            self.resample_block = resample_block_for(
                self.size, self.max_shift)
        # trial lattice (ISSUE 13): resolve "auto" to a concrete dtype
        # ONCE, outside any trace — via the parity-gated tuner sidecar
        # (search/tuning.py), falling back to f32.  The legacy
        # trial_nbits=8 flag is an explicit u8 force (validated above).
        forced_lattice = config.trial_lattice
        if config.trial_nbits == 8 and forced_lattice in ("auto", "f32"):
            forced_lattice = "u8"
        from .tuning import resolve_trial_lattice

        self.lattice = resolve_trial_lattice(
            forced_lattice, sidecar=config.tune_file,
            stage="dedisperse", nsamps=self.out_nsamps)
        if self.lattice == "u8" and hdr.nbits > 8:
            if config.trial_lattice == "u8":
                raise ConfigError(
                    "trial_lattice=u8 needs an integer (<=8-bit) input "
                    "filterbank: the u8 staircase scales by the input "
                    "dynamic range (same constraint as trial_nbits=8)")
            # stale sidecar pick for a float input: refuse it loudly
            warn_event(
                "lattice_fallback",
                f"ignoring tuner lattice pick 'u8' for a "
                f"{hdr.nbits}-bit input; using f32",
                picked="u8", nbits=int(hdr.nbits),
            )
            self.lattice = "f32"
        self.killmask = None
        if config.killfilename:
            self.killmask = load_killmask(config.killfilename, fil.nchans)
        self.birdies = np.zeros((0,), np.float32)
        self.bwidths = np.zeros((0,), np.float32)
        if config.zapfilename:
            from ..ops.zap import load_zaplist

            zl = load_zaplist(config.zapfilename)
            self.birdies = zl[:, 0].copy()
            self.bwidths = zl[:, 1].copy()
        nh_levels = range(config.nharmonics + 1)
        self.bounds = tuple(
            spectrum_search_bounds(
                self.size // 2 + 1, self.bin_width, nh,
                config.min_freq, config.max_freq,
            )
            for nh in nh_levels
        )

    def _data_bytes(self) -> int:
        """Device-resident footprint of the raw filterbank (the mesh
        drivers keep it in HBM across runs)."""
        itemsize = 1 if self.fil.header.nbits <= 8 else 4
        return self.fil.nchans * self.fil.nsamps * itemsize

    # -- peak-extraction method selection (ISSUE 6) -------------------------

    def peaks_methods_for(self, capacity: int) -> tuple:
        """Concrete extraction lowering per harmonic level at this
        peak-buffer capacity (search/tuning.py: forced config value,
        else measured sidecar/default costs, else size heuristic).
        Resolved OUTSIDE the jitted programs and passed down as a
        static arg; cached per capacity (escalation re-resolves)."""
        cache = self.__dict__.setdefault("_peaks_methods_cache", {})
        got = cache.get(capacity)
        if got is None:
            from .tuning import resolve_peaks_methods

            got = resolve_peaks_methods(
                self.bounds, capacity,
                forced=self.config.peaks_method,
                sidecar=self.config.tune_file,
                pallas_ok=_pallas_mode(),
            )
            for m in got:
                METRICS.inc(f"peaks.method_{m}")
            cache[capacity] = got
        return got

    def record_peaks_selection(self, capacity: int | None = None) -> None:
        """Audit the picked path per (device kind, stop bucket,
        capacity) into the tune sidecar (once per run)."""
        cfg = self.config
        if not cfg.tune_file:
            return
        from .tuning import record_peaks_choices

        cap = int(capacity or cfg.peak_capacity)
        record_peaks_choices(cfg.tune_file, self.bounds, cap,
                             self.peaks_methods_for(cap))

    # -- stages ------------------------------------------------------------

    def _subband_plan(self) -> dict | None:
        """Two-stage sub-band plan when configured AND profitable.

        Profitable = total adds (anchors*nchans + ndm*nsub) at most
        half the direct sweep's ndm*nchans — dense tolerance-stepped
        grids qualify, sparse grids (e.g. the 59-trial tutorial) do
        not and keep the exact direct sweep."""
        mode = self.config.subband_dedisp
        if mode == "never":
            return None
        if mode not in ("auto", "always"):
            raise ConfigError(
                f"subband_dedisp={mode!r}: use auto, always or never")
        from ..ops.dedisperse import subband_plan

        nchans = self.fil.nchans
        nsub = max(2, min(nchans, int(round(np.sqrt(nchans)))))
        plan = subband_plan(self.dm_list, self.delays, self.delay_tab,
                            nsub=nsub, eps=self.config.subband_eps)
        ndm = len(self.dm_list)
        cost = plan["n_anchors"] * nchans + ndm * len(plan["bounds"])
        if mode == "always" or 2 * cost <= ndm * nchans:
            return plan
        return None

    def dedisperse(self) -> jax.Array:
        data = jnp.asarray(self.fil.data.T, dtype=jnp.float32)
        km = None if self.killmask is None else jnp.asarray(self.killmask)
        plan = self._subband_plan()
        if plan is not None:
            from ..ops.dedisperse import dedisperse_subband

            if km is not None:
                data = data * km[:, None]
            trials = dedisperse_subband(
                data, jnp.asarray(self.delays), plan, self.out_nsamps)
        else:
            trials = dedisperse_trials(
                data, jnp.asarray(self.delays), self.out_nsamps, km
            )
        return self._maybe_quantise(trials)

    def _maybe_quantise(self, trials: jax.Array) -> jax.Array:
        """Apply the RESOLVED trial lattice (``self.lattice``): "u8" is
        the dedisp_execute out_nbits=8 staircase (`dedisperser.hpp:
        104-112`, also reachable via the legacy ``trial_nbits=8``
        flag), "bf16" the half-bandwidth round-trip cast, "f32" the
        identity.  Resolution happened in ``__init__`` — an "auto"
        config only lands here non-f32 through a parity-validated
        tuner pick."""
        lattice = getattr(self, "lattice", "f32")
        if lattice == "u8":
            from ..ops.dedisperse import quantise_trials_u8

            return quantise_trials_u8(
                trials, self.fil.header.nbits, self.fil.nchans)
        if lattice == "bf16":
            from ..ops.dedisperse import quantise_trials_bf16

            return quantise_trials_bf16(trials)
        return trials

    def _trial_tim(self, trials: jax.Array, idx: int) -> jax.Array:
        if self.out_nsamps >= self.size:
            return jax.lax.dynamic_slice(
                trials, (idx, 0), (1, self.size)
            ).reshape(self.size)
        tim = trials[idx]
        pad_mean = jnp.mean(tim)
        pad = jnp.full((self.size - self.out_nsamps,), pad_mean, jnp.float32)
        return jnp.concatenate([tim, pad])

    def search_dm_trial(self, trials: jax.Array, idx: int) -> list[Candidate]:
        return self._search_tim(self._trial_tim(trials, idx), idx)

    def _search_tim(self, tim: jax.Array, idx: int,
                    start_capacity: int | None = None,
                    accel_chunk: int | None = None) -> list[Candidate]:
        """Whiten + accel-search one prepared (fft-size) time series.

        Also the targeted re-run path for mesh overflow handling: a DM
        row whose peak buffers clipped in the big fused/chunked
        program is re-searched here with ``start_capacity`` sized to
        its true count — a small program where large top_k capacities
        are safe, instead of recompiling and re-running the whole
        multi-minute dispatch.
        """
        cfg = self.config
        dm = float(self.dm_list[idx])
        if cfg.dump_dir:
            dump_whiten_stages(
                cfg.dump_dir, idx, tim, jnp.asarray(self.birdies),
                jnp.asarray(self.bwidths), self.bin_width,
                cfg.boundary_5_freq, cfg.boundary_25_freq,
                bool(len(self.birdies)),
            )
        tim_w, mean, std = whiten_trial(
            tim,
            jnp.asarray(self.birdies),
            jnp.asarray(self.bwidths),
            self.bin_width,
            cfg.boundary_5_freq,
            cfg.boundary_25_freq,
            bool(len(self.birdies)),
        )
        acc_list = self.acc_plan.generate_accel_list(dm)
        # combined (accel, jerk) trial axis: slot k is accel k%na at
        # jerk k//na; a single zero-jerk trial returns acc_list
        # UNCHANGED (plan.combine_trials), so accel-only searches run
        # the exact pre-jerk trial sequence
        trial_accs, trial_jerks = combine_trials(
            acc_list, self.jerk_plan.jerk_list())
        has_jerk = self.jerk_plan.max_abs > 0.0
        n = len(trial_accs)
        chunk = max(1, min(accel_chunk or cfg.accel_chunk, n))
        padded = int(np.ceil(n / chunk)) * chunk
        accs = np.zeros(padded, np.float32)
        accs[:n] = trial_accs
        jerks = np.zeros(padded, np.float32)
        jerks[:n] = trial_jerks
        cap = start_capacity or cfg.peak_capacity
        chunk_tables = {}
        if self.resample_block is not None:
            from ..ops.resample import resample2_tables

            for c0 in range(0, padded, chunk):
                # capacity-independent: built once, reused across the
                # escalation retries below
                chunk_tables[c0] = tuple(
                    map(jnp.asarray, resample2_tables(
                        accs[c0 : c0 + chunk], float(self.fil.tsamp),
                        self.size, self.max_shift,
                        block=self.resample_block,
                        jerks=(jerks[c0 : c0 + chunk] if has_jerk
                               else None),
                        width=(self.table_width if has_jerk else None),
                    ))
                )
        # per-chunk modelled work (obs/costmodel.py), attached to the
        # span so a trace viewer can read achieved Gflop/s off any
        # Accel-Search slice; absent when no driver recorded costs
        # (targeted mesh re-runs construct searches record_run_costs
        # never saw)
        trial_gflops = getattr(self, "_per_trial_gflops", None)
        while True:  # auto-escalate on peak-buffer overflow: no silent
            methods = self.peaks_methods_for(cap)  # candidate loss
            all_idxs, all_snrs, all_counts = [], [], []
            for c0 in range(0, padded, chunk):
                n_live = int(min(chunk, n - c0))
                with span("Accel-Search", metric="accel_search",
                          dm_trial=int(idx), dm=dm, chunk_start=int(c0),
                          n_trials=n_live,
                          capacity=int(cap),
                          **({"gflops": round(trial_gflops * n_live, 3)}
                             if trial_gflops is not None else {})) as sp:
                    if self.resample_block is not None:
                        idxs, snrs, counts = search_accel_chunk(
                            tim_w, chunk_tables[c0], mean, std,
                            float(self.fil.tsamp), cfg.nharmonics,
                            self.bounds, cap, cfg.min_snr, self.max_shift,
                            self.resample_block, methods,
                        )
                    else:
                        batch = jnp.asarray(accs[c0 : c0 + chunk])
                        jbatch = (jnp.asarray(jerks[c0 : c0 + chunk])
                                  if has_jerk else None)
                        idxs, snrs, counts = search_accel_chunk_legacy(
                            tim_w, batch, mean, std, float(self.fil.tsamp),
                            cfg.nharmonics, self.bounds, cap, cfg.min_snr,
                            self.max_shift, methods, jbatch,
                        )
                    sp.block((idxs, snrs, counts))
                all_idxs.append(np.asarray(idxs))
                all_snrs.append(np.asarray(snrs))
                all_counts.append(np.asarray(counts))
            mx = int(max(c.max(initial=0) for c in all_counts))
            if mx <= cap:
                break
            cap = 1 << int(np.ceil(np.log2(mx)))
            warn_event(
                "capacity_escalation",
                f"peak buffer overflow on DM trial {idx} (count {mx}); "
                f"re-running with capacity={cap}",
                dm_trial=int(idx), count=mx, capacity=cap,
            )
        return self.process_dm_peaks(
            dm, idx, trial_accs,
            np.concatenate(all_idxs), np.concatenate(all_snrs),
            np.concatenate(all_counts),
            capacity=cap, jerk_list=trial_jerks,
        )

    # -- candidate lineage hooks (obs/lineage.py, ISSUE 19) ----------------

    def _lineage_run(self) -> str:
        """Run id stamped on this driver's lineage marks.  The batched
        mesh path temporarily overrides it per beam (each beam is its
        own run) around per-beam host re-searches."""
        return (getattr(self, "_lineage_run_override", "")
                or getattr(self.config, "lineage_run", ""))

    def _absorb_cb(self, still, lrun, stage=None):
        """``on_decision`` callback recording one distiller pass's
        absorptions as terminal lineage marks, or None when lineage is
        off (the distillers then skip pair bookkeeping entirely)."""
        if not lineage.enabled():
            return None
        rule = still.rule

        def cb(fund, absorbed, margin):
            lineage.mark(
                "absorbed", run=lrun,
                id=lineage.candidate_uid(lrun, absorbed),
                absorber=lineage.candidate_uid(lrun, fund),
                rule=rule, stage=stage,
                margin=round(float(margin), 9),
                snr=float(absorbed.snr), freq=float(absorbed.freq),
            )
        return cb

    def _mark_decoded(self, lrun, dm_idx, cands, stage) -> None:
        """One ``decoded`` mark: this DM row's merged peaks entered
        the id'd funnel population."""
        if not lineage.enabled():
            return
        ids = [lineage.candidate_uid(lrun, c) for c in cands]
        lineage.mark("decoded", run=lrun, ids=ids, n=len(ids),
                     stage=stage, dm_idx=int(dm_idx))

    def process_dm_peaks(self, dm, dm_idx, acc_list, idxs, snrs, counts,
                         capacity=None, jerk_list=None):
        """Turn per-(trial, spectrum) peak buffers into distilled
        per-DM candidates.  ``acc_list`` is the COMBINED trial axis;
        ``jerk_list`` its parallel per-trial jerks (None -> all 0)."""
        groups = [
            self._peaks_to_candidates(
                idxs[j], snrs[j], counts[j], dm, dm_idx, float(acc),
                capacity,
                jerk=(0.0 if jerk_list is None else float(jerk_list[j])),
            )
            for j, acc in enumerate(acc_list)
        ]
        if lineage.enabled():
            lrun = self._lineage_run()
            # pre-decode loss accounting (aggregates by design: these
            # peaks never got ids).  clipped = beyond capacity,
            # dropped = under-delivery sentinels, merged = duplicate
            # spectrum bins collapsed by identify_unique_peaks
            cap = capacity or self.config.peak_capacity
            n_take = n_drop = 0
            n_clip = 0
            for j in range(len(acc_list)):
                for level in range(len(self.bounds)):
                    cnt = int(counts[j][level])
                    take = min(cnt, cap)
                    n_clip += max(cnt - cap, 0)
                    bi = np.asarray(idxs[j][level][:take])
                    n_take += take
                    n_drop += int((bi < 0).sum())
            n_dec = sum(len(g) for g in groups)
            if n_clip:
                lineage.mark("clipped", run=lrun, n=n_clip,
                             stage="host", dm_idx=int(dm_idx))
            if n_drop:
                lineage.mark("dropped", run=lrun, n=n_drop,
                             stage="host", dm_idx=int(dm_idx))
            n_merge = n_take - n_drop - n_dec
            if n_merge:
                lineage.mark("merged", run=lrun, n=n_merge,
                             stage="host", dm_idx=int(dm_idx))
            self._mark_decoded(
                lrun, dm_idx, [c for g in groups for c in g], "host")
        return self._distill_accel_groups(groups)

    def _distill_dm_row(self, ii, group, acc_list, jerk_list=None,
                        lrun=None):
        """Build + distill one DM trial's candidates from its decoded
        peak group (None -> no peaks); the per-row fallback behind
        :meth:`_distill_rows_batch`."""
        if group is None:
            return []
        efreq, esnr, eacc, elvl = group
        dm = float(self.dm_list[ii])
        groups = []
        for j in range(len(acc_list)):
            m = eacc == j
            acc = float(acc_list[j])
            jerk = 0.0 if jerk_list is None else float(jerk_list[j])
            groups.append([
                Candidate(dm=dm, dm_idx=ii, acc=acc, jerk=jerk,
                          nh=int(nh), snr=float(sn), freq=float(fq))
                for fq, sn, nh in zip(efreq[m], esnr[m], elvl[m])
            ])
        if lrun is None:
            lrun = self._lineage_run()
        self._mark_decoded(lrun, ii, [c for g in groups for c in g],
                           "mesh")
        return self._distill_accel_groups(groups, lrun=lrun)

    def _distill_rows_batch(self, rows, dm_of=None, run_of=None) -> dict:
        """Vectorised per-DM distillation tail for many DM rows at once.

        ``rows``: iterable of ``(key, group_or_None, acc_list)`` with
        ``group = (freqs, snrs, acc_slot, level)`` arrays as produced by
        the mesh decode.  ``key`` is normally the DM index; batched
        dispatch passes ``(beam, dm_idx)`` keys with ``dm_of`` mapping a
        key to its DM index, so one segmented call distills every
        beam's rows while per-beam candidate separation is structural —
        rows from different beams are distinct segments and can never
        absorb each other.  Semantically identical to calling
        ``_distill_dm_row`` per row (harmonic distillation within each
        accel trial, then acceleration distillation across them,
        `pipeline_multi.cu:238,243`), but runs ONE segmented native call
        per distiller stage instead of ~4 ctypes calls per DM row, and
        builds Candidate objects only for the harmonic-stage survivors
        — the per-call marshalling otherwise dominates the host tail
        (~0.1 s of a 59-trial tutorial run).
        """
        from ..native import lib as _native
        from .distill import SPEED_OF_LIGHT

        cfg = self.config
        # rows may carry an optional 4th element: the per-trial jerks
        # parallel to acc_list (jerk-axis searches); pad to 4-tuples
        rows = [(r[0], r[1], r[2], r[3] if len(r) > 3 else None)
                for r in rows]
        if dm_of is None:
            dm_of = lambda k: k
        if run_of is None:
            run_of = lambda k: self._lineage_run()
        jp = getattr(self, "jerk_plan", None)
        # the native segmented distiller has no jerk predicate: any
        # jerk-axis search takes the per-row Python path (which chains
        # the JerkDistiller through _distill_accel_groups)
        jerk_free = jp is None or (jp.njerk == 1 and jp.max_abs == 0.0)
        if _native is None or not jerk_free:
            return {
                ii: self._distill_dm_row(dm_of(ii), grp, acc_list,
                                         jerks, lrun=run_of(ii))
                for ii, grp, acc_list, jerks in rows
            }
        want_lineage = lineage.enabled()
        out: dict = {}
        # ---- stage A: harmonic distill per (dm, accel) segment -------
        fa, sa, nha, acca = [], [], [], []
        bounds_a = [0]
        seg_rows: list[int] = []  # accel segment -> row ordinal
        row_meta = []  # (dm_idx, n_accel_trials)
        for ii, grp, acc_list, _jerks in rows:
            if grp is None:
                out[ii] = []
                continue
            efreq, esnr, eacc, elvl = grp
            for j, acc in enumerate(acc_list):
                m = eacc == j
                # stable SNR-descending order, matching the
                # std::sort-by-snr each BaseDistiller.distill applies
                order = np.argsort(-esnr[m], kind="stable")
                fa.append(np.asarray(efreq[m], np.float64)[order])
                sa.append(np.asarray(esnr[m], np.float64)[order])
                nha.append(np.asarray(elvl[m], np.int64)[order])
                acca.append(np.full(int(m.sum()), float(acc)))
                bounds_a.append(bounds_a[-1] + int(m.sum()))
                seg_rows.append(len(row_meta))
            row_meta.append((ii, len(acc_list)))
        if not fa:
            return out
        fa = np.concatenate(fa)
        sa = np.concatenate(sa)
        nha = np.concatenate(nha)
        acca = np.concatenate(acca)
        row_keys = [ii for ii, _na in row_meta]
        if want_lineage:
            # element -> row ordinal, for run/dm attribution of marks
            rowa = np.repeat(np.asarray(seg_rows, np.int64),
                             np.diff(bounds_a))
            for r, key in enumerate(row_keys):
                sel = np.nonzero(rowa == r)[0]
                lr = run_of(key)
                dmi = int(dm_of(key))
                ids = [lineage.uid_from_fields(
                    lr, dmi, acca[k], 0.0, nha[k], fa[k])
                    for k in sel]
                lineage.mark("decoded", run=lr, ids=ids, n=len(ids),
                             stage="batch", dm_idx=dmi)
        # pair recording feeds only lineage here (stage-A survivors
        # carry no assoc); uniqueness is independent of the flag, so
        # candidates stay bit-identical with lineage on or off
        uniq_a, pfa, paa = _native.distill_greedy_segmented(
            0, fa, (2.0 ** nha).astype(np.float64), bounds_a,
            cfg.freq_tol, cfg.max_harm, 0.0, want_lineage,
        )
        if want_lineage:
            from .distill import harmonic_margin

            seen_a: set[int] = set()  # pairs are in walk order:
            for fi, ai in zip(pfa, paa):  # first absorber wins
                if ai in seen_a:
                    continue
                seen_a.add(ai)
                key = row_keys[int(rowa[ai])]
                lr = run_of(key)
                dmi = int(dm_of(key))
                lineage.mark(
                    "absorbed", run=lr,
                    id=lineage.uid_from_fields(
                        lr, dmi, acca[ai], 0.0, nha[ai], fa[ai]),
                    absorber=lineage.uid_from_fields(
                        lr, dmi, acca[fi], 0.0, nha[fi], fa[fi]),
                    rule="harmonic", stage="dm_row",
                    margin=round(harmonic_margin(
                        fa[fi], fa[ai], int(2.0 ** nha[ai]),
                        cfg.freq_tol, cfg.max_harm), 9),
                    snr=float(sa[ai]), freq=float(fa[ai]),
                )
        # ---- stage B: acceleration distill per DM segment ------------
        fb, sb, nhb, accb = [], [], [], []
        bounds_b = [0]
        seg = 0
        for ii, naccel in row_meta:
            sel = np.concatenate([
                np.nonzero(uniq_a[bounds_a[seg + j]:bounds_a[seg + j + 1]])[0]
                + bounds_a[seg + j]
                for j in range(naccel)
            ]) if naccel else np.zeros(0, np.int64)
            seg += naccel
            order = np.argsort(-sa[sel], kind="stable")
            sel = sel[order]
            fb.append(fa[sel])
            sb.append(sa[sel])
            nhb.append(nha[sel])
            accb.append(acca[sel])
            bounds_b.append(bounds_b[-1] + len(sel))
        fb = np.concatenate(fb)
        sb = np.concatenate(sb)
        nhb = np.concatenate(nhb)
        accb = np.concatenate(accb)
        uniq_b, pf, pa_ = _native.distill_greedy_segmented(
            1, fb, accb, bounds_b, cfg.freq_tol, 0,
            self.tobs / SPEED_OF_LIGHT, True,
        )
        # ---- materialise Candidate objects (assoc via pair list) -----
        dmib = np.repeat([dm_of(ii) for ii, _na in row_meta],
                         np.diff(bounds_b))
        if want_lineage:
            from .distill import drift_margin

            tobs_over_c = self.tobs / SPEED_OF_LIGHT
            rowb = np.repeat(np.arange(len(row_meta), dtype=np.int64),
                             np.diff(bounds_b))
            seen_b: set[int] = set()
            for fi, ai in zip(pf, pa_):
                if ai in seen_b:
                    continue
                seen_b.add(ai)
                lr = run_of(row_keys[int(rowb[ai])])
                dmi = int(dmib[ai])
                lineage.mark(
                    "absorbed", run=lr,
                    id=lineage.uid_from_fields(
                        lr, dmi, accb[ai], 0.0, nhb[ai], fb[ai]),
                    absorber=lineage.uid_from_fields(
                        lr, dmi, accb[fi], 0.0, nhb[fi], fb[fi]),
                    rule="accel", stage="dm_row",
                    margin=round(drift_margin(
                        fb[fi], fb[ai],
                        (accb[fi] - accb[ai]) * tobs_over_c,
                        cfg.freq_tol), 9),
                    snr=float(sb[ai]), freq=float(fb[ai]),
                )
        objs = [
            Candidate(dm=float(self.dm_list[dmib[k]]),
                      dm_idx=int(dmib[k]), acc=float(accb[k]),
                      nh=int(nhb[k]), snr=float(sb[k]),
                      freq=float(fb[k]))
            for k in range(len(fb))
        ]
        for fi, ai in zip(pf, pa_):
            objs[fi].append(objs[ai])
        for (ii, _na), lo, hi in zip(row_meta, bounds_b[:-1],
                                     bounds_b[1:]):
            out[ii] = [objs[k] for k in range(lo, hi) if uniq_b[k]]
        return out

    def _distill_accel_groups(
        self, groups: list[list[Candidate]], lrun=None
    ) -> list[Candidate]:
        """Per-DM distillation tail shared by the host-loop and mesh
        paths: harmonic distillation within each accel trial
        (`pipeline_multi.cu:238`), acceleration distillation across
        them (`pipeline_multi.cu:243`)."""
        cfg = self.config
        if lrun is None:
            lrun = self._lineage_run()
        harm_still = HarmonicDistiller(cfg.freq_tol, cfg.max_harm, False)
        cb_h = self._absorb_cb(harm_still, lrun, stage="dm_row")
        accel_trial_cands: list[Candidate] = []
        for cands in groups:
            accel_trial_cands.extend(
                harm_still.distill(cands, on_decision=cb_h))
        acc_still = AccelerationDistiller(self.tobs, cfg.freq_tol, True)
        out = acc_still.distill(
            accel_trial_cands,
            on_decision=self._absorb_cb(acc_still, lrun,
                                        stage="dm_row"))
        jp = getattr(self, "jerk_plan", None)
        if jp is not None and jp.njerk > 1:
            # jerk-adjacent de-dup (ISSUE 13), only when the axis is
            # real — accel-only runs keep the exact pre-jerk chain
            jerk_still = JerkDistiller(self.tobs, cfg.freq_tol, True)
            out = jerk_still.distill(
                out, on_decision=self._absorb_cb(jerk_still, lrun,
                                                 stage="dm_row"))
        return out

    def _peaks_to_candidates(self, idxs, snrs, counts, dm, dm_idx, acc,
                             capacity=None, jerk=0.0):
        cands: list[Candidate] = []
        for level, (start, stop, factor) in enumerate(self.bounds):
            cnt = int(counts[level])
            cap = capacity or self.config.peak_capacity
            take = min(cnt, cap)
            if cnt > cap:
                warn_event(
                    "peak_buffer_overflow",
                    f"peak buffer overflow: {cnt} > capacity {cap} "
                    f"(dm={dm}, acc={acc}, nh={level}); raise peak_capacity",
                    count=cnt, capacity=int(cap), dm=float(dm),
                    acc=float(acc), nh=int(level),
                )
            bi = np.asarray(idxs[level][:take])
            bs = np.asarray(snrs[level][:take])
            if (bi < 0).any():
                # defensive: a -1 sentinel inside the claimed-valid
                # prefix means the device extraction under-delivered
                # (backend top-k anomaly); drop the sentinels rather
                # than fabricate freq<0 / snr=0 candidates
                warn_event(
                    "peak_underdelivery",
                    f"peak extraction under-delivered "
                    f"{int((bi < 0).sum())} of {take} slots "
                    f"(dm={dm}, acc={acc}, nh={level})",
                    missing=int((bi < 0).sum()), expected=int(take),
                    dm=float(dm), acc=float(acc), nh=int(level),
                )
                keep = bi >= 0
                bi, bs = bi[keep], bs[keep]
            # device buffers are SNR-ordered (extract_top_peaks); the
            # merge walk needs ascending bin order
            order = np.argsort(bi, kind="stable")
            pidx, psnr = identify_unique_peaks(bi[order], bs[order])
            for p, s in zip(pidx, psnr):
                cands.append(
                    Candidate(dm=dm, dm_idx=dm_idx, acc=acc, jerk=jerk,
                              nh=level, snr=float(s),
                              freq=float(p * factor))
                )
        return cands

    # -- full run ----------------------------------------------------------

    def _identity_config(self, cfg=None):
        """``cfg`` with an "auto" trial lattice replaced by the
        RESOLVED dtype: the checkpoint/tuner identity must pin the
        concrete lattice (two "auto" runs that resolve differently are
        different searches)."""
        cfg = self.config if cfg is None else cfg
        lattice = getattr(self, "lattice", "f32")
        if cfg.trial_lattice == lattice:
            return cfg
        from dataclasses import replace

        return replace(cfg, trial_lattice=lattice)

    def _make_checkpoint(self, fil=None, cfg=None):
        # batched dispatch passes per-beam (fil, cfg) so every beam
        # keeps its own checkpoint identity/file; default: this search
        fil = self.fil if fil is None else fil
        cfg = self.config if cfg is None else cfg
        if not cfg.checkpoint_file:
            return None, {}
        from .checkpoint import (
            SearchCheckpoint,
            legacy_search_keys,
            search_key,
        )

        key_cfg = self._identity_config(cfg)
        ckpt = SearchCheckpoint(
            cfg.checkpoint_file,
            search_key(cfg.infilename, fil, key_cfg),
            cfg.checkpoint_interval,
            advisory={"input": cfg.infilename},
            legacy=legacy_search_keys(cfg.infilename, fil, key_cfg),
        )
        return ckpt, (ckpt.load() or {})

    def _tune_key(self) -> str:
        """Identity key for the persistent buffer-tuning sidecar (same
        key the checkpoint uses: input + geometry + parameters)."""
        from .checkpoint import search_key

        return search_key(self.config.infilename, self.fil,
                          self._identity_config())

    def run(self) -> SearchResult:
        from ..obs.compilation import set_compile_context
        from ..obs.costmodel import record_run_costs
        from ..obs.metrics import install_compile_hook
        from ..utils import ProgressBar

        install_compile_hook()
        # compile attribution (ISSUE 18): ledger every backend compile
        # this run triggers against its search geometry
        set_compile_context(
            program="pipeline.search",
            geometry={"nchans": int(self.fil.nchans),
                      "nbits": int(self.fil.header.nbits),
                      "size": int(self.size),
                      "out_nsamps": int(self.out_nsamps),
                      "n_dm": len(self.dm_list)})
        self._span_cursor0 = span_cursor()
        cfg = self.config
        timers: dict[str, float] = {}
        t_total = time.time()
        METRICS.inc("runs.host_loop")
        METRICS.gauge("hbm.budget_bytes", cfg.hbm_budget_gb * 1e9)
        METRICS.gauge("hbm.data_bytes", self._data_bytes())
        METRICS.gauge("search.n_dm_trials", len(self.dm_list))
        METRICS.gauge("search.fft_size", self.size)
        costs = record_run_costs(self)["stages"]
        self.record_peaks_selection()

        # consult the checkpoint BEFORE dedispersing: a fully-complete
        # resume only needs trials if folding will run
        ckpt, done = self._make_checkpoint()
        complete = len(done) == len(self.dm_list)
        trials = None
        timers["dedispersion"] = 0.0
        if not (complete and cfg.npdmp == 0):
            t0 = time.time()
            with span("Dedisperse", metric="dedispersion",
                      n_dm_trials=len(self.dm_list),
                      out_nsamps=int(self.out_nsamps),
                      gflops=round(costs["dedisperse"].flops / 1e9, 3),
                      gbytes=round(
                          costs["dedisperse"].bytes_total / 1e9, 3)) as sp:
                trials = self.dedisperse()
                sp.block(trials)
            timers["dedispersion"] = time.time() - t0

        t0 = time.time()
        dm_cands = CandidateCollection()
        pbar = ProgressBar(len(self.dm_list), "DM trials ",
                           enabled=cfg.progress_bar)
        pbar.start()
        with span("DM-Loop", metric="searching",
                  n_dm_trials=len(self.dm_list)):
            for ii in range(len(self.dm_list)):
                if ii not in done:
                    done[ii] = self.search_dm_trial(trials, ii)
                    if ckpt:
                        ckpt.maybe_save(done)
                dm_cands.append(done[ii])
                pbar.update(ii + 1)
        pbar.finish()
        if ckpt:
            ckpt.save(done)
        timers["searching"] = time.time() - t0
        result = self._finalise(dm_cands, trials, timers, t_total)
        if ckpt:
            ckpt.remove()  # run completed; resume no longer needed
        return result

    # -- batched multi-observation dispatch (ISSUE 9) ----------------------

    # True after a run_batch() that actually used a single batched
    # device program (the mesh fused path); False after the sequential
    # fallback — the worker's scheduler.batched_dispatches counter and
    # the batch-smoke gate key off this.
    last_dispatch_batched = False

    def _spawn(self, fil, cfg):
        """Fresh driver of this type for one batch-mate observation."""
        return type(self)(fil, cfg)

    @staticmethod
    def _batch_fields(fil):
        hdr = fil.header
        return (fil.nsamps, fil.nchans, int(hdr.nbits), float(hdr.tsamp),
                float(hdr.fch1), float(hdr.foff))

    def _assert_batch_compatible(self, fils):
        """Batched dispatch shares ONE plan (delay table, accel grid,
        fft size) across beams, so every observation must match the
        leader's geometry exactly — the worker's bucket key guarantees
        this; anything else is a caller bug, not a data problem."""
        want = self._batch_fields(self.fil)
        for i, fil in enumerate(fils):
            got = self._batch_fields(fil)
            if got != want:
                raise ConfigError(
                    f"batch beam {i} geometry {got} != leader {want}; "
                    f"batched dispatch requires one geometry bucket"
                )

    def run_batch(self, fils, configs=None) -> list:
        """Search B same-geometry observations; one result per beam.

        Returns a list aligned with ``fils`` whose slots are either a
        :class:`SearchResult` or the exception that beam raised — a
        failing beam never poisons its batch-mates.  This base
        implementation runs the beams sequentially (the host-loop
        driver has no batched program); :class:`MeshPulsarSearch`
        overrides it with the single-dispatch ``(B, ...)`` fused
        program.  ``self`` must have been built from ``fils[0]``;
        ``configs`` may differ per beam only in path-like fields
        (outdir / checkpoint_file / infilename).
        """
        configs = ([self.config] * len(fils) if configs is None
                   else list(configs))
        self._assert_batch_compatible(fils)
        self.last_dispatch_batched = False
        results = []
        for fil, cfg in zip(fils, configs):
            try:
                drv = (self if fil is self.fil and cfg is self.config
                       else self._spawn(fil, cfg))
                results.append(drv.run())
            except Exception as exc:  # per-beam failure isolation
                results.append(exc)
        return results

    def _finalise(self, dm_cands, trials, timers, t_total,
                  trials_provider=None, config=None,
                  fold_fuser=None) -> SearchResult:
        """Shared tail of every driver (`pipeline_multi.cu:362-391`):
        cross-DM distillation, scoring, folding, limit, result.

        ``trials_provider``: bounded-HBM drivers pass a callable
        (dm_idxs) -> (trials, row_map) instead of resident trials; the
        candidate DM rows are re-dedispersed only if folding runs.

        ``fold_fuser``: resumed-path alternative (ISSUE 11) — a
        callable (dm_idxs) -> (fold_program, row_map) that fuses the
        candidate rows' dedispersion INTO the fold dispatch
        (``MeshPulsarSearch._fused_fold_provider``), so the trial
        lattice never exists off-device and candidates cross the link
        exactly once.  Checked before ``trials_provider``.

        ``config``: batched dispatch passes the per-beam config (same
        search parameters by construction, beam-specific paths) so the
        SearchResult routes outputs to that beam's outdir.
        """
        cfg = self.config if config is None else config
        lrun = (getattr(cfg, "lineage_run", "")
                or self._lineage_run())
        with span("Distill", metric="distillation",
                  n_candidates=len(dm_cands.cands)):
            dm_still = DMDistiller(cfg.freq_tol, True)
            harm_still = HarmonicDistiller(cfg.freq_tol, cfg.max_harm, True,
                                           False)
            cands = dm_still.distill(
                dm_cands.cands,
                on_decision=self._absorb_cb(dm_still, lrun,
                                            stage="cross_dm"))
            cands = harm_still.distill(
                cands,
                on_decision=self._absorb_cb(harm_still, lrun,
                                            stage="cross_dm"))

        hdr = self.fil.header
        scorer = CandidateScorer(
            hdr.tsamp, hdr.cfreq, hdr.foff, abs(hdr.foff) * self.fil.nchans
        )
        on_score = None
        if lineage.enabled():
            def on_score(c, flags):
                lineage.mark("scored", run=lrun,
                             id=lineage.candidate_uid(lrun, c),
                             flags=flags)
        scorer.score_all(cands, on_score=on_score)

        import time

        t0 = time.time()
        did_fold = False
        if cfg.npdmp > 0:
            dm_row_lookup = None
            fold_program = None
            n_fold_rows = 0
            if trials is None and (fold_fuser is not None
                                   or trials_provider is not None):
                # same filter fold_candidates applies — don't
                # re-dedisperse rows that will never be folded
                fold_dms = {
                    c.dm_idx for c in cands[: cfg.npdmp]
                    if FOLD_MIN_PERIOD < 1.0 / c.freq < FOLD_MAX_PERIOD
                }
                if fold_dms and fold_fuser is not None:
                    fold_program, dm_row_lookup = fold_fuser(fold_dms)
                    n_fold_rows = len(dm_row_lookup)
                elif fold_dms:
                    trials, dm_row_lookup = trials_provider(fold_dms)
            if trials is not None or fold_program is not None:
                budget = int(cfg.hbm_budget_gb * 1e9)
                # fused fold: the candidate rows' trials are a transient
                # inside the fold program, not a resident buffer
                trial_bytes = (trials.size * 4 if trials is not None
                               else n_fold_rows * self.out_nsamps * 4)
                resident = self._data_bytes() + trial_bytes + (2 << 30)
                free = budget - resident
                fold_costs = getattr(self, "_stage_costs", None)
                if free < budget // 4:
                    # headroom is tight: free the search-phase
                    # executables' reserved arenas before folding — TPU
                    # executables hold their temp buffers while loaded,
                    # and the 96 B/samp fold batch coefficient (plus
                    # the 2 GB reserve above) is calibrated with them
                    # GONE (the mesh driver also frees its chunk
                    # program; this covers the host-loop driver's
                    # accel-chunk programs).  Skipped when headroom is
                    # plentiful: gc.collect() costs ~20-30 ms per run.
                    import gc

                    search_accel_chunk.clear_cache()
                    search_accel_chunk_legacy.clear_cache()
                    gc.collect()
                did_fold = True
                with span("Folding", metric="folding",
                          npdmp=int(cfg.npdmp),
                          **({"gflops": round(
                              fold_costs["stages"]["fold"].flops / 1e9,
                              3)}
                             if fold_costs is not None else {})):
                    fold_candidates(
                        cands, trials, self.out_nsamps, hdr.tsamp,
                        cfg.npdmp,
                        boundary_5_freq=cfg.boundary_5_freq,
                        boundary_25_freq=cfg.boundary_25_freq,
                        dm_row_lookup=dm_row_lookup,
                        hbm_free_bytes=max(free, 0),
                        device_cache=self.__dict__.setdefault(
                            "_fold_input_cache", FoldInputCache()),
                        fold_program=fold_program,
                    )
        timers["folding"] = time.time() - t0

        if lineage.enabled():
            # terminal: everything beyond the output limit is cut;
            # the survivors are emitted with their final rank.  The
            # fold top-N selection is annotated (non-terminal) so a
            # `why` query states whether a candidate was folded or
            # ranked out of the fold budget.
            for rank, c in enumerate(cands[cfg.limit:],
                                     start=cfg.limit):
                lineage.mark("cut", run=lrun,
                             id=lineage.candidate_uid(lrun, c),
                             stage="limit", rank=rank,
                             snr=float(c.snr))
        cands = cands[: cfg.limit]
        if lineage.enabled():
            for rank, c in enumerate(cands):
                cid = lineage.candidate_uid(lrun, c)
                if did_fold and (
                        FOLD_MIN_PERIOD < 1.0 / c.freq
                        < FOLD_MAX_PERIOD):
                    lineage.mark(
                        "folded" if rank < cfg.npdmp else "fold_cut",
                        run=lrun, id=cid, rank=rank)
                lineage.mark("emitted", run=lrun, id=cid, rank=rank,
                             snr=float(c.snr), freq=float(c.freq),
                             dm_idx=int(c.dm_idx))
        injection = None
        if cfg.injection_manifest:
            try:
                injection = self._injection_budget(cands, cfg)
            except Exception as exc:
                # diagnostics must never kill a science run
                warn_event(
                    "injection_probe_failed",
                    f"SNR budget probe failed: {exc}",
                    manifest=cfg.injection_manifest,
                )
        timers["total"] = time.time() - t_total
        # the run's device_duty_cycle (ISSUE 11): measured device/link
        # seconds over the span ledger since run() start, per
        # wall-clock second — 1.0 means the devices never waited on
        # the host.  A gauge, so it lands in run_report.json and the
        # telemetry samples automatically; the worker drain overwrites
        # it with the drain-level figure for the serve ledger.
        if timers["total"] > 0:
            METRICS.gauge("device_duty_cycle", round(
                device_seconds(getattr(self, "_span_cursor0", 0))
                / timers["total"], 4))
        return SearchResult(
            candidates=CandidateCollection(cands),
            dm_list=self.dm_list,
            acc_list_dm0=self.acc_plan.generate_accel_list(0.0),
            timers=timers,
            config=cfg,
            header=hdr,
            injection=injection,
            provenance=self._provenance(cfg),
        )

    def _provenance(self, cfg) -> dict:
        """The provenance block stamped into store records and
        overview.xml (ISSUE 19): enough to reconstruct where a
        candidate came from — run id (hashes into candidate ids), git
        sha, geometry fingerprint (joins the compile ledger and
        warehouse rows), the RESOLVED trial lattice plus what the
        config requested (tuner verdict visibility), and the host."""
        import socket

        from ..obs.history import git_describe
        from ..obs.warehouse import geometry_fingerprint

        geo = {
            "nchans": int(self.fil.nchans),
            "nbits": int(self.fil.header.nbits),
            "size": int(self.size),
            "out_nsamps": int(self.out_nsamps),
            "n_dm": int(len(self.dm_list)),
        }
        git = git_describe()
        return {
            "run": getattr(cfg, "lineage_run", ""),
            "git_sha": str(git.get("sha", "")),
            "geometry": geometry_fingerprint(geo),
            "lattice": getattr(self, "lattice", "f32"),
            "lattice_requested": getattr(cfg, "trial_lattice", "f32"),
            "host": socket.gethostname(),
        }

    def _injection_budget(self, cands, cfg) -> dict:
        """Per-stage SNR budget of an injected signal (ISSUE 14).

        Re-runs the whitening/resample front half on the single trial
        nearest the manifest's (DM, accel, jerk) — through the SAME
        jitted ``whiten_trial`` / ``resample2`` / quantised-lattice code
        the search used — then taps the injected spin's amplitude at
        each stage, z-scored exactly like ``_spectra_peaks`` normalises
        spectra:

        * ``whiten``: exact single-frequency DFT of the resampled
          whitened series at the manifest spin — the scalloping-free
          matched ceiling everything downstream is measured against;
        * ``fourier_bin``: plain ``|rfft|`` at the nearest bin — the
          drop from ``whiten`` is pure interbin scalloping;
        * ``interbin``: ``form_interpolated`` at that bin — what the
          estimator wins back;
        * ``harmonic``: each summed level's value at the fundamental's
          stretched index (the reference's ``(i*m + 2^(k-1)) >> k``
          read collapses to ``spec[k0*m]`` on the fundamental's exact
          grid point), mismatch shows up as a sub-sqrt(2^k) gain;
        * ``peak``: the strongest candidate the recovery matcher
          accepts — the drop from ``harmonic_best`` is extraction /
          distillation loss.

        The u8/bf16 trial lattice is applied when resolved, so lattice
        quantisation loss lands in every tap.  Returns the budget dict
        attached to ``SearchResult.injection``; gauges + an
        ``Injection-Probe`` span make it land in run_report.json and
        the telemetry stream automatically.
        """
        import os

        from ..obs.injection import load_manifest, match_candidates
        from ..ops.resample import resample2

        man = load_manifest(cfg.injection_manifest)
        f0 = float(man["freq"])
        tsamp = float(self.fil.tsamp)

        # nearest trial coordinates on this search's grid
        dm_idx = int(np.argmin(np.abs(self.dm_list - float(man["dm"]))))
        dm = float(self.dm_list[dm_idx])
        acc_list = np.asarray(self.acc_plan.generate_accel_list(dm))
        acc = float(acc_list[int(np.argmin(
            np.abs(acc_list - float(man["accel"]))))])
        jerk_list = np.asarray(self.jerk_plan.jerk_list())
        jerk = float(jerk_list[int(np.argmin(
            np.abs(jerk_list - float(man["jerk"]))))])

        # the injected file's data (batched drivers finalise per-beam
        # configs against self.fil == beam 0; the manifest knows which
        # file it describes)
        fil = self.fil
        path = man.get("path", "")
        if path and os.path.exists(path):
            try:
                from ..io.sigproc import read_filterbank

                probe_fil = read_filterbank(path)
                if probe_fil.nchans == fil.nchans:
                    fil = probe_fil
            except Exception:
                pass

        # host dedispersion of the one matched DM row (same channel sum
        # as ops.dedisperse), then the resolved trial lattice and the
        # driver's pad/trim rule
        dj = np.asarray(self.delays[dm_idx], dtype=np.int64)
        out_n = min(self.out_nsamps, fil.nsamps - int(dj.max()))
        data = np.asarray(fil.data)
        row = np.zeros(out_n, dtype=np.float64)
        for j in range(fil.nchans):
            row += data[dj[j] : dj[j] + out_n, j].astype(np.float64)
        row = np.asarray(
            self._maybe_quantise(jnp.asarray(row[None, :], jnp.float32)),
            dtype=np.float64)[0]
        if out_n >= self.size:
            tim = row[: self.size]
        else:
            tim = np.concatenate(
                [row, np.full(self.size - out_n, row.mean())])

        tim_w, mean, std = whiten_trial(
            jnp.asarray(tim, jnp.float32),
            jnp.asarray(self.birdies),
            jnp.asarray(self.bwidths),
            self.bin_width,
            cfg.boundary_5_freq,
            cfg.boundary_25_freq,
            bool(len(self.birdies)),
        )
        tim_r = np.asarray(
            resample2(tim_w, acc, tsamp, None, jerk), dtype=np.float64)
        mean = float(mean)
        std = float(std)
        z = lambda a: round(float((a - mean) / std), 4)

        # stage taps
        t = np.arange(self.size, dtype=np.float64)
        amp_exact = np.abs(np.sum(
            tim_r * np.exp(-2j * np.pi * f0 * tsamp * t)))
        fs = np.fft.rfft(tim_r)
        k0 = int(round(f0 / self.bin_width))
        k0 = min(max(k0, 1), len(fs) - 1)
        amp_bin = np.abs(fs[k0])
        amp_ib = np.sqrt(max(
            np.abs(fs[k0]) ** 2, 0.5 * np.abs(fs[k0] - fs[k0 - 1]) ** 2))
        spec = np.abs(fs)
        spec[1:] = np.sqrt(np.maximum(
            spec[1:] ** 2, 0.5 * np.abs(np.diff(fs)) ** 2))
        spec = (spec - mean) / std
        harmonics = []
        for lvl in range(1, cfg.nharmonics + 1):
            _, stop, _ = self.bounds[lvl]
            if k0 * (1 << lvl) >= stop:
                break  # fundamental's stretched index is unsearchable
            folds = k0 * np.arange(1, (1 << lvl) + 1)
            tap = spec[np.minimum(folds, len(spec) - 1)].sum() \
                / np.sqrt(float(1 << lvl))
            harmonics.append(round(float(tap), 4))
        snr_whiten = z(amp_exact)
        snr_bin = z(amp_bin)
        snr_interbin = z(amp_ib)
        harmonic_best = max([snr_interbin] + harmonics)

        verdict = match_candidates(man, cands, tobs=self.tobs)
        peak = round(float(verdict["best_snr"]), 4)
        budget = {
            "manifest": cfg.injection_manifest,
            "freq": f0,
            "bin": k0,
            "lattice": getattr(self, "lattice", "f32"),
            "trial": {"dm": dm, "dm_idx": dm_idx, "acc": acc,
                      "jerk": jerk},
            "snr": {
                "whiten": snr_whiten,
                "fourier_bin": snr_bin,
                "interbin": snr_interbin,
                "harmonic": harmonics,
                "harmonic_best": harmonic_best,
                "peak": peak,
            },
            "loss": {
                "scalloping": round(snr_whiten - snr_bin, 4),
                "interbin_residual": round(snr_whiten - snr_interbin, 4),
                "harmonic": round(snr_interbin - harmonic_best, 4),
                "extraction": round(harmonic_best - peak, 4),
            },
            "recovered": bool(verdict["recovered"]),
            "n_matches": int(verdict["n_matches"]),
        }
        METRICS.gauge("injection.snr_whiten", snr_whiten)
        METRICS.gauge("injection.snr_interbin", snr_interbin)
        METRICS.gauge("injection.snr_peak", peak)
        METRICS.gauge("injection.recovered", int(budget["recovered"]))
        with span("Injection-Probe", freq=f0, dm=dm, acc=acc, jerk=jerk,
                  snr_whiten=snr_whiten, snr_interbin=snr_interbin,
                  snr_peak=peak, recovered=budget["recovered"]):
            pass
        return budget


# --------------------------------------------------------------------------
# folding (MultiFolder equivalent, folder.hpp:337-442)
# --------------------------------------------------------------------------

# foldable-period window (`folder.hpp:424-427`); shared between
# fold_candidates and the _finalise pre-filter
FOLD_MIN_PERIOD = 0.001
FOLD_MAX_PERIOD = 10.0

def _rewhiten_core(tim, bin_width):
    """The fold path re-whitens without zapping or interbinning
    (`folder.hpp:382-389`)."""
    fseries = jnp.fft.rfft(tim.astype(jnp.float32)).astype(jnp.complex64)
    pspec = form_power(fseries)
    median = running_median(pspec, bin_width)
    fseries = deredden(fseries, median)
    return jnp.fft.irfft(fseries, n=tim.shape[0]).astype(jnp.float32)


_rewhiten_for_fold = jax.jit(_rewhiten_core, static_argnames=("bin_width",))


class FoldInputCache(dict):
    """Bounded LRU for the fold's digest-keyed device inputs (ISSUE 11
    satellite): a long-lived worker folds many distinct candidate
    sets, and the previous plain dict pinned every packed-table upload
    for the worker's lifetime.  ``get`` refreshes recency; inserting
    past ``maxsize`` drops the least-recently-used entry (counted in
    ``fold.cache_evicted``; jax refcounting frees its device buffers).
    Still a dict, so every ``device_cache=`` call site — including
    tests passing plain ``{}`` — keeps working."""

    #: a handful of entries covers the intended hits (benchmark
    #: reruns, checkpoint resumes); each entry pins its packed-table
    #: device buffers, so small beats complete
    maxsize = 8

    def __init__(self, maxsize: int | None = None):
        super().__init__()
        if maxsize is not None:
            self.maxsize = int(maxsize)

    def get(self, key, default=None):
        if key not in self:
            return default
        val = super().pop(key)
        super().__setitem__(key, val)  # re-insert = most recent
        return val

    def __setitem__(self, key, value):
        if key in self:
            super().pop(key)
        elif len(self) >= self.maxsize:
            super().pop(next(iter(self)))
            METRICS.inc("fold.cache_evicted")
        super().__setitem__(key, value)


def fold_epilogue_core(
    trials, packed_in, periods, bin_width, fold_nsamps, tsamp, nbins,
    nints, max_shift, block, nu, nb, w,
):
    """Re-whiten + resample + fold + optimise every candidate in ONE
    dispatch (vmapped); ships home only the optimum per candidate.
    Plain traceable function so the mesh driver can compose it behind
    an on-device dedispersion of the candidate rows (the fused fold
    epilogue, ISSUE 11); ``_batched_fold_program`` below is its jitted
    standalone face.

    Whitens once per DISTINCT DM row, exactly as the reference groups
    candidates by dm_idx and re-whitens each trial once
    (`folder.hpp:376-389`).

    ``packed_in`` is ONE int32 buffer holding every per-batch integer
    input — kernel-I staircase resample tables (`resample1_tables`;
    device-side f64 index math is both inexact on real TPUs and a full
    random gather, `ops/resample.py`), the ``nu`` distinct trial rows
    (padded by repeating the last — duplicates are wasted work, never
    wrong) and each candidate's row slot.  One buffer = one host->
    device transfer: per-transfer latency on a remote-attached TPU is
    tens of ms, comparable to the whole fold's device time.
    """
    from ..ops.resample import resample2_from_tables

    B = periods.shape[0]
    o = 0
    d0 = packed_in[o : o + B * nb].reshape(B, nb)
    o += B * nb
    pos_t = packed_in[o : o + B * nb * w].reshape(B, nb, w)
    o += B * nb * w
    step_t = packed_in[o : o + B * nb * w].reshape(B, nb, w)
    o += B * nb * w
    uniq_rows = packed_in[o : o + nu]
    o += nu
    cand_slots = packed_in[o : o + B]

    def whiten_row(row):
        # the caller guarantees fold_nsamps <= trials.shape[1]
        tim = jax.lax.dynamic_slice(
            trials, (row, jnp.int32(0)), (1, fold_nsamps)
        ).reshape(-1)
        return _rewhiten_core(tim, bin_width)

    tws = jax.vmap(whiten_row)(uniq_rows)  # (nuniq, fold_nsamps)

    def one(slot, rtab, period):
        tim_w = jax.lax.dynamic_slice(
            tws, (slot, jnp.int32(0)), (1, fold_nsamps)
        ).reshape(-1)
        d0_c, pos_c, step_c = rtab
        tim_r = resample2_from_tables(tim_w, d0_c, pos_c, step_c,
                                      max_shift, block=block)
        subints = fold_time_series_core(tim_r, period, tsamp, nbins, nints)
        return optimise_device(subints)

    argmaxes, opt_folds, opt_profs = jax.vmap(one)(
        cand_slots, (d0, pos_t, step_t), periods)
    # one packed f32 buffer -> a single device->host round trip.
    # argmax < nshifts*nbins*ntemplates ~ 2^18 is exact in f32 (and
    # bitcast_convert_type miscompiles on v5e, see parallel/mesh.py)
    return jnp.concatenate([
        argmaxes.astype(jnp.float32),
        opt_folds.reshape(B * nints * nbins),
        opt_profs.reshape(B * nbins),
    ])


#: the standalone jitted fold program (the host-resident-trials path).
#: Keeps this exact attribute name: obs/metrics.py's
#: jit_program_cache_sizes probes it for the run report.
_batched_fold_program = partial(
    jax.jit,
    static_argnames=("bin_width", "fold_nsamps", "tsamp", "nbins", "nints",
                     "max_shift", "block", "nu", "nb", "w"),
)(fold_epilogue_core)


def fold_candidates(
    cands: list[Candidate],
    trials: jax.Array | None,
    trials_nsamps: int,
    tsamp: float,
    npdmp: int,
    nbins: int = FOLD_NBINS,
    nints: int = FOLD_NINTS,
    min_period: float = FOLD_MIN_PERIOD,
    max_period: float = FOLD_MAX_PERIOD,
    boundary_5_freq: float = 0.05,
    boundary_25_freq: float = 0.5,
    dm_row_lookup: dict | None = None,
    hbm_free_bytes: int | None = None,
    device_cache: dict | None = None,
    fold_program=None,
) -> None:
    """Fold + optimise the top ``npdmp`` candidates in place, then sort
    by max(snr, folded_snr) (`folder.hpp:424-434,25-31`).

    ``dm_row_lookup`` maps candidate ``dm_idx`` to a row of ``trials``
    when the caller passes a compacted trials array (the bounded-HBM
    path re-dedisperses only the candidate DM rows).

    ``fold_program``: fused-fold alternative (ISSUE 11) — a callable
    with ``_batched_fold_program``'s signature minus ``trials`` that
    materialises the candidate rows on device itself
    (``MeshPulsarSearch._fused_fold_provider``); ``trials`` may then
    be None, and ``trials_nsamps`` must be the row length the program
    produces (>= its prev_power_of_two is guaranteed)."""
    if trials is None and fold_program is None:
        raise ConfigError(
            "fold_candidates needs resident trials or a fold_program")
    # both drivers hand over trials with >= prev_power_of_two(
    # trials_nsamps) real columns; a narrower caller gets zero-padded
    # so the fold FFT length stays the reference's power of two
    # (matching the old DeviceTimeSeries zero-fill semantics)
    nsamps = prev_power_of_two(trials_nsamps)
    if trials is not None and nsamps > trials.shape[1]:
        trials = jnp.pad(trials, ((0, 0), (0, nsamps - trials.shape[1])))
    tobs = nsamps * tsamp
    bin_width = 1.0 / tobs
    from ..ops.resample import resample1_tables, resample2_max_shift

    fold_ids = [
        ii
        for ii in range(min(npdmp, len(cands)))
        if min_period < 1.0 / cands[ii].freq < max_period
    ]
    # staircase-table validity (4*shift < nsamps): an extreme-
    # acceleration candidate outside the domain is skipped with a
    # warning (its search-stage snr/candidate record survives) rather
    # than aborting the whole run at the folding stage
    shifts = {
        ii: resample2_max_shift(abs(float(cands[ii].acc)), tsamp, nsamps)
        for ii in fold_ids
    }
    bad = [ii for ii in fold_ids if 4 * max(shifts[ii], 1) >= nsamps]
    if bad:
        warn_event(
            "fold_domain_skip",
            f"skipping fold of {len(bad)} candidate(s) whose "
            f"acceleration shift exceeds the resampler's validity "
            f"domain for a {nsamps}-sample fold (needs 4*shift < nsamps)",
            n_skipped=len(bad), nsamps=int(nsamps),
        )
        fold_ids = [ii for ii in fold_ids if ii not in bad]
    if not fold_ids:
        cands.sort(key=lambda c: -max(c.snr, c.folded_snr))
        return
    lookup = dm_row_lookup if dm_row_lookup is not None else {}
    rows_np = np.asarray(
        [lookup.get(cands[i].dm_idx, cands[i].dm_idx) for i in fold_ids],
        np.int32,
    )
    accs = [float(cands[i].acc) for i in fold_ids]
    # f32: x64 is disabled on TPU and the relative phase error over a
    # 2^17-sample fold (~1e-7) is far below one phase bin
    periods_np = np.asarray(
        [1.0 / cands[i].freq for i in fold_ids], np.float32
    )
    from ..utils.hostfetch import fetch_to_host

    fold_ms = max(max(shifts[ii] for ii in fold_ids), 1)
    fold_block = resample_block_for(nsamps, fold_ms)
    if fold_block is None:
        # 4*fold_ms < nsamps is guaranteed by the domain filter above
        fold_block = min(nsamps, 128)  # power-of-two nsamps guaranteed
    rtabs_np = resample1_tables(
        accs, float(tsamp), nsamps, fold_ms, block=fold_block)
    # batch size from free HBM: compiled-program memory_analysis at
    # 2^22 fold samples measures ~72 B/samp marginal per candidate
    # (0.30 GB each); 96 B/samp adds margin.  (The earlier 10-wide OOM
    # at production scale was the chunk executables' retained arenas —
    # now freed before folding — plus this chain.)  On TPU the one-hot
    # matmul fold adds a live (nints, nper, nbins) bf16 operand —
    # 2*nbins B/samp per candidate — on top of that chain.  At tutorial
    # scale this still folds every candidate in ONE dispatch — each
    # extra dispatch costs a ~0.11 s host round-trip on the
    # remote-attached TPU.
    from ..obs.memprof import probed_bytes_per
    from ..ops.harmonics import _on_tpu

    n = len(fold_ids)
    # measured coefficient first (ISSUE 18): the memprof probe returns
    # the live compiler's marginal B/samp for the fold program (None
    # off-TPU / on failure -> the hand-measured fallback below).  The
    # probe measures the 72 B/samp chain without the retained-arena
    # margin, so the same 96/72 headroom factor is applied on top
    probed = probed_bytes_per("fold_samp")
    tpu_extra = 2 * nbins + 32 if _on_tpu() else 0
    if probed:
        bytes_per_samp = int(probed * 96.0 / 72.0) + tpu_extra
    else:
        bytes_per_samp = 96 + tpu_extra
    if hbm_free_bytes is not None:
        batch = int(max(1, min(
            n, hbm_free_bytes // (bytes_per_samp * nsamps))))
    else:
        batch = 4  # conservative when the caller gives no HBM figure
    argmaxes = np.empty(n, np.int64)
    opt_folds = np.empty((n, nints, nbins), np.float32)
    opt_profs = np.empty((n, nbins), np.float32)
    cache = device_cache if device_cache is not None else {}
    # either the caller's fused program (dedisperses the candidate
    # rows on device) or the resident-trials epilogue — identical
    # post-``trials`` signatures, so the loop below is agnostic
    fp = (fold_program if fold_program is not None
          else (lambda *a: _batched_fold_program(trials, *a)))
    for b0 in range(0, n, batch):
        b1 = min(b0 + batch, n)
        m = b1 - b0
        # whiten once per DISTINCT row in the batch.  nuniq is padded
        # to a power-of-two bucket (repeating the first row) so repeat
        # runs hit a handful of stable program shapes — compiles are
        # the dominant folding cost on a remote-attached TPU
        uniq, slots = np.unique(rows_np[b0:b1], return_inverse=True)
        nu = 1 << int(np.ceil(np.log2(len(uniq))))
        uniq = np.pad(uniq, (0, nu - len(uniq)), mode="edge")
        d0b, posb, stepb = (a[b0:b1] for a in rtabs_np)
        nb_t, w = posb.shape[1], posb.shape[2]
        packed_np = np.concatenate([
            d0b.ravel(), posb.ravel(), stepb.ravel(),
            uniq.astype(np.int32), slots.astype(np.int32),
        ]).astype(np.int32)
        # content-keyed device-input cache: a repeat fold of the same
        # candidates (benchmark reruns, checkpoint resumes) pays ZERO
        # uploads — same upload-once policy as the search's
        # _device_inputs; keys are digests so the cache holds a few
        # dozen bytes per entry, not the ~100 KB packed tables
        import hashlib

        pkey = (nsamps, b0,
                hashlib.sha256(packed_np.tobytes()).digest(),
                hashlib.sha256(periods_np[b0:b1].tobytes()).digest())
        dev = cache.get(pkey)
        if dev is None:
            dev = (jnp.asarray(packed_np),
                   jnp.asarray(periods_np[b0:b1]))
            cache[pkey] = dev
        packed_d, periods_d = dev
        packed = fetch_to_host(fp(
            packed_d, periods_d, bin_width, nsamps,
            float(tsamp), nbins, nints, fold_ms, fold_block,
            nu, nb_t, w,
        ))
        argmaxes[b0:b1] = packed[:m].astype(np.int64)
        opt_folds[b0:b1] = packed[m : m + m * nints * nbins].reshape(
            m, nints, nbins)
        opt_profs[b0:b1] = packed[m + m * nints * nbins :].reshape(
            m, nbins)
    for k, ci in enumerate(fold_ids):
        cand = cands[ci]
        period = 1.0 / cand.freq
        opt = finalise_fold(
            int(argmaxes[k]), opt_profs[k], opt_folds[k], period, tobs
        )
        cand.folded_snr = opt.opt_sn
        cand.fold = opt.opt_fold
        cand.nbins = nbins
        cand.nints = nints
        cand.opt_period = opt.opt_period
    cands.sort(key=lambda c: -max(c.snr, c.folded_snr))


def load_dm_file(filename: str) -> np.ndarray:
    """Parse a one-DM-per-line trial list (user-supplied grid, the
    file-based face of ``dedisp_set_dm_list``, `dedisperser.hpp:34-48`).
    Blank lines and ``#`` comments are skipped."""
    vals: list[float] = []
    with open(filename) as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if line:
                vals.append(float(line))
    return np.asarray(vals, dtype=np.float32)


def load_killmask(filename: str, nchans: int) -> np.ndarray:
    """Parse a one-0/1-per-line channel mask (`dedisperser.hpp:71-95`)."""
    vals: list[int] = []
    with open(filename) as f:
        for line in f:
            if len(vals) >= nchans:
                break
            line = line.strip()
            if line:
                vals.append(int(line))
    if len(vals) != nchans:
        warn_event(
            "killmask_mismatch",
            "killmask is not the same size as nchans; ignoring",
            killmask_len=len(vals), nchans=int(nchans), path=filename,
        )
        return np.ones(nchans, np.float32)
    return np.array(vals, dtype=np.float32)
