"""Per-DM-trial candidate checkpointing (no reference equivalent).

The reference pipeline is single-shot: a crash in a multi-hour search
loses everything (SURVEY.md section 5 — "No retry, no checkpoint, no
partial-result recovery").  Here the host-loop driver checkpoints its
per-DM candidate lists every ``interval`` trials and the mesh driver
checkpoints once after its (single-dispatch) search, so a re-run with
the same input and configuration resumes instead of recomputing.

The checkpoint key ties the file to the exact search: observation
CONTENT identity (header fields + data geometry, NOT the input path —
a survey spool must be relocatable without invalidating every resume,
serve/queue.py), and every result-affecting ``SearchConfig`` field.
A key mismatch invalidates the checkpoint with a warning (the search
runs afresh).
"""

from __future__ import annotations

import json
import os
from dataclasses import asdict

import numpy as np

from ..data.candidates import Candidate
from ..errors import CheckpointError
from ..obs.events import warn_event
from ..obs.metrics import REGISTRY as METRICS

# v3: append-only JSONL — header line then one line per completed DM
# row, so each save is O(rows added) not O(all rows accumulated)
# (v2 re-serialised the whole dict per save: O(ndm^2/interval) I/O
# over a run; v1 was pickle — dropped because unpickling a user-named
# file executes arbitrary code on a substituted checkpoint).
# v4: the key's input identity is the header/geometry fingerprint, not
# the absolute path — moving or renaming the observation (or the whole
# spool) no longer discards a resume; paths are advisory header fields
# v5: candidates carry the jerk axis (ISSUE 13) and the config identity
# gains jerk_start/jerk_end/jerk_step/trial_lattice; a v4 file remains
# resumable when the search has no jerk axis and an f32 trial lattice
# (see legacy_search_keys) — its rows deserialise with jerk=0.0
_FORMAT_VERSION = 5

#: config fields that did not exist in v4 checkpoints; stripped when
#: computing the v4-compat key for migration
_V5_NEW_FIELDS = ("jerk_start", "jerk_end", "jerk_step", "trial_lattice")


# presentation/runtime knobs that do not change the search's results
# (note: compact_capacity and max_num_threads DO stay in the key — both
# can alter the mesh driver's candidate set via buffer truncation).
# Sidecar PATHS (kill/zap/dm_file) are non-identity like the input
# path: their CONTENT enters the key via the digests below, so editing
# a sidecar still invalidates but relocating it does not.
_NON_IDENTITY_FIELDS = {
    "verbose", "progress_bar", "checkpoint_file", "checkpoint_interval",
    "outdir", "accel_chunk", "dump_dir", "measure_stages", "tune_file",
    "events_log", "metrics_json", "infilename", "killfilename",
    "zapfilename", "dm_file",
    # extraction lowering: changes WHEN work happens, never which
    # candidates are produced (ops/peaks.py) — like the buffer sizes
    "peaks_method",
}


def _file_digest(path: str) -> str:
    """Content hash of a sidecar file (kill/zap list); '' if unset."""
    if not path:
        return ""
    import hashlib

    try:
        with open(path, "rb") as f:
            return hashlib.sha256(f.read()).hexdigest()
    except OSError:
        return "<unreadable>"


def observation_fingerprint(fil) -> str:
    """Content identity of an observation: sha256 over every SIGPROC
    header field plus the loaded data geometry.  Two copies of the
    same filterbank fingerprint identically wherever they live; any
    header or geometry difference (tsamp, fch1, nbits, sample count,
    even source_name) separates them."""
    import hashlib

    h = hashlib.sha256()
    for k, v in sorted(fil.header.to_dict().items()):
        h.update(f"{k}={v!r};".encode())
    h.update(f"nsamps={fil.nsamps};nchans={fil.nchans}".encode())
    return h.hexdigest()


def search_key(infile: str, fil, config) -> str:
    """Stable identity of a search (observation content + geometry +
    parameters).

    The input enters by header/geometry FINGERPRINT, not by path:
    relocating a spool directory (or the observation itself) must not
    invalidate every resume (``infile`` is kept in the signature as
    an advisory-only argument for callers and the checkpoint header).
    Kill/zap/dm-list sidecar files likewise enter by CONTENT hash, so
    editing one between crash and resume invalidates the checkpoint
    but moving it does not.
    """
    return _search_key_impl(fil, config, _FORMAT_VERSION)


def _search_key_impl(fil, config, version: int, drop: tuple = ()) -> str:
    hdr = fil.header
    cfg_items = sorted(
        # a custom dm_list enters as an explicit tuple: repr() of a long
        # ndarray elides the middle with "...", which would alias the
        # keys of different grids
        (k, tuple(float(x) for x in np.asarray(v).ravel())
         if k == "dm_list" and v is not None else v)
        for k, v in asdict(config).items()
        if k not in _NON_IDENTITY_FIELDS and k not in drop
    )
    return repr((
        version, observation_fingerprint(fil),
        fil.nsamps, fil.nchans, hdr.nbits, float(hdr.tsamp),
        float(hdr.fch1), float(hdr.foff), cfg_items,
        _file_digest(config.killfilename),
        _file_digest(config.zapfilename),
        _file_digest(getattr(config, "dm_file", "")),
    ))


def legacy_search_keys(infile: str, fil, config) -> dict[int, str]:
    """Keys under which OLDER checkpoint formats stay resumable.

    A v4 file — written before the jerk axis and trial lattice existed
    — describes the same search iff this one has no jerk axis and an
    f32 lattice ("auto" that resolved to f32 counts: quantisation
    never engages silently, pipeline passes the RESOLVED config here).
    Its v4-compat key is byte-identical to what the v4 writer emitted:
    version 4 with the v5-only config fields stripped.
    """
    jerk_free = (float(config.jerk_start) == 0.0
                 and float(config.jerk_end) == 0.0
                 and float(config.jerk_step) == 0.0)
    lattice = getattr(config, "trial_lattice", "f32")
    if not jerk_free or lattice not in ("auto", "f32"):
        return {}
    return {4: _search_key_impl(fil, config, 4, drop=_V5_NEW_FIELDS)}


def _cand_to_obj(c: Candidate) -> dict:
    """Candidate -> JSON-safe dict (recursive over assoc)."""
    obj = {
        "dm": c.dm, "dm_idx": c.dm_idx, "acc": c.acc, "jerk": c.jerk,
        "nh": c.nh,
        "snr": c.snr, "freq": c.freq, "folded_snr": c.folded_snr,
        "opt_period": c.opt_period, "is_adjacent": c.is_adjacent,
        "is_physical": c.is_physical,
        "ddm_count_ratio": c.ddm_count_ratio,
        "ddm_snr_ratio": c.ddm_snr_ratio,
        "nbins": c.nbins, "nints": c.nints,
        "assoc": [_cand_to_obj(a) for a in c.assoc],
    }
    if c.fold is not None:
        obj["fold"] = np.asarray(c.fold, np.float32).tolist()
    return obj


def _cand_from_obj(obj: dict) -> Candidate:
    assoc = [_cand_from_obj(a) for a in obj.get("assoc", [])]
    fold = obj.get("fold")
    return Candidate(
        dm=float(obj["dm"]), dm_idx=int(obj["dm_idx"]),
        acc=float(obj["acc"]),
        # absent in v4 rows: pre-jerk searches are jerk=0 by definition
        jerk=float(obj.get("jerk", 0.0)),
        nh=int(obj["nh"]), snr=float(obj["snr"]),
        freq=float(obj["freq"]), folded_snr=float(obj["folded_snr"]),
        opt_period=float(obj["opt_period"]),
        is_adjacent=bool(obj["is_adjacent"]),
        is_physical=bool(obj["is_physical"]),
        ddm_count_ratio=float(obj["ddm_count_ratio"]),
        ddm_snr_ratio=float(obj["ddm_snr_ratio"]),
        nbins=int(obj["nbins"]), nints=int(obj["nints"]),
        assoc=assoc,
        fold=None if fold is None else np.asarray(fold, np.float32),
    )


class SearchCheckpoint:
    """Append-only JSONL checkpoint of {dm_idx: [Candidate]} progress.

    Line 1 is the header ``{"version", "key"}``; every further line is
    one completed DM row ``{"dm_idx", "cands"}``.  Saves append ONLY
    rows not yet on disk, so ``maybe_save`` cost is independent of how
    many rows have accumulated.  A torn final line (crash mid-append)
    is detected on load, dropped, and truncated away so the resumed
    run's appends continue from a clean tail.

    JSON, not pickle: the path is user-named, and unpickling a
    corrupted or substituted file would execute arbitrary code."""

    def __init__(self, path: str, key: str, interval: int = 8,
                 advisory: dict | None = None,
                 legacy: dict[int, str] | None = None):
        self.path = path
        self.key = key
        self.interval = max(int(interval), 1)
        #: informational header fields (e.g. the input path at save
        #: time) — written alongside version/key, NEVER compared on
        #: load: the key carries the content identity
        self.advisory = dict(advisory or {})
        #: {older format version: compat key} under which a pre-v5
        #: checkpoint still resumes (see ``legacy_search_keys``); rows
        #: from such a file deserialise with jerk=0.0 and appends keep
        #: its original header (v5 only ADDS an optional row field)
        self.legacy = dict(legacy or {})
        self._since_save = 0
        self._written: set[int] = set()
        self._resuming = False  # load() found a valid same-key file

    def load(self) -> dict[int, list[Candidate]] | None:
        """Return completed per-DM candidates, or None if absent/stale."""
        if not self.path or not os.path.exists(self.path):
            return None
        try:
            with open(self.path) as f:
                lines = f.readlines()
            # same torn-tail rule as row lines: a crash that flushed
            # the header JSON without its newline would merge row 1
            # onto the header on the next append, so a newline-less
            # header means "no usable checkpoint" (overwritable)
            if lines and not lines[0].endswith("\n"):
                raise CheckpointError("unterminated header line")
            header = json.loads(lines[0]) if lines else None
            if not isinstance(header, dict):
                raise CheckpointError("missing header line")
        except Exception as exc:
            warn_event(
                "checkpoint_invalid",
                f"ignoring unreadable checkpoint {self.path!r}: {exc}",
                path=self.path, reason="unreadable", error=str(exc),
            )
            return None
        version = header.get("version")
        if version != _FORMAT_VERSION:
            compat = self.legacy.get(version)
            if compat is None or header.get("key") != compat:
                warn_event(
                    "checkpoint_invalid",
                    f"ignoring checkpoint {self.path!r}: format version "
                    f"{version} != {_FORMAT_VERSION}",
                    path=self.path, reason="version_mismatch",
                    found=version, expected=_FORMAT_VERSION,
                )
                return None
            # migration: an older-format file whose compat key matches
            # resumes in place — this run's appends continue under the
            # original header (the row format is append-compatible)
            warn_event(
                "checkpoint_migrated",
                f"resuming v{version} checkpoint {self.path!r} under "
                f"format v{_FORMAT_VERSION} (jerk-free search)",
                path=self.path, found=version, expected=_FORMAT_VERSION,
            )
        elif header.get("key") != self.key:
            warn_event(
                "checkpoint_invalid",
                f"ignoring checkpoint {self.path!r}: it belongs to a "
                "different search (input/config mismatch)",
                path=self.path, reason="key_mismatch",
            )
            return None
        out: dict[int, list[Candidate]] = {}
        # byte offsets, not character counts: truncate() takes bytes
        # and the key can embed non-ASCII input paths
        good_bytes = len(lines[0].encode("utf-8"))
        for ln, line in enumerate(lines[1:], start=2):
            try:
                if not line.endswith("\n"):
                    # a crash between json.dump(row) and the newline
                    # write leaves a VALID-JSON newline-less tail; the
                    # next append would merge two rows onto one line,
                    # so a missing terminator is torn regardless of
                    # parseability
                    raise CheckpointError("unterminated final line")
                row = json.loads(line)
                out[int(row["dm_idx"])] = [
                    _cand_from_obj(o) for o in row["cands"]
                ]
            except Exception:
                # torn tail from a crash mid-append: keep the rows
                # before it and truncate the garbage so this run's
                # appends land on a clean line boundary
                warn_event(
                    "checkpoint_torn_tail",
                    f"checkpoint {self.path!r}: dropping corrupt data "
                    f"from line {ln} ({len(out)} completed rows kept)",
                    path=self.path, line=ln, rows_kept=len(out),
                )
                with open(self.path, "r+") as f:
                    f.truncate(good_bytes)
                break
            good_bytes += len(line.encode("utf-8"))
        self._written = set(out)
        self._resuming = True
        # resume observability: the survey worker's smoke/serve tests
        # assert a re-claimed job resumed instead of recomputing
        METRICS.inc("checkpoint.resumes")
        METRICS.inc("checkpoint.rows_resumed", len(out))
        return out

    def _append_rows(self, cands_by_dm: dict) -> None:
        new = [k for k in cands_by_dm if k not in self._written]
        if not new and self._resuming:
            return
        mode = "a" if (self._resuming or self._written) else "w"
        with open(self.path, mode) as f:
            if mode == "w":
                json.dump({"version": _FORMAT_VERSION, "key": self.key,
                           **self.advisory}, f)
                f.write("\n")
            for k in new:
                json.dump({"dm_idx": int(k),
                           "cands": [_cand_to_obj(c)
                                     for c in cands_by_dm[k]]}, f)
                f.write("\n")
        self._written.update(new)
        self._resuming = True  # header now on disk

    def save(self, cands_by_dm: dict[int, list[Candidate]]) -> None:
        self._append_rows(cands_by_dm)

    def maybe_save(self, cands_by_dm: dict[int, list[Candidate]]) -> None:
        """Append new rows every ``interval`` calls (host-loop cadence
        control); each save's cost is O(rows added since last save)."""
        self._since_save += 1
        if self._since_save >= self.interval:
            self.save(cands_by_dm)
            self._since_save = 0

    def remove(self) -> None:
        """Drop the checkpoint after a fully successful run."""
        if self.path and os.path.exists(self.path):
            os.remove(self.path)
