"""Per-DM-trial candidate checkpointing (no reference equivalent).

The reference pipeline is single-shot: a crash in a multi-hour search
loses everything (SURVEY.md section 5 — "No retry, no checkpoint, no
partial-result recovery").  Here the host-loop driver checkpoints its
per-DM candidate lists every ``interval`` trials and the mesh driver
checkpoints once after its (single-dispatch) search, so a re-run with
the same input and configuration resumes instead of recomputing.

The checkpoint key ties the file to the exact search: input path,
filterbank geometry, and every ``SearchConfig`` field.  A key mismatch
silently invalidates the checkpoint (the search simply runs afresh).
"""

from __future__ import annotations

import os
import pickle
from dataclasses import asdict

from ..data.candidates import Candidate

_FORMAT_VERSION = 1


# presentation/runtime knobs that do not change the search's results
# (note: compact_capacity and max_num_threads DO stay in the key — both
# can alter the mesh driver's candidate set via buffer truncation)
_NON_IDENTITY_FIELDS = {
    "verbose", "progress_bar", "checkpoint_file", "checkpoint_interval",
    "outdir", "accel_chunk",
}


def _file_digest(path: str) -> str:
    """Content hash of a sidecar file (kill/zap list); '' if unset."""
    if not path:
        return ""
    import hashlib

    try:
        with open(path, "rb") as f:
            return hashlib.sha256(f.read()).hexdigest()
    except OSError:
        return "<unreadable>"


def search_key(infile: str, fil, config) -> str:
    """Stable identity of a search (input + geometry + parameters).

    Kill/zap sidecar files enter by CONTENT hash, not just path, so
    editing them between crash and resume invalidates the checkpoint.
    """
    hdr = fil.header
    cfg_items = sorted(
        (k, v) for k, v in asdict(config).items()
        if k not in _NON_IDENTITY_FIELDS
    )
    return repr((
        _FORMAT_VERSION, os.path.abspath(infile or config.infilename),
        fil.nsamps, fil.nchans, hdr.nbits, float(hdr.tsamp),
        float(hdr.fch1), float(hdr.foff), cfg_items,
        _file_digest(config.killfilename),
        _file_digest(config.zapfilename),
    ))


class SearchCheckpoint:
    """Atomic pickle checkpoint of {dm_idx: [Candidate]} progress."""

    def __init__(self, path: str, key: str, interval: int = 8):
        self.path = path
        self.key = key
        self.interval = max(int(interval), 1)
        self._since_save = 0

    def load(self) -> dict[int, list[Candidate]] | None:
        """Return completed per-DM candidates, or None if absent/stale."""
        if not self.path or not os.path.exists(self.path):
            return None
        try:
            with open(self.path, "rb") as f:
                payload = pickle.load(f)
            if (
                not isinstance(payload, dict)
                or payload.get("key") != self.key
            ):
                return None
            return payload["cands_by_dm"]
        except Exception:
            return None

    def save(self, cands_by_dm: dict[int, list[Candidate]]) -> None:
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump({"key": self.key, "cands_by_dm": cands_by_dm}, f)
        os.replace(tmp, self.path)

    def maybe_save(self, cands_by_dm: dict[int, list[Candidate]]) -> None:
        """Save every ``interval`` calls (host-loop cadence control).

        Each save re-pickles the whole accumulated dict, so total
        checkpoint I/O over a run is O(ndm^2 / interval); keep
        ``interval`` >= the default for searches with many DM trials
        (interval=1 is for tests/tiny runs).
        """
        self._since_save += 1
        if self._since_save >= self.interval:
            self.save(cands_by_dm)
            self._since_save = 0

    def remove(self) -> None:
        """Drop the checkpoint after a fully successful run."""
        if self.path and os.path.exists(self.path):
            os.remove(self.path)
