from .plan import AccelerationPlan, SearchConfig, prev_power_of_two
from .distill import HarmonicDistiller, AccelerationDistiller, DMDistiller
from .score import CandidateScorer
