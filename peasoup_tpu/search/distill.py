"""Candidate distillation (de-duplication) passes.

Host-side greedy SNR-sorted dedup, exact semantics of
`include/transforms/distiller.hpp:16-197`:

* ``BaseDistiller.distill``: sort by SNR descending; walk the survivors
  in order, letting each "fundamental" absorb (mark non-unique, and
  optionally append to its ``assoc`` list) everything its ``condition``
  matches further down the list.
* ``HarmonicDistiller``: absorbs candidates whose frequency is a
  (fractional, up to 2^nh denominators) harmonic ratio of the
  fundamental within tolerance.
* ``AccelerationDistiller``: absorbs candidates whose frequency lies
  within the Doppler drift window f*da*tobs/c of the fundamental.
* ``DMDistiller``: absorbs candidates with matching frequency ratio
  regardless of DM.
"""

from __future__ import annotations

import numpy as np

from ..data.candidates import Candidate

SPEED_OF_LIGHT = 299792458.0


class BaseDistiller:
    def __init__(self, keep_related: bool):
        self.keep_related = keep_related

    def condition(self, cands, idx, unique):
        raise NotImplementedError

    def distill(self, cands: list[Candidate]) -> list[Candidate]:
        size = len(cands)
        # std::sort with snr-greater comparator; stable for determinism
        cands = sorted(cands, key=lambda c: -c.snr)
        unique = np.ones(size, dtype=bool)
        for idx in range(size):
            if unique[idx]:
                self.condition(cands, idx, unique)
        return [cands[i] for i in range(size) if unique[i]]


class HarmonicDistiller(BaseDistiller):
    def __init__(self, tol: float, max_harm: int, keep_related: bool,
                 fractional_harms: bool = True):
        super().__init__(keep_related)
        self.tolerance = tol
        self.max_harm = int(max_harm)
        self.fractional_harms = fractional_harms

    def condition(self, cands, idx, unique):
        fundi_freq = cands[idx].freq
        upper = 1 + self.tolerance
        lower = 1 - self.tolerance
        # like the reference, already-absorbed candidates are still
        # tested (and may be appended to this fundamental's assoc too)
        for ii in range(idx + 1, len(cands)):
            freq = cands[ii].freq
            nh = cands[ii].nh
            max_denominator = int(2.0 ** nh) if self.fractional_harms else 1
            matched = False
            for jj in range(1, self.max_harm + 1):
                for kk in range(1, max_denominator + 1):
                    ratio = kk * freq / (jj * fundi_freq)
                    if lower < ratio < upper:
                        matched = True
                        break
                if matched:
                    break
            if matched:
                if self.keep_related:
                    cands[idx].append(cands[ii])
                unique[ii] = False


class AccelerationDistiller(BaseDistiller):
    def __init__(self, tobs: float, tolerance: float, keep_related: bool):
        super().__init__(keep_related)
        self.tobs = tobs
        self.tobs_over_c = tobs / SPEED_OF_LIGHT
        self.tolerance = tolerance

    def correct_for_acceleration(self, freq, delta_acc):
        return freq + delta_acc * freq * self.tobs_over_c

    def condition(self, cands, idx, unique):
        fundi_freq = cands[idx].freq
        fundi_acc = cands[idx].acc
        edge = fundi_freq * self.tolerance
        for ii in range(idx + 1, len(cands)):
            delta_acc = fundi_acc - cands[ii].acc
            acc_freq = self.correct_for_acceleration(fundi_freq, delta_acc)
            if acc_freq > fundi_freq:
                hit = fundi_freq - edge < cands[ii].freq < acc_freq + edge
            else:
                hit = acc_freq - edge < cands[ii].freq < fundi_freq + edge
            if hit:
                if self.keep_related:
                    cands[idx].append(cands[ii])
                unique[ii] = False


class DMDistiller(BaseDistiller):
    def __init__(self, tolerance: float, keep_related: bool):
        super().__init__(keep_related)
        self.tolerance = tolerance

    def condition(self, cands, idx, unique):
        fundi_freq = cands[idx].freq
        upper = 1 + self.tolerance
        lower = 1 - self.tolerance
        for ii in range(idx + 1, len(cands)):
            ratio = cands[ii].freq / fundi_freq
            if lower < ratio < upper:
                if self.keep_related:
                    cands[idx].append(cands[ii])
                unique[ii] = False
