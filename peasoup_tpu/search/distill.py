"""Candidate distillation (de-duplication) passes.

Host-side greedy SNR-sorted dedup, exact semantics of
`include/transforms/distiller.hpp:16-197`:

* ``BaseDistiller.distill``: sort by SNR descending; walk the survivors
  in order, letting each "fundamental" absorb (mark non-unique, and
  optionally append to its ``assoc`` list) everything its match
  predicate hits further down the list.  Like the reference,
  already-absorbed candidates are still tested (and may be appended to
  several fundamentals' ``assoc`` lists).
* ``HarmonicDistiller``: absorbs candidates whose frequency is a
  (fractional, up to 2^nh denominators) harmonic ratio of the
  fundamental within tolerance.
* ``AccelerationDistiller``: absorbs candidates whose frequency lies
  within the Doppler drift window f*da*tobs/c of the fundamental.
* ``DMDistiller``: absorbs candidates with matching frequency ratio
  regardless of DM.

The O(n^2) pair predicates are vectorised over the trailing candidates
(the reference's inner loops, `distiller.hpp:69-197`, are per-pair).

Distillers are strictly per-observation: every pass runs over ONE
SearchResult's candidates.  Batched multi-observation dispatch
(ISSUE 9) preserves this — the driver keys its batched distillation
rows by ``(beam, dm_idx)`` so each beam's candidates flow through
separate native segments, and a fundamental in one beam can never
absorb a harmonic from a batch-mate.  Cross-OBSERVATION matching is a
different operation with different semantics (position/epoch aware)
and lives in the survey layer (``serve/store.py``'s coincidence
queries), not here.
"""

from __future__ import annotations

import numpy as np

from ..data.candidates import Candidate

SPEED_OF_LIGHT = 299792458.0


def _native_lib():
    try:
        from ..native import lib
    except Exception:
        return None
    return lib


# -- tolerance-margin helpers (candidate lineage, ISSUE 19) ----------------
# How far inside its distiller's acceptance window an absorbed
# candidate sat (>= 0; ~0 means a borderline absorption a slightly
# tighter tolerance would have kept).  Shared by the per-object
# ``pair_margin`` methods below and the mesh driver's segmented batch
# path, so both report identical margins for identical pairs.

def harmonic_margin(f_fund: float, f_abs: float, max_denom: int,
                    tol: float, max_harm: int) -> float:
    """tol minus the closest |k*f_abs/(j*f_fund) - 1| over the (j, k)
    ratio grid the harmonic predicate searched."""
    jj = np.arange(1, max(int(max_harm), 1) + 1, dtype=np.float64)
    kk = np.arange(1, max(int(max_denom), 1) + 1, dtype=np.float64)
    ratio = kk[:, None] * float(f_abs) / (jj[None, :] * float(f_fund))
    return float(tol - np.abs(ratio - 1.0).min())


def drift_margin(f_fund: float, f_abs: float, drift: float,
                 tol: float) -> float:
    """Distance of ``f_abs`` from the nearer edge of the drift window
    [min(f, f+drift*f) - tol*f, max(f, f+drift*f) + tol*f], as a
    fraction of ``f_fund`` (the accel/jerk window shape)."""
    f0 = float(f_fund)
    shifted = f0 + float(drift) * f0
    edge = f0 * float(tol)
    lo = min(shifted, f0) - edge
    hi = max(shifted, f0) + edge
    return float(min(float(f_abs) - lo, hi - float(f_abs)) / f0)


def dm_margin(f_fund: float, f_abs: float, tol: float) -> float:
    """tol minus |f_abs/f_fund - 1| (the DM distiller's freq-ratio
    window)."""
    return float(tol - abs(float(f_abs) / float(f_fund) - 1.0))


class BaseDistiller:
    #: native predicate id for distill_greedy, or None (numpy path only)
    native_type: int | None = None

    #: lineage rule name stamped on absorption decisions (ISSUE 19)
    rule = "distill"

    def __init__(self, keep_related: bool):
        self.keep_related = keep_related

    def matches(self, idx: int) -> np.ndarray:
        """Bool array over candidates idx+1.. that this fundamental
        absorbs."""
        raise NotImplementedError

    def pair_margin(self, fi: int, ai: int) -> float:
        """Tolerance margin of the (fundamental ``fi``, absorbed
        ``ai``) pair — how far inside the acceptance window the
        absorption sat.  Valid after :meth:`setup`; indices are into
        the SNR-sorted candidate order."""
        return 0.0

    def match_counts(self, idx: int) -> np.ndarray:
        """Int array over candidates idx+1..: how many times each is
        absorbed (the reference appends one assoc entry per matching
        predicate combination — only >1 for the harmonic distiller's
        (j,k) grid, `distiller.hpp:91-100`)."""
        return self.matches(idx).astype(np.int64)

    def setup(self, cands: list[Candidate]) -> None:
        self.freqs = np.array([c.freq for c in cands], np.float64)

    def native_args(self) -> tuple:
        """(aux_array, max_harm, tobs_over_c) for distill_greedy."""
        raise NotImplementedError

    def distill(self, cands: list[Candidate],
                on_decision=None) -> list[Candidate]:
        """Greedy SNR-sorted dedup; survivors in sorted order.

        ``on_decision(fundamental, absorbed, margin)`` — the lineage
        callback (ISSUE 19) — fires once per absorbed candidate, for
        its FIRST (highest-SNR) absorber, with the pair's tolerance
        margin.  Purely observational: candidate output (uniqueness
        AND assoc lists) is bit-identical with or without it.
        """
        size = len(cands)
        # std::sort with snr-greater comparator; stable for determinism
        cands = sorted(cands, key=lambda c: -c.snr)
        self.setup(cands)
        native = _native_lib() if self.native_type is not None else None
        if native is not None:
            aux, max_harm, tobs_over_c = self.native_args()
            # pair recording only feeds assoc/lineage; uniqueness is
            # independent of the flag (native/distill.c), so asking
            # for pairs never changes the survivors
            record = self.keep_related or on_decision is not None
            unique, pf, pa = native.distill_greedy(
                self.native_type, self.freqs, aux, self.tolerance,
                max_harm, tobs_over_c, record,
            )
            if self.keep_related:
                for fi, ai in zip(pf, pa):
                    cands[fi].append(cands[ai])
            if on_decision is not None:
                seen: set[int] = set()  # pairs are in walk order:
                for fi, ai in zip(pf, pa):  # first absorber wins
                    if ai not in seen:
                        seen.add(ai)
                        on_decision(cands[fi], cands[ai],
                                    self.pair_margin(int(fi), int(ai)))
            return [cands[i] for i in range(size) if unique[i]]
        unique = np.ones(size, dtype=bool)
        for idx in range(size):
            if not unique[idx]:
                continue
            counts = self.match_counts(idx)
            hit = np.nonzero(counts)[0] + idx + 1
            if self.keep_related:
                for ii in hit:
                    for _ in range(int(counts[ii - idx - 1])):
                        cands[idx].append(cands[ii])
            if on_decision is not None:
                for ii in hit:
                    if unique[ii]:  # first absorber wins
                        on_decision(cands[idx], cands[ii],
                                    self.pair_margin(int(idx), int(ii)))
            unique[hit] = False
        return [cands[i] for i in range(size) if unique[i]]


class HarmonicDistiller(BaseDistiller):
    native_type = 0
    rule = "harmonic"

    def __init__(self, tol: float, max_harm: int, keep_related: bool,
                 fractional_harms: bool = True):
        super().__init__(keep_related)
        self.tolerance = tol
        self.max_harm = int(max_harm)
        self.fractional_harms = fractional_harms

    def native_args(self):
        return self.max_denoms.astype(np.float64), self.max_harm, 0.0

    def setup(self, cands):
        super().setup(cands)
        if self.fractional_harms:
            self.max_denoms = np.array(
                [int(2.0 ** c.nh) for c in cands], np.int64
            )
            kmax = int(self.max_denoms.max(initial=1))
        else:
            self.max_denoms = np.ones(len(cands), np.int64)
            kmax = 1
        self.jj = np.arange(1, self.max_harm + 1, dtype=np.float64)
        self.kk = np.arange(1, kmax + 1, dtype=np.float64)

    def _ok_grid(self, idx):
        fundi_freq = self.freqs[idx]
        freqs = self.freqs[idx + 1 :]
        # ratio[i, k, j] = kk[k] * f_i / (jj[j] * f0)
        ratio = (
            self.kk[None, :, None]
            * freqs[:, None, None]
            / (self.jj[None, None, :] * fundi_freq)
        )
        ok = (ratio > 1 - self.tolerance) & (ratio < 1 + self.tolerance)
        ok &= self.kk[None, :, None] <= self.max_denoms[idx + 1 :, None, None]
        return ok

    def matches(self, idx):
        return self._ok_grid(idx).any(axis=(1, 2))

    def match_counts(self, idx):
        # one absorption per matching (j,k), like distiller.hpp:91-100
        return self._ok_grid(idx).sum(axis=(1, 2))

    def pair_margin(self, fi, ai):
        return harmonic_margin(self.freqs[fi], self.freqs[ai],
                               int(self.max_denoms[ai]),
                               self.tolerance, self.max_harm)


class AccelerationDistiller(BaseDistiller):
    native_type = 1
    rule = "accel"

    def __init__(self, tobs: float, tolerance: float, keep_related: bool):
        super().__init__(keep_related)
        self.tobs = tobs
        self.tobs_over_c = tobs / SPEED_OF_LIGHT
        self.tolerance = tolerance

    def native_args(self):
        return self.accs, 0, self.tobs_over_c

    def setup(self, cands):
        super().setup(cands)
        self.accs = np.array([c.acc for c in cands], np.float64)

    def matches(self, idx):
        fundi_freq = self.freqs[idx]
        freqs = self.freqs[idx + 1 :]
        delta_acc = self.accs[idx] - self.accs[idx + 1 :]
        acc_freq = fundi_freq + delta_acc * fundi_freq * self.tobs_over_c
        edge = fundi_freq * self.tolerance
        lo = np.minimum(acc_freq, fundi_freq) - edge
        hi = np.maximum(acc_freq, fundi_freq) + edge
        return (freqs > lo) & (freqs < hi)

    def pair_margin(self, fi, ai):
        drift = (self.accs[fi] - self.accs[ai]) * self.tobs_over_c
        return drift_margin(self.freqs[fi], self.freqs[ai], drift,
                            self.tolerance)


class JerkDistiller(BaseDistiller):
    """Jerk-adjacent de-dup (ISSUE 13): the jerk-axis analogue of
    :class:`AccelerationDistiller`.  A jerk mismatch dj smears a
    signal's apparent frequency by up to f*|dj|*tobs^2/(6c) over the
    observation (the cubic resample term's peak fractional shift), so
    a fundamental absorbs candidates whose frequency sits inside that
    drift window plus the usual tolerance edge.  Runs only when the
    search has >1 jerk trial — accel-only runs never construct it, so
    their distillation chain is untouched.  Python-vectorised only
    (no native predicate id; jerk grids are small)."""

    native_type = None
    rule = "jerk"

    def __init__(self, tobs: float, tolerance: float, keep_related: bool):
        super().__init__(keep_related)
        self.tobs = tobs
        self.tobs2_over_6c = tobs * tobs / (6.0 * SPEED_OF_LIGHT)
        self.tolerance = tolerance

    def setup(self, cands):
        super().setup(cands)
        self.jerks = np.array([c.jerk for c in cands], np.float64)

    def matches(self, idx):
        fundi_freq = self.freqs[idx]
        freqs = self.freqs[idx + 1 :]
        delta_jerk = self.jerks[idx] - self.jerks[idx + 1 :]
        jerk_freq = (fundi_freq
                     + delta_jerk * fundi_freq * self.tobs2_over_6c)
        edge = fundi_freq * self.tolerance
        lo = np.minimum(jerk_freq, fundi_freq) - edge
        hi = np.maximum(jerk_freq, fundi_freq) + edge
        return (freqs > lo) & (freqs < hi)

    def pair_margin(self, fi, ai):
        drift = (self.jerks[fi] - self.jerks[ai]) * self.tobs2_over_6c
        return drift_margin(self.freqs[fi], self.freqs[ai], drift,
                            self.tolerance)


class DMDistiller(BaseDistiller):
    native_type = 2
    rule = "dm"

    def __init__(self, tolerance: float, keep_related: bool):
        super().__init__(keep_related)
        self.tolerance = tolerance

    def native_args(self):
        return np.zeros_like(self.freqs), 0, 0.0

    def matches(self, idx):
        ratio = self.freqs[idx + 1 :] / self.freqs[idx]
        return (ratio > 1 - self.tolerance) & (ratio < 1 + self.tolerance)

    def pair_margin(self, fi, ai):
        return dm_margin(self.freqs[fi], self.freqs[ai],
                         self.tolerance)
