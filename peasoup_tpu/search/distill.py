"""Candidate distillation (de-duplication) passes.

Host-side greedy SNR-sorted dedup, exact semantics of
`include/transforms/distiller.hpp:16-197`:

* ``BaseDistiller.distill``: sort by SNR descending; walk the survivors
  in order, letting each "fundamental" absorb (mark non-unique, and
  optionally append to its ``assoc`` list) everything its match
  predicate hits further down the list.  Like the reference,
  already-absorbed candidates are still tested (and may be appended to
  several fundamentals' ``assoc`` lists).
* ``HarmonicDistiller``: absorbs candidates whose frequency is a
  (fractional, up to 2^nh denominators) harmonic ratio of the
  fundamental within tolerance.
* ``AccelerationDistiller``: absorbs candidates whose frequency lies
  within the Doppler drift window f*da*tobs/c of the fundamental.
* ``DMDistiller``: absorbs candidates with matching frequency ratio
  regardless of DM.

The O(n^2) pair predicates are vectorised over the trailing candidates
(the reference's inner loops, `distiller.hpp:69-197`, are per-pair).

Distillers are strictly per-observation: every pass runs over ONE
SearchResult's candidates.  Batched multi-observation dispatch
(ISSUE 9) preserves this — the driver keys its batched distillation
rows by ``(beam, dm_idx)`` so each beam's candidates flow through
separate native segments, and a fundamental in one beam can never
absorb a harmonic from a batch-mate.  Cross-OBSERVATION matching is a
different operation with different semantics (position/epoch aware)
and lives in the survey layer (``serve/store.py``'s coincidence
queries), not here.
"""

from __future__ import annotations

import numpy as np

from ..data.candidates import Candidate

SPEED_OF_LIGHT = 299792458.0


def _native_lib():
    try:
        from ..native import lib
    except Exception:
        return None
    return lib


class BaseDistiller:
    #: native predicate id for distill_greedy, or None (numpy path only)
    native_type: int | None = None

    def __init__(self, keep_related: bool):
        self.keep_related = keep_related

    def matches(self, idx: int) -> np.ndarray:
        """Bool array over candidates idx+1.. that this fundamental
        absorbs."""
        raise NotImplementedError

    def match_counts(self, idx: int) -> np.ndarray:
        """Int array over candidates idx+1..: how many times each is
        absorbed (the reference appends one assoc entry per matching
        predicate combination — only >1 for the harmonic distiller's
        (j,k) grid, `distiller.hpp:91-100`)."""
        return self.matches(idx).astype(np.int64)

    def setup(self, cands: list[Candidate]) -> None:
        self.freqs = np.array([c.freq for c in cands], np.float64)

    def native_args(self) -> tuple:
        """(aux_array, max_harm, tobs_over_c) for distill_greedy."""
        raise NotImplementedError

    def distill(self, cands: list[Candidate]) -> list[Candidate]:
        size = len(cands)
        # std::sort with snr-greater comparator; stable for determinism
        cands = sorted(cands, key=lambda c: -c.snr)
        self.setup(cands)
        native = _native_lib() if self.native_type is not None else None
        if native is not None:
            aux, max_harm, tobs_over_c = self.native_args()
            unique, pf, pa = native.distill_greedy(
                self.native_type, self.freqs, aux, self.tolerance,
                max_harm, tobs_over_c, self.keep_related,
            )
            if self.keep_related:
                for fi, ai in zip(pf, pa):
                    cands[fi].append(cands[ai])
            return [cands[i] for i in range(size) if unique[i]]
        unique = np.ones(size, dtype=bool)
        for idx in range(size):
            if not unique[idx]:
                continue
            counts = self.match_counts(idx)
            hit = np.nonzero(counts)[0] + idx + 1
            if self.keep_related:
                for ii in hit:
                    for _ in range(int(counts[ii - idx - 1])):
                        cands[idx].append(cands[ii])
            unique[hit] = False
        return [cands[i] for i in range(size) if unique[i]]


class HarmonicDistiller(BaseDistiller):
    native_type = 0

    def __init__(self, tol: float, max_harm: int, keep_related: bool,
                 fractional_harms: bool = True):
        super().__init__(keep_related)
        self.tolerance = tol
        self.max_harm = int(max_harm)
        self.fractional_harms = fractional_harms

    def native_args(self):
        return self.max_denoms.astype(np.float64), self.max_harm, 0.0

    def setup(self, cands):
        super().setup(cands)
        if self.fractional_harms:
            self.max_denoms = np.array(
                [int(2.0 ** c.nh) for c in cands], np.int64
            )
            kmax = int(self.max_denoms.max(initial=1))
        else:
            self.max_denoms = np.ones(len(cands), np.int64)
            kmax = 1
        self.jj = np.arange(1, self.max_harm + 1, dtype=np.float64)
        self.kk = np.arange(1, kmax + 1, dtype=np.float64)

    def _ok_grid(self, idx):
        fundi_freq = self.freqs[idx]
        freqs = self.freqs[idx + 1 :]
        # ratio[i, k, j] = kk[k] * f_i / (jj[j] * f0)
        ratio = (
            self.kk[None, :, None]
            * freqs[:, None, None]
            / (self.jj[None, None, :] * fundi_freq)
        )
        ok = (ratio > 1 - self.tolerance) & (ratio < 1 + self.tolerance)
        ok &= self.kk[None, :, None] <= self.max_denoms[idx + 1 :, None, None]
        return ok

    def matches(self, idx):
        return self._ok_grid(idx).any(axis=(1, 2))

    def match_counts(self, idx):
        # one absorption per matching (j,k), like distiller.hpp:91-100
        return self._ok_grid(idx).sum(axis=(1, 2))


class AccelerationDistiller(BaseDistiller):
    native_type = 1

    def __init__(self, tobs: float, tolerance: float, keep_related: bool):
        super().__init__(keep_related)
        self.tobs = tobs
        self.tobs_over_c = tobs / SPEED_OF_LIGHT
        self.tolerance = tolerance

    def native_args(self):
        return self.accs, 0, self.tobs_over_c

    def setup(self, cands):
        super().setup(cands)
        self.accs = np.array([c.acc for c in cands], np.float64)

    def matches(self, idx):
        fundi_freq = self.freqs[idx]
        freqs = self.freqs[idx + 1 :]
        delta_acc = self.accs[idx] - self.accs[idx + 1 :]
        acc_freq = fundi_freq + delta_acc * fundi_freq * self.tobs_over_c
        edge = fundi_freq * self.tolerance
        lo = np.minimum(acc_freq, fundi_freq) - edge
        hi = np.maximum(acc_freq, fundi_freq) + edge
        return (freqs > lo) & (freqs < hi)


class JerkDistiller(BaseDistiller):
    """Jerk-adjacent de-dup (ISSUE 13): the jerk-axis analogue of
    :class:`AccelerationDistiller`.  A jerk mismatch dj smears a
    signal's apparent frequency by up to f*|dj|*tobs^2/(6c) over the
    observation (the cubic resample term's peak fractional shift), so
    a fundamental absorbs candidates whose frequency sits inside that
    drift window plus the usual tolerance edge.  Runs only when the
    search has >1 jerk trial — accel-only runs never construct it, so
    their distillation chain is untouched.  Python-vectorised only
    (no native predicate id; jerk grids are small)."""

    native_type = None

    def __init__(self, tobs: float, tolerance: float, keep_related: bool):
        super().__init__(keep_related)
        self.tobs = tobs
        self.tobs2_over_6c = tobs * tobs / (6.0 * SPEED_OF_LIGHT)
        self.tolerance = tolerance

    def setup(self, cands):
        super().setup(cands)
        self.jerks = np.array([c.jerk for c in cands], np.float64)

    def matches(self, idx):
        fundi_freq = self.freqs[idx]
        freqs = self.freqs[idx + 1 :]
        delta_jerk = self.jerks[idx] - self.jerks[idx + 1 :]
        jerk_freq = (fundi_freq
                     + delta_jerk * fundi_freq * self.tobs2_over_6c)
        edge = fundi_freq * self.tolerance
        lo = np.minimum(jerk_freq, fundi_freq) - edge
        hi = np.maximum(jerk_freq, fundi_freq) + edge
        return (freqs > lo) & (freqs < hi)


class DMDistiller(BaseDistiller):
    native_type = 2

    def __init__(self, tolerance: float, keep_related: bool):
        super().__init__(keep_related)
        self.tolerance = tolerance

    def native_args(self):
        return np.zeros_like(self.freqs), 0, 0.0

    def matches(self, idx):
        ratio = self.freqs[idx + 1 :] / self.freqs[idx]
        return (ratio > 1 - self.tolerance) & (ratio < 1 + self.tolerance)
