"""Persistent per-search buffer auto-tuning (no reference equivalent).

The reference sizes its peak buffer once at 100000 entries
(`include/transforms/peakfinder.hpp:17,61`) and silently truncates
beyond it.  This build instead uses small fixed-capacity buffers inside
the jitted programs and re-searches any DM row whose true count
exceeded them — no silent loss, but the re-run costs real time (per-row
dispatches plus fresh XLA compiles at the escalated capacity).

This module closes the loop across *runs*: a successful search records
its observed high-water marks (max per-spectrum above-threshold count,
max per-shard valid-peak total) in a tiny JSON sidecar keyed by the
same search identity the checkpoint uses.  The next run of the same
search sizes its buffers from the record, so

* the capacity covers the BULK of rows (when per-row counts are
  recorded, :func:`pick_row_capacity` deliberately leaves rare
  pathological rows — a blazing pulsar or RFI-loud trial — to the
  re-search path rather than inflate every spectrum's top_k), and
* the compacted transfer buffer shrinks from worst-case to observed
  size (+margin) -> less data over the (slow) device->host link.

A key mismatch (different input/config) ignores the record; results
are identical either way — buffer sizes only affect *when* work
happens, never which candidates are produced.
"""

from __future__ import annotations

import json
import os

from ..obs.events import warn_event

_TUNE_VERSION = 1


def load_tuning(path: str, key: str) -> dict | None:
    """Return {"cap_hw": int, "ck_hw": int, "row_hw": list|None} or
    None if absent/stale."""
    if not path or not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            obj = json.load(f)
    except Exception as exc:
        warn_event(
            "tune_io_error",
            f"ignoring unreadable tune file {path!r}: {exc}",
            path=path, op="load", error=str(exc),
        )
        return None
    if obj.get("version") != _TUNE_VERSION or obj.get("key") != key:
        return None
    try:
        row_hw = obj.get("row_hw")
        return {"cap_hw": int(obj["cap_hw"]), "ck_hw": int(obj["ck_hw"]),
                "row_hw": ([int(v) for v in row_hw]
                           if row_hw is not None else None)}
    except (KeyError, TypeError, ValueError):
        return None


def save_tuning(path: str, key: str, cap_hw: int, ck_hw: int,
                row_hw=None) -> None:
    """Atomically record the observed high-water marks.

    ``row_hw``: optional per-DM-row max above-threshold counts — lets
    the next run choose a capacity that covers the BULK of rows and
    leaves pathological ones (a blazing pulsar/RFI row whose count is
    10x everyone else's) to the cheap re-search path, instead of
    paying the loudest row's top_k capacity on every spectrum."""
    if not path:
        return
    tmp = path + ".tmp"
    try:
        obj = {"version": _TUNE_VERSION, "key": key,
               "cap_hw": int(cap_hw), "ck_hw": int(ck_hw)}
        if row_hw is not None:
            obj["row_hw"] = [int(v) for v in row_hw]
        with open(tmp, "w") as f:
            json.dump(obj, f)
        os.replace(tmp, path)
    except OSError as exc:
        warn_event(
            "tune_io_error",
            f"could not write tune file {path!r}: {exc}",
            path=path, op="save", error=str(exc),
        )


def pick_row_capacity(row_hw, n_accel_trials: int, quantum: int = 64,
                      lo: int = 64, hi: int = 1 << 20) -> int:
    """Capacity minimising (modelled) run cost from per-row counts.

    Raising the per-spectrum capacity makes EVERY accel trial's top_k
    bigger (measured on v5e at 2^22 bins: the 5-level extraction goes
    3.0 ms at cap 1024 -> 26 ms at cap 13184, ~1.9 us per slot per
    trial), while every row whose count exceeds the capacity costs one
    host-path re-search (~2 s with the shared-capacity compile).  A
    single pathological row must therefore NOT set the global
    capacity; this picks argmin over the distinct candidate caps.
    """
    import numpy as np

    m = np.asarray(row_hw, np.int64)
    slot_s = 1.9e-6 * max(n_accel_trials, 1)
    best_c, best_cost = None, None
    cands = sorted({round_up(int(v) + 32, quantum, lo, hi) for v in m})
    for c in cands:
        n_re = int((m > c).sum())
        cost = slot_s * c + 2.0 * n_re + (20.0 if n_re else 0.0)
        if best_cost is None or cost < best_cost:
            best_c, best_cost = c, cost
    return int(min(hi, max(lo, best_c if best_c is not None else lo)))


def round_up(value: int, quantum: int, lo: int, hi: int) -> int:
    """Round ``value`` up to a multiple of ``quantum``, clamped."""
    return int(min(hi, max(lo, -(-value // quantum) * quantum)))
