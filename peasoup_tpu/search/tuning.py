"""Persistent per-search buffer auto-tuning (no reference equivalent).

The reference sizes its peak buffer once at 100000 entries
(`include/transforms/peakfinder.hpp:17,61`) and silently truncates
beyond it.  This build instead uses small fixed-capacity buffers inside
the jitted programs and re-searches any DM row whose true count
exceeded them — no silent loss, but the re-run costs real time (per-row
dispatches plus fresh XLA compiles at the escalated capacity).

This module closes the loop across *runs*: a successful search records
its observed high-water marks (max per-spectrum above-threshold count,
max per-shard valid-peak total) in a tiny JSON sidecar keyed by the
same search identity the checkpoint uses.  The next run of the same
search sizes its buffers from the record, so

* no row clips -> the re-search phase disappears entirely, and
* the compacted transfer buffer shrinks from worst-case to observed
  size (+margin) -> less data over the (slow) device->host link.

A key mismatch (different input/config) ignores the record; results
are identical either way — buffer sizes only affect *when* work
happens, never which candidates are produced.
"""

from __future__ import annotations

import json
import os
import warnings

_TUNE_VERSION = 1


def load_tuning(path: str, key: str) -> dict | None:
    """Return {"cap_hw": int, "ck_hw": int} or None if absent/stale."""
    if not path or not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            obj = json.load(f)
    except Exception as exc:
        warnings.warn(f"ignoring unreadable tune file {path!r}: {exc}")
        return None
    if obj.get("version") != _TUNE_VERSION or obj.get("key") != key:
        return None
    try:
        return {"cap_hw": int(obj["cap_hw"]), "ck_hw": int(obj["ck_hw"])}
    except (KeyError, TypeError, ValueError):
        return None


def save_tuning(path: str, key: str, cap_hw: int, ck_hw: int) -> None:
    """Atomically record the observed high-water marks."""
    if not path:
        return
    tmp = path + ".tmp"
    try:
        with open(tmp, "w") as f:
            json.dump({"version": _TUNE_VERSION, "key": key,
                       "cap_hw": int(cap_hw), "ck_hw": int(ck_hw)}, f)
        os.replace(tmp, path)
    except OSError as exc:
        warnings.warn(f"could not write tune file {path!r}: {exc}")


def round_up(value: int, quantum: int, lo: int, hi: int) -> int:
    """Round ``value`` up to a multiple of ``quantum``, clamped."""
    return int(min(hi, max(lo, -(-value // quantum) * quantum)))
