"""Persistent per-search buffer auto-tuning (no reference equivalent).

The reference sizes its peak buffer once at 100000 entries
(`include/transforms/peakfinder.hpp:17,61`) and silently truncates
beyond it.  This build instead uses small fixed-capacity buffers inside
the jitted programs and re-searches any DM row whose true count
exceeded them — no silent loss, but the re-run costs real time (per-row
dispatches plus fresh XLA compiles at the escalated capacity).

This module closes the loop across *runs*: a successful search records
its observed high-water marks (max per-spectrum above-threshold count,
max per-shard valid-peak total) in a tiny JSON sidecar keyed by the
same search identity the checkpoint uses.  The next run of the same
search sizes its buffers from the record, so

* the capacity covers the BULK of rows (when per-row counts are
  recorded, :func:`pick_row_capacity` deliberately leaves rare
  pathological rows — a blazing pulsar or RFI-loud trial — to the
  re-search path rather than inflate every spectrum's top_k), and
* the compacted transfer buffer shrinks from worst-case to observed
  size (+margin) -> less data over the (slow) device->host link.

A key mismatch (different input/config) ignores the record; results
are identical either way — buffer sizes only affect *when* work
happens, never which candidates are produced.

Peak-extraction method selection (ISSUE 6)
------------------------------------------

The same sidecar file carries a second, search-key-INDEPENDENT
section, ``"extraction"``: measured per-spectrum extraction costs and
the picked lowering per ``(device kind, stop-index bucket, capacity)``
for the three peak-extraction methods (``sort`` / ``two_stage`` /
``pallas`` — see ``ops/peaks.py``).  Costs are written by
``benchmarks/micro.py peaks`` (standalone + in-program device time)
and ``benchmarks/peaks_sweep.py`` (which also records which two-stage
(C, stop, cap) cells are SAFE — the r5 sweep crashed a v5e worker at
C=64/stop=65537, so unsafe cells must never be picked);
:func:`resolve_peaks_methods` consumes them, falling back to the
committed v5e defaults (:data:`DEFAULT_EXTRACTION_COSTS`) and then to
the legacy size heuristic.  The section survives ``save_tuning``
rewrites and search-key mismatches: extraction costs are a property
of the device, not of one search.

Runtime cost calibration (ROADMAP item 5, first half)
-----------------------------------------------------

:func:`pick_row_capacity`'s cost model shipped with v5e-measured
constants (1.9e-6 s/slot/trial extraction, 2 s re-search, 20 s
compile), so capacity picks silently regressed on other TPU
generations.  The sidecar now carries a third search-key-INDEPENDENT
section, ``"calibration"``: per device kind, the *measured* per-slot
extraction cost (derived from this device's measured extraction cells
— cell cost / capacity for the method the run actually picked), the
measured re-search cost per clipped row, and the measured mean XLA
compile seconds (the ``jit_compile`` stage timer fed by
``obs.metrics.install_compile_hook``).  Each run merges its
measurements in via an exponential moving average
(:func:`record_run_calibration`, called where the drivers save their
high-water marks), and :func:`calibration_constants` hands them back
to ``pick_row_capacity`` — the hardcoded v5e constants remain the
fallback for a fresh sidecar or an unknown device.

Batch axis (ISSUE 9)
--------------------

Batched multi-observation dispatch (``MeshPulsarSearch.run_batch``)
deliberately does NOT extend either key with the batch width ``B``.
Every quantity this sidecar records is a per-spectrum / per-beam
figure — the max above-threshold count of ONE spectrum, the valid
-peak total of ONE beam's shard, the extraction cost of ONE spectrum's
top-k — because each beam in a batch compacts its own buffer through
the same per-beam program body a solo run uses.  A batched run
therefore saves the max over its beams' high-water marks under the
unchanged search key, and a hint recorded at ``B=4`` sizes a ``B=1``
run (or vice versa) exactly as well as one recorded solo.  Keying
cells by ``B`` would instead fragment the record (cold hints after
every batch-width change) for no information gain;
``tests/test_search.py::TestBatchedDispatch`` pins the invariance.
"""

from __future__ import annotations

import json
import os

from ..obs.events import warn_event

_TUNE_VERSION = 1

#: the selectable peak-extraction lowerings (ops/peaks.py)
EXTRACTION_METHODS = ("sort", "two_stage", "pallas")

#: hardcoded v5e cost-model fallbacks (see ``pick_row_capacity``);
#: overridden per device kind by the sidecar's measured calibration
DEFAULT_SLOT_S = 1.9e-6     # s per capacity slot per accel trial
DEFAULT_RESEARCH_S = 2.0    # s per re-searched clipped row
DEFAULT_COMPILE_S = 20.0    # s per fresh XLA compile

#: EWMA weight of the newest measurement when merging calibration
_CAL_ALPHA = 0.5


def load_tuning(path: str, key: str) -> dict | None:
    """Return {"cap_hw": int, "ck_hw": int, "row_hw": list|None} or
    None if absent/stale."""
    if not path or not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            obj = json.load(f)
    except Exception as exc:
        warn_event(
            "tune_io_error",
            f"ignoring unreadable tune file {path!r}: {exc}",
            path=path, op="load", error=str(exc),
        )
        return None
    if obj.get("version") != _TUNE_VERSION or obj.get("key") != key:
        return None
    try:
        row_hw = obj.get("row_hw")
        return {"cap_hw": int(obj["cap_hw"]), "ck_hw": int(obj["ck_hw"]),
                "row_hw": ([int(v) for v in row_hw]
                           if row_hw is not None else None)}
    except (KeyError, TypeError, ValueError):
        return None


def save_tuning(path: str, key: str, cap_hw: int, ck_hw: int,
                row_hw=None) -> None:
    """Atomically record the observed high-water marks.

    ``row_hw``: optional per-DM-row max above-threshold counts — lets
    the next run choose a capacity that covers the BULK of rows and
    leaves pathological ones (a blazing pulsar/RFI row whose count is
    10x everyone else's) to the cheap re-search path, instead of
    paying the loudest row's top_k capacity on every spectrum."""
    if not path:
        return
    tmp = path + ".tmp"
    try:
        obj = {"version": _TUNE_VERSION, "key": key,
               "cap_hw": int(cap_hw), "ck_hw": int(ck_hw)}
        if row_hw is not None:
            obj["row_hw"] = [int(v) for v in row_hw]
        # the extraction, calibration and lattice sections are
        # device-keyed, not search-keyed: carry them across rewrites
        # (and across search-key changes)
        extraction = load_extraction(path)
        if extraction:
            obj["extraction"] = extraction
        calibration = load_calibration(path)
        if calibration:
            obj["calibration"] = calibration
        lattice = load_lattice(path)
        if lattice:
            obj["lattice"] = lattice
        with open(tmp, "w") as f:
            json.dump(obj, f)
        os.replace(tmp, path)
    except OSError as exc:
        warn_event(
            "tune_io_error",
            f"could not write tune file {path!r}: {exc}",
            path=path, op="save", error=str(exc),
        )


def pick_row_capacity(row_hw, n_accel_trials: int, quantum: int = 64,
                      lo: int = 64, hi: int = 1 << 20, *,
                      slot_s: float | None = None,
                      research_s: float | None = None,
                      compile_s: float | None = None) -> int:
    """Capacity minimising (modelled) run cost from per-row counts.

    Raising the per-spectrum capacity makes EVERY accel trial's top_k
    bigger (measured on v5e at 2^22 bins: the 5-level extraction goes
    3.0 ms at cap 1024 -> 26 ms at cap 13184, ~1.9 us per slot per
    trial), while every row whose count exceeds the capacity costs one
    host-path re-search (~2 s with the shared-capacity compile).  A
    single pathological row must therefore NOT set the global
    capacity; this picks argmin over the distinct candidate caps.

    The three cost constants default to the v5e measurements above;
    pass :func:`calibration_constants` values to use this device's
    measured figures instead (self-calibrating tuner, ROADMAP item 5).
    """
    import numpy as np

    m = np.asarray(row_hw, np.int64)
    per_slot = DEFAULT_SLOT_S if slot_s is None else float(slot_s)
    re_s = DEFAULT_RESEARCH_S if research_s is None else float(research_s)
    comp_s = DEFAULT_COMPILE_S if compile_s is None else float(compile_s)
    slot_cost = per_slot * max(n_accel_trials, 1)
    best_c, best_cost = None, None
    cands = sorted({round_up(int(v) + 32, quantum, lo, hi) for v in m})
    for c in cands:
        n_re = int((m > c).sum())
        cost = slot_cost * c + re_s * n_re + (comp_s if n_re else 0.0)
        if best_cost is None or cost < best_cost:
            best_c, best_cost = c, cost
    return int(min(hi, max(lo, best_c if best_c is not None else lo)))


def round_up(value: int, quantum: int, lo: int, hi: int) -> int:
    """Round ``value`` up to a multiple of ``quantum``, clamped."""
    return int(min(hi, max(lo, -(-value // quantum) * quantum)))


# --------------------------------------------------------------------------
# peak-extraction method selection (ISSUE 6; see module docstring)
# --------------------------------------------------------------------------

#: committed v5e measurements (benchmarks/peaks_sweep.json +
#: benchmarks/micro.py peaks, r6 session): IN-PROGRAM device seconds
#: per single-spectrum extraction, keyed "stop_bucket/capacity".
#: In-program, not standalone — the r5 attribution gap: sorts inside
#: the fused program serialise against the surrounding ops and run
#: ~1.35x their standalone time, while the compaction kernel's
#: streaming pass overlaps cleanly (trace_summary_r6.md).  Buckets are
#: next-power-of-two of the searched prefix (the tutorial's five
#: harmonic levels land in 16384..131072; production 2^22-bin spectra
#: in 4194304).  Only relative order matters to the argmin.
DEFAULT_EXTRACTION_COSTS: dict[str, dict] = {
    "TPU v5 lite": {
        "16384/64":    {"sort": 1.8e-5, "two_stage": 9e-6,
                        "pallas": 3.1e-6},
        "16384/320":   {"sort": 2.1e-5, "two_stage": 2.8e-5,
                        "pallas": 3.2e-6},
        "32768/64":    {"sort": 3.2e-5, "two_stage": 1.2e-5,
                        "pallas": 4.0e-6},
        "32768/320":   {"sort": 3.6e-5, "two_stage": 4.6e-5,
                        "pallas": 4.1e-6},
        "65536/320":   {"sort": 8.2e-5, "two_stage": 1.04e-4,
                        "pallas": 5.0e-6},
        "65536/1024":  {"sort": 8.7e-5, "two_stage": 1.21e-4,
                        "pallas": 6.9e-6},
        "131072/64":   {"sort": 6.9e-5, "two_stage": 2.4e-5,
                        "pallas": 5.9e-6},
        "131072/320":  {"sort": 7.2e-5, "two_stage": 1.03e-4,
                        "pallas": 6.2e-6},
        "131072/1024": {"sort": 7.8e-5, "two_stage": 1.3e-4,
                        "pallas": 8.8e-6},
        "131072/2048": {"sort": 8.6e-5, "two_stage": 1.7e-4,
                        "pallas": 1.2e-5},
        "4194304/320": {"sort": 8.3e-3, "two_stage": 5.1e-4,
                        "pallas": 9.7e-5},
        "4194304/2048": {"sort": 8.9e-3, "two_stage": 9.4e-4,
                         "pallas": 1.4e-4},
    },
}

#: two-stage (row_width, min_stop) cells recorded UNSAFE by the sweep
#: (subprocess died / backend crash): C=64 with a >= 2^16 searched
#: prefix kills the v5e worker (Mosaic row count >= 1024 on a 64-lane
#: tile).  The narrow default (C=128, ops/peaks.py) avoids them; the
#: sweep refuses to re-run them outside --include-unsafe.
TWO_STAGE_UNSAFE: dict[str, list] = {
    "TPU v5 lite": [{"row_width": 64, "min_stop": 65536}],
}


def stop_bucket(stop_idx: int) -> int:
    """Next-power-of-two bucket of a searched-prefix length (the
    extraction cost table's row key)."""
    b = 1
    while b < max(int(stop_idx), 1):
        b <<= 1
    return b


def _cost_key(bucket: int, capacity: int) -> str:
    return f"{int(bucket)}/{int(capacity)}"


def _cell_for(table: dict, bucket: int, capacity: int) -> dict | None:
    """The (stop bucket, capacity) cost cell, falling back to the
    NEAREST-capacity cell within the same stop bucket.

    The fallback is what makes the tuner's verdict apply to the
    escalated-capacity re-search path: a clipped row regrows its peak
    buffer to the next power of two (e.g. 320 -> 4096), a capacity no
    sweep ever measured, and an exact-key miss used to drop the whole
    resolution to the legacy size heuristic — recompiling a fresh XLA
    sort program on the very dispatch that is already paying an
    escalation.  Relative method order is a property of the searched-
    prefix length far more than of the output capacity (every method's
    cost is dominated by streaming/sorting the prefix), and the
    two-stage ``safe`` flag depends on row width (chosen from the
    prefix length, not the capacity), so the donor cell's verdict
    transfers within a bucket.  Ties prefer the smaller capacity (the
    conservative, always-measured end of the sweep grid).
    """
    cell = table.get(_cost_key(bucket, capacity))
    if isinstance(cell, dict) and cell:
        return cell
    best = None
    for key, val in table.items():
        if not (isinstance(val, dict) and val):
            continue
        try:
            b_s, c_s = str(key).split("/")
            b, c = int(b_s), int(c_s)
        except ValueError:
            continue
        if b != int(bucket):
            continue
        rank = (abs(c - int(capacity)), c)
        if best is None or rank < best[0]:
            best = (rank, val)
    return best[1] if best else None


def _kind_entry(table: dict, device_kind: str | None) -> dict | None:
    """Case-insensitive substring match of a device kind against the
    table's keys (same matching rule as ``obs.costmodel.device_peak``)."""
    if not device_kind:
        return None
    norm = str(device_kind).lower()
    for key, val in table.items():
        if key.lower() in norm or norm in key.lower():
            return val
    return None


def load_extraction(path: str) -> dict:
    """The sidecar's ``"extraction"`` section ({} when absent or
    unreadable) — deliberately ignores the search-key/version gate:
    extraction costs belong to the device, not to one search."""
    if not path or not os.path.exists(path):
        return {}
    try:
        with open(path) as f:
            obj = json.load(f)
    except Exception:
        return {}
    sec = obj.get("extraction")
    return sec if isinstance(sec, dict) else {}


def update_extraction(path: str, device_kind: str, stop_idx: int,
                      capacity: int, *, costs: dict | None = None,
                      picked: str | None = None,
                      safe: bool | None = None) -> None:
    """Merge one measured-cost / picked-path / safety entry into the
    sidecar's ``"extraction"`` section (read-modify-write, atomic;
    every other key of the file is preserved)."""
    if not path:
        return
    try:
        obj = {}
        if os.path.exists(path):
            try:
                with open(path) as f:
                    obj = json.load(f)
            except Exception:
                obj = {}
        if not isinstance(obj, dict):
            obj = {}
        sec = obj.setdefault("extraction", {})
        cell = sec.setdefault(str(device_kind), {}).setdefault(
            _cost_key(stop_bucket(stop_idx), capacity), {})
        if costs:
            for m, s in costs.items():
                if m in EXTRACTION_METHODS and s is not None:
                    cell[m] = float(s)
        if picked is not None:
            cell["picked"] = str(picked)
        if safe is not None:
            cell["safe"] = bool(safe)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(obj, f)
        os.replace(tmp, path)
    except OSError as exc:
        warn_event(
            "tune_io_error",
            f"could not update extraction sidecar {path!r}: {exc}",
            path=path, op="update_extraction", error=str(exc),
        )


# --------------------------------------------------------------------------
# runtime cost calibration (ROADMAP item 5; see module docstring)
# --------------------------------------------------------------------------

def load_calibration(path: str) -> dict:
    """The sidecar's ``"calibration"`` section ({} when absent or
    unreadable) — like ``"extraction"``, it ignores the
    search-key/version gate: cost constants belong to the device."""
    if not path or not os.path.exists(path):
        return {}
    try:
        with open(path) as f:
            obj = json.load(f)
    except Exception:
        return {}
    sec = obj.get("calibration")
    return sec if isinstance(sec, dict) else {}


def update_calibration(path: str, device_kind: str, *,
                       slot_s: float | None = None,
                       research_s: float | None = None,
                       compile_s: float | None = None) -> None:
    """Merge one run's measured cost constants for ``device_kind``
    into the sidecar (read-modify-write, atomic, every other key
    preserved).  Measurements blend via an exponential moving average
    (newest weighted :data:`_CAL_ALPHA`) so one outlier run — a cold
    compile cache, a congested host — cannot swing the model; ``n``
    counts the merged runs."""
    if not path or (slot_s is None and research_s is None
                    and compile_s is None):
        return
    try:
        obj = {}
        if os.path.exists(path):
            try:
                with open(path) as f:
                    obj = json.load(f)
            except Exception:
                obj = {}
        if not isinstance(obj, dict):
            obj = {}
        sec = obj.setdefault("calibration", {})
        cell = sec.setdefault(str(device_kind), {})
        for name, val in (("slot_s", slot_s),
                          ("research_s", research_s),
                          ("compile_s", compile_s)):
            if val is None or not val > 0:
                continue
            old = cell.get(name)
            if isinstance(old, (int, float)) and old > 0:
                cell[name] = (1 - _CAL_ALPHA) * float(old) \
                    + _CAL_ALPHA * float(val)
            else:
                cell[name] = float(val)
        cell["n"] = int(cell.get("n", 0)) + 1
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(obj, f)
        os.replace(tmp, path)
    except OSError as exc:
        warn_event(
            "tune_io_error",
            f"could not update calibration sidecar {path!r}: {exc}",
            path=path, op="update_calibration", error=str(exc),
        )


def calibration_constants(path: str = "",
                          device_kind: str | None = None) -> dict:
    """The cost constants :func:`pick_row_capacity` should use here:
    this device kind's measured calibration where the sidecar has one,
    the committed v5e defaults otherwise.  ``measured`` says which."""
    out = {"slot_s": DEFAULT_SLOT_S, "research_s": DEFAULT_RESEARCH_S,
           "compile_s": DEFAULT_COMPILE_S, "measured": False}
    cell = _kind_entry(load_calibration(path),
                       device_kind or _device_kind_default())
    if isinstance(cell, dict):
        for name in ("slot_s", "research_s", "compile_s"):
            val = cell.get(name)
            if isinstance(val, (int, float)) and val > 0:
                out[name] = float(val)
                out["measured"] = True
    return out


def _measured_slot_cost(sidecar: str, device_kind: str) -> float | None:
    """Per-slot-per-trial extraction cost implied by this device's
    MEASURED extraction cells (cell cost / capacity for the picked —
    else cheapest measured — method; median across cells).  Builtin
    default costs deliberately do not count: calibration records what
    this device was actually measured to do."""
    cells = _kind_entry(load_extraction(sidecar), device_kind) or {}
    vals = []
    for key, cell in cells.items():
        if not isinstance(cell, dict):
            continue
        try:
            _bucket, cap = str(key).split("/")
            cap = int(cap)
        except ValueError:
            continue
        if cap <= 0:
            continue
        costs = {m: cell[m] for m in EXTRACTION_METHODS
                 if isinstance(cell.get(m), (int, float))
                 and cell[m] > 0}
        if not costs:
            continue
        picked = cell.get("picked")
        cost = costs.get(picked) if picked in costs else min(costs.values())
        vals.append(float(cost) / cap)
    if not vals:
        return None
    vals.sort()
    mid = len(vals) // 2
    return (vals[mid] if len(vals) % 2
            else 0.5 * (vals[mid - 1] + vals[mid]))


def record_run_calibration(sidecar: str, device_kind: str | None = None,
                           *, research_s: float | None = None,
                           registry=None) -> None:
    """Record this run's measured cost constants (called by the mesh
    drivers where they save their high-water marks; best effort).

    ``compile_s`` comes from the process's ``jit_compile`` stage timer
    (mean seconds per XLA backend compile — real measurements, via
    ``install_compile_hook``); ``slot_s`` from the sidecar's measured
    extraction cells (:func:`_measured_slot_cost`); ``research_s`` is
    passed by the chunked driver as measured re-search wall-clock per
    clipped row (None when no row clipped this run)."""
    if not sidecar:
        return
    device_kind = device_kind or _device_kind_default()
    if registry is None:
        from ..obs.metrics import REGISTRY as registry
    compile_s = None
    timer = registry.snapshot().get("timers", {}).get("jit_compile")
    if timer and timer.get("count", 0) > 0:
        compile_s = float(timer["host_s"]) / float(timer["count"])
    update_calibration(
        sidecar, device_kind,
        slot_s=_measured_slot_cost(sidecar, device_kind),
        research_s=research_s,
        compile_s=compile_s,
    )


def _device_kind_default() -> str:
    try:
        import jax

        return str(jax.devices()[0].device_kind)
    except Exception:
        return "unknown"


def resolve_peaks_methods(bounds, capacity: int, *, forced: str = "auto",
                          device_kind: str | None = None,
                          sidecar: str = "",
                          pallas_ok: str | None = None) -> tuple:
    """Concrete extraction method per harmonic level.

    ``bounds``: the drivers' per-level (start, stop, freq_factor)
    tuples; ``forced``: ``SearchConfig.peaks_method`` (a concrete
    method wins unconditionally — the A/B forcing path; ``"pallas"``
    stays forced even where the kernel is unavailable, so the ops-
    level contract-preserving fallback and its warn_event fire);
    ``pallas_ok``: ``"compiled"`` | ``"interpret"`` | None — how the
    pallas kernel can run here (``ops.peaks_pallas``).

    Auto resolution per level, in order: a measured sidecar cell for
    (device kind, stop bucket, capacity) — falling back to the
    nearest-capacity cell in the same stop bucket, so escalated
    re-search capacities inherit the tuner's verdict instead of
    recompiling the heuristic's sort (see :func:`_cell_for`) ->
    cheapest available method; the committed v5e defaults (same
    nearest-capacity rule); the legacy size heuristic (two-stage above
    2^17, sort below), with compiled pallas preferred on devices the
    measured tables say nothing about — interpret-mode pallas is never
    auto-picked (it is a test vehicle, ~100x compiled).
    """
    if forced != "auto" and forced not in EXTRACTION_METHODS:
        from ..errors import ConfigError

        raise ConfigError(
            f"peaks_method={forced!r}: use auto, "
            + ", ".join(EXTRACTION_METHODS))
    if forced == "pallas":
        # warm the capability probe OUTSIDE any enclosing trace (the
        # first forced-pallas extract otherwise probes mid-trace)
        from ..ops.peaks_pallas import pallas_peaks_supported

        pallas_peaks_supported()
    if forced != "auto":
        return tuple(forced for _ in bounds)
    device_kind = device_kind or _device_kind_default()
    measured = _kind_entry(load_extraction(sidecar), device_kind) or {}
    builtin = _kind_entry(DEFAULT_EXTRACTION_COSTS, device_kind) or {}
    avail = ["sort", "two_stage"] + (
        ["pallas"] if pallas_ok == "compiled" else [])
    out = []
    for (_start, stop, _f) in bounds:
        bucket = stop_bucket(stop)
        cell = (_cell_for(measured, bucket, capacity)
                or _cell_for(builtin, bucket, capacity) or {})
        costs = {m: cell[m] for m in avail
                 if isinstance(cell.get(m), (int, float))}
        if cell.get("safe") is False:
            costs.pop("two_stage", None)
        if costs:
            out.append(min(costs, key=costs.get))
        elif pallas_ok == "compiled":
            out.append("pallas")
        else:
            from ..ops.peaks import _TWO_STAGE_MIN_SIZE

            out.append("two_stage" if stop > _TWO_STAGE_MIN_SIZE
                       else "sort")
    return tuple(out)


# --------------------------------------------------------------------------
# trial-lattice selection (ISSUE 13; see search/plan.py trial_lattice)
# --------------------------------------------------------------------------

#: the selectable trial-lattice dtypes (ops/dedisperse.py): identity,
#: dedisp's uint8 staircase, and a bf16 round-trip of the f32 trials
LATTICE_DTYPES = ("f32", "u8", "bf16")

#: per-trial-sample bytes each lattice costs the bandwidth-bound
#: dedisperse-write / spectrum-read stages (obs/costmodel.py consumes
#: this; u8 quantises THROUGH one byte then widens on read)
LATTICE_ITEMSIZE = {"f32": 4, "u8": 1, "bf16": 2}

#: committed defaults: no device kind ships a non-f32 pick — quantised
#: lattices engage only after a MEASURED, parity-validated sidecar
#: entry (or an explicit config force).  The table exists so a future
#: sweep can commit known-good picks the way DEFAULT_EXTRACTION_COSTS
#: commits v5e extraction costs.
DEFAULT_LATTICE_PICKS: dict[str, dict] = {}


def lattice_bucket(nsamps: int) -> int:
    """Geometry bucket of a lattice cell: next-power-of-two of the
    trial row length (same bucketing rule as ``stop_bucket``)."""
    return stop_bucket(nsamps)


def _lattice_key(stage: str, bucket: int) -> str:
    return f"{stage}/{int(bucket)}"


def load_lattice(path: str) -> dict:
    """The sidecar's ``"lattice"`` section ({} when absent or
    unreadable) — like ``"extraction"``, it ignores the search-key/
    version gate: lattice economics belong to the device, not to one
    search."""
    if not path or not os.path.exists(path):
        return {}
    try:
        with open(path) as f:
            obj = json.load(f)
    except Exception:
        return {}
    sec = obj.get("lattice")
    return sec if isinstance(sec, dict) else {}


def update_lattice(path: str, device_kind: str, stage: str, nsamps: int,
                   *, costs: dict | None = None,
                   picked: str | None = None,
                   parity: dict | None = None) -> None:
    """Merge one measured-cost / picked-path / parity entry into the
    sidecar's ``"lattice"`` section (read-modify-write, atomic; every
    other key of the file is preserved).

    ``costs``: measured device seconds per lattice dtype for this
    (stage, geometry bucket).  ``parity``: {dtype: {"ok": bool,
    "max_snr_delta": float, "candidates_moved": int}} — the parity
    harness's verdict vs the f32 reference; ``resolve_trial_lattice``
    refuses any auto pick whose parity entry is missing or not ok.
    A verdict may additionally carry ``"recovery_delta"`` (the
    sensitivity sweep's injected-pulsar recovery_fraction under this
    lattice minus the f32 reference's — see ``tools/sensitivity.py
    run_lattice_sweep``); it is copied through verbatim so the sidecar
    records not just "no candidate moved" but "no sensitivity lost"."""
    if not path:
        return
    try:
        obj = {}
        if os.path.exists(path):
            try:
                with open(path) as f:
                    obj = json.load(f)
            except Exception:
                obj = {}
        if not isinstance(obj, dict):
            obj = {}
        sec = obj.setdefault("lattice", {})
        cell = sec.setdefault(str(device_kind), {}).setdefault(
            _lattice_key(stage, lattice_bucket(nsamps)), {})
        if costs:
            for d, s in costs.items():
                if d in LATTICE_DTYPES and s is not None:
                    cell[d] = float(s)
        if picked is not None:
            cell["picked"] = str(picked)
        if parity:
            pcell = cell.setdefault("parity", {})
            for d, verdict in parity.items():
                if d in LATTICE_DTYPES and isinstance(verdict, dict):
                    pcell[d] = {
                        "ok": bool(verdict.get("ok", False)),
                        "max_snr_delta": float(
                            verdict.get("max_snr_delta", 0.0)),
                        "candidates_moved": int(
                            verdict.get("candidates_moved", 0)),
                    }
                    if "recovery_delta" in verdict:
                        pcell[d]["recovery_delta"] = float(
                            verdict["recovery_delta"])
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(obj, f)
        os.replace(tmp, path)
    except OSError as exc:
        warn_event(
            "tune_io_error",
            f"could not update lattice sidecar {path!r}: {exc}",
            path=path, op="update_lattice", error=str(exc),
        )


def _lattice_parity_ok(cell: dict, dtype: str) -> bool:
    """True iff the parity harness has validated ``dtype`` in this
    cell: its verdict exists, is ok, and moved no golden candidate.
    f32 is the reference — always ok."""
    if dtype == "f32":
        return True
    verdict = (cell.get("parity") or {}).get(dtype)
    return (isinstance(verdict, dict) and bool(verdict.get("ok"))
            and int(verdict.get("candidates_moved", 1)) == 0)


def resolve_trial_lattice(forced: str = "auto", *,
                          device_kind: str | None = None,
                          sidecar: str = "", stage: str = "dedisperse",
                          nsamps: int = 0) -> str:
    """The concrete trial-lattice dtype a run should use.

    ``forced``: ``SearchConfig.trial_lattice`` — a concrete dtype wins
    unconditionally (the A/B forcing path; parity is the operator's
    problem when they force).  ``"auto"`` resolution: the sidecar's
    measured cell for (device kind, stage, geometry bucket) — a
    recorded ``picked`` whose parity verdict is ok wins; else the
    cheapest measured dtype whose parity verdict is ok; else the
    committed defaults (same parity rule); else ``"f32"``.  A
    quantised lattice therefore NEVER engages silently: it takes
    either an explicit force or a measured, parity-validated sidecar
    entry (the acceptance gate of ISSUE 13).
    """
    if forced != "auto" and forced not in LATTICE_DTYPES:
        from ..errors import ConfigError

        raise ConfigError(
            f"trial_lattice={forced!r}: use auto, "
            + ", ".join(LATTICE_DTYPES))
    if forced != "auto":
        return forced
    device_kind = device_kind or _device_kind_default()
    key = _lattice_key(stage, lattice_bucket(nsamps))
    for table in (load_lattice(sidecar), DEFAULT_LATTICE_PICKS):
        cell = (_kind_entry(table, device_kind) or {}).get(key)
        if not isinstance(cell, dict):
            continue
        picked = cell.get("picked")
        if picked in LATTICE_DTYPES and _lattice_parity_ok(cell, picked):
            return picked
        costs = {d: cell[d] for d in LATTICE_DTYPES
                 if isinstance(cell.get(d), (int, float))
                 and _lattice_parity_ok(cell, d)}
        if costs:
            return min(costs, key=costs.get)
    return "f32"


def record_peaks_choices(sidecar: str, bounds, capacity: int, methods,
                         device_kind: str | None = None) -> None:
    """Record which extraction path a run actually used per (device
    kind, stop bucket, capacity) — the tuner-sidecar audit trail the
    acceptance gate reads (and METRICS mirrors as
    ``peaks.method_<m>`` gauges)."""
    if not sidecar:
        return
    device_kind = device_kind or _device_kind_default()
    seen = set()
    for (_start, stop, _f), m in zip(bounds, methods):
        cell = (stop_bucket(stop), int(capacity))
        if cell in seen:
            continue
        seen.add(cell)
        update_extraction(sidecar, device_kind, stop, capacity, picked=m)
