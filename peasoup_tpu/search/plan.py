"""Search configuration and trial-grid planning."""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..errors import ConfigError


#: fold profile geometry (`folder.hpp:337-442`): 64 phase bins x 16
#: subintegrations.  Shared by the fold driver (search/pipeline.py)
#: and the analytical cost model (obs/costmodel.py), so the perf
#: accounting can never disagree with the program it describes.
FOLD_NBINS = 64
FOLD_NINTS = 16


def prev_power_of_two(val: int) -> int:
    """Exact reference semantics (`include/utils/utils.hpp:12-18`):
    doubles n while 2n < val — the largest power of two strictly below
    val; note an exact power of two maps to its *half*."""
    n = 1
    while n * 2 < val:
        n *= 2
    return n


@dataclass
class SearchConfig:
    """All tunables of the search, defaults matching the reference CLI
    (`include/utils/cmdline.hpp:95-173`)."""

    outdir: str = ""
    killfilename: str = ""
    zapfilename: str = ""
    max_num_threads: int = 14
    limit: int = 1000
    size: int = 0  # fft length; 0 -> prev_power_of_two(nsamps)
    dm_start: float = 0.0
    dm_end: float = 100.0
    dm_tol: float = 1.10
    dm_pulse_width: float = 64.0  # us
    acc_start: float = 0.0
    acc_end: float = 0.0
    acc_tol: float = 1.10
    acc_pulse_width: float = 64.0  # us
    # fixed-step acceleration grid (`src/pipeline.cpp:287`, the
    # unshipped serial driver: for jj=acc_start; jj<acc_end; jj+=0.5
    # in float32 — DM-independent, acc_end excluded, no forced zero
    # trial).  0 keeps the tolerance-stepped DM-dependent grid of
    # pipeline_multi.
    acc_step: float = 0.0
    boundary_5_freq: float = 0.05
    boundary_25_freq: float = 0.5
    nharmonics: int = 4
    npdmp: int = 0
    min_snr: float = 9.0
    min_freq: float = 0.1
    max_freq: float = 1100.0
    max_harm: int = 16
    freq_tol: float = 0.0001
    verbose: bool = False
    progress_bar: bool = False
    # user-supplied DM trials (``dedisp_set_dm_list`` equivalent,
    # `include/transforms/dedisperser.hpp:34-48`): either an explicit
    # array/sequence of DMs, or a one-DM-per-line text file.  Either
    # overrides the generated dm_start/dm_end/dm_tol grid.
    dm_list: object = None
    dm_file: str = ""
    # dedispersed-trial sample format.  The reference's dedisp call
    # quantises every trial to uint8 (`dedisperser.hpp:104-112`,
    # out_nbits=8); this build keeps f32 sums by default (strictly
    # more information — documented deviation, ops/dedisperse.py).
    # trial_nbits=8 opts in to a dedisp-style uint8 lattice
    # (ops.dedisperse.quantise_trials_u8) for sensitivity studies —
    # NOT tighter golden parity; see the NOTE on quantise_trials_u8.
    trial_nbits: int = 32
    # jerk (acceleration-derivative) trial axis (Andersen & Ransom
    # 2018): a fixed-step DM-independent grid jerk_start..jerk_end in
    # m/s^3, combined with every accel trial into one flattened trial
    # axis per DM (search/plan.py:combine_trials).  The defaults keep
    # a single zero-jerk trial — bit-identical to the accel-only
    # search (the kernel-II ramp skips the cubic term entirely).
    jerk_start: float = 0.0
    jerk_end: float = 0.0
    jerk_step: float = 0.0
    # dedispersed-trial storage lattice for the bandwidth-bound
    # dedisperse/resample/spectrum stages: "f32" (exact, default
    # resolution), "u8" (dedisp's uint8 lattice, = trial_nbits=8),
    # "bf16" (round-trip bfloat16 — halves trial bytes, keeps range).
    # "auto" resolves through the tuner sidecar's parity-gated
    # ``lattice`` section (search/tuning.py) and falls back to f32:
    # a quantised lattice NEVER engages silently — only via a
    # parity-validated sidecar pick or this explicit flag.
    trial_lattice: str = "auto"
    # TPU-build extras (no reference equivalent)
    peak_capacity: int = 1024  # fixed-size device peak buffer per spectrum
    accel_chunk: int = 16      # accel trials batched per device step
    compact_capacity: int = 131072  # per-shard compacted peak buffer (fused)
    # bounded-HBM chunked execution (production scale: the reference
    # streams one DM trial at a time, `src/pipeline_multi.cu:145-157`;
    # we stream DM chunks x accel blocks through one scanned program)
    hbm_budget_gb: float = 13.0  # per-device working-set budget
    dm_chunk: int = 0            # DM trials per chunk step (0 = auto)
    accel_block: int = 0         # accel trials per inner step (0 = auto)
    checkpoint_file: str = ""      # per-DM candidate checkpoint (resume)
    checkpoint_interval: int = 8   # host-loop trials between checkpoint saves
    infilename: str = ""
    # debug buffer dumps (`Utils::dump_device_buffer`,
    # `include/utils/utils.hpp:62-72`): per-DM-trial whitening stages
    # saved as .npy under this directory when non-empty
    dump_dir: str = ""
    # measure the dedispersion stage with a dedicated timed dispatch so
    # overview.xml's <execution_times> is non-degenerate (the mesh
    # programs fuse dedispersion into the search dispatch, so the
    # per-stage number otherwise does not exist); costs one extra
    # dedisp execution — opt in via the CLI's --measure_stages flag
    measure_stages: bool = False
    # persistent buffer auto-tuning (search/tuning.py): a successful
    # run records its peak-count high-waters here so the next run of
    # the SAME search sizes its device buffers right the first time
    # (no clipped-row re-search, minimal compacted transfer)
    tune_file: str = ""
    # two-stage sub-band dedispersion (ops.dedisperse.subband_plan —
    # the algorithm class of the external dedisp library the reference
    # links, `dedisperser.hpp:104-112`): "auto" uses it when the DM
    # grid is dense enough that total adds compress >= 2x (sub-sample
    # smearing bounded by eps+1 samples, exactly like dedisp itself);
    # "always" forces it.  Default "never": the direct sweep is EXACT,
    # an accuracy improvement over the reference's dedisp (same class
    # of documented deviation as keeping f32 trials instead of u8),
    # and results stay identical across drivers.  Opt in for dense
    # tolerance-stepped grids: measured r5 on v5e (dedisp_bench.json)
    # the tree wins 2.15x at 1024 chans / 2.79x at 4096.  (The cost
    # model's 5.3x is unreachable on TPU: anchors pad to the 8-sublane
    # register granularity — 5 anchors cost 8 rows of sweep — and the
    # fixed stage-2 assembly adds ~0.01 s/chunk, so the realistic
    # ceiling is ~3.5x.  Kept opt-in: a ~2x win on one pipeline stage
    # does not justify giving up exact-by-default trials.)
    subband_dedisp: str = "never"
    # stage-2 residual smearing bound in samples (0 = anchors compress
    # only across identical-delay trials, making sub-band output
    # bit-identical to the direct sweep)
    subband_eps: float = 0.5
    # peak-extraction lowering: "auto" lets search/tuning.py pick per
    # (device kind, stop bucket, capacity) from measured costs; force
    # "sort" (approx_max_k/top_k full sorts), "two_stage" (row-reduced
    # top_k) or "pallas" (threshold-compaction kernel,
    # ops/peaks_pallas.py) for A/B benchmarking.  All three lowerings
    # produce identical candidates (slot ORDER differs; every consumer
    # sorts before the peak merge), so this is a non-identity field —
    # switching it never invalidates a checkpoint or tune record.
    peaks_method: str = "auto"
    # run-telemetry sinks (obs/): structured JSONL event log and the
    # machine-readable run_report.json.  Empty = default next to
    # overview.xml in outdir (CLI); presentation-only, never part of
    # the search identity key
    events_log: str = ""
    metrics_json: str = ""
    # async dispatch pipeline depth (parallel/dispatch.py, ISSUE 11):
    # number of device dispatches in flight before the oldest chunk's
    # results are fetched/decoded.  2 = the historical double-buffer
    # (steady-state host work hides behind device time, and the packed
    # result fetch starts async at dispatch); 1 = unpipelined A/B
    # reference; higher keeps more result buffers HBM-resident.
    # Scheduling-only — candidates are bit-identical at every depth —
    # so never part of the search identity key (checkpoints and tune
    # records survive a depth change).
    pipeline_depth: int = 2
    # span-trace export (obs/trace.py): Chrome trace-event JSON,
    # loadable in Perfetto/chrome://tracing; multihost runs merge all
    # hosts' spans into the one file process 0 writes.  Empty =
    # <outdir>/trace.json (CLI default)
    trace_json: str = ""
    # injection-manifest path (obs/injection.py, ISSUE 14): when set,
    # the drivers run the per-stage SNR budget probe against the
    # manifest's known signal and attach the budget to the result /
    # run_report.json.  Diagnostics-only — never part of the search
    # identity key, never changes the candidate list
    injection_manifest: str = ""
    # candidate-lineage run id (obs/lineage.py, ISSUE 19): stamped on
    # every decision mark and hashed into candidate ids; the worker
    # sets it to the job id, the CLI to the observation basename.
    # Diagnostics-only — never part of the search identity key, never
    # changes the candidate list
    lineage_run: str = ""

    # -- geometry accessors (the cost model reads these; keeping them
    # -- here means plan-derived figures have exactly one definition)

    @property
    def nlevels(self) -> int:
        """Harmonic-spectrum levels searched per trial (the fundamental
        plus ``nharmonics`` summed levels)."""
        return self.nharmonics + 1

    def fft_size_for(self, nsamps: int) -> int:
        """The transform length this config uses on an ``nsamps``-sample
        observation (explicit ``size`` or the reference's
        prev-power-of-two rule)."""
        return self.size or prev_power_of_two(nsamps)


@dataclass(frozen=True)
class TrialGridGeometry:
    """Closed-form summary of the full DM x accel x jerk trial grid.

    The jerk axis multiplies every DM's accel list into one combined
    flattened trial axis (:func:`combine_trials`), so ``namax`` is the
    widest per-DM ACCEL count while ``n_trials_total`` counts combined
    (accel, jerk) trials; ``njerk == 1`` is the accel-only grid."""

    n_dm: int
    namax: int            # widest per-DM accel-trial count
    n_trials_total: int   # sum over DMs of combined (accel, jerk) trials
    njerk: int = 1        # jerk trials (1 = accel-only grid)


def trial_grid_geometry(dm_list, acc_plan, acc_lists=None,
                        jerk_plan=None) -> TrialGridGeometry:
    """Grid geometry for ``dm_list`` under ``acc_plan`` (and the
    optional ``jerk_plan`` third axis); pass the per-DM ``acc_lists``
    when the caller already generated them (the mesh driver does) to
    skip regenerating the grid.  ``acc_lists`` here are PURE accel
    lists — combined flattened lists would double-count the jerk
    multiplier."""
    if acc_lists is None:
        acc_lists = [acc_plan.generate_accel_list(float(dm))
                     for dm in dm_list]
    counts = [len(a) for a in acc_lists]
    njerk = jerk_plan.njerk if jerk_plan is not None else 1
    return TrialGridGeometry(
        n_dm=len(counts),
        namax=max(counts) if counts else 0,
        n_trials_total=int(sum(counts)) * int(njerk),
        njerk=int(njerk),
    )


class JerkPlan:
    """Fixed-step jerk (acceleration-derivative) trial grid, in m/s^3.

    DM-independent by design: the jerk-induced smearing is a
    second-order correction to the accel tolerance, so a fixed step
    (Andersen & Ransom 2018 use a uniform w-dot grid) is the standard
    choice.  A zero trial is always present when the range straddles
    zero, and the grid is sorted/deduplicated — the forced zero must
    not shadow an on-grid zero.  ``jerk_lo == jerk_hi`` collapses to
    one trial; the all-zero default is the accel-only search."""

    def __init__(self, jerk_lo: float, jerk_hi: float, step: float):
        lo, hi = float(jerk_lo), float(jerk_hi)
        if hi < lo:
            raise ConfigError(
                f"jerk_start={lo} > jerk_end={hi}: empty jerk grid")
        if lo == hi:
            grid = [lo]
        else:
            if not step > 0.0:
                raise ConfigError(
                    f"jerk_step={step} must be > 0 when jerk_start="
                    f"{lo} < jerk_end={hi}")
            grid = list(np.arange(lo, hi, np.float64(step)))
            grid.append(hi)
            if lo < 0.0 < hi:
                grid.append(0.0)  # forced zero-jerk trial
        self._grid = np.unique(np.asarray(grid, dtype=np.float32))

    def jerk_list(self) -> np.ndarray:
        return self._grid.copy()

    @property
    def njerk(self) -> int:
        return len(self._grid)

    @property
    def max_abs(self) -> float:
        """|jerk| bound for static max-shift/residual-width planning."""
        return float(np.abs(self._grid).max(initial=0.0))


def combine_trials(acc_list, jerk_list):
    """Flatten one DM's (accel, jerk) trial product into the combined
    trial axis the drivers batch over: accel varies fastest, so slot
    ``k`` maps back as ``acc = acc_list[k % na]``,
    ``jerk = jerk_list[k // na]``.  Returns ``(accs_flat, jerks_flat)``
    float32.  With one zero-jerk trial the combined axis IS the accel
    list (identical values and order), keeping the accel-only search
    bit-identical."""
    acc = np.asarray(acc_list, dtype=np.float32)
    jerks = np.asarray(jerk_list, dtype=np.float32)
    if len(jerks) == 1 and float(jerks[0]) == 0.0:
        return acc, np.zeros(len(acc), np.float32)
    return (np.tile(acc, len(jerks)),
            np.repeat(jerks, len(acc)))


class AccelerationPlan:
    """DM-dependent acceleration trial grid.

    Faithful to `include/utils/utils.hpp:140-193` including its quirks:
    ``pulse_width`` is divided by 1e3 on construction (so the effective
    pulse width is pulse_width/1e3 us), the DM-smearing term uses the
    centre frequency in MHz (making it negligible), and ``tsamp`` enters
    in seconds while the other smearing terms are microseconds.  The
    2014-era golden output (example_output/overview.xml, 3 accel trials
    for -5..5) corresponds to passing ``pulse_width=64000``.
    """

    def __init__(self, acc_lo, acc_hi, tol, pulse_width, nsamps, tsamp,
                 cfreq, bw):
        self.acc_lo = np.float32(acc_lo)
        self.acc_hi = np.float32(acc_hi)
        self.tol = np.float32(tol)
        self.pulse_width = np.float32(pulse_width) / np.float32(1.0e3)
        self.nsamps = int(nsamps)
        self.tsamp = np.float32(tsamp)
        self.cfreq = np.float32(cfreq)
        self.bw = np.float32(abs(bw))
        self.tobs = np.float32(nsamps) * np.float32(tsamp)

    def generate_accel_list(self, dm: float) -> np.ndarray:
        if self.acc_hi == self.acc_lo:
            return np.array([0.0], dtype=np.float32)
        tdm = np.float32(
            (8.3 * float(self.bw) / float(self.cfreq) ** 3 * float(dm)) ** 2
        )
        tpulse = self.pulse_width * self.pulse_width
        ttsamp = self.tsamp * self.tsamp
        w_us = np.float32(np.sqrt(np.float32(tdm + tpulse + ttsamp)))
        alt_a = np.float32(
            2.0 * float(w_us) * 1.0e-6 * 24.0 * 299792458.0
            / float(self.tobs) / float(self.tobs)
            * np.sqrt(float(self.tol) * float(self.tol) - 1.0)
        )
        out: list[np.float32] = []
        if self.acc_hi != 0 and self.acc_lo != 0:
            out.append(np.float32(0.0))  # explicitly force zero acceleration
        acc = self.acc_lo
        while acc < self.acc_hi:
            out.append(acc)
            acc = np.float32(acc + alt_a)
        out.append(self.acc_hi)
        return np.array(out, dtype=np.float32)

    def max_trials(self, dm_list: np.ndarray) -> int:
        return max(len(self.generate_accel_list(dm)) for dm in dm_list)


class FixedAccelerationPlan:
    """Fixed-step acceleration grid of the reference's unshipped serial
    driver (`src/pipeline.cpp:287`): ``for (float jj=acc_start;
    jj<acc_end; jj+=step)`` — float32 accumulation, DM-independent,
    ``acc_end`` excluded, no forced zero trial."""

    def __init__(self, acc_lo: float, acc_hi: float, step: float):
        self.acc_lo = np.float32(acc_lo)
        self.acc_hi = np.float32(acc_hi)
        self.step = np.float32(step)
        # DM-independent: build once, serve every generate_accel_list
        self._cached = self._grid()
        if len(self._cached) == 0:
            raise ConfigError(
                f"empty fixed-step accel grid (acc_start={acc_lo} >= "
                f"acc_end={acc_hi}): the serial driver would search "
                f"zero trials"
            )

    def _grid(self) -> np.ndarray:
        out = []
        jj = self.acc_lo
        while jj < self.acc_hi:
            out.append(jj)
            nxt = np.float32(jj + self.step)
            if not nxt > jj:
                # f32 increment no longer advances (step <= 0 or below
                # the magnitude's epsilon): the C loop would spin
                # forever — fail instead
                raise ConfigError(
                    f"acc_step={float(self.step)} does not advance the "
                    f"float32 grid at {float(jj)}; use a larger step"
                )
            jj = nxt
        return np.array(out, dtype=np.float32)

    def generate_accel_list(self, dm: float) -> np.ndarray:
        return self._cached.copy()

    def max_trials(self, dm_list: np.ndarray) -> int:
        return len(self._cached)
