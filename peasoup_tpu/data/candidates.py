"""Candidate records and collections.

Mirrors `include/data_types/candidates.hpp:10-166`: a detection with
(dm, dm_idx, acc, nh, snr, freq), optional folded results, and a
recursive ``assoc`` list of related detections absorbed by the
distillers.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np


@dataclass
class Candidate:
    dm: float = 0.0
    dm_idx: int = 0
    acc: float = 0.0
    #: acceleration derivative (m/s^3) of the trial that produced this
    #: detection; 0.0 for accel-only searches (and for every candidate
    #: deserialised from a pre-jerk checkpoint)
    jerk: float = 0.0
    nh: int = 0
    snr: float = 0.0
    freq: float = 0.0
    folded_snr: float = 0.0
    opt_period: float = 0.0
    is_adjacent: bool = False
    is_physical: bool = False
    ddm_count_ratio: float = 0.0
    ddm_snr_ratio: float = 0.0
    assoc: list["Candidate"] = field(default_factory=list)
    fold: np.ndarray | None = None
    nbins: int = 0
    nints: int = 0

    @property
    def period(self) -> float:
        return 1.0 / self.freq

    def append(self, other: "Candidate") -> None:
        self.assoc.append(other)

    def count_assoc(self) -> int:
        return sum(1 + a.count_assoc() for a in self.assoc)

    def collect(self) -> list["Candidate"]:
        """Flatten self + the assoc tree (pre-order, like the reference
        ``collect_candidates``)."""
        out = [self]
        for a in self.assoc:
            out.extend(a.collect())
        return out


class CandidateCollection:
    def __init__(self, cands: list[Candidate] | None = None):
        self.cands: list[Candidate] = list(cands) if cands else []

    def append(self, other) -> None:
        if isinstance(other, CandidateCollection):
            self.cands.extend(other.cands)
        else:
            self.cands.extend(other)

    def __len__(self) -> int:
        return len(self.cands)

    def __iter__(self):
        return iter(self.cands)

    def __getitem__(self, i):
        return self.cands[i]
