from .candidates import Candidate, CandidateCollection
